"""KMS-backed master keys (encryption/src/master_key/kms.rs + cloud/src/
kms.rs): the master key material lives in the KMS; the store persists only
the wrapped blob and unwraps through the provider at startup."""

import http.client
import json
import os

import pytest

from tikv_tpu.sidecar.kms import AwsKms, FakeKms, KmsError, KmsMasterKey
from tikv_tpu.storage.encryption import DataKeyManager, seal, unseal


@pytest.fixture
def kms():
    srv = FakeKms(key_id="unit-key")
    yield srv
    srv.stop()


def _provider(kms):
    return AwsKms("unit-key", access_key="AK", secret_key="SK",
                  endpoint=kms.endpoint)


def test_generate_and_decrypt_roundtrip(kms):
    p = _provider(kms)
    pt, ct = p.generate_data_key()
    assert len(pt) == 32
    assert ct != pt
    assert p.decrypt_data_key(ct) == pt


def test_wrong_key_id_rejected(kms):
    p = AwsKms("other-key", access_key="AK", secret_key="SK", endpoint=kms.endpoint)
    with pytest.raises(KmsError):
        p.generate_data_key()


def test_unsigned_requests_rejected(kms):
    conn = http.client.HTTPConnection(*kms.addr, timeout=10)
    body = json.dumps({"KeyId": "unit-key"}).encode()
    conn.request("POST", "/", body=body,
                 headers={"X-Amz-Target": "TrentService.GenerateDataKey",
                          "Content-Type": "application/x-amz-json-1.1"})
    assert conn.getresponse().status == 403
    conn.close()


def test_master_key_open_persists_and_reopens(kms, tmp_path):
    state = str(tmp_path / "kms-wrapped.key")
    p = _provider(kms)
    mk1 = KmsMasterKey.open(p, state)
    assert os.path.exists(state)
    # "restart": a new provider instance unwraps the SAME key material
    mk2 = KmsMasterKey.open(_provider(kms), state)
    assert mk1.key == mk2.key
    assert mk1.ciphertext == mk2.ciphertext


def test_data_keys_under_kms_master(kms, tmp_path):
    state = str(tmp_path / "wrapped.key")
    dict_path = str(tmp_path / "keydict")
    mk = KmsMasterKey.open(_provider(kms), state)
    dkm = DataKeyManager(mk, dict_path=dict_path)
    kid, key = dkm.current()
    sealed = seal(key, b"secret-sst-bytes")
    # full restart: unwrap via KMS, reload the dict, decrypt old data
    mk2 = KmsMasterKey.open(_provider(kms), state)
    dkm2 = DataKeyManager.open(mk2, dict_path)
    assert unseal(dkm2.by_id(kid), sealed) == b"secret-sst-bytes"


def test_rotate_master_via_kms(kms, tmp_path):
    """Master rotation through the KMS: mint a fresh wrapped key, re-seal
    the dictionary under it — old data keys (and files) stay readable."""
    p = _provider(kms)
    dict_path = str(tmp_path / "keydict")
    mk_old = KmsMasterKey.open(p, str(tmp_path / "wrapped-1.key"))
    dkm = DataKeyManager(mk_old, dict_path=dict_path)
    kid_old, key_old = dkm.current()
    sealed = seal(key_old, b"pre-rotation")
    mk_new = KmsMasterKey.open(p, str(tmp_path / "wrapped-2.key"))
    assert mk_new.key != mk_old.key
    dkm.rotate_master(mk_new)
    dkm.rotate()  # new data key under the new master
    # restart under the NEW master only
    dkm2 = DataKeyManager.open(
        KmsMasterKey.open(p, str(tmp_path / "wrapped-2.key")), dict_path)
    assert unseal(dkm2.by_id(kid_old), sealed) == b"pre-rotation"


def test_master_key_file_hex_only_at_exact_key_length(tmp_path):
    """Only a 64-char all-hex file decodes as hex (exactly 32 key bytes);
    all-hex content of any other length is deliberate raw key material."""
    from tikv_tpu.storage.encryption import MasterKey

    hex64 = "ab" * 32
    p = tmp_path / "k1"
    p.write_text(hex64)
    assert MasterKey.from_file(str(p)).key == MasterKey(bytes.fromhex(hex64)).key

    # 32 ASCII-hex chars: a legitimate 32-byte raw key that HAPPENS to look
    # like hex — must be used as raw bytes, not silently re-decoded
    rawish = "deadbeef" * 4
    p2 = tmp_path / "k2"
    p2.write_text(rawish)
    assert MasterKey.from_file(str(p2)).key == MasterKey(rawish.encode()).key

    # near-hex at the exact key length: corrupted hex, loud error
    import pytest

    bad = "ab" * 31 + "zz"
    p3 = tmp_path / "k3"
    p3.write_text(bad)
    with pytest.raises(ValueError, match="hex"):
        MasterKey.from_file(str(p3))
