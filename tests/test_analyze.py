"""ANALYZE statistics, CHECKSUM, and streaming coprocessor tests
(reference: src/coprocessor/statistics + checksum.rs + streaming path)."""

import pytest

from tikv_tpu.copr.analyze import CmSketch, FmSketch, Histogram, checksum_range, crc64
from tikv_tpu.copr.dag import BatchExecutorsRunner, DagRequest, TableScan
from tikv_tpu.copr.endpoint import (
    CoprRequest,
    Endpoint,
    REQ_TYPE_ANALYZE,
    REQ_TYPE_CHECKSUM,
)
from tikv_tpu.copr.executors import FixtureScanSource
from tikv_tpu.copr.table import record_range
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.util import codec

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, numeric_table_kvs, product_engine


def test_crc64_known_properties():
    assert crc64(b"") == 0
    a, b = crc64(b"hello"), crc64(b"hellp")
    assert a != b
    assert crc64(b"hello") == a  # deterministic


def test_checksum_order_independent_and_mergeable():
    kvs = [(b"a", b"1"), (b"b", b"2"), (b"c", b"3")]
    r1 = checksum_range(kvs)
    r2 = checksum_range(list(reversed(kvs)))
    assert r1["checksum"] == r2["checksum"]
    assert r1["total_kvs"] == 3
    # split-merge: XOR of part checksums == whole
    p1, p2 = checksum_range(kvs[:1]), checksum_range(kvs[1:])
    assert p1["checksum"] ^ p2["checksum"] == r1["checksum"]
    assert checksum_range([]) == {"checksum": 0, "total_kvs": 0, "total_bytes": 0}


def test_fm_sketch_ndv_estimate():
    fm = FmSketch(max_size=64)
    for i in range(10000):
        fm.insert(b"v%d" % (i % 500))
    est = fm.ndv()
    assert 250 <= est <= 1000  # ~500 distinct


def test_cm_sketch_frequency():
    cm = CmSketch()
    for i in range(1000):
        cm.insert(b"common")
    for i in range(10):
        cm.insert(b"rare%d" % i)
    assert cm.query(b"common") >= 1000
    assert cm.query(b"rare3") >= 1
    assert cm.query(b"rare3") < 100  # sketch error bounded
    assert cm.count == 1010


def test_histogram_equi_depth():
    vals = sorted(codec.encode_var_i64(i % 100) for i in range(1000))
    h = Histogram.build(vals, max_buckets=10)
    assert h.ndv == 100
    assert h.total_count() == 1000
    assert len(h.buckets) <= 11
    # cumulative counts strictly increasing
    counts = [b.count for b in h.buckets]
    assert counts == sorted(counts)
    assert all(b.lower <= b.upper for b in h.buckets)


def test_analyze_endpoint():
    eng = LocalEngine(product_engine())
    ep = Endpoint(eng, enable_device=False)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r = ep.handle_request(
        CoprRequest(REQ_TYPE_ANALYZE, dag, [record_range(TABLE_ID)], 200, context={})
    )
    sampled, off = codec.decode_var_u64(r.data, 0)
    n_cols, off = codec.decode_var_u64(r.data, off)
    assert sampled == 6 and n_cols == 4
    # first column (handle): ndv == 6 distinct handles
    ndv, off = codec.decode_var_u64(r.data, off)
    assert ndv == 6


def test_checksum_endpoint_detects_change():
    eng = LocalEngine(product_engine())
    ep = Endpoint(eng, enable_device=False)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    req = lambda ts: CoprRequest(REQ_TYPE_CHECKSUM, dag, [record_range(TABLE_ID)], ts, context={})
    c1 = ep.handle_request(req(200)).data
    c2 = ep.handle_request(req(200)).data
    assert c1 == c2
    # mutate one key → checksum changes ABOVE the write's commit ts, and the
    # snapshot at the old ts is unaffected (MVCC-consistent checksum)
    from fixtures import put_committed
    from tikv_tpu.copr.table import record_key

    put_committed(eng.kv, record_key(TABLE_ID, 1), b"tampered", 300, 301)
    assert ep.handle_request(req(200)).data == c1
    c3 = ep.handle_request(req(400)).data
    assert c3 != c1


def test_streaming_matches_unary():
    cols, kvs, _ = numeric_table_kvs(5000)
    dag = DagRequest(executors=[TableScan(TABLE_ID, cols)])
    unary = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
    dag2 = DagRequest(executors=[TableScan(TABLE_ID, cols)])
    runner = BatchExecutorsRunner(dag2, FixtureScanSource(kvs))
    frames = list(runner.handle_streaming_request(rows_per_stream=1024))
    assert len(frames) > 1  # actually streamed
    all_rows = []
    for f in frames:
        all_rows.extend(f.iter_rows())
    assert all_rows == unary.iter_rows()


def test_streaming_over_service():
    from tikv_tpu.copr.dag_wire import dag_to_wire
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    eng = LocalEngine(product_engine())
    svc = KvService(Storage(engine=eng), Endpoint(eng, enable_device=False))
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r = svc.coprocessor_stream(
        {"dag": dag_to_wire(dag), "ranges": [list(record_range(TABLE_ID))],
         "start_ts": 200, "rows_per_stream": 2}
    )
    import inspect

    assert inspect.isgenerator(r), r  # frames produced lazily, not buffered
    frames = [f["data"] for f in r]
    assert len(frames) >= 1
