"""Cloud external-storage backends against in-process fake servers
(reference: components/cloud/{aws,gcp} + external_storage; the fakes stand in
for MinIO/fake-gcs-server so the real wire protocol is exercised offline)."""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage, SstImporter
from tikv_tpu.sidecar.cloud import CloudError, GcsStorage, S3Storage, create_storage


class _FakeS3(BaseHTTPRequestHandler):
    """Minimal S3 wire protocol: PUT/GET/ListV2 + multipart upload, with a
    SigV4 Authorization check on every request."""

    store: dict[str, bytes] = {}
    uploads: dict[str, dict[int, bytes]] = {}
    fail_next: list[int] = []  # status codes to inject, consumed FIFO

    def log_message(self, *a):
        pass

    def _check_auth(self) -> bool:
        auth = self.headers.get("Authorization", "")
        ok = (
            auth.startswith("AWS4-HMAC-SHA256 Credential=AKID/")
            and "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in auth
            and "Signature=" in auth
            and self.headers.get("x-amz-content-sha256")
            and self.headers.get("x-amz-date")
        )
        if not ok:
            self.send_response(403)
            self.end_headers()
            self.wfile.write(b"<Error>SignatureDoesNotMatch</Error>")
        return ok

    def _inject(self) -> bool:
        if _FakeS3.fail_next:
            st = _FakeS3.fail_next.pop(0)
            self.send_response(st)
            self.end_headers()
            self.wfile.write(b"<Error>injected</Error>")
            return True
        return False

    def do_PUT(self):
        if not self._check_auth() or self._inject():
            return
        u = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        key = urllib.parse.unquote(u.path.lstrip("/"))
        if "partNumber" in q:
            _FakeS3.uploads.setdefault(q["uploadId"], {})[int(q["partNumber"])] = body
            self.send_response(200)
            self.send_header("ETag", f'"part{q["partNumber"]}"')
            self.end_headers()
            return
        _FakeS3.store[key] = body
        self.send_response(200)
        self.end_headers()

    def do_POST(self):
        if not self._check_auth() or self._inject():
            return
        u = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
        key = urllib.parse.unquote(u.path.lstrip("/"))
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if "uploads" in q:
            uid = f"up{len(_FakeS3.uploads)}"
            _FakeS3.uploads[uid] = {}
            self.send_response(200)
            self.end_headers()
            self.wfile.write(f"<UploadId>{uid}</UploadId>".encode())
            return
        if "uploadId" in q:  # complete: stitch parts in order
            parts = _FakeS3.uploads.pop(q["uploadId"])
            _FakeS3.store[key] = b"".join(parts[i] for i in sorted(parts))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"<CompleteMultipartUploadResult/>")
            return
        self.send_response(400)
        self.end_headers()

    def do_GET(self):
        if not self._check_auth() or self._inject():
            return
        u = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
        key = urllib.parse.unquote(u.path.lstrip("/"))
        if "list-type" in q:  # ListObjectsV2 on the bucket, paged at 2 keys
            bucket = key.rstrip("/")
            pre = f"{bucket}/" + q.get("prefix", "")
            keys = sorted(k[len(bucket) + 1 :] for k in _FakeS3.store if k.startswith(pre))
            start = int(q.get("continuation-token", "0"))
            page = keys[start : start + 2]
            xml = "".join(f"<Key>{k}</Key>" for k in page)
            if start + 2 < len(keys):
                xml += (
                    "<IsTruncated>true</IsTruncated>"
                    f"<NextContinuationToken>{start + 2}</NextContinuationToken>"
                )
            self.send_response(200)
            self.end_headers()
            self.wfile.write(f"<ListBucketResult>{xml}</ListBucketResult>".encode())
            return
        if key not in _FakeS3.store:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.end_headers()
        self.wfile.write(_FakeS3.store[key])


class _FakeGcs(BaseHTTPRequestHandler):
    store: dict[str, bytes] = {}

    def log_message(self, *a):
        pass

    def _authed(self) -> bool:
        if self.headers.get("Authorization") != "Bearer tok123":
            self.send_response(401)
            self.end_headers()
            return False
        return True

    def do_POST(self):
        if not self._authed():
            return
        q = dict(urllib.parse.parse_qsl(urllib.parse.urlparse(self.path).query))
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        _FakeGcs.store[urllib.parse.unquote(q["name"])] = body
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"{}")

    def do_GET(self):
        if not self._authed():
            return
        u = urllib.parse.urlparse(self.path)
        if u.path.endswith("/o"):  # list, paged at 2 items
            q = dict(urllib.parse.parse_qsl(u.query))
            pre = urllib.parse.unquote(q.get("prefix", ""))
            names = [k for k in sorted(_FakeGcs.store) if k.startswith(pre)]
            start = int(q.get("pageToken", "0"))
            doc = {"items": [{"name": k} for k in names[start : start + 2]]}
            if start + 2 < len(names):
                doc["nextPageToken"] = str(start + 2)
            self.send_response(200)
            self.end_headers()
            self.wfile.write(json.dumps(doc).encode())
            return
        obj = urllib.parse.unquote(u.path.rsplit("/o/", 1)[1])
        if obj not in _FakeGcs.store:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.end_headers()
        self.wfile.write(_FakeGcs.store[obj])


@pytest.fixture
def s3():
    _FakeS3.store, _FakeS3.uploads, _FakeS3.fail_next = {}, {}, []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield S3Storage(
        "bkt", prefix="backups", access_key="AKID", secret_key="SECRET",
        endpoint=f"http://127.0.0.1:{srv.server_port}", multipart_threshold=1024,
    )
    srv.shutdown()


@pytest.fixture
def gcs():
    _FakeGcs.store = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGcs)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield GcsStorage(
        "bkt", prefix="backups", token_provider=lambda: "tok123",
        endpoint=f"http://127.0.0.1:{srv.server_port}",
    )
    srv.shutdown()


def test_s3_roundtrip_and_list(s3):
    s3.write("f1.sst", b"alpha")
    s3.write("f2.sst", b"beta")
    assert s3.read("f1.sst") == b"alpha"
    assert s3.list() == ["f1.sst", "f2.sst"]
    with pytest.raises(FileNotFoundError):
        s3.read("missing.sst")


def test_s3_sigv4_rejected_on_bad_secret(s3):
    # the fake validates the Authorization header SHAPE; prove a client that
    # skips signing entirely is rejected
    import http.client

    conn = http.client.HTTPConnection(s3.host, s3.port)
    conn.request("PUT", "/bkt/backups/x", body=b"d")
    assert conn.getresponse().status == 403
    conn.close()


def test_s3_multipart_upload(s3):
    big = bytes(range(256)) * 20  # 5120 bytes > 1024 threshold -> 5 parts
    s3.write("big.sst", big)
    assert s3.read("big.sst") == big
    assert not _FakeS3.uploads  # completed (no dangling upload state)


def test_s3_retries_on_5xx_but_not_4xx(s3):
    _FakeS3.fail_next = [500]
    s3.write("r.sst", b"ok")  # one 500 then success
    assert s3.read("r.sst") == b"ok"
    _FakeS3.fail_next = [500, 500, 500]
    with pytest.raises(CloudError, match="retries exhausted"):
        s3.read("r.sst")
    # 429 backs off like a 5xx (GCS/S3 throttle signal)
    _FakeS3.fail_next = [429]
    assert s3.read("r.sst") == b"ok"
    # a permanent 4xx fails on the FIRST attempt — no retry burns
    _FakeS3.fail_next = [400, 500]
    with pytest.raises(CloudError, match="HTTP 400"):
        s3.read("r.sst")
    assert _FakeS3.fail_next == [500]  # the second injection was never consumed
    _FakeS3.fail_next = []


def test_s3_and_gcs_list_pagination(s3, gcs):
    """Both fakes page at 2 keys: listing 5 objects must follow
    continuation/page tokens instead of silently truncating."""
    for i in range(5):
        s3.write(f"p{i}.sst", b"x")
        gcs.write(f"p{i}.sst", b"x")
    expect = [f"p{i}.sst" for i in range(5)]
    assert s3.list() == expect
    assert gcs.list() == expect


def test_gcs_roundtrip_and_list(gcs):
    gcs.write("a.sst", b"one")
    gcs.write("b.sst", b"two")
    assert gcs.read("a.sst") == b"one"
    assert gcs.list() == ["a.sst", "b.sst"]
    with pytest.raises(FileNotFoundError):
        gcs.read("zzz")


def test_create_storage_urls(tmp_path, s3):
    st = create_storage(f"local://{tmp_path}")
    assert isinstance(st, LocalStorage)
    st.write("x", b"1")
    assert st.read("x") == b"1"
    s = create_storage("s3://mybucket/some/prefix", access_key="a", secret_key="b")
    assert isinstance(s, S3Storage) and s.bucket == "mybucket" and s.prefix == "some/prefix"
    g = create_storage("gcs://gbkt/p")
    assert isinstance(g, GcsStorage) and g.bucket == "gbkt"
    from tikv_tpu.sidecar.backup import NoopStorage

    assert isinstance(create_storage("noop://"), NoopStorage)
    with pytest.raises(ValueError):
        create_storage("ftp://nope")


def test_backup_restore_over_s3(s3):
    """The full backup->S3->restore cycle (BR's actual shape)."""
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    eng = BTreeEngine()
    st = Storage(engine=LocalEngine(eng))
    for i in range(5):
        k = b"k%d" % i
        st.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), b"v%d" % i)], k, 10 + i))
        st.sched_txn_command(Commit([Key.from_raw(k)], 10 + i, 20 + i))
    ep = BackupEndpoint(s3)
    meta = ep.backup_range(eng.snapshot(), "full.bak", backup_ts=100)
    assert meta["kvs"] == 5 and "full.bak" in s3.list()
    eng2 = BTreeEngine()
    SstImporter(s3).restore(LocalEngine(eng2), "full.bak", restore_ts=150)
    st2 = Storage(engine=LocalEngine(eng2))
    for i in range(5):
        assert st2.get(b"k%d" % i, 200) == b"v%d" % i
