"""Mesh-sharded evaluation must match single-device aggregation exactly."""

import numpy as np
import pytest

import jax

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan, TopN
from tikv_tpu.copr.jax_eval import _NO_ROW
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.parallel.mesh import (
    ShardedDagEvaluator,
    ShardedGroupedEvaluator,
    ShardedTopNEvaluator,
    make_mesh,
)

from copr_fixtures import TABLE_ID, numeric_table_kvs

COLS, _, (A, B, C) = numeric_table_kvs(4096)


def q6ish():
    return DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Selection([call("lt", col(1), const_int(500))]),
            Aggregation(
                [],
                [
                    AggDescriptor("count", None),
                    AggDescriptor("sum", col(3)),
                    AggDescriptor("min", col(1)),
                    AggDescriptor("max", col(2)),
                ],
            ),
        ]
    )


@pytest.mark.parametrize("groups", [1, 2, 4])
def test_sharded_simple_agg_matches_numpy(groups):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(groups=groups)
    rows_per_shard = 4096 // mesh.shape["regions"]
    ev = ShardedDagEvaluator(q6ish(), mesh, rows_per_shard, capacity=16)
    n = 4096
    columns = {
        1: (A.astype(np.int64), np.zeros(n, dtype=bool)),
        2: (B.astype(np.int64), np.zeros(n, dtype=bool)),
        3: (C.astype(np.int64), np.zeros(n, dtype=bool)),
    }
    gids = np.zeros(n, dtype=np.int32)
    first, carries = jax.tree.map(np.asarray, ev.run_arrays(columns, n, gids))
    mask = A < 500
    assert carries[0][0][0] == mask.sum()  # count
    assert carries[1][1][0] == C[mask].sum()  # sum
    assert carries[2][1][0] == A[mask].min()  # min
    assert carries[3][1][0] == B[mask].max()  # max
    assert first[0] == int(np.flatnonzero(mask)[0])


@pytest.mark.parametrize("groups", [2, 4])
def test_sharded_group_agg_matches_numpy(groups):
    mesh = make_mesh(groups=groups)
    rows_per_shard = 4096 // mesh.shape["regions"]
    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Aggregation([col(2)], [AggDescriptor("count", None), AggDescriptor("sum", col(3))]),
        ]
    )
    ev = ShardedDagEvaluator(dag, mesh, rows_per_shard, capacity=16)
    n = 4096
    gkey = (B % 16).astype(np.int32)
    columns = {
        2: (B.astype(np.int64), np.zeros(n, dtype=bool)),
        3: (C.astype(np.int64), np.zeros(n, dtype=bool)),
    }
    first, carries = jax.tree.map(np.asarray, ev.run_arrays(columns, n, gkey))
    for g in range(16):
        m = gkey == g
        assert carries[0][0][g] == m.sum()
        assert carries[1][1][g] == C[m].sum()
        if m.any():
            assert first[g] != _NO_ROW


def _columns(n, cols_map):
    return {i: (v.astype(np.int64), np.zeros(n, dtype=bool)) for i, v in cols_map.items()}


def test_multi_block_carry_simple_agg():
    """Aggregate state stays on device across super-blocks (long-scan carry)."""
    mesh = make_mesh(groups=2)
    rows = 4096 // mesh.shape["regions"] // 4  # 4 super-blocks
    ev = ShardedDagEvaluator(q6ish(), mesh, rows, capacity=16)
    total = ev.total_rows
    blocks = []
    for b in range(4):
        sl = slice(b * total, (b + 1) * total)
        blocks.append(
            (_columns(total, {1: A[sl], 2: B[sl], 3: C[sl]}), total, np.zeros(total, np.int32))
        )
    first, carries = jax.tree.map(np.asarray, ev.run_blocks(blocks))
    mask = A < 500
    assert carries[0][0][0] == mask.sum()
    assert carries[1][1][0] == C[mask].sum()
    assert carries[2][1][0] == A[mask].min()
    assert carries[3][1][0] == B[mask].max()
    assert first[0] == int(np.flatnonzero(mask)[0])


def grouped_dag():
    return DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Selection([call("lt", col(1), const_int(800))]),
            Aggregation(
                [col(2)],
                [
                    AggDescriptor("count", None),
                    AggDescriptor("sum", col(3)),
                    AggDescriptor("min", col(1)),
                ],
            ),
        ]
    )


def _grouped_oracle(mask, gkey):
    """numpy oracle: per-group count/sum/min in first-occurrence order."""
    order, seen = [], set()
    for i in np.flatnonzero(mask):
        g = int(gkey[i])
        if g not in seen:
            seen.add(g)
            order.append(g)
    return order


@pytest.mark.parametrize("groups", [1, 2])
def test_device_group_dict_matches_oracle(groups):
    """The group DICTIONARY is built on device across shards; results come
    back in first-occurrence order, matching the host dict-coded path."""
    mesh = make_mesh(groups=groups)
    rows_per_shard = 4096 // mesh.shape["regions"]
    ev = ShardedGroupedEvaluator(grouped_dag(), mesh, rows_per_shard, capacity=64)
    n = 4096
    gkey = (B % 13).astype(np.int64)
    columns = _columns(n, {1: A, 2: gkey, 3: C})
    out = ev.finalize(ev.run_blocks([(columns, n)]))
    assert not out["overflow"]
    mask = A < 800
    order = _grouped_oracle(mask, gkey)
    assert list(out["keys"]) == order
    for pos, g in enumerate(order):
        m = mask & (gkey == g)
        assert out["aggs"][0][0][pos] == m.sum()
        assert out["aggs"][1][1][pos] == C[m].sum()
        assert out["aggs"][2][1][pos] == A[m].min()


def test_device_group_dict_multi_block_carry():
    """New groups appearing in LATER blocks reshuffle the sorted dictionary;
    carried per-slot states must be remapped, and first-occurrence order must
    use the global stream index."""
    mesh = make_mesh(groups=2)
    rows = 4096 // mesh.shape["regions"] // 4
    ev = ShardedGroupedEvaluator(grouped_dag(), mesh, rows, capacity=64)
    total = ev.total_rows
    # force new (smaller-sorting) keys to appear only in later blocks
    gkey = (B % 7).astype(np.int64) + 20
    gkey[2 * total :] = (B[2 * total :] % 5).astype(np.int64)  # keys 0..4 late
    blocks = []
    for b in range(4):
        sl = slice(b * total, (b + 1) * total)
        blocks.append((_columns(total, {1: A[sl], 2: gkey[sl], 3: C[sl]}), total))
    out = ev.finalize(ev.run_blocks(blocks))
    assert not out["overflow"]
    mask = A < 800
    order = _grouped_oracle(mask, gkey)
    assert list(out["keys"]) == order
    for pos, g in enumerate(order):
        m = mask & (gkey == g)
        assert out["aggs"][0][0][pos] == m.sum()
        assert out["aggs"][1][1][pos] == C[m].sum()
        assert out["aggs"][2][1][pos] == A[m].min()


def test_group_dict_overflow_is_detected():
    mesh = make_mesh(groups=1)
    rows_per_shard = 4096 // mesh.shape["regions"]
    ev = ShardedGroupedEvaluator(grouped_dag(), mesh, rows_per_shard, capacity=8)
    n = 4096
    gkey = (np.arange(n) % 50).astype(np.int64)  # 50 groups > capacity 8
    columns = _columns(n, {1: A, 2: gkey, 3: C})
    out = ev.finalize(ev.run_blocks([(columns, n)]))
    assert out["overflow"], "50 groups into capacity 8 must flag overflow"


def topn_dag(k=10):
    return DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Selection([call("lt", col(1), const_int(700))]),
            TopN([(col(2), True), (col(3), False)], k),
        ]
    )


def _topn_oracle(mask, k):
    """numpy oracle: rows sorted by (B desc, C asc, stream order), top k."""
    idx = np.flatnonzero(mask)
    order = np.lexsort((idx, C[idx], -B[idx]))
    return idx[order][:k]


@pytest.mark.parametrize("n_blocks", [1, 4])
def test_sharded_topn_matches_oracle(n_blocks):
    """Per-shard running top-K + collective merge == single-stream top-K,
    including cross-shard tie-breaks by global stream order."""
    mesh = make_mesh(groups=2)
    rows = 4096 // mesh.shape["regions"] // n_blocks
    ev = ShardedTopNEvaluator(topn_dag(10), mesh, rows)
    total = ev.total_rows
    blocks = []
    for b in range(n_blocks):
        sl = slice(b * total, (b + 1) * total)
        h = np.arange(b * total, (b + 1) * total)
        blocks.append((_columns(total, {0: h, 1: A[sl], 2: B[sl], 3: C[sl]}), total))
    out = ev.finalize(ev.run_blocks(blocks))
    expect = _topn_oracle(A < 700, 10)
    assert out["rows"] == len(expect)
    assert list(out["gidx"]) == list(expect)
    # payload columns carry the right rows (0=handle, 1=A, 2=B, 3=C)
    np.testing.assert_array_equal(out["payload"][0][0], expect)
    np.testing.assert_array_equal(out["payload"][2][0], B[expect])
    np.testing.assert_array_equal(out["payload"][3][0], C[expect])


def test_sharded_topn_ties_resolve_in_stream_order():
    """Rows with IDENTICAL keys across different shards must come back in
    global stream order (the CPU executor's seq tie-break)."""
    mesh = make_mesh(groups=1)
    rows = 512 // mesh.shape["regions"]
    dag = DagRequest(executors=[TableScan(TABLE_ID, COLS), TopN([(col(2), False)], 6)])
    ev = ShardedTopNEvaluator(dag, mesh, rows)
    n = ev.total_rows
    const_b = np.full(n, 42, dtype=np.int64)  # every key ties
    columns = _columns(n, {0: np.arange(n), 1: A[:n], 2: const_b, 3: C[:n]})
    out = ev.finalize(ev.run_blocks([(columns, n)]))
    assert list(out["gidx"]) == [0, 1, 2, 3, 4, 5]


def test_sharded_topn_fewer_rows_than_k():
    mesh = make_mesh(groups=1)
    rows = 512 // mesh.shape["regions"]
    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Selection([call("lt", col(1), const_int(3))]),
            TopN([(col(1), False)], 50),
        ]
    )
    ev = ShardedTopNEvaluator(dag, mesh, rows)
    n = ev.total_rows
    columns = _columns(n, {0: np.arange(n), 1: A[:n], 2: B[:n], 3: C[:n]})
    out = ev.finalize(ev.run_blocks([(columns, n)]))
    assert out["rows"] == int((A[:n] < 3).sum())


def test_group_key_out_of_range_flags_overflow():
    """Values that cannot pack losslessly into the key lane (negative, or
    >= the NULL lane) must flag overflow — truncation would silently merge
    distinct groups."""
    mesh = make_mesh(groups=1)
    rows_per_shard = 512 // mesh.shape["regions"]
    ev = ShardedGroupedEvaluator(grouped_dag(), mesh, rows_per_shard, capacity=8)
    n = ev.total_rows
    gkey = np.zeros(n, dtype=np.int64)
    gkey[: n // 2] = -1                # negative: cannot pack
    gkey[n // 2 :] = (1 << 31) - 1     # collides with the NULL lane
    columns = _columns(n, {1: np.zeros(n, np.int64), 2: gkey, 3: C[:n]})
    out = ev.finalize(ev.run_blocks([(columns, n)]))
    assert out["overflow"], "out-of-range group keys must flag overflow"


def test_too_many_group_keys_rejected_at_init():
    with pytest.raises(ValueError):
        dag = DagRequest(
            executors=[
                TableScan(TABLE_ID, COLS),
                Aggregation([col(1), col(2), col(3)], [AggDescriptor("count", None)]),
            ]
        )
        ShardedGroupedEvaluator(dag, make_mesh(groups=1), 64, capacity=8)


# --- serving-path mesh integration (BASELINE config #5 shape) ---------------


def _mvcc_engine(n=3000):
    """Committed MVCC rows of the numeric table inside a BTreeEngine."""
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    cols, kvs, _ = numeric_table_kvs(n, seed=7)
    eng = BTreeEngine()
    wb = WriteBatch()
    for rk, val in kvs:
        wb.put_cf("write", Key.from_raw(rk).append_ts(11).encoded,
                  Write(WriteType.PUT, 10, short_value=val).to_bytes())
    eng.write(wb)
    return cols, eng


@pytest.mark.parametrize("groups", [1, 2])
def test_endpoint_mesh_serving_byte_identical(groups):
    """Endpoint.handle_request over an MVCC-decoded region must return
    byte-identical responses on 1 device and on the full 8-device mesh."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.kv import LocalEngine

    cols, eng = _mvcc_engine()
    mesh = make_mesh(groups=groups)
    ep_mesh = Endpoint(LocalEngine(eng), enable_device=True, mesh=mesh)
    ep_one = Endpoint(LocalEngine(eng), enable_device=True)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    plans = [
        # scalar aggregation with selection (Q6 shape)
        [TableScan(TABLE_ID, cols),
         Selection([call("lt", col(2), const_int(40))]),
         Aggregation([], [AggDescriptor("count", None),
                          AggDescriptor("sum", col(3)),
                          AggDescriptor("min", col(2)),
                          AggDescriptor("max", col(3))])],
        # grouped aggregation (Q1 shape)
        [TableScan(TABLE_ID, cols),
         Aggregation([col(2)], [AggDescriptor("count", None),
                                AggDescriptor("sum", col(3)),
                                AggDescriptor("avg", col(3))])],
    ]
    for execs in plans:
        req = lambda: CoprRequest(
            103, DagRequest(executors=execs), [record_range(TABLE_ID)], 100, context={})
        r_mesh = ep_mesh.handle_request(req())
        r_one = ep_one.handle_request(req())
        r_cpu = ep_cpu.handle_request(req())
        assert r_mesh.from_device, f"mesh path fell back: {ep_mesh.last_device_error}"
        assert r_mesh.data == r_one.data == r_cpu.data
    assert ep_mesh.device_fallbacks == 0, ep_mesh.last_device_error
    # the mesh runners were actually used for these aggregation DAGs
    assert len(ep_mesh._mesh_runners) == len(plans)


def test_endpoint_mesh_group_growth():
    """More groups than the initial sharded capacity: state migrates to a
    larger capacity mid-scan and the answer stays byte-identical."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.kv import LocalEngine

    cols, eng = _mvcc_engine(2500)
    ep_mesh = Endpoint(LocalEngine(eng), enable_device=True, mesh=make_mesh(groups=2))
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    # group by id % large modulus → dozens of groups (> capacity 16)
    execs = [TableScan(TABLE_ID, cols),
             Aggregation([call("mod", col(1), const_int(97))],
                         [AggDescriptor("count", None), AggDescriptor("sum", col(2))])]
    req = lambda: CoprRequest(
        103, DagRequest(executors=execs), [record_range(TABLE_ID)], 100, context={})
    r_mesh = ep_mesh.handle_request(req())
    r_cpu = ep_cpu.handle_request(req())
    assert r_mesh.from_device, ep_mesh.last_device_error
    assert r_mesh.data == r_cpu.data


def test_mesh_serving_runner_non_pow2_groups():
    """A groups axis of 3 must yield a divisible capacity, not an infinite
    capacity-search loop."""
    from tikv_tpu.parallel.mesh import MeshServingRunner

    mesh = make_mesh(jax.devices()[:6], groups=3)
    runner = MeshServingRunner(q6ish(), mesh, rows_per_shard=64)
    assert runner.sharded.capacity % 3 == 0


def test_mesh_rejects_non_agg_dag_cheaply():
    """Scan/TopN DAGs route to the single-device evaluator, and the negative
    outcome is cached so repeat requests skip re-probing."""
    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine

    ep = Endpoint(LocalEngine(BTreeEngine()), mesh=make_mesh(groups=2))
    dag = DagRequest(executors=[TableScan(TABLE_ID, COLS), TopN([(col(1), False)], 5)])
    assert ep._mesh_evaluator_for(dag) is None
    key = next(iter(ep._mesh_runners))
    assert ep._mesh_runners[key] is None  # cached negative
    assert ep._mesh_evaluator_for(dag) is None


def test_mesh_bit_aggs_and_first_decline():
    """bit_and/or/xor merge across region shards; 'first' (paired argmin
    carry) declines mesh construction so the endpoint memoizes the
    single-device route instead of re-probing."""
    import numpy as np
    import pytest

    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation, DagRequest, TableScan
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.rpn import col
    from tikv_tpu.parallel.mesh import ShardedDagEvaluator, make_mesh

    cols_info = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
    ]
    dag = DagRequest(executors=[
        TableScan(1, cols_info),
        Aggregation(group_by=[], agg_funcs=[
            AggDescriptor("bit_and", col(1)),
            AggDescriptor("bit_or", col(1)),
            AggDescriptor("bit_xor", col(1)),
            AggDescriptor("count", None),
        ]),
    ])
    mesh = make_mesh(jax.devices()[:8], groups=2)
    ev = ShardedDagEvaluator(dag, mesh, rows_per_shard=64, capacity=4)
    n = ev.total_rows
    rng = np.random.default_rng(2)
    vals = rng.integers(0, 1 << 20, n).astype(np.int64)
    columns = {i: (vals, np.zeros(n, dtype=bool)) for i in ev.ev.device_cols}
    gids = rng.integers(0, 4, n).astype(np.int32)
    state = jax.tree.map(np.asarray, ev.run_arrays(columns, n, gids))
    for slot in range(4):
        m = gids == slot
        assert int(state[1][0][1][slot]) == int(np.bitwise_and.reduce(vals[m])) if m.any() else True
        assert int(state[1][1][1][slot]) == int(np.bitwise_or.reduce(vals[m], initial=0))
        assert int(state[1][2][1][slot]) == int(np.bitwise_xor.reduce(vals[m], initial=0))

    first_dag = DagRequest(executors=[
        TableScan(1, cols_info),
        Aggregation(group_by=[], agg_funcs=[AggDescriptor("first", col(1))]),
    ])
    with pytest.raises(ValueError, match="mesh merge"):
        ShardedDagEvaluator(first_dag, mesh, rows_per_shard=64, capacity=4)
