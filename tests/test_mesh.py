"""Mesh-sharded evaluation must match single-device aggregation exactly."""

import numpy as np
import pytest

import jax

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
from tikv_tpu.copr.jax_eval import _NO_ROW
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.parallel.mesh import ShardedDagEvaluator, make_mesh

from copr_fixtures import TABLE_ID, numeric_table_kvs

COLS, _, (A, B, C) = numeric_table_kvs(4096)


def q6ish():
    return DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Selection([call("lt", col(1), const_int(500))]),
            Aggregation(
                [],
                [
                    AggDescriptor("count", None),
                    AggDescriptor("sum", col(3)),
                    AggDescriptor("min", col(1)),
                    AggDescriptor("max", col(2)),
                ],
            ),
        ]
    )


@pytest.mark.parametrize("groups", [1, 2, 4])
def test_sharded_simple_agg_matches_numpy(groups):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(groups=groups)
    rows_per_shard = 4096 // mesh.shape["regions"]
    ev = ShardedDagEvaluator(q6ish(), mesh, rows_per_shard, capacity=16)
    n = 4096
    columns = {
        1: (A.astype(np.int64), np.zeros(n, dtype=bool)),
        2: (B.astype(np.int64), np.zeros(n, dtype=bool)),
        3: (C.astype(np.int64), np.zeros(n, dtype=bool)),
    }
    gids = np.zeros(n, dtype=np.int32)
    first, carries = jax.tree.map(np.asarray, ev.run_arrays(columns, n, gids))
    mask = A < 500
    assert carries[0][0][0] == mask.sum()  # count
    assert carries[1][1][0] == C[mask].sum()  # sum
    assert carries[2][1][0] == A[mask].min()  # min
    assert carries[3][1][0] == B[mask].max()  # max
    assert first[0] == int(np.flatnonzero(mask)[0])


@pytest.mark.parametrize("groups", [2, 4])
def test_sharded_group_agg_matches_numpy(groups):
    mesh = make_mesh(groups=groups)
    rows_per_shard = 4096 // mesh.shape["regions"]
    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Aggregation([col(2)], [AggDescriptor("count", None), AggDescriptor("sum", col(3))]),
        ]
    )
    ev = ShardedDagEvaluator(dag, mesh, rows_per_shard, capacity=16)
    n = 4096
    gkey = (B % 16).astype(np.int32)
    columns = {
        2: (B.astype(np.int64), np.zeros(n, dtype=bool)),
        3: (C.astype(np.int64), np.zeros(n, dtype=bool)),
    }
    first, carries = jax.tree.map(np.asarray, ev.run_arrays(columns, n, gkey))
    for g in range(16):
        m = gkey == g
        assert carries[0][0][g] == m.sum()
        assert carries[1][1][g] == C[m].sum()
        if m.any():
            assert first[g] != _NO_ROW
