"""Coprocessor fixtures (reference: components/test_coprocessor ProductTable).

A "product" table: id (pk handle), name (varchar), count (int), price
(decimal(2)).  Helpers build it either as raw fixture KVs (no MVCC) or as
committed MVCC data inside a BTreeEngine.
"""

import numpy as np

from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.storage.btree_engine import BTreeEngine

from fixtures import put_committed

TABLE_ID = 42

PRODUCT_COLUMNS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.varchar()),
    ColumnInfo(3, FieldType.int64()),
    ColumnInfo(4, FieldType.decimal_type(2)),
]

# (id, name, count, price_scaled_by_100)
PRODUCT_ROWS = [
    (1, b"apple", 10, 150),
    (2, b"banana", 20, 75),
    (3, b"cherry", 30, 1250),
    (4, None, 5, 200),
    (5, b"apple", 15, 150),
    (6, b"banana", 8, None),
]


def product_kvs(rows=PRODUCT_ROWS, table_id=TABLE_ID):
    non_handle = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]
    out = []
    for rid, name, count, price in rows:
        key = record_key(table_id, rid)
        val = encode_row(non_handle, [name, count, price])
        out.append((key, val))
    return out


def product_engine(rows=PRODUCT_ROWS, table_id=TABLE_ID, commit_ts=100):
    eng = BTreeEngine()
    for i, (key, val) in enumerate(product_kvs(rows, table_id)):
        put_committed(eng, key, val, commit_ts - 10, commit_ts)
    return eng


def numeric_table_kvs(n, table_id=TABLE_ID, seed=0):
    """Large all-numeric table for perf-shaped tests: id, a int, b int, c decimal(2)."""
    rng = np.random.default_rng(seed)
    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.int64()),
        ColumnInfo(4, FieldType.decimal_type(2)),
    ]
    a = rng.integers(0, 1000, n)
    b = rng.integers(0, 100, n)
    c = rng.integers(0, 100000, n)
    non_handle = cols[1:]
    kvs = []
    for i in range(n):
        kvs.append((record_key(table_id, i), encode_row(non_handle, [int(a[i]), int(b[i]), int(c[i])])))
    return cols, kvs, (a, b, c)
