"""Differential tests: hand-rolled protobuf codec vs the real protobuf runtime.

Strategy: derive a .proto file mechanically from each message class's FIELDS
declaration, compile it with the baked-in protoc, then fuzz random instances
both ways:

* my encode() bytes must parse under google.protobuf into equal values
* google.protobuf SerializeToString() must equal my encode() byte-for-byte
  (both emit canonical ascending-field-number order)
* my decode() of protoc bytes must re-encode identically (round-trip)

This pins the wire-format implementation (varints, tags, packed runs, zigzag,
presence semantics) to the reference protobuf behavior; field-number fidelity
to the real kvproto/tipb protos is reconstructed (see tipb_pb.py docstring).
"""

from __future__ import annotations

import importlib
import random
import string
import subprocess
import sys

import pytest

from tikv_tpu.proto import kvproto_pb, tipb_pb, wire
from tikv_tpu.proto.wire import (
    K_BOOL, K_BYTES, K_DOUBLE, K_FIX32, K_FIX64, K_FLOAT, K_INT, K_MSG,
    K_SINT, K_STR, PbMessage,
)

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def message_classes(mod):
    out = []
    for name in dir(mod):
        obj = getattr(mod, name)
        if isinstance(obj, type) and issubclass(obj, PbMessage) and obj.FIELDS != () \
                and obj not in (PbMessage,) and obj.__module__ == mod.__name__:
            out.append(obj)
    # plus empty messages (StaleCommand) — declared FIELDS == ()
    for name in dir(mod):
        obj = getattr(mod, name)
        if isinstance(obj, type) and issubclass(obj, PbMessage) \
                and obj.__module__ == mod.__name__ and obj.FIELDS == () \
                and obj.__name__ not in ("Kv", "Tipb"):
            out.append(obj)
    return out


_PROTO_TYPE = {
    K_BOOL: "bool", K_BYTES: "bytes", K_STR: "string",
    K_DOUBLE: "double", K_FLOAT: "float",
    K_FIX64: "fixed64", K_FIX32: "fixed32", K_SINT: "sint64",
}


def gen_proto(package: str, classes, syntax: int) -> str:
    lines = [f'syntax = "proto{syntax}";', f"package {package};", ""]
    for cls in classes:
        lines.append(f"message {cls.__name__} {{")
        for f in sorted(cls.FIELDS, key=lambda f: f.number):
            if f.kind == K_MSG:
                tname = f.resolve().__name__
            elif f.kind == K_INT:
                tname = "int64" if f.signed else "uint64"
            else:
                tname = _PROTO_TYPE[f.kind]
            if f.repeated:
                label = "repeated "
                opts = ""
                if f.kind != K_MSG and f.kind not in (K_BYTES, K_STR):
                    packed = "true" if f.packed else "false"
                    opts = f" [packed = {packed}]"
                lines.append(f"  {label}{tname} {f.name} = {f.number}{opts};")
            else:
                label = "optional " if syntax == 2 else ""
                lines.append(f"  {label}{tname} {f.name} = {f.number};")
        lines.append("}")
        lines.append("")
    return "\n".join(lines)


@pytest.fixture(scope="module")
def pb2(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("protoc")
    mods = {}
    for mod, package, syntax in ((tipb_pb, "tipbx", 2), (kvproto_pb, "kvprotox", 3)):
        classes = message_classes(mod)
        proto = gen_proto(package, classes, syntax)
        (tmp / f"{package}.proto").write_text(proto)
        r = subprocess.run(
            ["protoc", f"--python_out={tmp}", f"-I{tmp}", f"{package}.proto"],
            capture_output=True, text=True, cwd=tmp,
        )
        assert r.returncode == 0, r.stderr
        sys.path.insert(0, str(tmp))
        try:
            mods[mod] = (importlib.import_module(f"{package}_pb2"), classes)
        finally:
            sys.path.pop(0)
    return mods


def rand_scalar(f, rng: random.Random):
    if f.kind == K_INT:
        if f.signed:
            return rng.choice([0, 1, -1, 127, 128, -(2**63), 2**63 - 1,
                               rng.randint(-(2**40), 2**40)])
        return rng.choice([0, 1, 127, 128, 2**64 - 1, rng.randint(0, 2**40)])
    if f.kind == K_SINT:
        return rng.randint(-(2**50), 2**50)
    if f.kind == K_BOOL:
        return rng.random() < 0.5
    if f.kind in (K_FIX64, K_FIX32):
        return rng.randint(0, 2**32 - 1)
    if f.kind == K_DOUBLE:
        return rng.choice([0.0, -1.5, 3.25, 1e300, rng.random()])
    if f.kind == K_FLOAT:
        return rng.choice([0.0, -1.5, 3.25])  # exactly representable in f32
    if f.kind == K_BYTES:
        return bytes(rng.randrange(256) for _ in range(rng.randrange(12)))
    if f.kind == K_STR:
        return "".join(rng.choice(string.ascii_letters) for _ in range(rng.randrange(10)))
    raise AssertionError(f.kind)


def fill_random(cls, rng: random.Random, depth: int = 0):
    """Build one of my messages with random field values."""
    msg = cls()
    for f in cls.FIELDS:
        if rng.random() < 0.35:  # leave some fields unset
            continue
        if f.kind == K_MSG:
            if depth >= 2 or f.resolve() is cls and depth >= 1:
                continue
            if f.repeated:
                setattr(msg, f.name,
                        [fill_random(f.resolve(), rng, depth + 1)
                         for _ in range(rng.randrange(3))])
            else:
                setattr(msg, f.name, fill_random(f.resolve(), rng, depth + 1))
        elif f.repeated:
            setattr(msg, f.name, [rand_scalar(f, rng) for _ in range(rng.randrange(4))])
        else:
            setattr(msg, f.name, rand_scalar(f, rng))
    return msg


def to_pb2(msg, pb2_mod):
    cls2 = getattr(pb2_mod, type(msg).__name__)
    out = cls2()
    for f in msg.FIELDS:
        v = msg.__dict__.get(f.name)
        if v is None:
            continue
        if f.kind == K_MSG:
            if f.repeated:
                for item in v:
                    getattr(out, f.name).append(to_pb2(item, pb2_mod))
            elif True:
                getattr(out, f.name).CopyFrom(to_pb2(v, pb2_mod))
        elif f.repeated:
            getattr(out, f.name).extend(v)
        else:
            if msg.SYNTAX == 2 or f.kind == K_MSG:
                setattr(out, f.name, v)
            else:
                setattr(out, f.name, v)
    return out


@pytest.mark.parametrize("which", ["tipb", "kvproto"])
def test_differential_fuzz(pb2, which):
    mod = tipb_pb if which == "tipb" else kvproto_pb
    pb2_mod, classes = pb2[mod]
    rng = random.Random(0xC0FFEE + (which == "tipb"))
    for cls in classes:
        for trial in range(12):
            mine = fill_random(cls, rng)
            theirs = to_pb2(mine, pb2_mod)
            my_bytes = mine.encode()
            their_bytes = theirs.SerializeToString()
            assert my_bytes == their_bytes, (
                f"{cls.__name__} trial {trial}: encoding mismatch\n"
                f"mine:   {my_bytes.hex()}\ntheirs: {their_bytes.hex()}\n{mine!r}"
            )
            # decode the reference bytes and re-encode: must round-trip
            rt = cls.decode(their_bytes).encode()
            assert rt == their_bytes, f"{cls.__name__} trial {trial}: round-trip mismatch"


def test_unknown_fields_skipped():
    # a message with an extra field decodes cleanly (forward compat)
    buf = bytearray()
    wire.write_tag(buf, 99, wire.WT_VARINT)
    wire.write_varint(buf, 7)
    buf += kvproto_pb.GetRequest(key=b"k", version=5).encode()
    m = kvproto_pb.GetRequest.decode(bytes(buf))
    assert m.key == b"k" and m.version == 5


def test_truncated_raises():
    good = kvproto_pb.GetRequest(key=b"k" * 20, version=5).encode()
    for cut in range(1, len(good)):
        try:
            kvproto_pb.GetRequest.decode(good[:cut])
        except ValueError:
            pass  # must raise ValueError, never IndexError/struct.error


def test_negative_int32_ten_byte_encoding(pb2):
    # proto int32/int64 negative values use the 10-byte two's-complement form
    pb2_mod, _ = pb2[tipb_pb]
    mine = tipb_pb.ErrorPb(code=-1, msg="x")
    theirs = pb2_mod.ErrorPb()
    theirs.code = -1
    theirs.msg = "x"
    assert mine.encode() == theirs.SerializeToString()
    assert tipb_pb.ErrorPb.decode(mine.encode()).code == -1
