"""Follower-serving read plane: read continuity through leader failure
(server/read_plane.py + the stale-read integration across raftkv, the copr
endpoint/scheduler, and the clients — docs/stale_reads.md).

The acceptance contract (ISSUE 7):

* a read for a region a store does not lead forwards ONE hop to the leader
  (loop-guarded by the ``forwarded`` ctx flag — asserted to never
  ping-pong), degrades to a follower stale read when the leader is
  unreachable and the request permits, else refuses with leader + safe_ts
  hints;
* ``DataNotReadyError`` is a retryable class with watermark-aware backoff;
* a tier-1 Nemesis scenario isolates the leader of a serving region
  mid-traffic: zero failed reads after retry-policy routing,
  follower-served device reads byte-identical to the CPU oracle, watermark
  advance resumes on heal, and fresh reads recover — deterministic under a
  fixed seed.
"""

import json
import urllib.request

import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, Limit, TableScan
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.raft.raftkv import RaftKv
from tikv_tpu.raft.region import NotLeaderError
from tikv_tpu.server.read_plane import ReadPlane
from tikv_tpu.server.service import KvService
from tikv_tpu.sidecar.resolved_ts import ResolvedTsEndpoint
from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
from tikv_tpu.storage.mvcc import PointGetter
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util import retry
from tikv_tpu.util.chaos import Nemesis
from tikv_tpu.util.metrics import REGISTRY

NON_HANDLE = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]

FORWARD_C = REGISTRY.counter("tikv_read_forward_total")
STALE_C = REGISTRY.counter("tikv_read_stale_serve_total")
REFUSE_C = REGISTRY.counter("tikv_read_refuse_total")
FOLLOWER_COPR_C = REGISTRY.counter("tikv_coprocessor_follower_read_total")


def _seed_rows(kv, region_id, n=24):
    """Commit n product rows at commit_ts 100 through the raft write path."""
    wb = WriteBatch()
    for i in range(n):
        k = Key.from_raw(record_key(TABLE_ID, i))
        w = Write(WriteType.PUT, 90,
                  short_value=encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]))
        wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
    kv.write({"region_id": region_id}, wb)


def _commit_kv(pd, storage, ctx, key, value):
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Mutation

    ts = pd.get_tso()
    storage.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(key), value)], key, ts), ctx)
    cts = pd.get_tso()
    storage.sched_txn_command(Commit([Key.from_raw(key)], ts, cts), ctx)
    return cts


def _cluster_with_watermark():
    """In-memory 3-store cluster + one shared resolved-ts endpoint, a
    committed kv row, and an advanced watermark."""
    pd = MockPd()
    c = Cluster(3, pd=pd)
    c.run()
    rts = ResolvedTsEndpoint(pd)
    for s in c.stores.values():
        rts.attach_store(s)
    leader = c.wait_leader(FIRST_REGION_ID)
    storage = Storage(engine=c.raftkv(leader.store.store_id))
    cts = _commit_kv(pd, storage, {"region_id": FIRST_REGION_ID}, b"rk", b"rv")
    w = rts.advance_all()[FIRST_REGION_ID]
    assert w >= cts
    return pd, c, rts, leader, w


def _svc_for(c, rts, sid, read_plane=None):
    kv = RaftKv(c.stores[sid], pump=c.process, resolved_ts=rts)
    return KvService(Storage(engine=kv), raft_router=c.stores[sid],
                     resolved_ts=rts, read_plane=read_plane)


# ---------------------------------------------------------------------------
# the ladder, rung by rung (in-process services, injected transport)
# ---------------------------------------------------------------------------

def test_forward_one_hop_serves_and_counts():
    pd, c, rts, leader, w = _cluster_with_watermark()
    fol = next(s for s in c.stores if s != leader.store.store_id)
    leader_svc = _svc_for(c, rts, leader.store.store_id)
    sent = []

    def send(sid, method, req, timeout):
        sent.append((sid, method, (req.get("context") or {}).get("forwarded")))
        return leader_svc.dispatch(method, req)

    plane = ReadPlane(store=c.stores[fol], resolved_ts=rts, send=send)
    fol_svc = _svc_for(c, rts, fol, read_plane=plane)
    ok0 = FORWARD_C.get(outcome="ok")
    r = fol_svc.kv_get({"key": b"rk", "version": w,
                        "context": {"region_id": FIRST_REGION_ID}})
    assert r.get("error") is None and r["value"] == b"rv"
    # one hop, to the leader, with the loop-guard flag stamped
    assert sent == [(leader.store.store_id, "kv_get", True)]
    assert FORWARD_C.get(outcome="ok") == ok0 + 1


def test_forward_loop_guard_never_ping_pongs():
    """Two followers with stale routes to each other: the forwarded flag
    stops the second hop — B never calls out, and the refusal carries
    hints back through A."""
    pd, c, rts, leader, w = _cluster_with_watermark()
    followers = [s for s in c.stores if s != leader.store.store_id]
    a_sid, b_sid = followers
    b_sent = []

    def b_send(sid, method, req, timeout):  # must never fire
        b_sent.append((sid, method))
        return {"error": {"other": "unexpected second hop"}}

    b_plane = ReadPlane(store=c.stores[b_sid], resolved_ts=rts, send=b_send)
    b_svc = _svc_for(c, rts, b_sid, read_plane=b_plane)

    def a_send(sid, method, req, timeout):
        # stale topology: A believes B leads the region
        return b_svc.dispatch(method, req)

    a_plane = ReadPlane(store=c.stores[a_sid], resolved_ts=rts, send=a_send)
    a_svc = _svc_for(c, rts, a_sid, read_plane=a_plane)
    # poison A's leader view so the hop goes follower -> follower
    a_svc.read_plane._leader_of = lambda rid: b_sid

    guard0 = FORWARD_C.get(outcome="loop_guard")
    remote0 = FORWARD_C.get(outcome="remote_region_error")
    r = a_svc.kv_get({"key": b"rk", "version": w,
                      "context": {"region_id": FIRST_REGION_ID}})
    assert b_sent == [], "a forwarded request must NEVER forward again"
    assert FORWARD_C.get(outcome="loop_guard") == guard0 + 1
    assert FORWARD_C.get(outcome="remote_region_error") == remote0 + 1
    err = r["error"]["not_leader"]
    # the typed refusal carries routing + staleness hints for the client
    assert err.get("leader_store") is not None
    assert err.get("safe_ts") == rts.safe_ts() > 0


def test_stale_fallback_when_leader_unreachable_iff_permitted():
    pd, c, rts, leader, w = _cluster_with_watermark()
    fol = next(s for s in c.stores if s != leader.store.store_id)

    def dead_send(sid, method, req, timeout):
        raise ConnectionError("leader store down")

    plane = ReadPlane(store=c.stores[fol], resolved_ts=rts, send=dead_send)
    svc = _svc_for(c, rts, fol, read_plane=plane)

    # permitted: stale_fallback + a version at/below the watermark serves
    s0 = STALE_C.get(path="kv", cause="leader_unreachable")
    r = svc.kv_get({"key": b"rk", "version": w,
                    "context": {"region_id": FIRST_REGION_ID,
                                "stale_fallback": True}})
    assert r.get("error") is None and r["value"] == b"rv"
    assert STALE_C.get(path="kv", cause="leader_unreachable") == s0 + 1

    # not permitted: typed NotLeader refusal with leader + safe_ts hints
    r0 = REFUSE_C.get(cause="no_permit")
    r = svc.kv_get({"key": b"rk", "version": w,
                    "context": {"region_id": FIRST_REGION_ID}})
    err = r["error"]["not_leader"]
    assert err["leader_store"] == leader.store.store_id
    assert err["safe_ts"] == rts.safe_ts()
    assert REFUSE_C.get(cause="no_permit") == r0 + 1

    # permitted but above the watermark: DataNotReady refusal carrying the
    # resolved ts the client's backoff waits on
    r = svc.kv_get({"key": b"rk", "version": w + 10_000,
                    "context": {"region_id": FIRST_REGION_ID,
                                "stale_fallback": True}})
    dnr = r["error"]["data_not_ready"]
    assert dnr["resolved"] == w and dnr["safe_ts"] == rts.safe_ts()


def test_direct_stale_read_serves_locally_without_forward():
    """A client-marked stale read is served by ANY data replica with zero
    hops — the scales-with-replicas path."""
    pd, c, rts, leader, w = _cluster_with_watermark()
    fol = next(s for s in c.stores if s != leader.store.store_id)

    def send(sid, method, req, timeout):  # must not be consulted
        raise AssertionError("direct stale read must not forward")

    plane = ReadPlane(store=c.stores[fol], resolved_ts=rts, send=send)
    svc = _svc_for(c, rts, fol, read_plane=plane)
    r = svc.kv_get({"key": b"rk", "version": w,
                    "context": {"region_id": FIRST_REGION_ID,
                                "stale_read": True, "read_ts": w}})
    assert r.get("error") is None and r["value"] == b"rv"


def test_stale_read_ts_clamped_to_mvcc_version():
    """A declared read_ts BELOW the request's MVCC version cannot sneak a
    fresh read past admission: the watermark check covers the ts the MVCC
    pass actually reads at (storage._stale_snap_ctx / the read plane's
    clamp), so a lagging replica refuses instead of silently serving a
    snapshot that may miss committed data."""
    pd, c, rts, leader, w = _cluster_with_watermark()
    fol = next(s for s in c.stores if s != leader.store.store_id)

    def dead_send(sid, method, req, timeout):
        raise ConnectionError("leader store down")

    plane = ReadPlane(store=c.stores[fol], resolved_ts=rts, send=dead_send)
    svc = _svc_for(c, rts, fol, read_plane=plane)
    for ctx_extra in ({"stale_read": True, "read_ts": w},
                      {"stale_fallback": True, "read_ts": w}):
        r = svc.kv_get({"key": b"rk", "version": w + 10_000,
                        "context": {"region_id": FIRST_REGION_ID,
                                    **ctx_extra}})
        dnr = (r.get("error") or {}).get("data_not_ready")
        assert dnr is not None, r
        # admission ran at the clamped (MVCC) ts, not the declared one
        assert dnr["read_ts"] == w + 10_000 and dnr["resolved"] == w


def test_lagging_stale_read_forwards_to_leader_then_refuses_typed():
    """DataNotReady on the local replica: one hop to the leader (whose
    progress is current) serves it; with the leader also unreachable the
    refusal is the typed data_not_ready with hints."""
    pd, c, rts, leader, w = _cluster_with_watermark()
    fol = next(s for s in c.stores if s != leader.store.store_id)
    # a read above every watermark: even the leader refuses, but the hop is
    # attempted and the refusal must stay TYPED end to end
    leader_svc = _svc_for(c, rts, leader.store.store_id)

    def send(sid, method, req, timeout):
        return leader_svc.dispatch(method, req)

    plane = ReadPlane(store=c.stores[fol], resolved_ts=rts, send=send)
    svc = _svc_for(c, rts, fol, read_plane=plane)
    remote0 = FORWARD_C.get(outcome="remote_region_error")
    r = svc.kv_get({"key": b"rk", "version": w + 999,
                    "context": {"region_id": FIRST_REGION_ID,
                                "stale_read": True, "read_ts": w + 999}})
    dnr = r["error"]["data_not_ready"]
    assert dnr["read_ts"] == w + 999 and dnr["resolved"] == w
    assert FORWARD_C.get(outcome="remote_region_error") == remote0 + 1
    # classified retryable with a watermark-aware backoff on the client
    exc = RaftKv.DataNotReadyError(dnr["region_id"], dnr["read_ts"], dnr["resolved"])
    assert retry.classify(exc) == "data_not_ready"
    assert retry.Retrier(site="t").should_retry(exc) is not None


# ---------------------------------------------------------------------------
# coprocessor integration: follower device serving + admission refusal
# ---------------------------------------------------------------------------

def _scan_req(ts, stale=False):
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), Limit(1 << 20)])
    ctx = {"region_id": FIRST_REGION_ID}
    if stale:
        ctx.update(stale_read=True, read_ts=ts)
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts, context=ctx)


def _agg_req(ts, stale=False):
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, PRODUCT_COLUMNS),
        Aggregation([], [AggDescriptor("count", None)]),
    ])
    ctx = {"region_id": FIRST_REGION_ID}
    if stale:
        ctx.update(stale_read=True, read_ts=ts)
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts, context=ctx)


def test_copr_follower_stale_serving_byte_identical_and_counted():
    pd = MockPd()
    c = Cluster(3, pd=pd)
    c.run()
    rts = ResolvedTsEndpoint(pd)
    for s in c.stores.values():
        rts.attach_store(s)
    leader = c.wait_leader(FIRST_REGION_ID)
    _seed_rows(c.raftkv(leader.store.store_id), FIRST_REGION_ID)
    w = rts.advance_all()[FIRST_REGION_ID]
    fol = next(s for s in c.stores if s != leader.store.store_id)
    fkv = RaftKv(c.stores[fol], pump=c.process, resolved_ts=rts)
    warm = Endpoint(fkv, enable_device=True)
    oracle = Endpoint(fkv, enable_device=False)

    before = sum(FOLLOWER_COPR_C._values.values())
    r1 = warm.handle_request(_scan_req(w, stale=True))
    want = oracle.handle_request(_scan_req(w, stale=True)).data
    assert r1.data == want
    # repeat read rides the warm region image (the invariant-asserted key)
    r2 = warm.handle_request(_scan_req(w, stale=True))
    assert r2.data == want
    assert warm.region_cache.stats.hits >= 1
    assert sum(FOLLOWER_COPR_C._values.values()) > before


def test_copr_scheduler_admission_raises_data_not_ready_before_dispatch():
    pd = MockPd()
    c = Cluster(3, pd=pd)
    c.run()
    rts = ResolvedTsEndpoint(pd)
    for s in c.stores.values():
        rts.attach_store(s)
    leader = c.wait_leader(FIRST_REGION_ID)
    _seed_rows(c.raftkv(leader.store.store_id), FIRST_REGION_ID)
    w = rts.advance_all()[FIRST_REGION_ID]
    fol = next(s for s in c.stores if s != leader.store.store_id)
    fkv = RaftKv(c.stores[fol], pump=c.process, resolved_ts=rts)
    ep = Endpoint(fkv, enable_device=True)

    batches = REGISTRY.counter("tikv_coprocessor_sched_batches_total")
    shed = REGISTRY.counter("tikv_coprocessor_sched_shed_total")
    b0 = sum(batches._values.values())
    s0 = shed.get(reason="data_not_ready")
    with pytest.raises(RaftKv.DataNotReadyError):
        ep.scheduler.execute(_agg_req(w + 10_000, stale=True))
    # batch path sheds it typed at dispatch too, sibling slots unharmed
    results, errors = ep.scheduler.run_batch(
        [_agg_req(w, stale=True), _agg_req(w + 10_000, stale=True)],
        return_errors=True)
    assert errors[0] is None and results[0] is not None
    assert isinstance(errors[1], RaftKv.DataNotReadyError) and results[1] is None
    assert sum(batches._values.values()) == b0, \
        "a watermark-lagging request must never form a device batch"
    assert shed.get(reason="data_not_ready") >= s0 + 2


# ---------------------------------------------------------------------------
# the tier-1 nemesis scenario: read continuity through leader isolation
# ---------------------------------------------------------------------------

def test_leader_isolation_reads_continue_with_bounded_staleness():
    """Isolate the serving region's leader mid-traffic (seeded, in-memory,
    deterministic): retry-policy-routed reads never fail (follower stale
    serving carries them), follower device reads stay byte-identical to the
    CPU oracle, the watermark resumes after heal, and fresh reads recover."""
    pd = MockPd()
    c = Cluster(3, pd=pd)
    c.run()
    rts = ResolvedTsEndpoint(pd)
    for s in c.stores.values():
        rts.attach_store(s)
    leader = c.wait_leader(FIRST_REGION_ID)
    leader_sid = leader.store.store_id
    _seed_rows(c.raftkv(leader_sid), FIRST_REGION_ID)
    storage = Storage(engine=c.raftkv(leader_sid))
    _commit_kv(pd, storage, {"region_id": FIRST_REGION_ID}, b"cont", b"v0")
    w0 = rts.advance_all()[FIRST_REGION_ID]

    endpoints = {
        sid: Endpoint(RaftKv(st, pump=c.process, resolved_ts=rts),
                      enable_device=True)
        for sid, st in c.stores.items()
    }
    oracles = {
        sid: Endpoint(RaftKv(st, pump=c.process, resolved_ts=rts),
                      enable_device=False)
        for sid, st in c.stores.items()
    }

    nem = Nemesis(c, seed=20250803)
    read_policy = retry.RetryPolicy(base_s=0.0, jitter=0.0, max_attempts=20)

    def routed_get(key, read_ts):
        """The client ladder under the shared retry policy: fresh read on
        the routed leader, degrade to follower stale at the watermark."""
        def attempt():
            lp = c.leader_peer(FIRST_REGION_ID)
            if lp is not None and lp.store.store_id not in isolated:
                kv = RaftKv(lp.store, pump=c.process, resolved_ts=rts,
                            propose_timeout=0.2)
                try:
                    snap = kv.snapshot({"region_id": FIRST_REGION_ID})
                    return PointGetter(snap, read_ts).get(Key.from_raw(key))
                except (NotLeaderError, TimeoutError):
                    pass
            for sid, st in c.stores.items():
                kv = RaftKv(st, pump=c.process, resolved_ts=rts)
                try:
                    snap = kv.snapshot({"region_id": FIRST_REGION_ID,
                                        "stale_read": True, "read_ts": read_ts})
                    return PointGetter(snap, read_ts).get(Key.from_raw(key))
                except (NotLeaderError, RaftKv.DataNotReadyError):
                    continue
            raise TimeoutError("no replica served the read")

        return retry.call(attempt, policy=read_policy,
                          sleep=lambda _s: c.tick(), site="test.routed_get")

    isolated: set = set()
    try:
        # mid-traffic isolation of the leader
        isolated = {leader_sid}
        nem.isolate(leader_sid)

        # zero failed reads through the retry-routed ladder, mid-isolation
        failures = 0
        for _ in range(8):
            try:
                assert routed_get(b"cont", w0) == b"v0"
            except Exception:  # noqa: BLE001 — counted, must stay 0
                failures += 1
            c.tick()
        assert failures == 0, "reads failed during leader isolation"

        # follower device serving stays byte-identical to the CPU oracle
        followers = [s for s in c.stores if s != leader_sid]
        for sid in followers:
            dev = endpoints[sid].handle_request(_scan_req(w0, stale=True))
            cpu = oracles[sid].handle_request(_scan_req(w0, stale=True))
            assert dev.data == cpu.data, f"follower {sid} diverged from oracle"

        # the watermark never regresses while the leader is gone
        w_iso = rts.advance_all().get(FIRST_REGION_ID, 0)
        assert w_iso >= w0

        # majority side elects a new leader and keeps accepting writes
        for _ in range(30):
            c.tick()
        c.must_put(b"during-iso", b"w")
    finally:
        isolated = set()
        nem.heal()
        nem.close()

    # heal: watermark advance resumes past new commits, fresh reads recover
    for _ in range(10):
        c.tick()
    lp = c.wait_leader(FIRST_REGION_ID)
    storage2 = Storage(engine=c.raftkv(lp.store.store_id))
    cts = _commit_kv(pd, storage2, {"region_id": FIRST_REGION_ID}, b"cont", b"v1")
    w1 = rts.advance_all()[FIRST_REGION_ID]
    assert w1 >= cts > w0, "watermark advance must resume after heal"
    assert routed_get(b"cont", w1) == b"v1"
    assert c.must_get(b"during-iso") == b"w"
    # follower stale reads at the NEW watermark see the new value
    fol = next(s for s in c.stores if s != lp.store.store_id)
    fkv = RaftKv(c.stores[fol], pump=c.process, resolved_ts=rts)
    snap = fkv.snapshot({"region_id": FIRST_REGION_ID,
                         "stale_read": True, "read_ts": w1})
    assert PointGetter(snap, w1).get(Key.from_raw(b"cont")) == b"v1"


# ---------------------------------------------------------------------------
# sockets: the ladder on the real networked stack
# ---------------------------------------------------------------------------

def test_server_cluster_forward_and_stale_continuity_over_sockets():
    """Real TCP: a follower store forwards a fresh read to the leader; with
    the leader process STOPPED, permitted reads keep serving from follower
    watermarks (read continuity through leader failure)."""
    from tikv_tpu.server.cluster import ServerCluster
    from tikv_tpu.server.server import Client

    c = ServerCluster(3, pd=MockPd(), full_service=True)
    c.run()
    clients = []
    try:
        leader_sid = c.wait_leader(FIRST_REGION_ID).store.store_id
        leader_client = Client(*c.addrs[leader_sid])
        clients.append(leader_client)
        c.must_put(b"raw-cont", b"rawv")  # engine-level row for the helpers
        ts = c.pd.get_tso()
        pr = leader_client.call("kv_prewrite", {
            "mutations": [{"op": "put", "key": b"sock", "value": b"sv"}],
            "primary_lock": b"sock", "start_version": ts,
            "context": {"region_id": FIRST_REGION_ID},
        })
        assert not pr.get("errors") and not pr.get("error"), pr
        commit_ts = c.pd.get_tso()
        cm = leader_client.call("kv_commit", {
            "keys": [b"sock"], "start_version": ts, "commit_version": commit_ts,
            "context": {"region_id": FIRST_REGION_ID},
        })
        assert not cm.get("error"), cm

        # two advance rounds: pairs publish on the first, disseminate to
        # follower stores on the second's check_leader fan-out
        c.advance_resolved_ts()
        c.advance_resolved_ts()
        read_ts = c.pd.get_tso()
        fol_sid = next(s for s in c.nodes if s != leader_sid)
        fol_client = Client(*c.addrs[fol_sid])
        clients.append(fol_client)

        # rung 1: fresh read on the follower forwards one hop and serves
        ok0 = FORWARD_C.get(outcome="ok")
        r = fol_client.call("kv_get", {
            "key": b"sock", "version": read_ts,
            "context": {"region_id": FIRST_REGION_ID},
        }, timeout=10.0)
        assert r.get("error") is None and r["value"] == b"sv", r
        assert FORWARD_C.get(outcome="ok") == ok0 + 1

        # rung 2: leader store gone — permitted reads degrade to follower
        # stale serving at the disseminated watermark
        fol_node = c.nodes[fol_sid]
        w = fol_node.resolved_ts.progress_of(FIRST_REGION_ID)[0]
        assert w >= commit_ts, "watermark never reached the follower store"
        c.stop_node(leader_sid)
        s0 = STALE_C.get(path="kv", cause="leader_unreachable")
        r = fol_client.call("kv_get", {
            "key": b"sock", "version": w,
            "context": {"region_id": FIRST_REGION_ID, "stale_fallback": True},
        }, timeout=15.0)
        assert r.get("error") is None and r["value"] == b"sv", r
        assert STALE_C.get(path="kv", cause="leader_unreachable") == s0 + 1

        # the cluster-harness helpers take the same degraded path: a stale
        # read off any surviving replica at the freshest watermark, and the
        # opt-in must_get fallback (bounded staleness) still answers
        assert c.stale_get(b"raw-cont") == b"rawv"
        assert c.must_get(b"raw-cont", timeout=3.0,
                          stale_fallback=True) == b"rawv"
    finally:
        for cl in clients:
            try:
                cl.close()
            except OSError:
                pass
        c.shutdown()


# ---------------------------------------------------------------------------
# ops surface: read progress exposure
# ---------------------------------------------------------------------------

def test_debug_read_progress_rpc_and_status_route():
    pd, c, rts, leader, w = _cluster_with_watermark()
    svc = _svc_for(c, rts, leader.store.store_id)
    out = svc.debug_read_progress({})
    assert out["safe_ts"] == rts.safe_ts() > 0
    assert out["regions"][FIRST_REGION_ID]["resolved_ts"] == w
    assert out["regions"][FIRST_REGION_ID]["required_apply_index"] >= 0
    narrowed = svc.debug_read_progress({"region_id": FIRST_REGION_ID})
    assert list(narrowed["regions"]) == [FIRST_REGION_ID]

    from tikv_tpu.server.status_server import StatusServer

    ss = StatusServer(read_progress=lambda: svc.debug_read_progress({}))
    ss.start()
    try:
        host, port = ss.addr
        body = urllib.request.urlopen(
            f"http://{host}:{port}/debug/read_progress").read()
        doc = json.loads(body)
        assert doc["safe_ts"] == rts.safe_ts()
        assert str(FIRST_REGION_ID) in doc["regions"]
    finally:
        ss.stop()


def test_server_cluster_route_cache_updates_from_not_leader_hints():
    """must_get consults the region->store route cache seeded by NotLeader
    hints instead of re-polling wait_leader's all-store scan."""
    from tikv_tpu.server.cluster import ServerCluster

    c = ServerCluster(3, pd=MockPd())
    c.run()
    try:
        c.must_put(b"route", b"r1")
        assert c.must_get(b"route") == b"r1"
        rid = c.region_for_key(b"route")
        assert c._route.get(rid) == c.wait_leader(rid).store.store_id
        # a stale cache entry heals through the hint/fallback path
        c._route[rid] = next(s for s in c.nodes
                             if s != c._route[rid])
        assert c.must_get(b"route") == b"r1"
        assert c._route.get(rid) == c.wait_leader(rid).store.store_id
    finally:
        c.shutdown()
