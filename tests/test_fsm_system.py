"""Generic FSM batch system: router/mailbox semantics, poller exclusivity,
hot-FSM fairness, and the 1,000-regions-over-4-pollers bound
(batch-system/src/batch.rs Poller::poll, src/mailbox.rs FsmState).
"""

from __future__ import annotations

import os
import threading
import time

from tikv_tpu.raft.fsm_system import BatchSystem, PollHandler, Router


class CountingHandler(PollHandler):
    """Shared-state handler that also asserts per-FSM exclusivity."""

    def __init__(self, state):
        self.state = state

    def handle(self, addr, msgs):
        st = self.state
        # exclusivity: no two pollers may hold the same FSM concurrently
        with st["mu"]:
            assert addr not in st["active"], f"fsm {addr} entered twice"
            st["active"].add(addr)
        if st.get("work_s"):
            time.sleep(st["work_s"])
        with st["mu"]:
            st["counts"][addr] = st["counts"].get(addr, 0) + len(msgs)
            st["active"].discard(addr)
            for m in msgs:
                if isinstance(m, tuple) and m[0] == "ts":
                    st["latencies"].append(time.monotonic() - m[1])

    def handle_control(self, msgs):
        with self.state["mu"]:
            self.state["control"] += len(msgs)


def make_system(pollers=4, **kw):
    router = Router()
    state = {"mu": threading.Lock(), "counts": {}, "active": set(),
             "control": 0, "latencies": [], **kw}
    system = BatchSystem(router, lambda: CountingHandler(state), pollers=pollers,
                         name="test-bs")
    return router, system, state


def test_thousand_fsms_over_four_pollers():
    """1,000 FSMs, 4 pollers: every message lands exactly once, exclusivity
    holds, and per-message latency stays bounded."""
    router, system, state = make_system(pollers=4)
    n_fsm, per_fsm = 1000, 20
    for i in range(n_fsm):
        router.register(i)
    system.spawn()
    t0 = time.monotonic()
    for round_ in range(per_fsm):
        for i in range(n_fsm):
            router.send(i, ("ts", time.monotonic()))
    deadline = time.monotonic() + 30
    total = n_fsm * per_fsm
    while time.monotonic() < deadline:
        with state["mu"]:
            if sum(state["counts"].values()) == total:
                break
        time.sleep(0.01)
    system.shutdown()
    assert not system.errors, system.errors[:3]
    with state["mu"]:
        assert sum(state["counts"].values()) == total
        assert len(state["counts"]) == n_fsm          # every FSM ran
        assert all(c == per_fsm for c in state["counts"].values())
        lats = sorted(state["latencies"])
    wall = time.monotonic() - t0
    p99 = lats[int(len(lats) * 0.99)]
    assert p99 < 10.0, f"p99 latency {p99:.2f}s over {wall:.2f}s wall"


def test_idle_fsms_cost_nothing():
    """Only notified FSMs reach a poller: 10k idle registrations generate
    zero handler calls."""
    router, system, state = make_system(pollers=2)
    for i in range(10_000):
        router.register(i)
    system.spawn()
    router.send(42, "only-this-one")
    time.sleep(0.3)
    system.shutdown()
    assert state["counts"] == {42: 1}


def test_hot_fsm_does_not_starve_others():
    """A flooding FSM is capped per round (messages_per_round) and must not
    keep quieter FSMs from being served promptly."""
    router = Router()
    state = {"mu": threading.Lock(), "counts": {}, "active": set(),
             "control": 0, "latencies": [], "work_s": 0.0005}
    system = BatchSystem(router, lambda: CountingHandler(state), pollers=1,
                         messages_per_round=16, name="hot-bs")
    router.register("hot")
    router.register("quiet")
    system.spawn()
    for _ in range(2000):
        router.send("hot", "x")
    t0 = time.monotonic()
    router.send("quiet", ("ts", t0))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with state["mu"]:
            if state["counts"].get("quiet"):
                break
        time.sleep(0.005)
    quiet_latency = time.monotonic() - t0
    system.shutdown()
    assert state["counts"].get("quiet") == 1
    # with a 16-message round cap the quiet FSM gets service long before the
    # 2000-message flood drains (which would take ~1s of handler work)
    assert quiet_latency < 0.5, f"quiet FSM waited {quiet_latency:.2f}s"


def test_release_renotifies_on_racing_send():
    """Messages sent while a poller holds the FSM are not lost: release()
    re-enqueues (mailbox.rs notify/release edge)."""
    router, system, state = make_system(pollers=1, work_s=0.02)
    router.register("a")
    system.spawn()
    router.send("a", "first")
    time.sleep(0.005)  # poller is now (likely) inside handle()
    router.send("a", "second")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with state["mu"]:
            if state["counts"].get("a") == 2:
                break
        time.sleep(0.005)
    system.shutdown()
    assert state["counts"]["a"] == 2


def test_closed_mailbox_rejects_and_drops():
    router, system, state = make_system(pollers=1)
    router.register("x")
    assert router.send("x", 1)
    router.close("x")
    assert not router.send("x", 2)
    system.spawn()
    time.sleep(0.2)
    system.shutdown()
    assert state["counts"].get("x") is None  # queued msg dropped at close


def test_control_fsm():
    router, system, state = make_system(pollers=2)
    system.spawn()
    for _ in range(10):
        router.send_control("ctl")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with state["mu"]:
            if state["control"] == 10:
                break
        time.sleep(0.01)
    system.shutdown()
    assert state["control"] == 10


def test_store_cluster_many_regions_bounded_latency():
    """Real stores: 3 server nodes, dozens of raft regions driven by the
    poller pool, concurrent writes to every region complete with bounded
    latency (the VERDICT r2 'batch system' acceptance shape, scaled to
    CI time; the 1,000-FSM bound above covers the generic mechanism)."""
    from tikv_tpu.raft.region import Peer as RegionPeer, Region, RegionEpoch
    from tikv_tpu.server.cluster import ServerCluster

    n_regions = 24
    # wall-clock bounds scale under the lock-order sanitizer: instrumented
    # acquisitions cost real time (TSan-style slowdown multiplier), and this
    # cluster pays one per mailbox/store/scheduler lock round
    slack = 3.0 if os.environ.get("TIKV_TPU_SANITIZE") == "1" else 1.0
    cluster = ServerCluster(3)
    try:
        cluster.start()
        # carve the keyspace into n_regions ranges, all replicated 3-way
        bounds = [b"" if i == 0 else b"k%03d" % i for i in range(n_regions)] + [b""]
        for i in range(n_regions):
            rid = 1 if i == 0 else cluster.alloc_id()
            peers = [RegionPeer(cluster.alloc_id(), sid) for sid in (1, 2, 3)]
            region = Region(rid, bounds[i], bounds[i + 1], RegionEpoch(), peers)
            cluster.pd.bootstrap_region(region.clone())
            for sid in (1, 2, 3):
                cluster.nodes[sid].store.create_peer(region)
            cluster.nodes[1].store.peers[rid].node.campaign()
        for i in range(n_regions):
            cluster.wait_leader(cluster.region_for_key(b"k%03d" % i if i else b"a"))
        lat = []
        t_all = time.monotonic()
        for round_ in range(3):
            for i in range(n_regions):
                key = (b"k%03dw" % i) if i else b"a-w"
                t0 = time.monotonic()
                cluster.must_put(key + str(round_).encode(), b"v",
                                 timeout=10 * slack)
                lat.append(time.monotonic() - t0)
        wall = time.monotonic() - t_all
        lat.sort()
        assert lat[int(len(lat) * 0.99)] < 5.0 * slack, \
            f"p99 {lat[-1]:.2f}s, wall {wall:.1f}s"
        for node in cluster.nodes.values():
            assert not node.node.thread_errors, node.node.thread_errors[:3]
    finally:
        cluster.shutdown()
