"""Multi-Raft store + cluster harness tests (reference: tests/integrations/
raftstore + components/test_raftstore)."""

import pytest

from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.raft.region import EpochError, NotLeaderError
from tikv_tpu.raft.store import PartitionFilter, RegionPacketFilter
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.storage import Storage


@pytest.fixture
def cluster():
    c = Cluster(3)
    c.run()
    return c


def test_put_get_replicated(cluster):
    cluster.must_put(b"k1", b"v1")
    assert cluster.must_get(b"k1") == b"v1"
    # all three stores applied it
    for sid in cluster.stores:
        assert cluster.get_on_store(sid, b"k1") == b"v1"
    cluster.must_delete(b"k1")
    assert cluster.must_get(b"k1") is None


def test_write_requires_leader(cluster):
    follower_store = None
    leader = cluster.wait_leader(FIRST_REGION_ID)
    for sid in cluster.stores:
        if sid != leader.store.store_id:
            follower_store = sid
            break
    kv = cluster.raftkv(follower_store)
    from tikv_tpu.storage.engine import WriteBatch

    wb = WriteBatch()
    wb.put_cf("default", b"k", b"v")
    with pytest.raises(NotLeaderError):
        kv.write({"region_id": FIRST_REGION_ID}, wb)


def test_leader_failover_preserves_data(cluster):
    cluster.must_put(b"k", b"v")
    leader = cluster.wait_leader(FIRST_REGION_ID)
    dead = leader.store.store_id
    cluster.stop_node(dead)
    other = next(sid for sid in cluster.stores if sid != dead)
    cluster.elect_leader(FIRST_REGION_ID, other)
    assert cluster.must_get(b"k") == b"v"
    cluster.must_put(b"k2", b"v2")
    # old leader restarts, catches up
    cluster.restart_node(dead)
    cluster.tick(5)
    assert cluster.get_on_store(dead, b"k2") == b"v2"


def test_split_region(cluster):
    cluster.must_put(b"a", b"1")
    cluster.must_put(b"m", b"2")
    cluster.must_put(b"z", b"3")
    new_id = cluster.split_region(FIRST_REGION_ID, b"m")
    assert cluster.region_for_key(b"a") == FIRST_REGION_ID
    assert cluster.region_for_key(b"m") == new_id
    assert cluster.region_for_key(b"z") == new_id
    # both regions keep serving reads and writes
    assert cluster.must_get(b"a") == b"1"
    assert cluster.must_get(b"m") == b"2"
    assert cluster.must_get(b"z") == b"3"
    cluster.must_put(b"b", b"4")
    cluster.must_put(b"x", b"5")
    assert cluster.must_get(b"b") == b"4"
    assert cluster.must_get(b"x") == b"5"


def test_split_epoch_check(cluster):
    leader = cluster.wait_leader(FIRST_REGION_ID)
    stale_epoch = (leader.region.epoch.conf_ver, leader.region.epoch.version)
    cluster.split_region(FIRST_REGION_ID, b"m")
    # command with the pre-split epoch must be rejected
    import threading

    res = []
    done = threading.Event()
    leader = cluster.wait_leader(FIRST_REGION_ID)
    leader.propose_cmd(
        {"epoch": stale_epoch, "ops": [("put", "default", b"a", b"x")]},
        lambda r: (res.append(r), done.set()),
    )
    while not done.is_set():
        cluster.process()
    assert isinstance(res[0], EpochError)


def test_conf_change_add_remove_peer():
    c = Cluster(4)
    region = c.bootstrap_subset([1, 2, 3])
    c.elect_leader(region.id, 1)
    c.must_put(b"k", b"v")
    # grow to store 4
    c.add_peer(region.id, 4)
    c.tick(5)
    assert c.get_on_store(4, b"k") == b"v"
    # writes reach the new peer
    c.must_put(b"k2", b"v2")
    c.tick(2)
    assert c.get_on_store(4, b"k2") == b"v2"
    # shrink: remove the peer on store 2
    leader = c.wait_leader(region.id)
    victim = leader.region.peer_on_store(2)
    c.remove_peer(region.id, victim.peer_id)
    c.tick(2)
    assert region.id not in c.stores[2].peers
    c.must_put(b"k3", b"v3")
    assert c.must_get(b"k3") == b"v3"


def test_lagging_removed_peer_is_tombstoned():
    """A peer that never RECEIVES its own removal entry (the leader stops
    replicating to it the moment the remove commits via the other replicas)
    must still be destroyed: the leader sends an explicit tombstone at the
    post-change epoch, and any later stale contact is answered with one
    (raftstore stale-peer GC)."""
    from tikv_tpu.raft.core import MsgType
    from tikv_tpu.raft.store import RegionPacketFilter

    c = Cluster(3)
    region = c.bootstrap()
    c.elect_leader(region.id, 1)
    c.must_put(b"k", b"v")
    # cut APPENDs to store 3 so it lags behind the removal entry
    filt = RegionPacketFilter(region.id, store_id=3, msg_types={MsgType.APPEND})
    c.transport.filters.append(filt)
    leader = c.wait_leader(region.id)
    victim = leader.region.peer_on_store(3)
    c.remove_peer(region.id, victim.peer_id)
    c.transport.filters.remove(filt)
    c.tick(3)
    assert region.id not in c.stores[3].peers, (
        "removed-but-lagging peer survived (tombstone lost AND no contact GC)"
    )
    # persisted identity erased too: a restart must not resurrect it
    c.stores[3].recover()
    assert region.id not in c.stores[3].peers


def test_stale_contact_draws_tombstone():
    """Backstop for a LOST removal-time tombstone: when the stale peer later
    campaigns, members answer the contact itself with a tombstone."""
    from tikv_tpu.raft.core import MsgType
    from tikv_tpu.raft.store import RegionPacketFilter

    c = Cluster(3)
    region = c.bootstrap()
    c.elect_leader(region.id, 1)
    c.must_put(b"k", b"v")
    # drop appends AND heartbeats to store 3: it learns nothing of its
    # removal, and the removal-time tombstone is dropped too
    filt = RegionPacketFilter(region.id, store_id=3)
    c.transport.filters.append(filt)
    leader = c.wait_leader(region.id)
    victim = leader.region.peer_on_store(3)
    c.remove_peer(region.id, victim.peer_id)
    c.tick(3)
    assert region.id in c.stores[3].peers  # fully isolated: still alive
    c.transport.filters.remove(filt)
    # the stale peer campaigns after silence; the contact draws a tombstone
    c.stores[3].peers[region.id].node.campaign()
    c.process()
    c.tick(3)
    assert region.id not in c.stores[3].peers


def test_partition_minority_stalls_majority_recovers(cluster):
    cluster.must_put(b"k", b"v1")
    leader = cluster.wait_leader(FIRST_REGION_ID)
    lsid = leader.store.store_id
    others = [sid for sid in cluster.stores if sid != lsid]
    cluster.transport.filters.append(PartitionFilter({lsid}, set(others)))
    # majority side elects a new leader and continues
    cluster.elect_leader(FIRST_REGION_ID, others[0])
    cluster.must_put(b"k", b"v2")
    cluster.transport.filters.clear()
    cluster.tick(5)
    # old leader converges
    assert cluster.get_on_store(lsid, b"k") == b"v2"


def test_snapshot_filter_blocks_then_catches_up(cluster):
    from tikv_tpu.raft.core import MsgType

    cluster.must_put(b"a", b"1")
    leader = cluster.wait_leader(FIRST_REGION_ID)
    lagging = next(sid for sid in cluster.stores if sid != leader.store.store_id)
    # drop all append traffic to the lagging store
    f = RegionPacketFilter(FIRST_REGION_ID, lagging, {MsgType.APPEND, MsgType.SNAPSHOT})
    cluster.transport.filters.append(f)
    for i in range(5):
        cluster.must_put(b"b%d" % i, b"x")
    assert cluster.get_on_store(lagging, b"b0") is None
    cluster.transport.filters.clear()
    cluster.tick(5)
    assert cluster.get_on_store(lagging, b"b4") == b"x"


def test_storage_over_raftkv(cluster):
    """Full stack: Percolator txn layer over the raft-replicated engine."""
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    leader = cluster.wait_leader(FIRST_REGION_ID)
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}
    r = store.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10), ctx
    )
    assert "errors" not in r
    store.sched_txn_command(Commit([Key.from_raw(b"k")], 10, 20), ctx)
    assert store.get(b"k", 30, ctx) == b"v"
    # the committed MVCC write replicated to every store
    for sid in cluster.stores:
        eng = cluster.stores[sid].engine
        from tikv_tpu.util import keys as keymod

        found = list(eng.scan_cf(CF_WRITE, b"", None))
        assert any(k.startswith(keymod.DATA_PREFIX) for k, _ in found)


def test_coprocessor_over_raft_region(cluster):
    """DAG pushdown over a RegionSnapshot — the full read path."""
    from tikv_tpu.copr.dag import BatchExecutorsRunner, DagRequest, TableScan
    from tikv_tpu.copr.executors import MvccScanSource
    from tikv_tpu.copr.mvcc_batch import MvccBatchScanSource
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__)))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_kvs

    leader = cluster.wait_leader(FIRST_REGION_ID)
    kv = cluster.raftkv(leader.store.store_id)
    store = Storage(engine=kv)
    ctx = {"region_id": FIRST_REGION_ID}
    for i, (rk, val) in enumerate(product_kvs()):
        ts = 10 + 2 * i
        store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(rk), val)], rk, ts), ctx)
        store.sched_txn_command(Commit([Key.from_raw(rk)], ts, ts + 1), ctx)
    snap = kv.snapshot(ctx)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    resp = BatchExecutorsRunner(dag, MvccScanSource(snap, 100, [record_range(TABLE_ID)])).handle_request()
    rows = resp.iter_rows()
    assert len(rows) == 6
    # vectorized MVCC source agrees over the raft snapshot too
    resp2 = BatchExecutorsRunner(
        DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)]),
        MvccBatchScanSource(snap, 100, [record_range(TABLE_ID)]),
    ).handle_request()
    assert resp2.encode() == resp.encode()


def test_store_recovery_from_persisted_state(cluster):
    """Kill a store's process state and rebuild it from the engine
    (PeerStorage recovery: fsm/store.rs init path)."""
    from tikv_tpu.raft.store import Store

    cluster.must_put(b"r1", b"v1")
    cluster.must_put(b"r2", b"v2")
    victim_id = 2
    old_store = cluster.stores[victim_id]
    old_peer = old_store.peers[FIRST_REGION_ID]
    applied_before = old_peer.node.applied
    # "crash": fresh Store object over the surviving engine
    new_store = Store(victim_id, cluster.transport, engine=old_store.engine)
    n = new_store.recover()
    assert n == 1
    peer = new_store.peers[FIRST_REGION_ID]
    assert peer.peer_id == old_peer.peer_id
    assert peer.region.voter_ids() == old_peer.region.voter_ids()
    assert peer.node.applied == applied_before
    assert peer.node.term == old_peer.node.term
    assert peer.node.log.last_index() >= applied_before
    # swap it into the cluster; replication continues to the recovered peer
    cluster.stores[victim_id] = new_store
    cluster.transport.register(new_store)
    cluster.must_put(b"r3", b"v3")
    cluster.tick(3)
    assert cluster.get_on_store(victim_id, b"r3") == b"v3"


def test_merge_regions(cluster):
    """Split then merge back: data survives, routing heals, source dies."""
    for k, v in [(b"a", b"1"), (b"m", b"2"), (b"z", b"3")]:
        cluster.must_put(k, v)
    right_id = cluster.split_region(FIRST_REGION_ID, b"m")
    cluster.must_put(b"q", b"4")
    cluster.merge_regions(FIRST_REGION_ID, right_id)
    # all keys route to the merged region and read back
    for k, v in [(b"a", b"1"), (b"m", b"2"), (b"q", b"4"), (b"z", b"3")]:
        assert cluster.region_for_key(k) == FIRST_REGION_ID
        assert cluster.must_get(k) == v
    # source peers destroyed everywhere
    for s in cluster.stores.values():
        assert right_id not in s.peers
    # merged region keeps accepting writes
    cluster.must_put(b"new", b"5")
    assert cluster.must_get(b"new") == b"5"


def test_merging_region_rejects_writes(cluster):
    import threading

    right_id = cluster.split_region(FIRST_REGION_ID, b"m")
    source = cluster.wait_leader(right_id)
    cmd = {
        "epoch": (source.region.epoch.conf_ver, source.region.epoch.version),
        "ops": [],
        "admin": ("prepare_merge", FIRST_REGION_ID),
    }
    cluster._run_admin(source, cmd)
    res, done = [], threading.Event()
    source.propose_cmd(
        {"epoch": (source.region.epoch.conf_ver, source.region.epoch.version),
         "ops": [("put", "default", b"x", b"y")]},
        lambda r: (res.append(r), done.set()),
    )
    while not done.is_set():
        cluster.process()
    assert isinstance(res[0], EpochError)


def test_lease_read_fast_path(cluster):
    """After quorum heartbeats the leader serves reads without ReadIndex."""
    cluster.must_put(b"k", b"v")
    leader = cluster.wait_leader(FIRST_REGION_ID)
    cluster.tick(3)  # heartbeat rounds grant the lease
    assert leader.node.lease_valid()
    reads_before = leader._read_seq
    assert cluster.must_get(b"k") == b"v"
    assert leader._read_seq == reads_before  # no ReadIndex issued
    # a deposed leader loses the lease
    other = next(sid for sid in cluster.stores if sid != leader.store.store_id)
    cluster.elect_leader(FIRST_REGION_ID, other)
    assert not leader.node.lease_valid()


def test_merging_flag_survives_recovery(cluster):
    """A restarted source peer must stay frozen mid-merge."""
    from tikv_tpu.raft.store import Store

    right_id = cluster.split_region(FIRST_REGION_ID, b"m")
    source = cluster.wait_leader(right_id)
    cmd = {
        "epoch": (source.region.epoch.conf_ver, source.region.epoch.version),
        "ops": [],
        "admin": ("prepare_merge", FIRST_REGION_ID),
    }
    cluster._run_admin(source, cmd)
    victim = source.store.store_id
    old = cluster.stores[victim]
    new_store = Store(victim, cluster.transport, engine=old.engine)
    new_store.recover()
    assert new_store.peers[right_id].merging is True


def test_learner_replicates_but_does_not_vote():
    """Learner flow (raft-rs learners): replicate → no quorum weight →
    promote → full voter."""
    c = Cluster(4)
    region = c.bootstrap_subset([1, 2])
    c.elect_leader(region.id, 1)
    c.must_put(b"k", b"v")
    pid = c.add_learner(region.id, 3)
    c.tick(5)
    # data reaches the learner
    assert c.get_on_store(3, b"k") == b"v"
    leader = c.wait_leader(region.id)
    assert pid in leader.node.learners and pid not in leader.node.voters
    # quorum is still 2-of-2 voters: stopping ONE voter stalls writes even
    # though the learner is alive
    c.stop_node(2)
    import pytest as _pytest

    with _pytest.raises(TimeoutError):
        kv = c.raftkv(leader.store.store_id)
        from tikv_tpu.storage.engine import WriteBatch

        wb = WriteBatch()
        wb.put_cf("default", b"stall", b"x")
        kv.write({"region_id": region.id}, wb)
    c.restart_node(2)
    c.tick(3)
    # promote: now 3 voters, quorum 2 — the learner counts
    c.promote_learner(region.id, pid)
    c.tick(2)
    leader = c.wait_leader(region.id)
    assert pid in leader.node.voters
    c.stop_node(2)
    c.must_put(b"after", b"y")  # 2-of-3 quorum via the promoted learner
    assert c.must_get(b"after") == b"y"


def test_pre_vote_prevents_term_inflation():
    """A partitioned node running election timeouts must not inflate the
    cluster term (pre-vote)."""
    c = Cluster(3)
    c.run()
    c.must_put(b"k", b"v")
    leader = c.wait_leader(FIRST_REGION_ID)
    term_before = leader.node.term
    isolated = next(s for s in c.stores if s != leader.store.store_id)
    from tikv_tpu.raft.store import PartitionFilter

    others = {s for s in c.stores if s != isolated}
    c.transport.filters.append(PartitionFilter({isolated}, others))
    # the isolated node times out many times — pre-vote keeps failing, term
    # must NOT grow
    iso_peer = c.stores[isolated].peers[FIRST_REGION_ID]
    for _ in range(60):
        iso_peer.node.tick()
        c.process()
    assert iso_peer.node.term == term_before
    c.transport.filters.clear()
    c.tick(3)
    # leader undisturbed on heal (no term churn)
    assert leader.node.is_leader()
    assert leader.node.term == term_before


def test_raft_log_gc_and_snapshot_catchup(cluster):
    """Logs compact past the threshold; a peer lagging beyond the slack is
    snapshot-seeded (store/worker/raftlog_gc.rs)."""
    from tikv_tpu.raft.core import MsgType
    from tikv_tpu.storage.engine import CF_RAFT
    from tikv_tpu.util import keys as keymod

    for i in range(60):
        cluster.must_put(b"lg%03d" % i, b"v")
    leader = cluster.wait_leader(FIRST_REGION_ID)
    lagging = next(sid for sid in cluster.stores if sid != leader.store.store_id)
    f = RegionPacketFilter(FIRST_REGION_ID, lagging, {MsgType.APPEND, MsgType.SNAPSHOT})
    cluster.transport.filters.append(f)
    for i in range(60, 120):
        cluster.must_put(b"lg%03d" % i, b"v")
    # compact every store's logs aggressively
    for s in cluster.stores.values():
        s.compact_raft_logs(threshold=20, slack=5)
    # leader kept at most ~threshold entries in memory and on disk
    assert leader.node.log.last_index() - leader.node.log.offset < 40
    log_prefix = keymod.region_raft_prefix(FIRST_REGION_ID) + keymod.RAFT_LOG_SUFFIX
    persisted = list(
        leader.store.engine.scan_cf(
            CF_RAFT, log_prefix, log_prefix[:-1] + bytes([log_prefix[-1] + 1])
        )
    )
    assert len(persisted) < 80
    # heal: the lagging peer catches up via SNAPSHOT (its gap was compacted)
    cluster.transport.filters.clear()
    cluster.tick(6)
    assert cluster.get_on_store(lagging, b"lg119") == b"v"
    lag_peer = cluster.stores[lagging].peers[FIRST_REGION_ID]
    assert lag_peer.node.log.snapshot_index > 0


def test_add_learner_on_existing_voter_is_noop(cluster):
    """add_learner targeting a voter must not demote it (views stay in
    lockstep with the raft node, which ignores such changes)."""
    leader = cluster.wait_leader(FIRST_REGION_ID)
    victim = next(p for p in leader.region.peers if p.peer_id != leader.peer_id)
    cmd = {
        "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
        "ops": [],
        "admin": ("conf_change", "add_learner", victim.peer_id, victim.store_id),
    }
    cluster._run_admin(leader, cmd)
    cluster.process()
    assert victim.peer_id in leader.node.voters
    assert leader.region.peer_by_id(victim.peer_id).role == "voter"
    # quorum still needs 2 of 3: stop one OTHER store and writes proceed
    other = next(
        p.store_id for p in leader.region.peers
        if p.peer_id not in (leader.peer_id, victim.peer_id)
    )
    cluster.stop_node(other)
    cluster.must_put(b"still", b"writes")
    assert cluster.must_get(b"still") == b"writes"


def test_merge_with_lagging_source_replica(cluster):
    """CatchUpLogs: CommitMerge carries the source leader's committed log
    tail, so a source replica that missed appends catches up from the payload
    instead of blocking the merge on quiesce (peer.rs CatchUpLogs)."""
    from tikv_tpu.raft.core import MsgType

    for k, v in [(b"a", b"1"), (b"m", b"2"), (b"z", b"3")]:
        cluster.must_put(k, v)
    right_id = cluster.split_region(FIRST_REGION_ID, b"m")
    src_leader = cluster.wait_leader(right_id)
    lagging = next(
        sid for sid in cluster.stores if sid != src_leader.store.store_id
    )
    # starve one source replica of ALL source-region replication
    f = RegionPacketFilter(right_id, lagging, {MsgType.APPEND, MsgType.SNAPSHOT})
    cluster.transport.filters.append(f)
    for i in range(5):
        cluster.must_put(b"q%d" % i, b"v%d" % i)  # source range (>= m)
    assert cluster.get_on_store(lagging, b"q0") is None  # genuinely lagging
    cluster.merge_regions(FIRST_REGION_ID, right_id)
    cluster.tick(5)  # commit_merge rides the (unfiltered) target region
    # still starved of source-region traffic: the data below can ONLY have
    # come from the CatchUpLogs payload inside the CommitMerge entry
    for i in range(5):
        assert cluster.get_on_store(lagging, b"q%d" % i) == b"v%d" % i, i
    cluster.transport.filters.clear()
    cluster.tick(3)
    assert cluster.get_on_store(lagging, b"z") == b"3"
    for s in cluster.stores.values():
        assert right_id not in s.peers
    cluster.must_put(b"post_merge", b"ok")
    assert cluster.must_get(b"post_merge") == b"ok"


def test_catch_up_applies_through_epoch_checks(cluster):
    """A committed-but-epoch-stale entry in the catch-up window must be
    rejected by the lagging replica exactly like every live replica rejected
    it — catch-up runs the NORMAL apply path, not a raw op executor."""
    from tikv_tpu.raft.core import MsgType
    from tikv_tpu.raft.store import encode_cmd

    cluster.must_put(b"m", b"2")
    right_id = cluster.split_region(FIRST_REGION_ID, b"m")
    src_leader = cluster.wait_leader(right_id)
    lagging = next(sid for sid in cluster.stores if sid != src_leader.store.store_id)
    f = RegionPacketFilter(right_id, lagging, {MsgType.APPEND, MsgType.SNAPSHOT})
    cluster.transport.filters.append(f)
    # a proposal that raced an epoch change: bypasses the propose-time check
    # (as a real in-flight proposal would) and commits, then every replica
    # rejects it at apply
    ep = src_leader.region.epoch
    stale = {
        "epoch": (ep.conf_ver, ep.version - 1),
        "ops": [("put", "default", b"q_stale", b"bad")],
    }
    src_leader.node.propose(encode_cmd(stale))
    cluster.process()
    cluster.must_put(b"q_good", b"ok")  # source range, current epoch
    cluster.merge_regions(FIRST_REGION_ID, right_id)
    cluster.tick(5)  # filter still on: catch-up comes from the payload
    assert cluster.get_on_store(lagging, b"q_good") == b"ok"
    for sid in cluster.stores:
        if FIRST_REGION_ID in cluster.stores[sid].peers or sid == lagging:
            assert cluster.get_on_store(sid, b"q_stale") is None, sid
    cluster.transport.filters.clear()


def test_merge_refused_before_freeze_when_straggler_needs_snapshot(cluster):
    """If the source log no longer reaches a straggler's applied index, the
    merge is refused BEFORE PrepareMerge freezes the source — a post-freeze
    refusal would wedge the region (the reference needs RollbackMerge for
    that; we make it unnecessary)."""
    from tikv_tpu.raft.core import MsgType

    cluster.must_put(b"m", b"x")
    right = cluster.split_region(FIRST_REGION_ID, b"m")
    lead = cluster.wait_leader(right)
    lag = next(sid for sid in cluster.stores if sid != lead.store.store_id)
    cluster.transport.filters.append(
        RegionPacketFilter(right, lag, {MsgType.APPEND, MsgType.SNAPSHOT})
    )
    for i in range(4):
        cluster.must_put(b"r%d" % i, b"y")
    # raft-log GC compacted the source leader's log above the straggler
    lead.node.log.compact_to(lead.node.commit - 1, lead.node.term)
    with pytest.raises(AssertionError, match="compacted below"):
        cluster.merge_regions(FIRST_REGION_ID, right)
    # source was never frozen: it keeps serving once the straggler heals
    cluster.transport.filters.clear()
    cluster.tick(5)
    cluster.must_put(b"still", b"alive")
    assert cluster.must_get(b"still") == b"alive"
    assert cluster.get_on_store(lag, b"r3") == b"y"


def test_unsafe_recover_restores_quorum(cluster):
    """tikv-ctl unsafe-recover remove-fail-stores: two of three stores die
    permanently; rewriting the survivor's persisted membership lets it elect
    itself and serve again (debug.rs remove_failed_stores)."""
    from tikv_tpu.raft.store import Store
    from tikv_tpu.server.debug import Debugger

    cluster.must_put(b"k", b"v")
    survivor = cluster.wait_leader(FIRST_REGION_ID).store.store_id
    dead = [sid for sid in cluster.stores if sid != survivor]
    for sid in dead:
        cluster.stop_node(sid)
    # the survivor alone cannot commit (2/3 quorum unreachable)
    import threading

    res, done = [], threading.Event()
    lead = cluster.stores[survivor].peers[FIRST_REGION_ID]
    lead.propose_cmd(
        {"epoch": (lead.region.epoch.conf_ver, lead.region.epoch.version),
         "ops": [("put", "default", b"stuck", b"x")]},
        lambda r: (res.append(r), done.set()),
    )
    cluster.tick(5)
    assert not done.is_set()  # stuck without quorum
    # offline surgery on the stopped store's engine, then restart
    eng = cluster.stores[survivor].engine
    modified = Debugger(eng).unsafe_recover(set(dead))
    assert FIRST_REGION_ID in modified
    new_store = Store(survivor, cluster.transport, engine=eng)
    assert new_store.recover() == 1
    peer = new_store.peers[FIRST_REGION_ID]
    assert peer.node.voters == {peer.peer_id}  # sole voter now
    cluster.stores[survivor] = new_store
    cluster.transport.register(new_store)
    cluster.elect_leader(FIRST_REGION_ID, survivor)
    cluster.must_put(b"recovered", b"yes")
    assert cluster.must_get(b"recovered") == b"yes"
    assert cluster.must_get(b"k") == b"v"  # old data intact


def test_region_properties(cluster):
    from tikv_tpu.server.debug import Debugger
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    leader = cluster.wait_leader(FIRST_REGION_ID)
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}
    for i in range(6):
        k = b"pk%d" % i
        store.sched_txn_command(
            Prewrite([Mutation.put(Key.from_raw(k), b"pv%d" % i)], k, 10 + 2 * i), ctx
        )
        store.sched_txn_command(Commit([Key.from_raw(k)], 10 + 2 * i, 11 + 2 * i), ctx)
    store.sched_txn_command(
        Prewrite([Mutation.delete(Key.from_raw(b"pk0"))], b"pk0", 50), ctx
    )
    store.sched_txn_command(Commit([Key.from_raw(b"pk0")], 50, 51), ctx)
    props = Debugger(leader.store.engine).region_properties(FIRST_REGION_ID)
    assert props["mvcc"]["num_puts"] == 6
    assert props["mvcc"]["num_deletes"] == 1
    assert props["mvcc"]["num_rows"] == 6  # distinct user keys
    assert props["mvcc"]["num_versions"] == 7  # pk0 has two versions
    assert props["mvcc"]["num_locks"] == 0
    assert props["mvcc"]["max_commit_ts"] >= props["mvcc"]["min_commit_ts"] > 0
    assert props["size"]["write"]["keys"] == 7
    assert props["middle_key"] is not None
    assert Debugger(leader.store.engine).region_properties(9999) is None


def test_witness_replica():
    """Witness (raftstore witness feature): a log-only voter — counts toward
    quorum and elections, stores NO data, never campaigns, never serves
    stale reads, and receives meta-only snapshots."""
    from tikv_tpu.raft.core import MsgType

    c = Cluster(4)
    c.bootstrap_subset([1, 2])
    c.elect_leader(FIRST_REGION_ID, 1)
    c.must_put(b"w1", b"v1")
    wpid = c.add_witness(FIRST_REGION_ID, 3)
    c.tick(5)
    leader = c.wait_leader(FIRST_REGION_ID)
    assert wpid in leader.node.voters and wpid in leader.node.witnesses
    # witness peer exists, advances its applied index, but stores NO data
    wpeer = c.stores[3].peers[FIRST_REGION_ID]
    assert wpeer.node.applied > 0
    assert c.get_on_store(3, b"w1") is None
    c.must_put(b"w2", b"v2")
    c.tick(3)
    assert c.get_on_store(3, b"w2") is None  # still no data
    assert c.get_on_store(2, b"w2") == b"v2"  # data replica has it
    # quorum arithmetic: data replica 2 dies; leader + witness = 2/3 quorum
    c.stop_node(2)
    c.must_put(b"w3", b"v3")
    assert c.must_get(b"w3") == b"v3"
    c.restart_node(2)
    c.tick(5)
    assert c.get_on_store(2, b"w3") == b"v3"
    # witness never campaigns on timeout
    c.stop_node(1)
    c.tick(60)
    lp = c.leader_peer(FIRST_REGION_ID)
    assert lp is None or lp.store.store_id != 3
    c.restart_node(1)
    c.tick(10)
    # witness role survives crash recovery of the witness store
    from tikv_tpu.raft.store import Store

    ns = Store(3, c.transport, engine=c.stores[3].engine)
    assert ns.recover() == 1
    assert ns.peers[FIRST_REGION_ID].peer_id in ns.peers[FIRST_REGION_ID].node.witnesses


def test_witness_rejects_stale_reads():
    from tikv_tpu.raft.region import NotLeaderError
    from tikv_tpu.sidecar.resolved_ts import ResolvedTsEndpoint

    c = Cluster(4)
    c.bootstrap_subset([1, 2])
    c.elect_leader(FIRST_REGION_ID, 1)
    c.add_witness(FIRST_REGION_ID, 3)
    c.tick(5)
    kv = c.raftkv(3)
    kv.resolved_ts = type("RT", (), {"progress_of": staticmethod(lambda rid: (10**18, 0))})()
    with pytest.raises(NotLeaderError):
        kv.snapshot({"region_id": FIRST_REGION_ID, "stale_read": True, "read_ts": 5})


def test_witness_review_fixes():
    """Split inherits the witness role; leadership transfer to a witness is
    refused; witness->data conversion reseeds with a full snapshot."""
    c = Cluster(4)
    c.bootstrap_subset([1, 2])
    c.elect_leader(FIRST_REGION_ID, 1)
    c.must_put(b"a", b"1")
    c.must_put(b"m", b"2")
    wpid = c.add_witness(FIRST_REGION_ID, 3)
    c.tick(5)
    # split: both children keep the witness role on store 3
    right = c.split_region(FIRST_REGION_ID, b"m")
    for rid in (FIRST_REGION_ID, right):
        p3 = c.stores[3].peers[rid]
        assert p3.peer_id in p3.node.witnesses, rid
        me = p3.region.peer_by_id(p3.peer_id)
        assert me.role == "witness"
    c.must_put(b"z", b"3")
    c.tick(3)
    assert c.get_on_store(3, b"z") is None  # child witness still log-only
    # transfer to the witness is refused: it never becomes candidate
    w = c.stores[3].peers[right]
    w.node.campaign()
    c.process()
    assert not w.node.is_leader()
    # witness -> data voter conversion reseeds via snapshot
    leader = c.wait_leader(right)
    cmd = {
        "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
        "ops": [],
        "admin": ("conf_change", "add", w.peer_id, 3),
    }
    c._run_admin(leader, cmd)
    c.tick(8)
    assert w.peer_id not in c.wait_leader(right).node.witnesses
    assert c.get_on_store(3, b"z") == b"3"  # data arrived with the reseed
