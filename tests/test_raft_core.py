"""Raft consensus core tests over a deterministic in-memory network."""

import random

import pytest

from tikv_tpu.raft.core import Entry, Message, MsgType, RaftNode, Role, Snapshot


class Net:
    """Deterministic simulator: drives ticks and delivers messages, with
    per-link drop/partition control (transport_simulate.rs in miniature)."""

    def __init__(self, n, seed=0):
        self.nodes = {i: RaftNode(i, list(range(1, n + 1)), rng=random.Random(seed * 100 + i)) for i in range(1, n + 1)}
        self.cut: set[tuple[int, int]] = set()
        self.applied: dict[int, list[bytes]] = {i: [] for i in self.nodes}
        self.persisted: dict[int, list[Entry]] = {i: [] for i in self.nodes}
        self.reads: dict[int, list[tuple[bytes, int]]] = {i: [] for i in self.nodes}

    def partition(self, a: int, b: int):
        self.cut.add((a, b))
        self.cut.add((b, a))

    def heal(self):
        self.cut.clear()

    def drain(self, max_rounds=50):
        for _ in range(max_rounds):
            moved = False
            for i, node in self.nodes.items():
                rd = node.ready()
                if rd.entries:
                    self.persisted[i].extend(rd.entries)
                if rd.read_states:
                    self.reads[i].extend(rd.read_states)
                for e in rd.committed_entries:
                    if e.conf_change is not None:
                        node.apply_conf_change(e.conf_change)
                    elif e.data:
                        self.applied[i].append(e.data)
                for m in rd.messages:
                    if (m.frm, m.to) in self.cut or m.to not in self.nodes:
                        continue
                    if m.type == MsgType.SNAPSHOT and m.snapshot is None:
                        # container duty: materialize a snapshot of applied state
                        src = self.nodes[m.frm]
                        m.snapshot = Snapshot(
                            index=src.applied, term=src.log.term_at(src.applied) or src.term,
                            data=b"|".join(self.applied[m.frm]), voters=tuple(src.voters),
                        )
                    self.nodes[m.to].step(m)
                    moved = True
            if not moved:
                return

    def tick_all(self, n=1):
        for _ in range(n):
            for node in self.nodes.values():
                node.tick()
            self.drain()

    def leader(self):
        leaders = [n for n in self.nodes.values() if n.role == Role.LEADER]
        return leaders[0] if len(leaders) == 1 else None

    def elect(self, node_id=1):
        self.nodes[node_id].campaign()
        self.drain()
        assert self.nodes[node_id].role == Role.LEADER
        return self.nodes[node_id]


def test_single_node_self_elects():
    net = Net(1)
    net.tick_all(25)
    assert net.nodes[1].role == Role.LEADER
    idx = net.nodes[1].propose(b"x")
    assert idx is not None
    net.drain()
    assert net.applied[1] == [b"x"]


def test_election_and_replication():
    net = Net(3)
    leader = net.elect(1)
    for i in range(5):
        leader.propose(b"cmd%d" % i)
    net.drain()
    expect = [b"cmd%d" % i for i in range(5)]
    for i in net.nodes:
        assert net.applied[i] == expect


def test_leader_failover():
    net = Net(3)
    net.elect(1)
    net.nodes[1].propose(b"a")
    net.drain()
    # isolate the leader; remaining two elect a new one
    net.partition(1, 2)
    net.partition(1, 3)
    net.nodes[2].campaign()
    net.drain()
    assert net.nodes[2].role == Role.LEADER
    net.nodes[2].propose(b"b")
    net.drain()
    assert net.applied[2] == [b"a", b"b"]
    assert net.applied[3] == [b"a", b"b"]
    # healed old leader catches up and steps down
    net.heal()
    net.tick_all(3)
    assert net.nodes[1].role == Role.FOLLOWER
    assert net.applied[1] == [b"a", b"b"]


def test_minority_cannot_commit():
    net = Net(3)
    net.elect(1)
    net.partition(1, 2)
    net.partition(1, 3)
    net.nodes[1].propose(b"lost")
    net.drain()
    assert net.applied[1] == []  # no quorum, never commits
    # majority side moves on with a higher term
    net.nodes[2].campaign()
    net.drain()
    net.nodes[2].propose(b"kept")
    net.drain()
    net.heal()
    net.tick_all(3)
    # the divergent entry is overwritten everywhere
    for i in net.nodes:
        assert net.applied[i] == [b"kept"], i


def test_log_consistency_check_backtracks():
    net = Net(3)
    leader = net.elect(1)
    for i in range(4):
        leader.propose(b"x%d" % i)
    net.drain()
    # peer 3 misses a batch
    net.partition(1, 3)
    for i in range(4, 8):
        leader.propose(b"x%d" % i)
    net.drain()
    net.heal()
    leader.propose(b"final")
    net.drain()
    assert net.applied[3] == [b"x%d" % i for i in range(8)] + [b"final"]


def test_conf_change_add_and_remove():
    net = Net(3)
    leader = net.elect(1)
    leader.propose(b"a")
    net.drain()
    # add node 4
    net.nodes[4] = RaftNode(4, [])  # empty config; learns via snapshot/append
    net.nodes[4].voters = {1, 2, 3, 4}
    net.applied[4] = []
    net.persisted[4] = []
    leader.propose_conf_change(("add", 4))
    net.drain()
    assert 4 in leader.voters
    leader.propose(b"b")
    net.drain()
    assert net.applied[4] == [b"a", b"b"]
    # remove node 3: quorum becomes 2 of {1,2,4}
    leader.propose_conf_change(("remove", 3))
    net.drain()
    assert 3 not in leader.voters
    net.partition(1, 3)
    leader.propose(b"c")
    net.drain()
    assert net.applied[1][-1] == b"c"


def test_snapshot_catchup_after_compaction():
    net = Net(3)
    leader = net.elect(1)
    for i in range(5):
        leader.propose(b"s%d" % i)
    net.drain()
    net.partition(1, 3)
    net.partition(2, 3)
    for i in range(5, 10):
        leader.propose(b"s%d" % i)
    net.drain()
    # compact the leader's log beyond peer 3's position
    leader.log.compact_to(leader.applied, leader.log.term_at(leader.applied))
    net.heal()
    net.tick_all(3)
    leader.propose(b"post")
    net.drain()
    assert net.applied[3][-1] == b"post"
    # node 3 received a snapshot covering the compacted prefix
    assert net.nodes[3].log.snapshot_index > 0


def test_read_index():
    net = Net(3)
    leader = net.elect(1)
    leader.propose(b"v")
    net.drain()
    leader.read_index(b"ctx1")
    net.drain()
    states = net.reads[leader.id]
    assert states and states[0][0] == b"ctx1"
    assert states[0][1] >= 2  # noop + proposal committed
    # follower-forwarded read index
    net.nodes[2].read_index(b"ctx2")
    net.drain()
    assert net.reads[2] and net.reads[2][0][0] == b"ctx2"


def test_stale_term_candidate_rejected():
    net = Net(3)
    net.elect(1)
    # node 3 goes stale and campaigns with an old log
    net.partition(1, 3)
    net.partition(2, 3)
    net.nodes[1].propose(b"new")
    net.drain()
    net.heal()
    net.nodes[3].campaign()
    net.drain()
    # 3 cannot win with a shorter log; cluster converges back to a real leader
    net.tick_all(25)
    leader = net.leader()
    assert leader is not None and leader.id in (1, 2)


def test_read_index_waits_for_current_term_commit():
    """A fresh leader must not serve ReadIndex before committing in its term
    (the stale-read scenario from Raft §6.4)."""
    net = Net(3)
    net.elect(1)
    net.nodes[1].propose(b"w")
    net.drain()
    # force a fresh election: node 2 takes over
    net.partition(1, 2)
    net.partition(1, 3)
    net.nodes[2].campaign()
    # don't drain yet — step only the vote exchange so the noop is NOT committed
    for m in net.nodes[2].ready().messages:
        if m.to == 3:
            net.nodes[3].step(m)
    for m in net.nodes[3].ready().messages:
        if m.to == 2:
            net.nodes[2].step(m)
    assert net.nodes[2].role == Role.LEADER
    assert not net.nodes[2]._committed_in_term()
    net.nodes[2].read_index(b"early")
    # read must NOT be released yet
    rd = net.nodes[2].ready()
    assert rd.read_states == []
    # re-inject its messages and finish the round: noop commits, read releases
    for m in rd.messages:
        if (2, m.to) not in net.cut and m.to in net.nodes:
            net.nodes[m.to].step(m)
    if rd.entries:
        net.persisted[2].extend(rd.entries)
    net.drain()
    assert net.reads[2] and net.reads[2][0][0] == b"early"
    idx = net.reads[2][0][1]
    assert net.nodes[2].log.term_at(idx) is not None


def test_vote_stickiness_protects_leases():
    """A follower that recently heard from its leader rejects natural
    (timeout) campaigns; explicit transfers still go through."""
    net = Net(3)
    net.elect(1)
    net.tick_all(2)  # fresh heartbeats
    # node 3 campaigns WITHOUT the transfer override (natural timeout)
    net.nodes[3].campaign(force=False)
    net.drain()
    assert net.nodes[3].role != Role.LEADER  # rejected by sticky followers
    assert net.nodes[1].role == Role.LEADER
    # explicit transfer (force) succeeds
    net.nodes[2].campaign(force=True)
    net.drain()
    assert net.nodes[2].role == Role.LEADER


def test_hibernation_cycle():
    """Idle groups stop exchanging messages; any proposal wakes them."""
    net = Net(3)
    for n in net.nodes.values():
        n.hibernate_after = 5
    leader = net.elect(1)
    leader.propose(b"x")
    net.drain()
    net.tick_all(10)  # idle: hibernate round happens in here
    assert all(n.hibernated for n in net.nodes.values())
    # hibernated: ticks produce NO messages
    for n in net.nodes.values():
        n.tick()
    msgs = sum(len(n.ready().messages) for n in net.nodes.values())
    assert msgs == 0
    # a new proposal wakes the group and commits normally
    idx = leader.propose(b"y")
    assert idx is not None and not leader.hibernated
    net.drain()
    assert net.applied[2][-1] == b"y"
    assert not net.nodes[2].hibernated
    # followers did not campaign while frozen
    assert leader.role == Role.LEADER


def test_stale_hibernate_heartbeat_cannot_freeze_higher_term():
    net = Net(3)
    net.elect(1)
    net.drain()
    from tikv_tpu.raft.core import _HIBERNATE_CTX

    stale = Message(MsgType.HEARTBEAT, frm=1, to=3, term=net.nodes[3].term - 1,
                    context=_HIBERNATE_CTX)
    net.nodes[3].step(stale)
    assert not net.nodes[3].hibernated  # stale term rejected, no freeze


def test_hibernated_leader_lease_dies_and_read_wakes():
    net = Net(3)
    for n in net.nodes.values():
        n.hibernate_after = 3
    leader = net.elect(1)
    leader.propose(b"x")
    net.drain()
    net.tick_all(3)  # heartbeats grant a lease while awake
    net.tick_all(8)  # then the group hibernates
    assert leader.hibernated
    assert not leader.lease_valid()  # frozen clock must not preserve leases
    # a read on the hibernated leader wakes it and completes
    leader.read_index(b"r")
    net.drain()
    assert not leader.hibernated
    assert net.reads[1] and net.reads[1][-1][0] == b"r"


def test_hibernated_group_elects_after_leader_death():
    """Pre-vote requests must wake hibernated peers, or a dead leader leaves
    the group leaderless forever."""
    net = Net(3)
    for n in net.nodes.values():
        n.hibernate_after = 3
    net.elect(1)
    net.nodes[1].propose(b"x")
    net.drain()
    net.tick_all(10)
    assert all(n.hibernated for n in net.nodes.values())
    # leader dies; a client request wakes follower 2 which must eventually win
    del net.nodes[1]
    net.nodes[2]._wake()
    for _ in range(80):
        for n in net.nodes.values():
            n.tick()
        net.drain()
        if any(n.role == Role.LEADER for n in net.nodes.values()):
            break
    assert any(n.role == Role.LEADER for n in net.nodes.values())


def test_read_index_ignores_learner_acks():
    """Learner heartbeat acks carry no read-quorum weight."""
    net = Net(3)
    leader = net.elect(1)
    leader.propose(b"v")
    net.drain()
    # add learner 4
    net.nodes[4] = RaftNode(4, [])
    net.nodes[4].voters = {1, 2, 3}
    net.nodes[4].learners = {4}
    net.applied[4] = []
    net.persisted[4] = []
    net.reads[4] = []
    leader.propose_conf_change(("add_learner", 4))
    net.drain()
    assert 4 in leader.learners
    # partition leader+learner away from the voters
    net.partition(1, 2)
    net.partition(1, 3)
    leader.read_index(b"stale?")
    net.drain()  # learner acks flow, voters don't
    assert net.reads[1] == []  # must NOT serve with only a learner ack


def test_stale_append_below_snapshot_is_ignored():
    """A late retransmit of pre-snapshot entries must not splice them into
    the log (offset-based index arithmetic would corrupt) or regress commit."""
    n = RaftNode(2, [1, 2, 3])
    n.term = 1
    n.log.reset_to_snapshot(Snapshot(index=4, term=1, data=b"", voters=(1, 2, 3)))
    n.commit = n.applied = 4
    n.step(
        Message(
            MsgType.APPEND, 1, 2, 1, log_index=0, log_term=0,
            entries=[Entry(1, 1, b"a"), Entry(1, 2, b"b"), Entry(1, 3, b"c")],
            commit=4,
        )
    )
    assert n.log.entries == []
    assert n.log.last_index() == 4
    assert n.commit == 4
    rd = n.ready()
    resps = [m for m in rd.messages if m.type == MsgType.APPEND_RESP]
    assert resps and not resps[0].reject and resps[0].log_index >= 4
