"""Durability tests for the native engine: WAL commit/replay, checkpoint
spill + truncation, torn-tail recovery, crash (kill -9) recovery across a
real process boundary, and raft-store recovery over a durable engine.

Reference contracts re-expressed: components/engine_rocks/src/engine.rs:1
(WAL + memtable flush), components/raft_log_engine/src/engine.rs:25
(purpose-built durable log), raftstore/src/store/peer_storage.rs:1
(RaftLocalState/ApplyState recovery on boot).
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tikv_tpu.storage.engine import CF_DEFAULT, CF_LOCK, CF_RAFT, WriteBatch

native = pytest.importorskip("tikv_tpu.native.engine")
if not native.native_available():  # pragma: no cover
    pytest.skip("native engine unavailable", allow_module_level=True)

from tikv_tpu.native.engine import NativeEngine  # noqa: E402


def test_reopen_recovers_writes_and_tombstones(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"a", b"1")
    wb.put_cf(CF_RAFT, b"rs", b"hardstate")
    e.write(wb)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"b", b"2")
    wb.delete_cf(CF_DEFAULT, b"a")
    e.write(wb)
    seq = e.seq()
    e.close()
    e2 = NativeEngine(path=d)
    assert e2.seq() == seq
    assert e2.get_cf(CF_DEFAULT, b"a") is None
    assert e2.get_cf(CF_DEFAULT, b"b") == b"2"
    assert e2.get_cf(CF_RAFT, b"rs") == b"hardstate"
    e2.close()


def test_delete_range_is_durable(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    wb = WriteBatch()
    for i in range(10):
        wb.put_cf(CF_DEFAULT, b"k%02d" % i, b"v%d" % i)
    e.write(wb)
    wb = WriteBatch()
    wb.delete_range_cf(CF_DEFAULT, b"k03", b"k07")
    e.write(wb)
    e.close()
    e2 = NativeEngine(path=d)
    got = [k for k, _ in e2.scan_cf(CF_DEFAULT, b"", None)]
    assert got == [b"k00", b"k01", b"k02", b"k07", b"k08", b"k09"]
    e2.close()


def test_checkpoint_truncates_wal_and_recovers(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    for i in range(50):
        wb = WriteBatch()
        wb.put_cf(CF_DEFAULT, b"k%03d" % i, b"v" * 100)
        e.write(wb)
    assert e.wal_bytes() > 0
    e.checkpoint()
    assert e.wal_bytes() == 0
    files = os.listdir(d)
    # the flush produced a sorted run + its completion marker, no legacy ckpt
    assert sum(f.startswith("run0-") for f in files) == 1
    assert sum(f.startswith("mark-") for f in files) == 1
    assert not any(f.startswith("ckpt-") for f in files)
    assert sum(f.startswith("wal-") for f in files) == 1
    assert e.run_count("default") == 1
    assert e.mem_bytes() == 0  # memtable cleared: memory stays flat
    # post-checkpoint writes land in the fresh WAL segment
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"after", b"x")
    e.write(wb)
    e.close()
    e2 = NativeEngine(path=d)
    assert e2.get_cf(CF_DEFAULT, b"k000") == b"v" * 100
    assert e2.get_cf(CF_DEFAULT, b"k049") == b"v" * 100
    assert e2.get_cf(CF_DEFAULT, b"after") == b"x"
    e2.close()


def test_auto_flush_on_wal_limit(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d, wal_limit=4096)
    for i in range(100):
        wb = WriteBatch()
        wb.put_cf(CF_DEFAULT, b"k%03d" % i, b"v" * 200)
        e.write(wb)
    assert any(f.startswith("run0-") for f in os.listdir(d))
    assert e.wal_bytes() < 4096 + 4096  # truncated at least once
    e.close()
    e2 = NativeEngine(path=d)
    assert e2.get_cf(CF_DEFAULT, b"k000") == b"v" * 200
    assert e2.get_cf(CF_DEFAULT, b"k099") == b"v" * 200
    e2.close()


def test_torn_wal_tail_keeps_committed_prefix(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    for i in range(5):
        wb = WriteBatch()
        wb.put_cf(CF_DEFAULT, b"k%d" % i, b"v%d" % i)
        e.write(wb)
    e.close()
    wal = [f for f in os.listdir(d) if f.startswith("wal-")]
    assert len(wal) == 1
    # simulate a torn append: garbage bytes at the tail
    with open(os.path.join(d, wal[0]), "ab") as f:
        f.write(b"\x13\x00\x00\x00GARBAGE-TORN-RECORD")
    e2 = NativeEngine(path=d)
    for i in range(5):
        assert e2.get_cf(CF_DEFAULT, b"k%d" % i) == b"v%d" % i
    # the engine keeps accepting writes after recovery
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"new", b"nv")
    e2.write(wb)
    e2.close()
    e3 = NativeEngine(path=d)
    assert e3.get_cf(CF_DEFAULT, b"new") == b"nv"
    e3.close()


def test_torn_tail_in_reused_segment_does_not_hide_new_writes(tmp_path):
    """A torn record at the head of the CURRENT segment (seq == segment start,
    i.e. right after a checkpoint) must be truncated on recovery — otherwise
    reopening the same file with O_APPEND puts acked post-recovery writes
    BEHIND the torn bytes, unreachable by every later replay."""
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"base", b"bv")
    e.write(wb)
    e.checkpoint()  # fresh wal-<seq> segment, empty
    e.close()
    wal = [f for f in os.listdir(d) if f.startswith("wal-")]
    assert len(wal) == 1
    with open(os.path.join(d, wal[0]), "ab") as f:
        f.write(b"\x40\x00\x00\x00TORN-FIRST-RECORD")  # torn at offset 0
    e2 = NativeEngine(path=d)  # seq == segment start: segment is REUSED
    assert e2.get_cf(CF_DEFAULT, b"base") == b"bv"
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"after", b"av")
    e2.write(wb)
    e2.close()
    e3 = NativeEngine(path=d)
    assert e3.get_cf(CF_DEFAULT, b"after") == b"av", (
        "acked post-recovery write lost behind a torn record"
    )
    assert e3.get_cf(CF_DEFAULT, b"base") == b"bv"
    e3.close()


def test_corrupt_checkpoint_falls_back_to_older(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"base", b"1")
    e.write(wb)
    e.checkpoint()
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"later", b"2")
    e.write(wb)
    e.close()
    # forge a newer-but-corrupt checkpoint: recovery must skip it and use
    # the valid one + WAL
    with open(os.path.join(d, "ckpt-ffffffffffffffff"), "wb") as f:
        f.write(b"TKCK1\n" + b"\xff" * 40)
    e2 = NativeEngine(path=d)
    assert e2.get_cf(CF_DEFAULT, b"base") == b"1"
    assert e2.get_cf(CF_DEFAULT, b"later") == b"2"
    e2.close()


def test_mem_accounting_moves_both_ways(tmp_path):
    e = NativeEngine()
    base = e.mem_bytes()
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"k", b"x" * 10_000)
    e.write(wb)
    grown = e.mem_bytes()
    assert grown >= base + 10_000
    # overwrite with a small value: old version compacted away (no snapshot)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"k", b"y")
    e.write(wb)
    assert e.mem_bytes() < grown
    e.close()


_CRASH_WRITER = textwrap.dedent(
    """
    import sys
    from tikv_tpu.native.engine import NativeEngine
    from tikv_tpu.storage.engine import CF_DEFAULT, WriteBatch

    e = NativeEngine(path=sys.argv[1])
    i = 0
    while True:
        wb = WriteBatch()
        wb.put_cf(CF_DEFAULT, b"key-%08d" % i, b"value-%d" % i)
        e.write(wb)
        # the write returned: it is ACKED — print AFTER, so every acked
        # index the parent observes must survive the kill -9
        print(i, flush=True)
        i += 1
    """
)


def test_kill9_mid_workload_recovers_all_acked_writes(tmp_path):
    """The VERDICT's durability contract: kill -9 a process mid-workload,
    reopen the engine directory, every acknowledged write is recovered."""
    d = str(tmp_path / "db")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CRASH_WRITER, d],
        stdout=subprocess.PIPE,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    acked = -1
    deadline = time.time() + 30
    while acked < 25 and time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        acked = int(line)
    assert acked >= 25, f"writer too slow or died early (acked={acked})"
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    e = NativeEngine(path=d)
    for i in range(acked + 1):
        assert e.get_cf(CF_DEFAULT, b"key-%08d" % i) == b"value-%d" % i, i
    e.close()


def test_raft_store_recovery_across_process_boundary(tmp_path):
    """Boot a raft store over a durable engine in a CHILD process, commit
    writes through the raft propose/apply path, kill -9 the process, then
    recover the store here: region meta, raft state and applied data all
    come back (peer_storage.rs recovery semantics)."""
    d = str(tmp_path / "store")
    child = textwrap.dedent(
        """
        import sys
        from tikv_tpu.native.engine import NativeEngine
        from tikv_tpu.raft.cluster import FIRST_REGION_ID
        from tikv_tpu.raft.store import ChannelTransport, Store
        from tikv_tpu.raft.raftkv import RaftKv
        from tikv_tpu.storage.storage import Storage
        from tikv_tpu.storage.txn.commands import Commit, Prewrite
        from tikv_tpu.storage.txn_types import Key, Mutation
        from tikv_tpu.server.node import Node
        from tikv_tpu.pd.client import MockPd

        eng = NativeEngine(path=sys.argv[1])
        transport = ChannelTransport()
        pd = MockPd()
        node = Node(pd, transport, engine=eng)
        transport.register(node.store)
        node.try_bootstrap_cluster([node.store_id])
        node.create_region_peers()
        peer = node.store.peers[FIRST_REGION_ID]
        peer.node.campaign()
        node.pump()
        assert peer.node.is_leader()

        def pump():
            node.store.process_messages()
            node.store.handle_readies()

        storage = Storage(engine=RaftKv(node.store, pump=pump))
        ctx = {"region_id": FIRST_REGION_ID}
        ts = 10
        for i in range(20):
            k = b"rk-%04d" % i
            storage.sched_txn_command(
                Prewrite([Mutation.put(Key.from_raw(k), b"rv-%d" % i)], k, ts), ctx)
            storage.sched_txn_command(Commit([Key.from_raw(k)], ts, ts + 1), ctx)
            node.pump()
            ts += 10
            print(i, flush=True)
        print("READY %d" % node.store_id, flush=True)
        import time
        time.sleep(60)  # parent kills us here
        """
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", child, d],
        stdout=subprocess.PIPE,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    deadline = time.time() + 60
    store_id = None
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith(b"READY"):
            store_id = int(line.split()[1])
            break
    assert store_id is not None, "child store never finished its workload"
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.cluster import FIRST_REGION_ID
    from tikv_tpu.raft.raftkv import RaftKv
    from tikv_tpu.raft.store import ChannelTransport, Store
    from tikv_tpu.storage.storage import Storage

    eng = NativeEngine(path=d)
    transport = ChannelTransport()
    store = Store(store_id, transport, engine=eng)
    transport.register(store)
    n = store.recover()
    assert n == 1  # the bootstrapped region came back from RegionLocalState
    peer = store.peers[FIRST_REGION_ID]
    peer.node.campaign()
    store.process_messages()
    assert peer.node.is_leader()

    def pump():
        store.process_messages()
        store.handle_readies()

    storage = Storage(engine=RaftKv(store, pump=pump))
    ctx = {"region_id": FIRST_REGION_ID}
    for i in range(20):
        assert storage.get(b"rk-%04d" % i, 10_000, ctx) == b"rv-%d" % i, i


# ------------------------------------------------------- compaction + props

def test_compaction_erases_tombstoned_keys():
    from tikv_tpu.storage.engine import WriteBatch

    e = NativeEngine()
    wb = WriteBatch()
    for i in range(200):
        wb.put_cf("default", b"k%04d" % i, b"v" * 32)
    e.write(wb)
    wb = WriteBatch()
    for i in range(120):
        wb.delete_cf("default", b"k%04d" % i)
    e.write(wb)
    mem_before = e.mem_bytes()
    dropped = e.compact(slice_keys=16)  # force many slices
    assert dropped >= 120
    assert e._lib.eng_stats_keys(e._handle, 0) == 80
    assert e.mem_bytes() < mem_before
    # survivors still readable
    snap = e.snapshot()
    assert snap.get_cf("default", b"k0150") == b"v" * 32
    assert snap.get_cf("default", b"k0000") is None
    snap.release()
    e.close()


def test_compaction_respects_live_snapshots():
    from tikv_tpu.storage.engine import WriteBatch

    e = NativeEngine()
    wb = WriteBatch()
    wb.put_cf("default", b"a", b"old")
    e.write(wb)
    snap = e.snapshot()  # pins the pre-delete state
    wb = WriteBatch()
    wb.delete_cf("default", b"a")
    e.write(wb)
    e.compact()
    # the old snapshot still sees the value — compaction must not erase it
    assert snap.get_cf("default", b"a") == b"old"
    snap.release()
    # once the snapshot is gone, compaction erases the key
    e.compact()
    assert e._lib.eng_stats_keys(e._handle, 0) == 0
    e.close()


def test_auto_compaction_thread():
    from tikv_tpu.storage.engine import WriteBatch

    e = NativeEngine()
    wb = WriteBatch()
    wb.put_cf("default", b"x", b"1")
    wb.delete_cf("default", b"x")
    e.write(wb)
    e.start_auto_compaction(interval_s=0.05)
    deadline = time.time() + 5
    while time.time() < deadline and e._lib.eng_stats_keys(e._handle, 0):
        time.sleep(0.05)
    assert e._lib.eng_stats_keys(e._handle, 0) == 0
    e.stop_auto_compaction()
    e.close()


def test_mvcc_properties_drive_need_gc():
    from tikv_tpu.storage.engine import WriteBatch
    from tikv_tpu.storage.txn_types import Write, WriteType, append_ts

    e = NativeEngine()
    wb = WriteBatch()
    # 10 rows x 3 versions, newest is a DELETE for half of them
    for i in range(10):
        user = b"row%02d" % i
        for ts in (10, 20, 30):
            wt = WriteType.DELETE if (ts == 30 and i % 2 == 0) else WriteType.PUT
            wb.put_cf("write", append_ts(user, ts), Write(wt, ts - 1).to_bytes())
    e.write(wb)
    p = e.mvcc_properties()
    assert p["num_rows"] == 10
    assert p["num_entries"] == 30
    assert p["num_deletes"] == 5
    assert p["num_puts"] == 25
    assert (p["min_commit_ts"], p["max_commit_ts"]) == (10, 30)
    assert p["max_row_versions"] == 3
    assert e.need_gc(safe_point=35)
    # nothing visible below the safe point → no GC needed
    assert not e.need_gc(safe_point=5)
    e.close()


def test_durability_survives_compaction():
    from tikv_tpu.storage.engine import WriteBatch

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        e = NativeEngine(path=d)
        wb = WriteBatch()
        wb.put_cf("default", b"keep", b"1")
        wb.put_cf("default", b"gone", b"2")
        e.write(wb)
        wb = WriteBatch()
        wb.delete_cf("default", b"gone")
        e.write(wb)
        e.compact()
        e.close()
        e2 = NativeEngine(path=d)
        snap = e2.snapshot()
        assert snap.get_cf("default", b"keep") == b"1"
        assert snap.get_cf("default", b"gone") is None
        snap.release()
        e2.close()


# ------------------------------------------------------------------ SST files

def test_sst_build_ingest_recover_checkpoint():
    import tempfile as _tf

    from tikv_tpu.native.engine import build_sst

    with _tf.TemporaryDirectory() as d:
        sst = os.path.join(d, "load.sst")
        build_sst(sst, [("default", b"k%04d" % i, b"v%d" % i) for i in range(1000)])
        e = NativeEngine(path=os.path.join(d, "db"))
        e.ingest_sst(sst)
        snap = e.snapshot()
        assert snap.get_cf("default", b"k0500") == b"v500"
        snap.release()
        e.close()
        # reopen: the WAL op-4 reference replays from the copied sst file
        e2 = NativeEngine(path=os.path.join(d, "db"))
        s2 = e2.snapshot()
        assert s2.get_cf("default", b"k0999") == b"v999"
        s2.release()
        e2.checkpoint()  # folds + deletes the sst segment
        assert not [f for f in os.listdir(os.path.join(d, "db")) if f.startswith("sst-")]
        e2.close()
        e3 = NativeEngine(path=os.path.join(d, "db"))
        s3 = e3.snapshot()
        assert s3.get_cf("default", b"k0001") == b"v1"
        s3.release()
        e3.close()


def test_sst_rejects_unsorted_and_corrupt():
    import tempfile as _tf

    from tikv_tpu.native.engine import build_sst

    with _tf.TemporaryDirectory() as d:
        bad = os.path.join(d, "bad.sst")
        with pytest.raises(RuntimeError):
            build_sst(bad, [("default", b"b", b"1"), ("default", b"a", b"2")])
        good = os.path.join(d, "good.sst")
        build_sst(good, [("default", b"a", b"1")])
        # corrupt a body byte: ingest must reject on CRC
        raw = bytearray(open(good, "rb").read())
        raw[12] ^= 0xFF
        open(good, "wb").write(bytes(raw))
        e = NativeEngine()
        with pytest.raises(RuntimeError):
            e.ingest_sst(good)
        e.close()


def test_restore_via_sst_matches_writebatch_restore():
    import tempfile as _tf

    from tikv_tpu.sidecar.backup import BackupEndpoint, SstImporter
    from tikv_tpu.sidecar.cloud import create_storage
    from tikv_tpu.storage.mvcc import ForwardScanner

    with _tf.TemporaryDirectory() as d:
        # build a backup from a small committed dataset
        src = NativeEngine()
        wb = WriteBatch()
        from tikv_tpu.storage.txn_types import Key, Write, WriteType

        for i in range(50):
            k = Key.from_raw(b"row%03d" % i)
            w = Write(WriteType.PUT, 10, short_value=b"val%d" % i)
            wb.put_cf("write", k.append_ts(11).encoded, w.to_bytes())
        src.write(wb)
        storage = create_storage(f"local://{d}/backup")
        rep = BackupEndpoint(storage).backup_range(src.snapshot(), "b1", backup_ts=20)
        assert rep["kvs"] == 50

        imp = SstImporter(storage)
        dst = NativeEngine(path=os.path.join(d, "dst"))
        rep2 = imp.restore_via_sst(dst, "b1", restore_ts=100, workdir=d)
        assert rep2["kvs"] == 50 and rep2["via"] == "sst"
        rows = list(ForwardScanner(dst.snapshot(), 200, None, None))
        assert len(rows) == 50
        assert rows[0][1] == b"val0"
        dst.close()
        src.close()
