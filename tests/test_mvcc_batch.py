"""MvccBatchScanSource must match the per-key ForwardScanner exactly."""

import numpy as np
import pytest

from tikv_tpu.copr.dag import BatchExecutorsRunner, DagRequest, TableScan
from tikv_tpu.copr.executors import MvccScanSource
from tikv_tpu.copr.mvcc_batch import MvccBatchScanSource
from tikv_tpu.copr.table import record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.mvcc import KeyIsLockedError

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_engine
from fixtures import delete_committed, lock_key, put_committed, put_committed_large, rollback


def drain(src):
    keys, vals = [], []
    drained = False
    while not drained:
        k, v, drained = src.next_batch(1000)
        keys.extend(k)
        vals.extend(v)
    return keys, vals


def both(eng, ts, rng):
    a = drain(MvccScanSource(eng.snapshot(), ts, [rng]))
    b = drain(MvccBatchScanSource(eng.snapshot(), ts, [rng]))
    return a, b


def test_simple_range_identical():
    eng = product_engine()
    rng = record_range(TABLE_ID)
    a, b = both(eng, 200, rng)
    assert a == b
    assert len(a[0]) == 6


def test_version_resolution_identical():
    eng = BTreeEngine()
    rng = record_range(TABLE_ID)
    for h in range(50):
        put_committed(eng, record_key(TABLE_ID, h), b"v1-%d" % h, 10, 20)
        put_committed(eng, record_key(TABLE_ID, h), b"v2-%d" % h, 30, 40)
    for ts in (5, 20, 39, 40, 100):
        a, b = both(eng, ts, rng)
        assert a == b, f"ts={ts}"


def test_deletes_and_rollbacks_fall_back_identically():
    eng = BTreeEngine()
    rng = record_range(TABLE_ID)
    for h in range(20):
        put_committed(eng, record_key(TABLE_ID, h), b"v-%d" % h, 10, 20)
    delete_committed(eng, record_key(TABLE_ID, 3), 30, 40)
    rollback(eng, record_key(TABLE_ID, 4), 35)
    put_committed_large(eng, record_key(TABLE_ID, 5), b"L" * 300, 30, 41)
    for ts in (20, 40, 100):
        a, b = both(eng, ts, rng)
        assert a == b, f"ts={ts}"


def test_lock_blocks_batch_scan():
    eng = product_engine()
    rng = record_range(TABLE_ID)
    lock_key(eng, record_key(TABLE_ID, 3), b"pk", start_ts=150)
    with pytest.raises(KeyIsLockedError):
        drain(MvccBatchScanSource(eng.snapshot(), 200, [rng]))
    # below the lock and bypassing both still work and agree
    a, b = both(eng, 100, rng)
    assert a == b
    c = drain(MvccBatchScanSource(eng.snapshot(), 200, [rng], bypass_locks=frozenset([150])))
    d = drain(MvccScanSource(eng.snapshot(), 200, [rng], bypass_locks=frozenset([150])))
    assert c == d


def test_dag_over_batch_source_identical():
    eng = product_engine()
    rng = record_range(TABLE_ID)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r1 = BatchExecutorsRunner(dag, MvccScanSource(eng.snapshot(), 200, [rng])).handle_request()
    dag2 = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r2 = BatchExecutorsRunner(dag2, MvccBatchScanSource(eng.snapshot(), 200, [rng])).handle_request()
    assert r1.encode() == r2.encode()


def test_multiple_ranges():
    eng = product_engine()
    k = lambda h: record_key(TABLE_ID, h)
    ranges = [(k(1), k(3)), (k(5), k(100))]
    a = drain(MvccScanSource(eng.snapshot(), 200, ranges))
    b = drain(MvccBatchScanSource(eng.snapshot(), 200, ranges))
    assert a == b
    assert len(a[0]) == 4  # handles 1,2,5,6


def test_native_snapshot_fast_path_identical():
    """MvccBatchScanSource over a native snapshot must match the generic path."""
    pytest.importorskip("tikv_tpu.native.engine")
    from tikv_tpu.native.engine import NativeEngine, native_available

    if not native_available():
        pytest.skip("native engine unavailable")
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    rng = record_range(TABLE_ID)
    nat = NativeEngine()
    py = BTreeEngine()
    for h in range(500):
        k = Key.from_raw(record_key(TABLE_ID, h))
        rec = (k.append_ts(20).encoded, Write(WriteType.PUT, 10, short_value=b"val%03d" % h).to_bytes())
        for eng in (nat, py):
            eng.put_cf(CF_WRITE, *rec)
    a = drain(MvccBatchScanSource(nat.snapshot(), 100, [rng]))
    b = drain(MvccBatchScanSource(py.snapshot(), 100, [rng]))
    assert a == b
    assert len(a[0]) == 500
    # below the commit ts: both empty
    assert drain(MvccBatchScanSource(nat.snapshot(), 5, [rng])) == ([], [])
