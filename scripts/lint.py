#!/usr/bin/env python3
"""Console entry for the project linter (see docs/static_analysis.md).

    python scripts/lint.py tikv_tpu tests
    python scripts/lint.py --list-rules

Exits non-zero on any unwaived finding; waive in-line with
``# lint: allow(rule) -- reason``.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tikv_tpu.analysis.lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
