#!/usr/bin/env python
"""Observatory floor gate: per-plan-signature rows/s regression detector.

The observatory measures what every serving path delivers per plan
signature (docs/observatory.md).  This gate turns those measurements into
a live, per-query-shape regression detector — the BENCH_*.json trajectory,
but keyed by plan shape instead of one blessed benchmark query:

    # snapshot today's measured throughput as the floor
    python scripts/obs_diff.py --write-floor --current snap.json --floor floor.json
    python scripts/obs_diff.py --write-floor --addr HOST:PORT --floor floor.json

    # gate: fail (exit 1) if any (sig, path) dropped >2x below its floor
    python scripts/obs_diff.py --floor floor.json --current snap.json
    python scripts/obs_diff.py --floor floor.json --addr HOST:PORT

``--current`` takes an observatory snapshot JSON (``debug_observatory`` /
``GET /debug/observatory?format=json`` output, or a ``floor()`` dict);
``--addr`` scrapes a live store over the debug RPC.  A (sig, path) present
in the floor but absent (or under ``--min-count``) in the current run is
reported as missing — a warning, not a failure, unless ``--strict``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tikv_tpu.copr.observatory import floor_diff  # noqa: E402


def _load_current(args) -> dict:
    if args.current:
        with open(args.current) as f:
            return json.load(f)
    from tikv_tpu.server.server import Client

    host, port = args.addr.rsplit(":", 1)
    c = Client(host, int(port))
    try:
        return c.call("debug_observatory", {"floor": True,
                                            "min_count": args.min_count})
    finally:
        c.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="obs_diff")
    ap.add_argument("--floor", required=True,
                    help="floor JSON (written by --write-floor or "
                         "Observatory.write_floor)")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--current", help="current observatory snapshot JSON")
    src.add_argument("--addr", help="live store RPC address host:port")
    ap.add_argument("--write-floor", action="store_true",
                    help="write the current measurements AS the floor "
                         "instead of diffing")
    ap.add_argument("--ratio", type=float, default=2.0,
                    help="max tolerated rows/s drop factor (default 2.0)")
    ap.add_argument("--min-count", type=int, default=3,
                    help="min window observations for a comparable profile")
    ap.add_argument("--strict", action="store_true",
                    help="missing (sig, path) profiles fail the gate too")
    args = ap.parse_args(argv)

    current = _load_current(args)
    if args.write_floor:
        # normalize whatever shape we got into the floor shape
        sigs = {}
        for s, entry in (current.get("sigs") or {}).items():
            paths = entry.get("paths", entry)
            out = {}
            for pk, v in paths.items():
                if not isinstance(v, dict) or "rows_per_s" not in v:
                    continue
                if v.get("count", 0) >= args.min_count and v["rows_per_s"] > 0:
                    out[pk] = {"rows_per_s": v["rows_per_s"],
                               "p95_ms": v.get("p95_ms"),
                               "count": v["count"],
                               "desc": v.get("desc", entry.get("desc", ""))}
                    if v.get("pruned_fraction") is not None:
                        # zone-map pruning floor (docs/zone_maps.md)
                        out[pk]["pruned_fraction"] = v["pruned_fraction"]
            if out:
                sigs[s] = out
        import time

        floor = {"version": 1, "written_at": time.time(), "sigs": sigs}
        with open(args.floor, "w") as f:
            json.dump(floor, f, indent=2, sort_keys=True)
        n = sum(len(p) for p in sigs.values())
        print(f"obs_diff: floor written to {args.floor} "
              f"({len(sigs)} sigs, {n} profiles)")
        return 0

    with open(args.floor) as f:
        floor = json.load(f)
    verdict = floor_diff(floor, current, ratio=args.ratio,
                         min_count=args.min_count)
    for m in verdict["missing"]:
        print(f"obs_diff: missing profile {m} (floor has it, current run "
              f"does not)", file=sys.stderr)
    for r in verdict["regressions"]:
        if r.get("kind") == "pruning":
            print(f"obs_diff: PRUNING REGRESSION {r['sig']}/{r['path']} "
                  f"({r['desc']}): pruned fraction "
                  f"{r['pruned_fraction']:.3f} vs floor "
                  f"{r['floor_pruned_fraction']:.3f}", file=sys.stderr)
            continue
        print(f"obs_diff: REGRESSION {r['sig']}/{r['path']} "
              f"({r['desc']}): {r['rows_per_s']:.1f} rows/s vs floor "
              f"{r['floor_rows_per_s']:.1f} ({r['drop']}x drop "
              f"> {verdict['ratio']}x)", file=sys.stderr)
    ok = verdict["ok"] and (not args.strict or not verdict["missing"])
    print(f"obs_diff: {'ok' if ok else 'FAIL'} — checked "
          f"{verdict['checked']} profiles, "
          f"{len(verdict['regressions'])} regressions, "
          f"{len(verdict['missing'])} missing")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
