#!/usr/bin/env bash
# CI gate: project lint (incl. metric/failpoint drift) + a sanitize-enabled
# concurrency smoke pass.  See docs/static_analysis.md.
#
#   scripts/check.sh            # lint + sanitize smoke
#   scripts/check.sh --lint     # lint only (fast pre-commit hook)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint (blocking-under-lock, jit recompile, metric/failpoint drift, buffer aliasing) =="
python scripts/lint.py tikv_tpu tests

if [[ "${1:-}" == "--lint" ]]; then
  exit 0
fi

echo "== sanitize smoke: concurrency hot paths under TIKV_TPU_SANITIZE=1 =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  tests/test_sanitizer.py tests/test_txn_scheduler.py tests/test_raftstore.py \
  tests/test_copr_scheduler.py tests/test_write_through.py \
  tests/test_worker_pool.py tests/test_fsm_system.py

echo "== chaos smoke: nemesis + retry/breaker fault paths under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_chaos_nemesis.py tests/test_retry_policy.py

echo "== follower-read chaos smoke: leader isolation + read ladder under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_follower_reads.py

echo "== integrity smoke: SDC scrubber + shadow reads + corruption chaos under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_integrity.py

echo "== trace smoke: sampled request end-to-end span tree under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_tracing.py

echo "== observatory smoke: per-sig path profiles, compile ledger, exemplars, floor gate under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_observatory.py

echo "== compressed-columns smoke: encoded residency, delta demotions, code-space rewrites under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_encoding.py tests/test_compressed_columns.py

echo "== chunk-wire smoke: TypeChunk negotiation, differential byte-identity, zero-copy parts under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_chunk_codec.py tests/test_chunk_wire.py

echo "== zone-map smoke: prune soundness, fold widening, early exits, pruned byte-identity under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_zone_maps.py

echo "== overload smoke: tenant quotas, adaptive admission, hot-tenant flood continuity under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_overload.py

echo "== cost-router smoke: measured routing, explore bounds, kill-switch identity, tuner convergence under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_cost_router.py

echo "== device-join smoke: rank/hash join differential pool, no-decode survivors, decline causes under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_device_join.py

echo "== bufsan smoke: zero-copy exposure ledger over chunk wire + warm serve + wt folds under the sanitizer =="
JAX_PLATFORMS=cpu TIKV_TPU_SANITIZE=1 python -m pytest -q -p no:cacheprovider \
  -m 'not slow' tests/test_bufsan.py tests/test_chunk_wire.py \
  tests/test_write_through.py

echo "check.sh: all gates green"
