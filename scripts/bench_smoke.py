#!/usr/bin/env python
"""Bench smoke: the region column cache, the read scheduler AND the
mesh-sharded warm path must hold their wins.

Runs three mock-table configurations on the CPU backend and FAILS when any
regresses:

* ``region_cache`` (ISSUE 1): endpoint-served scan/selection over a real
  MVCC region, cold vs cached, with a delta apply mid-sequence.  Fails on
  any byte divergence or a cached speedup below the 2x floor.
* ``xregion`` (ISSUE 2): the unified read scheduler's cross-region batched
  serving vs per-request device serving on an 8-region synthetic workload
  (mixed plan signatures, multiple clients per region).  Fails on any byte
  divergence from the serial path / CPU oracle or a batched-vs-serial
  speedup below the 2x floor.
* ``sharded_xregion`` (ISSUE 3): the same warm cross-region workload over a
  SIMULATED 8-DEVICE CPU MESH — region images sharded over owner devices,
  one shard_map program per batch — vs single-device serial serving.  Runs
  in a subprocess (the virtual-device flag must precede jax init).  Fails
  on byte divergence or a speedup below the 1.5x floor; per-device
  occupancy is reported.
* ``cost_router`` (ISSUE 17): cost-based path routing + geometry
  auto-tuning (docs/cost_router.md) — a mixed three-signature workload
  where the static ladder sends one group-by shape to a badly padded
  device tile the CPU pipeline beats.  Fails on byte divergence of any
  routed response vs the CPU oracle, a router-on vs router-off aggregate
  speedup below the 1.2x floor, or a geometry tuner that never walks the
  deliberately bad block_rows down.
* ``join`` (ISSUE 18): device-resident join (docs/device_join.md) — an
  equi-join of a probe region against a second warm build region on the
  rank and hash device paths vs the CPU join pipeline, byte-checked per
  trial.  Fails on byte divergence, a rank-vs-CPU speedup below the 2x
  floor, or zero device-served joins.
* ``mixed_rw`` (ISSUE 4): writers commit through the txn scheduler over a
  raft group while readers serve the warm region.  Fails on byte
  divergence, a grouped-vs-per-command commit speedup below the 2x floor,
  or a warm hit-rate under write load below 50%.
* ``wire`` (ISSUE 8): socket-level coalesced generic serving (continuous
  scheduler lanes + zero-copy frames, the standalone default) vs
  per-request CPU serving over real TCP connections.  Fails on byte
  divergence, a speedup below the 5x floor, or zero batch-served requests.
* ``wire_chunk`` (ISSUE 14): TypeChunk column-slab responses vs datum rows
  on the SAME socket workload (6 client connections, client decode
  included) — fails on value divergence, a chunk-vs-datum speedup below
  the 3x floor, or zero TypeChunk-served responses.
* ``compressed`` (ISSUE 10): encoded device-resident columns
  (docs/compressed_columns.md) — byte-identity of encoded serving vs the
  CPU oracle, and the warm-capacity multiplier at one fixed byte budget.
  Fails on byte divergence or under 2x regions resident encoded-vs-decoded.
* ``scan_pruned`` (ISSUE 16): zone-map pruned execution
  (docs/zone_maps.md) — a selective pk-range scan and a Limit-bearing scan
  over a warm region, pruning on vs kill-switched off.  Fails on byte
  divergence from the CPU oracle, a speedup below the 2x floor, or zero
  blocks ever pruned.

Exit code 0 = healthy; 1 = regression.  One JSON line on stdout either way,
so CI logs stay grep-able:

    python scripts/bench_smoke.py [--rows N] [--trials K]
"""

import argparse
import json
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

MIN_SPEEDUP = 2.0
MIN_XREGION_SPEEDUP = 2.0
MIN_SHARDED_SPEEDUP = 1.5
MIN_GROUP_SPEEDUP = 2.0
MIN_WARM_HIT_RATE = 0.5
MIN_WIRE_SPEEDUP = 5.0
MIN_WIRE_CHUNK_SPEEDUP = 3.0
MIN_COMPRESSED_CAPACITY = 2.0
MIN_PRUNED_SPEEDUP = 2.0
MIN_OVERLOAD_RETENTION = 0.5
MIN_COST_ROUTER_SPEEDUP = 1.2
MIN_JOIN_SPEEDUP = 2.0
SHARDED_DEVICES = 8


def _sharded_child(args) -> int:
    """Child entry: runs the sharded event under the virtual-device mesh and
    prints its raw result JSON (parent enforces the floor)."""
    import bench

    bench._force_cpu()
    r = bench._op_sharded_xregion({
        "regions": args.xregion_regions, "rows": args.xregion_rows,
        "clients": 3, "trials": max(args.trials, 3),
    }, {})
    print(json.dumps(r))
    return 0


def _run_sharded(args) -> dict:
    """Run the sharded event in its 8-virtual-device child; EVERY failure
    mode (wedge, crash, garbage stdout) folds into {"error": ...} so the
    parent keeps the one-JSON-line contract."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={SHARDED_DEVICES}"
    ).strip()
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sharded-child",
             "--xregion-rows", str(args.xregion_rows),
             "--xregion-regions", str(args.xregion_regions),
             "--trials", str(args.trials)],
            env=env, capture_output=True, text=True, timeout=900,
        )
    except subprocess.TimeoutExpired:
        return {"error": "sharded child wedged past 900s (killed)"}
    if out.returncode != 0:
        return {"error": f"child rc={out.returncode}: {out.stderr[-500:]}"}
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (IndexError, ValueError) as exc:
        return {"error": f"child produced no result JSON ({exc}); "
                         f"stdout tail: {out.stdout[-300:]!r}"}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=int(os.environ.get("SMOKE_ROWS", "60000")))
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--xregion-rows", type=int,
                    default=int(os.environ.get("SMOKE_XREGION_ROWS", "32000")))
    ap.add_argument("--xregion-regions", type=int, default=8)
    ap.add_argument("--sharded-child", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.sharded_child:
        return _sharded_child(args)

    import bench

    bench._force_cpu()
    import numpy as np

    r = bench._op_region_cache({"rows": args.rows, "trials": args.trials}, {})
    out = {"rows": args.rows, "match": bool(r["match"])}
    ok = r["match"]
    for kind in ("scan", "selection"):
        cold = float(np.median(r[kind]["cold_ts"]))
        warm = float(np.median(r[kind]["warm_ts"]))
        speedup = cold / warm
        out[f"{kind}_cached_speedup"] = round(speedup, 2)
        out[f"{kind}_outcome"] = r[kind]["outcome"]
        if speedup < MIN_SPEEDUP:
            ok = False
            out[f"{kind}_regression"] = f"{speedup:.2f}x < {MIN_SPEEDUP}x floor"
    out["delta"] = r.get("delta")

    # cross-region batched-vs-serial (scheduler regression tripwire)
    rx = bench._op_xregion({
        "regions": args.xregion_regions, "rows": args.xregion_rows,
        "clients": 3, "trials": max(args.trials, 3),
    }, {})
    out["xregion_match"] = bool(rx["match"])
    out["xregion_from_device"] = bool(rx["from_device"])
    ok = ok and rx["match"] and rx["from_device"]
    serial_t = float(np.median(rx["serial_ts"]))
    batch_t = float(np.median(rx["batch_ts"]))
    xspeed = serial_t / batch_t
    out["xregion_requests"] = rx["requests"]
    out["xregion_speedup"] = round(xspeed, 2)
    if xspeed < MIN_XREGION_SPEEDUP:
        ok = False
        out["xregion_regression"] = (
            f"{xspeed:.2f}x < {MIN_XREGION_SPEEDUP}x floor")

    # cluster wire floor (ISSUE 8): SOCKET-level coalesced generic serving
    # must beat per-request CPU serving ≥5x — relative, so CI stays
    # hardware-independent (docs/wire_path.md)
    rw = bench._op_wire({
        "regions": args.xregion_regions, "rows": args.xregion_rows,
        "clients": 3, "trials": max(args.trials, 3),
    }, {})
    out["wire_match"] = bool(rw["match"])
    ok = ok and rw["match"]
    w_coal = float(np.median(rw["coalesced_ts"]))
    w_per = float(np.median(rw["per_request_ts"]))
    wspeed = w_per / w_coal
    out["wire_requests"] = rw["requests"]
    out["wire_speedup"] = round(wspeed, 2)
    out["wire_coalesced_batched"] = rw["coalesced_batched"]
    wire_regressions = []
    if wspeed < MIN_WIRE_SPEEDUP:
        wire_regressions.append(f"{wspeed:.2f}x < {MIN_WIRE_SPEEDUP}x floor")
    if rw["coalesced_batched"] <= 0:
        wire_regressions.append("no requests served out of coalesced batches")
    if wire_regressions:
        ok = False
        out["wire_regression"] = "; ".join(wire_regressions)

    # columnar chunk wire floor (ISSUE 14): the SAME socket workload (6
    # client connections) served TypeChunk must beat the datum wire path
    # ≥3x end-to-end INCLUDING the client decode — shipping column slabs to
    # the client is the contract (docs/wire_path.md)
    rk = bench._op_wire_chunk({
        "regions": 4, "rows": args.xregion_rows,
        "trials": max(args.trials, 3),
    }, {})
    out["wire_chunk_match"] = bool(rk["match"])
    ok = ok and rk["match"]
    k_datum = float(np.median(rk["datum_ts"]))
    k_chunk = float(np.median(rk["chunk_ts"]))
    kspeed = k_datum / k_chunk
    out["wire_chunk_requests"] = rk["requests"]
    out["wire_chunk_speedup"] = round(kspeed, 2)
    out["wire_chunk_served"] = rk["chunk_served"]
    chunk_regressions = []
    if kspeed < MIN_WIRE_CHUNK_SPEEDUP:
        chunk_regressions.append(
            f"{kspeed:.2f}x < {MIN_WIRE_CHUNK_SPEEDUP}x floor")
    if rk["chunk_served"] <= 0:
        chunk_regressions.append("no responses served TypeChunk")
    if chunk_regressions:
        ok = False
        out["wire_chunk_regression"] = "; ".join(chunk_regressions)

    # mesh-sharded warm serving on the 8-virtual-device mesh (ISSUE 3)
    rs = _run_sharded(args)
    if rs.get("error") or rs.get("skipped"):
        ok = False
        out["sharded_xregion_regression"] = rs.get("error") or rs.get("reason")
    else:
        out["sharded_match"] = bool(rs["match"])
        out["sharded_from_device"] = bool(rs["from_device"])
        ok = ok and rs["match"] and rs["from_device"]
        s_t = float(np.median(rs["serial_ts"]))
        b_t = float(np.median(rs["batch_ts"]))
        sspeed = s_t / b_t
        out["sharded_devices"] = rs["devices"]
        out["sharded_speedup"] = round(sspeed, 2)
        out["sharded_device_occupancy"] = rs["device_occupancy"]
        out["sharded_device_bytes"] = rs["device_bytes_pinned"]
        if sspeed < MIN_SHARDED_SPEEDUP:
            ok = False
            out["sharded_xregion_regression"] = (
                f"{sspeed:.2f}x < {MIN_SHARDED_SPEEDUP}x floor")

    # compressed device-resident columns (ISSUE 10): encoded serving must
    # be byte-identical AND keep ≥2x the regions warm at one byte budget
    rc = bench._op_scan_compressed({
        "rows": int(os.environ.get("SMOKE_COMPRESSED_ROWS", "16000")),
        "trials": max(args.trials, 3),
    }, {})
    out["compressed_match"] = bool(rc["match"])
    ok = ok and rc["match"]
    out["compressed_ratio"] = round(float(rc["compression_ratio"]), 2)
    out["compressed_capacity_ratio"] = round(float(rc["warm_capacity_ratio"]), 2)
    out["compressed_regions_resident"] = [
        rc["regions_resident_decoded"], rc["regions_resident_encoded"]]
    out["compressed_encodings"] = rc["encodings"]
    if rc["warm_capacity_ratio"] < MIN_COMPRESSED_CAPACITY:
        ok = False
        out["compressed_regression"] = (
            f"{rc['warm_capacity_ratio']:.2f}x warm regions < "
            f"{MIN_COMPRESSED_CAPACITY}x floor at equal budget")

    # zone-map pruned execution (ISSUE 16): a selective pk-range scan and a
    # Limit-bearing scan over a warm region must serve ≥2x faster with
    # block pruning on than with the kill switch thrown — byte-identical to
    # the CPU oracle either way (docs/zone_maps.md)
    rp = bench._op_scan_pruned({
        "rows": int(os.environ.get("SMOKE_PRUNED_ROWS", "60000")),
        "trials": max(args.trials, 3),
    }, {})
    out["pruned_match"] = bool(rp["match"])
    ok = ok and rp["match"]
    pruned_regressions = []
    for name in ("selective", "limit"):
        p = float(np.median(rp[name]["pruned_ts"]))
        u = float(np.median(rp[name]["unpruned_ts"]))
        pspeed = u / p
        out[f"pruned_{name}_speedup"] = round(pspeed, 2)
        if pspeed < MIN_PRUNED_SPEEDUP:
            pruned_regressions.append(
                f"{name} {pspeed:.2f}x < {MIN_PRUNED_SPEEDUP}x floor")
    out["pruned_blocks"] = [rp["blocks_pruned"], rp["blocks_examined"]]
    if rp["blocks_pruned"] <= 0:
        pruned_regressions.append("no blocks were ever pruned")
    if pruned_regressions:
        ok = False
        out["pruned_regression"] = "; ".join(pruned_regressions)

    # overload control plane (ISSUE 15): a hot tenant saturating the
    # scheduler must not cost the well-behaved tenant more than half its
    # throughput, and must never fail one of its reads — per-tenant quotas
    # shed the flood, not the victim (docs/robustness.md "Overload")
    ro = bench._op_overload({
        "regions": 4,
        "rows": int(os.environ.get("SMOKE_OVERLOAD_ROWS", "8000")),
        "clients": 2, "trials": max(args.trials, 3),
    }, {})
    out["overload_retention"] = round(float(ro["retention"]), 3)
    out["overload_victim_failures"] = ro["victim_failures"]
    out["overload_hot_shed"] = ro["hot_shed"]
    overload_regressions = []
    if ro["victim_failures"]:
        overload_regressions.append(
            f"{ro['victim_failures']} victim reads failed under flood")
    if ro["retention"] < MIN_OVERLOAD_RETENTION:
        overload_regressions.append(
            f"victim retention {ro['retention']:.2f} < "
            f"{MIN_OVERLOAD_RETENTION} floor")
    if ro["hot_shed"] <= 0:
        overload_regressions.append("hot tenant overage was never shed")
    if overload_regressions:
        ok = False
        out["overload_regression"] = "; ".join(overload_regressions)

    # cost-based path routing + geometry auto-tuning (ISSUE 17): the
    # router must beat the static ladder on the mixed workload where the
    # ladder demonstrably picks a worse path, byte-identically, and the
    # tuner must fix the deliberately bad block geometry
    rr = bench._op_cost_router({
        "regions": 2,
        "rows": int(os.environ.get("SMOKE_COST_ROUTER_ROWS", "2048")),
        "trials": max(args.trials, 3),
    }, {})
    out["cost_router_match"] = bool(rr["match"])
    ok = ok and rr["match"]
    out["cost_router_speedup"] = round(float(rr["speedup"]), 2)
    out["cost_router_route_dist"] = rr["route_dist"]
    out["cost_router_tuner_final_block_rows"] = rr["tuner_final_block_rows"]
    out["cost_router_tuner_counts"] = rr["tuner_counts"]
    router_regressions = []
    if rr["speedup"] < MIN_COST_ROUTER_SPEEDUP:
        router_regressions.append(
            f"router-on {rr['speedup']:.2f}x < {MIN_COST_ROUTER_SPEEDUP}x floor")
    if rr["tuner_final_block_rows"] >= rr["tuner_initial_block_rows"]:
        router_regressions.append(
            f"tuner never improved block_rows "
            f"({rr['tuner_initial_block_rows']} -> "
            f"{rr['tuner_final_block_rows']})")
    if rr["tuner_counts"].get("keep", 0) < 1:
        router_regressions.append("tuner kept no geometry move")
    if router_regressions:
        ok = False
        out["cost_router_regression"] = "; ".join(router_regressions)

    # device-resident join (ISSUE 18): the rank path over two warm images
    # must beat the CPU join pipeline ≥2x with byte identity every trial;
    # the hash path is reported (no floor — int-keyed probes pay the same
    # kernels but a different table build)
    rj = bench._op_join({
        "rows": int(os.environ.get("SMOKE_JOIN_ROWS", "20000")),
        "trials": max(args.trials, 3),
    }, {})
    out["join_match"] = bool(rj["match"])
    ok = ok and rj["match"]
    j_cpu = float(np.median(rj["cpu_ts"]))
    jspeed = j_cpu / float(np.median(rj["rank_ts"]))
    out["join_rank_speedup"] = round(jspeed, 2)
    out["join_hash_speedup"] = round(
        j_cpu / float(np.median(rj["hash_ts"])), 2)
    out["join_served"] = rj["served"]
    join_regressions = []
    if jspeed < MIN_JOIN_SPEEDUP:
        join_regressions.append(
            f"rank {jspeed:.2f}x < {MIN_JOIN_SPEEDUP}x floor")
    if rj["served"]["rank"] <= 0 or rj["served"]["hash"] <= 0:
        join_regressions.append("a device join path never served")
    if join_regressions:
        ok = False
        out["join_regression"] = "; ".join(join_regressions)

    # group-commit write path + warm serving under writes (ISSUE 4)
    rm = bench._op_mixed_rw({
        "rows": int(os.environ.get("SMOKE_MIXED_RW_ROWS", "2048")),
        "writes": int(os.environ.get("SMOKE_MIXED_RW_WRITES", "64")),
        "trials": max(args.trials, 3),
    }, {})
    out["mixed_rw_match"] = bool(rm["match"])
    ok = ok and rm["match"]
    out["mixed_rw_group_speedup"] = round(rm["group_speedup"], 2)
    out["mixed_rw_warm_hit_rate"] = round(rm["warm_hit_rate"], 3)
    out["mixed_rw_scan_deltas"] = rm["scan_deltas"]
    out["mixed_rw_commits_per_s_grouped"] = round(rm["commits_per_s_grouped"], 1)
    if rm["group_speedup"] < MIN_GROUP_SPEEDUP:
        ok = False
        out["mixed_rw_group_regression"] = (
            f"group commit {rm['group_speedup']:.2f}x < {MIN_GROUP_SPEEDUP}x floor")
    if rm["warm_hit_rate"] < MIN_WARM_HIT_RATE:
        ok = False
        out["mixed_rw_hit_rate_regression"] = (
            f"warm hit-rate {rm['warm_hit_rate']:.2f} < {MIN_WARM_HIT_RATE} "
            f"under writes (outcomes: {rm['outcomes']})")

    out["ok"] = bool(ok)
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
