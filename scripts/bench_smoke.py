#!/usr/bin/env python
"""Bench smoke: the region column cache AND the read scheduler must hold
their wins.

Runs two mock-table configurations on the CPU backend and FAILS when either
regresses:

* ``region_cache`` (ISSUE 1): endpoint-served scan/selection over a real
  MVCC region, cold vs cached, with a delta apply mid-sequence.  Fails on
  any byte divergence or a cached speedup below the 2x floor.
* ``xregion`` (ISSUE 2): the unified read scheduler's cross-region batched
  serving vs per-request device serving on an 8-region synthetic workload
  (mixed plan signatures, multiple clients per region).  Fails on any byte
  divergence from the serial path / CPU oracle or a batched-vs-serial
  speedup below the 2x floor.

Exit code 0 = healthy; 1 = regression.  One JSON line on stdout either way,
so CI logs stay grep-able:

    python scripts/bench_smoke.py [--rows N] [--trials K]
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

MIN_SPEEDUP = 2.0
MIN_XREGION_SPEEDUP = 2.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=int(os.environ.get("SMOKE_ROWS", "60000")))
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--xregion-rows", type=int,
                    default=int(os.environ.get("SMOKE_XREGION_ROWS", "32000")))
    ap.add_argument("--xregion-regions", type=int, default=8)
    args = ap.parse_args()

    import bench

    bench._force_cpu()
    import numpy as np

    r = bench._op_region_cache({"rows": args.rows, "trials": args.trials}, {})
    out = {"rows": args.rows, "match": bool(r["match"])}
    ok = r["match"]
    for kind in ("scan", "selection"):
        cold = float(np.median(r[kind]["cold_ts"]))
        warm = float(np.median(r[kind]["warm_ts"]))
        speedup = cold / warm
        out[f"{kind}_cached_speedup"] = round(speedup, 2)
        out[f"{kind}_outcome"] = r[kind]["outcome"]
        if speedup < MIN_SPEEDUP:
            ok = False
            out[f"{kind}_regression"] = f"{speedup:.2f}x < {MIN_SPEEDUP}x floor"
    out["delta"] = r.get("delta")

    # cross-region batched-vs-serial (scheduler regression tripwire)
    rx = bench._op_xregion({
        "regions": args.xregion_regions, "rows": args.xregion_rows,
        "clients": 3, "trials": max(args.trials, 3),
    }, {})
    out["xregion_match"] = bool(rx["match"])
    out["xregion_from_device"] = bool(rx["from_device"])
    ok = ok and rx["match"] and rx["from_device"]
    serial_t = float(np.median(rx["serial_ts"]))
    batch_t = float(np.median(rx["batch_ts"]))
    xspeed = serial_t / batch_t
    out["xregion_requests"] = rx["requests"]
    out["xregion_speedup"] = round(xspeed, 2)
    if xspeed < MIN_XREGION_SPEEDUP:
        ok = False
        out["xregion_regression"] = (
            f"{xspeed:.2f}x < {MIN_XREGION_SPEEDUP}x floor")

    out["ok"] = bool(ok)
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
