#!/usr/bin/env python
"""Bench smoke: the region column cache must hold its win.

Runs the mock-table region-cache configuration (bench.py's ``region_cache``
op — endpoint-served scan/selection over a real MVCC region, cold vs cached,
with a delta apply mid-sequence) on the CPU backend and FAILS when:

* any cached response diverges byte-wise from the cold path, or
* the cached-scan or cached-selection speedup regresses below the 2x floor
  (ISSUE 1 acceptance: scan/selection must stay off the 1.0x floor).

Exit code 0 = healthy; 1 = regression.  One JSON line on stdout either way,
so CI logs stay grep-able:

    python scripts/bench_smoke.py [--rows N] [--trials K]
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

MIN_SPEEDUP = 2.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=int(os.environ.get("SMOKE_ROWS", "60000")))
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()

    import bench

    bench._force_cpu()
    import numpy as np

    r = bench._op_region_cache({"rows": args.rows, "trials": args.trials}, {})
    out = {"rows": args.rows, "match": bool(r["match"])}
    ok = r["match"]
    for kind in ("scan", "selection"):
        cold = float(np.median(r[kind]["cold_ts"]))
        warm = float(np.median(r[kind]["warm_ts"]))
        speedup = cold / warm
        out[f"{kind}_cached_speedup"] = round(speedup, 2)
        out[f"{kind}_outcome"] = r[kind]["outcome"]
        if speedup < MIN_SPEEDUP:
            ok = False
            out[f"{kind}_regression"] = f"{speedup:.2f}x < {MIN_SPEEDUP}x floor"
    out["delta"] = r.get("delta")
    out["ok"] = bool(ok)
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
