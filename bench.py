#!/usr/bin/env python
"""Driver benchmark: TPC-H Q1/Q6-shaped coprocessor pushdown at 100M rows.

Measures the JAX/TPU DAG evaluator against the CPU read-pool pipeline
(BatchExecutorsRunner) on a lineitem-shaped table, asserting byte-identical
SelectResponses, and prints ONE JSON line:

    {"metric": ..., "value": <tpu rows/sec>, "unit": "rows/sec", "vs_baseline": <speedup>}

vs_baseline = (TPU rows/s) / (CPU rows/s) on the K-query batched serving
shape; per-query Q1/Q6 warm/cold speedups ride the stderr detail JSON.

Backend acquisition (the part that failed rounds 1-3): ONE persistent device
worker subprocess is spawned at start and given a long init budget
(BENCH_INIT_BUDGET, default 900s — the tunnel backend is known to HANG at
init rather than fail fast, so the worker heartbeats while it waits and the
parent overlaps ALL CPU-side measurement with the wait).  Every device trial
runs through that worker over a line-JSON pipe; the parent never initializes
the device backend itself (JAX caches the first backend-init failure for the
process lifetime).  Only after the budget expires is the run demoted to an
in-process CPU fallback, and the full probe timeline is emitted in the
detail JSON so a hang is diagnosable from BENCH_rN.json alone.

Row count via BENCH_ROWS (default 100,000,000 — BASELINE.md config 4 scale).
The 100M-row warm fixture is built columnar (the decoded image of
``build_kvs``, validated block-for-block against a real decode in
``fixture_selfcheck``); cold trials decode real KV bytes at BENCH_COLD_ROWS
(default 1M).  BENCH_MVCC=1 (default) adds an engine-backed MVCC region
validation and an endpoint-driven device TopN.
"""

import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

import numpy as np

TABLE_ID = 101
_JAX_CACHE_DIR = os.path.join(_HERE, ".jax_cache")


def _mem_available_gb() -> float:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable"):
                    return int(line.split()[1]) / 2**20
    except OSError:
        pass
    return 0.0


def _force_cpu() -> None:
    """Must go through jax.config: this image's sitecustomize re-exports
    JAX_PLATFORMS=axon at every interpreter start, so a shell-level env
    override is silently clobbered."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def _lineitem():
    from tikv_tpu.copr.datatypes import NOT_NULL_FLAG, ColumnInfo, FieldType

    def nn(ft):
        # TPC-H lineitem columns are all NOT NULL; declaring it lets both
        # pipelines skip null-mask work honestly
        ft.flag |= NOT_NULL_FLAG
        return ft

    return [
        ColumnInfo(1, nn(FieldType.int64()), is_pk_handle=True),
        ColumnInfo(2, nn(FieldType.int64())),  # l_quantity
        ColumnInfo(3, nn(FieldType.decimal_type(2))),  # l_extendedprice
        ColumnInfo(4, nn(FieldType.decimal_type(2))),  # l_discount
        ColumnInfo(5, nn(FieldType.int64())),  # l_shipdate (days)
        ColumnInfo(6, nn(FieldType.varchar())),  # l_returnflag
        ColumnInfo(7, nn(FieldType.varchar())),  # l_linestatus
    ]


def build_arrays(n: int, seed: int = 0) -> dict:
    """The raw column draws — the single source of randomness, shared by the
    KV-bytes fixture and the columnar fixture so both processes see the same
    table for a given (n, seed)."""
    rng = np.random.default_rng(seed)
    return {
        "qty": rng.integers(1, 51, n),
        "price": rng.integers(90000, 10500000, n),  # 900.00 .. 105000.00
        "disc": rng.integers(0, 11, n),  # 0.00 .. 0.10
        "ship": rng.integers(8400, 10600, n),
        "rf": rng.integers(0, 3, n),
        "ls": rng.integers(0, 2, n),
    }


def build_kvs(n: int, seed: int = 0):
    """Vectorized KV fixture: rows share one fixed layout, so the whole
    table is a byte matrix filled by batch codecs.  Used for cold trials
    (real decode) and engine-region validations — bounded row counts."""
    from tikv_tpu.copr.table import RowBatchDecoder, encode_row, record_key
    from tikv_tpu.util.codec import encode_i64_batch

    a = build_arrays(n, seed)
    schema = _lineitem()
    flags = np.frombuffer(b"ANR", dtype=np.uint8)
    stats = np.frombuffer(b"FO", dtype=np.uint8)
    non_handle = schema[1:]
    row0 = encode_row(non_handle, [1, 1, 1, 1, b"A", b"F"])
    layout = RowBatchDecoder(schema)._parse_layout(row0)
    mat = np.tile(np.frombuffer(row0, dtype=np.uint8), (n, 1))
    for col_id, arr in ((2, a["qty"]), (3, a["price"]), (4, a["disc"]), (5, a["ship"])):
        _kind, off = layout["cols"][col_id]
        mat[:, off : off + 8] = encode_i64_batch(arr)
    _k, off_rf = layout["cols"][6]
    _k, off_ls = layout["cols"][7]
    mat[:, off_rf] = flags[a["rf"]]
    mat[:, off_ls] = stats[a["ls"]]
    values = [r.tobytes() for r in mat]
    kmat = np.tile(np.frombuffer(record_key(TABLE_ID, 0), dtype=np.uint8), (n, 1))
    kmat[:, 11:19] = encode_i64_batch(np.arange(n, dtype=np.int64))
    keys = [r.tobytes() for r in kmat]
    return list(zip(keys, values))


def build_cache(n: int, block_rows: int, seed: int = 0):
    """The decoded-column image of build_kvs(n, seed) as a filled
    ColumnBlockCache, WITHOUT materializing n Python byte objects — this is
    what makes the 100M-row warm configuration buildable.  Layout must match
    RowBatchDecoder exactly (fixture_selfcheck proves it block-for-block):
    ints/decimals as int64 data, varchar as dictionary codes with ONE shared
    dictionary object across blocks (the decoder's per-column dict cache
    does the same — the device group-by fast path keys on identity)."""
    from tikv_tpu.copr.cache import ColumnBlockCache
    from tikv_tpu.copr.datatypes import Column, EvalType

    a = build_arrays(n, seed)
    # sorted unique byte values, as the decoder's np.unique produces them
    dict_rf = np.empty(3, dtype=object)
    dict_rf[:] = [b"A", b"N", b"R"]
    dict_ls = np.empty(2, dtype=object)
    dict_ls[:] = [b"F", b"O"]
    handles = np.arange(n, dtype=np.int64)
    cache = ColumnBlockCache()
    for s in range(0, n, block_rows):
        e = min(s + block_rows, n)
        m = e - s
        nz = [np.zeros(m, dtype=bool) for _ in range(7)]
        cols = [
            Column(EvalType.INT, handles[s:e], nz[0]),
            Column(EvalType.INT, a["qty"][s:e], nz[1]),
            Column(EvalType.DECIMAL, a["price"][s:e], nz[2], 2),
            Column(EvalType.DECIMAL, a["disc"][s:e], nz[3], 2),
            Column(EvalType.INT, a["ship"][s:e], nz[4]),
            Column(EvalType.BYTES, a["rf"][s:e], nz[5], 0, dict_rf),
            Column(EvalType.BYTES, a["ls"][s:e], nz[6], 0, dict_ls),
        ]
        cache.add(cols, m)
    cache.filled = True
    return cache


def fixture_selfcheck(n: int = 65536) -> None:
    """Prove build_cache == decode(build_kvs) column-for-column at one block,
    so the 100M columnar fixture is a faithful stand-in for real decode."""
    from tikv_tpu.copr.table import RowBatchDecoder, decode_record_handles

    kvs = build_kvs(n, seed=0)
    dec = RowBatchDecoder(_lineitem())
    handles = decode_record_handles([k for k, _ in kvs])
    decoded = dec.decode(handles, [v for _, v in kvs])
    built = build_cache(n, block_rows=n, seed=0).blocks[0].cols
    assert len(decoded) == len(built)
    for i, (c, d) in enumerate(zip(decoded, built)):
        assert c.eval_type == d.eval_type, i
        assert np.array_equal(np.asarray(c.data), np.asarray(d.data)), i
        assert np.array_equal(np.asarray(c.nulls), np.asarray(d.nulls)), i
        assert c.frac == d.frac, i
        cd = c.dictionary
        dd = d.dictionary
        assert (cd is None) == (dd is None), i
        if cd is not None:
            assert list(cd) == list(dd), i


def q6_dag():
    # sum(l_extendedprice * l_discount) where shipdate in [y, y+365) and
    # discount between 0.02 and 0.04 and quantity < 24
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
    from tikv_tpu.copr.rpn import call, col, const_decimal, const_int

    conds = [
        call("ge", col(4), const_int(9000)),
        call("lt", col(4), const_int(9365)),
        call("ge", col(3), const_decimal(2, 2)),
        call("le", col(3), const_decimal(4, 2)),
        call("lt", col(1), const_int(24)),
    ]
    aggs = [AggDescriptor("sum", call("multiply", col(2), col(3)))]
    return DagRequest(
        executors=[TableScan(TABLE_ID, _lineitem()), Selection(conds), Aggregation([], aggs)]
    )


def q1_dag():
    # group by returnflag, linestatus: sum(qty), sum(price), avg(price),
    # avg(disc), count(*) where shipdate <= cutoff
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
    from tikv_tpu.copr.rpn import call, col, const_int

    conds = [call("le", col(4), const_int(10500))]
    aggs = [
        AggDescriptor("sum", col(1)),
        AggDescriptor("sum", col(2)),
        AggDescriptor("avg", col(2)),
        AggDescriptor("avg", col(3)),
        AggDescriptor("count", None),
    ]
    return DagRequest(
        executors=[
            TableScan(TABLE_ID, _lineitem()),
            Selection(conds),
            Aggregation([col(5), col(6)], aggs),
        ]
    )


_DAGS = {"q6": q6_dag, "q1": q1_dag}


def run_cpu(dag, kvs=None, cache=None):
    """The CPU read-pool pipeline (BatchExecutorsRunner) over either real KV
    bytes or the shared block cache."""
    from tikv_tpu.copr.dag import BatchExecutorsRunner
    from tikv_tpu.copr.executors import CachedBlocksExecutor, FixtureScanSource

    t0 = time.perf_counter()
    leaf = CachedBlocksExecutor(cache, _lineitem()) if cache is not None else None
    src = None if cache is not None else FixtureScanSource(kvs)
    resp = BatchExecutorsRunner(dag, src, leaf=leaf).handle_request()
    return resp, time.perf_counter() - t0


# ---------------------------------------------------------------------------
# Device-side operations.  These run inside the worker subprocess when the
# device backend is up, or in-process (after _force_cpu) on fallback — same
# code either way, so the fallback measures exactly what the device would.
# ---------------------------------------------------------------------------


def _op_build(req, state):
    from tikv_tpu.copr.jax_eval import JaxDagEvaluator, supports

    n = req["rows"]
    block_rows = req["block_rows"]
    t0 = time.perf_counter()
    if state.get("cache_key") != (n, block_rows, req.get("seed", 0)):
        # the in-process CPU fallback pre-seeds the parent's cache under
        # this key so the 100M-row fixture is never built twice in one RSS
        state["cache"] = build_cache(n, block_rows, seed=req.get("seed", 0))
        state["cache_key"] = (n, block_rows, req.get("seed", 0))
    build_s = time.perf_counter() - t0
    state["rows"] = n
    state["block_rows"] = block_rows
    state["evs"] = {}
    for name, dag_fn in _DAGS.items():
        dag = dag_fn()
        assert supports(dag), f"{name} must be device-eligible"
        state["evs"][name] = JaxDagEvaluator(dag, block_rows=block_rows)
    return {"build_s": round(build_s, 2)}


def _op_warm(req, state):
    """Best-of-N warm trials over the HBM-pinned block cache."""
    ev = state["evs"][req["q"]]
    cache = state["cache"]
    ev.run(None, cache=cache)  # compile + pin device arrays
    ts = []
    for _ in range(req.get("trials", 3)):
        t0 = time.perf_counter()
        resp = ev.run(None, cache=cache)
        ts.append(time.perf_counter() - t0)
    return {"ts": ts, "resp": resp.encode().hex()}


def _op_batch(req, state):
    """K queries fused into one device program (the batch_commands /
    batch_coprocessor serving pattern)."""
    from tikv_tpu.copr.jax_eval import JaxDagEvaluator, run_batch_cached

    k = req["k"]
    cache = state["cache"]
    block_rows = state["block_rows"]
    evs = []
    for name, dag_fn in _DAGS.items():
        for _ in range(k // 2):
            evs.append(JaxDagEvaluator(dag_fn(), block_rows=block_rows))
    run_batch_cached(evs, cache)  # compile warmup
    ts = []
    for _ in range(req.get("trials", 2)):
        t0 = time.perf_counter()
        resps = run_batch_cached(evs, cache)
        ts.append(time.perf_counter() - t0)
    return {"ts": ts, "resps": [r.encode().hex() for r in resps], "queries": len(evs)}


def _op_cold(req, state):
    """Scan + decode + execute from real KV bytes (no cache)."""
    from tikv_tpu.copr.executors import FixtureScanSource
    from tikv_tpu.copr.jax_eval import JaxDagEvaluator

    n = req["rows"]
    kvs = state.get("cold_kvs")
    if kvs is None or state.get("cold_rows") != n:
        kvs = state["cold_kvs"] = build_kvs(n, seed=req.get("seed", 1))
        state["cold_rows"] = n
    ev = JaxDagEvaluator(_DAGS[req["q"]](), block_rows=state["block_rows"])
    if req.get("warmup"):
        ev.run(FixtureScanSource(kvs[: state["block_rows"]]))
    t0 = time.perf_counter()
    resp = ev.run(FixtureScanSource(kvs))
    return {"t": time.perf_counter() - t0, "resp": resp.encode().hex()}


def _op_mvcc(req, state):
    """BASELINE config-4 flavor: Q6 over a real MVCC region on the native
    engine, through the batched MVCC decode leaf."""
    from tikv_tpu.copr.jax_eval import JaxDagEvaluator
    from tikv_tpu.copr.mvcc_batch import MvccBatchScanSource
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    n = req["rows"]
    kvs = build_kvs(n, seed=3)
    try:
        from tikv_tpu.native.engine import NativeEngine, native_available

        eng = NativeEngine() if native_available() else None
    except ImportError:
        eng = None
    if eng is None:
        from tikv_tpu.storage.btree_engine import BTreeEngine

        eng = BTreeEngine()
    items = []
    for rk, v in kvs:
        items.append(
            (Key.from_raw(rk).append_ts(20).encoded, Write(WriteType.PUT, 10, short_value=v).to_bytes())
        )
    eng.bulk_load(CF_WRITE, items)
    ev = JaxDagEvaluator(q6_dag(), block_rows=state.get("block_rows", 1 << 17))
    src = MvccBatchScanSource(eng.snapshot(), ts=100, ranges=[record_range(TABLE_ID)])
    t0 = time.perf_counter()
    resp = ev.run(src)
    return {"t": time.perf_counter() - t0, "resp": resp.encode().hex()}


def _topn_endpoint(n: int, enable_device: bool):
    """ONE definition of the TopN validation fixture + plan, shared by the
    device op and the CPU oracle so they can never drift apart."""
    from tikv_tpu.copr.dag import DagRequest, Selection, TableScan, TopN
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.rpn import call, col, const_int
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    kvs = build_kvs(n, seed=7)
    eng = BTreeEngine()
    items = []
    for rk, v in kvs:
        items.append(
            (Key.from_raw(rk).append_ts(20).encoded, Write(WriteType.PUT, 10, short_value=v).to_bytes())
        )
    eng.bulk_load(CF_WRITE, items)
    schema = _lineitem()

    def dag():
        return DagRequest(
            executors=[
                TableScan(TABLE_ID, schema[:5]),
                Selection([call("le", col(4), const_int(10500))]),
                TopN([(col(2), True), (col(1), False)], 100),
            ]
        )

    ep = Endpoint(LocalEngine(eng), enable_device=enable_device)
    return ep, dag, lambda: CoprRequest(103, dag(), [record_range(TABLE_ID)], 100)


def _op_topn(req, state):
    """Endpoint-driven device TopN over a real MVCC region: proves the
    device top-K merge runs behind the full request path with zero CPU
    fallbacks."""
    from tikv_tpu.copr.jax_eval import supports

    ep, dag, req_of = _topn_endpoint(req["rows"], enable_device=True)
    assert supports(dag()), "TopN plan must be device-eligible"
    r_warm = ep.handle_request(req_of())  # compile warmup
    t0 = time.perf_counter()
    r_dev = ep.handle_request(req_of())
    dt = time.perf_counter() - t0
    return {
        "t": dt,
        "resp": r_dev.data.hex(),
        "warm_resp": r_warm.data.hex(),
        "from_device": bool(r_dev.from_device),
        "fallbacks": ep.device_fallbacks,
        "err": str(ep.last_device_error or ""),
    }


def _filter_dag(kind: str, limit: int = 100_000):
    """ONE definition of the BASELINE config 1-2 plans (the _topn_endpoint
    rule: device op and CPU oracle share the fixture so they can never
    drift apart).  The Limit bounds the response so the metric measures
    scan+mask plumbing, not gigabytes of response encoding (the reference's
    criterion bench likewise consumes batches without a response); the
    region-cache events tighten it further for the same reason — they
    isolate the decode+MVCC cost the cache removes."""
    from tikv_tpu.copr.dag import DagRequest, Limit, Selection, TableScan
    from tikv_tpu.copr.rpn import call, col, const_int

    if kind == "scan":
        return DagRequest(executors=[
            TableScan(TABLE_ID, _lineitem()), Limit(limit),
        ])
    return DagRequest(executors=[
        TableScan(TABLE_ID, _lineitem()),
        Selection([
            call("lt", col(4), const_int(10500)),
            call("gt", col(1), const_int(5)),
            call("ge", col(2), const_int(100000)),
        ]),
        Limit(limit),
    ])


def _op_filter(req, state):
    """BASELINE configs 1-2: pure table scan (no predicate) and a
    3-predicate selection filter, through the device mask path over the
    shared block cache."""
    from tikv_tpu.copr.jax_eval import JaxDagEvaluator, supports

    cache = state["cache"]
    dag = _filter_dag(req["kind"])
    assert supports(dag)
    ev = JaxDagEvaluator(dag, block_rows=state["block_rows"])
    ev.run(None, cache=cache)  # compile
    ts = []
    for _ in range(req.get("trials", 3)):
        t0 = time.perf_counter()
        resp = ev.run(None, cache=cache)
        ts.append(time.perf_counter() - t0)
    return {"ts": ts, "resp": resp.encode().hex()}


def _op_region_cache(req, state):
    """scan_cached / selection_cached events: endpoint-served scan and
    selection DAGs over a real MVCC region, cold (region cache off — full
    vectorized MVCC resolve + batch decode EVERY request, today's production
    path) vs warm through the device-resident region column cache.  An
    update delta rides the sequence to prove byte-identity survives the
    incremental apply.  Both endpoints answer from the same engine, so any
    divergence is a correctness failure, not noise."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_key, record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    n = req["rows"]
    trials = req.get("trials", 3)
    kvs = build_kvs(n, seed=11)
    eng = BTreeEngine()
    items = []
    for rk, v in kvs:
        items.append(
            (Key.from_raw(rk).append_ts(20).encoded, Write(WriteType.PUT, 10, short_value=v).to_bytes())
        )
    eng.bulk_load(CF_WRITE, items)
    ep_warm = Endpoint(LocalEngine(eng), enable_device=True)
    ep_cold = Endpoint(LocalEngine(eng), enable_device=True, enable_region_cache=False)
    ctx = {"region_id": 1, "region_epoch": (1, 1)}

    limit = req.get("limit", 10_000)

    def mk(kind, ts, apply_index):
        return CoprRequest(103, _filter_dag(kind, limit=limit),
                           [record_range(TABLE_ID)], ts,
                           context=dict(ctx, apply_index=apply_index))

    out = {"match": True}
    for kind in ("scan", "selection"):
        r_cold = ep_cold.handle_request(mk(kind, 100, 7))  # compile warmup
        r_fill = ep_warm.handle_request(mk(kind, 100, 7))  # fills the image
        out["match"] &= r_fill.data == r_cold.data
        cold_ts, warm_ts = [], []
        for _ in range(trials):
            t0 = time.perf_counter()
            rc = ep_cold.handle_request(mk(kind, 100, 7))
            cold_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            rw = ep_warm.handle_request(mk(kind, 100, 7))
            warm_ts.append(time.perf_counter() - t0)
            out["match"] &= rw.data == rc.data
        out[kind] = {
            "cold_ts": cold_ts,
            "warm_ts": warm_ts,
            "outcome": rw.metrics.get("region_cache"),
        }
    # delta apply: update ~0.5% of rows at a later commit, bump apply_index
    n_delta = max(n // 200, 1)
    upd = build_kvs(n_delta, seed=12)
    wb_items = []
    for i, (_rk, v) in enumerate(upd):
        rk = record_key(TABLE_ID, i * (n // n_delta))
        wb_items.append(
            (Key.from_raw(rk).append_ts(40).encoded, Write(WriteType.PUT, 30, short_value=v).to_bytes())
        )
    eng.bulk_load(CF_WRITE, wb_items)
    delta_match = True
    for kind in ("scan", "selection"):
        rw = ep_warm.handle_request(mk(kind, 200, 8))
        rc = ep_cold.handle_request(mk(kind, 200, 8))
        delta_match &= rw.data == rc.data
        out.setdefault("delta", {})[kind] = {
            "outcome": rw.metrics.get("region_cache"),
            "delta_rows": rw.metrics.get("region_cache_delta_rows"),
        }
    out["match"] = bool(out["match"] and delta_match)
    out["stats"] = ep_warm.region_cache.stats.to_dict()
    return out


def _op_scan_compressed(req, state):
    """scan_compressed + warm-capacity event (docs/compressed_columns.md):
    the SAME engine region served three ways — cold (region cache off),
    warm DECODED-resident (--no-column-encoding behavior), warm
    ENCODED-resident (the default) — proving byte-identity and measuring
    warm throughput over encoded pins.  The capacity half fills as many
    region images as fit one fixed byte budget with encoding off vs on:
    the resident-region ratio IS the density win the HBM budget buys."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.region_cache import RegionColumnCache
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    n = req["rows"]
    trials = req.get("trials", 3)
    kvs = build_kvs(n, seed=13)
    eng = BTreeEngine()
    eng.bulk_load(CF_WRITE, [
        (Key.from_raw(rk).append_ts(20).encoded,
         Write(WriteType.PUT, 10, short_value=v).to_bytes())
        for rk, v in kvs
    ])
    le = LocalEngine(eng)
    ep_cold = Endpoint(le, enable_device=True, enable_region_cache=False)
    ep_dec = Endpoint(le, enable_device=True, encode_columns=False)
    ep_enc = Endpoint(le, enable_device=True)

    limit = req.get("limit", 10_000)

    def mk(kind, region_id=1):
        return CoprRequest(103, _filter_dag(kind, limit=limit),
                           [record_range(TABLE_ID)], 100,
                           context={"region_id": region_id,
                                    "region_epoch": (1, 1), "apply_index": 7})

    out = {"match": True}
    for kind in ("scan", "selection"):
        oracle = ep_cold.handle_request(mk(kind)).data
        out["match"] &= ep_dec.handle_request(mk(kind)).data == oracle
        out["match"] &= ep_enc.handle_request(mk(kind)).data == oracle
        enc_ts, dec_ts = [], []
        for _ in range(trials):
            t0 = time.perf_counter()
            rd = ep_dec.handle_request(mk(kind))
            dec_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            re_ = ep_enc.handle_request(mk(kind))
            enc_ts.append(time.perf_counter() - t0)
            out["match"] &= rd.data == oracle and re_.data == oracle
        out[kind] = {"encoded_ts": enc_ts, "decoded_ts": dec_ts,
                     "outcome": re_.metrics.get("region_cache")}
    [img_dec] = ep_dec.region_cache._images.values()
    [img_enc] = ep_enc.region_cache._images.values()
    out["decoded_image_bytes"] = img_dec.nbytes
    out["encoded_image_bytes"] = img_enc.nbytes
    out["compression_ratio"] = (
        img_enc.block_cache.nbytes_decoded() / max(img_enc.block_cache.nbytes(), 1)
    )
    out["encodings"] = sorted(set(img_enc.encodings.values()))

    # warm capacity at ONE byte budget: how many regions stay resident
    budget = img_dec.nbytes * req.get("budget_regions", 3)
    regions = req.get("regions", 12)
    resident = {}
    for label, encode in (("decoded", False), ("encoded", True)):
        rc = RegionColumnCache(byte_budget=budget, max_regions=4 * regions,
                               encode_columns=encode)
        ep = Endpoint(le, enable_device=True, region_cache=rc)
        for rid in range(1, regions + 1):
            ep.handle_request(mk("scan", region_id=rid))
        resident[label] = len(rc)
    out["budget_bytes"] = budget
    out["regions_offered"] = regions
    out["regions_resident_decoded"] = resident["decoded"]
    out["regions_resident_encoded"] = resident["encoded"]
    out["warm_capacity_ratio"] = resident["encoded"] / max(resident["decoded"], 1)
    return out


def _op_scan_pruned(req, state):
    """scan_pruned event (docs/zone_maps.md): a selective pk-range scan and
    a Limit-bearing scan over ONE warm region, timed with zone-map pruning
    on vs force-disabled through the kill switch.  Handles are clustered, so
    per-block handle zones are tight and a range predicate prunes ~90% of
    the blocks; the unpruned runs dispatch every block.  Every serve is
    byte-checked against the CPU oracle — a divergence is a correctness
    failure, not noise."""
    from tikv_tpu.copr import zone_maps
    from tikv_tpu.copr.dag import DagRequest, Limit, Selection, TableScan
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.rpn import call, col, const_int
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType
    from tikv_tpu.util.metrics import REGISTRY

    n = req["rows"]
    trials = req.get("trials", 3)
    kvs = build_kvs(n, seed=17)
    eng = BTreeEngine()
    eng.bulk_load(CF_WRITE, [
        (Key.from_raw(rk).append_ts(20).encoded,
         Write(WriteType.PUT, 10, short_value=v).to_bytes())
        for rk, v in kvs
    ])
    le = LocalEngine(eng)
    # enough blocks that per-block dispatch (what pruning saves) dominates
    # the request's fixed costs
    block_rows = req.get("block_rows", max(512, n // 64))
    ep_warm = Endpoint(le, enable_device=True, block_rows=block_rows)
    ep_cpu = Endpoint(le, enable_device=False, enable_region_cache=False)

    cut = n - max(n // 100, 1)

    def sel():
        return Selection([call("ge", col(0), const_int(cut))])

    dags = {
        "selective": DagRequest(executors=[
            TableScan(TABLE_ID, _lineitem()), sel(), Limit(1 << 20)]),
        "limit": DagRequest(executors=[
            TableScan(TABLE_ID, _lineitem()), sel(), Limit(32)]),
    }

    def mk(dag):
        return CoprRequest(103, dag, [record_range(TABLE_ID)], 100,
                           context={"region_id": 1, "region_epoch": (1, 1),
                                    "apply_index": 7})

    out = {"match": True, "block_rows": block_rows}
    try:
        for name, dag in dags.items():
            oracle = ep_cpu.handle_request(mk(dag)).data
            ep_warm.handle_request(mk(dag))  # fill + compile
            pruned_ts, unpruned_ts = [], []
            for _ in range(trials):
                zone_maps.set_enabled(False)
                t0 = time.perf_counter()
                ru = ep_warm.handle_request(mk(dag))
                unpruned_ts.append(time.perf_counter() - t0)
                zone_maps.set_enabled(True)
                t0 = time.perf_counter()
                rp = ep_warm.handle_request(mk(dag))
                pruned_ts.append(time.perf_counter() - t0)
                out["match"] &= rp.data == oracle and ru.data == oracle
            out[name] = {"pruned_ts": pruned_ts, "unpruned_ts": unpruned_ts,
                         "from_device": bool(rp.from_device)}
    finally:
        zone_maps.set_enabled(None)
    c = REGISTRY.counter("tikv_coprocessor_zone_prune_total", "")
    out["blocks_pruned"] = int(c.get(path="unary", outcome="pruned"))
    out["blocks_examined"] = int(c.get(path="unary", outcome="examined"))
    return out


def _op_join(req, state):
    """join event (docs/device_join.md): an equi-join of a probe region
    against a second warm build region, served on the device rank and hash
    paths (forced via the path override) vs the CPU join pipeline.  Keys
    are low-cardinality dict strings so BOTH device paths are feasible on
    one fixture; build-side multiplicity is fixed at 4 so the output stays
    ~2x the probe rows.  Every serve is byte-checked against the CPU
    oracle — a divergence is a correctness failure, not noise."""
    from tikv_tpu.copr import jax_join
    from tikv_tpu.copr.dag import DagRequest, Join, TableScan
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import encode_row, record_key, record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType
    from tikv_tpu.util.metrics import REGISTRY

    n = req["rows"]
    trials = req.get("trials", 3)
    distinct = max(64, n // 16)          # dict-eligible on both images
    nb = 4 * distinct                    # build multiplicity = 4
    pool = [b"k%06d" % i for i in range(2 * distinct)]  # half match
    cols = [ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
            ColumnInfo(2, FieldType.varchar()),
            ColumnInfo(3, FieldType.int64())]
    rng = np.random.default_rng(23)

    def rows_for(tid, count, keys):
        picks = rng.integers(0, len(keys), size=count)
        pay = rng.integers(0, 1 << 20, size=count)
        return [
            (Key.from_raw(record_key(tid, i)).append_ts(20).encoded,
             Write(WriteType.PUT, 10, short_value=encode_row(
                 cols[1:], [keys[int(picks[i])], int(pay[i])])).to_bytes())
            for i in range(count)
        ]

    probe_tid, build_tid = TABLE_ID, TABLE_ID + 1
    eng = BTreeEngine()
    eng.bulk_load(CF_WRITE, rows_for(probe_tid, n, pool) +
                  rows_for(build_tid, nb, pool[:distinct]))
    le = LocalEngine(eng)
    ep_warm = Endpoint(le, enable_device=True)
    ep_cpu = Endpoint(le, enable_device=False, enable_region_cache=False)

    def mk():
        dag = DagRequest(executors=[
            TableScan(probe_tid, cols),
            Join([TableScan(build_tid, cols)], [record_range(build_tid)],
                 1, 1, join_type="inner",
                 build_context={"region_id": 2, "region_epoch": (1, 1),
                                "apply_index": 7}),
        ])
        return CoprRequest(103, dag, [record_range(probe_tid)], 100,
                           context={"region_id": 1, "region_epoch": (1, 1),
                                    "apply_index": 7})

    oracle = ep_cpu.handle_request(mk()).data
    out = {"match": True, "probe_rows": n, "build_rows": nb}
    ts = {"rank": [], "hash": [], "cpu": []}
    try:
        for path in ("rank", "hash"):   # fill images + compile both paths
            jax_join.set_path_override(path)
            r = ep_warm.handle_request(mk())
            out["match"] &= r.data == oracle and r.from_device
        for _ in range(trials):
            for path in ("rank", "hash"):
                jax_join.set_path_override(path)
                t0 = time.perf_counter()
                r = ep_warm.handle_request(mk())
                ts[path].append(time.perf_counter() - t0)
                out["match"] &= r.data == oracle and r.from_device
            t0 = time.perf_counter()
            rc = ep_cpu.handle_request(mk())
            ts["cpu"].append(time.perf_counter() - t0)
            out["match"] &= rc.data == oracle
    finally:
        jax_join.set_path_override(None)
    c = REGISTRY.counter("tikv_coprocessor_join_total", "")
    out["served"] = {p: int(c.get(path=p, outcome="served"))
                    for p in ("rank", "hash")}
    for p, v in ts.items():
        out[f"{p}_ts"] = [round(x, 4) for x in v]
    return out


def _xregion_q6(cut: int):
    """A Q6-shaped selection+aggregation (no group-by): the dispatch-bound
    serving shape where cross-region batching pays off on every backend."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
    from tikv_tpu.copr.rpn import call, col, const_int

    return DagRequest(executors=[
        TableScan(TABLE_ID, _lineitem()),
        Selection([call("le", col(4), const_int(cut)),
                   call("lt", col(1), const_int(30))]),
        Aggregation([], [AggDescriptor("sum", call("multiply", col(2), col(3))),
                         AggDescriptor("count", None)]),
    ])


def _xregion_harness(req, seed: int):
    """Shared fixture for the xregion events: the loaded engine, the block
    geometry, and the mixed-workload request sweep (two Q6-shaped
    signatures + the Q1 group-by, ``clients`` per (region, query))."""
    from tikv_tpu.copr.endpoint import CoprRequest
    from tikv_tpu.copr.table import record_key
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    regions = req.get("regions", 8)
    rows_per = req.get("rows", 32000) // regions
    clients = req.get("clients", 3)
    kvs = build_kvs(regions * rows_per, seed=seed)
    eng = BTreeEngine()
    eng.bulk_load(CF_WRITE, [
        (Key.from_raw(rk).append_ts(20).encoded,
         Write(WriteType.PUT, 10, short_value=v).to_bytes())
        for rk, v in kvs
    ])
    # block geometry sized to the region: padding a 4k-row region to the 64k
    # default would spend 16x the compute per dispatch and bury the win
    block_rows = 1 << max(10, (rows_per - 1).bit_length())
    dags = [lambda: _xregion_q6(10500), lambda: _xregion_q6(9000), q1_dag]

    def mk(region, dag_fn):
        lo = record_key(TABLE_ID, region * rows_per)
        hi = record_key(TABLE_ID, (region + 1) * rows_per)
        return CoprRequest(103, dag_fn(), [(lo, hi)], 100,
                           context={"region_id": region + 1,
                                    "region_epoch": (1, 1), "apply_index": 7})

    def sweep():
        return [mk(r, d) for d in dags for r in range(regions)
                for _ in range(clients)]

    return eng, block_rows, sweep, regions, rows_per, clients


def _xregion_trials(ep_serial, ep_batch, ep_cpu, sweep, trials: int):
    """Warm both endpoints, assert three-way byte-identity (serial path,
    batched path, CPU oracle), then time serial-vs-batched sweeps."""
    for _ in range(2):  # warmup: fill region images, compile both paths
        serial = [ep_serial.handle_request(q) for q in sweep()]
        batched = ep_batch.handle_batch(sweep())
    oracle = [ep_cpu.handle_request(q) for q in sweep()]
    match = all(s.data == b.data == o.data
                for s, b, o in zip(serial, batched, oracle))
    from_device = all(b.from_device for b in batched)
    serial_ts, batch_ts = [], []
    for _ in range(trials):
        t0 = time.perf_counter()
        for q in sweep():
            ep_serial.handle_request(q)
        serial_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        ep_batch.handle_batch(sweep())
        batch_ts.append(time.perf_counter() - t0)
    return {
        "match": bool(match),
        "from_device": bool(from_device),
        "requests": len(sweep()),
        "serial_ts": [round(x, 4) for x in serial_ts],
        "batch_ts": [round(x, 4) for x in batch_ts],
    }


def _op_xregion(req, state):
    """xregion_batch event: the unified read scheduler's cross-region
    continuous batching (copr/scheduler.py) vs per-request device serving.

    An 8-region table serves a mixed workload — a Q6-shaped selection
    aggregate, a second Q6 variant (different signature), and the Q1
    group-by — issued by ``clients`` concurrent clients per region, the
    batch_commands fan-in shape.  Serial = one handle_request per request
    (today's per-request device path, warm region-cache hits throughout);
    batched = ONE handle_batch, which the scheduler collapses into one
    cross-region program per plan signature (identical requests from
    different clients share an execution slot).  Responses must be
    byte-identical to the serial path AND the CPU pipeline."""
    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.util.metrics import REGISTRY

    eng, block_rows, sweep, regions, rows_per, clients = _xregion_harness(req, seed=17)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=block_rows)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    out = _xregion_trials(ep, ep, ep_cpu, sweep, req.get("trials", 5))
    return {
        **out,
        "regions": regions,
        "clients": clients,
        "rows_per_region": rows_per,
        "total_rows": out["requests"] * rows_per,
        "xregion_batches": REGISTRY.counter(
            "tikv_coprocessor_sched_batches_total", "").get(kind="xregion"),
    }


def _op_wire(req, state):
    """wire event (docs/wire_path.md): SOCKET-level coalesced generic
    serving vs per-request CPU serving over the same engine.

    Two real TCP servers serve the xregion mixed workload to concurrent
    client connections:

    * **coalesced** — device endpoint with the read scheduler's continuous
      lanes started (the standalone default): unary requests from many
      connections coalesce into cross-region programs, identical requests
      share a slot, responses ride the zero-copy frame writer.
    * **per-request CPU** — enable_device=False endpoint, scheduler
      stopped: every request runs the Python MVCC pipeline alone (the
      pre-PR cluster serving shape, the frozen-28k-rows/s wall).

    Responses must be byte-identical between the two modes; the speedup is
    the bench_smoke cluster wire floor (relative, hardware-independent)."""
    from tikv_tpu.copr.dag_wire import dag_to_wire
    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.util.metrics import REGISTRY

    eng, block_rows, sweep, regions, rows_per, clients = _xregion_harness(req, seed=29)
    trials = req.get("trials", 3)
    reqs = [
        {"dag": dag_to_wire(r.dag), "ranges": [list(t) for t in r.ranges],
         "start_ts": r.start_ts, "context": dict(r.context)}
        for r in sweep()
    ]
    n_conns = min(len(reqs), req.get("conns", 6))

    def serve_all(addr):
        conns = [Client(*addr) for _ in range(n_conns)]
        results: list = [None] * len(reqs)
        errs: list = []

        def worker(ci):
            try:
                for i in range(ci, len(reqs), n_conns):
                    results[i] = conns[ci].call("coprocessor", reqs[i],
                                                timeout=300.0)
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=worker, args=(ci,))
                   for ci in range(n_conns)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        for c in conns:
            c.close()
        if errs:
            raise errs[0]
        for r in results:
            if not isinstance(r, dict) or r.get("error"):
                raise RuntimeError(f"wire serving failed: {r}")
        return [r["data"] for r in results], dt

    def run_mode(enable_device: bool, continuous: bool):
        ep = Endpoint(LocalEngine(eng), enable_device=enable_device,
                      block_rows=block_rows)
        svc = KvService(Storage(engine=LocalEngine(eng)), ep)
        srv = Server(svc)
        srv.start()
        if continuous:
            ep.scheduler.start()
        try:
            serve_all(srv.addr)  # warmup: cache fill + compile
            datas = None
            ts = []
            for _ in range(trials):
                datas, dt = serve_all(srv.addr)
                ts.append(dt)
            return datas, ts
        finally:
            ep.scheduler.stop()
            srv.stop()

    coalesce = REGISTRY.counter("tikv_wire_coalesce_total", "")
    batched_before = coalesce.get(outcome="batched")
    coal_datas, coal_ts = run_mode(True, True)
    batched_delta = coalesce.get(outcome="batched") - batched_before
    cpu_datas, cpu_ts = run_mode(False, False)
    return {
        "match": coal_datas == cpu_datas,
        "requests": len(reqs),
        "conns": n_conns,
        "regions": regions,
        "rows_per_region": rows_per,
        "coalesced_ts": [round(x, 4) for x in coal_ts],
        "per_request_ts": [round(x, 4) for x in cpu_ts],
        "coalesced_batched": int(batched_delta),
    }


def _op_wire_chunk(req, state):
    """wire_chunk event (docs/wire_path.md "Columnar chunk responses"):
    the SAME socket workload served datum-encoded vs TypeChunk-encoded.

    A selection scan (ship ≤ cut passes ~95% of rows) over warm region
    images is the encode-bound wire shape: the device path computes the row
    mask, and the response cost is row materialization + codec on the
    server plus per-datum Python decode at the client.  Both modes run the
    identical requests over real TCP with 6 client connections against the
    same warm endpoint; the timed window includes the CLIENT decode —
    datum responses must decode row by row to be usable, chunk responses
    decode each column slab with one numpy pass (chunk_codec.column_numpy)
    — because shipping columns to the client IS the contract being
    measured.  Decoded values must be identical across encodings; the
    bench_smoke floor is chunk ≥3x datum rows/s."""
    from tikv_tpu.copr import chunk_codec
    from tikv_tpu.copr.dag import (
        ENC_TYPE_CHUNK,
        DagRequest,
        Selection,
        SelectResponse,
        TableScan,
        chunk_output_field_types,
        decode_wire_response,
        response_data,
    )
    from tikv_tpu.copr.dag_wire import dag_to_wire
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.rpn import call, col, const_int
    from tikv_tpu.copr.table import record_key
    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn_types import Key, Write, WriteType
    from tikv_tpu.util.metrics import REGISTRY

    regions = req.get("regions", 4)
    rows_per = req.get("rows", 32000) // regions
    trials = req.get("trials", 3)
    kvs = build_kvs(regions * rows_per, seed=43)
    eng = BTreeEngine()
    eng.bulk_load(CF_WRITE, [
        (Key.from_raw(rk).append_ts(20).encoded,
         Write(WriteType.PUT, 10, short_value=v).to_bytes())
        for rk, v in kvs
    ])
    block_rows = 1 << max(10, (rows_per - 1).bit_length())

    def scan_dag(enc):
        return DagRequest(
            executors=[TableScan(TABLE_ID, _lineitem()),
                       Selection([call("le", col(4), const_int(10500))])],
            encode_type=enc,
        )

    def wire_reqs(enc):
        d = dag_to_wire(scan_dag(enc))
        out = []
        for r in range(regions):
            lo = record_key(TABLE_ID, r * rows_per)
            hi = record_key(TABLE_ID, (r + 1) * rows_per)
            out.append({"dag": d, "ranges": [[lo, hi]], "start_ts": 100,
                        "context": {"region_id": r + 1, "region_epoch": (1, 1),
                                    "apply_index": 7}})
        return out

    chunk_fts = chunk_output_field_types(scan_dag(ENC_TYPE_CHUNK))
    n_conns = req.get("conns", 6)

    def decode_rows_count(r):
        """Client-side decode in the mode's native shape (timed)."""
        if r.get("encode_type"):
            n = 0
            for chunk in SelectResponse.decode(response_data(r)).chunks:
                for c in chunk_codec.decode_chunk(chunk, chunk_fts):
                    chunk_codec.column_numpy(c)
                n += c.rows
            return n
        return len(SelectResponse.decode(r["data"]).iter_rows())

    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=block_rows)
    svc = KvService(Storage(engine=LocalEngine(eng)), ep)
    srv = Server(svc)
    srv.start()
    try:
        def serve_all(reqs, decode=True):
            conns = [Client(*srv.addr) for _ in range(n_conns)]
            rows_seen = [0] * n_conns
            raw: list = [None] * len(reqs)
            errs: list = []

            def worker(ci):
                try:
                    for i in range(ci, len(reqs), n_conns):
                        r = conns[ci].call("coprocessor", reqs[i], timeout=300.0)
                        if r.get("error"):
                            raise RuntimeError(str(r["error"]))
                        raw[i] = r
                        if decode:
                            rows_seen[ci] += decode_rows_count(r)
                except Exception as exc:  # noqa: BLE001
                    errs.append(exc)

            threads = [threading.Thread(target=worker, args=(ci,))
                       for ci in range(n_conns)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            for c in conns:
                c.close()
            if errs:
                raise errs[0]
            return raw, sum(rows_seen), dt

        # one request per (region, client slot): every connection decodes
        per_round = wire_reqs(0) * n_conns
        per_round_c = wire_reqs(ENC_TYPE_CHUNK) * n_conns
        serve_all(per_round)    # warmup: cache fill + compile + route
        serve_all(per_round_c)
        chunk_counter = REGISTRY.counter("tikv_wire_chunk_total", "")
        chunk_before = chunk_counter.get(outcome="chunk", cause="")
        datum_ts, chunk_ts = [], []
        rows_total = 0
        for _ in range(trials):
            _raw, n_rows, dt = serve_all(per_round)
            datum_ts.append(dt)
            rows_total = n_rows
            _raw, n_rows_c, dt = serve_all(per_round_c)
            chunk_ts.append(dt)
            if n_rows_c != rows_total:
                raise AssertionError(
                    f"chunk decoded {n_rows_c} rows, datum {rows_total}")
        chunk_served = chunk_counter.get(outcome="chunk", cause="") - chunk_before
        # full value-level differential on one response per region
        raw_d, _n, _dt = serve_all(wire_reqs(0), decode=False)
        raw_c, _n, _dt = serve_all(wire_reqs(ENC_TYPE_CHUNK), decode=False)
        match = all(
            decode_wire_response(rd, scan_dag(0)).iter_rows()
            == decode_wire_response(rc, scan_dag(ENC_TYPE_CHUNK)).iter_rows()
            for rd, rc in zip(raw_d, raw_c)
        )
        return {
            "match": bool(match),
            "requests": len(per_round),
            "conns": n_conns,
            "regions": regions,
            "rows_per_region": rows_per,
            "rows_decoded_per_round": rows_total,
            "datum_ts": [round(x, 4) for x in datum_ts],
            "chunk_ts": [round(x, 4) for x in chunk_ts],
            "chunk_served": int(chunk_served),
        }
    finally:
        srv.stop()


def _op_sharded_xregion(req, state):
    """sharded_xregion event (ISSUE 3): the SAME warm cross-region workload
    as ``xregion``, but over MESH-SHARDED region images — the scheduler
    packs slots per owner device and dispatches ONE shard_map program over
    every visible device, partial aggregate states merging with
    psum/pmin/pmax — vs per-request serving on a single-device endpoint
    over the same warm images.  Byte-identity is asserted against both the
    single-device path and the CPU pipeline; per-device slab occupancy and
    bytes pinned are reported."""
    import jax

    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.parallel.mesh import device_slab_load, make_mesh
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.util.metrics import REGISTRY

    n_dev = jax.device_count()
    if n_dev < 2:
        return {"skipped": True, "reason": f"need >1 devices, have {n_dev}"}
    eng, block_rows, sweep, regions, rows_per, clients = _xregion_harness(req, seed=23)
    mesh = make_mesh(groups=2 if n_dev % 2 == 0 else 1)
    ep_shard = Endpoint(LocalEngine(eng), enable_device=True,
                        block_rows=block_rows, mesh=mesh)
    ep_single = Endpoint(LocalEngine(eng), enable_device=True,
                         block_rows=block_rows)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    out = _xregion_trials(ep_single, ep_shard, ep_cpu, sweep,
                          req.get("trials", 5))
    placement = ep_shard.region_cache.placement()
    caches = ep_shard.region_cache.resident_block_caches()
    load = device_slab_load(caches, mesh) if caches else {}
    s_max = max(max(load.values()), 1) if load else 1
    return {
        **out,
        "devices": n_dev,
        "regions": regions,
        "clients": clients,
        "rows_per_region": rows_per,
        "sharded_batches": REGISTRY.counter(
            "tikv_coprocessor_sched_batches_total", "").get(kind="xregion_sharded"),
        "device_bytes_pinned": {str(k): int(v) for k, v in placement.items()},
        "device_occupancy": {str(k): round(v / s_max, 3) for k, v in load.items()},
    }


def _op_mixed_rw(req, state):
    """mixed_rw event (ISSUE 4): readers hammer a warm region WHILE writers
    commit through the txn scheduler over a single-store raft group.

    Two measurements on the same engine:

    * write path — W single-key update txns (prewrite + commit) through the
      scheduler, per-command (``group_commit_max=1``: one raft proposal per
      command, today's shape) vs grouped (queued compatible commands
      coalesce into one proposal).  The speedup is the propose→apply→ack
      amortization of group commit.
    * warm serving under writes — after every grouped write batch, one
      coprocessor read of the region.  With write-through deltas the read
      folds the buffered change into the resident image (outcome
      ``wt_delta``/``hit``) instead of re-scanning CF_WRITE; the hit-rate
      is warm outcomes / reads.  Every read is byte-checked against the
      CPU pipeline over the same engine.
    """
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import encode_row, record_key, record_range
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
    from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn.scheduler import Scheduler
    from tikv_tpu.storage.txn_types import Key, Mutation, Write, WriteType

    rows = req.get("rows", 2048)
    n_writes = req.get("writes", 64)  # txns per measured batch
    rounds = req.get("rounds", 6)  # mixed read/write rounds
    trials = req.get("trials", 3)
    block_rows = 1 << max(10, (rows - 1).bit_length())

    c = Cluster(1)
    c.run()
    kv = c.raftkv(1)
    ctx = {"region_id": FIRST_REGION_ID}
    # seed the table as ONE raft proposal (a bulk-load shape)
    kvs = build_kvs(rows, seed=29)
    wb = WriteBatch()
    for rk, v in kvs:
        wb.put_cf(CF_WRITE, Key.from_raw(rk).append_ts(20).encoded,
                  Write(WriteType.PUT, 10, short_value=v).to_bytes())
    kv.write(ctx, wb)
    ep = Endpoint(kv, enable_device=True, block_rows=block_rows)
    ep_cpu = Endpoint(kv, enable_device=False)
    non_handle = _lineitem()[1:]
    ts_state = {"ts": 1000}

    def next_ts():
        ts_state["ts"] += 1
        return ts_state["ts"]

    def commit_batch(sched, handles):
        """W update txns: async-submit all prewrites, wait, then all
        commits — the queue depth group commit feeds on."""
        pending = []
        for h in handles:
            rk = record_key(TABLE_ID, int(h))
            row = encode_row(non_handle,
                             [int(h) % 50 + 1, 100000, 5, 9000, b"A", b"F"])
            start = next_ts()
            task = sched.submit(Prewrite(
                [Mutation.put(Key.from_raw(rk), row)], rk, start_ts=start), ctx)
            pending.append((rk, start, task))
        for _rk, _start, t in pending:
            t.done.wait(60)
            if t.exc is not None:
                raise t.exc
        commits = [sched.submit(Commit([Key.from_raw(rk)], start, next_ts()), ctx)
                   for rk, start, _t in pending]
        for t in commits:
            t.done.wait(60)
            if t.exc is not None:
                raise t.exc

    rng = np.random.default_rng(31)

    def measure(group_max):
        sched = Scheduler(kv, pool_size=1, group_commit_max=group_max)
        try:
            ts = []
            for _ in range(trials):
                handles = rng.choice(rows, size=n_writes, replace=False)
                t0 = time.perf_counter()
                commit_batch(sched, handles)
                ts.append(time.perf_counter() - t0)
        finally:
            sched.stop()
        return ts

    def read(ts):
        req_ = CoprRequest(103, _filter_dag("scan", limit=2000),
                           [record_range(TABLE_ID)], ts, context=dict(ctx))
        return ep.handle_request(req_)

    def read_cpu(ts):
        req_ = CoprRequest(103, _filter_dag("scan", limit=2000),
                           [record_range(TABLE_ID)], ts, context=dict(ctx))
        return ep_cpu.handle_request(req_)

    # warm the image + compile before timing anything
    r0 = read(next_ts())
    match = r0.data == read_cpu(ts_state["ts"]).data

    percmd_ts = measure(1)
    grouped_ts = measure(32)

    # mixed phase: grouped writers + a reader per batch
    sched = Scheduler(kv, pool_size=1, group_commit_max=32)
    outcomes: list[str] = []
    read_ts: list[float] = []
    try:
        for _ in range(rounds):
            handles = rng.choice(rows, size=n_writes, replace=False)
            commit_batch(sched, handles)
            ts = next_ts()
            t0 = time.perf_counter()
            r = read(ts)
            read_ts.append(time.perf_counter() - t0)
            outcomes.append(r.metrics.get("region_cache", ""))
            match &= r.data == read_cpu(ts).data
    finally:
        sched.stop()
    warm = sum(1 for o in outcomes if o in ("wt_delta", "hit"))
    st = ep.region_cache.stats
    return {
        "match": bool(match),
        "rows": rows,
        "writes_per_batch": n_writes,
        "rounds": rounds,
        "percmd_ts": [round(x, 4) for x in percmd_ts],
        "grouped_ts": [round(x, 4) for x in grouped_ts],
        "commits_per_s_percmd": n_writes / float(np.median(percmd_ts)),
        "commits_per_s_grouped": n_writes / float(np.median(grouped_ts)),
        "group_speedup": float(np.median(percmd_ts)) / float(np.median(grouped_ts)),
        "warm_hit_rate": warm / max(len(outcomes), 1),
        "outcomes": outcomes,
        "read_rows_per_s": rows * len(read_ts) / max(sum(read_ts), 1e-9),
        "scan_deltas": st.deltas,
        "wt_deltas": st.wt_deltas,
    }


def _op_overload(req, state):
    """overload event (docs/robustness.md "Overload control plane"):
    well-behaved-tenant throughput retention at saturation.

    One device endpoint with continuous scheduler lanes and per-tenant
    quotas: a ``victim`` tenant runs the cross-region sweep sequentially
    (baseline), then re-runs it while a ``hot`` tenant floods identical
    device-eligible work from ``flood_threads`` threads at many times its
    quota.  Reported: victim throughput retention (loaded / baseline),
    victim failures (must be 0 — quotas shed the HOT tenant, not the
    victim), and how much hot overage was shed."""
    import itertools as _it

    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.copr.overload import (
        OverloadConfig, OverloadControl, TenantQuota,
    )
    from tikv_tpu.copr.scheduler import SchedulerConfig
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.util.metrics import REGISTRY

    eng, block_rows, sweep, regions, rows_per, clients = _xregion_harness(
        req, seed=43)
    trials = req.get("trials", 3)
    flood_threads = req.get("flood_threads", 3)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=block_rows,
                  sched_config=SchedulerConfig(max_queue=64, busy_reject=True))
    ep.overload = OverloadControl(
        OverloadConfig(
            tenants={"hot": TenantQuota(requests_per_s=20.0, burst_s=0.5,
                                        max_priority="low")},
            max_priority="normal", max_wait_s=0.002, adaptive=False,
        ),
        region_cache=ep.region_cache)
    admission = REGISTRY.counter("tikv_overload_admission_total", "")

    def tag(q, tenant, ts):
        q.context = dict(q.context, tenant=tenant)
        q.start_ts = ts
        return q

    ep.scheduler.start()
    try:
        for _ in range(2):  # warm images + compile
            for q in sweep():
                ep.handle_request(tag(q, "victim", 100))
        base_ts, load_ts, failures = [], [], 0
        for _ in range(trials):
            reqs = [tag(q, "victim", 100) for q in sweep()]
            t0 = time.perf_counter()
            for q in reqs:
                ep.scheduler.execute(q)
            base_ts.append(time.perf_counter() - t0)
        shed0 = admission.get(tenant="hot", outcome="shed", where="sched")
        stop = threading.Event()
        hot_sent = _it.count()
        # paced flood: ~hot_qps submissions/s (25x the 20 rps quota) — a
        # real client herd, not a GIL-burning spin loop (the floor measures
        # the ADMISSION policy's fairness, not Python thread contention)
        interval = flood_threads / float(req.get("hot_qps", 500.0))

        def flood():
            while not stop.is_set():
                try:
                    ep.scheduler.execute(tag(sweep()[0], "hot", 100))
                except Exception:  # noqa: BLE001 — shed IS the mechanism
                    pass
                next(hot_sent)
                stop.wait(interval)

        hot = [threading.Thread(target=flood, daemon=True)
               for _ in range(flood_threads)]
        for t in hot:
            t.start()
        try:
            # one unmeasured sweep under flood: the hot burst drains and
            # the admission plane reaches steady state before timing
            for q in sweep():
                try:
                    ep.scheduler.execute(tag(q, "victim", 100))
                except Exception:  # noqa: BLE001
                    failures += 1
            for _ in range(trials):
                reqs = [tag(q, "victim", 100) for q in sweep()]
                t0 = time.perf_counter()
                for q in reqs:
                    try:
                        ep.scheduler.execute(q)
                    except Exception:  # noqa: BLE001 — victim must not shed
                        failures += 1
                load_ts.append(time.perf_counter() - t0)
        finally:
            stop.set()
            for t in hot:
                t.join(timeout=5.0)
        hot_shed = admission.get(tenant="hot", outcome="shed",
                                 where="sched") - shed0
        base = float(np.median(base_ts))
        load = float(np.median(load_ts))
        return {
            "regions": regions,
            "rows_per_region": rows_per,
            "requests_per_sweep": len(sweep()),
            "baseline_ts": [round(x, 4) for x in base_ts],
            "loaded_ts": [round(x, 4) for x in load_ts],
            "retention": round(base / load, 3) if load else 0.0,
            "victim_failures": failures,
            "hot_submitted": next(hot_sent),
            "hot_shed": int(hot_shed),
        }
    finally:
        ep.scheduler.stop()


def _op_cost_router(req, state):
    """cost_router event (docs/cost_router.md): the self-tuning dispatch
    loop.  Mixed workload of three plan signatures over small regions
    under a deliberately oversized block geometry: both Q6 selections stay
    far faster on the device even padded, but the Q1 group-by pays the
    whole padded tile per serve and the CPU pipeline beats it.  The static
    ladder sends all three to the device; the cost router learns per-sig
    path costs from the observatory and routes Q1 to the CPU.  Reported:
    router-on vs router-off aggregate throughput (floor >= 1.2x), byte
    identity of EVERY routed response vs the CPU oracle, the chosen-path
    distribution, and the geometry tuner's end state once it is let loose
    on block_rows (one change in flight, warmup-discarded judgment,
    automatic revert on floor regression)."""
    from tikv_tpu.copr import observatory as _obs
    from tikv_tpu.copr.costmodel import (
        CostRouter, GeometryTuner, RouterConfig, TunerConfig,
    )
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_key
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    # the event measures the router LEARNING from its own warm rounds:
    # plan signatures don't key on table size or geometry, so earlier
    # bench ops serving the same Q1/Q6 shapes at different block
    # geometry would leak warm (and here-misleading) path profiles into
    # the process-global observatory
    _obs.OBSERVATORY.reset()

    regions = req.get("regions", 2)
    rows_per = req.get("rows", 2048) // regions
    trials = req.get("trials", 3)
    block_rows = req.get("block_rows", 1 << 18)
    kvs = build_kvs(regions * rows_per, seed=31)
    eng = BTreeEngine()
    eng.bulk_load(CF_WRITE, [
        (Key.from_raw(rk).append_ts(20).encoded,
         Write(WriteType.PUT, 10, short_value=v).to_bytes())
        for rk, v in kvs
    ])
    dags = [lambda: _xregion_q6(10500), lambda: _xregion_q6(9000), q1_dag]
    sig_ids = {_obs.dag_sig(d())[0] for d in dags}

    def mk(region, dag_fn):
        lo = record_key(TABLE_ID, region * rows_per)
        hi = record_key(TABLE_ID, (region + 1) * rows_per)
        return CoprRequest(103, dag_fn(), [(lo, hi)], 100,
                           context={"region_id": region + 1,
                                    "region_epoch": (1, 1), "apply_index": 7})

    def sweep():
        return [mk(r, d) for d in dags for r in range(regions)]

    ep_off = Endpoint(LocalEngine(eng), enable_device=True,
                      block_rows=block_rows,
                      cost_router=CostRouter(enabled=False))
    ep_on = Endpoint(LocalEngine(eng), enable_device=True,
                     block_rows=block_rows,
                     cost_router=CostRouter(config=RouterConfig(
                         seed=req.get("seed", 11), epsilon=0.05,
                         cold_probe_rate=0.05, min_count=3)))
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)

    # warm images + compiles on both device endpoints AND run the oracle:
    # the observatory is process-global and keyed by plan signature, so the
    # oracle's serves ARE the cpu-path profiles the router prices against
    for _ in range(3):
        for q in sweep():
            ep_off.handle_request(q)
        for q in sweep():
            ep_cpu.handle_request(q)
        for q in sweep():
            ep_on.handle_request(q)
    oracle = [ep_cpu.handle_request(q).data for q in sweep()]
    routed = [ep_on.handle_request(q).data for q in sweep()]
    serial = [ep_off.handle_request(q).data for q in sweep()]
    match = (all(r == o for r, o in zip(routed, oracle))
             and all(s == o for s, o in zip(serial, oracle)))

    off_ts, on_ts = [], []
    for _ in range(trials):
        reqs = sweep()
        t0 = time.perf_counter()
        for q in reqs:
            ep_off.handle_request(q)
        off_ts.append(time.perf_counter() - t0)
        reqs = sweep()
        t0 = time.perf_counter()
        for q in reqs:
            ep_on.handle_request(q)
        on_ts.append(time.perf_counter() - t0)
    sweep_rows = len(sweep()) * rows_per
    off = float(np.median(off_ts))
    on = float(np.median(on_ts))

    # chosen-path distribution for OUR three signatures (the observatory
    # carries every sig served in this process)
    dist: dict = {}
    for s, entry in _obs.OBSERVATORY.snapshot()["sigs"].items():
        if s not in sig_ids:
            continue
        for k, v in entry.get("routes", {}).items():
            dist[k] = dist.get(k, 0) + v

    # geometry auto-tuning: hand the router-on endpoint's block geometry to
    # the tuner and let the control loop walk it down from the deliberately
    # bad initial value, one change in flight
    tuner = GeometryTuner(config=TunerConfig(
        min_serves=req.get("tuner_min_serves", 12), warmup_ticks=1))
    tuner.register("coprocessor.block_rows",
                   lambda: ep_on.block_rows,
                   lambda v: ep_on.set_block_rows(int(v)),
                   1 << 12, block_rows, integer=True)
    initial_br = ep_on.block_rows
    target_br = req.get("tuner_target", 1 << 14)
    for _ in range(req.get("tuner_ticks", 30)):
        for _ in range(3):
            for q in sweep():
                ep_on.handle_request(q)
        tuner.tick()
        if ep_on.block_rows <= target_br:
            break
    tuned = [ep_on.handle_request(q).data for q in sweep()]
    match = match and all(t == o for t, o in zip(tuned, oracle))
    tsnap = tuner.snapshot()
    return {
        "regions": regions,
        "rows_per_region": rows_per,
        "block_rows": block_rows,
        "match": bool(match),
        "off_ts": [round(x, 4) for x in off_ts],
        "on_ts": [round(x, 4) for x in on_ts],
        "speedup": round(off / on, 3) if on else 0.0,
        "rows_per_s_off": round(sweep_rows / off, 1) if off else 0.0,
        "rows_per_s_on": round(sweep_rows / on, 1) if on else 0.0,
        "route_dist": dist,
        "router": ep_on.cost_router.snapshot()["decisions_by_reason"],
        "tuner_initial_block_rows": initial_br,
        "tuner_final_block_rows": ep_on.block_rows,
        "tuner_counts": tsnap["counts"],
        "tuner_history": tsnap["history"][-8:],
    }


_OPS = {
    "build": _op_build,
    "warm": _op_warm,
    "batch": _op_batch,
    "cold": _op_cold,
    "mvcc": _op_mvcc,
    "topn": _op_topn,
    "filter": _op_filter,
    "region_cache": _op_region_cache,
    "scan_compressed": _op_scan_compressed,
    "scan_pruned": _op_scan_pruned,
    "join": _op_join,
    "xregion": _op_xregion,
    "wire": _op_wire,
    "wire_chunk": _op_wire_chunk,
    "sharded_xregion": _op_sharded_xregion,
    "mixed_rw": _op_mixed_rw,
    "overload": _op_overload,
    "cost_router": _op_cost_router,
}


# ---------------------------------------------------------------------------
# Worker subprocess
# ---------------------------------------------------------------------------


def _worker_main() -> None:
    t0 = time.time()

    def emit(obj):
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    stop_hb = threading.Event()

    def hb():
        while not stop_hb.wait(10.0):
            emit({"ev": "init_wait", "t": round(time.time() - t0, 1)})

    threading.Thread(target=hb, daemon=True).start()
    import jax

    try:
        # AOT persistence: compiled programs survive across bench runs, so
        # cold trials stop paying XLA compilation on every invocation
        jax.config.update("jax_compilation_cache_dir", _JAX_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 — older jax: cache is an optimization
        pass
    import jax.numpy as jnp

    x = jnp.ones((256, 256), jnp.float32)
    (x @ x).block_until_ready()  # backend init — the step that hangs/fails
    stop_hb.set()
    emit({"ev": "ready", "platform": jax.devices()[0].platform, "t": round(time.time() - t0, 1)})
    state: dict = {}
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        req = json.loads(line)
        if req.get("op") == "quit":
            emit({"id": req.get("id"), "ok": True})
            break
        try:
            out = _OPS[req["op"]](req, state)
            out["id"] = req.get("id")
            out["ok"] = True
        except Exception as e:  # noqa: BLE001 — parent decides what is fatal
            import traceback

            out = {
                "id": req.get("id"),
                "ok": False,
                "err": f"{type(e).__name__}: {e}",
                "tb": traceback.format_exc()[-2000:],
            }
        emit(out)


class WorkerDied(RuntimeError):
    pass


class DeviceWorker:
    """Parent-side handle on the persistent device worker.

    Wedge detection runs on its OWN monitor thread from the moment of
    spawn, not only inside ``wait_ready``: the BENCH_r05 failure shape was a
    worker that heartbeated ``init_wait`` for the full 900s budget while the
    parent was busy building the CPU fixtures, then died with
    ``init_budget_exhausted`` / ``device_cache_built s:0.0`` and no cause.
    Now the verdict lands at BENCH_INIT_STALL (default 300s) of worker
    uptime with zero progress — or at BENCH_INIT_STALL of heartbeat
    SILENCE (backend init holding the GIL wedges even the heartbeat
    thread) — whichever comes first, the worker is killed immediately with
    a named cause in the event log, and the remaining init budget is never
    burned."""

    def __init__(self, timeline: list):
        self.timeline = timeline
        self.t0 = time.time()
        env = {k: v for k, v in os.environ.items()}
        env.pop("JAX_PLATFORMS", None)  # sitecustomize re-exports the device
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=None,  # worker stderr goes straight to ours
            text=True,
            start_new_session=True,
            env=env,
        )
        self._mark("spawn")
        self.platform = None
        self._q: queue.Queue = queue.Queue()
        self._seq = 0
        self._stall_s = float(os.environ.get("BENCH_INIT_STALL", "300"))
        self._spawned_at = time.time()
        self._last_msg = time.time()
        self._ready_seen = False
        self._wedged: str | None = None  # cause, set once by any detector
        self._wedge_mu = threading.Lock()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()

    def _mark(self, ev, **kw):
        entry = {"t": round(time.time() - self.t0, 1), "ev": ev, **kw}
        self.timeline.append(entry)
        print(f"bench: [{entry['t']:7.1f}s] {ev} {kw if kw else ''}", file=sys.stderr)

    def _mark_init_wait(self, worker_t) -> None:
        """Coalesced init heartbeat: the worker emits one ``init_wait``
        every ~10s for up to the whole 900s budget, and BENCH_r05 showed 90
        near-identical timeline lines drowning the JSON tail.  ONE timeline
        entry is updated in place (``first_t``/``last_t``/``count``); the
        stderr line prints only on the first beat.  The ``backend_probe``
        verdict (ok/timeout/error + cause) is produced independently by the
        monitor/wait_ready flow and is untouched by this folding."""
        e = getattr(self, "_init_wait_entry", None)
        if e is None:
            self._init_wait_entry = e = {
                "t": round(time.time() - self.t0, 1), "ev": "worker_init_wait",
                "first_t": worker_t, "last_t": worker_t, "count": 1,
            }
            self.timeline.append(e)
            print(f"bench: [{e['t']:7.1f}s] worker_init_wait (coalescing "
                  f"further heartbeats)", file=sys.stderr)
            return
        e["t"] = round(time.time() - self.t0, 1)
        e["last_t"] = worker_t
        e["count"] += 1

    def _read_loop(self):
        for line in self.proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            self._last_msg = time.time()
            if msg.get("ev") == "ready":
                self._ready_seen = True
            self._q.put(msg)
        self._q.put({"ev": "eof"})

    def _declare_wedged(self, cause: str, **kw) -> None:
        """Fail fast with a named cause: kill the worker (EOFs the pipe, so
        any parked consumer wakes) and record the verdict exactly once."""
        with self._wedge_mu:
            if self._wedged is not None or self._ready_seen:
                return
            self._wedged = cause
        self._mark("worker_wedged", cause=cause, stall_s=self._stall_s, **kw)
        self.kill()

    def _monitor_loop(self):
        """Spawn-time wedge watchdog: fires even while the parent is busy
        elsewhere (the r05 stall burned the budget precisely because
        detection only ran inside wait_ready's drain loop)."""
        while True:
            time.sleep(5.0)
            if self._ready_seen or self._wedged is not None:
                return
            if self.proc.poll() is not None:
                return  # died: wait_ready's eof handling owns this verdict
            now = time.time()
            # a live init heartbeats every few seconds, so prolonged SILENCE
            # (backend init holding the GIL) earns its verdict well before
            # the uptime budget — with the same threshold the uptime check
            # would always fire first and this cause could never be named
            if now - self._last_msg >= min(self._stall_s, 60.0):
                self._declare_wedged(
                    "heartbeat_silent",
                    silent_s=round(now - self._last_msg, 1))
                return
            if now - self._spawned_at >= self._stall_s:
                self._declare_wedged(
                    "backend_init_stall",
                    worker_t=round(now - self._spawned_at, 1))
                return

    def wait_ready(self, budget_s: float) -> str:
        """'ready' | 'died' (respawnable: init failed fast or slow) |
        'timeout' (budget gone or worker wedged — never respawned: the
        monitor's cause says the backend hangs rather than fails)."""
        deadline = time.time() + budget_s
        while True:
            if self._wedged is not None:
                return "timeout"
            remaining = deadline - time.time()
            if remaining <= 0:
                self._mark("init_budget_exhausted", budget_s=budget_s)
                return "timeout"
            try:
                msg = self._q.get(timeout=min(remaining, 30.0))
            except queue.Empty:
                continue
            ev = msg.get("ev")
            if ev == "init_wait":
                self._mark_init_wait(msg.get("t"))
                if float(msg.get("t") or 0.0) >= self._stall_s:
                    # backstop for a monitor thread that could not run
                    self._declare_wedged("backend_init_stall",
                                         worker_t=msg.get("t"))
                    return "timeout"
            elif ev == "ready":
                self.platform = msg.get("platform")
                self._mark("ready", platform=self.platform, worker_t=msg.get("t"))
                return "ready"
            elif ev == "eof":
                if self._wedged is not None:
                    return "timeout"  # our own kill, not a crash: no respawn
                self._mark("worker_died_at_init", rc=self.proc.poll())
                return "died"

    def call(self, op: str, timeout: float | None = None, **kw) -> dict:
        if timeout is None:
            timeout = float(os.environ.get("BENCH_OP_TIMEOUT", "1800"))
        self._seq += 1
        req = {"op": op, "id": self._seq, **kw}
        try:
            self.proc.stdin.write(json.dumps(req) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise WorkerDied(f"worker stdin closed: {e}") from e
        deadline = time.time() + timeout
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                self.kill()
                raise WorkerDied(f"op {op!r} timed out after {timeout:.0f}s")
            try:
                msg = self._q.get(timeout=min(remaining, 30.0))
            except queue.Empty:
                continue
            if msg.get("ev") == "eof":
                raise WorkerDied(f"worker exited during op {op!r} (rc={self.proc.poll()})")
            if msg.get("ev") == "init_wait":
                continue
            if msg.get("id") != self._seq:
                continue
            if not msg.get("ok"):
                raise WorkerDied(f"op {op!r} failed in worker: {msg.get('err')}\n{msg.get('tb', '')}")
            return msg

    def kill(self):
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except OSError:
            try:
                self.proc.kill()
            except OSError:
                pass
        if not getattr(self, "_kill_marked", False):
            self._kill_marked = True
            self._mark("worker_killed")


class LocalDevice:
    """In-process fallback: the same ops on the CPU backend.  Keeps the two
    code paths identical so a fallback run still measures JAX-vs-pipeline —
    just labeled cpu_fallback, never attested under the TPU metric name."""

    platform = "cpu_fallback"

    def __init__(self):
        self.state: dict = {}

    def call(self, op: str, timeout: float | None = None, **kw) -> dict:
        out = _OPS[op]({"op": op, **kw}, self.state)
        out["ok"] = True
        return out


# ---------------------------------------------------------------------------
# Parent driver
# ---------------------------------------------------------------------------


def main() -> None:
    timeline: list = [{"t": 0.0, "ev": "start"}]
    n = int(os.environ.get("BENCH_ROWS", "100000000"))
    n_cold = min(n, int(os.environ.get("BENCH_COLD_ROWS", "1000000")))
    block_rows = int(os.environ.get("BENCH_BLOCK_ROWS", str(1 << 21)))
    n_mvcc = int(os.environ.get("BENCH_MVCC_ROWS", "200000"))
    K = int(os.environ.get("BENCH_BATCH", "16"))
    budget_s = float(os.environ.get("BENCH_INIT_BUDGET", "900"))
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"

    worker = None if force_cpu else DeviceWorker(timeline)

    # ---- CPU side, fully overlapped with worker backend init -------------
    _force_cpu()
    t0 = time.time()
    fixture_selfcheck()
    timeline.append({"t": round(time.time() - t0, 1), "ev": "selfcheck_ok"})
    t_build = time.perf_counter()
    cache = build_cache(n, block_rows)
    build_s = time.perf_counter() - t_build
    timeline.append({"t": round(time.time() - t0, 1), "ev": "cpu_cache_built", "s": round(build_s, 1)})

    cpu = {}
    cpu_warm_ts: dict = {}
    for name in ("q6", "q1"):
        ts = []
        for _ in range(3):
            resp, dt = run_cpu(_DAGS[name](), cache=cache)
            ts.append(dt)
        cpu_warm_ts[name] = ts
        cpu[f"{name}_warm"] = resp.encode()
    kvs_cold = build_kvs(n_cold, seed=1)
    for name in ("q6", "q1"):
        resp, dt = run_cpu(_DAGS[name](), kvs=kvs_cold)
        cpu[f"{name}_cold"] = (resp.encode(), dt)
    # K-query serving batch on the CPU pipeline (1 worker per core)
    from concurrent.futures import ThreadPoolExecutor

    cpu_workers = min(K, os.cpu_count() or 1)
    batch_dags = [name for name in ("q6", "q1") for _ in range(K // 2)]
    cpu_batch_ts: list = []
    with ThreadPoolExecutor(max_workers=cpu_workers) as pool:
        for _ in range(3):  # same trial count as the device side: median vs median
            bt0 = time.perf_counter()
            cpu_batch_resps = list(
                pool.map(lambda name: run_cpu(_DAGS[name](), cache=cache)[0].encode(), batch_dags)
            )
            cpu_batch_ts.append(time.perf_counter() - bt0)
    timeline.append({"t": round(time.time() - t0, 1), "ev": "cpu_trials_done"})
    # CPU checks for the engine-backed validations
    kvs_mvcc = build_kvs(n_mvcc, seed=3)
    mvcc_cpu = run_cpu(q6_dag(), kvs=kvs_mvcc)[0].encode()
    del kvs_mvcc
    timeline.append({"t": round(time.time() - t0, 1), "ev": "cpu_mvcc_oracle_done"})

    # ---- device side -----------------------------------------------------
    # the tunnel backend is known to either hang for many minutes or die
    # with UNAVAILABLE after a long stall; a dead worker is respawned (JAX
    # caches the init failure per-process) until the budget is spent
    backend = "cpu_fallback"
    # backend-probe attestation (ROADMAP bench gap): the probe — the
    # worker subprocess's backend init, timeout-guarded by the wedge
    # monitor — earns a NAMED verdict (ok / timeout / error) recorded in
    # the bench JSON and the observatory counter, so a cpu_fallback run
    # caused by a wedged probe is distinguishable from a cpu-only host
    probe = {"verdict": "skipped", "cause": "forced_cpu"}
    if worker is not None:
        t_probe = worker.t0
        deadline = worker.t0 + budget_s
        while True:
            outcome = worker.wait_ready(max(deadline - time.time(), 60.0))
            if outcome == "ready":
                backend = worker.platform or "unknown"
                probe = {"verdict": "ok", "platform": backend,
                         "elapsed_s": round(time.time() - t_probe, 1)}
                break
            wedge_cause = worker._wedged
            rc = worker.proc.poll()
            worker.kill()
            if outcome == "died" and time.time() < deadline:
                worker = DeviceWorker(timeline)
                worker.t0 = deadline - budget_s  # keep the global deadline
                continue
            if outcome == "timeout":
                probe = {"verdict": "timeout",
                         "cause": wedge_cause or "init_budget_exhausted",
                         "elapsed_s": round(time.time() - t_probe, 1)}
            else:
                probe = {"verdict": "error", "cause": "worker_died",
                         "rc": rc,
                         "elapsed_s": round(time.time() - t_probe, 1)}
            worker = None
            break
    timeline.append({"t": round(time.time() - t0, 1),
                     "ev": "backend_probe", **probe})
    try:
        from tikv_tpu.copr.observatory import count_backend_probe

        count_backend_probe(probe["verdict"])
    except Exception:  # noqa: BLE001 — attestation must not fail the bench
        pass
    dev = worker if worker is not None else LocalDevice()
    if isinstance(dev, LocalDevice):
        print("bench: device backend unrecoverable — running on CPU", file=sys.stderr)
        # share the parent's fixtures instead of rebuilding them in-process
        dev.state["cache"] = cache
        dev.state["cache_key"] = (n, block_rows, 0)
        dev.state["cold_kvs"] = kvs_cold
        dev.state["cold_rows"] = n_cold
    elif _mem_available_gb() > n * 7 * 8 * 2.5 / 2**30 + 8:
        # enough RAM for the worker's copy AND ours: keep the parent cache so
        # CPU and device warm trials can interleave (machine drift hits both)
        del kvs_cold
    else:
        # the worker builds its own copies; drop the parent's (~GBs at 100M
        # rows) so the two processes don't both hold the full fixture
        del cache, kvs_cold
        cache = None

    results: dict = {}

    def _mark(ev, **kw):
        entry = {"t": round(time.time() - t0, 1), "ev": ev, **kw}
        timeline.append(entry)
        print(f"bench: [{entry['t']:7.1f}s] {ev} {kw if kw else ''}", file=sys.stderr)

    r = dev.call("build", rows=n, block_rows=block_rows)
    if isinstance(dev, LocalDevice):
        # the CPU fallback shares the parent's pre-built fixture, so the
        # op's own build_s is ~0 — report the REAL build cost (measured at
        # cpu_cache_built) instead of attesting a free cache build
        _mark("device_cache_built", s=round(build_s, 2), shared_parent_cache=True)
    else:
        _mark("device_cache_built", s=r.get("build_s"))
    interleave = cache is not None
    for name in ("q6", "q1"):
        # median-of-N with CPU trials interleaved between device trials when
        # the parent kept its cache: single-core baseline variance (commit
        # 91511b1) then hits both sides, and the headline is a median, not a
        # best-of-N racing that variance
        want = cpu[f"{name}_warm"]
        dev_ts: list = []
        for t in range(3):
            r = dev.call("warm", q=name, trials=1)
            if bytes.fromhex(r["resp"]) != want:
                _fail(f"{name}_WARM_MISMATCH")
            dev_ts += r["ts"]
            if interleave:
                _, dt = run_cpu(_DAGS[name](), cache=cache)
                cpu_warm_ts[name].append(dt)
        cpu_ts = cpu_warm_ts[name]
        cpu_t = float(np.median(cpu_ts))
        dev_t = float(np.median(dev_ts))
        results[f"{name}_cpu_warm_rows_per_s"] = n / cpu_t
        results[f"{name}_tpu_warm_rows_per_s"] = n / dev_t
        results[f"{name}_warm_speedup"] = cpu_t / dev_t
        results[f"{name}_cpu_warm_ts"] = [round(x, 4) for x in cpu_ts]
        results[f"{name}_tpu_warm_ts"] = [round(x, 4) for x in dev_ts]
        spread = max(max(cpu_ts) / min(cpu_ts), max(dev_ts) / min(dev_ts))
        results[f"{name}_warm_spread"] = round(spread, 2)
        if spread > 2.0:
            results[f"{name}_warm_spread_warning"] = (
                f"trial spread {spread:.1f}x > 2x — single-core machine drift; "
                "median shown, individual trials in *_warm_ts"
            )
        _mark(f"warm_{name}", speedup=round(cpu_t / dev_t, 2), spread=round(spread, 2))
    for name in ("q6", "q1"):
        # both queries get a one-block compile warmup so cold numbers
        # measure scan+decode+execute, not XLA compilation, symmetrically
        r = dev.call("cold", q=name, rows=n_cold, warmup=True)
        want, cpu_t = cpu[f"{name}_cold"]
        if bytes.fromhex(r["resp"]) != want:
            _fail(f"{name}_COLD_MISMATCH")
        results[f"{name}_cpu_cold_rows_per_s"] = n_cold / cpu_t
        results[f"{name}_tpu_cold_rows_per_s"] = n_cold / r["t"]
        results[f"{name}_cold_speedup"] = cpu_t / r["t"]
        _mark(f"cold_{name}", speedup=round(cpu_t / r["t"], 2))
    r = dev.call("batch", k=K, trials=3)
    for got_hex, want in zip(r["resps"], cpu_batch_resps):
        if bytes.fromhex(got_hex) != want:
            _fail("BATCH_MISMATCH")
    tpu_batch_t = float(np.median(r["ts"]))
    cpu_batch_t = float(np.median(cpu_batch_ts))
    total_rows = n * r["queries"]
    batch_speedup = cpu_batch_t / tpu_batch_t
    results["batch_queries"] = r["queries"]
    results["batch_cpu_workers"] = cpu_workers
    results["batch_cpu_rows_per_s"] = total_rows / cpu_batch_t
    results["batch_tpu_rows_per_s"] = total_rows / tpu_batch_t
    results["batch_speedup"] = batch_speedup
    results["batch_cpu_ts"] = [round(x, 3) for x in cpu_batch_ts]
    results["batch_tpu_ts"] = [round(x, 3) for x in r["ts"]]
    bspread = max(
        max(cpu_batch_ts) / min(cpu_batch_ts), max(r["ts"]) / min(r["ts"])
    )
    results["batch_spread"] = round(bspread, 2)
    if bspread > 2.0:
        results["batch_spread_warning"] = (
            f"trial spread {bspread:.1f}x > 2x — median shown, trials recorded"
        )
    _mark("batch", speedup=round(batch_speedup, 2), spread=round(bspread, 2))

    # BASELINE configs 1-2 (scan passthrough + 3-predicate selection):
    # AFTER the headline ops — an infra failure here must not strand a dead
    # worker for batch/cold, and a tolerated WorkerDied only loses these
    # auxiliary rows.  Data mismatches stay fatal (_fail), like mvcc/topn.
    if interleave:
        for kind in ("scan", "selection"):
            try:
                r = dev.call("filter", kind=kind, trials=3)
                cpu_ts = []
                for _ in range(3):
                    cresp, dt = run_cpu(_filter_dag(kind), cache=cache)
                    cpu_ts.append(dt)
                if bytes.fromhex(r["resp"]) != cresp.encode():
                    _fail(f"{kind.upper()}_MISMATCH")
                cpu_t = float(np.median(cpu_ts))
                dev_t = float(np.median(r["ts"]))
                results[f"{kind}_cpu_s"] = round(cpu_t, 4)
                results[f"{kind}_tpu_s"] = round(dev_t, 4)
                results[f"{kind}_speedup"] = round(cpu_t / dev_t, 2)
                _mark(kind, speedup=round(cpu_t / dev_t, 2))
            except (WorkerDied, AssertionError) as e:
                results[f"{kind}_error"] = str(e)[:200]
                _mark(f"{kind}_error", err=str(e)[:120])
    else:
        # parent cache was dropped (low-RAM branch): record the skip so the
        # attested JSON distinguishes 'skipped' from 'not implemented'
        _mark("filter_skipped_no_parent_cache")
        results["filter_skipped"] = "no parent cache for the CPU oracle"

    if os.environ.get("BENCH_REGION_CACHE", "1") != "0":
        # region column cache events (ISSUE 1): cached scan/selection vs the
        # per-request cold path over a real MVCC region, with a delta apply
        # mid-sequence.  Auxiliary like mvcc/topn — infra failures don't zero
        # the headline — but a byte mismatch is fatal.
        try:
            r = dev.call(
                "region_cache",
                rows=int(os.environ.get("BENCH_REGION_CACHE_ROWS", "200000")),
            )
            if not r["match"]:
                _fail("REGION_CACHE_MISMATCH")
            for kind in ("scan", "selection"):
                cold_t = float(np.median(r[kind]["cold_ts"]))
                warm_t = float(np.median(r[kind]["warm_ts"]))
                results[f"{kind}_cached_cold_s"] = round(cold_t, 4)
                results[f"{kind}_cached_s"] = round(warm_t, 4)
                results[f"{kind}_cached_speedup"] = round(cold_t / warm_t, 2)
                _mark(f"{kind}_cached", speedup=round(cold_t / warm_t, 2),
                      outcome=r[kind]["outcome"])
            results["region_cache_delta"] = r.get("delta")
            results["region_cache_stats"] = r.get("stats")
        except WorkerDied as e:
            results["region_cache_error"] = str(e)[:200]
            _mark("region_cache_error", err=str(e)[:120])

    if os.environ.get("BENCH_XREGION", "1") != "0":
        # cross-region continuous batching (ISSUE 2): the read scheduler's
        # handle_batch vs per-request device serving on an 8-region mixed
        # workload with 3 clients per (region, query).  Auxiliary for infra
        # failures; a byte mismatch is fatal.
        try:
            r = dev.call(
                "xregion",
                regions=int(os.environ.get("BENCH_XREGION_REGIONS", "8")),
                rows=int(os.environ.get("BENCH_XREGION_ROWS", "64000")),
                clients=int(os.environ.get("BENCH_XREGION_CLIENTS", "3")),
            )
            if not r["match"]:
                _fail("XREGION_MISMATCH")
            serial_t = float(np.median(r["serial_ts"]))
            batch_t = float(np.median(r["batch_ts"]))
            results["xregion_requests"] = r["requests"]
            results["xregion_regions"] = r["regions"]
            results["xregion_clients"] = r["clients"]
            results["xregion_serial_rows_per_s"] = r["total_rows"] / serial_t
            results["xregion_batch_rows_per_s"] = r["total_rows"] / batch_t
            results["xregion_speedup"] = serial_t / batch_t
            results["xregion_from_device"] = r["from_device"]
            results["xregion_serial_ts"] = r["serial_ts"]
            results["xregion_batch_ts"] = r["batch_ts"]
            _mark("xregion_batch", speedup=round(serial_t / batch_t, 2),
                  requests=r["requests"], from_device=r["from_device"])
        except WorkerDied as e:
            results["xregion_error"] = str(e)[:200]
            _mark("xregion_error", err=str(e)[:120])

    if os.environ.get("BENCH_MIXED_RW", "1") != "0":
        # group-commit write path + warm serving under writes (ISSUE 4):
        # runs in-parent on the CPU backend — it measures raft-proposal
        # amortization and write-through cache behavior, not device compute.
        # Auxiliary for infra failures; a byte mismatch is fatal.
        try:
            r = _op_mixed_rw({
                "rows": int(os.environ.get("BENCH_MIXED_RW_ROWS", "2048")),
                "writes": int(os.environ.get("BENCH_MIXED_RW_WRITES", "64")),
            }, {})
            if not r["match"]:
                _fail("MIXED_RW_MISMATCH")
            results["mixed_rw_group_speedup"] = r["group_speedup"]
            results["mixed_rw_commits_per_s_percmd"] = r["commits_per_s_percmd"]
            results["mixed_rw_commits_per_s_grouped"] = r["commits_per_s_grouped"]
            results["mixed_rw_warm_hit_rate"] = r["warm_hit_rate"]
            results["mixed_rw_read_rows_per_s"] = r["read_rows_per_s"]
            results["mixed_rw_scan_deltas"] = r["scan_deltas"]
            results["mixed_rw_wt_deltas"] = r["wt_deltas"]
            _mark("mixed_rw", group_speedup=round(r["group_speedup"], 2),
                  warm_hit_rate=round(r["warm_hit_rate"], 3),
                  scan_deltas=r["scan_deltas"])
        except Exception as e:  # noqa: BLE001
            results["mixed_rw_error"] = str(e)[:200]
            _mark("mixed_rw_error", err=str(e)[:120])

    if os.environ.get("BENCH_COMPRESSED", "1") != "0":
        # compressed device-resident columns (ISSUE 10): byte-identity of
        # encoded-resident serving + the warm-capacity multiplier at one
        # fixed byte budget.  In-parent on CPU — it measures residency
        # accounting and encode/decode correctness, not device compute.
        try:
            r = _op_scan_compressed({
                "rows": int(os.environ.get("BENCH_COMPRESSED_ROWS", "20000")),
            }, {})
            if not r["match"]:
                _fail("COMPRESSED_MISMATCH")
            results["compressed_ratio"] = r["compression_ratio"]
            results["compressed_warm_capacity_ratio"] = r["warm_capacity_ratio"]
            results["compressed_regions_resident"] = [
                r["regions_resident_decoded"], r["regions_resident_encoded"]]
            results["compressed_encodings"] = r["encodings"]
            _mark("scan_compressed",
                  ratio=round(r["compression_ratio"], 2),
                  capacity=round(r["warm_capacity_ratio"], 2),
                  encodings=r["encodings"])
        except Exception as e:  # noqa: BLE001
            results["compressed_error"] = str(e)[:200]
            _mark("compressed_error", err=str(e)[:120])

    if os.environ.get("BENCH_PRUNED", "1") != "0":
        # zone-map pruned execution (ISSUE 16): selective and Limit-bearing
        # scans with block pruning on vs kill-switched off, byte-checked
        # against the CPU oracle.  In-parent on CPU — it measures how many
        # block dispatches the zones save, not device compute.
        try:
            r = _op_scan_pruned({
                "rows": int(os.environ.get("BENCH_PRUNED_ROWS", "60000")),
            }, {})
            if not r["match"]:
                _fail("PRUNED_MISMATCH")
            for name in ("selective", "limit"):
                p = float(np.median(r[name]["pruned_ts"]))
                u = float(np.median(r[name]["unpruned_ts"]))
                results[f"scan_pruned_{name}_speedup"] = round(u / p, 2)
            results["scan_pruned_blocks"] = [
                r["blocks_pruned"], r["blocks_examined"]]
            _mark("scan_pruned",
                  selective=results["scan_pruned_selective_speedup"],
                  limit=results["scan_pruned_limit_speedup"],
                  blocks=results["scan_pruned_blocks"])
        except Exception as e:  # noqa: BLE001
            results["scan_pruned_error"] = str(e)[:200]
            _mark("scan_pruned_error", err=str(e)[:120])

    if os.environ.get("BENCH_JOIN", "1") != "0":
        # device-resident join (ISSUE 18): rank/hash device joins over two
        # warm region images vs the CPU join pipeline, byte-checked per
        # trial.  In-parent on CPU — it measures the join serving path,
        # not device compute.
        try:
            r = _op_join({
                "rows": int(os.environ.get("BENCH_JOIN_ROWS", "40000")),
            }, {})
            if not r["match"]:
                _fail("JOIN_MISMATCH")
            cpu = float(np.median(r["cpu_ts"]))
            for p in ("rank", "hash"):
                results[f"join_{p}_speedup"] = round(
                    cpu / float(np.median(r[f"{p}_ts"])), 2)
            results["join_served"] = r["served"]
            _mark("join", rank=results["join_rank_speedup"],
                  hash=results["join_hash_speedup"],
                  probe_rows=r["probe_rows"], build_rows=r["build_rows"])
        except Exception as e:  # noqa: BLE001
            results["join_error"] = str(e)[:200]
            _mark("join_error", err=str(e)[:120])

    if os.environ.get("BENCH_OVERLOAD", "1") != "0":
        # overload control plane (ISSUE 15): well-behaved-tenant throughput
        # retention while a hot tenant floods past its quota.  In-parent on
        # CPU — it measures admission policy, not device compute.
        try:
            r = _op_overload({
                "regions": 4,
                "rows": int(os.environ.get("BENCH_OVERLOAD_ROWS", "16000")),
                "clients": 2,
            }, {})
            if r["victim_failures"]:
                _fail("OVERLOAD_VICTIM_FAILURES")
            results["overload_retention"] = r["retention"]
            results["overload_hot_shed"] = r["hot_shed"]
            results["overload_hot_submitted"] = r["hot_submitted"]
            _mark("overload", retention=round(r["retention"], 3),
                  hot_shed=r["hot_shed"],
                  victim_failures=r["victim_failures"])
        except Exception as e:  # noqa: BLE001
            results["overload_error"] = str(e)[:200]
            _mark("overload_error", err=str(e)[:120])

    if os.environ.get("BENCH_COST_ROUTER", "1") != "0":
        # cost-based path routing (ISSUE 17): mixed plan shapes where the
        # static ladder picks a measurably-worse path for one of them; the
        # router must win >= 1.2x aggregate with byte identity, and the
        # geometry tuner must walk the deliberately bad block_rows down.
        # In-parent on CPU — it measures dispatch policy, not device compute.
        try:
            r = _op_cost_router({
                "regions": 2,
                "rows": int(os.environ.get("BENCH_COST_ROUTER_ROWS", "2048")),
            }, {})
            if not r["match"]:
                _fail("COST_ROUTER_MISMATCH")
            results["cost_router_speedup"] = r["speedup"]
            results["cost_router_route_dist"] = r["route_dist"]
            results["cost_router_tuner_final_block_rows"] = \
                r["tuner_final_block_rows"]
            results["cost_router_tuner_counts"] = r["tuner_counts"]
            _mark("cost_router", speedup=r["speedup"],
                  rows_per_s_on=r["rows_per_s_on"],
                  rows_per_s_off=r["rows_per_s_off"],
                  tuner_final_block_rows=r["tuner_final_block_rows"],
                  tuner_counts=r["tuner_counts"])
        except Exception as e:  # noqa: BLE001
            results["cost_router_error"] = str(e)[:200]
            _mark("cost_router_error", err=str(e)[:120])

    if os.environ.get("BENCH_MVCC", "1") != "0":
        try:
            r = dev.call("mvcc", rows=n_mvcc)
            if bytes.fromhex(r["resp"]) != mvcc_cpu:
                _fail("MVCC_MISMATCH")
            results["mvcc_q6_rows_per_s"] = n_mvcc / r["t"]
            _mark("mvcc_ok")
            r = dev.call("topn", rows=n_mvcc)
            assert r["from_device"] and r["fallbacks"] == 0, r.get("err")
            assert r["resp"] == r["warm_resp"], "TopN warm/steady mismatch"
            # CPU endpoint oracle
            topn_cpu = _topn_cpu_oracle(n_mvcc)
            if bytes.fromhex(r["resp"]) != topn_cpu:
                _fail("TOPN_MISMATCH")
            results["endpoint_topn_device_rows_per_s"] = n_mvcc / r["t"]
            _mark("topn_ok")
        except (WorkerDied, AssertionError) as e:
            # auxiliary validations must not zero out the headline metric
            results["aux_error"] = str(e)[:300]
            _mark("aux_error", err=str(e)[:120])

    if worker is not None:
        # free the (single) device before the cluster phase: the device
        # store process must be able to initialize the same chip
        try:
            worker.call("quit", timeout=10)
        except WorkerDied:
            pass
        worker = None

    if os.environ.get("BENCH_CLUSTER", "1") != "0":
        # BASELINE config #5: 3 store processes + PD over TCP serving
        # YCSB-E scans and Q1 pushdown (bench_cluster.py) — store 1 runs with
        # --enable-device on whatever backend this run captured, and the Q1
        # device phase routes every region there via replica reads; auxiliary
        # — a cluster failure must not zero the headline device metric
        try:
            import bench_cluster

            _mark("cluster_start")
            c = bench_cluster.run(
                rows=int(os.environ.get("BENCH_CLUSTER_ROWS", "60000")),
                scan_seconds=float(os.environ.get("BENCH_CLUSTER_SCAN_SECONDS", "8")),
                device_platform=backend,
            )
            for k in ("load_rows_per_s", "ycsb_e_scans_per_s", "ycsb_e_rows_per_s",
                      "q1_pushdown_rows_per_s", "q1_device_rows_per_s",
                      "q1_device_cold_rows_per_s", "q1_device_round_ms",
                      "ycsb_e_p50_ms", "ycsb_e_p99_ms",
                      "q1_device_from_device", "q1_device_platform",
                      "q1_wire_rows_per_s", "q1_wire_requests",
                      "q1_owner_routed_rows_per_s", "q1_owner_routed_requests",
                      "wire_stages", "device_owners",
                      "regions", "leader_stores"):
                results[f"cluster_{k}"] = c.get(k)
            _mark("cluster_ok", q1=c.get("q1_pushdown_rows_per_s"),
                  q1_wire=c.get("q1_wire_rows_per_s"),
                  q1_owner=c.get("q1_owner_routed_rows_per_s"),
                  q1_dev=c.get("q1_device_rows_per_s"))
        except Exception as e:  # noqa: BLE001
            results["cluster_error"] = str(e)[:300]
            _mark("cluster_error", err=str(e)[:120])

    geo = float(
        np.exp(np.mean(np.log([results["q6_warm_speedup"], results["q1_warm_speedup"]])))
    )
    detail = {
        "rows": n,
        "cold_rows": n_cold,
        "block_rows": block_rows,
        "backend": backend,
        "backend_probe": probe,
        "build_s": round(build_s, 2),
        "warm_geo_speedup": round(geo, 3),
        **{k: (round(v, 1) if isinstance(v, float) else v) for k, v in results.items()},
        "probe_timeline": timeline,
    }
    print(json.dumps(detail), file=sys.stderr)
    metric = "copr_q1q6_batched_tpu_rows_per_sec"
    if backend.startswith("cpu"):
        # no device backend (tunnel down or CPU-only host): CPU-vs-CPU
        # number, never attested under the TPU metric name
        metric += "_cpu_fallback"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(results["batch_tpu_rows_per_s"], 1),
                "unit": "rows/sec",
                "vs_baseline": round(results["batch_speedup"], 3),
            }
        )
    )


def _topn_cpu_oracle(n: int) -> bytes:
    """CPU endpoint result for the TopN validation (same fixture as _op_topn)."""
    ep, _dag, req_of = _topn_endpoint(n, enable_device=False)
    return ep.handle_request(req_of()).data


def _fail(tag: str) -> None:
    print(json.dumps({"metric": tag, "value": 0, "unit": "rows/sec", "vs_baseline": 0}))
    sys.exit(1)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        _worker_main()
        sys.exit(0)
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the driver needs a parsed JSON line
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": f"bench_error_{type(e).__name__}",
                    "value": 0.0,
                    "unit": "rows/sec",
                    "vs_baseline": 0.0,
                }
            )
        )
        sys.exit(1)
