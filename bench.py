#!/usr/bin/env python
"""Driver benchmark: TPC-H Q1/Q6-shaped coprocessor pushdown.

Measures the JAX/TPU DAG evaluator against the CPU read-pool pipeline
(BatchExecutorsRunner) on a lineitem-shaped table, asserting byte-identical
SelectResponses, and prints ONE JSON line:

    {"metric": ..., "value": <tpu rows/sec>, "unit": "rows/sec", "vs_baseline": <speedup>}

vs_baseline = geometric mean over {Q1, Q6} of (TPU rows/s) / (CPU rows/s).
Row count via BENCH_ROWS (default 2,000,000); BENCH_MVCC=1 additionally
validates the MVCC leaf on a 200k-row engine-backed region.
"""

import json
import os
import subprocess
import sys
import time

_PROBE_DONE = "BENCH_BACKEND_RESOLVED"


def _resolve_backend() -> str:
    """Probe the configured JAX backend out-of-process with retry/backoff.

    BENCH_r01/BENCH_r02 both died with rc=1 at axon backend init
    (``Unable to initialize backend 'axon': UNAVAILABLE``) before any bench
    work ran.  Two properties force the shape of this guard:

    * JAX caches the first backend-init failure for the life of the process,
      so retrying in-process is useless — the probe runs in a subprocess and
      the parent only imports device modules after a probe succeeded.
    * The tunnel backend can also HANG at init (observed: minutes with no
      error), so each probe attempt carries a hard timeout.

    On unrecoverable failure we force the CPU platform and continue, so the
    driver still captures a parsed one-line JSON artifact (the metric name is
    suffixed ``_cpu_fallback``) instead of a raw traceback.  The forcing MUST
    go through ``jax.config.update('jax_platforms', 'cpu')`` — this image's
    sitecustomize re-exports JAX_PLATFORMS=axon at every interpreter start,
    so a shell-level env override is silently clobbered (observed: a
    JAX_PLATFORMS=cpu run still initializing 'axon' and hanging).
    """
    resolved = os.environ.get(_PROBE_DONE)
    if resolved:
        if resolved.startswith("cpu"):
            _force_cpu()
        return resolved
    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
    backoff = 10.0
    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((256, 256), jnp.float32);"
        "(x @ x).block_until_ready();"
        "print('PLATFORM=' + jax.devices()[0].platform)"
    )
    import signal

    for i in range(attempts):
        t0 = time.time()
        err = ""
        # start_new_session + killpg: the tunnel plugin may fork helpers that
        # inherit the pipes; killing only the direct child would leave
        # communicate() blocked on the helper's copy of the write end.
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True,
        )
        try:
            out, errtxt = proc.communicate(timeout=timeout)
            for line in out.splitlines():
                if line.startswith("PLATFORM="):
                    plat = line.split("=", 1)[1]
                    os.environ[_PROBE_DONE] = plat
                    print(f"bench: backend '{plat}' up after probe {i + 1} "
                          f"({time.time() - t0:.1f}s)", file=sys.stderr)
                    return plat
            tail = (errtxt or "").strip().splitlines()
            err = tail[-1][:300] if tail else f"rc={proc.returncode}, no output"
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            proc.communicate()
            err = f"probe hung past {timeout:.0f}s (killed group)"
        print(f"bench: backend probe {i + 1}/{attempts} failed: {err}",
              file=sys.stderr)
        if i + 1 < attempts:
            time.sleep(backoff)
            backoff = min(backoff * 2, 90.0)
    print("bench: device backend unrecoverable — running on CPU", file=sys.stderr)
    os.environ[_PROBE_DONE] = "cpu_fallback"
    _force_cpu()
    return "cpu_fallback"


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


if __name__ == "__main__":
    _BACKEND = _resolve_backend()
else:
    _BACKEND = os.environ.get(_PROBE_DONE, "")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import (
    Aggregation,
    BatchExecutorsRunner,
    DagRequest,
    Selection,
    TableScan,
)
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.cache import ColumnBlockCache
from tikv_tpu.copr.executors import CachedBlocksExecutor, FixtureScanSource
from tikv_tpu.copr.jax_eval import JaxDagEvaluator, run_batch_cached, supports
from tikv_tpu.copr.rpn import call, col, const_decimal, const_int
from tikv_tpu.copr.table import encode_row, record_key

TABLE_ID = 101

LINEITEM = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),  # l_quantity
    ColumnInfo(3, FieldType.decimal_type(2)),  # l_extendedprice
    ColumnInfo(4, FieldType.decimal_type(2)),  # l_discount
    ColumnInfo(5, FieldType.int64()),  # l_shipdate (days)
    ColumnInfo(6, FieldType.varchar()),  # l_returnflag
    ColumnInfo(7, FieldType.varchar()),  # l_linestatus
]


def build_kvs(n: int, seed: int = 0):
    """Vectorized fixture builder: rows share one fixed layout, so the whole
    table is a byte matrix filled by batch codecs."""
    from tikv_tpu.copr.table import RowBatchDecoder
    from tikv_tpu.util.codec import encode_i64_batch

    rng = np.random.default_rng(seed)
    qty = rng.integers(1, 51, n)
    price = rng.integers(90000, 10500000, n)  # 900.00 .. 105000.00
    disc = rng.integers(0, 11, n)  # 0.00 .. 0.10
    ship = rng.integers(8400, 10600, n)
    rf = rng.integers(0, 3, n)
    ls = rng.integers(0, 2, n)
    flags = np.frombuffer(b"ANR", dtype=np.uint8)
    stats = np.frombuffer(b"FO", dtype=np.uint8)
    non_handle = LINEITEM[1:]
    row0 = encode_row(non_handle, [1, 1, 1, 1, b"A", b"F"])
    layout = RowBatchDecoder(LINEITEM)._parse_layout(row0)
    mat = np.tile(np.frombuffer(row0, dtype=np.uint8), (n, 1))
    for col_id, arr in ((2, qty), (3, price), (4, disc), (5, ship)):
        _kind, off = layout["cols"][col_id]
        mat[:, off : off + 8] = encode_i64_batch(arr)
    _k, off_rf = layout["cols"][6]
    _k, off_ls = layout["cols"][7]
    mat[:, off_rf] = flags[rf]
    mat[:, off_ls] = stats[ls]
    values = [r.tobytes() for r in mat]
    kmat = np.tile(np.frombuffer(record_key(TABLE_ID, 0), dtype=np.uint8), (n, 1))
    kmat[:, 11:19] = encode_i64_batch(np.arange(n, dtype=np.int64))
    keys = [r.tobytes() for r in kmat]
    return list(zip(keys, values))


def q6_dag() -> DagRequest:
    # sum(l_extendedprice * l_discount) where shipdate in [y, y+365) and
    # discount between 0.02 and 0.04 and quantity < 24
    conds = [
        call("ge", col(4), const_int(9000)),
        call("lt", col(4), const_int(9365)),
        call("ge", col(3), const_decimal(2, 2)),
        call("le", col(3), const_decimal(4, 2)),
        call("lt", col(1), const_int(24)),
    ]
    aggs = [AggDescriptor("sum", call("multiply", col(2), col(3)))]
    return DagRequest(executors=[TableScan(TABLE_ID, LINEITEM), Selection(conds), Aggregation([], aggs)])


def q1_dag() -> DagRequest:
    # group by returnflag, linestatus: sum(qty), sum(price), avg(price),
    # avg(disc), count(*) where shipdate <= cutoff
    conds = [call("le", col(4), const_int(10500))]
    aggs = [
        AggDescriptor("sum", col(1)),
        AggDescriptor("sum", col(2)),
        AggDescriptor("avg", col(2)),
        AggDescriptor("avg", col(3)),
        AggDescriptor("count", None),
    ]
    return DagRequest(
        executors=[
            TableScan(TABLE_ID, LINEITEM),
            Selection(conds),
            Aggregation([col(5), col(6)], aggs),
        ]
    )


def run_cpu(dag, kvs, cache=None):
    t0 = time.perf_counter()
    leaf = CachedBlocksExecutor(cache, LINEITEM) if cache is not None else None
    src = None if cache is not None else FixtureScanSource(kvs)
    resp = BatchExecutorsRunner(dag, src, leaf=leaf).handle_request()
    return resp, time.perf_counter() - t0


def run_tpu(ev, kvs, cache=None):
    t0 = time.perf_counter()
    src = None if (cache is not None and cache.filled) else FixtureScanSource(kvs)
    resp = ev.run(src, cache=cache)
    return resp, time.perf_counter() - t0


def bench_endpoint_topn(n=200_000):
    """Endpoint-driven device TopN over a real MVCC region: proves the device
    top-K merge runs on the actual accelerator behind the full request path
    (handle_request → MvccBatchScanSource → JaxDagEvaluator), with zero CPU
    fallbacks and bytes identical to the CPU pipeline."""
    from tikv_tpu.copr.dag import TopN
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    kvs = build_kvs(n, seed=7)
    eng = BTreeEngine()
    items = []
    for rk, v in kvs:
        items.append((Key.from_raw(rk).append_ts(20).encoded,
                      Write(WriteType.PUT, 10, short_value=v).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    # order by price desc, qty asc, top 100 — raw TopN device merge path.
    # Numeric columns only: the device TopN ships every schema column as
    # payload state and bytes columns are (correctly) gated off-device.
    dag = lambda: DagRequest(executors=[
        TableScan(TABLE_ID, LINEITEM[:5]),
        Selection([call("le", col(4), const_int(10500))]),
        TopN([(col(2), True), (col(1), False)], 100),
    ])
    assert supports(dag()), "TopN plan must be device-eligible"
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    req = lambda: CoprRequest(103, dag(), [record_range(TABLE_ID)], ts := 100)
    r_warm = ep.handle_request(req())  # compile warmup
    t0 = time.perf_counter()
    r_dev = ep.handle_request(req())
    dt = time.perf_counter() - t0
    r_cpu = ep_cpu.handle_request(req())
    assert r_dev.from_device, f"TopN fell off device: {ep.last_device_error}"
    assert ep.device_fallbacks == 0, ep.last_device_error
    assert r_dev.data == r_cpu.data == r_warm.data, "TopN device/CPU mismatch"
    return n / dt


def bench_mvcc_validation(n=200_000):
    """BASELINE config-4 flavor: the same DAG over a real MVCC region."""
    from tikv_tpu.copr.mvcc_batch import MvccBatchScanSource
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    kvs = build_kvs(n, seed=3)
    try:
        from tikv_tpu.native.engine import NativeEngine, native_available

        eng = NativeEngine() if native_available() else BTreeEngine()
    except ImportError:
        eng = BTreeEngine()
    items = []
    for rk, v in kvs:
        k = Key.from_raw(rk)
        items.append((k.append_ts(20).encoded, Write(WriteType.PUT, 10, short_value=v).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    rng = record_range(TABLE_ID)
    dag = q6_dag()
    src = MvccBatchScanSource(eng.snapshot(), ts=100, ranges=[rng])
    t0 = time.perf_counter()
    resp = JaxDagEvaluator(dag).run(src)
    dt = time.perf_counter() - t0
    cpu_resp, _ = run_cpu(q6_dag(), kvs)
    assert resp.encode() == cpu_resp.encode(), "MVCC-leaf response mismatch"
    return n / dt


def main():
    n = int(os.environ.get("BENCH_ROWS", "8000000"))
    n_cold = min(n, int(os.environ.get("BENCH_COLD_ROWS", "1000000")))
    block_rows = int(os.environ.get("BENCH_BLOCK_ROWS", str(1 << 17)))
    t_build = time.perf_counter()
    kvs = build_kvs(n)
    build_s = time.perf_counter() - t_build

    results = {}
    speedups = []
    cache = ColumnBlockCache()
    for name, dag_fn in (("q6", q6_dag), ("q1", q1_dag)):
        dag = dag_fn()
        assert supports(dag), f"{name} must be device-eligible"
        ev = JaxDagEvaluator(dag, block_rows=block_rows)
        # warmup/compile on a small prefix
        run_tpu(ev, kvs[:block_rows])
        # cold: scan + decode + execute, both paths (bounded subset)
        cpu_resp_c, cpu_cold_t = run_cpu(dag_fn(), kvs[:n_cold])
        tpu_resp_c, tpu_cold_t = run_tpu(ev, kvs[:n_cold])
        if tpu_resp_c.encode() != cpu_resp_c.encode():
            print(json.dumps({"metric": f"{name}_COLD_MISMATCH", "value": 0, "unit": "rows/sec", "vs_baseline": 0}))
            sys.exit(1)
        cpu_resp, _ = run_cpu(dag_fn(), kvs)
        # warm: both paths read the same decoded block cache (the serving
        # steady state — TiKV's cop-cache analog); device arrays pinned in
        # HBM.  Like-for-like trials: best-of-3 on BOTH paths.
        run_tpu(ev, kvs, cache=cache)  # fills cache + pins device arrays
        best_cpu_warm = float("inf")
        for _ in range(3):
            cpu_w, cpu_warm_t = run_cpu(dag_fn(), kvs, cache=cache)
            best_cpu_warm = min(best_cpu_warm, cpu_warm_t)
        cpu_warm_t = best_cpu_warm
        best_warm = float("inf")
        for _ in range(3):
            tpu_w, tpu_warm_t = run_tpu(ev, kvs, cache=cache)
            best_warm = min(best_warm, tpu_warm_t)
        if tpu_w.encode() != cpu_w.encode() or tpu_w.encode() != cpu_resp.encode():
            print(json.dumps({"metric": f"{name}_WARM_MISMATCH", "value": 0, "unit": "rows/sec", "vs_baseline": 0}))
            sys.exit(1)
        results[name] = {
            "cpu_cold_rows_per_s": n_cold / cpu_cold_t,
            "tpu_cold_rows_per_s": n_cold / tpu_cold_t,
            "cold_speedup": cpu_cold_t / tpu_cold_t,
            "cpu_warm_rows_per_s": n / cpu_warm_t,
            "tpu_warm_rows_per_s": n / best_warm,
            "warm_speedup": cpu_warm_t / best_warm,
        }
        speedups.append(cpu_warm_t / best_warm)

    # throughput under concurrent load: K queries fused into one device
    # program (the batch_commands / batch_coprocessor serving pattern) vs the
    # CPU pipeline answering the same K queries over the same cache on a
    # thread pool sized to the machine (like-for-like: both sides use their
    # natural concurrency mechanism, and both take best-of-3 trials).
    from concurrent.futures import ThreadPoolExecutor

    K = int(os.environ.get("BENCH_BATCH", "16"))
    cpu_workers = min(K, os.cpu_count() or 1)
    evs = []
    for name, dag_fn in (("q6", q6_dag), ("q1", q1_dag)):
        ev = JaxDagEvaluator(dag_fn(), block_rows=block_rows)
        evs.append((name, dag_fn, ev))
    batch = [(n, d, e) for (n, d, e) in evs for _ in range(K // 2)]
    run_batch_cached([e for _, _, e in batch], cache)  # compile warmup
    tpu_batch_t = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        resps = run_batch_cached([e for _, _, e in batch], cache)
        tpu_batch_t = min(tpu_batch_t, time.perf_counter() - t0)
    cpu_batch_t = float("inf")
    with ThreadPoolExecutor(max_workers=cpu_workers) as pool:
        for _ in range(3):
            t0 = time.perf_counter()
            cpu_resps = list(pool.map(
                lambda args: run_cpu(args[1](), kvs, cache=cache)[0], batch))
            cpu_batch_t = min(cpu_batch_t, time.perf_counter() - t0)
    for r, c in zip(resps, cpu_resps):
        if r.encode() != c.encode():
            print(json.dumps({"metric": "BATCH_MISMATCH", "value": 0, "unit": "rows/sec", "vs_baseline": 0}))
            sys.exit(1)
    total_rows = n * len(batch)
    batch_speedup = cpu_batch_t / tpu_batch_t
    results["batch"] = {
        "queries": len(batch),
        "cpu_workers": cpu_workers,
        "cpu_rows_per_s": total_rows / cpu_batch_t,
        "tpu_rows_per_s": total_rows / tpu_batch_t,
        "speedup": batch_speedup,
    }

    mvcc_rows_s = None
    topn_rows_s = None
    if os.environ.get("BENCH_MVCC", "1") != "0":
        mvcc_rows_s = bench_mvcc_validation()
        topn_rows_s = bench_endpoint_topn()

    geo = float(np.exp(np.mean(np.log(speedups))))
    tpu_rows = results["batch"]["tpu_rows_per_s"]
    detail = {
        "rows": n,
        "backend": _BACKEND,
        "build_s": round(build_s, 2),
        "warm_geo_speedup": round(geo, 3),
        **{f"{k}_{m}": round(v2, 1) for k, r in results.items() for m, v2 in r.items()},
    }
    if mvcc_rows_s:
        detail["mvcc_q6_rows_per_s"] = round(mvcc_rows_s, 1)
    if topn_rows_s:
        detail["endpoint_topn_device_rows_per_s"] = round(topn_rows_s, 1)
    print(json.dumps(detail), file=sys.stderr)
    metric = "copr_q1q6_batched_tpu_rows_per_sec"
    if _BACKEND.startswith("cpu"):
        # no device backend (tunnel down or CPU-only host): CPU-vs-CPU number,
        # never attested under the TPU metric name
        metric += "_cpu_fallback"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(tpu_rows, 1),
                "unit": "rows/sec",
                "vs_baseline": round(batch_speedup, 3),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the driver needs a parsed JSON line, not a traceback
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": f"bench_error_{type(e).__name__}",
                    "value": 0.0,
                    "unit": "rows/sec",
                    "vs_baseline": 0.0,
                }
            )
        )
        sys.exit(1)
