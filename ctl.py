#!/usr/bin/env python
"""tpu-tikv-ctl — ops CLI (the reference's cmd/tikv-ctl re-expression).

Operates on a live store's TCP endpoint (``--addr``) for KV/raw commands, or
directly on persisted engine state for offline inspection (debug commands run
against a store process in this build's in-process harnesses; offline mode
takes over once the native engine lands).

    ctl.py --addr HOST:PORT raw-get <key>
    ctl.py --addr HOST:PORT raw-put <key> <value>
    ctl.py --addr HOST:PORT raw-scan [--start S] [--limit N]
    ctl.py --addr HOST:PORT mvcc <key> --version TS --region R
    ctl.py --addr HOST:PORT scan-lock --max-ts TS
    ctl.py --addr HOST:PORT resolve-lock --start-ts TS [--commit-ts TS]
    ctl.py --addr HOST:PORT region-info|region-properties [--region R]
    ctl.py --addr HOST:PORT read-progress [--region R]
    ctl.py --addr HOST:PORT integrity
    ctl.py --addr HOST:PORT consistency-check [--trigger] [--region R]
    ctl.py --addr HOST:PORT bad-regions|all-regions
    ctl.py --status ADDR metrics|config
    ctl.py --status ADDR reconfig section.key=value ...

Offline (destructive) commands operate on a STOPPED store's engine directory
(cmd/tikv-ctl/src/main.rs:1513-1642 unsafe-recover / recover-mvcc /
recreate-region / tombstone / compact — these rewrite persisted state and
must never run against a live process):

    ctl.py --db PATH unsafe-recover --stores 2,3
    ctl.py --db PATH recover-mvcc [--apply] [--safe-ts TS]
    ctl.py --db PATH tombstone --region R
    ctl.py --db PATH recreate-region --region R --store S --peer P
    ctl.py --db PATH compact [--cf CF]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

from tikv_tpu.server.server import Client


def _client(addr: str) -> Client:
    host, port = addr.rsplit(":", 1)
    return Client(host, int(port))


class _DataKeyEngine:
    """Engine-trait adapter for offline restore: writes land under the z
    data-key prefix — where RegionSnapshot reads look — instead of at raw
    encoded keys (which only a prefixless wrapper could ever see again)."""

    def __init__(self, inner):
        self.inner = inner

    def write(self, ctx, wb) -> None:
        from tikv_tpu.storage.engine import WriteBatch
        from tikv_tpu.util import keys as keymod

        out = WriteBatch()
        for op, cf, key, val in wb.ops:
            if op == "put":
                out.put_cf(cf, keymod.data_key(key), val)
            elif op == "delete":
                out.delete_cf(cf, keymod.data_key(key))
            else:
                out.delete_range_cf(cf, keymod.data_key(key), keymod.data_key(val))
        self.inner.write(out)

    def snapshot(self, ctx=None):
        return self.inner.snapshot()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-tikv-ctl")
    p.add_argument("--addr", help="store RPC address host:port")
    p.add_argument("--status", help="status server address host:port")
    p.add_argument("--db", help="engine dir of a STOPPED store (offline mode)")
    p.add_argument("--encryption-master-key", default=None,
                   help="master key file of an encrypted store (reads "
                        "<db>/keys.dict)")
    p.add_argument("--region", type=int, default=1)
    sub = p.add_subparsers(dest="cmd", required=True)

    for name in ("raw-get", "mvcc"):
        sp = sub.add_parser(name)
        sp.add_argument("key")
        sp.add_argument("--version", type=int, default=None)
    sp = sub.add_parser("raw-put")
    sp.add_argument("key")
    sp.add_argument("value")
    sp = sub.add_parser("raw-scan")
    sp.add_argument("--start", default="")
    sp.add_argument("--limit", type=int, default=30)
    sp = sub.add_parser("scan-lock")
    sp.add_argument("--max-ts", type=int, default=2**63)
    sp = sub.add_parser("resolve-lock")
    sp.add_argument("--start-ts", type=int, required=True)
    sp.add_argument("--commit-ts", type=int, default=0)
    for name in ("region-info", "region-properties"):
        sp = sub.add_parser(name)
        # SUPPRESS: a value given after the subcommand wins; otherwise the
        # parent-level --region (or its default) stays in effect
        sp.add_argument("--region", type=int, default=argparse.SUPPRESS)
    sp = sub.add_parser(
        "read-progress",
        help="per-region (resolved_ts, required_apply_index) + store "
             "safe_ts — why a follower refuses stale reads")
    # its own dest: the parent --region default (1) must not narrow the
    # default all-regions view
    sp.add_argument("--region", type=int, dest="progress_region", default=None,
                    help="narrow to one region (default: every region)")
    sub.add_parser(
        "overload",
        help="overload-control view (docs/robustness.md): per-tenant "
             "bucket levels + effective rates, defer/shed counts, the "
             "adaptive controller's scale and evidence, and HBM partition "
             "occupancy")
    sub.add_parser(
        "cost-router",
        help="cost-based path router + geometry tuner view "
             "(docs/cost_router.md): per-sig decision counts by reason, "
             "recent routing decisions, and the tuner's knob history")
    sub.add_parser(
        "integrity",
        help="derived-plane integrity view: per-region image fingerprints "
             "+ apply points, quarantine ledger, scrubber progress, "
             "shadow-read sample/mismatch counts (docs/integrity.md)")
    sp = sub.add_parser(
        "consistency-check",
        help="raft consistency-check surface: recorded per-region hashes "
             "and divergences; --trigger proposes a fresh compute_hash "
             "round on every led region (results land asynchronously — "
             "re-run without --trigger to read them)")
    sp.add_argument("--trigger", action="store_true",
                    help="schedule a new round instead of reading results")
    sp.add_argument("--region", type=int, dest="cc_region", default=None,
                    help="narrow --trigger to one region")
    sp = sub.add_parser(
        "trace",
        help="distributed tracing surface (docs/tracing.md): `trace list` "
             "shows recent+slow traces (--addr), `trace show --trace-id T` "
             "renders one trace's timeline (--addr), `trace set-sample-rate "
             "R` reconfigures head sampling online (--status)")
    sp.add_argument("action", choices=["list", "show", "set-sample-rate"])
    sp.add_argument("rate", nargs="?", type=float, default=None,
                    help="sample rate in [0,1] for set-sample-rate")
    sp.add_argument("--trace-id", default=None, help="trace id for show")
    sp.add_argument("--limit", type=int, default=20)
    sp.add_argument("--slow", action="store_true",
                    help="list only the slow/promoted ring")
    sp = sub.add_parser(
        "observatory",
        help="performance observatory (docs/observatory.md): `observatory "
             "top` lists (sig, path) rows by time spent like a live "
             "profiler; `observatory sig <SIG>` shows one plan signature's "
             "per-path cost profiles with exemplar trace ids; `observatory "
             "compiles` dumps the device compile ledger")
    sp.add_argument("action", choices=["top", "sig", "compiles"])
    sp.add_argument("sig", nargs="?", default=None,
                    help="plan signature id for `observatory sig`")
    sp.add_argument("--limit", type=int, default=20)
    sp.add_argument("--json", action="store_true",
                    help="raw JSON instead of the text rendering")
    sub.add_parser("bad-regions")
    sub.add_parser("all-regions")
    sub.add_parser("metrics")
    sub.add_parser("config")
    sp = sub.add_parser("reconfig")
    sp.add_argument("changes", nargs="+", help="section.key=value")
    # offline (destructive) commands: --db required
    sp = sub.add_parser("unsafe-recover")
    sp.add_argument("--stores", required=True, help="failed store ids, comma-separated")
    sp = sub.add_parser("recover-mvcc")
    sp.add_argument("--apply", action="store_true", help="write fixes (default: dry run)")
    sp.add_argument("--safe-ts", type=int, default=0,
                    help="GC safe point; locks below it are orphans (default 0: none)")
    sp = sub.add_parser("tombstone")
    sp.add_argument("--region", type=int, required=True)
    sp = sub.add_parser("recreate-region")
    sp.add_argument("--region", type=int, required=True)
    sp.add_argument("--store", type=int, required=True)
    sp.add_argument("--peer", type=int, required=True)
    sp.add_argument("--start", default="")
    sp.add_argument("--end", default="")
    sp = sub.add_parser("compact")
    sp.add_argument("--cf", default=None)
    # BR-style offline backup/restore over a stopped store (--db required)
    sp = sub.add_parser("backup")
    sp.add_argument("--out", required=True, help="backup storage directory")
    sp.add_argument("--name", default="full")
    sp.add_argument("--backup-ts", type=int, required=True)
    sp = sub.add_parser("backup-verify")
    sp.add_argument("--out", required=True)
    sp.add_argument("--name", default="full")
    sp = sub.add_parser("restore")
    sp.add_argument("--out", required=True, help="backup storage directory")
    sp.add_argument("--name", default="full")
    sp.add_argument("--restore-ts", type=int, required=True)
    sp.add_argument("--region-id", type=int, default=1,
                    help="region id for the restored whole-range region")
    sp.add_argument("--store", type=int, default=1)
    sp.add_argument("--peer", type=int, default=1)

    args = p.parse_args(argv)
    ctx = {"region_id": args.region}

    if args.cmd == "backup-verify":
        # pure storage-side validation: no engine, no --db (BR validate
        # runs wherever the backup lives)
        from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage

        out = BackupEndpoint(LocalStorage(args.out)).verify(args.name)
        print(json.dumps(out, indent=2))
        return 0

    offline_cmds = ("unsafe-recover", "recover-mvcc", "tombstone",
                    "recreate-region", "compact", "backup", "restore")
    if args.cmd in offline_cmds:
        if not args.db:
            print("--db required (offline commands run on a stopped store)",
                  file=sys.stderr)
            return 2
        from tikv_tpu.native.engine import NativeEngine
        from tikv_tpu.server.debug import Debugger

        keys_mgr = None
        if args.encryption_master_key:
            from tikv_tpu.storage.encryption import DataKeyManager, MasterKey

            os.makedirs(args.db, exist_ok=True)
            keys_mgr = DataKeyManager.open(
                MasterKey.from_file(args.encryption_master_key),
                os.path.join(args.db, "keys.dict"),
            )
        eng = NativeEngine(path=args.db, keys_mgr=keys_mgr)
        rlog = None
        rlog_dir = os.path.join(args.db, "raftlog")
        if os.path.isdir(rlog_dir):
            # the store ran with the log engine: region surgery must reach it
            from tikv_tpu.native.raftlog import NativeRaftLog, raftlog_available

            if raftlog_available():
                rlog = NativeRaftLog(rlog_dir, keys_mgr=keys_mgr)
        try:
            dbg = Debugger(eng, raft_log=rlog)
            if args.cmd == "unsafe-recover":
                failed = {int(s) for s in args.stores.split(",")}
                modified = dbg.unsafe_recover(failed)
                out = {"modified_regions": modified, "removed_stores": sorted(failed)}
            elif args.cmd == "recover-mvcc":
                out = dbg.recover_mvcc(dry_run=not args.apply, safe_ts=args.safe_ts)
            elif args.cmd == "tombstone":
                out = {"tombstoned": dbg.tombstone_region(args.region)}
            elif args.cmd == "recreate-region":
                dbg.recreate_region(args.region, args.start.encode(),
                                    args.end.encode(), args.store, args.peer)
                out = {"recreated": args.region}
            elif args.cmd in ("backup", "restore"):
                from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage

                ep = BackupEndpoint(LocalStorage(args.out))
                if args.cmd == "backup":
                    meta = ep.backup_offline(eng, args.name, args.backup_ts)
                    out = {"name": args.name, "regions": len(meta["regions"]),
                           "total_kvs": meta["total_kvs"],
                           "crc64xor": meta["crc64xor"]}
                else:
                    # restore must produce a BOOTABLE store dir: data under
                    # the z data-key prefix (where region reads look) plus a
                    # whole-range region meta the next recover() finds —
                    # recreate-region semantics with the data already in
                    out = ep.restore(_DataKeyEngine(eng), args.name,
                                     args.restore_ts, keys_mgr=keys_mgr)
                    dbg.recreate_region(args.region_id, b"", b"",
                                        args.store, args.peer)
                    out["region"] = args.region_id
            else:
                out = dbg.compact(args.cf)
            eng.flush()
            print(json.dumps(out, indent=2))
            return 0
        finally:
            eng.close()
            if rlog is not None:
                rlog.close()

    if args.cmd == "trace" and args.action == "set-sample-rate":
        # runtime knob through the online-config controller (POST /config)
        if not args.status:
            print("--status required for set-sample-rate", file=sys.stderr)
            return 2
        if args.rate is None:
            print("usage: trace set-sample-rate RATE", file=sys.stderr)
            return 2
        req = urllib.request.Request(
            f"http://{args.status}/config",
            data=json.dumps({"trace.sample_rate": args.rate}).encode(),
            method="POST")
        try:
            print(urllib.request.urlopen(req).read().decode())
        except urllib.error.HTTPError as e:
            print(f"set-sample-rate rejected: {e.read().decode()}",
                  file=sys.stderr)
            return 1
        return 0

    if args.cmd in ("metrics", "config", "reconfig"):
        if not args.status:
            print("--status required", file=sys.stderr)
            return 2
        base = f"http://{args.status}"
        if args.cmd == "metrics":
            print(urllib.request.urlopen(base + "/metrics").read().decode())
        elif args.cmd == "config":
            print(json.dumps(json.loads(urllib.request.urlopen(base + "/config").read()), indent=2))
        else:
            changes = {}
            for ch in args.changes:
                k, _, v = ch.partition("=")
                try:
                    v = json.loads(v)
                except json.JSONDecodeError:
                    pass
                changes[k] = v
            req = urllib.request.Request(base + "/config", data=json.dumps(changes).encode(), method="POST")
            try:
                print(urllib.request.urlopen(req).read().decode())
            except urllib.error.HTTPError as e:
                print(f"reconfig rejected: {e.read().decode()}", file=sys.stderr)
                return 1
        return 0

    if not args.addr:
        print("--addr required", file=sys.stderr)
        return 2
    c = _client(args.addr)
    try:
        if args.cmd == "raw-get":
            r = c.call("raw_get", {"key": args.key.encode(), "context": ctx})
        elif args.cmd == "raw-put":
            r = c.call("raw_put", {"key": args.key.encode(), "value": args.value.encode(), "context": ctx})
        elif args.cmd == "raw-scan":
            r = c.call("raw_scan", {"start_key": args.start.encode(), "limit": args.limit, "context": ctx})
        elif args.cmd == "mvcc":
            r = c.call("kv_get", {"key": args.key.encode(), "version": args.version or 2**63, "context": ctx})
        elif args.cmd == "scan-lock":
            r = c.call("kv_scan_lock", {"max_version": args.max_ts, "context": ctx})
        elif args.cmd == "resolve-lock":
            r = c.call(
                "kv_resolve_lock",
                {"start_version": args.start_ts, "commit_version": args.commit_ts, "context": ctx},
            )
        elif args.cmd == "trace":
            from tikv_tpu.util.trace import timeline

            if args.action == "show":
                if not args.trace_id:
                    print("trace show requires --trace-id", file=sys.stderr)
                    return 2
                r = c.call("debug_traces", {"trace_id": args.trace_id})
                if "timeline" in r:
                    print(r["timeline"])
                    return 0
            else:  # list
                r = c.call("debug_traces", {"limit": args.limit})
                if "error" not in r:
                    rings = ("slow",) if args.slow else ("slow", "recent")
                    print(f"sample_rate={r['sample_rate']} "
                          f"slow_threshold_s={r['slow_threshold_s']} "
                          f"live={r['live']}")
                    for ring in rings:
                        print(f"-- {ring} ({len(r[ring])}) --")
                        for t in reversed(r[ring]):
                            print(timeline(t))
                    return 0
        elif args.cmd == "observatory":
            from tikv_tpu.copr.observatory import format_sig, format_top

            if args.action == "top":
                r = c.call("debug_observatory", {"top": True,
                                                 "limit": args.limit})
                if "error" not in r and not args.json:
                    print(format_top(r["top"]))
                    return 0
            elif args.action == "sig":
                if not args.sig:
                    print("observatory sig requires a SIG id", file=sys.stderr)
                    return 2
                r = c.call("debug_observatory", {"sig": args.sig})
                if "error" not in r and not args.json:
                    entry = r.get("sigs", {}).get(args.sig)
                    if entry is None:
                        print(f"sig {args.sig} not profiled", file=sys.stderr)
                        return 1
                    print(format_sig(args.sig, entry))
                    return 0
            else:  # compiles
                r = c.call("debug_observatory", {})
                if "error" not in r and not args.json:
                    comp = r["compiles"]
                    print(f"compile events ({len(comp['events'])}), "
                          f"executable caches: {comp['executable_cache_sizes']}")
                    for ev in comp["events"][-args.limit:]:
                        extra = "".join(
                            f" {k}={ev[k]}" for k in
                            ("cache_size", "flops", "bytes_accessed")
                            if k in ev)
                        print(f"  [{ev['t']:9.3f}s] {ev['site']:<22} "
                              f"path={ev['path']:<8} sig={ev['sig']} "
                              f"wall={ev['wall_s'] * 1e3:.1f}ms{extra}")
                    return 0
        elif args.cmd == "read-progress":
            req = {}
            if args.progress_region is not None:
                req["region_id"] = args.progress_region
            r = c.call("debug_read_progress", req)
        elif args.cmd == "region-info":
            r = c.call("debug_region_info", {"region_id": args.region})
        elif args.cmd == "region-properties":
            r = c.call("debug_region_properties", {"region_id": args.region})
        elif args.cmd == "integrity":
            r = c.call("debug_integrity", {})
        elif args.cmd == "overload":
            r = c.call("debug_overload", {})
        elif args.cmd == "cost-router":
            r = c.call("debug_cost_router", {})
        elif args.cmd == "consistency-check":
            if args.trigger:
                req = {}
                if args.cc_region is not None:
                    req["region_id"] = args.cc_region
                r = c.call("debug_consistency_check", req)
            else:
                r = c.call("debug_consistency", {})
        elif args.cmd == "bad-regions":
            r = c.call("debug_bad_regions", {})
        elif args.cmd == "all-regions":
            r = c.call("debug_all_regions", {})
        else:
            raise AssertionError(args.cmd)
        print(json.dumps(r, default=lambda b: b.decode("utf8", "replace") if isinstance(b, bytes) else str(b), indent=2))
        return 0 if "error" not in r else 1
    finally:
        c.close()


if __name__ == "__main__":
    sys.exit(main())
