"""ctypes binding for the native C++ engine.

Implements the ``KvEngine``/``Snapshot``/``Cursor`` trait surface over
``engine.cc`` (the RocksDB role from components/engine_rocks, as a versioned
ordered memtable with O(1) sequence-number snapshots).  The shared library is
built on first use with the baked-in g++ (no pip deps; pybind11 unavailable —
plain C ABI via ctypes).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Iterator

from ..storage.engine import ALL_CFS, Cursor, KvEngine, Snapshot, WriteBatch
from ..util.io_limiter import IoType

_CF_IDS = {cf: i for i, cf in enumerate(ALL_CFS)}

# background compaction folds a CF's sorted runs once this many accumulate
MERGE_FANIN = 4

def _serialize_ops(ops) -> bytes:
    """The native wire format (op u8 | cf u8 | klen u32 | key | vlen u32 |
    val) has exactly ONE encoder — write() and bulk_load() both come here.
    Join-based with precomputed 2-byte prefixes: this loop is the Python
    side of the ingestion hot path."""
    parts = []
    ap = parts.append
    pack = _U32.pack
    for op, cf, key, val in ops:
        v = val if val is not None else b""
        ap(_OP_CF_PREFIX[op, cf])
        ap(pack(len(key)))
        ap(key)
        ap(pack(len(v)))
        ap(v)
    return b"".join(parts)


_OP_CF_PREFIX = {
    (op, cf): bytes([opc, cfc])
    for op, opc in (("put", 1), ("delete", 2), ("delete_range", 3))
    for cf, cfc in _CF_IDS.items()
}
_U32 = struct.Struct("<I")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "engine.cc")
_SO = os.path.join(_HERE, "libtikv_engine.so")

_lib = None
_lib_err: str | None = None
_build_mu = threading.Lock()


def _so_stale(so: str, *srcs: str) -> bool:
    """True when the shared object predates ANY of its sources (the .cc
    plus shared headers) — the one place the dependency list lives."""
    if not os.path.exists(so):
        return True
    newest = max(
        (os.path.getmtime(p) for p in srcs if os.path.exists(p)), default=0
    )
    return os.path.getmtime(so) < newest


def _build() -> None:
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO, _SRC],
        check=True,
        capture_output=True,
    )


def _load():
    global _lib, _lib_err
    with _build_mu:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            if _so_stale(_SO, _SRC, os.path.join(_HERE, "crypt.h")):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError) as e:
            _lib_err = str(e)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.eng_open.restype = ctypes.c_void_p
        lib.eng_close.argtypes = [ctypes.c_void_p]
        lib.eng_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.eng_write.restype = ctypes.c_int
        lib.eng_snapshot.argtypes = [ctypes.c_void_p]
        lib.eng_snapshot.restype = ctypes.c_uint64
        lib.eng_release_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.eng_get.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.eng_get.restype = ctypes.c_int
        lib.eng_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.eng_scan.restype = ctypes.c_long
        lib.eng_seek.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.eng_seek.restype = ctypes.c_int
        lib.eng_free.argtypes = [u8p]
        lib.eng_stats_keys.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.eng_stats_keys.restype = ctypes.c_uint64
        lib.eng_open_at.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.eng_open_at.restype = ctypes.c_void_p
        lib.eng_open_at_enc.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_char_p, ctypes.c_int,
        ]
        lib.eng_open_at_enc.restype = ctypes.c_void_p
        lib.eng_set_encryption.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_char_p, ctypes.c_int,
        ]
        lib.eng_set_encryption.restype = ctypes.c_int
        lib.eng_checkpoint.argtypes = [ctypes.c_void_p]
        lib.eng_checkpoint.restype = ctypes.c_int
        lib.eng_set_wal_limit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.eng_set_sync.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.eng_set_sync.restype = ctypes.c_int
        for fn in (lib.eng_seq, lib.eng_mem_bytes, lib.eng_wal_bytes):
            fn.argtypes = [ctypes.c_void_p]
            fn.restype = ctypes.c_uint64
        lib.eng_compact_step.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.eng_compact_step.restype = ctypes.c_long
        lib.eng_mvcc_props.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.eng_mvcc_props.restype = ctypes.c_int
        lib.eng_build_sst.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.eng_build_sst.restype = ctypes.c_int
        lib.eng_ingest_sst.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.eng_ingest_sst.restype = ctypes.c_int
        lib.eng_flush.argtypes = [ctypes.c_void_p]
        lib.eng_flush.restype = ctypes.c_int
        lib.eng_set_mem_limit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.eng_run_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.eng_run_count.restype = ctypes.c_int
        lib.eng_merge_runs.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.eng_merge_runs.restype = ctypes.c_int
        lib.eng_perf.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        _lib = lib
        return _lib


def build_sst(path: str, entries) -> None:
    """Write an immutable SST file: ``entries`` = iterable of
    (cf_name, key, value), sorted by (cf, key).  The native side frames it
    (magic + CRC footer) and re-validates sortedness before the atomic
    tmp+rename publish."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native engine unavailable: {_lib_err}")
    parts = []
    for cf, key, val in entries:
        parts.append(bytes([_CF_IDS[cf]]))
        parts.append(_U32.pack(len(key)))
        parts.append(key)
        parts.append(_U32.pack(len(val)))
        parts.append(val)
    body = b"".join(parts)
    r = lib.eng_build_sst(os.fsencode(path), body, len(body))
    if r != 0:
        raise RuntimeError(f"eng_build_sst failed: {r} (entries must be sorted)")


def native_available() -> bool:
    return _load() is not None


def parse_frames(buf: bytes, n: int):
    """Iterate (key, value) pairs of the native scan frame format
    (klen u32le | key | vlen u32le | val) — THE decoder for this layout."""
    off = 0
    for _ in range(n):
        (klen,) = _U32.unpack_from(buf, off)
        off += 4
        k = buf[off : off + klen]
        off += klen
        (vlen,) = _U32.unpack_from(buf, off)
        off += 4
        v = buf[off : off + vlen]
        off += vlen
        yield k, v


def _take(lib, ptr, length) -> bytes:
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.eng_free(ptr)


class _NativeCursor(Cursor):
    """Cursor via repeated bounded seeks (each seek resolves MVCC versions
    natively; next/prev re-seek from the current key)."""

    def __init__(self, snap: "NativeSnapshot", cf: int, lower: bytes | None, upper: bytes | None):
        self._snap = snap
        self._cf = cf
        self._lower = lower or b""
        self._upper = upper
        self._key: bytes | None = None
        self._value: bytes | None = None

    def _do_seek(self, target: bytes, for_prev: bool) -> bool:
        lib = self._snap._lib
        kout = ctypes.POINTER(ctypes.c_uint8)()
        klen = ctypes.c_uint64()
        vout = ctypes.POINTER(ctypes.c_uint8)()
        vlen = ctypes.c_uint64()
        upper = self._upper
        r = lib.eng_seek(
            self._snap._handle, self._cf, self._snap._seq,
            target, len(target),
            self._lower, len(self._lower),
            upper or b"", len(upper or b""), 1 if upper is not None else 0,
            1 if for_prev else 0,
            ctypes.byref(kout), ctypes.byref(klen),
            ctypes.byref(vout), ctypes.byref(vlen),
        )
        if r == 1:
            self._key = _take(lib, kout, klen.value)
            self._value = _take(lib, vout, vlen.value)
            return True
        self._key = self._value = None
        return False

    def seek(self, key: bytes) -> bool:
        return self._do_seek(key, False)

    def seek_for_prev(self, key: bytes) -> bool:
        return self._do_seek(key, True)

    def seek_to_first(self) -> bool:
        return self._do_seek(self._lower, False)

    def seek_to_last(self) -> bool:
        if self._upper is not None:
            # upper is exclusive; for_prev at upper then step below it
            if self._do_seek(self._upper, True) and self._key < self._upper:
                return True
            return self.prev() if self._key is not None else False
        return self._do_seek(b"\xff" * 64, True)

    def next(self) -> bool:
        if self._key is None:
            return False
        return self._do_seek(self._key + b"\x00", False)

    def prev(self) -> bool:
        """Step to the largest visible key strictly below the current one.

        Byte-string order has no exact predecessor, so seek_for_prev targets
        the tightest constructible bound: for ...X00 the prefix itself, else
        decrement the last byte and pad with 0xff (safe for keys shorter than
        the pad — true for all key layouts in this system).
        """
        if self._key is None:
            return False
        k = self._key
        if len(k) == 0:
            self._key = self._value = None
            return False
        if k.endswith(b"\x00"):
            target = k[:-1]
        else:
            target = k[:-1] + bytes([k[-1] - 1]) + b"\xff" * 64
        ok = self._do_seek(target, True)
        if ok and self._key >= k:
            self._key = self._value = None
            return False
        return ok

    def valid(self) -> bool:
        return self._key is not None

    def key(self) -> bytes:
        return self._key

    def value(self) -> bytes:
        return self._value


class NativeSnapshot(Snapshot):
    def __init__(self, engine: "NativeEngine"):
        self._lib = engine._lib
        self._handle = engine._handle
        self._engine = engine
        self._seq = self._lib.eng_snapshot(self._handle)
        self._released = False

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def release(self) -> None:
        if not self._released and self._engine._handle is not None:
            self._lib.eng_release_snapshot(self._handle, self._seq)
            self._released = True

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        r = self._lib.eng_get(
            self._handle, _CF_IDS[cf], key, len(key), self._seq,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if r == 1:
            val = _take(self._lib, out, out_len.value)
            self._engine._io(IoType.FOREGROUND_READ, len(val))
            return val
        return None

    def cursor_cf(self, cf: str, lower: bytes | None = None, upper: bytes | None = None) -> Cursor:
        return _NativeCursor(self, _CF_IDS[cf], lower, upper)

    def scan_raw(self, cf: str, start: bytes, end: bytes | None, limit=None, reverse=False) -> tuple[int, bytes]:
        """One FFI crossing for a whole range: (n_pairs, framed buffer).
        Frame: repeated (klen u32le | key | vlen u32le | val)."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        n = self._lib.eng_scan(
            self._handle, _CF_IDS[cf], self._seq,
            start, len(start), end or b"", len(end or b""), 1 if end is not None else 0,
            limit or 0, 1 if reverse else 0,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if n < 0:
            raise RuntimeError(f"eng_scan failed: {n}")
        buf = _take(self._lib, out, out_len.value)
        self._engine._io(IoType.FOREGROUND_READ, len(buf))
        return n, buf

    def scan_cf(self, cf, start, end, limit=None, reverse=False) -> Iterator[tuple[bytes, bytes]]:
        n, buf = self.scan_raw(cf, start, end, limit, reverse)
        yield from parse_frames(buf, n)


def _key_registry(keys_mgr):
    """(ids_array, keys_blob, current_id) for the FFI from a DataKeyManager."""
    items = sorted(keys_mgr.all_keys().items())
    ids = (ctypes.c_uint32 * len(items))(*[i for i, _k in items])
    keys = b"".join(k for _i, k in items)
    current, _ = keys_mgr.current()
    return ids, keys, current


class NativeEngine(KvEngine):
    """In-memory by default; pass ``path`` for a durable LSM engine: every
    committed WriteBatch is WAL-appended + fdatasync'd before the write
    returns (``sync=False`` keeps OS-buffered appends); memtable flushes
    write immutable block-indexed, bloom-filtered sorted runs and truncate
    the WAL; reads merge memtable + runs; background merges fold runs and
    drop bottom-level tombstones (engine_rocks over rocksdb: WAL + memtable
    flush + SST levels + compaction + perf context, re-derived)."""

    def __init__(self, path: str | None = None, sync: bool = True,
                 wal_limit: int | None = None, mem_limit: int | None = None,
                 io_limiter=None, keys_mgr=None):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native engine unavailable: {_lib_err}")
        self._lib = lib
        self.path = path
        # per-IO-type classification + throttling (components/file_system:
        # every engine IO is tagged foreground_write / flush / compaction /
        # foreground_read and, when a limiter is attached, pays its byte
        # budget — background compaction is the one the budget exists for)
        self._io_limiter = io_limiter
        self._io_bytes = {t: 0 for t in IoType}
        self._io_mu = threading.Lock()
        # encryption at rest (manager/mod.rs:398 + engine_rocks/src/
        # encryption.rs:30 role): the DataKeyManager's raw keys cross the FFI
        # once; every file written from here on is ChaCha20-encrypted with a
        # per-file sidecar naming its key id, so master/data-key rotation
        # never rewrites data files
        self._keys_mgr = keys_mgr
        if path is None:
            self._handle = lib.eng_open()
        else:
            if keys_mgr is not None:
                ids, keys, current = _key_registry(keys_mgr)
                self._handle = lib.eng_open_at_enc(
                    os.fsencode(path), 1 if sync else 0, current, ids, keys,
                    len(ids),
                )
            else:
                self._handle = lib.eng_open_at(
                    os.fsencode(path), 1 if sync else 0
                )
            if not self._handle:
                raise RuntimeError(f"cannot open engine dir {path!r}")
        if wal_limit is not None:
            lib.eng_set_wal_limit(self._handle, wal_limit)
        if mem_limit is not None:
            lib.eng_set_mem_limit(self._handle, mem_limit)

    def refresh_encryption(self) -> None:
        """Re-read the key registry from the DataKeyManager (after an
        external rotate): files written from now on use the new current key
        while existing files keep their sidecar key."""
        if self._keys_mgr is None:
            raise RuntimeError("engine opened without encryption")
        ids, keys, current = _key_registry(self._keys_mgr)
        if self._lib.eng_set_encryption(self._handle, current, ids, keys, len(ids)) != 0:
            raise RuntimeError("eng_set_encryption failed")

    def rotate_data_key(self) -> int:
        """Mint a new data key and refresh the engine registry."""
        if self._keys_mgr is None:
            raise RuntimeError("engine opened without encryption")
        new_id = self._keys_mgr.rotate()
        self.refresh_encryption()
        return new_id

    def _io(self, io_type, nbytes: int) -> None:
        if nbytes <= 0 or self.path is None:
            return  # in-memory engines do no file IO: nothing to classify
        with self._io_mu:
            self._io_bytes[io_type] += nbytes
        if self._io_limiter is not None:
            self._io_limiter.request(nbytes, io_type)

    def io_stats(self) -> dict:
        """Bytes moved per IO type (file_system IOStats role)."""
        with self._io_mu:
            return {t.value: n for t, n in self._io_bytes.items() if n}

    def checkpoint(self) -> None:
        """Flush the memtable to sorted runs; truncates the WAL.  O(memtable),
        never O(database) — the incremental successor of the full spill."""
        nbytes = self.mem_bytes() if self.path is not None else 0
        r = self._lib.eng_checkpoint(self._handle)
        if r != 0:
            raise RuntimeError(f"eng_checkpoint failed: {r}")
        self._io(IoType.FLUSH, nbytes)

    flush = checkpoint

    def set_mem_limit(self, limit: int) -> None:
        """Memtable flush threshold in bytes (0 = manual flush only)."""
        self._lib.eng_set_mem_limit(self._handle, limit)

    def run_count(self, cf: str = "default") -> int:
        """On-disk sorted runs for one CF."""
        return self._lib.eng_run_count(self._handle, _CF_IDS[cf])

    def merge_runs(self, cf: str) -> int:
        """Merge every run of a CF into one (background compaction step);
        returns 1 if a merge happened."""
        nbytes = 0
        if self.path is not None and self.run_count(cf) >= 2:
            # compaction reads every input run and writes one output of
            # roughly the same size: charge the run bytes on disk (skip
            # in-flight .tmp files; a file unlinked mid-scan just drops out)
            prefix = f"run{_CF_IDS[cf]}-"
            try:
                names = os.listdir(self.path)
            except OSError:
                names = []
            for f in names:
                if f.startswith(prefix) and not f.endswith(".tmp"):
                    try:
                        nbytes += os.path.getsize(os.path.join(self.path, f))
                    except OSError:
                        pass
        r = self._lib.eng_merge_runs(self._handle, _CF_IDS[cf])
        if r < 0:
            raise RuntimeError(f"eng_merge_runs failed: {r}")
        if r:
            self._io(IoType.COMPACTION, nbytes)
        return r

    def perf_context(self) -> dict:
        """Per-read statistics (engine_rocks perf_context.rs role)."""
        import ctypes

        out = (ctypes.c_uint64 * 7)()
        self._lib.eng_perf(self._handle, out)
        names = ("gets", "memtable_hits", "run_probes", "bloom_skips",
                 "blocks_read", "flushes", "run_merges")
        return dict(zip(names, out))

    def set_sync(self, sync: bool) -> None:
        """Import-mode tuning (import_mode.rs): buffered WAL during bulk
        load, fdatasync restored (and the window closed) when done."""
        r = self._lib.eng_set_sync(self._handle, 1 if sync else 0)
        if r != 0:
            # the flush closing the unsynced window failed: the buffered tail
            # is not durable and the engine has latched into refuse-writes
            raise RuntimeError(f"eng_set_sync failed: {r}")

    def seq(self) -> int:
        return self._lib.eng_seq(self._handle)

    def mem_bytes(self) -> int:
        """Approximate resident key+value bytes (tikv_alloc-style accounting)."""
        return self._lib.eng_mem_bytes(self._handle)

    def wal_bytes(self) -> int:
        return self._lib.eng_wal_bytes(self._handle)

    # -- compaction ---------------------------------------------------------

    def compact_cf(self, cf: str, slice_keys: int = 4096) -> int:
        """One full compaction pass over a CF in bounded slices; returns
        versions dropped.  Each slice holds the engine's write lock for at
        most ``slice_keys`` keys, so reads/writes interleave between slices
        (the rocksdb background-compaction property, with the scheduling
        living here and the work in native code — ctypes releases the GIL
        for the duration of each step)."""
        import ctypes

        total = 0
        cursor = b""
        while True:
            resume = ctypes.POINTER(ctypes.c_uint8)()
            resume_len = ctypes.c_uint64(0)
            done = ctypes.c_int(0)
            r = self._lib.eng_compact_step(
                self._handle, _CF_IDS[cf], cursor, len(cursor), slice_keys,
                ctypes.byref(resume), ctypes.byref(resume_len), ctypes.byref(done),
            )
            if r < 0:
                raise RuntimeError(f"eng_compact_step failed: {r}")
            total += r
            if done.value:
                return total
            cursor = _take(self._lib, resume, resume_len.value)

    def compact(self, slice_keys: int = 4096) -> int:
        """Compact every CF; returns total versions dropped."""
        return sum(self.compact_cf(cf, slice_keys) for cf in _CF_IDS)

    def start_auto_compaction(self, interval_s: float = 10.0) -> None:
        """Background compaction loop (rocksdb's background job threads)."""
        import threading

        if getattr(self, "_compactor", None) is not None:
            return
        self._compact_stop = threading.Event()

        def loop():
            while not self._compact_stop.wait(interval_s):
                try:
                    self.compact()
                    # fold accumulated runs (leveled-compaction role): merge
                    # whenever a CF's run count reaches the fan-in
                    if self.path is not None:
                        for cf in _CF_IDS:
                            if self.run_count(cf) >= MERGE_FANIN:
                                self.merge_runs(cf)
                except RuntimeError:
                    return

        self._compactor = threading.Thread(
            target=loop, name="native-compaction", daemon=True
        )
        self._compactor.start()

    def stop_auto_compaction(self) -> None:
        if getattr(self, "_compactor", None) is not None:
            self._compact_stop.set()
            self._compactor.join(timeout=5.0)
            self._compactor = None

    # -- SST ingest ---------------------------------------------------------

    def ingest_sst(self, path: str) -> None:
        """Ingest an immutable SST file (sst_importer ingest:158): validated,
        copied into the engine dir, WAL-referenced (manifest-style), loaded.
        Survives crash/reopen; folded into the next checkpoint."""
        r = self._lib.eng_ingest_sst(self._handle, os.fsencode(path))
        if r != 0:
            raise RuntimeError(f"eng_ingest_sst failed: {r}")

    # -- MVCC properties ----------------------------------------------------

    def mvcc_properties(self, start: bytes = b"", end: bytes | None = None,
                        cf: str = "write") -> dict:
        """Range statistics steering GC (engine_rocks properties.rs
        MvccProperties): whether a sweep over this range can collect
        anything at all."""
        import ctypes

        out = (ctypes.c_uint64 * 8)()
        r = self._lib.eng_mvcc_props(
            self._handle, _CF_IDS[cf], start, len(start),
            end or b"", len(end or b""), 0 if end is None else 1,
            self.seq(), out,
        )
        if r != 0:
            raise RuntimeError(f"eng_mvcc_props failed: {r}")
        return {
            "num_entries": out[0],
            "num_rows": out[1],
            "num_puts": out[2],
            "num_deletes": out[3],
            "num_locks_rollbacks": out[4],
            "min_commit_ts": out[5],
            "max_commit_ts": out[6],
            "max_row_versions": out[7],
        }

    def need_gc(self, safe_point: int, ratio_threshold: float = 1.1,
                start: bytes = b"", end: bytes | None = None) -> bool:
        """The compaction-filter gate (gc_worker check_need_gc): skip ranges
        where versions/rows is below the threshold and nothing is deleted."""
        p = self.mvcc_properties(start, end)
        if p["num_rows"] == 0:
            return False
        if p["min_commit_ts"] > safe_point:
            return False  # every version still visible above the safe point
        if p["num_deletes"] > 0 or p["num_locks_rollbacks"] > 0:
            return True
        return p["num_entries"] >= p["num_rows"] * ratio_threshold

    def close(self) -> None:
        self.stop_auto_compaction()
        if self._handle is not None:
            self._lib.eng_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def _write_buf(self, out: bytes) -> None:
        self._io(IoType.FOREGROUND_WRITE, len(out))
        r = self._lib.eng_write(self._handle, out, len(out))
        if r != 0:
            raise RuntimeError(f"eng_write failed: {r}")

    def write(self, batch: WriteBatch) -> None:
        self._write_buf(_serialize_ops(batch.ops))

    def bulk_load(self, cf: str, items: list[tuple[bytes, bytes]]) -> None:
        # chunked so the parts list and joined buffer stay allocator-friendly
        CH = 32768
        for off in range(0, len(items), CH):
            self._write_buf(
                _serialize_ops(
                    ("put", cf, k, v) for k, v in items[off : off + CH]
                )
            )

    def snapshot(self) -> NativeSnapshot:
        return NativeSnapshot(self)

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        snap = self.snapshot()
        try:
            return snap.get_cf(cf, key)
        finally:
            snap.release()

    def scan_cf(self, cf, start, end, limit=None, reverse=False):
        snap = self.snapshot()
        try:
            return list(snap.scan_cf(cf, start, end, limit, reverse))
        finally:
            snap.release()
