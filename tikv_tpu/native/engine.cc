// Native ordered multi-CF storage engine.
//
// Plays the role RocksDB plays in the reference (components/engine_rocks):
// the storage medium under the engine-trait layer.  Design is a versioned
// ordered memtable (rocksdb-memtable-like): every write carries a sequence
// number; a snapshot is just a sequence, so snapshots are O(1) and never
// copy; iterators resolve the newest version <= snapshot per key.  Obsolete
// versions are compacted away once no live snapshot can see them.
//
// Exposed as a C API consumed via ctypes (no pybind11 in this image).  Scans
// return length-prefixed buffers so one FFI crossing moves a whole range.

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <shared_mutex>
#include <string>
#include <vector>

namespace {

struct Version {
  uint64_t seq;
  bool tombstone;
  std::string value;
};

// newest-first version chain per key
using Chain = std::vector<Version>;
using Table = std::map<std::string, Chain>;

constexpr int kNumCfs = 4;  // default, lock, write, raft

struct Engine {
  Table cfs[kNumCfs];
  uint64_t seq = 0;
  std::multiset<uint64_t> snapshots;
  mutable std::shared_mutex mu;

  uint64_t min_live_snapshot() const {
    return snapshots.empty() ? UINT64_MAX : *snapshots.begin();
  }
};

const std::string* resolve(const Chain& chain, uint64_t snap_seq) {
  for (const auto& v : chain) {  // newest first
    if (v.seq <= snap_seq) {
      return v.tombstone ? nullptr : &v.value;
    }
  }
  return nullptr;
}

void push_version(Chain& chain, uint64_t seq, bool tomb, std::string value,
                  uint64_t min_snap) {
  chain.insert(chain.begin(), Version{seq, tomb, std::move(value)});
  // compact: keep the newest version <= min_snap, drop everything older
  if (chain.size() > 1) {
    size_t keep = chain.size();
    for (size_t i = 0; i < chain.size(); i++) {
      if (chain[i].seq <= min_snap) {
        keep = i + 1;
        break;
      }
    }
    if (keep < chain.size()) chain.resize(keep);
  }
}

void put_version(Table& t, std::string key, uint64_t seq, bool tomb,
                 std::string value, uint64_t min_snap) {
  // bulk ingestion (restore, snapshot apply, bench load) streams keys in
  // ascending order: appending past the current max is O(1) with an end
  // hint instead of a full O(log n) descent + key copy per record
  Chain* chain;
  if (t.empty() || t.rbegin()->first < key) {
    chain = &t.emplace_hint(t.end(), std::move(key), Chain{})->second;
  } else {
    auto it = t.lower_bound(key);
    if (it != t.end() && it->first == key) {
      chain = &it->second;
    } else {
      chain = &t.emplace_hint(it, std::move(key), Chain{})->second;
    }
  }
  push_version(*chain, seq, tomb, std::move(value), min_snap);
}

// --- buffer helpers ---------------------------------------------------------

void append_u32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  out.append(b, 4);
}

uint32_t read_u32(const uint8_t*& p) {
  uint32_t v;
  memcpy(&v, p, 4);
  p += 4;
  return v;
}

}  // namespace

extern "C" {

void* eng_open() { return new Engine(); }

void eng_close(void* h) { delete static_cast<Engine*>(h); }

// batch format: repeated records
//   op u8 (1=put, 2=delete, 3=delete_range) | cf u8 |
//   klen u32 | key | vlen u32 | val      (val = end key for delete_range)
int eng_write(void* h, const uint8_t* data, uint64_t len) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  uint64_t seq = ++e->seq;
  uint64_t min_snap = e->min_live_snapshot();
  if (min_snap > seq) min_snap = seq;  // nothing older than this write is needed
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    if (end - p < 2) return -1;
    uint8_t op = *p++;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    if (end - p < 4) return -1;
    uint32_t klen = read_u32(p);
    if (end - p < klen) return -1;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    if (end - p < 4) return -1;
    uint32_t vlen = read_u32(p);
    if (end - p < vlen) return -1;
    std::string val(reinterpret_cast<const char*>(p), vlen);
    p += vlen;
    Table& t = e->cfs[cf];
    if (op == 1) {
      put_version(t, std::move(key), seq, false, std::move(val), min_snap);
    } else if (op == 2) {
      put_version(t, std::move(key), seq, true, "", min_snap);
    } else if (op == 3) {
      auto it = t.lower_bound(key);
      auto stop = t.lower_bound(val);
      for (; it != stop; ++it) {
        // the iterator already holds the chain: no per-key re-lookup
        push_version(it->second, seq, true, "", min_snap);
      }
    } else {
      return -3;
    }
  }
  return 0;
}

uint64_t eng_snapshot(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  e->snapshots.insert(e->seq);
  return e->seq;
}

void eng_release_snapshot(void* h, uint64_t seq) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  auto it = e->snapshots.find(seq);
  if (it != e->snapshots.end()) e->snapshots.erase(it);
}

// get: returns 1 + copies value if found, 0 if not, <0 on error.
// caller frees *out with eng_free.
int eng_get(void* h, int cf, const uint8_t* key, uint64_t klen,
            uint64_t snap_seq, uint8_t** out, uint64_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  auto it = t.find(std::string(reinterpret_cast<const char*>(key), klen));
  if (it == t.end()) return 0;
  const std::string* v = resolve(it->second, snap_seq);
  if (v == nullptr) return 0;
  *out = static_cast<uint8_t*>(malloc(v->size()));
  memcpy(*out, v->data(), v->size());
  *out_len = v->size();
  return 1;
}

// scan [start, end) visible at snap_seq; limit 0 = unlimited.
// Output buffer: repeated (klen u32 | key | vlen u32 | val); caller eng_free.
// Returns number of pairs, or <0 on error.
long eng_scan(void* h, int cf, uint64_t snap_seq, const uint8_t* start,
              uint64_t start_len, const uint8_t* end_key, uint64_t end_len,
              int has_end, uint64_t limit, int reverse, uint8_t** out,
              uint64_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  std::string s(reinterpret_cast<const char*>(start), start_len);
  std::string en(reinterpret_cast<const char*>(end_key), end_len);
  std::string buf;
  long n = 0;
  auto emit = [&](const std::string& k, const std::string& v) {
    append_u32(buf, static_cast<uint32_t>(k.size()));
    buf.append(k);
    append_u32(buf, static_cast<uint32_t>(v.size()));
    buf.append(v);
    n++;
  };
  if (!reverse) {
    auto it = t.lower_bound(s);
    auto stop = has_end ? t.lower_bound(en) : t.end();
    for (; it != stop && (limit == 0 || n < static_cast<long>(limit)); ++it) {
      const std::string* v = resolve(it->second, snap_seq);
      if (v != nullptr) emit(it->first, *v);
    }
  } else {
    auto it = has_end ? t.lower_bound(en) : t.end();
    auto stop = t.lower_bound(s);
    while (it != stop && (limit == 0 || n < static_cast<long>(limit))) {
      --it;
      const std::string* v = resolve(it->second, snap_seq);
      if (v != nullptr) emit(it->first, *v);
      if (it == stop) break;
    }
  }
  *out = static_cast<uint8_t*>(malloc(buf.size()));
  memcpy(*out, buf.data(), buf.size());
  *out_len = buf.size();
  return n;
}

// cursor-style seek: find first key >= target (or last key <= target when
// for_prev) within [lower, upper); returns 1 + key/value copies, else 0.
int eng_seek(void* h, int cf, uint64_t snap_seq, const uint8_t* target,
             uint64_t target_len, const uint8_t* lower, uint64_t lower_len,
             const uint8_t* upper, uint64_t upper_len, int has_upper,
             int for_prev, uint8_t** kout, uint64_t* kout_len, uint8_t** vout,
             uint64_t* vout_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  std::string tg(reinterpret_cast<const char*>(target), target_len);
  std::string lo(reinterpret_cast<const char*>(lower), lower_len);
  std::string up(reinterpret_cast<const char*>(upper), upper_len);
  if (!for_prev) {
    auto it = t.lower_bound(tg < lo ? lo : tg);
    auto stop = has_upper ? t.lower_bound(up) : t.end();
    for (; it != stop; ++it) {
      const std::string* v = resolve(it->second, snap_seq);
      if (v == nullptr) continue;
      *kout = static_cast<uint8_t*>(malloc(it->first.size()));
      memcpy(*kout, it->first.data(), it->first.size());
      *kout_len = it->first.size();
      *vout = static_cast<uint8_t*>(malloc(v->size()));
      memcpy(*vout, v->data(), v->size());
      *vout_len = v->size();
      return 1;
    }
    return 0;
  }
  // seek_for_prev: last visible key <= target within [lower, upper)
  auto it = t.upper_bound(tg);
  while (it != t.begin()) {
    --it;
    if (it->first < lo) return 0;
    if (has_upper && it->first >= up) continue;
    const std::string* v = resolve(it->second, snap_seq);
    if (v == nullptr) continue;
    *kout = static_cast<uint8_t*>(malloc(it->first.size()));
    memcpy(*kout, it->first.data(), it->first.size());
    *kout_len = it->first.size();
    *vout = static_cast<uint8_t*>(malloc(v->size()));
    memcpy(*vout, v->data(), v->size());
    *vout_len = v->size();
    return 1;
  }
  return 0;
}

void eng_free(uint8_t* p) { free(p); }

uint64_t eng_stats_keys(void* h, int cf) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->cfs[cf].size();
}

}  // extern "C"
