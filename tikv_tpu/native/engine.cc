// Native ordered multi-CF storage engine.
//
// Plays the role RocksDB plays in the reference (components/engine_rocks):
// the storage medium under the engine-trait layer.  Design is a versioned
// ordered memtable (rocksdb-memtable-like): every write carries a sequence
// number; a snapshot is just a sequence, so snapshots are O(1) and never
// copy; iterators resolve the newest version <= snapshot per key.  Obsolete
// versions are compacted away once no live snapshot can see them.
//
// Durability (engine_rocks WAL + memtable flush, raft_log_engine's purpose
// built log): when opened on a directory, every committed write batch is
// appended to a CRC-framed write-ahead log (group commit: the batch IS the
// group) and fdatasync'd before the write call returns; a checkpoint spills
// the full visible state to an SST-like immutable file via atomic
// tmp+rename, after which older WAL segments are deleted.  Open() recovers
// the newest valid checkpoint then replays WAL segments, stopping at the
// first torn record (standard WAL semantics).
//
// Exposed as a C API consumed via ctypes (no pybind11 in this image).  Scans
// return length-prefixed buffers so one FFI crossing moves a whole range.

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <shared_mutex>
#include <string>
#include <vector>

namespace {

struct Version {
  uint64_t seq;
  bool tombstone;
  std::string value;
};

// newest-first version chain per key
using Chain = std::vector<Version>;
using Table = std::map<std::string, Chain>;

constexpr int kNumCfs = 4;  // default, lock, write, raft

// crc32c (Castagnoli), table-driven — integrity check for WAL records and
// checkpoint bodies (the role rocksdb's kCRC32c block checksums play)
uint32_t crc32c_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0x82f63b78u ^ (c >> 1) : c >> 1;
      crc32c_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32c(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = crc32c_table[(crc ^ p[i]) & 0xff] ^ (crc >> 8);
  return ~crc;
}

struct Engine {
  Table cfs[kNumCfs];
  uint64_t seq = 0;
  std::multiset<uint64_t> snapshots;
  mutable std::shared_mutex mu;

  // --- durability state (empty dir => pure in-memory engine) ---
  std::string dir;        // "" = in-memory
  int wal_fd = -1;
  int sync_mode = 1;      // 0 = buffered, 1 = fdatasync per commit
  uint64_t wal_bytes = 0;         // bytes in the live WAL segment
  uint64_t wal_limit = 64ull << 20;  // auto-checkpoint threshold; 0 = manual
  uint64_t mem_bytes = 0;         // approximate key+value bytes resident
  bool failed = false;  // a WAL append failed mid-record: the log tail is
                        // torn, so further appends could shadow-lose acked
                        // writes — refuse everything (rocksdb read-only mode)

  uint64_t min_live_snapshot() const {
    return snapshots.empty() ? UINT64_MAX : *snapshots.begin();
  }
};

const std::string* resolve(const Chain& chain, uint64_t snap_seq) {
  for (const auto& v : chain) {  // newest first
    if (v.seq <= snap_seq) {
      return v.tombstone ? nullptr : &v.value;
    }
  }
  return nullptr;
}

constexpr uint64_t kVersionOverhead = 48;  // Version struct + string header
constexpr uint64_t kKeyOverhead = 80;      // map node + key string header

void push_version(Engine* e, Chain& chain, uint64_t seq, bool tomb,
                  std::string value, uint64_t min_snap) {
  e->mem_bytes += value.size() + kVersionOverhead;
  chain.insert(chain.begin(), Version{seq, tomb, std::move(value)});
  // compact: keep the newest version <= min_snap, drop everything older
  if (chain.size() > 1) {
    size_t keep = chain.size();
    for (size_t i = 0; i < chain.size(); i++) {
      if (chain[i].seq <= min_snap) {
        keep = i + 1;
        break;
      }
    }
    if (keep < chain.size()) {
      for (size_t i = keep; i < chain.size(); i++)
        e->mem_bytes -= std::min(e->mem_bytes,
                                 chain[i].value.size() + kVersionOverhead);
      chain.resize(keep);
    }
  }
}

void put_version(Engine* e, Table& t, std::string key, uint64_t seq, bool tomb,
                 std::string value, uint64_t min_snap) {
  // bulk ingestion (restore, snapshot apply, bench load) streams keys in
  // ascending order: appending past the current max is O(1) with an end
  // hint instead of a full O(log n) descent + key copy per record
  Chain* chain;
  size_t key_size = key.size();
  if (t.empty() || t.rbegin()->first < key) {
    chain = &t.emplace_hint(t.end(), std::move(key), Chain{})->second;
    e->mem_bytes += key_size + kKeyOverhead;
  } else {
    auto it = t.lower_bound(key);
    if (it != t.end() && it->first == key) {
      chain = &it->second;
    } else {
      chain = &t.emplace_hint(it, std::move(key), Chain{})->second;
      e->mem_bytes += key_size + kKeyOverhead;
    }
  }
  push_version(e, *chain, seq, tomb, std::move(value), min_snap);
}

// --- buffer helpers ---------------------------------------------------------

void append_u32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  out.append(b, 4);
}

uint32_t read_u32(const uint8_t*& p) {
  uint32_t v;
  memcpy(&v, p, 4);
  p += 4;
  return v;
}

// batch format: repeated records
//   op u8 (1=put, 2=delete, 3=delete_range, 4=ingest_sst) | cf u8 |
//   klen u32 | key | vlen u32 | val      (val = end key for delete_range;
//   for ingest_sst the key is the SST file name inside the engine dir —
//   the WAL records the *reference*, rocksdb-manifest style, and replay
//   reloads the file)

// Structural validation WITHOUT applying: a malformed batch must be
// rejected before it reaches the WAL — once fsync'd, a bad record would
// poison replay and shadow-lose every later acked write.
int validate_batch(const uint8_t* data, uint64_t len) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    if (end - p < 2) return -1;
    uint8_t op = *p++;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    // op 4 (ingest_sst) is NOT accepted from client batches: only
    // eng_ingest_sst forges it after validating the file, preserving the
    // "validated batch cannot fail to apply" invariant eng_write relies on
    if (op < 1 || op > 3) return -3;
    if (end - p < 4) return -1;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4)
      return -1;
    p += klen;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -1;
    p += vlen;
  }
  return 0;
}

// --- SST files --------------------------------------------------------------
//
// Immutable sorted ingest file (the role sst_importer's SST plays):
//   "TKST1\n" | u32 count | repeated (cf u8|klen u32|key|vlen u32|val)
//   | "KSTE" | u32 crc32c(body)
// Entries must be sorted by (cf, key).  Ingest copies the file into the
// engine dir as sst-<seq>, WAL-appends an op-4 record naming it (the
// reference, not the bytes — rocksdb's manifest AddFile shape), then loads
// it; recovery replays the op-4 record and reloads from the dir.

constexpr char kSstMagic[] = "TKST1\n";
constexpr char kSstFoot[] = "KSTE";

int load_sst_file(Engine* e, const std::string& path, uint64_t seq);

// THE one batch applier: the live write path and WAL replay both come here.
int apply_batch(Engine* e, const uint8_t* data, uint64_t len, uint64_t seq) {
  uint64_t min_snap = e->min_live_snapshot();
  if (min_snap > seq) min_snap = seq;  // nothing older than this write is needed
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    if (end - p < 2) return -1;
    uint8_t op = *p++;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    if (end - p < 4) return -1;
    uint32_t klen = read_u32(p);
    if (end - p < klen) return -1;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    if (end - p < 4) return -1;
    uint32_t vlen = read_u32(p);
    if (end - p < vlen) return -1;
    std::string val(reinterpret_cast<const char*>(p), vlen);
    p += vlen;
    Table& t = e->cfs[cf];
    if (op == 1) {
      put_version(e, t, std::move(key), seq, false, std::move(val), min_snap);
    } else if (op == 2) {
      put_version(e, t, std::move(key), seq, true, "", min_snap);
    } else if (op == 3) {
      auto it = t.lower_bound(key);
      auto stop = t.lower_bound(val);
      for (; it != stop; ++it) {
        // the iterator already holds the chain: no per-key re-lookup
        push_version(e, it->second, seq, true, "", min_snap);
      }
    } else if (op == 4) {
      std::string path = e->dir.empty() ? key : e->dir + "/" + key;
      if (load_sst_file(e, path, seq) != 0) return -6;
    } else {
      return -3;
    }
  }
  return 0;
}

// apply an already-validated SST image's entries at `seq`
int load_sst_from_buf(Engine* e, const uint8_t* data, uint64_t len, uint64_t seq) {
  if (len < 18) return -1;
  uint64_t min_snap = e->min_live_snapshot();
  if (min_snap > seq) min_snap = seq;
  const uint8_t* p = data + 10;
  const uint8_t* end = data + len - 8;
  while (p < end) {
    if (end - p < 5) return -1;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -1;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4) return -1;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -1;
    // sorted input streams through the emplace-hint fast path in put_version
    put_version(e, e->cfs[cf], std::move(key), seq, false,
                std::string(reinterpret_cast<const char*>(p), vlen), min_snap);
    p += vlen;
  }
  return 0;
}

int sst_validate(const uint8_t* data, uint64_t len);

int load_sst_file(Engine* e, const std::string& path, uint64_t seq) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 18) { fclose(f); return -1; }
  std::string buf;
  buf.resize(sz);
  bool rok = fread(&buf[0], 1, sz, f) == static_cast<size_t>(sz);
  fclose(f);
  if (!rok) return -1;
  const uint8_t* d = reinterpret_cast<const uint8_t*>(buf.data());
  if (sst_validate(d, buf.size()) != 0) return -1;
  return load_sst_from_buf(e, d, buf.size(), seq);
}

// validate an SST byte buffer without applying (used before copy-in)
int sst_validate(const uint8_t* data, uint64_t len) {
  if (len < 18) return -1;
  if (memcmp(data, kSstMagic, 6) != 0) return -1;
  if (memcmp(data + len - 8, kSstFoot, 4) != 0) return -1;
  uint32_t crc;
  memcpy(&crc, data + len - 4, 4);
  if (crc32c(data + 10, len - 18) != crc) return -1;
  // entries sorted by (cf, key)?
  const uint8_t* p = data + 10;
  const uint8_t* end = data + len - 8;
  int last_cf = -1;
  std::string last_key;
  while (p < end) {
    if (end - p < 5) return -2;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4) return -2;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -2;
    p += vlen;
    if (cf < last_cf || (cf == last_cf && key <= last_key)) return -3;
    last_cf = cf;
    last_key = std::move(key);
  }
  return 0;
}

// --- durability: WAL segments + checkpoint files ----------------------------
//
// Layout in e->dir:
//   wal-<start_seq:016x>   CRC-framed log; records carry seq > start_seq
//   ckpt-<seq:016x>        immutable full-state spill, atomic tmp+rename
//
// WAL record: u32 payload_len | u32 crc32c(seq||payload) | u64 seq | payload
// Checkpoint: "TKCK1\n" | u64 seq | repeated (cf u8|klen u32|key|vlen u32|
// val) | "KCE1" u32 crc32c(body)   — only live values spill (tombstones and
// version history die at the checkpoint boundary, like a full compaction).

constexpr char kCkptMagic[] = "TKCK1\n";
constexpr char kCkptFoot[] = "KCE1";

std::string seg_name(const char* prefix, uint64_t seq) {
  char buf[64];
  snprintf(buf, sizeof buf, "%s-%016llx", prefix,
           static_cast<unsigned long long>(seq));
  return buf;
}

bool parse_seg(const std::string& name, const char* prefix, uint64_t* seq) {
  size_t plen = strlen(prefix);
  if (name.size() != plen + 17 || name.compare(0, plen, prefix) != 0 ||
      name[plen] != '-')
    return false;
  *seq = strtoull(name.c_str() + plen + 1, nullptr, 16);
  return true;
}

void list_segs(const std::string& dir, const char* prefix,
               std::vector<uint64_t>* out) {
  DIR* d = opendir(dir.c_str());
  if (!d) return;
  struct dirent* ent;
  uint64_t seq;
  while ((ent = readdir(d)) != nullptr) {
    if (parse_seg(ent->d_name, prefix, &seq)) out->push_back(seq);
  }
  closedir(d);
  std::sort(out->begin(), out->end());
}

int fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (fd < 0) return -1;
  int r = fsync(fd);
  close(fd);
  return r;
}

int wal_open_segment(Engine* e, uint64_t start_seq) {
  if (e->wal_fd >= 0) close(e->wal_fd);
  std::string path = e->dir + "/" + seg_name("wal", start_seq);
  e->wal_fd = open(path.c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
  e->wal_bytes = 0;
  if (e->wal_fd < 0) return -1;
  fsync_dir(e->dir);  // the new segment name must survive a crash
  return 0;
}

int wal_append(Engine* e, uint64_t seq, const uint8_t* payload, uint64_t len) {
  if (e->dir.empty()) return 0;  // pure in-memory engine: no WAL
  if (e->wal_fd < 0) return -1;  // durable engine with a dead log fd
  std::string rec;
  rec.reserve(16 + len);
  append_u32(rec, static_cast<uint32_t>(len));
  uint8_t seq_le[8];
  memcpy(seq_le, &seq, 8);
  uint32_t crc = crc32c(seq_le, 8);
  crc = crc32c(payload, len, crc);
  append_u32(rec, crc);
  rec.append(reinterpret_cast<const char*>(seq_le), 8);
  rec.append(reinterpret_cast<const char*>(payload), len);
  const char* p = rec.data();
  size_t left = rec.size();
  while (left > 0) {
    ssize_t n = ::write(e->wal_fd, p, left);
    if (n <= 0) return -1;
    p += n;
    left -= n;
  }
  e->wal_bytes += rec.size();
  if (e->sync_mode == 1 && fdatasync(e->wal_fd) != 0) return -1;
  return 0;
}

// replay one WAL segment; stops cleanly at the first torn/corrupt record and
// TRUNCATES the file to its valid prefix.  Without the truncate, reopening
// the same segment with O_APPEND (eng_open_at when e->seq equals the segment
// start) would append acked records BEHIND the torn bytes — unreachable by
// every later replay, i.e. silent loss of post-recovery writes.  Returns
// non-zero when a needed truncate FAILED — the caller must not open the
// engine for writing over a segment it could not repair.
int wal_replay(Engine* e, const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return 0;
  std::string buf;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  buf.resize(sz);
  if (sz > 0 && fread(&buf[0], 1, sz, f) != static_cast<size_t>(sz)) {
    fclose(f);
    return -1;  // unreadable segment: do not trust the directory for writes
  }
  fclose(f);
  const uint8_t* base = reinterpret_cast<const uint8_t*>(buf.data());
  const uint8_t* p = base;
  const uint8_t* end = p + buf.size();
  uint64_t valid_end = buf.size();  // offset just past the last whole record
  bool torn = false;
  while (end - p >= 16) {
    const uint8_t* rec_start = p;
    uint32_t len = read_u32(p);
    uint32_t crc = read_u32(p);
    if (static_cast<uint64_t>(end - p) < 8 + static_cast<uint64_t>(len)) {
      valid_end = rec_start - base;
      torn = true;
      break;
    }
    uint64_t seq;
    memcpy(&seq, p, 8);
    uint32_t actual = crc32c(p, 8 + len);
    if (actual != crc) {  // torn tail: stop, later records unreachable
      valid_end = rec_start - base;
      torn = true;
      break;
    }
    p += 8;
    if (seq > e->seq) {  // records <= checkpoint seq are already folded in
      // CRC-valid records were individually acked (validated before the
      // append), so an apply failure skips just this record
      if (apply_batch(e, p, len, seq) == 0) e->seq = seq;
    }
    p += len;
  }
  // a partial header at the tail (loop exhausted, <16 bytes left) is torn too
  if (!torn && end - p > 0) valid_end = p - base;
  if (valid_end < static_cast<uint64_t>(sz)) {
    if (truncate(path.c_str(), static_cast<off_t>(valid_end)) != 0)
      return -1;  // unrepaired torn tail would hide acked writes appended later
  }
  return 0;
}

int ckpt_write(Engine* e) {
  // caller holds the write lock; spill everything visible at e->seq.
  // Streamed straight to the file with a chained crc32c — never a full
  // in-memory copy of the dataset (the engine already holds the data once;
  // doubling residency under the write lock is the one thing this spill
  // must not do).
  uint64_t at = e->seq;
  std::string tmp = e->dir + "/ckpt.tmp";
  std::string fin = e->dir + "/" + seg_name("ckpt", at);
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  uint64_t at_le = at;
  bool ok = fwrite(kCkptMagic, 1, 6, f) == 6 && fwrite(&at_le, 1, 8, f) == 8;
  uint32_t crc = 0;
  std::string hdr;
  for (int cf = 0; cf < kNumCfs && ok; cf++) {
    for (const auto& [key, chain] : e->cfs[cf]) {
      const std::string* v = resolve(chain, at);
      if (v == nullptr) continue;
      hdr.clear();
      hdr.push_back(static_cast<char>(cf));
      append_u32(hdr, static_cast<uint32_t>(key.size()));
      hdr.append(key);
      append_u32(hdr, static_cast<uint32_t>(v->size()));
      crc = crc32c(reinterpret_cast<const uint8_t*>(hdr.data()), hdr.size(), crc);
      crc = crc32c(reinterpret_cast<const uint8_t*>(v->data()), v->size(), crc);
      ok = fwrite(hdr.data(), 1, hdr.size(), f) == hdr.size() &&
           (v->empty() || fwrite(v->data(), 1, v->size(), f) == v->size());
      if (!ok) break;
    }
  }
  ok = ok && fwrite(kCkptFoot, 1, 4, f) == 4 && fwrite(&crc, 1, 4, f) == 4;
  ok = ok && fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), fin.c_str()) != 0) {
    unlink(tmp.c_str());
    return -1;
  }
  fsync_dir(e->dir);
  // new WAL segment BEFORE deleting the old ones: if the open fails the
  // previous log remains intact and the engine can refuse further writes
  // without having lost anything
  if (wal_open_segment(e, at) != 0) return -1;
  std::vector<uint64_t> old;
  list_segs(e->dir, "ckpt", &old);
  for (uint64_t s : old)
    if (s < at) unlink((e->dir + "/" + seg_name("ckpt", s)).c_str());
  old.clear();
  list_segs(e->dir, "wal", &old);
  for (uint64_t s : old)
    if (s < at) unlink((e->dir + "/" + seg_name("wal", s)).c_str());
  // ingested SSTs at-or-below the checkpoint are folded in: drop the files
  old.clear();
  list_segs(e->dir, "sst", &old);
  for (uint64_t s : old)
    if (s <= at) unlink((e->dir + "/" + seg_name("sst", s)).c_str());
  return 0;
}

// load the newest structurally-valid checkpoint; returns its seq (0 = none)
uint64_t ckpt_load(Engine* e) {
  std::vector<uint64_t> cks;
  list_segs(e->dir, "ckpt", &cks);
  for (auto it = cks.rbegin(); it != cks.rend(); ++it) {
    std::string path = e->dir + "/" + seg_name("ckpt", *it);
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) continue;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    if (sz < 22) { fclose(f); continue; }
    std::string buf;
    buf.resize(sz);
    bool rok = fread(&buf[0], 1, sz, f) == static_cast<size_t>(sz);
    fclose(f);
    if (!rok || buf.compare(0, 6, kCkptMagic) != 0) continue;
    if (buf.compare(sz - 8, 4, kCkptFoot) != 0) continue;
    uint32_t crc;
    memcpy(&crc, buf.data() + sz - 4, 4);
    const uint8_t* body = reinterpret_cast<const uint8_t*>(buf.data()) + 14;
    size_t body_len = sz - 22;
    if (crc32c(body, body_len) != crc) continue;
    uint64_t at;
    memcpy(&at, buf.data() + 6, 8);
    const uint8_t* p = body;
    const uint8_t* end = body + body_len;
    while (p < end) {
      uint8_t cf = *p++;
      if (cf >= kNumCfs || end - p < 4) break;
      uint32_t klen = read_u32(p);
      if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4)
        break;
      std::string key(reinterpret_cast<const char*>(p), klen);
      p += klen;
      uint32_t vlen = read_u32(p);
      if (static_cast<uint64_t>(end - p) < vlen) break;
      // checkpoints are written in cf-then-key order: O(1) hinted appends
      put_version(e, e->cfs[cf], std::move(key), at, false,
                  std::string(reinterpret_cast<const char*>(p), vlen), at);
      p += vlen;
    }
    e->seq = at;
    return at;
  }
  return 0;
}

}  // namespace

extern "C" {

void* eng_open() { return new Engine(); }

// Open (or create) a durable engine on a directory.  sync_mode: 1 = WAL
// fdatasync on every commit (crash-durable), 0 = OS-buffered (fast, loses
// the tail on power loss — still consistent via WAL framing).
void* eng_open_at(const char* path, int sync_mode) {
  Engine* e = new Engine();
  e->dir = path;
  e->sync_mode = sync_mode;
  mkdir(path, 0755);
  uint64_t ck = ckpt_load(e);
  std::vector<uint64_t> wals;
  list_segs(e->dir, "wal", &wals);
  for (uint64_t s : wals) {
    if (s < ck) continue;  // fully folded into the checkpoint
    if (wal_replay(e, e->dir + "/" + seg_name("wal", s)) != 0) {
      delete e;  // could not repair a torn segment: refuse the open
      return nullptr;
    }
  }
  // recovered WAL segments are re-folded on the next checkpoint; append to a
  // fresh segment so replay order stays strictly by start-seq
  if (wal_open_segment(e, e->seq) != 0) {
    delete e;
    return nullptr;
  }
  return e;
}

void eng_close(void* h) {
  Engine* e = static_cast<Engine*>(h);
  if (e->wal_fd >= 0) close(e->wal_fd);
  delete e;
}

int eng_write(void* h, const uint8_t* data, uint64_t len) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  if (e->failed) return -5;
  // validate BEFORE logging: a malformed batch must never reach the WAL
  int r = validate_batch(data, len);
  if (r != 0) return r;
  uint64_t seq = e->seq + 1;
  // WAL first: a batch is committed iff its record is durable (fsync'd
  // before apply, exactly rocksdb's WriteBatch-then-memtable order)
  if (wal_append(e, seq, data, len) != 0) {
    e->failed = true;
    return -4;
  }
  r = apply_batch(e, data, len, seq);
  if (r != 0) return r;  // unreachable after validate; defensive
  e->seq = seq;
  if (e->wal_limit > 0 && e->wal_bytes >= e->wal_limit && !e->dir.empty()) {
    // inline auto-spill (memtable-full flush equivalent); a failed spill
    // that lost its log fd must stop acking writes, not go silently
    // non-durable
    if (ckpt_write(e) != 0 && e->wal_fd < 0) e->failed = true;
  }
  return 0;
}

// Build an SST file at `path` from a serialized run of (cf|klen|key|vlen|val)
// records (must be sorted by (cf, key)).  Standalone: no engine handle.
int eng_build_sst(const char* path, const uint8_t* body, uint64_t len) {
  // frame it, then validate the full image (sortedness + crc round-trip)
  std::string img;
  img.reserve(18 + len);
  img.append(kSstMagic, 6);
  append_u32(img, 0);  // count unused (size-delimited records); kept for layout
  img.append(reinterpret_cast<const char*>(body), len);
  img.append(kSstFoot, 4);
  append_u32(img, crc32c(body, len));
  if (sst_validate(reinterpret_cast<const uint8_t*>(img.data()), img.size()) != 0)
    return -3;
  std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  bool ok = fwrite(img.data(), 1, img.size(), f) == img.size() &&
            fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), path) != 0) {
    unlink(tmp.c_str());
    return -1;
  }
  return 0;
}

// Ingest an external SST: validate, copy into the engine dir as sst-<seq>,
// WAL-log the op-4 reference, load.  For a pure in-memory engine the file
// is loaded in place (no copy, no WAL).
int eng_ingest_sst(void* h, const char* src_path) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  if (e->failed) return -5;
  FILE* f = fopen(src_path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 18 || sz > (1ll << 40)) {  // bounds BEFORE resize: a directory
    fclose(f);                         // fopen succeeds and ftell lies
    return -1;
  }
  std::string buf;
  buf.resize(sz);
  bool rok = fread(&buf[0], 1, sz, f) == static_cast<size_t>(sz);
  fclose(f);
  if (!rok) return -1;
  int v = sst_validate(reinterpret_cast<const uint8_t*>(buf.data()), buf.size());
  if (v != 0) return v;
  uint64_t seq = e->seq + 1;
  std::string rec_key;
  if (e->dir.empty()) {
    rec_key = src_path;  // in-memory: reference the source directly
  } else {
    rec_key = seg_name("sst", seq);
    std::string dst = e->dir + "/" + rec_key;
    std::string tmp = dst + ".tmp";
    FILE* out = fopen(tmp.c_str(), "wb");
    if (!out) return -1;
    bool ok = fwrite(buf.data(), 1, buf.size(), out) == buf.size() &&
              fflush(out) == 0 && fsync(fileno(out)) == 0;
    fclose(out);
    if (!ok || rename(tmp.c_str(), dst.c_str()) != 0) {
      unlink(tmp.c_str());
      return -1;
    }
    fsync_dir(e->dir);  // the file must exist before its WAL reference
  }
  // op-4 batch record: | op | cf | klen | name | vlen=0 |
  std::string rec;
  rec.push_back(4);
  rec.push_back(0);
  append_u32(rec, static_cast<uint32_t>(rec_key.size()));
  rec.append(rec_key);
  append_u32(rec, 0);
  const uint8_t* rp = reinterpret_cast<const uint8_t*>(rec.data());
  if (wal_append(e, seq, rp, rec.size()) != 0) {
    e->failed = true;
    return -4;
  }
  // apply straight from the validated bytes — no second read/parse of the
  // copy; WAL replay goes through apply_batch → load_sst_file instead
  int r = load_sst_from_buf(
      e, reinterpret_cast<const uint8_t*>(buf.data()), buf.size(), seq);
  if (r != 0) {
    // The WAL record for this seq is already durable; failing to apply it
    // without bumping e->seq would let the next write reuse the seq and make
    // replay silently drop the second (acked) record.  Stop acking instead.
    e->failed = true;
    return r;
  }
  e->seq = seq;
  return 0;
}

int eng_checkpoint(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  if (e->dir.empty()) return -1;
  int r = ckpt_write(e);
  if (r != 0 && e->wal_fd < 0) e->failed = true;  // log fd lost: stop acking
  return r;
}

void eng_set_wal_limit(void* h, uint64_t bytes) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  e->wal_limit = bytes;
}

// import-mode tuning (sst_importer/src/import_mode.rs): bulk loads drop to
// buffered WAL writes, then restore sync + checkpoint when done.  Returns
// non-zero if the flush that closes the unsynced window fails — in that case
// the buffered tail is NOT durable and the engine stops acking writes rather
// than promising per-commit durability it cannot deliver.
int eng_set_sync(void* h, int sync_mode) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  if (e->sync_mode == 0 && sync_mode == 1 && e->wal_fd >= 0) {
    if (fdatasync(e->wal_fd) != 0) {
      e->failed = true;
      return -4;
    }
  }
  e->sync_mode = sync_mode;
  return 0;
}

uint64_t eng_seq(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->seq;
}

uint64_t eng_mem_bytes(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->mem_bytes;
}

uint64_t eng_wal_bytes(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->wal_bytes;
}

uint64_t eng_snapshot(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  e->snapshots.insert(e->seq);
  return e->seq;
}

void eng_release_snapshot(void* h, uint64_t seq) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  auto it = e->snapshots.find(seq);
  if (it != e->snapshots.end()) e->snapshots.erase(it);
}

// get: returns 1 + copies value if found, 0 if not, <0 on error.
// caller frees *out with eng_free.
int eng_get(void* h, int cf, const uint8_t* key, uint64_t klen,
            uint64_t snap_seq, uint8_t** out, uint64_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  auto it = t.find(std::string(reinterpret_cast<const char*>(key), klen));
  if (it == t.end()) return 0;
  const std::string* v = resolve(it->second, snap_seq);
  if (v == nullptr) return 0;
  *out = static_cast<uint8_t*>(malloc(v->size()));
  memcpy(*out, v->data(), v->size());
  *out_len = v->size();
  return 1;
}

// scan [start, end) visible at snap_seq; limit 0 = unlimited.
// Output buffer: repeated (klen u32 | key | vlen u32 | val); caller eng_free.
// Returns number of pairs, or <0 on error.
long eng_scan(void* h, int cf, uint64_t snap_seq, const uint8_t* start,
              uint64_t start_len, const uint8_t* end_key, uint64_t end_len,
              int has_end, uint64_t limit, int reverse, uint8_t** out,
              uint64_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  std::string s(reinterpret_cast<const char*>(start), start_len);
  std::string en(reinterpret_cast<const char*>(end_key), end_len);
  std::string buf;
  long n = 0;
  auto emit = [&](const std::string& k, const std::string& v) {
    append_u32(buf, static_cast<uint32_t>(k.size()));
    buf.append(k);
    append_u32(buf, static_cast<uint32_t>(v.size()));
    buf.append(v);
    n++;
  };
  if (!reverse) {
    auto it = t.lower_bound(s);
    auto stop = has_end ? t.lower_bound(en) : t.end();
    for (; it != stop && (limit == 0 || n < static_cast<long>(limit)); ++it) {
      const std::string* v = resolve(it->second, snap_seq);
      if (v != nullptr) emit(it->first, *v);
    }
  } else {
    auto it = has_end ? t.lower_bound(en) : t.end();
    auto stop = t.lower_bound(s);
    while (it != stop && (limit == 0 || n < static_cast<long>(limit))) {
      --it;
      const std::string* v = resolve(it->second, snap_seq);
      if (v != nullptr) emit(it->first, *v);
      if (it == stop) break;
    }
  }
  *out = static_cast<uint8_t*>(malloc(buf.size()));
  memcpy(*out, buf.data(), buf.size());
  *out_len = buf.size();
  return n;
}

// cursor-style seek: find first key >= target (or last key <= target when
// for_prev) within [lower, upper); returns 1 + key/value copies, else 0.
int eng_seek(void* h, int cf, uint64_t snap_seq, const uint8_t* target,
             uint64_t target_len, const uint8_t* lower, uint64_t lower_len,
             const uint8_t* upper, uint64_t upper_len, int has_upper,
             int for_prev, uint8_t** kout, uint64_t* kout_len, uint8_t** vout,
             uint64_t* vout_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  std::string tg(reinterpret_cast<const char*>(target), target_len);
  std::string lo(reinterpret_cast<const char*>(lower), lower_len);
  std::string up(reinterpret_cast<const char*>(upper), upper_len);
  if (!for_prev) {
    auto it = t.lower_bound(tg < lo ? lo : tg);
    auto stop = has_upper ? t.lower_bound(up) : t.end();
    for (; it != stop; ++it) {
      const std::string* v = resolve(it->second, snap_seq);
      if (v == nullptr) continue;
      *kout = static_cast<uint8_t*>(malloc(it->first.size()));
      memcpy(*kout, it->first.data(), it->first.size());
      *kout_len = it->first.size();
      *vout = static_cast<uint8_t*>(malloc(v->size()));
      memcpy(*vout, v->data(), v->size());
      *vout_len = v->size();
      return 1;
    }
    return 0;
  }
  // seek_for_prev: last visible key <= target within [lower, upper)
  auto it = t.upper_bound(tg);
  while (it != t.begin()) {
    --it;
    if (it->first < lo) return 0;
    if (has_upper && it->first >= up) continue;
    const std::string* v = resolve(it->second, snap_seq);
    if (v == nullptr) continue;
    *kout = static_cast<uint8_t*>(malloc(it->first.size()));
    memcpy(*kout, it->first.data(), it->first.size());
    *kout_len = it->first.size();
    *vout = static_cast<uint8_t*>(malloc(v->size()));
    memcpy(*vout, v->data(), v->size());
    *vout_len = v->size();
    return 1;
  }
  return 0;
}

void eng_free(uint8_t* p) { free(p); }

uint64_t eng_stats_keys(void* h, int cf) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->cfs[cf].size();
}

// --- compaction -------------------------------------------------------------
//
// The write path only trims a key's version chain when that key is written
// again; deleted-and-never-touched keys would otherwise hold a tombstone
// forever (rocksdb removes them in background compaction).  One compaction
// step walks at most max_keys keys of one CF under the write lock, drops
// versions no live snapshot can see, and physically erases keys whose
// newest reachable state is a tombstone.  The caller (a Python driver
// thread — the GIL is released during the call, so it is genuinely
// background work) resumes from *resume to bound write-lock hold times,
// exactly the slice-by-slice shape of rocksdb's per-file compactions.
//
// Returns versions dropped (erased keys count their whole chain); sets
// *done=1 when the CF is exhausted, else *resume/*resume_len (caller
// eng_free) is the key to continue from.
long eng_compact_step(void* h, int cf, const uint8_t* from, uint64_t from_len,
                      uint64_t max_keys, uint8_t** resume,
                      uint64_t* resume_len, int* done) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::unique_lock lk(e->mu);
  Table& t = e->cfs[cf];
  uint64_t min_snap = std::min(e->min_live_snapshot(), e->seq);
  long dropped = 0;
  uint64_t seen = 0;
  auto it = t.lower_bound(std::string(reinterpret_cast<const char*>(from), from_len));
  while (it != t.end() && seen < max_keys) {
    Chain& chain = it->second;
    // trim: keep versions newer than min_snap plus the newest one <= min_snap
    size_t keep = chain.size();
    for (size_t i = 0; i < chain.size(); i++) {
      if (chain[i].seq <= min_snap) {
        keep = i + 1;
        break;
      }
    }
    for (size_t i = keep; i < chain.size(); i++) {
      e->mem_bytes -= std::min(e->mem_bytes,
                               chain[i].value.size() + kVersionOverhead);
      dropped++;
    }
    chain.resize(keep);
    // erase: the newest version overall is a tombstone no snapshot can miss
    if (!chain.empty() && chain.front().tombstone &&
        chain.front().seq <= min_snap) {
      dropped += static_cast<long>(chain.size());
      uint64_t key_cost = it->first.size() + kKeyOverhead;
      for (const auto& v : chain)
        key_cost += v.value.size() + kVersionOverhead;
      e->mem_bytes -= std::min(e->mem_bytes, key_cost);
      it = t.erase(it);
    } else {
      ++it;
    }
    seen++;
  }
  if (it == t.end()) {
    *done = 1;
  } else {
    *done = 0;
    *resume = static_cast<uint8_t*>(malloc(it->first.size()));
    memcpy(*resume, it->first.data(), it->first.size());
    *resume_len = it->first.size();
  }
  return dropped;
}

// --- MVCC range properties --------------------------------------------------
//
// The role of engine_rocks' MvccPropertiesCollector (properties.rs): cheap
// per-range statistics that tell GC whether a sweep is worth it at all.
// The collector knows this framework's CF_WRITE shape — keys carry an
// 8-byte descending-encoded commit_ts suffix, values start with the write
// type byte ('P'ut/'D'elete/'L'ock/'R'ollback).
//
// out[0]=num_entries  out[1]=num_rows (distinct user keys)
// out[2]=num_puts     out[3]=num_deletes
// out[4]=num_locks_rollbacks       out[5]=min_commit_ts  out[6]=max_commit_ts
// out[7]=max_row_versions (worst per-key version count)
int eng_mvcc_props(void* h, int cf, const uint8_t* start, uint64_t start_len,
                   const uint8_t* end_key, uint64_t end_len, int has_end,
                   uint64_t snap_seq, uint64_t* out) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  const Table& t = e->cfs[cf];
  std::string s(reinterpret_cast<const char*>(start), start_len);
  std::string en(reinterpret_cast<const char*>(end_key), end_len);
  uint64_t entries = 0, rows = 0, puts = 0, dels = 0, other = 0;
  uint64_t min_ts = UINT64_MAX, max_ts = 0, max_row = 0, cur_row = 0;
  std::string cur_user;
  bool have_user = false;
  auto it = t.lower_bound(s);
  auto stop = has_end ? t.lower_bound(en) : t.end();
  for (; it != stop; ++it) {
    const std::string* v = resolve(it->second, snap_seq);
    if (v == nullptr) continue;
    entries++;
    const std::string& k = it->first;
    if (k.size() >= 8) {
      // commit_ts rides the last 8 key bytes, bit-inverted big-endian
      uint64_t ts = 0;
      for (int i = 0; i < 8; i++)
        ts = (ts << 8) | static_cast<uint8_t>(~k[k.size() - 8 + i]);
      if (ts < min_ts) min_ts = ts;
      if (ts > max_ts) max_ts = ts;
      std::string user = k.substr(0, k.size() - 8);
      if (!have_user || user != cur_user) {
        rows++;
        cur_user = std::move(user);
        have_user = true;
        cur_row = 0;
      }
      cur_row++;
      if (cur_row > max_row) max_row = cur_row;
    }
    if (!v->empty()) {
      char wt = (*v)[0];
      if (wt == 'P') puts++;
      else if (wt == 'D') dels++;
      else other++;
    }
  }
  out[0] = entries;
  out[1] = rows;
  out[2] = puts;
  out[3] = dels;
  out[4] = other;
  out[5] = min_ts == UINT64_MAX ? 0 : min_ts;
  out[6] = max_ts;
  out[7] = max_row;
  return 0;
}

}  // extern "C"
