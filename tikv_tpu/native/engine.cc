// Native ordered multi-CF storage engine.
//
// Plays the role RocksDB plays in the reference (components/engine_rocks):
// the storage medium under the engine-trait layer.  Design is a versioned
// ordered memtable (rocksdb-memtable-like): every write carries a sequence
// number; a snapshot is just a sequence, so snapshots are O(1) and never
// copy; iterators resolve the newest version <= snapshot per key.  Obsolete
// versions are compacted away once no live snapshot can see them.
//
// Durability (engine_rocks WAL + memtable flush, raft_log_engine's purpose
// built log): when opened on a directory, every committed write batch is
// appended to a CRC-framed write-ahead log (group commit: the batch IS the
// group) and fdatasync'd before the write call returns; a checkpoint spills
// the full visible state to an SST-like immutable file via atomic
// tmp+rename, after which older WAL segments are deleted.  Open() recovers
// the newest valid checkpoint then replays WAL segments, stopping at the
// first torn record (standard WAL semantics).
//
// Exposed as a C API consumed via ctypes (no pybind11 in this image).  Scans
// return length-prefixed buffers so one FFI crossing moves a whole range.

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <shared_mutex>
#include <string>
#include <vector>

#include "crypt.h"

namespace {

struct Version {
  uint64_t seq;
  bool tombstone;
  std::string value;
};

// newest-first version chain per key
using Chain = std::vector<Version>;
using Table = std::map<std::string, Chain>;

constexpr int kNumCfs = 4;  // default, lock, write, raft

// crc32c (Castagnoli), table-driven — integrity check for WAL records and
// checkpoint bodies (the role rocksdb's kCRC32c block checksums play)
uint32_t crc32c_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0x82f63b78u ^ (c >> 1) : c >> 1;
      crc32c_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32c(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = crc32c_table[(crc ^ p[i]) & 0xff] ^ (crc >> 8);
  return ~crc;
}

// a range delete as a first-class record (rocksdb DeleteRange shape): keys
// in [start, end) with version seq < this are masked.  Lives in the
// memtable's side list until flushed into a run; dies at a bottom-level
// merge once no snapshot can see below it.  Never expanded into per-key
// tombstones — a range delete is O(1) on the write path regardless of how
// many flushed keys it covers.
struct RangeTomb {
  std::string start, end;  // end exclusive
  uint64_t seq = 0;
};

// newest covering range-tombstone seq <= snap for `key`, 0 if none
uint64_t rtomb_covering(const std::vector<RangeTomb>& v, const std::string& key,
                        uint64_t snap) {
  uint64_t best = 0;
  for (const auto& rt : v)
    if (rt.seq <= snap && rt.seq > best && rt.start <= key && key < rt.end)
      best = rt.seq;
  return best;
}

// one immutable sorted-run file on disk (the LSM level structure rocksdb's
// SSTs provide, engine_rocks/src/ + properties.rs): block-partitioned sorted
// (key, seq, tomb, value) entries with a first-key block index and a bloom
// filter, loaded at open; data blocks pread on demand (OS page cache is the
// block cache)
struct Run {
  std::string path;
  int fd = -1;
  int cf = 0;
  int kind = 0;  // 0 = memtable flush, 1 = full-cf merge output
  uint64_t max_seq = 0;   // every version in this run has seq <= max_seq
  uint64_t n_entries = 0;
  struct Block {
    uint64_t off;
    uint32_t len;
    uint32_t crc;
    std::string first_key;
  };
  std::vector<Block> blocks;
  std::vector<uint64_t> bloom;  // bit words; empty = no filter
  uint32_t bloom_k = 0;
  std::vector<RangeTomb> rtombs;  // range deletes flushed with this run
  enc::FileKey fk;                // per-file encryption (sidecar-derived)
  ~Run() { if (fd >= 0) close(fd); }
};

// per-read statistics (engine_rocks/src/perf_context.rs role)
struct Perf {
  std::atomic<uint64_t> gets{0};
  std::atomic<uint64_t> memtable_hits{0};
  std::atomic<uint64_t> run_probes{0};   // run consulted for a point read
  std::atomic<uint64_t> bloom_skips{0};  // run skipped by its bloom filter
  std::atomic<uint64_t> blocks_read{0};  // data blocks pread + crc-checked
  std::atomic<uint64_t> flushes{0};
  std::atomic<uint64_t> run_merges{0};
};

struct Engine {
  Table cfs[kNumCfs];
  uint64_t seq = 0;
  std::multiset<uint64_t> snapshots;
  mutable std::shared_mutex mu;
  // Writer serialization, SEPARATE from mu: the WAL append + fdatasync —
  // the slow part of every commit — runs under write_mu only, so readers
  // (shared mu) never stall behind a disk sync; mu is then taken unique
  // just for the in-memory apply + seq publish.  Lock order: write_mu
  // before mu, always.  WAL state (wal_fd/sync_mode/failed) is guarded by
  // write_mu; memtables/runs/seq/snapshots stay under mu.
  std::mutex write_mu;
  // sorted runs per CF, NEWEST FIRST: all versions in runs[cf][i] are newer
  // than any in runs[cf][i+1], and the memtable is newer than every run
  std::vector<std::shared_ptr<Run>> runs[kNumCfs];
  std::vector<RangeTomb> mem_rtombs[kNumCfs];  // unflushed range deletes
  uint64_t flushed_seq = 0;          // all state <= this lives in runs
  uint64_t mem_limit = 256ull << 20; // memtable flush threshold; 0 = manual
  std::mutex compact_mu;             // one run-merge at a time
  Perf perf;

  // --- durability state (empty dir => pure in-memory engine) ---
  std::string dir;        // "" = in-memory
  int wal_fd = -1;
  int sync_mode = 1;      // 0 = buffered, 1 = fdatasync per commit
  uint64_t wal_bytes = 0;         // bytes in the live WAL segment
  uint64_t wal_off = 0;           // absolute file offset (encryption stream)
  // data keys (fed by the DataKeyManager FFI).  Guarded by enc_mu: rotation
  // runs concurrently with background compaction's writer setup
  enc::State enc;
  mutable std::mutex enc_mu;
  enc::FileKey wal_key;           // live WAL segment's file key

  enc::State enc_snapshot() const {
    std::lock_guard<std::mutex> lk(enc_mu);
    return enc;
  }
  uint64_t wal_limit = 64ull << 20;  // auto-checkpoint threshold; 0 = manual
  uint64_t mem_bytes = 0;         // approximate key+value bytes resident
  bool failed = false;  // a WAL append failed mid-record: the log tail is
                        // torn, so further appends could shadow-lose acked
                        // writes — refuse everything (rocksdb read-only mode)

  uint64_t min_live_snapshot() const {
    return snapshots.empty() ? UINT64_MAX : *snapshots.begin();
  }
};

// tri-state resolve: MISS means "no version visible here, consult older
// sources (runs)"; TOMB stops the lookup (the delete masks older sources).
// out_seq carries the hit's version so callers can test range-tombstone
// masking (a range delete at a later seq covers the value).
enum class Res { MISS, HIT, TOMB };

Res resolve3(const Chain& chain, uint64_t snap_seq, const std::string** out,
             uint64_t* out_seq) {
  for (const auto& v : chain) {
    if (v.seq <= snap_seq) {
      if (v.tombstone) return Res::TOMB;
      *out = &v.value;
      *out_seq = v.seq;
      return Res::HIT;
    }
  }
  return Res::MISS;
}

constexpr uint64_t kVersionOverhead = 48;  // Version struct + string header
constexpr uint64_t kKeyOverhead = 80;      // map node + key string header

void push_version(Engine* e, Chain& chain, uint64_t seq, bool tomb,
                  std::string value, uint64_t min_snap) {
  e->mem_bytes += value.size() + kVersionOverhead;
  chain.insert(chain.begin(), Version{seq, tomb, std::move(value)});
  // compact: keep the newest version <= min_snap, drop everything older
  if (chain.size() > 1) {
    size_t keep = chain.size();
    for (size_t i = 0; i < chain.size(); i++) {
      if (chain[i].seq <= min_snap) {
        keep = i + 1;
        break;
      }
    }
    if (keep < chain.size()) {
      for (size_t i = keep; i < chain.size(); i++)
        e->mem_bytes -= std::min(e->mem_bytes,
                                 chain[i].value.size() + kVersionOverhead);
      chain.resize(keep);
    }
  }
}

void put_version(Engine* e, Table& t, std::string key, uint64_t seq, bool tomb,
                 std::string value, uint64_t min_snap) {
  // bulk ingestion (restore, snapshot apply, bench load) streams keys in
  // ascending order: appending past the current max is O(1) with an end
  // hint instead of a full O(log n) descent + key copy per record
  Chain* chain;
  size_t key_size = key.size();
  if (t.empty() || t.rbegin()->first < key) {
    chain = &t.emplace_hint(t.end(), std::move(key), Chain{})->second;
    e->mem_bytes += key_size + kKeyOverhead;
  } else {
    auto it = t.lower_bound(key);
    if (it != t.end() && it->first == key) {
      chain = &it->second;
    } else {
      chain = &t.emplace_hint(it, std::move(key), Chain{})->second;
      e->mem_bytes += key_size + kKeyOverhead;
    }
  }
  push_version(e, *chain, seq, tomb, std::move(value), min_snap);
}

// --- buffer helpers ---------------------------------------------------------

void append_u32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  out.append(b, 4);
}

uint32_t read_u32(const uint8_t*& p) {
  uint32_t v;
  memcpy(&v, p, 4);
  p += 4;
  return v;
}

// batch format: repeated records
//   op u8 (1=put, 2=delete, 3=delete_range, 4=ingest_sst) | cf u8 |
//   klen u32 | key | vlen u32 | val      (val = end key for delete_range;
//   for ingest_sst the key is the SST file name inside the engine dir —
//   the WAL records the *reference*, rocksdb-manifest style, and replay
//   reloads the file)

// Structural validation WITHOUT applying: a malformed batch must be
// rejected before it reaches the WAL — once fsync'd, a bad record would
// poison replay and shadow-lose every later acked write.
int validate_batch(const uint8_t* data, uint64_t len) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    if (end - p < 2) return -1;
    uint8_t op = *p++;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    // op 4 (ingest_sst) is NOT accepted from client batches: only
    // eng_ingest_sst forges it after validating the file, preserving the
    // "validated batch cannot fail to apply" invariant eng_write relies on
    if (op < 1 || op > 3) return -3;
    if (end - p < 4) return -1;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4)
      return -1;
    p += klen;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -1;
    p += vlen;
  }
  return 0;
}

// --- SST files --------------------------------------------------------------
//
// Immutable sorted ingest file (the role sst_importer's SST plays):
//   "TKST1\n" | u32 count | repeated (cf u8|klen u32|key|vlen u32|val)
//   | "KSTE" | u32 crc32c(body)
// Entries must be sorted by (cf, key).  Ingest copies the file into the
// engine dir as sst-<seq>, WAL-appends an op-4 record naming it (the
// reference, not the bytes — rocksdb's manifest AddFile shape), then loads
// it; recovery replays the op-4 record and reloads from the dir.

constexpr char kSstMagic[] = "TKST1\n";
constexpr char kSstFoot[] = "KSTE";

int load_sst_file(Engine* e, const std::string& path, uint64_t seq);

// THE one batch applier: the live write path and WAL replay both come here.
int apply_batch(Engine* e, const uint8_t* data, uint64_t len, uint64_t seq) {
  uint64_t min_snap = e->min_live_snapshot();
  if (min_snap > seq) min_snap = seq;  // nothing older than this write is needed
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    if (end - p < 2) return -1;
    uint8_t op = *p++;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    if (end - p < 4) return -1;
    uint32_t klen = read_u32(p);
    if (end - p < klen) return -1;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    if (end - p < 4) return -1;
    uint32_t vlen = read_u32(p);
    if (end - p < vlen) return -1;
    std::string val(reinterpret_cast<const char*>(p), vlen);
    p += vlen;
    Table& t = e->cfs[cf];
    if (op == 1) {
      put_version(e, t, std::move(key), seq, false, std::move(val), min_snap);
    } else if (op == 2) {
      put_version(e, t, std::move(key), seq, true, "", min_snap);
    } else if (op == 3) {
      // range delete: O(1) on the write path no matter how many keys —
      // memtable and flushed alike — it covers.  Masking happens at read /
      // merge time (ties: a range delete at the same seq as a put in one
      // batch wins, matching per-key tombstone ordering)
      if (key < val) {
        e->mem_bytes += key.size() + val.size() + kVersionOverhead;
        e->mem_rtombs[cf].push_back(RangeTomb{std::move(key), std::move(val), seq});
      }
    } else if (op == 4) {
      std::string path = e->dir.empty() ? key : e->dir + "/" + key;
      if (load_sst_file(e, path, seq) != 0) return -6;
    } else {
      return -3;
    }
  }
  return 0;
}

// apply an already-validated SST image's entries at `seq`
int load_sst_from_buf(Engine* e, const uint8_t* data, uint64_t len, uint64_t seq) {
  if (len < 18) return -1;
  uint64_t min_snap = e->min_live_snapshot();
  if (min_snap > seq) min_snap = seq;
  const uint8_t* p = data + 10;
  const uint8_t* end = data + len - 8;
  while (p < end) {
    if (end - p < 5) return -1;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -1;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4) return -1;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -1;
    // sorted input streams through the emplace-hint fast path in put_version
    put_version(e, e->cfs[cf], std::move(key), seq, false,
                std::string(reinterpret_cast<const char*>(p), vlen), min_snap);
    p += vlen;
  }
  return 0;
}

int sst_validate(const uint8_t* data, uint64_t len);

int load_sst_file(Engine* e, const std::string& path, uint64_t seq) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 18) { fclose(f); return -1; }
  std::string buf;
  buf.resize(sz);
  bool rok = fread(&buf[0], 1, sz, f) == static_cast<size_t>(sz);
  fclose(f);
  if (!rok) return -1;
  const uint8_t* d = reinterpret_cast<const uint8_t*>(buf.data());
  if (sst_validate(d, buf.size()) != 0) return -1;
  return load_sst_from_buf(e, d, buf.size(), seq);
}

// validate an SST byte buffer without applying (used before copy-in)
int sst_validate(const uint8_t* data, uint64_t len) {
  if (len < 18) return -1;
  if (memcmp(data, kSstMagic, 6) != 0) return -1;
  if (memcmp(data + len - 8, kSstFoot, 4) != 0) return -1;
  uint32_t crc;
  memcpy(&crc, data + len - 4, 4);
  if (crc32c(data + 10, len - 18) != crc) return -1;
  // entries sorted by (cf, key)?
  const uint8_t* p = data + 10;
  const uint8_t* end = data + len - 8;
  int last_cf = -1;
  std::string last_key;
  while (p < end) {
    if (end - p < 5) return -2;
    uint8_t cf = *p++;
    if (cf >= kNumCfs) return -2;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4) return -2;
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -2;
    p += vlen;
    if (cf < last_cf || (cf == last_cf && key <= last_key)) return -3;
    last_cf = cf;
    last_key = std::move(key);
  }
  return 0;
}

// --- durability: WAL segments + checkpoint files ----------------------------
//
// Layout in e->dir:
//   wal-<start_seq:016x>   CRC-framed log; records carry seq > start_seq
//   ckpt-<seq:016x>        immutable full-state spill, atomic tmp+rename
//
// WAL record: u32 payload_len | u32 crc32c(seq||payload) | u64 seq | payload
// Checkpoint: "TKCK1\n" | u64 seq | repeated (cf u8|klen u32|key|vlen u32|
// val) | "KCE1" u32 crc32c(body)   — only live values spill (tombstones and
// version history die at the checkpoint boundary, like a full compaction).

constexpr char kCkptMagic[] = "TKCK1\n";
constexpr char kCkptFoot[] = "KCE1";

std::string seg_name(const char* prefix, uint64_t seq) {
  char buf[64];
  snprintf(buf, sizeof buf, "%s-%016llx", prefix,
           static_cast<unsigned long long>(seq));
  return buf;
}

bool parse_seg(const std::string& name, const char* prefix, uint64_t* seq) {
  size_t plen = strlen(prefix);
  if (name.size() != plen + 17 || name.compare(0, plen, prefix) != 0 ||
      name[plen] != '-')
    return false;
  *seq = strtoull(name.c_str() + plen + 1, nullptr, 16);
  return true;
}

void list_segs(const std::string& dir, const char* prefix,
               std::vector<uint64_t>* out) {
  DIR* d = opendir(dir.c_str());
  if (!d) return;
  struct dirent* ent;
  uint64_t seq;
  while ((ent = readdir(d)) != nullptr) {
    if (parse_seg(ent->d_name, prefix, &seq)) out->push_back(seq);
  }
  closedir(d);
  std::sort(out->begin(), out->end());
}

// data files and their encryption sidecars leave together
void unlink_with_sidecar(const std::string& path) {
  unlink(path.c_str());
  unlink(enc::sidecar_path(path).c_str());
}

int fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (fd < 0) return -1;
  int r = fsync(fd);
  close(fd);
  return r;
}

int wal_open_segment(Engine* e, uint64_t start_seq) {
  if (e->wal_fd >= 0) close(e->wal_fd);
  e->wal_fd = -1;  // callers latch `failed` on wal_fd < 0: no stale fd here
  std::string path = e->dir + "/" + seg_name("wal", start_seq);
  bool existed = access(path.c_str(), F_OK) == 0;
  enc::State est = e->enc_snapshot();
  if (existed) {
    // reopening a recovered segment for append: its cipher identity is
    // whatever it was written with (plaintext when the sidecar is absent —
    // encryption then starts at the next rotation)
    if (enc::sidecar_read(est, path, &e->wal_key) < 0) return -1;
  } else if (est.on) {
    // sidecar persists (fsynced) BEFORE the segment becomes visible
    if (enc::file_begin(est, path, &e->wal_key) != 0) return -1;
  } else {
    e->wal_key.on = false;
  }
  e->wal_fd = open(path.c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
  e->wal_bytes = 0;
  if (e->wal_fd < 0) return -1;
  off_t sz = lseek(e->wal_fd, 0, SEEK_END);
  e->wal_off = sz < 0 ? 0 : static_cast<uint64_t>(sz);
  fsync_dir(e->dir);  // the new segment name must survive a crash
  return 0;
}

int wal_append(Engine* e, uint64_t seq, const uint8_t* payload, uint64_t len) {
  if (e->dir.empty()) return 0;  // pure in-memory engine: no WAL
  if (e->wal_fd < 0) return -1;  // durable engine with a dead log fd
  std::string rec;
  rec.reserve(16 + len);
  append_u32(rec, static_cast<uint32_t>(len));
  uint8_t seq_le[8];
  memcpy(seq_le, &seq, 8);
  uint32_t crc = crc32c(seq_le, 8);
  crc = crc32c(payload, len, crc);
  append_u32(rec, crc);
  rec.append(reinterpret_cast<const char*>(seq_le), 8);
  rec.append(reinterpret_cast<const char*>(payload), len);
  enc::maybe_xor(e->wal_key, e->wal_off, &rec[0], rec.size());
  const char* p = rec.data();
  size_t left = rec.size();
  while (left > 0) {
    ssize_t n = ::write(e->wal_fd, p, left);
    if (n <= 0) return -1;
    p += n;
    left -= n;
  }
  e->wal_bytes += rec.size();
  e->wal_off += rec.size();
  if (e->sync_mode == 1 && fdatasync(e->wal_fd) != 0) return -1;
  return 0;
}

// replay one WAL segment; stops cleanly at the first torn/corrupt record and
// TRUNCATES the file to its valid prefix.  Without the truncate, reopening
// the same segment with O_APPEND (eng_open_at when e->seq equals the segment
// start) would append acked records BEHIND the torn bytes — unreachable by
// every later replay, i.e. silent loss of post-recovery writes.  Returns
// non-zero when a needed truncate FAILED — the caller must not open the
// engine for writing over a segment it could not repair.
int wal_replay(Engine* e, const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return 0;
  std::string buf;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  buf.resize(sz);
  if (sz > 0 && fread(&buf[0], 1, sz, f) != static_cast<size_t>(sz)) {
    fclose(f);
    return -1;  // unreadable segment: do not trust the directory for writes
  }
  fclose(f);
  enc::FileKey fk;
  if (enc::sidecar_read(e->enc_snapshot(), path, &fk) < 0) return -1;
  if (sz > 0) enc::maybe_xor(fk, 0, &buf[0], buf.size());
  const uint8_t* base = reinterpret_cast<const uint8_t*>(buf.data());
  const uint8_t* p = base;
  const uint8_t* end = p + buf.size();
  uint64_t valid_end = buf.size();  // offset just past the last whole record
  bool torn = false;
  while (end - p >= 16) {
    const uint8_t* rec_start = p;
    uint32_t len = read_u32(p);
    uint32_t crc = read_u32(p);
    if (static_cast<uint64_t>(end - p) < 8 + static_cast<uint64_t>(len)) {
      valid_end = rec_start - base;
      torn = true;
      break;
    }
    uint64_t seq;
    memcpy(&seq, p, 8);
    uint32_t actual = crc32c(p, 8 + len);
    if (actual != crc) {  // torn tail: stop, later records unreachable
      valid_end = rec_start - base;
      torn = true;
      break;
    }
    p += 8;
    if (seq > e->seq) {  // records <= checkpoint seq are already folded in
      // CRC-valid records were individually acked (validated before the
      // append), so an apply failure skips just this record
      if (apply_batch(e, p, len, seq) == 0) e->seq = seq;
    }
    p += len;
  }
  // a partial header at the tail (loop exhausted, <16 bytes left) is torn too
  if (!torn && end - p > 0) valid_end = p - base;
  if (valid_end < static_cast<uint64_t>(sz)) {
    if (truncate(path.c_str(), static_cast<off_t>(valid_end)) != 0)
      return -1;  // unrepaired torn tail would hide acked writes appended later
  }
  return 0;
}

// --- LSM sorted runs --------------------------------------------------------
//
// run<cf>-<max_seq:016x>: immutable sorted run flushed from the memtable (or
// produced by a merge).  Layout:
//   "TKRN2\n" | u8 cf | u8 kind (0 flush, 1 merged) | u64 max_seq
//   data blocks: repeated (klen u32 | key | seq u64 | tomb u8 | vlen u32 | val)
//   index: u32 n_blocks | per block (off u64 | len u32 | crc u32 |
//          first_klen u32 | first_key)
//   bloom: u64 n_bits | u32 k | u32 pad | words u64[]
//   rtombs: u32 count | per rt (slen u32 | start | elen u32 | end | seq u64)
//   footer: u64 index_off | u64 bloom_off | u64 n_entries |
//           u32 crc32c(index..rtombs) | "TKRE"
// Entries are sorted by key; a key's versions are adjacent, newest first.
// Tombstones (point and range alike) are real entries: they mask older runs
// and die only when a merge reaches the oldest run.

constexpr char kRunMagic[] = "TKRN2\n";
constexpr char kRunFoot[] = "TKRE";
constexpr size_t kRunBlockTarget = 32 << 10;

const char* run_prefix(int cf) {
  static const char* names[kNumCfs] = {"run0", "run1", "run2", "run3"};
  return names[cf];
}

uint64_t hash64(const uint8_t* p, size_t n, uint64_t seed) {
  uint64_t h = 1469598103934665603ull ^ seed;
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  return h;
}

struct RunWriter {
  FILE* f = nullptr;
  std::string tmp, fin;
  uint64_t off = 0;
  enc::FileKey fk;
  uint64_t n_entries = 0;
  std::string block;
  std::string block_first;
  std::vector<Run::Block> index;
  std::vector<uint64_t> key_hashes;  // one per distinct key
  std::string last_key;
  std::vector<RangeTomb> rtombs;  // set before finish(); written after bloom
  bool ok = true;

  // encrypt-then-write at the current offset (no-op when encryption is off)
  bool wr(const void* data, size_t len) {
    if (!fk.on) return fwrite(data, 1, len, f) == len;
    std::string tmpbuf(static_cast<const char*>(data), len);
    enc::maybe_xor(fk, off, &tmpbuf[0], len);
    return fwrite(tmpbuf.data(), 1, len, f) == len;
  }

  int open(const std::string& dir, const enc::State& est, int cf,
           uint64_t max_seq, int kind) {
    fin = dir + "/" + seg_name(run_prefix(cf), max_seq);
    // the sidecar for the FINAL name is durable before finish() renames the
    // data file into visibility — an encrypted run can never appear without
    // its metadata.  (Final names are unique per directory lifetime, see
    // below, so a sidecar never describes two generations of a file.)
    if (enc::file_begin(est, fin, &fk) != 0) return -1;
    // a flush (under the engine lock) and a merge (without it) may write
    // concurrently: the temp name must be private to this writer.  Final
    // names never collide — a flush's max_seq is the current seq, a merge
    // reuses its newest input's (older) name — so fin-derived is unique.
    tmp = fin + (kind == 1 ? ".mrg.tmp" : ".tmp");
    f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    setvbuf(f, nullptr, _IOFBF, 1 << 20);
    std::string hdr(kRunMagic, 6);
    hdr.push_back(static_cast<char>(cf));
    hdr.push_back(static_cast<char>(kind));
    hdr.append(reinterpret_cast<const char*>(&max_seq), 8);
    off = 0;
    ok = wr(hdr.data(), hdr.size());
    off = hdr.size();
    return ok ? 0 : -1;
  }

  void flush_block() {
    if (block.empty()) return;
    Run::Block b;
    b.off = off;
    b.len = static_cast<uint32_t>(block.size());
    b.crc = crc32c(reinterpret_cast<const uint8_t*>(block.data()), block.size());
    b.first_key = block_first;
    ok = ok && wr(block.data(), block.size());
    off += block.size();
    index.push_back(std::move(b));
    block.clear();
  }

  void add(const std::string& key, uint64_t seq, bool tomb, const std::string& val) {
    if (block.empty()) block_first = key;
    append_u32(block, static_cast<uint32_t>(key.size()));
    block.append(key);
    block.append(reinterpret_cast<const char*>(&seq), 8);
    block.push_back(tomb ? 1 : 0);
    append_u32(block, static_cast<uint32_t>(val.size()));
    block.append(val);
    n_entries++;
    if (key != last_key) {
      key_hashes.push_back(
          hash64(reinterpret_cast<const uint8_t*>(key.data()), key.size(), 0));
      last_key = key;
    }
    // never split one key's version group across blocks: close only when the
    // NEXT key starts (callers add all versions of a key consecutively), so
    // flush at add() time happens on key boundaries via maybe_rotate()
  }

  void maybe_rotate(const std::string& next_key) {
    if (block.size() >= kRunBlockTarget && next_key != last_key) flush_block();
  }

  // returns a loaded Run (fd open) or nullptr
  std::shared_ptr<Run> finish(int cf, uint64_t max_seq, int kind = 0) {
    flush_block();
    auto run = std::make_shared<Run>();
    run->cf = cf;
    run->kind = kind;
    run->max_seq = max_seq;
    run->n_entries = n_entries;
    run->path = fin;
    // index section
    std::string sec;
    uint64_t index_off = off;
    append_u32(sec, static_cast<uint32_t>(index.size()));
    for (const auto& b : index) {
      sec.append(reinterpret_cast<const char*>(&b.off), 8);
      append_u32(sec, b.len);
      append_u32(sec, b.crc);
      append_u32(sec, static_cast<uint32_t>(b.first_key.size()));
      sec.append(b.first_key);
    }
    // bloom section (10 bits/key, 6 probes)
    uint64_t n_bits = key_hashes.empty() ? 64 : key_hashes.size() * 10;
    n_bits = (n_bits + 63) / 64 * 64;
    std::vector<uint64_t> bloom(n_bits / 64, 0);
    uint32_t k = 6;
    for (uint64_t h : key_hashes) {
      uint64_t h2 = h * 0x9e3779b97f4a7c15ull + 1;
      for (uint32_t i = 0; i < k; i++) {
        uint64_t bit = (h + i * h2) % n_bits;
        bloom[bit / 64] |= 1ull << (bit % 64);
      }
    }
    uint64_t bloom_off = index_off + sec.size();
    sec.append(reinterpret_cast<const char*>(&n_bits), 8);
    append_u32(sec, k);
    append_u32(sec, 0);
    sec.append(reinterpret_cast<const char*>(bloom.data()), bloom.size() * 8);
    // range-tombstone section
    append_u32(sec, static_cast<uint32_t>(rtombs.size()));
    for (const auto& rt : rtombs) {
      append_u32(sec, static_cast<uint32_t>(rt.start.size()));
      sec.append(rt.start);
      append_u32(sec, static_cast<uint32_t>(rt.end.size()));
      sec.append(rt.end);
      sec.append(reinterpret_cast<const char*>(&rt.seq), 8);
    }
    uint32_t sec_crc = crc32c(reinterpret_cast<const uint8_t*>(sec.data()), sec.size());
    std::string foot;
    foot.append(reinterpret_cast<const char*>(&index_off), 8);
    foot.append(reinterpret_cast<const char*>(&bloom_off), 8);
    foot.append(reinterpret_cast<const char*>(&n_entries), 8);
    append_u32(foot, sec_crc);
    foot.append(kRunFoot, 4);
    bool w1 = wr(sec.data(), sec.size());
    off += sec.size();
    bool w2 = wr(foot.data(), foot.size());
    off += foot.size();
    ok = ok && w1 && w2 && fflush(f) == 0 && fsync(fileno(f)) == 0;
    fclose(f);
    f = nullptr;
    if (!ok || rename(tmp.c_str(), fin.c_str()) != 0) {
      unlink(tmp.c_str());
      return nullptr;
    }
    run->blocks = std::move(index);
    run->bloom = std::move(bloom);
    run->bloom_k = k;
    run->rtombs = std::move(rtombs);
    run->fk = fk;
    run->fd = ::open(fin.c_str(), O_RDONLY);
    if (run->fd < 0) return nullptr;
    return run;
  }
};

// open + validate an existing run file; nullptr on structural damage
std::shared_ptr<Run> run_open_with(const std::string& path, const enc::FileKey& fk) {
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return nullptr;
  off_t sz = lseek(fd, 0, SEEK_END);
  if (sz < 16 + 32) { close(fd); return nullptr; }
  char foot[32];
  if (pread(fd, foot, 32, sz - 32) != 32) { close(fd); return nullptr; }
  enc::maybe_xor(fk, sz - 32, foot, 32);
  if (memcmp(foot + 28, kRunFoot, 4) != 0) {
    close(fd);
    return nullptr;
  }
  uint64_t index_off, bloom_off, n_entries;
  uint32_t sec_crc;
  memcpy(&index_off, foot, 8);
  memcpy(&bloom_off, foot + 8, 8);
  memcpy(&n_entries, foot + 16, 8);
  memcpy(&sec_crc, foot + 24, 4);
  if (index_off < 16 || index_off > static_cast<uint64_t>(sz) ||
      bloom_off < index_off || bloom_off > static_cast<uint64_t>(sz)) {
    close(fd);
    return nullptr;
  }
  char hdr[16];
  if (pread(fd, hdr, 16, 0) != 16) { close(fd); return nullptr; }
  enc::maybe_xor(fk, 0, hdr, 16);
  if (memcmp(hdr, kRunMagic, 6) != 0) {
    close(fd);
    return nullptr;
  }
  auto run = std::make_shared<Run>();
  run->path = path;
  run->fk = fk;
  run->cf = static_cast<uint8_t>(hdr[6]);
  run->kind = static_cast<uint8_t>(hdr[7]);
  memcpy(&run->max_seq, hdr + 8, 8);
  run->n_entries = n_entries;
  size_t sec_len = sz - 32 - index_off;
  std::string sec(sec_len, '\0');
  if (pread(fd, &sec[0], sec_len, index_off) != static_cast<ssize_t>(sec_len)) {
    close(fd);
    return nullptr;
  }
  enc::maybe_xor(fk, index_off, &sec[0], sec_len);
  if (crc32c(reinterpret_cast<const uint8_t*>(sec.data()), sec_len) != sec_crc) {
    close(fd);
    return nullptr;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(sec.data());
  const uint8_t* end = p + sec_len;
  if (end - p < 4) { close(fd); return nullptr; }
  uint32_t n_blocks = read_u32(p);
  for (uint32_t i = 0; i < n_blocks; i++) {
    if (end - p < 20) { close(fd); return nullptr; }
    Run::Block b;
    memcpy(&b.off, p, 8);
    p += 8;
    b.len = read_u32(p);
    b.crc = read_u32(p);
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < klen) { close(fd); return nullptr; }
    b.first_key.assign(reinterpret_cast<const char*>(p), klen);
    p += klen;
    run->blocks.push_back(std::move(b));
  }
  if (end - p < 16) { close(fd); return nullptr; }
  uint64_t n_bits;
  memcpy(&n_bits, p, 8);
  p += 8;
  run->bloom_k = read_u32(p);
  p += 4;  // pad
  if (static_cast<uint64_t>(end - p) < n_bits / 8) { close(fd); return nullptr; }
  run->bloom.resize(n_bits / 64);
  memcpy(run->bloom.data(), p, n_bits / 8);
  p += n_bits / 8;
  if (end - p < 4) { close(fd); return nullptr; }
  uint32_t n_rt = read_u32(p);
  for (uint32_t i = 0; i < n_rt; i++) {
    RangeTomb rt;
    if (end - p < 4) { close(fd); return nullptr; }
    uint32_t slen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(slen) + 4) {
      close(fd);
      return nullptr;
    }
    rt.start.assign(reinterpret_cast<const char*>(p), slen);
    p += slen;
    uint32_t elen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(elen) + 8) {
      close(fd);
      return nullptr;
    }
    rt.end.assign(reinterpret_cast<const char*>(p), elen);
    p += elen;
    memcpy(&rt.seq, p, 8);
    p += 8;
    run->rtombs.push_back(std::move(rt));
  }
  run->fd = fd;
  return run;
}

// Open + validate a run, trying every cipher identity its sidecar lists
// (newest first) and finally plaintext: a compaction that crashed between
// sidecar update and data rename leaves the OLD file behind the NEW entry,
// and the file's own magic + section CRC identify which candidate fits.
std::shared_ptr<Run> run_open(const std::string& path, const enc::State& est) {
  std::vector<enc::FileKey> cands;
  int r = enc::sidecar_read_all(est, path, &cands);
  if (r < 0) return nullptr;  // sidecar damaged or its keys unknown
  cands.push_back(enc::FileKey{});  // plaintext fallback (migration / crash)
  for (const enc::FileKey& fk : cands) {
    auto run = run_open_with(path, fk);
    if (run) return run;
  }
  return nullptr;
}

bool bloom_may_contain(const Run& r, const std::string& key) {
  if (r.bloom.empty()) return true;
  uint64_t n_bits = r.bloom.size() * 64;
  uint64_t h = hash64(reinterpret_cast<const uint8_t*>(key.data()), key.size(), 0);
  uint64_t h2 = h * 0x9e3779b97f4a7c15ull + 1;
  for (uint32_t i = 0; i < r.bloom_k; i++) {
    uint64_t bit = (h + i * h2) % n_bits;
    if (!(r.bloom[bit / 64] & (1ull << (bit % 64)))) return false;
  }
  return true;
}

int run_read_block(const Run& r, size_t bi, std::string* out, Perf* perf) {
  const Run::Block& b = r.blocks[bi];
  out->resize(b.len);
  if (pread(r.fd, &(*out)[0], b.len, b.off) != static_cast<ssize_t>(b.len))
    return -1;
  if (b.len) enc::maybe_xor(r.fk, b.off, &(*out)[0], b.len);
  if (crc32c(reinterpret_cast<const uint8_t*>(out->data()), b.len) != b.crc)
    return -1;
  if (perf) perf->blocks_read.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

// last block whose first_key <= key (the block that could hold it)
long run_block_for(const Run& r, const std::string& key) {
  long lo = 0, hi = static_cast<long>(r.blocks.size()) - 1, ans = -1;
  while (lo <= hi) {
    long mid = (lo + hi) / 2;
    if (r.blocks[mid].first_key <= key) {
      ans = mid;
      lo = mid + 1;
    } else {
      hi = mid - 1;
    }
  }
  return ans;
}

// point lookup in one run: 0 = absent, 1 = value, 2 = tombstone, <0 = error
int run_get(const Run& r, const std::string& key, uint64_t snap_seq,
            std::string* val, uint64_t* out_seq, Perf* perf) {
  if (!bloom_may_contain(r, key)) {
    if (perf) perf->bloom_skips.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  long bi = run_block_for(r, key);
  if (bi < 0) return 0;
  if (perf) perf->run_probes.fetch_add(1, std::memory_order_relaxed);
  std::string block;
  if (run_read_block(r, bi, &block, perf) != 0) return -1;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(block.data());
  const uint8_t* end = p + block.size();
  while (p < end) {
    if (end - p < 4) return -1;
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 13) return -1;
    int cmp = memcmp(p, key.data(), std::min<size_t>(klen, key.size()));
    if (cmp == 0) cmp = (klen < key.size()) ? -1 : (klen > key.size() ? 1 : 0);
    p += klen;
    uint64_t seq;
    memcpy(&seq, p, 8);
    p += 8;
    uint8_t tomb = *p++;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) return -1;
    if (cmp > 0) return 0;  // past the key: absent in this run
    if (cmp == 0 && seq <= snap_seq) {
      if (tomb) return 2;
      val->assign(reinterpret_cast<const char*>(p), vlen);
      *out_seq = seq;
      return 1;
    }
    p += vlen;
  }
  return 0;
}

// sequential cursor over one run's per-key version groups, range-aware
struct RunCursor {
  const Run* run;
  Perf* perf;
  std::string block;
  size_t bi = 0;          // next block index to load
  const uint8_t* p = nullptr;
  const uint8_t* end = nullptr;
  std::string key;
  std::vector<Version> versions;  // newest first (run entry order)
  bool valid = false;

  void seek(const Run* r, const std::string& start, Perf* pf) {
    run = r;
    perf = pf;
    long b = run_block_for(*r, start);
    bi = b < 0 ? 0 : static_cast<size_t>(b);
    p = end = nullptr;
    valid = true;
    next_group();
    while (valid && key < start) next_group();
  }

  bool load_next_block() {
    while (bi < run->blocks.size()) {
      if (run_read_block(*run, bi, &block, perf) != 0) { valid = false; return false; }
      bi++;
      p = reinterpret_cast<const uint8_t*>(block.data());
      end = p + block.size();
      if (p < end) return true;
    }
    return false;
  }

  // parse one entry at p (advances); false on exhaustion/corruption
  bool parse(std::string* k, uint64_t* seq, bool* tomb, std::string* v) {
    if (p >= end && !load_next_block()) return false;
    if (end - p < 4) { valid = false; return false; }
    uint32_t klen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 13) {
      valid = false;
      return false;
    }
    k->assign(reinterpret_cast<const char*>(p), klen);
    p += klen;
    memcpy(seq, p, 8);
    p += 8;
    *tomb = *p++ != 0;
    uint32_t vlen = read_u32(p);
    if (static_cast<uint64_t>(end - p) < vlen) { valid = false; return false; }
    v->assign(reinterpret_cast<const char*>(p), vlen);
    p += vlen;
    return true;
  }

  std::string pending_key;
  std::vector<Version> pending;
  bool have_pending = false;

  void next_group() {
    if (!valid) return;
    key.clear();
    versions.clear();
    bool have_key = false;
    if (have_pending) {
      key = std::move(pending_key);
      versions = std::move(pending);
      pending.clear();
      have_pending = false;
      have_key = true;
    }
    std::string k, v;
    uint64_t seq;
    bool tomb;
    while (parse(&k, &seq, &tomb, &v)) {
      if (!have_key) {
        key = k;
        have_key = true;
      }
      if (k == key) {
        versions.push_back(Version{seq, tomb, std::move(v)});
        continue;
      }
      // next key's first version: stash it
      pending_key = std::move(k);
      pending.clear();
      pending.push_back(Version{seq, tomb, std::move(v)});
      have_pending = true;
      return;
    }
    if (versions.empty()) valid = false;
  }
};

// One materialized memtable resolution: the key's visible state at the
// snapshot, captured under the engine lock so the merge can run without it.
struct MemEntry {
  std::string key;
  bool tomb;
  uint64_t seq;
  std::string value;
};

// forward merged iterator over memtable + all runs of one CF, resolving
// versions at a snapshot and filtering tombstones.  init() is called under
// (at least) the shared engine lock and copies everything it needs — the
// memtable subrange resolved at the snapshot, run shared_ptrs (which pin
// the files across a concurrent merge swap), and the relevant range
// tombstones — so next(), which does run-block file IO (pread + crc),
// runs with NO engine lock held: range scans no longer serialize writers
// behind disk IO (the eng_get treatment, extended to ranges).
struct MergeIter {
  std::vector<MemEntry> mem;  // resolved memtable subrange, ascending
  size_t mpos = 0;
  std::vector<std::shared_ptr<Run>> runs_keep;
  std::vector<RunCursor> cursors;
  Perf* perf = nullptr;
  uint64_t snap;
  std::string lower;  // run-cursor seek start
  std::string upper;  // exclusive; empty + !has_upper = unbounded
  bool has_upper = false;
  bool seeked = false;  // run cursors positioned (deferred: seeking reads)
  // mem_cap / mem_bytes_cap bound how many memtable entries (and copied
  // bytes) init may walk under the lock (0 = unlimited).  When hit,
  // `truncated` is set and `resume_key` names the first un-walked key: the
  // whole merge is clamped below it and the caller continues from there
  // with a fresh init (ChunkedMerge).
  uint64_t mem_cap = 0;
  uint64_t mem_bytes_cap = 0;
  bool truncated = false;
  std::string resume_key;

  std::vector<RangeTomb> rts;  // tombstones visible at snap touching range

  void init(Engine* e, int cf, uint64_t snap_seq, const std::string& start,
            const std::string& end, bool bounded) {
    snap = snap_seq;
    lower = start;
    upper = end;
    has_upper = bounded;
    if (bounded && end <= start) {
      seeked = true;  // empty range: nothing to position
      return;
    }
    const Table& t = e->cfs[cf];
    auto endit = bounded ? t.lower_bound(end) : t.end();
    uint64_t walked = 0, bytes = 0;
    for (auto it = t.lower_bound(start); it != endit; ++it) {
      if ((mem_cap != 0 && walked == mem_cap) ||
          (mem_bytes_cap != 0 && bytes >= mem_bytes_cap)) {
        truncated = true;
        resume_key = it->first;
        break;
      }
      walked++;
      const std::string* v = nullptr;
      uint64_t v_seq = 0;
      Res r = resolve3(it->second, snap_seq, &v, &v_seq);
      if (r == Res::MISS) continue;  // runs decide, same as key-absent
      bytes += it->first.size() + (r == Res::HIT ? v->size() : 0);
      mem.push_back(MemEntry{it->first, r == Res::TOMB, v_seq,
                             r == Res::HIT ? *v : std::string()});
    }
    runs_keep = e->runs[cf];
    perf = &e->perf;
    // hoist the relevant range tombstones once: per-key masking below walks
    // only this (usually empty) filtered list, not every run's full set
    auto want = [&](const RangeTomb& rt) {
      return rt.seq <= snap_seq && rt.end > start && (!bounded || rt.start < end);
    };
    for (const auto& rt : e->mem_rtombs[cf])
      if (want(rt)) rts.push_back(rt);
    for (const auto& run : runs_keep)
      for (const auto& rt : run->rtombs)
        if (want(rt)) rts.push_back(rt);
  }

  // next visible (key, value); false when exhausted.  Run-block IO happens
  // here, after init's lock is released.
  bool next(std::string* out_k, std::string* out_v) {
    if (!seeked) {
      seeked = true;
      cursors.resize(runs_keep.size());
      for (size_t i = 0; i < cursors.size(); i++)
        cursors[i].seek(runs_keep[i].get(), lower, perf);
    }
    while (true) {
      const std::string* min_key = nullptr;
      if (mpos < mem.size()) min_key = &mem[mpos].key;
      for (auto& c : cursors) {
        if (!c.valid) continue;
        if (has_upper && c.key >= upper) { c.valid = false; continue; }
        if (min_key == nullptr || c.key < *min_key) min_key = &c.key;
      }
      if (min_key == nullptr) return false;
      if (truncated && *min_key >= resume_key) return false;  // chunk edge
      std::string key = *min_key;
      // resolve newest-source-first: memtable, then runs in list order
      Res r = Res::MISS;
      const std::string* v = nullptr;
      uint64_t v_seq = 0;
      bool mem_here = mpos < mem.size() && mem[mpos].key == key;
      if (mem_here) {
        r = mem[mpos].tomb ? Res::TOMB : Res::HIT;
        v = &mem[mpos].value;
        v_seq = mem[mpos].seq;
      }
      std::string run_val;
      if (r == Res::MISS) {
        for (auto& c : cursors) {
          if (!c.valid || c.key != key) continue;
          for (const auto& ver : c.versions) {
            if (ver.seq <= snap) {
              if (ver.tombstone) {
                r = Res::TOMB;
              } else {
                run_val = ver.value;
                v_seq = ver.seq;
                r = Res::HIT;
                v = &run_val;
              }
              break;
            }
          }
          if (r != Res::MISS) break;
        }
      }
      // advance every source positioned at this key
      if (mem_here) mpos++;
      for (auto& c : cursors)
        if (c.valid && c.key == key) c.next_group();
      if (r == Res::HIT && rtomb_covering(rts, key, snap) < v_seq) {
        *out_k = std::move(key);
        *out_v = *v;
        return true;
      }
      // MISS (all newer than snap), TOMB, or range-delete-masked: skip
    }
  }
};

// reverse merged iteration materializes per-key resolution walking backward:
// run blocks are forward-parsed but visited in reverse block order
struct ReverseRunCursor {
  const Run* run = nullptr;
  Perf* perf;
  long bi = -1;  // block currently loaded
  std::vector<std::pair<std::string, std::vector<Version>>> groups;
  long gi = -1;  // current group (descending)
  bool valid = false;

  void seek_last_below(const Run* r, const std::string& upper, bool bounded,
                       Perf* pf) {
    run = r;
    perf = pf;
    bi = static_cast<long>(r->blocks.size()) - 1;
    if (bounded) {
      long b = run_block_for(*r, upper);
      bi = b < 0 ? -1 : b;
    }
    valid = bi >= 0;
    groups.clear();
    gi = -1;
    if (valid) load(bounded ? &upper : nullptr);
  }

  void load(const std::string* upper) {
    groups.clear();
    gi = -1;
    while (bi >= 0 && groups.empty()) {
      std::string block;
      if (run_read_block(*run, bi, &block, perf) != 0) { valid = false; return; }
      const uint8_t* p = reinterpret_cast<const uint8_t*>(block.data());
      const uint8_t* end = p + block.size();
      while (p < end) {
        if (end - p < 4) { valid = false; return; }
        uint32_t klen = read_u32(p);
        if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 13) {
          valid = false;
          return;
        }
        std::string k(reinterpret_cast<const char*>(p), klen);
        p += klen;
        uint64_t seq;
        memcpy(&seq, p, 8);
        p += 8;
        bool tomb = *p++ != 0;
        uint32_t vlen = read_u32(p);
        if (static_cast<uint64_t>(end - p) < vlen) { valid = false; return; }
        if (upper == nullptr || k < *upper) {
          if (groups.empty() || groups.back().first != k)
            groups.emplace_back(std::move(k), std::vector<Version>{});
          groups.back().second.push_back(
              Version{seq, tomb, std::string(reinterpret_cast<const char*>(p), vlen)});
        }
        p += vlen;
      }
      bi--;
    }
    if (groups.empty()) {
      valid = false;
      return;
    }
    gi = static_cast<long>(groups.size()) - 1;
  }

  const std::string& key() const { return groups[gi].first; }
  const std::vector<Version>& versions() const { return groups[gi].second; }

  void prev_group() {
    // a key can span a block boundary (its versions split across blocks) —
    // the writer prevents that (maybe_rotate splits only at key boundaries),
    // so stepping is purely positional
    gi--;
    if (gi < 0 && valid) load(nullptr);
    if (gi < 0) valid = false;
  }
};

struct ReverseMergeIter {
  std::vector<MemEntry> mem;  // resolved memtable subrange, DESCENDING
  size_t mpos = 0;
  std::vector<std::shared_ptr<Run>> runs_keep;
  std::vector<ReverseRunCursor> cursors;
  Perf* perf = nullptr;
  uint64_t snap;
  std::string lower;   // inclusive bound
  std::string upper_;  // exclusive cursor-seek bound
  bool bounded_ = false;
  bool seeked = false;
  // bounded memtable walk, mirroring MergeIter: when the cap is hit,
  // resume_key is the key at which the descending walk stopped (NOT
  // materialized).  The merge is clamped to keys strictly above it, and the
  // next chunk's exclusive upper bound is resume_key + one zero byte so the
  // stopped-at key itself is included there.
  uint64_t mem_cap = 0;
  uint64_t mem_bytes_cap = 0;
  bool truncated = false;
  std::string resume_key;

  std::vector<RangeTomb> rts;  // tombstones visible at snap touching range

  // Same locking contract as MergeIter: init under the shared engine lock
  // (no file IO), next() unlocked.
  void init(Engine* e, int cf, uint64_t snap_seq, const std::string& start,
            const std::string& end, bool bounded) {
    snap = snap_seq;
    lower = start;
    upper_ = end;
    bounded_ = bounded;
    if (bounded && end <= start) {
      seeked = true;  // empty range
      return;
    }
    const Table& t = e->cfs[cf];
    auto mbegin = t.lower_bound(start);
    auto it = bounded ? t.lower_bound(end) : t.end();
    uint64_t walked = 0, bytes = 0;
    while (it != mbegin) {
      --it;
      if ((mem_cap != 0 && walked == mem_cap) ||
          (mem_bytes_cap != 0 && bytes >= mem_bytes_cap)) {
        truncated = true;
        resume_key = it->first;  // un-materialized; next chunk includes it
        break;
      }
      walked++;
      const std::string* v = nullptr;
      uint64_t v_seq = 0;
      Res r = resolve3(it->second, snap_seq, &v, &v_seq);
      if (r == Res::MISS) continue;
      bytes += it->first.size() + (r == Res::HIT ? v->size() : 0);
      mem.push_back(MemEntry{it->first, r == Res::TOMB, v_seq,
                             r == Res::HIT ? *v : std::string()});
    }
    runs_keep = e->runs[cf];
    perf = &e->perf;
    auto want = [&](const RangeTomb& rt) {
      return rt.seq <= snap_seq && rt.end > start && (!bounded || rt.start < end);
    };
    for (const auto& rt : e->mem_rtombs[cf])
      if (want(rt)) rts.push_back(rt);
    for (const auto& run : runs_keep)
      for (const auto& rt : run->rtombs)
        if (want(rt)) rts.push_back(rt);
  }

  bool next(std::string* out_k, std::string* out_v) {
    if (!seeked) {
      seeked = true;
      cursors.resize(runs_keep.size());
      for (size_t i = 0; i < cursors.size(); i++) {
        cursors[i].seek_last_below(runs_keep[i].get(), upper_, bounded_, perf);
        if (cursors[i].valid && cursors[i].key() < lower)
          cursors[i].valid = false;
      }
    }
    while (true) {
      const std::string* max_key = nullptr;
      if (mpos < mem.size()) max_key = &mem[mpos].key;
      for (auto& c : cursors) {
        if (!c.valid) continue;
        if (c.key() < lower) { c.valid = false; continue; }
        if (max_key == nullptr || c.key() > *max_key) max_key = &c.key();
      }
      if (max_key == nullptr) return false;
      if (truncated && *max_key <= resume_key) return false;  // chunk edge
      std::string key = *max_key;
      Res r = Res::MISS;
      const std::string* v = nullptr;
      uint64_t v_seq = 0;
      bool mem_here = mpos < mem.size() && mem[mpos].key == key;
      if (mem_here) {
        r = mem[mpos].tomb ? Res::TOMB : Res::HIT;
        v = &mem[mpos].value;
        v_seq = mem[mpos].seq;
      }
      std::string run_val;
      if (r == Res::MISS) {
        for (auto& c : cursors) {
          if (!c.valid || c.key() != key) continue;
          for (const auto& ver : c.versions()) {
            if (ver.seq <= snap) {
              if (ver.tombstone) {
                r = Res::TOMB;
              } else {
                run_val = ver.value;
                v_seq = ver.seq;
                r = Res::HIT;
                v = &run_val;
              }
              break;
            }
          }
          if (r != Res::MISS) break;
        }
      }
      if (mem_here) mpos++;
      for (auto& c : cursors) {
        if (c.valid && c.key() == key) {
          c.prev_group();
          if (c.valid && c.key() < lower) c.valid = false;
        }
      }
      if (r == Res::HIT && rtomb_covering(rts, key, snap) < v_seq) {
        *out_k = std::move(key);
        *out_v = *v;
        return true;
      }
    }
  }
};

// Drives MergeIter in bounded-memtable chunks.  Each chunk takes a fresh
// shared-lock view at the SAME registered snapshot — safe, because versions
// visible at a live snapshot can neither disappear (the snapshot pins them
// against compaction and version-chain trimming; a flush only moves them
// into a run the fresh view includes) nor appear (new writes carry seqs
// above it).  So no lock is ever held across run-block IO and no single
// init walks more than `cap` memtable entries.
constexpr uint64_t kScanMemChunk = 65536;     // memtable entries / locked walk
constexpr uint64_t kMemChunkBytes = 4 << 20;  // copied bytes / locked walk

struct ChunkedMerge {
  Engine* e;
  int cf;
  uint64_t snap;
  std::string cur, upper;
  bool has_upper;
  uint64_t cap;  // grows ×4 per re-init: single-row seeks start tiny
  MergeIter mi;

  ChunkedMerge(Engine* e_, int cf_, uint64_t snap_, std::string start,
               std::string end, bool bounded, uint64_t cap_)
      : e(e_), cf(cf_), snap(snap_), cur(std::move(start)),
        upper(std::move(end)), has_upper(bounded), cap(cap_) {
    open();
  }

  void open() {
    mi = MergeIter{};
    mi.mem_cap = cap;
    mi.mem_bytes_cap = kMemChunkBytes;
    std::shared_lock lk(e->mu);
    mi.init(e, cf, snap, cur, upper, has_upper);
  }

  bool next(std::string* k, std::string* v) {
    while (true) {
      if (mi.next(k, v)) return true;
      if (!mi.truncated) return false;
      cur = mi.resume_key;  // strictly advances: ≥1 entry walked per chunk
      cap = std::min<uint64_t>(cap * 4, kScanMemChunk);
      open();
    }
  }
};

struct ReverseChunkedMerge {
  Engine* e;
  int cf;
  uint64_t snap;
  std::string lower, cur_upper;
  bool has_upper;
  uint64_t cap;
  ReverseMergeIter mi;

  ReverseChunkedMerge(Engine* e_, int cf_, uint64_t snap_, std::string start,
                      std::string end, bool bounded, uint64_t cap_)
      : e(e_), cf(cf_), snap(snap_), lower(std::move(start)),
        cur_upper(std::move(end)), has_upper(bounded), cap(cap_) {
    open();
  }

  void open() {
    mi = ReverseMergeIter{};
    mi.mem_cap = cap;
    mi.mem_bytes_cap = kMemChunkBytes;
    std::shared_lock lk(e->mu);
    mi.init(e, cf, snap, lower, cur_upper, has_upper);
  }

  bool next(std::string* k, std::string* v) {
    while (true) {
      if (mi.next(k, v)) return true;
      if (!mi.truncated) return false;
      // stopped-at key was not materialized: include it in the next chunk
      cur_upper = mi.resume_key + std::string(1, '\0');
      has_upper = true;
      cap = std::min<uint64_t>(cap * 4, kScanMemChunk);
      open();
    }
  }
};

// write the whole memtable of one CF (chains + range tombstones) as a run
std::shared_ptr<Run> run_from_table(Engine* e, int cf, uint64_t max_seq) {
  RunWriter w;
  if (w.open(e->dir, e->enc_snapshot(), cf, max_seq, 0) != 0) return nullptr;
  for (const auto& [key, chain] : e->cfs[cf]) {
    w.maybe_rotate(key);
    for (const auto& v : chain) w.add(key, v.seq, v.tombstone, v.value);
  }
  w.rtombs = e->mem_rtombs[cf];
  return w.finish(cf, max_seq);
}

// spill the whole memtable to per-CF runs, clear it, rotate the WAL — the
// incremental replacement for the O(DB) checkpoint spill: each flush costs
// O(memtable), never O(database).  Caller holds the write lock.
int flush_memtable(Engine* e) {
  if (e->dir.empty()) return -1;
  uint64_t at = e->seq;
  std::vector<std::shared_ptr<Run>> created;
  if (at > e->flushed_seq) {
    for (int cf = 0; cf < kNumCfs; cf++) {
      if (e->cfs[cf].empty() && e->mem_rtombs[cf].empty()) continue;
      auto run = run_from_table(e, cf, at);
      if (!run) {
        for (auto& r : created) unlink_with_sidecar(r->path);
        return -1;
      }
      created.push_back(run);
    }
    fsync_dir(e->dir);
    // completion marker: a flush is visible to recovery only once ALL its
    // per-CF runs are durable (multi-file atomicity).  Written even when
    // no run was produced (every record since the last flush was a no-op):
    // the marker is what tells recovery the older WAL is fully covered, so
    // it must advance whenever the WAL is about to be truncated — deleting
    // mark-N without a successor would make recovery distrust every run.
    std::string mark = e->dir + "/" + seg_name("mark", at);
    int mfd = ::open(mark.c_str(), O_CREAT | O_WRONLY, 0644);
    if (mfd < 0) {
      for (auto& r : created) unlink_with_sidecar(r->path);
      return -1;
    }
    fsync(mfd);
    close(mfd);
    fsync_dir(e->dir);
  }
  // new WAL segment BEFORE deleting old ones: if the open fails the previous
  // log remains intact and the engine refuses further writes, losing nothing
  if (wal_open_segment(e, at) != 0) return -1;
  for (auto& r : created)
    e->runs[r->cf].insert(e->runs[r->cf].begin(), r);
  if (at > e->flushed_seq) {
    for (int cf = 0; cf < kNumCfs; cf++) {
      e->cfs[cf].clear();
      e->mem_rtombs[cf].clear();
    }
    e->mem_bytes = 0;
    e->flushed_seq = at;
    e->perf.flushes.fetch_add(1, std::memory_order_relaxed);
  }
  std::vector<uint64_t> old;
  list_segs(e->dir, "wal", &old);
  for (uint64_t s : old)
    if (s < at) unlink_with_sidecar(e->dir + "/" + seg_name("wal", s));
  // legacy checkpoints and folded ingests are superseded: the flush captured
  // the whole memtable, which included anything they had loaded
  old.clear();
  list_segs(e->dir, "ckpt", &old);
  for (uint64_t s : old)
    if (s <= at) unlink((e->dir + "/" + seg_name("ckpt", s)).c_str());
  old.clear();
  list_segs(e->dir, "sst", &old);
  for (uint64_t s : old)
    if (s <= at) unlink((e->dir + "/" + seg_name("sst", s)).c_str());
  old.clear();
  list_segs(e->dir, "mark", &old);
  for (uint64_t s : old)
    if (s < at) unlink((e->dir + "/" + seg_name("mark", s)).c_str());
  return 0;
}

// k-way merge of every current run of one CF into a single run, dropping
// version history below the snapshot horizon and bottom-level tombstones.
// Runs are immutable, so the merge reads WITHOUT the engine lock; the swap
// takes it briefly (rocksdb compaction's locking shape).
int merge_runs_cf(Engine* e, int cf) {
  std::unique_lock cl(e->compact_mu);
  std::vector<std::shared_ptr<Run>> inputs;
  uint64_t min_snap;
  {
    std::shared_lock lk(e->mu);
    if (e->runs[cf].size() < 2) return 0;
    inputs = e->runs[cf];
    min_snap = std::min(e->min_live_snapshot(), e->seq);
  }
  uint64_t max_seq = inputs.front()->max_seq;
  RunWriter w;
  if (w.open(e->dir, e->enc_snapshot(), cf, max_seq, 1) != 0) return -1;
  // range tombstones: ones no snapshot can see below fold into the output
  // now (applied to the merged versions, then dropped — this is the only
  // level, so nothing older remains for them to mask; memtable versions are
  // all newer than any run seq, out of reach by construction).  Newer ones
  // ride along into the output run.
  std::vector<RangeTomb> dying_rtombs, kept_rtombs;
  for (const auto& r : inputs)
    for (const auto& rt : r->rtombs)
      (rt.seq <= min_snap ? dying_rtombs : kept_rtombs).push_back(rt);
  w.rtombs = kept_rtombs;
  std::vector<RunCursor> cur(inputs.size());
  for (size_t i = 0; i < inputs.size(); i++)
    cur[i].seek(inputs[i].get(), std::string(), &e->perf);
  std::vector<Version> merged;
  while (true) {
    const std::string* min_key = nullptr;
    for (auto& c : cur)
      if (c.valid && (min_key == nullptr || c.key < *min_key)) min_key = &c.key;
    if (min_key == nullptr) break;
    std::string key = *min_key;
    merged.clear();
    for (auto& c : cur) {  // newest source first: global newest-first order
      if (c.valid && c.key == key) {
        for (auto& v : c.versions) merged.push_back(std::move(v));
        c.next_group();
      }
    }
    // trim: versions > min_snap plus the newest <= min_snap
    size_t keep = merged.size();
    for (size_t i = 0; i < merged.size(); i++) {
      if (merged[i].seq <= min_snap) {
        keep = i + 1;
        break;
      }
    }
    merged.resize(keep);
    // apply dying range tombstones now: a version at/below a folded range
    // delete is invisible to every future snapshot (all >= min_snap)
    uint64_t rts = 0;
    for (const auto& rt : dying_rtombs)
      if (rt.seq > rts && rt.start <= key && key < rt.end) rts = rt.seq;
    while (!merged.empty() && merged.back().seq <= rts) merged.pop_back();
    if (merged.empty()) continue;
    // bottom level: a tombstone no snapshot can miss masks nothing anymore
    if (merged.size() == 1 && merged[0].tombstone && merged[0].seq <= min_snap)
      continue;
    w.maybe_rotate(key);
    for (const auto& v : merged) w.add(key, v.seq, v.tombstone, v.value);
  }
  // the output keeps inputs.front()'s name: rename clobbers that path (old
  // readers keep their fd; POSIX keeps the old inode alive), so it must NOT
  // be unlinked below
  auto out = w.finish(cf, max_seq, 1);
  if (!out) return -1;
  // the rename must be on disk before the input unlinks below can be:
  // otherwise a crash could persist the unlinks but not the rename, leaving
  // only the stale pre-merge run at the output's path
  fsync_dir(e->dir);
  {
    std::unique_lock lk(e->mu);
    auto& rs = e->runs[cf];
    // inputs occupy a contiguous tail (flushes only prepend); replace it
    size_t pos = 0;
    while (pos < rs.size() && rs[pos] != inputs.front()) pos++;
    if (pos == rs.size()) { unlink_with_sidecar(out->path); return -1; }  // raced
    rs.resize(pos);
    rs.push_back(out);
  }
  for (size_t i = 1; i < inputs.size(); i++) unlink_with_sidecar(inputs[i]->path);
  e->perf.run_merges.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

// load the newest structurally-valid checkpoint; returns its seq (0 = none)
uint64_t ckpt_load(Engine* e) {
  std::vector<uint64_t> cks;
  list_segs(e->dir, "ckpt", &cks);
  for (auto it = cks.rbegin(); it != cks.rend(); ++it) {
    std::string path = e->dir + "/" + seg_name("ckpt", *it);
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) continue;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    if (sz < 22) { fclose(f); continue; }
    std::string buf;
    buf.resize(sz);
    bool rok = fread(&buf[0], 1, sz, f) == static_cast<size_t>(sz);
    fclose(f);
    if (!rok || buf.compare(0, 6, kCkptMagic) != 0) continue;
    if (buf.compare(sz - 8, 4, kCkptFoot) != 0) continue;
    uint32_t crc;
    memcpy(&crc, buf.data() + sz - 4, 4);
    const uint8_t* body = reinterpret_cast<const uint8_t*>(buf.data()) + 14;
    size_t body_len = sz - 22;
    if (crc32c(body, body_len) != crc) continue;
    uint64_t at;
    memcpy(&at, buf.data() + 6, 8);
    const uint8_t* p = body;
    const uint8_t* end = body + body_len;
    while (p < end) {
      uint8_t cf = *p++;
      if (cf >= kNumCfs || end - p < 4) break;
      uint32_t klen = read_u32(p);
      if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(klen) + 4)
        break;
      std::string key(reinterpret_cast<const char*>(p), klen);
      p += klen;
      uint32_t vlen = read_u32(p);
      if (static_cast<uint64_t>(end - p) < vlen) break;
      // checkpoints are written in cf-then-key order: O(1) hinted appends
      put_version(e, e->cfs[cf], std::move(key), at, false,
                  std::string(reinterpret_cast<const char*>(p), vlen), at);
      p += vlen;
    }
    e->seq = at;
    return at;
  }
  return 0;
}

}  // namespace

extern "C" {

static thread_local const enc::State* g_pending_enc = nullptr;

void* eng_open() { return new Engine(); }

// Open (or create) a durable engine on a directory.  sync_mode: 1 = WAL
// fdatasync on every commit (crash-durable), 0 = OS-buffered (fast, loses
// the tail on power loss — still consistent via WAL framing).
static enc::State make_enc_state(uint32_t current_id, const uint32_t* ids,
                                 const uint8_t* keys32, int n) {
  enc::State st;
  for (int i = 0; i < n; i++) {
    std::array<uint8_t, 32> k;
    memcpy(k.data(), keys32 + 32 * i, 32);
    st.keys[ids[i]] = k;
  }
  st.current = current_id;
  st.on = n > 0;
  return st;
}

void* eng_open_at(const char* path, int sync_mode) {
  Engine* e = new Engine();
  e->dir = path;
  e->sync_mode = sync_mode;
  if (g_pending_enc) e->enc = *g_pending_enc;
  mkdir(path, 0755);
  // drop temp files of crashed flushes/merges (never renamed = never trusted)
  if (DIR* d = opendir(path)) {
    struct dirent* ent;
    while ((ent = readdir(d)) != nullptr) {
      std::string n = ent->d_name;
      if (n.size() > 4 && n.compare(n.size() - 4, 4, ".tmp") == 0)
        unlink((e->dir + "/" + n).c_str());
    }
    closedir(d);
  }
  // sorted runs first (newest list position = highest seq).  Only runs at or
  // below the newest completion marker are trusted: runs above it belong to
  // a flush that crashed mid-way (its data is still in the WAL), and once a
  // merged-kind run is seen, everything older in that CF was its input.
  std::vector<uint64_t> marks;
  list_segs(e->dir, "mark", &marks);
  uint64_t mark = marks.empty() ? 0 : marks.back();
  bool have_runs = false;
  for (int cf = 0; cf < kNumCfs; cf++) {
    std::vector<uint64_t> seqs;
    list_segs(e->dir, run_prefix(cf), &seqs);
    for (auto it = seqs.rbegin(); it != seqs.rend(); ++it) {
      std::string rp = e->dir + "/" + seg_name(run_prefix(cf), *it);
      if (*it > mark) {
        unlink_with_sidecar(rp);  // partial flush: WAL still covers these records
        continue;
      }
      if (!e->runs[cf].empty() && e->runs[cf].back()->kind == 1) {
        unlink_with_sidecar(rp);  // leftover input of a completed full-cf merge
        continue;
      }
      auto run = run_open(rp, e->enc_snapshot());
      if (!run) {
        // a trusted run (at/below the marker) is damaged and the WAL that
        // covered it is gone: opening would silently lose acked writes —
        // refuse, like a torn WAL segment
        delete e;
        return nullptr;
      }
      e->runs[cf].push_back(run);
      have_runs = true;
    }
  }
  e->flushed_seq = mark;
  e->seq = e->flushed_seq;
  // legacy full-state checkpoints load only when no runs exist (runs always
  // supersede them: a flush deletes folded checkpoints, and loading an older
  // checkpoint into the memtable would break the memtable-newest invariant)
  uint64_t ck = have_runs ? e->flushed_seq : ckpt_load(e);
  if (ck > e->seq) e->seq = ck;
  std::vector<uint64_t> wals;
  list_segs(e->dir, "wal", &wals);
  for (uint64_t s : wals) {
    if (s < ck) continue;  // fully folded into the checkpoint/runs
    if (wal_replay(e, e->dir + "/" + seg_name("wal", s)) != 0) {
      delete e;  // could not repair a torn segment: refuse the open
      return nullptr;
    }
  }
  // recovered WAL segments are re-folded on the next checkpoint; append to a
  // fresh segment so replay order stays strictly by start-seq
  if (wal_open_segment(e, e->seq) != 0) {
    delete e;
    return nullptr;
  }
  return e;
}

// Durable open with encryption at rest: (ids, keys32) is the data-key
// registry from the Python DataKeyManager (manager/mod.rs:398 role); files
// written from here on encrypt under `current_id`, existing files decrypt
// under whichever key their sidecar names, and sidecar-less files read as
// plaintext (migration).  An unknown key id in any sidecar fails the open.
void* eng_open_at_enc(const char* path, int sync_mode, uint32_t current_id,
                      const uint32_t* ids, const uint8_t* keys32, int n) {
  // recovery must decrypt, so the key registry has to exist before the
  // directory scan — stage it on a throwaway engine, then hand it to the
  // real open through a thread-local (the open path stays ONE function)
  enc::State st = make_enc_state(current_id, ids, keys32, n);
  g_pending_enc = &st;
  void* e = eng_open_at(path, sync_mode);
  g_pending_enc = nullptr;
  return e;
}

// Rotate the data-key registry on a RUNNING engine: new runs/WAL segments
// use `current_id`; files already on disk keep their sidecar key.
int eng_set_encryption(void* h, uint32_t current_id, const uint32_t* ids,
                       const uint8_t* keys32, int n) {
  Engine* e = static_cast<Engine*>(h);
  // write_mu keeps the live WAL segment's identity stable; enc_mu covers
  // concurrent readers of the registry (background compaction writers)
  std::lock_guard<std::mutex> wl(e->write_mu);
  std::lock_guard<std::mutex> el(e->enc_mu);
  e->enc = make_enc_state(current_id, ids, keys32, n);
  return 0;
}

void eng_close(void* h) {
  Engine* e = static_cast<Engine*>(h);
  if (e->wal_fd >= 0) close(e->wal_fd);
  delete e;
}

int eng_write(void* h, const uint8_t* data, uint64_t len) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock wlk(e->write_mu);
  if (e->failed) return -5;
  // validate BEFORE logging: a malformed batch must never reach the WAL
  int r = validate_batch(data, len);
  if (r != 0) return r;
  // seq is only mutated by writers, and writers serialize on write_mu —
  // reading it here without mu races nothing
  uint64_t seq = e->seq + 1;
  // WAL first: a batch is committed iff its record is durable (fsync'd
  // before apply, exactly rocksdb's WriteBatch-then-memtable order).
  // Deliberately OUTSIDE mu: the fdatasync must not stall readers.
  if (wal_append(e, seq, data, len) != 0) {
    e->failed = true;
    return -4;
  }
  bool need_flush;
  {
    std::unique_lock lk(e->mu);
    r = apply_batch(e, data, len, seq);
    if (r != 0) return r;  // unreachable after validate; defensive
    e->seq = seq;
    need_flush = !e->dir.empty() &&
        ((e->wal_limit > 0 && e->wal_bytes >= e->wal_limit) ||
         (e->mem_limit > 0 && e->mem_bytes >= e->mem_limit));
  }
  if (need_flush) {
    // inline memtable flush (rocksdb's memtable-full write stall, bounded
    // by memtable size — never O(database)); a failed flush that lost its
    // log fd must stop acking writes, not go silently non-durable
    std::unique_lock lk(e->mu);
    if (flush_memtable(e) != 0 && e->wal_fd < 0) e->failed = true;
  }
  return 0;
}

// Build an SST file at `path` from a serialized run of (cf|klen|key|vlen|val)
// records (must be sorted by (cf, key)).  Standalone: no engine handle.
int eng_build_sst(const char* path, const uint8_t* body, uint64_t len) {
  // frame it, then validate the full image (sortedness + crc round-trip)
  std::string img;
  img.reserve(18 + len);
  img.append(kSstMagic, 6);
  append_u32(img, 0);  // count unused (size-delimited records); kept for layout
  img.append(reinterpret_cast<const char*>(body), len);
  img.append(kSstFoot, 4);
  append_u32(img, crc32c(body, len));
  if (sst_validate(reinterpret_cast<const uint8_t*>(img.data()), img.size()) != 0)
    return -3;
  std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  bool ok = fwrite(img.data(), 1, img.size(), f) == img.size() &&
            fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), path) != 0) {
    unlink(tmp.c_str());
    return -1;
  }
  return 0;
}

// Ingest an external SST: validate, copy into the engine dir as sst-<seq>,
// WAL-log the op-4 reference, load.  For a pure in-memory engine the file
// is loaded in place (no copy, no WAL).
int eng_ingest_sst(void* h, const char* src_path) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock wlk(e->write_mu);  // WAL writer: ahead of mu (lock order)
  std::unique_lock lk(e->mu);
  if (e->failed) return -5;
  FILE* f = fopen(src_path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 18 || sz > (1ll << 40)) {  // bounds BEFORE resize: a directory
    fclose(f);                         // fopen succeeds and ftell lies
    return -1;
  }
  std::string buf;
  buf.resize(sz);
  bool rok = fread(&buf[0], 1, sz, f) == static_cast<size_t>(sz);
  fclose(f);
  if (!rok) return -1;
  int v = sst_validate(reinterpret_cast<const uint8_t*>(buf.data()), buf.size());
  if (v != 0) return v;
  uint64_t seq = e->seq + 1;
  std::string rec_key;
  if (e->dir.empty()) {
    rec_key = src_path;  // in-memory: reference the source directly
  } else {
    rec_key = seg_name("sst", seq);
    std::string dst = e->dir + "/" + rec_key;
    std::string tmp = dst + ".tmp";
    FILE* out = fopen(tmp.c_str(), "wb");
    if (!out) return -1;
    bool ok = fwrite(buf.data(), 1, buf.size(), out) == buf.size() &&
              fflush(out) == 0 && fsync(fileno(out)) == 0;
    fclose(out);
    if (!ok || rename(tmp.c_str(), dst.c_str()) != 0) {
      unlink(tmp.c_str());
      return -1;
    }
    fsync_dir(e->dir);  // the file must exist before its WAL reference
  }
  // op-4 batch record: | op | cf | klen | name | vlen=0 |
  std::string rec;
  rec.push_back(4);
  rec.push_back(0);
  append_u32(rec, static_cast<uint32_t>(rec_key.size()));
  rec.append(rec_key);
  append_u32(rec, 0);
  const uint8_t* rp = reinterpret_cast<const uint8_t*>(rec.data());
  if (wal_append(e, seq, rp, rec.size()) != 0) {
    e->failed = true;
    return -4;
  }
  // apply straight from the validated bytes — no second read/parse of the
  // copy; WAL replay goes through apply_batch → load_sst_file instead
  int r = load_sst_from_buf(
      e, reinterpret_cast<const uint8_t*>(buf.data()), buf.size(), seq);
  if (r != 0) {
    // The WAL record for this seq is already durable; failing to apply it
    // without bumping e->seq would let the next write reuse the seq and make
    // replay silently drop the second (acked) record.  Stop acking instead.
    e->failed = true;
    return r;
  }
  e->seq = seq;
  return 0;
}

int eng_checkpoint(void* h) {
  // checkpoint == memtable flush: durable sorted runs + WAL truncation.
  // (The legacy O(DB) full-state spill is gone; ckpt_load remains for
  // reading directories written by it.)
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock wlk(e->write_mu);  // flush rotates the WAL segment
  std::unique_lock lk(e->mu);
  if (e->dir.empty()) return -1;
  int r = flush_memtable(e);
  if (r != 0 && e->wal_fd < 0) e->failed = true;  // log fd lost: stop acking
  return r;
}

int eng_flush(void* h) { return eng_checkpoint(h); }

void eng_set_mem_limit(void* h, uint64_t bytes) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  e->mem_limit = bytes;
}

// number of on-disk sorted runs for one CF
int eng_run_count(void* h, int cf) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::shared_lock lk(e->mu);
  return static_cast<int>(e->runs[cf].size());
}

// merge all runs of one CF into a single run (background compaction step);
// returns 1 when a merge happened, 0 when <2 runs, <0 on error
int eng_merge_runs(void* h, int cf) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  return merge_runs_cf(e, cf);
}

// perf context (engine_rocks/src/perf_context.rs):
// out[0]=gets out[1]=memtable_hits out[2]=run_probes out[3]=bloom_skips
// out[4]=blocks_read out[5]=flushes out[6]=run_merges
void eng_perf(void* h, uint64_t* out) {
  Engine* e = static_cast<Engine*>(h);
  out[0] = e->perf.gets.load(std::memory_order_relaxed);
  out[1] = e->perf.memtable_hits.load(std::memory_order_relaxed);
  out[2] = e->perf.run_probes.load(std::memory_order_relaxed);
  out[3] = e->perf.bloom_skips.load(std::memory_order_relaxed);
  out[4] = e->perf.blocks_read.load(std::memory_order_relaxed);
  out[5] = e->perf.flushes.load(std::memory_order_relaxed);
  out[6] = e->perf.run_merges.load(std::memory_order_relaxed);
}

void eng_set_wal_limit(void* h, uint64_t bytes) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  e->wal_limit = bytes;
}

// import-mode tuning (sst_importer/src/import_mode.rs): bulk loads drop to
// buffered WAL writes, then restore sync + checkpoint when done.  Returns
// non-zero if the flush that closes the unsynced window fails — in that case
// the buffered tail is NOT durable and the engine stops acking writes rather
// than promising per-commit durability it cannot deliver.
int eng_set_sync(void* h, int sync_mode) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock wlk(e->write_mu);  // WAL state lives under write_mu
  if (e->sync_mode == 0 && sync_mode == 1 && e->wal_fd >= 0) {
    if (fdatasync(e->wal_fd) != 0) {
      e->failed = true;
      return -4;
    }
  }
  e->sync_mode = sync_mode;
  return 0;
}

uint64_t eng_seq(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->seq;
}

uint64_t eng_mem_bytes(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->mem_bytes;
}

uint64_t eng_wal_bytes(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> wlk(e->write_mu);  // wal state's guard
  return e->wal_bytes;
}

uint64_t eng_snapshot(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  e->snapshots.insert(e->seq);
  return e->seq;
}

void eng_release_snapshot(void* h, uint64_t seq) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock lk(e->mu);
  auto it = e->snapshots.find(seq);
  if (it != e->snapshots.end()) e->snapshots.erase(it);
}

// get: returns 1 + copies value if found, 0 if not, <0 on error.
// caller frees *out with eng_free.
int eng_get(void* h, int cf, const uint8_t* key, uint64_t klen,
            uint64_t snap_seq, uint8_t** out, uint64_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::string k(reinterpret_cast<const char*>(key), klen);
  std::string mem_val;
  uint64_t v_seq = 0;
  uint64_t rts = 0;  // newest covering range-delete seq <= snap
  Res r = Res::MISS;
  std::vector<std::shared_ptr<Run>> runs_copy;
  {
    // short critical section: memtable resolve + the (memory-only) range-
    // tombstone check + a shared_ptr copy of the run list.  Run probing
    // does file IO (pread + crc) and must NOT hold the engine lock — runs
    // are immutable and the copied shared_ptrs keep their files alive
    // across a concurrent merge swap.  A memtable MISS stays valid after
    // unlock: only versions newer than snap can appear, and a flush moving
    // versions to a run moves none visible at snap (they would have
    // resolved HIT/TOMB here).
    std::shared_lock lk(e->mu);
    e->perf.gets.fetch_add(1, std::memory_order_relaxed);
    const Table& t = e->cfs[cf];
    const std::string* v = nullptr;
    auto it = t.find(k);
    if (it != t.end()) r = resolve3(it->second, snap_seq, &v, &v_seq);
    if (r == Res::TOMB) return 0;
    rts = rtomb_covering(e->mem_rtombs[cf], k, snap_seq);
    for (const auto& run : e->runs[cf]) {
      uint64_t s = rtomb_covering(run->rtombs, k, snap_seq);
      if (s > rts) rts = s;
    }
    if (r == Res::HIT) {
      e->perf.memtable_hits.fetch_add(1, std::memory_order_relaxed);
      if (rts >= v_seq) return 0;  // range delete masks the memtable value
      mem_val = *v;  // copy under the lock; the chain may mutate after
    } else {
      runs_copy = e->runs[cf];
    }
  }
  std::string run_val;
  const std::string* v = (r == Res::HIT) ? &mem_val : nullptr;
  if (r == Res::MISS) {
    // newest run first; a hit or tombstone in a newer run masks older ones
    for (const auto& run : runs_copy) {
      int rr = run_get(*run, k, snap_seq, &run_val, &v_seq, &e->perf);
      if (rr < 0) return -3;
      if (rr == 2) return 0;  // tombstone
      if (rr == 1) {
        v = &run_val;
        r = Res::HIT;
        break;
      }
    }
  }
  if (r != Res::HIT) return 0;
  if (rts >= v_seq) return 0;  // range delete masks the run value
  *out = static_cast<uint8_t*>(malloc(v->size()));
  memcpy(*out, v->data(), v->size());
  *out_len = v->size();
  return 1;
}

// scan [start, end) visible at snap_seq; limit 0 = unlimited.
// Output buffer: repeated (klen u32 | key | vlen u32 | val); caller eng_free.
// Returns number of pairs, or <0 on error.
// The shared lock covers only MergeIter::init (a bounded memtable
// materialization + run shared_ptr copies — memory-only); the run-block
// pread+crc IO runs unlocked, so a cold range scan never stalls writers,
// and ChunkedMerge re-inits keep any single locked walk ≤ kScanMemChunk
// memtable entries.
long eng_scan(void* h, int cf, uint64_t snap_seq, const uint8_t* start,
              uint64_t start_len, const uint8_t* end_key, uint64_t end_len,
              int has_end, uint64_t limit, int reverse, uint8_t** out,
              uint64_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::string s(reinterpret_cast<const char*>(start), start_len);
  std::string en(reinterpret_cast<const char*>(end_key), end_len);
  std::string buf;
  long n = 0;
  auto emit = [&](const std::string& k, const std::string& v) {
    append_u32(buf, static_cast<uint32_t>(k.size()));
    buf.append(k);
    append_u32(buf, static_cast<uint32_t>(v.size()));
    buf.append(v);
    n++;
  };
  // a limited scan caps its locked walk proportionally to the output it can
  // produce (tombstone-heavy ranges continue via chunk re-init)
  uint64_t cap = limit ? std::max<uint64_t>(2 * limit, 4096) : kScanMemChunk;
  std::string k, v;
  if (!reverse) {
    ChunkedMerge cm(e, cf, snap_seq, s, en, has_end != 0, cap);
    while ((limit == 0 || n < static_cast<long>(limit)) && cm.next(&k, &v))
      emit(k, v);
  } else {
    ReverseChunkedMerge cm(e, cf, snap_seq, s, en, has_end != 0, cap);
    while ((limit == 0 || n < static_cast<long>(limit)) && cm.next(&k, &v))
      emit(k, v);
  }
  *out = static_cast<uint8_t*>(malloc(buf.size()));
  memcpy(*out, buf.data(), buf.size());
  *out_len = buf.size();
  return n;
}

// cursor-style seek: find first key >= target (or last key <= target when
// for_prev) within [lower, upper); returns 1 + key/value copies, else 0.
int eng_seek(void* h, int cf, uint64_t snap_seq, const uint8_t* target,
             uint64_t target_len, const uint8_t* lower, uint64_t lower_len,
             const uint8_t* upper, uint64_t upper_len, int has_upper,
             int for_prev, uint8_t** kout, uint64_t* kout_len, uint8_t** vout,
             uint64_t* vout_len) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::string tg(reinterpret_cast<const char*>(target), target_len);
  std::string lo(reinterpret_cast<const char*>(lower), lower_len);
  std::string up(reinterpret_cast<const char*>(upper), upper_len);
  std::string k, v;
  bool found;
  // single-row seeks start with a tiny locked walk (cursor stepping issues
  // one seek per row); a run of snapshot-invisible or tombstoned entries
  // continues via chunk re-init with ×4 growth
  constexpr uint64_t kSeekMemChunk = 16;
  if (!for_prev) {
    ChunkedMerge cm(e, cf, snap_seq, tg < lo ? lo : tg, up, has_upper != 0,
                    kSeekMemChunk);
    found = cm.next(&k, &v);
  } else {
    // last visible key <= target within [lower, upper): the reverse bound is
    // exclusive, so extend the inclusive target by one zero byte
    std::string end_incl = tg + std::string(1, '\0');
    if (has_upper && up < end_incl) end_incl = up;
    ReverseChunkedMerge cm(e, cf, snap_seq, lo, end_incl, true, kSeekMemChunk);
    found = cm.next(&k, &v);
  }
  if (!found) return 0;
  *kout = static_cast<uint8_t*>(malloc(k.size()));
  memcpy(*kout, k.data(), k.size());
  *kout_len = k.size();
  *vout = static_cast<uint8_t*>(malloc(v.size()));
  memcpy(*vout, v.data(), v.size());
  *vout_len = v.size();
  return 1;
}

void eng_free(uint8_t* p) { free(p); }

uint64_t eng_stats_keys(void* h, int cf) {
  Engine* e = static_cast<Engine*>(h);
  std::shared_lock lk(e->mu);
  return e->cfs[cf].size();
}

// --- compaction -------------------------------------------------------------
//
// The write path only trims a key's version chain when that key is written
// again; deleted-and-never-touched keys would otherwise hold a tombstone
// forever (rocksdb removes them in background compaction).  One compaction
// step walks at most max_keys keys of one CF under the write lock, drops
// versions no live snapshot can see, and physically erases keys whose
// newest reachable state is a tombstone.  The caller (a Python driver
// thread — the GIL is released during the call, so it is genuinely
// background work) resumes from *resume to bound write-lock hold times,
// exactly the slice-by-slice shape of rocksdb's per-file compactions.
//
// Returns versions dropped (erased keys count their whole chain); sets
// *done=1 when the CF is exhausted, else *resume/*resume_len (caller
// eng_free) is the key to continue from.
long eng_compact_step(void* h, int cf, const uint8_t* from, uint64_t from_len,
                      uint64_t max_keys, uint8_t** resume,
                      uint64_t* resume_len, int* done) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::unique_lock lk(e->mu);
  Table& t = e->cfs[cf];
  uint64_t min_snap = std::min(e->min_live_snapshot(), e->seq);
  long dropped = 0;
  // deferred range-delete application: with no runs (in-memory engines, or
  // durable CFs before their first flush) the memtable is the whole store,
  // so a range tombstone no snapshot can see below is applied here and
  // reclaimed — compaction is where deferred deletes get paid for.  With
  // runs present the tombstone still masks flushed data and must stay
  // until flush carries it into a run and a merge folds it.
  if (e->runs[cf].empty() && !e->mem_rtombs[cf].empty()) {
    std::vector<RangeTomb> still_needed;
    for (auto& rt : e->mem_rtombs[cf]) {
      if (rt.seq > min_snap) {
        still_needed.push_back(std::move(rt));
        continue;
      }
      auto rit = t.lower_bound(rt.start);
      auto stop = t.lower_bound(rt.end);
      while (rit != stop) {
        Chain& ch = rit->second;
        while (!ch.empty() && ch.back().seq <= rt.seq) {
          e->mem_bytes -= std::min(e->mem_bytes,
                                   ch.back().value.size() + kVersionOverhead);
          ch.pop_back();
          dropped++;
        }
        if (ch.empty()) {
          e->mem_bytes -= std::min(e->mem_bytes,
                                   rit->first.size() + kKeyOverhead);
          rit = t.erase(rit);
        } else {
          ++rit;
        }
      }
      e->mem_bytes -= std::min(
          e->mem_bytes, rt.start.size() + rt.end.size() + kVersionOverhead);
    }
    e->mem_rtombs[cf] = std::move(still_needed);
  }
  uint64_t seen = 0;
  auto it = t.lower_bound(std::string(reinterpret_cast<const char*>(from), from_len));
  while (it != t.end() && seen < max_keys) {
    Chain& chain = it->second;
    // trim: keep versions newer than min_snap plus the newest one <= min_snap
    size_t keep = chain.size();
    for (size_t i = 0; i < chain.size(); i++) {
      if (chain[i].seq <= min_snap) {
        keep = i + 1;
        break;
      }
    }
    for (size_t i = keep; i < chain.size(); i++) {
      e->mem_bytes -= std::min(e->mem_bytes,
                               chain[i].value.size() + kVersionOverhead);
      dropped++;
    }
    chain.resize(keep);
    // erase: the newest version overall is a tombstone no snapshot can miss
    // — but only when no sorted run could hold an older value it still
    // masks; with runs present the tombstone must survive in the memtable
    // (and later in a run) until a bottom-level merge drops it
    if (!chain.empty() && chain.front().tombstone &&
        chain.front().seq <= min_snap && e->runs[cf].empty()) {
      dropped += static_cast<long>(chain.size());
      uint64_t key_cost = it->first.size() + kKeyOverhead;
      for (const auto& v : chain)
        key_cost += v.value.size() + kVersionOverhead;
      e->mem_bytes -= std::min(e->mem_bytes, key_cost);
      it = t.erase(it);
    } else {
      ++it;
    }
    seen++;
  }
  if (it == t.end()) {
    *done = 1;
  } else {
    *done = 0;
    *resume = static_cast<uint8_t*>(malloc(it->first.size()));
    memcpy(*resume, it->first.data(), it->first.size());
    *resume_len = it->first.size();
  }
  return dropped;
}

// --- MVCC range properties --------------------------------------------------
//
// The role of engine_rocks' MvccPropertiesCollector (properties.rs): cheap
// per-range statistics that tell GC whether a sweep is worth it at all.
// The collector knows this framework's CF_WRITE shape — keys carry an
// 8-byte descending-encoded commit_ts suffix, values start with the write
// type byte ('P'ut/'D'elete/'L'ock/'R'ollback).
//
// out[0]=num_entries  out[1]=num_rows (distinct user keys)
// out[2]=num_puts     out[3]=num_deletes
// out[4]=num_locks_rollbacks       out[5]=min_commit_ts  out[6]=max_commit_ts
// out[7]=max_row_versions (worst per-key version count)
int eng_mvcc_props(void* h, int cf, const uint8_t* start, uint64_t start_len,
                   const uint8_t* end_key, uint64_t end_len, int has_end,
                   uint64_t snap_seq, uint64_t* out) {
  Engine* e = static_cast<Engine*>(h);
  if (cf < 0 || cf >= kNumCfs) return -2;
  std::string s(reinterpret_cast<const char*>(start), start_len);
  std::string en(reinterpret_cast<const char*>(end_key), end_len);
  uint64_t entries = 0, rows = 0, puts = 0, dels = 0, other = 0;
  uint64_t min_ts = UINT64_MAX, max_ts = 0, max_row = 0, cur_row = 0;
  std::string cur_user;
  bool have_user = false;
  // Callers pass the CURRENT seq, not a registered snapshot; ChunkedMerge's
  // chunk re-inits are only consistent at a *pinned* seq (otherwise version
  // chains visible at snap_seq can be trimmed between chunks), so register
  // it for the duration of the walk.
  {
    std::unique_lock lk(e->mu);
    e->snapshots.insert(snap_seq);
  }
  ChunkedMerge mi(e, cf, snap_seq, s, en, has_end != 0, kScanMemChunk);
  std::string k, val;
  while (mi.next(&k, &val)) {
    const std::string* v = &val;
    entries++;
    if (k.size() >= 8) {
      // commit_ts rides the last 8 key bytes, bit-inverted big-endian
      uint64_t ts = 0;
      for (int i = 0; i < 8; i++)
        ts = (ts << 8) | static_cast<uint8_t>(~k[k.size() - 8 + i]);
      if (ts < min_ts) min_ts = ts;
      if (ts > max_ts) max_ts = ts;
      std::string user = k.substr(0, k.size() - 8);
      if (!have_user || user != cur_user) {
        rows++;
        cur_user = std::move(user);
        have_user = true;
        cur_row = 0;
      }
      cur_row++;
      if (cur_row > max_row) max_row = cur_row;
    }
    if (!v->empty()) {
      char wt = (*v)[0];
      if (wt == 'P') puts++;
      else if (wt == 'D') dels++;
      else other++;
    }
  }
  {
    std::unique_lock lk(e->mu);
    auto sit = e->snapshots.find(snap_seq);
    if (sit != e->snapshots.end()) e->snapshots.erase(sit);
  }
  out[0] = entries;
  out[1] = rows;
  out[2] = puts;
  out[3] = dels;
  out[4] = other;
  out[5] = min_ts == UINT64_MAX ? 0 : min_ts;
  out[6] = max_ts;
  out[7] = max_row;
  return 0;
}

}  // extern "C"
