// File-at-rest encryption primitives shared by the LSM engine and the raft
// log engine — the native half of the reference's encryption env
// (components/encryption/src/manager/mod.rs:398 DataKeyManager +
// engine_rocks/src/encryption.rs:30 env wrapper), re-expressed for this
// framework's file formats:
//
//   * cipher: ChaCha20 (RFC 7539 block function) used as an offset-
//     addressable keystream — functionally the reference's AES-CTR choice
//     (crypter.rs) with a primitive this toolchain can carry dependency-free.
//     The keystream is seekable by 64-byte block, so whole files XOR in place
//     and pread-at-offset reads decrypt exactly the bytes they fetched;
//     formats and offsets stay byte-identical to the plaintext layout.
//   * per-file metadata: a `<file>.enc` sidecar holding (key id, nonce) —
//     the per-file form of the reference's file dictionary
//     (file_dict_file.rs).  Sidecars carry NO key material; raw data keys
//     arrive over the FFI from the Python DataKeyManager, whose persisted
//     dictionary is sealed under the master key.
//   * migration: a data file without a sidecar is plaintext and stays
//     readable; encryption applies to files written after it is enabled.
//
// Crash ordering contract: the sidecar is written and fsynced BEFORE its
// data file becomes visible (rename / first append), so an encrypted file
// can never exist without the metadata needed to read it.
#pragma once

#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <unistd.h>

#include <map>
#include <array>
#include <string>
#include <vector>

namespace enc {

inline uint32_t rotl32(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

inline void chacha_block(const uint8_t key[32], const uint8_t nonce[12],
                         uint32_t counter, uint8_t out[64]) {
  static const uint32_t c[4] = {0x61707865, 0x3320646e, 0x79622d32, 0x6b206574};
  uint32_t st[16];
  st[0] = c[0]; st[1] = c[1]; st[2] = c[2]; st[3] = c[3];
  for (int i = 0; i < 8; i++) memcpy(&st[4 + i], key + 4 * i, 4);
  st[12] = counter;
  memcpy(&st[13], nonce, 4);
  memcpy(&st[14], nonce + 4, 4);
  memcpy(&st[15], nonce + 8, 4);
  uint32_t x[16];
  memcpy(x, st, sizeof(x));
#define QR(a, b, c, d)                                        \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotl32(x[d], 16);        \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotl32(x[b], 12);        \
  x[a] += x[b]; x[d] ^= x[a]; x[d] = rotl32(x[d], 8);         \
  x[c] += x[d]; x[b] ^= x[c]; x[b] = rotl32(x[b], 7)
  for (int i = 0; i < 10; i++) {
    QR(0, 4, 8, 12); QR(1, 5, 9, 13); QR(2, 6, 10, 14); QR(3, 7, 11, 15);
    QR(0, 5, 10, 15); QR(1, 6, 11, 12); QR(2, 7, 8, 13); QR(3, 4, 9, 14);
  }
#undef QR
  for (int i = 0; i < 16; i++) {
    uint32_t v = x[i] + st[i];
    memcpy(out + 4 * i, &v, 4);
  }
}

// XOR `len` bytes at absolute file offset `off` with the (key, nonce)
// keystream.  Counter 0 corresponds to file offset 0; any suffix/slice of a
// file decrypts independently.
//
// The RFC 7539 block counter is 32 bits, which runs out at 2^32 blocks =
// 256 GiB — past that a bare truncation would REUSE the first keystream
// blocks (two-time pad).  XChaCha-style, the high 32 bits of the 64-bit
// block index fold into the first nonce word instead: offsets below the
// boundary are byte-identical to the plain construction (high bits are 0),
// and every 256 GiB segment beyond it runs under a distinct effective
// nonce, so the keystream never repeats within a file.
inline void xor_at(const uint8_t key[32], const uint8_t nonce[12],
                   uint64_t off, uint8_t* buf, size_t len) {
  uint8_t ks[64];
  size_t done = 0;
  while (done < len) {
    uint64_t block = (off + done) / 64;
    size_t skip = (off + done) % 64;
    uint32_t hi = static_cast<uint32_t>(block >> 32);
    if (hi == 0) {
      chacha_block(key, nonce, static_cast<uint32_t>(block), ks);
    } else {
      uint8_t n2[12];
      memcpy(n2, nonce, 12);
      uint32_t w0;
      memcpy(&w0, n2, 4);
      w0 ^= hi;
      memcpy(n2, &w0, 4);
      chacha_block(key, n2, static_cast<uint32_t>(block), ks);
    }
    size_t take = 64 - skip;
    if (take > len - done) take = len - done;
    for (size_t i = 0; i < take; i++) buf[done + i] ^= ks[skip + i];
    done += take;
  }
}

struct FileKey {
  bool on = false;
  uint32_t key_id = 0;
  std::array<uint8_t, 32> key{};
  std::array<uint8_t, 12> nonce{};
};

// engine-wide key registry, fed from the Python DataKeyManager over the FFI
struct State {
  bool on = false;
  uint32_t current = 0;
  std::map<uint32_t, std::array<uint8_t, 32>> keys;
};

static const char kSidecarMagic[4] = {'E', 'N', 'C', '1'};
static const size_t kSidecarEntry = 16;  // key_id u32 + nonce 12
static const size_t kSidecarMaxEntries = 4;

inline std::string sidecar_path(const std::string& path) { return path + ".enc"; }

// Write + fsync a sidecar holding `entries` (key_id, nonce) pairs, NEWEST
// first.  A sidecar may describe more than one cipher identity for its data
// file: when a compaction reuses an input run's final name, the new entry is
// PREPENDED and the old one kept, so whichever generation of the file a
// crash leaves behind stays decryptable — the run reader validates each
// candidate against the file's own magic/CRC and picks the one that fits.
inline int sidecar_write(const std::string& path,
                         const FileKey* entries, size_t n) {
  std::string sp = sidecar_path(path);
  std::string tmp = sp + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  std::string buf(kSidecarMagic, 4);
  for (size_t i = 0; i < n && i < kSidecarMaxEntries; i++) {
    char e[kSidecarEntry];
    memcpy(e, &entries[i].key_id, 4);
    memcpy(e + 4, entries[i].nonce.data(), 12);
    buf.append(e, kSidecarEntry);
  }
  bool ok = fwrite(buf.data(), 1, buf.size(), f) == buf.size() &&
            fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), sp.c_str()) != 0) {
    unlink(tmp.c_str());
    return -1;
  }
  return 0;
}

// Every entry of the sidecar, newest first.  0 = found (out filled; entries
// whose key id is unknown are skipped UNLESS that leaves none — then -1),
// 1 = absent (plaintext file), -1 = damaged/undecryptable.
inline int sidecar_read_all(const State& st, const std::string& path,
                            std::vector<FileKey>* out) {
  out->clear();
  FILE* f = fopen(sidecar_path(path).c_str(), "rb");
  if (!f) return 1;
  char buf[4 + kSidecarMaxEntries * kSidecarEntry];
  size_t got = fread(buf, 1, sizeof(buf), f);
  fclose(f);
  if (got < 4 || memcmp(buf, kSidecarMagic, 4) != 0 ||
      (got - 4) % kSidecarEntry != 0) {
    return -1;
  }
  size_t n = (got - 4) / kSidecarEntry;
  bool any_entry = n > 0;
  for (size_t i = 0; i < n; i++) {
    const char* e = buf + 4 + i * kSidecarEntry;
    FileKey fk;
    memcpy(&fk.key_id, e, 4);
    memcpy(fk.nonce.data(), e + 4, 12);
    auto it = st.keys.find(fk.key_id);
    if (it == st.keys.end()) continue;  // rotated-away key: try the others
    fk.key = it->second;
    fk.on = true;
    out->push_back(fk);
  }
  if (any_entry && out->empty()) return -1;  // keys unknown: fail loudly
  return 0;
}

// Newest-entry convenience for files whose names are never reused (WAL and
// raft-log segments): exactly one cipher identity can apply.
inline int sidecar_read(const State& st, const std::string& path, FileKey* fk) {
  std::vector<FileKey> all;
  int r = sidecar_read_all(st, path, &all);
  if (r != 0) {
    fk->on = false;
    return r;
  }
  if (all.empty()) {
    fk->on = false;
    return 1;
  }
  *fk = all.front();
  return 0;
}

// Create the FileKey for a file about to be (re)written under the current
// data key with a fresh random nonce, persisting the sidecar FIRST.  Any
// existing entries for the path are kept behind the new one (name-reuse
// safety, see sidecar_write).  Returns 0 on success.
inline int file_begin(const State& st, const std::string& path, FileKey* fk) {
  if (!st.on) {
    fk->on = false;
    return 0;
  }
  int rfd = open("/dev/urandom", O_RDONLY);
  if (rfd < 0) return -1;
  bool ok = read(rfd, fk->nonce.data(), 12) == 12;
  close(rfd);
  if (!ok) return -1;
  auto it = st.keys.find(st.current);
  if (it == st.keys.end()) return -1;
  fk->key_id = st.current;
  fk->key = it->second;
  fk->on = true;
  std::vector<FileKey> entries;
  entries.push_back(*fk);
  std::vector<FileKey> prior;
  if (sidecar_read_all(st, path, &prior) == 0) {
    for (const FileKey& p : prior) {
      if (entries.size() >= kSidecarMaxEntries) break;
      entries.push_back(p);
    }
  }
  return sidecar_write(path, entries.data(), entries.size());
}

inline void maybe_xor(const FileKey& fk, uint64_t off, void* buf, size_t len) {
  if (fk.on && len) {
    xor_at(fk.key.data(), fk.nonce.data(), off, static_cast<uint8_t*>(buf), len);
  }
}

}  // namespace enc
