// raftlog.cc — purpose-built, append-optimized raft log store.
//
// The role of the reference's raft-engine crate (selected at
// components/server/src/server.rs:153-157, trait surface
// components/raft_log_engine/src/engine.rs:25): raft log entries and hard
// state live in segmented append-only files with GROUP-COMMIT fdatasync,
// logical purge markers instead of range deletes, and rewrite of live tail
// records out of mostly-dead segments so old files can be unlinked.  This is
// deliberately NOT the LSM in engine.cc — an LSM pays sorted-run machinery
// (memtable ordering, run merges, bloom filters) for point-lookup workloads
// the raft log never has: the log is written append-only in index order and
// read back only as contiguous ranges (catch-up) or sequentially (recovery).
//
// On-disk format, per segment file "%010u.rlog":
//   record  := crc32(u32, over type+payload) | len(u32, payload bytes) |
//              type(u8) | payload
//   ENTRIES := region(u64) | first_index(u64) | count(u32) |
//              count x len(u32) | count x blob        (type 1)
//   STATE   := region(u64) | blob                     (type 2)
//   PURGE   := region(u64) | to(u64)                  (type 3)
//   CLEAN   := region(u64)                            (type 4)
//   REWRITE := same payload as ENTRIES                (type 5)
//
// Replay rules (which make crash recovery a pure left fold over segments):
//   ENTRIES  truncates any indexed suffix >= first_index, then appends —
//            the raft conflict-truncation rule, applied at the storage layer.
//   REWRITE  replaces the stored location of indexes it already holds and is
//            otherwise ignored — relocation only, never truncation, so a
//            rewrite record replayed after a later conflicting append cannot
//            resurrect dead entries.
//   PURGE    drops indexed entries <= to.
//   CLEAN    forgets the region entirely.
// A torn record at the tail of the LAST segment is truncated (crash mid
// append); corruption anywhere else fails open() loudly.
//
// Concurrency: appends serialize on wmu (one writer to the active file);
// index updates take mu exclusively but are O(batch); readers (fetch/term
// queries) take mu shared and pread segment files through shared_ptr-held
// fds, so a concurrent segment unlink never yanks a file out from under a
// reader.  fdatasync is group-committed: every waiter whose append landed
// before the in-flight fsync started piggybacks on it; the rest elect one
// new syncer (sync_done covers all appends <= the covered sequence).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include "crypt.h"

namespace {

// ---------------------------------------------------------------------------
// crc32 (IEEE, table-driven) — same polynomial engine.cc uses, re-derived
// here so the two libraries stay independently buildable.
// ---------------------------------------------------------------------------

uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n, uint32_t seed = 0) {
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// little-endian scalar IO on byte buffers
// ---------------------------------------------------------------------------

void put_u32(std::string& b, uint32_t v) { b.append(reinterpret_cast<const char*>(&v), 4); }
void put_u64(std::string& b, uint64_t v) { b.append(reinterpret_cast<const char*>(&v), 8); }
uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
uint64_t get_u64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }

constexpr uint8_t REC_ENTRIES = 1;
constexpr uint8_t REC_STATE = 2;
constexpr uint8_t REC_PURGE = 3;
constexpr uint8_t REC_CLEAN = 4;
constexpr uint8_t REC_REWRITE = 5;
constexpr size_t REC_HDR = 9;  // crc(4) + len(4) + type(1)

struct Seg {
  uint32_t id;
  int fd;
  enc::FileKey fk;  // per-segment encryption (sidecar-derived)
  explicit Seg(uint32_t i, int f) : id(i), fd(f) {}
  ~Seg() {
    if (fd >= 0) close(fd);
  }
  Seg(const Seg&) = delete;
  Seg& operator=(const Seg&) = delete;
};

struct Loc {
  uint32_t seg;
  uint32_t off;  // byte offset of the entry blob within the segment file
  uint32_t len;
};

struct RegionIdx {
  uint64_t first = 0;  // raft index of locs.front(); meaningless when empty
  std::deque<Loc> locs;
  std::string state;     // latest hard-state blob (served from memory)
  uint32_t state_seg = 0;  // segment holding the latest STATE record (0=none)
  bool has_state = false;
  uint64_t last() const { return first + locs.size() - 1; }
};

int fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (fd < 0) return -1;
  int r = fsync(fd);
  close(fd);
  return r;
}

struct RaftLogEng {
  enc::State enc;  // data-key registry (DataKeyManager over the FFI)
  std::string dir;
  uint64_t seg_bytes;
  int sync_default;          // 1 = grouped fdatasync per append, 0 = buffered
  uint32_t rewrite_max;      // rewrite a dead-ish segment holding <= this many live entries

  std::shared_mutex mu;      // index + segment map
  std::mutex wmu;            // file appends (one writer to the active file)
  std::map<uint32_t, std::shared_ptr<Seg>> segs;
  uint32_t active = 0;
  std::atomic<uint64_t> active_size{0};
  std::unordered_map<uint64_t, RegionIdx> regions;
  std::unordered_map<uint32_t, uint64_t> live;  // live entry count per segment

  // group fsync state
  std::mutex smu;
  std::condition_variable scv;
  uint64_t append_seq = 0;   // bumped under wmu after each append lands
  uint64_t sync_done = 0;    // all appends <= this are fdatasync-durable
  bool syncing = false;

  // stats
  uint64_t rewrites = 0;
  uint64_t purged_entries = 0;

  std::string err;

  // ---- segment lifecycle (wmu held) ----
  std::string seg_path(uint32_t id) const {
    char name[32];
    snprintf(name, sizeof(name), "%010u.rlog", id);
    return dir + "/" + name;
  }

  bool roll_segment() {
    // finish the old active: its bytes must be durable before the new file
    // supersedes it, otherwise sync_done (which a later fsync of the NEW
    // file advances past them) would lie about them
    if (active != 0) {
      auto it = segs.find(active);
      if (it != segs.end()) fdatasync(it->second->fd);
      std::lock_guard<std::mutex> lk(smu);
      sync_done = append_seq;
    }
    uint32_t id = active + 1;
    enc::FileKey fk;
    if (enc::file_begin(enc, seg_path(id), &fk) != 0) {
      err = "encryption sidecar write failed: " + seg_path(id);
      return false;
    }
    int fd = open(seg_path(id).c_str(), O_CREAT | O_RDWR | O_APPEND, 0644);
    if (fd < 0) {
      err = "open segment failed: " + seg_path(id);
      return false;
    }
    fsync_dir(dir);
    std::unique_lock<std::shared_mutex> lk(mu);
    auto seg = std::make_shared<Seg>(id, fd);
    seg->fk = fk;
    segs.emplace(id, seg);
    active = id;
    active_size = 0;
    return true;
  }

  // append one framed record; returns payload offset in the active segment
  // or UINT64_MAX on IO error.  wmu held.
  uint64_t write_record(uint8_t type, const std::string& payload) {
    if (active == 0 || active_size >= seg_bytes) {
      if (!roll_segment()) return UINT64_MAX;
    }
    std::string frame;
    frame.reserve(REC_HDR + payload.size());
    uint32_t crc = crc32(&type, 1);
    crc = crc32(reinterpret_cast<const uint8_t*>(payload.data()), payload.size(), crc);
    put_u32(frame, crc);
    put_u32(frame, static_cast<uint32_t>(payload.size()));
    frame.push_back(static_cast<char>(type));
    frame += payload;
    int fd;
    enc::FileKey fk;
    {
      // gc can erase other map nodes under mu concurrently; the active
      // segment itself is never a gc victim, but the map needs the lock
      std::shared_lock<std::shared_mutex> lk(mu);
      fd = segs[active]->fd;
      fk = segs[active]->fk;
    }
    enc::maybe_xor(fk, active_size, &frame[0], frame.size());
    const char* p = frame.data();
    size_t left = frame.size();
    while (left > 0) {
      ssize_t w = write(fd, p, left);
      if (w < 0) {
        err = "segment write failed";
        return UINT64_MAX;
      }
      p += w;
      left -= static_cast<size_t>(w);
    }
    uint64_t payload_off = active_size + REC_HDR;
    active_size += frame.size();
    return payload_off;
  }

  // group-commit: wait until everything appended up to my_seq is fsynced,
  // doing the fsync ourselves if no in-flight sync will cover it.
  void sync_to(uint64_t my_seq) {
    std::unique_lock<std::mutex> lk(smu);
    for (;;) {
      if (sync_done >= my_seq) return;
      if (!syncing) break;
      scv.wait(lk);
    }
    syncing = true;
    // everything appended so far rides this fsync (the group)
    uint64_t covered = append_seq;
    lk.unlock();
    // mu is never taken while smu is held (ABBA guard: rl_stats and this
    // function both order mu -> smu / smu-released -> mu).  A roll between
    // the capture above and the pread of `active` fsyncs the old file, so
    // fsyncing whatever is active NOW still covers every append <= covered.
    std::shared_ptr<Seg> s;
    {
      std::shared_lock<std::shared_mutex> ilk(mu);
      auto it = segs.find(active);
      if (it != segs.end()) s = it->second;
    }
    if (s) fdatasync(s->fd);
    lk.lock();
    syncing = false;
    if (covered > sync_done) sync_done = covered;
    scv.notify_all();
  }

  // ---- index mutation (mu exclusive) ----

  void index_append(uint64_t region, uint64_t first_index, uint32_t count,
                    const uint32_t* lens, uint64_t blob_base, uint32_t seg) {
    RegionIdx& ri = regions[region];
    if (!ri.locs.empty()) {
      if (first_index <= ri.last()) {
        // conflict truncation: drop indexed suffix >= first_index
        uint64_t keep = first_index > ri.first ? first_index - ri.first : 0;
        while (ri.locs.size() > keep) {
          live[ri.locs.back().seg]--;
          ri.locs.pop_back();
        }
      }
      // a gap (first_index > last+1) only happens after snapshot-install
      // purged everything; with a non-empty deque it means corruption
      if (!ri.locs.empty() && first_index != ri.last() + 1) {
        // defensive: reset to the new contiguous run
        for (const Loc& l : ri.locs) live[l.seg]--;
        ri.locs.clear();
      }
    }
    if (ri.locs.empty()) ri.first = first_index;
    uint64_t off = blob_base;
    for (uint32_t i = 0; i < count; i++) {
      ri.locs.push_back(Loc{seg, static_cast<uint32_t>(off), lens[i]});
      off += lens[i];
    }
    live[seg] += count;
  }

  // REWRITE semantics: relocate indexes we already hold, and (re)insert
  // contiguously-adjacent ones we don't — after gc unlinks the victim
  // segment, a REWRITE record in a later segment is the ONLY copy of those
  // entries on replay, and they may sit BELOW the region's current first
  // (their original record died with the victim).  Never truncates, so a
  // rewrite replayed after a conflicting append cannot resurrect a dead
  // suffix; non-contiguous leftovers (purged later in the record stream
  // than this rewrite was written) are dropped by the PURGE replay anyway.
  void index_rewrite(uint64_t region, uint64_t first_index, uint32_t count,
                     const uint32_t* lens, uint64_t blob_base, uint32_t seg) {
    if (count == 0) return;
    std::vector<uint64_t> offs(count);
    uint64_t off = blob_base;
    for (uint32_t i = 0; i < count; i++) {
      offs[i] = off;
      off += lens[i];
    }
    RegionIdx& ri = regions[region];
    if (ri.locs.empty()) {
      ri.first = first_index;
      for (uint32_t i = 0; i < count; i++)
        ri.locs.push_back(Loc{seg, static_cast<uint32_t>(offs[i]), lens[i]});
      live[seg] += count;
      return;
    }
    uint64_t lo = ri.first;  // portion below this prepends (descending pass)
    for (int64_t i = static_cast<int64_t>(count) - 1; i >= 0; i--) {
      uint64_t idx = first_index + static_cast<uint64_t>(i);
      if (idx >= lo) continue;
      if (idx == ri.first - 1) {
        ri.locs.push_front(Loc{seg, static_cast<uint32_t>(offs[i]), lens[i]});
        ri.first--;
        live[seg]++;
      }  // else: non-adjacent below-front — unreachable entry, drop
    }
    for (uint32_t i = 0; i < count; i++) {
      uint64_t idx = first_index + i;
      if (idx < lo) continue;  // handled (or dropped) above
      if (idx <= ri.last()) {
        Loc& l = ri.locs[idx - ri.first];
        live[l.seg]--;
        l = Loc{seg, static_cast<uint32_t>(offs[i]), lens[i]};
        live[seg]++;
      } else if (idx == ri.last() + 1) {
        ri.locs.push_back(Loc{seg, static_cast<uint32_t>(offs[i]), lens[i]});
        live[seg]++;
      }
    }
  }

  void index_purge(uint64_t region, uint64_t to) {
    auto it = regions.find(region);
    if (it == regions.end()) return;
    RegionIdx& ri = it->second;
    while (!ri.locs.empty() && ri.first <= to) {
      live[ri.locs.front().seg]--;
      ri.locs.pop_front();
      ri.first++;
      purged_entries++;
    }
  }

  void index_clean(uint64_t region) {
    auto it = regions.find(region);
    if (it == regions.end()) return;
    for (const Loc& l : it->second.locs) live[l.seg]--;
    regions.erase(it);
  }

  // ---- segment GC: unlink dead segments, rewrite nearly-dead ones ----

  struct RewritePlan {
    uint64_t region;
    uint64_t first_index;
    std::vector<Loc> locs;  // contiguous run living in the victim segment
  };

  // Re-check a plan against the live index (caller holds wmu, takes mu
  // shared): every planned index must still point at exactly the loc we
  // preread, else a concurrent conflict-truncating append replaced those
  // entries and writing the stale REWRITE record would poison replay.
  bool plan_still_valid(const RewritePlan& p) {
    std::shared_lock<std::shared_mutex> lk(mu);
    auto it = regions.find(p.region);
    if (it == regions.end() || it->second.locs.empty()) return false;
    const RegionIdx& ri = it->second;
    for (size_t i = 0; i < p.locs.size(); i++) {
      uint64_t idx = p.first_index + i;
      if (idx < ri.first || idx > ri.last()) return false;
      const Loc& cur = ri.locs[idx - ri.first];
      const Loc& old = p.locs[i];
      if (cur.seg != old.seg || cur.off != old.off || cur.len != old.len) return false;
    }
    return true;
  }

  // Decide what (if anything) to do about the oldest segment.  Returns:
  // 0 = nothing, 1 = deleted it, 2 = caller should run `plans` rewrites.
  int gc_step(std::vector<RewritePlan>& plans, std::vector<uint64_t>& state_regions) {
    std::unique_lock<std::shared_mutex> lk(mu);
    if (segs.size() <= 1) return 0;
    uint32_t victim = segs.begin()->first;
    if (victim == active) return 0;
    uint64_t nlive = 0;
    auto lit = live.find(victim);
    if (lit != live.end()) nlive = lit->second;
    bool state_pinned = false;
    for (auto& [rid, ri] : regions) {
      if (ri.has_state && ri.state_seg == victim) {
        state_pinned = true;
        state_regions.push_back(rid);
      }
    }
    if (nlive == 0 && !state_pinned) {
      std::string path = seg_path(victim);
      segs.erase(victim);  // shared_ptr: open readers keep the fd alive
      live.erase(victim);
      lk.unlock();
      unlink(path.c_str());
      unlink(enc::sidecar_path(path).c_str());
      fsync_dir(dir);
      return 1;
    }
    if (nlive > rewrite_max) return 0;
    // collect contiguous runs of victim-resident entries per region
    for (auto& [rid, ri] : regions) {
      uint64_t idx = ri.first;
      RewritePlan cur{rid, 0, {}};
      for (const Loc& l : ri.locs) {
        if (l.seg == victim) {
          if (cur.locs.empty()) cur.first_index = idx;
          if (!cur.locs.empty() && cur.first_index + cur.locs.size() != idx) {
            plans.push_back(std::move(cur));
            cur = RewritePlan{rid, idx, {}};
          }
          cur.locs.push_back(l);
        } else if (!cur.locs.empty()) {
          plans.push_back(std::move(cur));
          cur = RewritePlan{rid, 0, {}};
        }
        idx++;
      }
      if (!cur.locs.empty()) plans.push_back(std::move(cur));
    }
    return 2;
  }

  bool pread_exact(const std::shared_ptr<Seg>& s, uint64_t off, uint32_t len, uint8_t* out) {
    ssize_t r = pread(s->fd, out, len, static_cast<off_t>(off));
    if (r != static_cast<ssize_t>(len)) return false;
    enc::maybe_xor(s->fk, off, out, len);
    return true;
  }

  // run the GC loop after a purge/clean.  Never holds mu across file IO.
  void gc() {
    for (int guard = 0; guard < 64; guard++) {
      std::vector<RewritePlan> plans;
      std::vector<uint64_t> state_regions;
      int what = gc_step(plans, state_regions);
      if (what == 0) return;
      if (what == 1) continue;  // deleted one; try the next oldest
      // rewrite: copy live records out of the victim into the active seg
      bool wrote_any = false;
      for (const RewritePlan& p : plans) {
        std::shared_ptr<Seg> src;
        {
          std::shared_lock<std::shared_mutex> lk(mu);
          auto it = segs.find(p.locs[0].seg);
          if (it == segs.end()) continue;  // raced with delete
          src = it->second;
        }
        std::string payload;
        put_u64(payload, p.region);
        put_u64(payload, p.first_index);
        put_u32(payload, static_cast<uint32_t>(p.locs.size()));
        std::vector<uint32_t> lens;
        lens.reserve(p.locs.size());
        for (const Loc& l : p.locs) {
          put_u32(payload, l.len);
          lens.push_back(l.len);
        }
        size_t blobs_at = payload.size();
        size_t total = 0;
        for (const Loc& l : p.locs) total += l.len;
        payload.resize(blobs_at + total);
        uint8_t* dst = reinterpret_cast<uint8_t*>(&payload[blobs_at]);
        bool ok = true;
        for (const Loc& l : p.locs) {
          if (!pread_exact(src, l.off, l.len, dst)) {
            ok = false;
            break;
          }
          dst += l.len;
        }
        if (!ok) return;  // IO error: leave the segment alone
        std::lock_guard<std::mutex> wlk(wmu);
        // a conflicting append may have replaced these indexes between plan
        // capture and now; appends serialize on wmu, so a validation here
        // stays true through the write below.  Abort the whole plan on any
        // change — the next purge re-plans from fresh state.
        if (!plan_still_valid(p)) continue;
        uint64_t payload_off = write_record(REC_REWRITE, payload);
        if (payload_off == UINT64_MAX) return;
        wrote_any = true;
        uint32_t seg_now;
        {
          std::unique_lock<std::shared_mutex> lk(mu);
          seg_now = active;
          index_rewrite(p.region, p.first_index, static_cast<uint32_t>(lens.size()),
                        lens.data(), payload_off + 20 + 4 * lens.size(), seg_now);
        }
        std::lock_guard<std::mutex> slk(smu);
        append_seq++;
      }
      // re-home pinned states (served from memory; just re-emit)
      for (uint64_t rid : state_regions) {
        std::string blob;
        {
          std::shared_lock<std::shared_mutex> lk(mu);
          auto it = regions.find(rid);
          if (it == regions.end() || !it->second.has_state) continue;
          blob = it->second.state;
        }
        std::string payload;
        put_u64(payload, rid);
        payload += blob;
        std::lock_guard<std::mutex> wlk(wmu);
        if (write_record(REC_STATE, payload) == UINT64_MAX) return;
        wrote_any = true;
        {
          std::unique_lock<std::shared_mutex> lk(mu);
          auto it = regions.find(rid);
          if (it != regions.end()) it->second.state_seg = active;
        }
        std::lock_guard<std::mutex> slk(smu);
        append_seq++;
      }
      rewrites++;
      if (wrote_any) {
        // the relocated records MUST be durable before the next gc_step
        // unlinks their only other copy — regardless of sync_default, since
        // unlink itself is immediately durable (fsync_dir)
        uint64_t seq;
        {
          std::lock_guard<std::mutex> slk(smu);
          seq = append_seq;
        }
        sync_to(seq);
      }
      // loop: next gc_step sees the victim fully dead and unlinks it
    }
  }

  // ---- replay ----

  bool replay_segment(uint32_t id, int fd, const enc::FileKey& fk, bool is_last) {
    struct stat st;
    if (fstat(fd, &st) != 0) {
      err = "fstat failed";
      return false;
    }
    uint64_t size = static_cast<uint64_t>(st.st_size);
    std::vector<uint8_t> buf(size);
    if (size > 0) {
      ssize_t r = pread(fd, buf.data(), size, 0);
      if (r != static_cast<ssize_t>(size)) {
        err = "segment read failed";
        return false;
      }
      enc::maybe_xor(fk, 0, buf.data(), size);
    }
    uint64_t pos = 0;
    while (pos + REC_HDR <= size) {
      uint32_t crc = get_u32(&buf[pos]);
      uint32_t len = get_u32(&buf[pos + 4]);
      uint8_t type = buf[pos + 8];
      if (pos + REC_HDR + len > size) break;  // torn tail
      uint32_t got = crc32(&buf[pos + 8], 1);
      got = crc32(&buf[pos + 9], len, got);
      if (got != crc) break;  // torn/corrupt tail
      const uint8_t* pl = &buf[pos + 9];
      uint64_t payload_off = pos + REC_HDR;
      switch (type) {
        case REC_ENTRIES:
        case REC_REWRITE: {
          if (len < 20) break;
          uint64_t region = get_u64(pl);
          uint64_t first_index = get_u64(pl + 8);
          uint32_t count = get_u32(pl + 16);
          if (20 + 4ull * count > len) break;
          std::vector<uint32_t> lens(count);
          for (uint32_t i = 0; i < count; i++) lens[i] = get_u32(pl + 20 + 4 * i);
          uint64_t blob_base = payload_off + 20 + 4ull * count;
          if (type == REC_ENTRIES)
            index_append(region, first_index, count, lens.data(), blob_base, id);
          else
            index_rewrite(region, first_index, count, lens.data(), blob_base, id);
          break;
        }
        case REC_STATE: {
          if (len < 8) break;
          uint64_t region = get_u64(pl);
          RegionIdx& ri = regions[region];
          ri.state.assign(reinterpret_cast<const char*>(pl + 8), len - 8);
          ri.state_seg = id;
          ri.has_state = true;
          break;
        }
        case REC_PURGE: {
          if (len < 16) break;
          index_purge(get_u64(pl), get_u64(pl + 8));
          break;
        }
        case REC_CLEAN: {
          if (len < 8) break;
          index_clean(get_u64(pl));
          break;
        }
        default:
          break;  // forward-compat: unknown record types are skipped
      }
      pos += REC_HDR + len;
    }
    if (pos < size) {
      if (!is_last) {
        char msg[96];
        snprintf(msg, sizeof(msg), "corrupt record in non-tail segment %u at offset %llu",
                 id, static_cast<unsigned long long>(pos));
        err = msg;
        return false;
      }
      if (ftruncate(fd, static_cast<off_t>(pos)) != 0) {
        err = "tail truncate failed";
        return false;
      }
    }
    if (is_last) active_size = pos;
    return true;
  }

  bool open_dir() {
    mkdir(dir.c_str(), 0755);
    std::vector<uint32_t> ids;
    DIR* d = opendir(dir.c_str());
    if (d == nullptr) {
      err = "opendir failed: " + dir;
      return false;
    }
    while (dirent* de = readdir(d)) {
      unsigned id = 0;
      if (sscanf(de->d_name, "%10u.rlog", &id) == 1 && id > 0) ids.push_back(id);
    }
    closedir(d);
    std::sort(ids.begin(), ids.end());
    for (size_t i = 0; i < ids.size(); i++) {
      enc::FileKey fk;
      if (enc::sidecar_read(enc, seg_path(ids[i]), &fk) < 0) {
        err = "unreadable encryption sidecar: " + seg_path(ids[i]);
        return false;
      }
      int fd = open(seg_path(ids[i]).c_str(), O_RDWR | O_APPEND);
      if (fd < 0) {
        err = "open segment failed: " + seg_path(ids[i]);
        return false;
      }
      auto seg = std::make_shared<Seg>(ids[i], fd);
      seg->fk = fk;
      segs.emplace(ids[i], seg);
      if (!replay_segment(ids[i], fd, fk, i + 1 == ids.size())) return false;
    }
    if (!ids.empty()) active = ids.back();
    return true;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

static enc::State rl_make_enc(uint32_t current_id, const uint32_t* ids,
                              const uint8_t* keys32, int n) {
  enc::State st;
  for (int i = 0; i < n; i++) {
    std::array<uint8_t, 32> k;
    memcpy(k.data(), keys32 + 32 * i, 32);
    st.keys[ids[i]] = k;
  }
  st.current = current_id;
  st.on = n > 0;
  return st;
}

void* rl_open_enc(const char* dir, uint64_t seg_bytes, int sync_default,
                  uint32_t rewrite_max, uint32_t current_id,
                  const uint32_t* ids, const uint8_t* keys32, int n,
                  char* errbuf, int errcap);

void* rl_open(const char* dir, uint64_t seg_bytes, int sync_default,
              uint32_t rewrite_max, char* errbuf, int errcap) {
  return rl_open_enc(dir, seg_bytes, sync_default, rewrite_max, 0, nullptr,
                     nullptr, 0, errbuf, errcap);
}

// Encrypted open (and the ONE open path — rl_open delegates with an empty
// registry): segments written from here on encrypt under current_id;
// existing segments decrypt per their sidecar; sidecar-less files read as
// plaintext (CF_RAFT-era migration continues to work).
void* rl_open_enc(const char* dir, uint64_t seg_bytes, int sync_default,
                  uint32_t rewrite_max, uint32_t current_id,
                  const uint32_t* ids, const uint8_t* keys32, int n,
                  char* errbuf, int errcap) {
  auto* e = new RaftLogEng();
  e->dir = dir;
  e->seg_bytes = seg_bytes ? seg_bytes : (64ull << 20);
  e->sync_default = sync_default;
  e->rewrite_max = rewrite_max ? rewrite_max : 4096;
  e->enc = rl_make_enc(current_id, ids, keys32, n);
  if (!e->open_dir()) {
    if (errbuf != nullptr && errcap > 0) {
      snprintf(errbuf, static_cast<size_t>(errcap), "%s", e->err.c_str());
    }
    delete e;
    return nullptr;
  }
  return e;
}

// Data-key rotation on a running log: new segments use current_id.
int rl_set_encryption(void* h, uint32_t current_id, const uint32_t* ids,
                      const uint8_t* keys32, int n) {
  auto* e = static_cast<RaftLogEng*>(h);
  std::lock_guard<std::mutex> wlk(e->wmu);
  std::unique_lock<std::shared_mutex> lk(e->mu);
  e->enc = rl_make_enc(current_id, ids, keys32, n);
  return 0;
}

void rl_close(void* h) { delete static_cast<RaftLogEng*>(h); }

// Append `count` entries (concatenated blobs + lens) starting at first_index,
// optionally with a new hard-state blob, in ONE durable record batch.
int rl_append(void* h, uint64_t region, uint64_t first_index, uint32_t count,
              const uint8_t* blobs, const uint32_t* lens, const uint8_t* state,
              uint32_t state_len, int sync) {
  auto* e = static_cast<RaftLogEng*>(h);
  uint64_t my_seq;
  {
    std::lock_guard<std::mutex> wlk(e->wmu);
    uint64_t blob_base = 0;
    if (count > 0) {
      std::string payload;
      size_t total = 0;
      for (uint32_t i = 0; i < count; i++) total += lens[i];
      payload.reserve(20 + 4 * count + total);
      put_u64(payload, region);
      put_u64(payload, first_index);
      put_u32(payload, count);
      for (uint32_t i = 0; i < count; i++) put_u32(payload, lens[i]);
      payload.append(reinterpret_cast<const char*>(blobs), total);
      uint64_t payload_off = e->write_record(REC_ENTRIES, payload);
      if (payload_off == UINT64_MAX) return -1;
      blob_base = payload_off + 20 + 4ull * count;
    }
    uint32_t entry_seg = e->active;
    if (state != nullptr && state_len > 0) {
      std::string payload;
      put_u64(payload, region);
      payload.append(reinterpret_cast<const char*>(state), state_len);
      if (e->write_record(REC_STATE, payload) == UINT64_MAX) return -1;
    }
    {
      std::unique_lock<std::shared_mutex> lk(e->mu);
      if (count > 0) e->index_append(region, first_index, count, lens, blob_base, entry_seg);
      if (state != nullptr && state_len > 0) {
        RegionIdx& ri = e->regions[region];
        ri.state.assign(reinterpret_cast<const char*>(state), state_len);
        ri.state_seg = e->active;
        ri.has_state = true;
      }
    }
    std::lock_guard<std::mutex> slk(e->smu);
    my_seq = ++e->append_seq;
  }
  int want_sync = sync < 0 ? e->sync_default : sync;
  if (want_sync != 0) e->sync_to(my_seq);
  return 0;
}

int rl_put_state(void* h, uint64_t region, const uint8_t* blob, uint32_t len, int sync) {
  return rl_append(h, region, 0, 0, nullptr, nullptr, blob, len, sync);
}

int64_t rl_first_index(void* h, uint64_t region) {
  auto* e = static_cast<RaftLogEng*>(h);
  std::shared_lock<std::shared_mutex> lk(e->mu);
  auto it = e->regions.find(region);
  if (it == e->regions.end() || it->second.locs.empty()) return 0;
  return static_cast<int64_t>(it->second.first);
}

int64_t rl_last_index(void* h, uint64_t region) {
  auto* e = static_cast<RaftLogEng*>(h);
  std::shared_lock<std::shared_mutex> lk(e->mu);
  auto it = e->regions.find(region);
  if (it == e->regions.end() || it->second.locs.empty()) return 0;
  return static_cast<int64_t>(it->second.last());
}

// Bytes needed by rl_fetch for [lo, hi) — framing is idx(u64) + len(u32) + blob.
int64_t rl_fetch_size(void* h, uint64_t region, uint64_t lo, uint64_t hi) {
  auto* e = static_cast<RaftLogEng*>(h);
  std::shared_lock<std::shared_mutex> lk(e->mu);
  auto it = e->regions.find(region);
  if (it == e->regions.end() || it->second.locs.empty()) return 0;
  const RegionIdx& ri = it->second;
  uint64_t a = std::max(lo, ri.first), b = std::min(hi, ri.last() + 1);
  int64_t total = 0;
  for (uint64_t i = a; i < b; i++) total += 12 + ri.locs[i - ri.first].len;
  return total;
}

// Copy entries [lo, hi) into out as idx(u64)|len(u32)|blob frames.
// Returns the number of entries written, or -1 if cap is too small.
int64_t rl_fetch(void* h, uint64_t region, uint64_t lo, uint64_t hi, uint8_t* out,
                 uint64_t cap) {
  auto* e = static_cast<RaftLogEng*>(h);
  struct Piece {
    uint64_t idx;
    std::shared_ptr<Seg> seg;
    uint32_t off, len;
  };
  std::vector<Piece> pieces;
  {
    std::shared_lock<std::shared_mutex> lk(e->mu);
    auto it = e->regions.find(region);
    if (it == e->regions.end() || it->second.locs.empty()) return 0;
    const RegionIdx& ri = it->second;
    uint64_t a = std::max(lo, ri.first), b = std::min(hi, ri.last() + 1);
    uint64_t need = 0;
    for (uint64_t i = a; i < b; i++) need += 12 + ri.locs[i - ri.first].len;
    if (need > cap) return -1;
    pieces.reserve(b > a ? b - a : 0);
    for (uint64_t i = a; i < b; i++) {
      const Loc& l = ri.locs[i - ri.first];
      auto sit = e->segs.find(l.seg);
      if (sit == e->segs.end()) return -2;  // should not happen
      pieces.push_back(Piece{i, sit->second, l.off, l.len});
    }
  }
  // file IO outside the index lock; shared_ptr keeps unlinked files readable
  uint8_t* p = out;
  for (const Piece& pc : pieces) {
    memcpy(p, &pc.idx, 8);
    memcpy(p + 8, &pc.len, 4);
    if (pc.len > 0) {
      if (pread(pc.seg->fd, p + 12, pc.len, static_cast<off_t>(pc.off)) !=
          static_cast<ssize_t>(pc.len)) {
        return -2;
      }
      enc::maybe_xor(pc.seg->fk, pc.off, p + 12, pc.len);
    }
    p += 12 + pc.len;
  }
  return static_cast<int64_t>(pieces.size());
}

// Latest hard-state blob; returns its length, -1 if cap too small, -2 if none.
int rl_state(void* h, uint64_t region, uint8_t* out, uint32_t cap) {
  auto* e = static_cast<RaftLogEng*>(h);
  std::shared_lock<std::shared_mutex> lk(e->mu);
  auto it = e->regions.find(region);
  if (it == e->regions.end() || !it->second.has_state) return -2;
  const std::string& s = it->second.state;
  if (s.size() > cap) return -1;
  memcpy(out, s.data(), s.size());
  return static_cast<int>(s.size());
}

int rl_purge(void* h, uint64_t region, uint64_t to) {
  auto* e = static_cast<RaftLogEng*>(h);
  {
    std::lock_guard<std::mutex> wlk(e->wmu);
    std::string payload;
    put_u64(payload, region);
    put_u64(payload, to);
    if (e->write_record(REC_PURGE, payload) == UINT64_MAX) return -1;
    std::unique_lock<std::shared_mutex> lk(e->mu);
    e->index_purge(region, to);
    std::lock_guard<std::mutex> slk(e->smu);
    e->append_seq++;
  }
  e->gc();
  return 0;
}

int rl_clean(void* h, uint64_t region) {
  auto* e = static_cast<RaftLogEng*>(h);
  {
    std::lock_guard<std::mutex> wlk(e->wmu);
    std::string payload;
    put_u64(payload, region);
    if (e->write_record(REC_CLEAN, payload) == UINT64_MAX) return -1;
    std::unique_lock<std::shared_mutex> lk(e->mu);
    e->index_clean(region);
    std::lock_guard<std::mutex> slk(e->smu);
    e->append_seq++;
  }
  e->gc();
  return 0;
}

// All region ids with any indexed entries or state; returns count (caller
// re-calls with a bigger buffer when count > cap).
int64_t rl_regions(void* h, uint64_t* out, uint32_t cap) {
  auto* e = static_cast<RaftLogEng*>(h);
  std::shared_lock<std::shared_mutex> lk(e->mu);
  uint32_t n = 0;
  for (auto& [rid, ri] : e->regions) {
    if (ri.locs.empty() && !ri.has_state) continue;
    if (n < cap) out[n] = rid;
    n++;
  }
  return n;
}

int rl_sync(void* h) {
  auto* e = static_cast<RaftLogEng*>(h);
  uint64_t seq;
  {
    std::lock_guard<std::mutex> slk(e->smu);
    seq = e->append_seq;
  }
  e->sync_to(seq);
  return 0;
}

// segments | active_size | live_total | rewrites | purged | append_seq
void rl_stats(void* h, uint64_t* out6) {
  auto* e = static_cast<RaftLogEng*>(h);
  {
    std::shared_lock<std::shared_mutex> lk(e->mu);
    uint64_t live_total = 0;
    for (auto& [s, n] : e->live) live_total += n;
    out6[0] = e->segs.size();
    out6[1] = e->active_size;
    out6[2] = live_total;
    out6[3] = e->rewrites;
    out6[4] = e->purged_entries;
  }
  std::lock_guard<std::mutex> slk(e->smu);
  out6[5] = e->append_seq;
}

}  // extern "C"
