"""ctypes binding for the native raft log engine (raftlog.cc).

The RaftEngine role from the reference (components/raft_log_engine/src/
engine.rs:25, selected per-store at components/server/src/server.rs:153-157):
raft log entries + hard-state blobs in segmented append-only files with
group-commit fdatasync, logical purge, and live-record rewrite — instead of
riding CF_RAFT of the general-purpose LSM.  Built on first use with the
baked-in g++ (plain C ABI via ctypes; pybind11 unavailable in this image).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "raftlog.cc")
_SO = os.path.join(_HERE, "libtikv_raftlog.so")

_lib = None
_lib_err: str | None = None
_build_mu = threading.Lock()

_U32 = struct.Struct("<I")
_FRAME = struct.Struct("<QI")  # idx u64 | len u32


def _so_stale(so: str, *srcs: str) -> bool:
    """True when the shared object predates ANY of its sources (the .cc
    plus shared headers) — the one place the dependency list lives."""
    if not os.path.exists(so):
        return True
    newest = max(
        (os.path.getmtime(p) for p in srcs if os.path.exists(p)), default=0
    )
    return os.path.getmtime(so) < newest


def _build() -> None:
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO, _SRC],
        check=True,
        capture_output=True,
    )


def _load():
    global _lib, _lib_err
    with _build_mu:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            if _so_stale(_SO, _SRC, os.path.join(_HERE, "crypt.h")):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError) as e:
            _lib_err = str(e)
            return None
        c = ctypes
        lib.rl_open.argtypes = [c.c_char_p, c.c_uint64, c.c_int, c.c_uint32, c.c_char_p, c.c_int]
        lib.rl_open.restype = c.c_void_p
        lib.rl_open_enc.argtypes = [
            c.c_char_p, c.c_uint64, c.c_int, c.c_uint32, c.c_uint32,
            c.POINTER(c.c_uint32), c.c_char_p, c.c_int, c.c_char_p, c.c_int,
        ]
        lib.rl_open_enc.restype = c.c_void_p
        lib.rl_set_encryption.argtypes = [
            c.c_void_p, c.c_uint32, c.POINTER(c.c_uint32), c.c_char_p, c.c_int,
        ]
        lib.rl_set_encryption.restype = c.c_int
        lib.rl_close.argtypes = [c.c_void_p]
        lib.rl_append.argtypes = [
            c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint32,
            c.c_char_p, c.POINTER(c.c_uint32), c.c_char_p, c.c_uint32, c.c_int,
        ]
        lib.rl_append.restype = c.c_int
        lib.rl_put_state.argtypes = [c.c_void_p, c.c_uint64, c.c_char_p, c.c_uint32, c.c_int]
        lib.rl_put_state.restype = c.c_int
        for fn in (lib.rl_first_index, lib.rl_last_index):
            fn.argtypes = [c.c_void_p, c.c_uint64]
            fn.restype = c.c_int64
        lib.rl_fetch_size.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64]
        lib.rl_fetch_size.restype = c.c_int64
        lib.rl_fetch.argtypes = [
            c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64, c.c_char_p, c.c_uint64
        ]
        lib.rl_fetch.restype = c.c_int64
        lib.rl_state.argtypes = [c.c_void_p, c.c_uint64, c.c_char_p, c.c_uint32]
        lib.rl_state.restype = c.c_int
        lib.rl_purge.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64]
        lib.rl_purge.restype = c.c_int
        lib.rl_clean.argtypes = [c.c_void_p, c.c_uint64]
        lib.rl_clean.restype = c.c_int
        lib.rl_regions.argtypes = [c.c_void_p, c.POINTER(c.c_uint64), c.c_uint32]
        lib.rl_regions.restype = c.c_int64
        lib.rl_sync.argtypes = [c.c_void_p]
        lib.rl_sync.restype = c.c_int
        lib.rl_stats.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
        _lib = lib
        return lib


def raftlog_available() -> bool:
    return _load() is not None


def _key_registry(keys_mgr):
    """(ids_array, keys_blob, current_id) for the FFI (engine.py twin)."""
    items = sorted(keys_mgr.all_keys().items())
    ids = (ctypes.c_uint32 * len(items))(*[i for i, _k in items])
    keys = b"".join(k for _i, k in items)
    current, _ = keys_mgr.current()
    return ids, keys, current


class NativeRaftLog:
    """One store's raft log: entries + hard-state blobs keyed by region id.

    Thread-safe; the entry blob format is opaque to this layer (the store's
    ``_encode_entry`` bytes go in and come back verbatim).
    """

    def __init__(self, path: str, segment_bytes: int = 64 << 20,
                 sync: bool = True, rewrite_max: int = 4096, keys_mgr=None):
        lib = _load()
        if lib is None:
            raise ImportError(f"native raftlog unavailable: {_lib_err}")
        self._lib = lib
        self._keys_mgr = keys_mgr
        err = ctypes.create_string_buffer(256)
        if keys_mgr is not None:
            ids, keys, current = _key_registry(keys_mgr)
            self._h = lib.rl_open_enc(
                os.fsencode(path), segment_bytes, 1 if sync else 0,
                rewrite_max, current, ids, keys, len(ids), err, 256,
            )
        else:
            self._h = lib.rl_open(
                os.fsencode(path), segment_bytes, 1 if sync else 0, rewrite_max, err, 256
            )
        if not self._h:
            raise RuntimeError(f"raftlog open failed: {err.value.decode()}")
        self.path = path
        self._closed = False

    def refresh_encryption(self) -> None:
        """Re-read the key registry after an external rotate."""
        if self._keys_mgr is None:
            raise RuntimeError("raftlog opened without encryption")
        ids, keys, current = _key_registry(self._keys_mgr)
        if self._lib.rl_set_encryption(self._h, current, ids, keys, len(ids)) != 0:
            raise RuntimeError("rl_set_encryption failed")

    def rotate_data_key(self) -> int:
        """Mint a new data key and refresh the registry; new segments
        encrypt under it."""
        if self._keys_mgr is None:
            raise RuntimeError("raftlog opened without encryption")
        new_id = self._keys_mgr.rotate()
        self.refresh_encryption()
        return new_id

    # -- write path ---------------------------------------------------------

    def append(self, region_id: int, first_index: int, blobs: list[bytes],
               state: bytes | None = None, sync: int = -1) -> None:
        """Append ``blobs`` as entries [first_index, ...) — truncating any
        conflicting indexed suffix — plus an optional hard-state blob, as one
        durable batch (sync -1 = engine default, grouped fdatasync)."""
        n = len(blobs)
        lens = (ctypes.c_uint32 * n)(*[len(b) for b in blobs]) if n else None
        buf = b"".join(blobs)
        st = state if state is not None else b""
        r = self._lib.rl_append(
            self._h, region_id, first_index, n, buf, lens, st, len(st), sync
        )
        if r != 0:
            raise OSError("raftlog append failed")

    def put_state(self, region_id: int, state: bytes, sync: int = -1) -> None:
        if self._lib.rl_put_state(self._h, region_id, state, len(state), sync) != 0:
            raise OSError("raftlog put_state failed")

    def purge(self, region_id: int, to_index: int) -> None:
        """Logically drop entries <= to_index; dead segments are unlinked and
        nearly-dead ones rewritten (engine.rs purge_expired_files role)."""
        if self._lib.rl_purge(self._h, region_id, to_index) != 0:
            raise OSError("raftlog purge failed")

    def clean(self, region_id: int) -> None:
        if self._lib.rl_clean(self._h, region_id) != 0:
            raise OSError("raftlog clean failed")

    def sync(self) -> None:
        self._lib.rl_sync(self._h)

    # -- read path ----------------------------------------------------------

    def first_index(self, region_id: int) -> int:
        return self._lib.rl_first_index(self._h, region_id)

    def last_index(self, region_id: int) -> int:
        return self._lib.rl_last_index(self._h, region_id)

    def state(self, region_id: int) -> bytes | None:
        cap = 512
        while True:
            buf = ctypes.create_string_buffer(cap)
            r = self._lib.rl_state(self._h, region_id, buf, cap)
            if r == -2:
                return None
            if r == -1:
                cap *= 4
                continue
            return buf.raw[:r]

    def entries(self, region_id: int, lo: int = 0, hi: int = 1 << 62) -> list[tuple[int, bytes]]:
        """(index, blob) pairs for [lo, hi), ascending."""
        need = self._lib.rl_fetch_size(self._h, region_id, lo, hi)
        if need <= 0:
            return []
        while True:
            buf = ctypes.create_string_buffer(int(need))
            n = self._lib.rl_fetch(self._h, region_id, lo, hi, buf, need)
            if n == -1:  # raced with an append that grew the range
                need = self._lib.rl_fetch_size(self._h, region_id, lo, hi)
                continue
            if n == -2:
                raise OSError("raftlog fetch IO error")
            out = []
            pos = 0
            raw = buf.raw
            for _ in range(n):
                idx, ln = _FRAME.unpack_from(raw, pos)
                pos += 12
                out.append((idx, raw[pos:pos + ln]))
                pos += ln
            return out

    def regions(self) -> list[int]:
        cap = 1024
        while True:
            arr = (ctypes.c_uint64 * cap)()
            n = self._lib.rl_regions(self._h, arr, cap)
            if n <= cap:
                return [arr[i] for i in range(n)]
            cap = int(n) + 64

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.rl_stats(self._h, out)
        return {
            "segments": out[0],
            "active_size": out[1],
            "live_entries": out[2],
            "rewrites": out[3],
            "purged_entries": out[4],
            "appends": out[5],
        }

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.rl_close(self._h)

    def __del__(self):  # pragma: no cover - interpreter shutdown ordering
        try:
            self.close()
        except Exception:
            pass
