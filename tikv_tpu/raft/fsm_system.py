"""Generic FSM batch system: router + mailboxes + a small poller pool.

The mechanism that lets one store host thousands of raft regions without
O(regions) work per loop iteration (re-expression of the reference's
``batch-system/src/batch.rs:284`` Poller::poll, ``src/router.rs`` and
``src/mailbox.rs:18``): every FSM owns a mailbox; senders enqueue a message
and, on the mailbox's IDLE -> NOTIFIED edge, push the FSM onto a shared ready
queue; N poller threads pop ready FSMs in batches and run the handler.  An
FSM with no traffic costs nothing; a hot FSM is rescheduled to the back of
the queue after a per-round message cap so it cannot starve the rest
(batch.rs's hot-FSM reschedule).

Exclusivity: a mailbox is handed to at most one poller at a time — the
IDLE/NOTIFIED state gates entry to the ready queue, and release() re-notifies
only if messages arrived while the poller held the FSM.  Per-FSM state
therefore stays single-threaded without any per-FSM lock.

The control FSM (address None) models store-level work (router.rs
control_box): messages that need cross-region coordination.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Hashable

from ..analysis.sanitizer import make_lock

_IDLE = 0
_NOTIFIED = 1
_CLOSED = 2

CONTROL = None  # the control FSM's address


class Mailbox:
    __slots__ = ("addr", "_mu", "_queue", "_state")

    def __init__(self, addr: Hashable):
        self.addr = addr
        self._mu = make_lock("raft.fsm.mailbox", label=repr(addr))
        self._queue: list = []
        self._state = _IDLE

    def __len__(self) -> int:
        with self._mu:
            return len(self._queue)


class Router:
    """Address -> mailbox map plus the shared ready queue."""

    def __init__(self):
        self._mu = make_lock("raft.fsm.router")
        self._mailboxes: dict[Hashable, Mailbox] = {CONTROL: Mailbox(CONTROL)}
        self.ready: queue.SimpleQueue[Mailbox] = queue.SimpleQueue()

    def register(self, addr: Hashable) -> None:
        with self._mu:
            if addr not in self._mailboxes:
                self._mailboxes[addr] = Mailbox(addr)

    def close(self, addr: Hashable) -> None:
        """Close an FSM's mailbox; queued messages are dropped (router.rs
        close marks the state DROP so senders see closure)."""
        with self._mu:
            mb = self._mailboxes.pop(addr, None)
        if mb is not None:
            with mb._mu:
                mb._state = _CLOSED
                mb._queue.clear()

    def addrs(self) -> list[Hashable]:
        with self._mu:
            return [a for a in self._mailboxes if a is not CONTROL]

    def send(self, addr: Hashable, msg) -> bool:
        """Enqueue for ``addr``; False if the mailbox is closed/unknown."""
        with self._mu:
            mb = self._mailboxes.get(addr)
        if mb is None:
            return False
        with mb._mu:
            if mb._state == _CLOSED:
                return False
            mb._queue.append(msg)
            if mb._state == _IDLE:
                mb._state = _NOTIFIED
                notify = True
            else:
                notify = False
        if notify:
            self.ready.put(mb)
        return True

    def send_control(self, msg) -> bool:
        return self.send(CONTROL, msg)

    def broadcast(self, msg_fn: Callable[[Hashable], object]) -> None:
        """Send msg_fn(addr) to every registered normal FSM (router.rs
        broadcast_normal) — used for ticks."""
        for addr in self.addrs():
            self.send(addr, msg_fn(addr))

    # -- poller side -------------------------------------------------------

    def _take(self, mb: Mailbox, cap: int) -> list:
        with mb._mu:
            if cap >= len(mb._queue):
                msgs, mb._queue = mb._queue, []
            else:
                msgs, mb._queue = mb._queue[:cap], mb._queue[cap:]
            return msgs

    def _release(self, mb: Mailbox) -> None:
        """Poller is done with this FSM: back to IDLE, or straight back onto
        the ready queue if traffic arrived while it was held."""
        with mb._mu:
            if mb._state == _CLOSED:
                return
            if mb._queue:
                renotify = True  # stay NOTIFIED
            else:
                mb._state = _IDLE
                renotify = False
        if renotify:
            self.ready.put(mb)


class PollHandler:
    """One instance per poller thread (batch.rs HandlerBuilder::build)."""

    def begin(self, batch_size: int) -> None:  # noqa: B027
        pass

    def handle(self, addr: Hashable, msgs: list) -> None:
        raise NotImplementedError

    def handle_control(self, msgs: list) -> None:
        raise NotImplementedError

    def end(self, addrs: list[Hashable]) -> None:  # noqa: B027
        pass


class BatchSystem:
    """N poller threads batch-polling ready FSMs off one router."""

    def __init__(
        self,
        router: Router,
        handler_factory: Callable[[], PollHandler],
        pollers: int = 2,
        max_batch_size: int = 32,
        messages_per_round: int = 256,
        name: str = "batch-system",
    ):
        self.router = router
        self._factory = handler_factory
        self._pollers = pollers
        self._max_batch = max_batch_size
        self._per_round = messages_per_round
        self._name = name
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.errors: list[Exception] = []

    def spawn(self) -> None:
        for i in range(self._pollers):
            t = threading.Thread(
                target=self._poll_loop, args=(self._factory(),),
                name=f"{self._name}-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def shutdown(self, timeout: float = 5.0) -> None:
        self._stop.set()
        # wake every poller blocked on ready.get
        for _ in self._threads:
            self.router.ready.put(None)  # type: ignore[arg-type]
        for t in self._threads:
            t.join(timeout=timeout)

    def _poll_loop(self, handler: PollHandler) -> None:
        router = self.router
        while not self._stop.is_set():
            try:
                mb = router.ready.get(timeout=0.5)
            except queue.Empty:
                continue
            if mb is None:
                continue
            batch = [mb]
            while len(batch) < self._max_batch:
                try:
                    nxt = router.ready.get_nowait()
                except queue.Empty:
                    break
                if nxt is not None:
                    batch.append(nxt)
            try:
                handler.begin(len(batch))
            except Exception as e:  # noqa: BLE001
                self._record(e)
            for mb in batch:
                # cap per round: a hot FSM yields the poller after
                # messages_per_round and re-enters via _release's renotify
                msgs = router._take(mb, self._per_round)
                if not msgs:
                    # closed-mailbox race (close() cleared the queue after
                    # the notify): nothing to do, don't invoke the handler
                    router._release(mb)
                    continue
                try:
                    if mb.addr is CONTROL:
                        handler.handle_control(msgs)
                    else:
                        handler.handle(mb.addr, msgs)
                except Exception as e:  # noqa: BLE001 — one FSM must not kill the poller
                    self._record(e)
                router._release(mb)
            try:
                handler.end([mb.addr for mb in batch])
            except Exception as e:  # noqa: BLE001
                self._record(e)

    def _record(self, e: Exception) -> None:
        if len(self.errors) < 128:
            self.errors.append(e)
