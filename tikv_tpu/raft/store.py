"""Multi-Raft store: one raft group per region, multiplexed on a node.

Re-expression of ``components/raftstore`` (store/fsm/{store,peer}.rs +
store/fsm/apply.rs + batch-system): a ``Store`` owns every region peer placed
on one node; peers propose serialized commands through their raft group and
apply committed entries to the shared engine; the store routes messages,
drives ticks, and executes admin commands (split, conf change).

Data layout on the shared engine matches keys.py: user data under the ``z``
prefix, raft log + states under store-local keys — so one engine hosts many
regions, exactly like the reference's single RocksDB with a raft CF.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..analysis.sanitizer import make_lock, make_rlock
from ..storage.btree_engine import BTreeEngine
from ..util.failpoint import fail_point
from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_RAFT, CF_WRITE, WriteBatch
from ..util import codec, keys
from ..util import logger as slog


def _notify_region_cache(region_id: int, reason: str) -> None:
    """Coprocessor region-column-cache invalidation on epoch change (split /
    merge / conf change).  Lazy import: the raft layer must stay importable
    without the coprocessor stack."""
    try:
        from ..copr.region_cache import notify_region_epoch_change
    except ImportError:
        return
    notify_region_epoch_change(region_id, reason=reason)


def _notify_region_write_lost(region_id: int, apply_index: int,
                              token=None) -> None:
    """Region data changed by means the write-through path cannot express
    (raft snapshot apply, emission disabled): caches drop pending deltas and
    repair through scan_delta (docs/write_path.md)."""
    try:
        from ..copr.region_cache import notify_region_write_lost
    except ImportError:
        return
    notify_region_write_lost(region_id, apply_index, token=token)


def _count_consistency(result: str) -> None:
    """Consistency-check observability (docs/integrity.md): compute_hash
    applies count ``compute``, verify_hash applies count ``match`` or
    ``mismatch`` — the series the divergence alert fires on."""
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_raft_consistency_check_total",
        "Raft consistency-check applies, by result",
    ).inc(result=result)
from .core import Entry, Message, MsgType, RaftNode, Role
from .core import Snapshot as RaftSnapshot
from .region import EpochError, KeyNotInRegionError, NotLeaderError, Peer as RegionPeer, Region, RegionEpoch

_LOG = slog.get_logger("raftstore")

DATA_CFS = (CF_DEFAULT, CF_LOCK, CF_WRITE)


# ---------------------------------------------------------------------------
# Command codec (RaftCmdRequest equivalent, deterministic bytes)
# ---------------------------------------------------------------------------

def encode_cmd(cmd: dict) -> bytes:
    """Commands: {"epoch": (cv, v), "ops": [(op, cf, key, val)]} or
    {"epoch":…, "admin": ("split", split_key, new_region_id, [new_peer_ids])
                        | ("conf_change", op, peer_id, store_id)}."""
    out = bytearray()
    cv, v = cmd["epoch"]
    out += codec.encode_var_u64(cv)
    out += codec.encode_var_u64(v)
    admin = cmd.get("admin")
    if admin is None:
        out.append(0)
        ops = cmd["ops"]
        out += codec.encode_var_u64(len(ops))
        for op, cf, key, val in ops:
            out.append({"put": 1, "delete": 2, "delete_range": 3}[op])
            out += codec.encode_compact_bytes(cf.encode())
            out += codec.encode_compact_bytes(key)
            out += codec.encode_compact_bytes(val if val is not None else b"")
    elif admin[0] == "split":
        out.append(1)
        out += codec.encode_compact_bytes(admin[1])
        out += codec.encode_var_u64(admin[2])
        out += codec.encode_var_u64(len(admin[3]))
        for pid in admin[3]:
            out += codec.encode_var_u64(pid)
    elif admin[0] == "conf_change":
        out.append(2)
        out += codec.encode_compact_bytes(admin[1].encode())
        out += codec.encode_var_u64(admin[2])
        out += codec.encode_var_u64(admin[3])
    elif admin[0] == "compute_hash":
        out.append(5)
    elif admin[0] == "verify_hash":
        out.append(6)
        out += codec.encode_var_u64(admin[1])  # apply index of the hash
        out += codec.encode_var_u64(admin[2])  # expected hash
        # derived-plane image fingerprints (docs/integrity.md): replicas
        # cross-check their device images against the leader's at the same
        # apply index — sorted so the entry bytes stay deterministic
        fps = admin[3] if len(admin) > 3 and admin[3] else {}
        out += codec.encode_var_u64(len(fps))
        for kid in sorted(fps):
            rec = fps[kid]
            out += codec.encode_compact_bytes(kid.encode())
            out += codec.encode_var_u64(max(int(rec["apply_index"]), 0))
            out += codec.encode_var_u64(max(int(rec["snapshot_ts"]), 0))
            out += codec.encode_var_u64(max(int(rec["max_commit_ts"]), 0))
            out += codec.encode_var_u64(int(rec["fingerprint"]))
    elif admin[0] == "prepare_merge":
        out.append(3)
        out += codec.encode_var_u64(admin[1])  # target region id
    elif admin[0] == "commit_merge":
        out.append(4)
        out += codec.encode_var_u64(admin[1])  # source region id
        out += codec.encode_compact_bytes(admin[2])  # source end key
        out += codec.encode_var_u64(admin[3])  # source epoch version
        out += codec.encode_var_u64(admin[4])  # source commit index
        entries = admin[5]  # CatchUpLogs payload: encoded source entries
        out += codec.encode_var_u64(len(entries))
        for eb in entries:
            out += codec.encode_compact_bytes(eb)
    elif admin[0] == "ingest_sst":
        # the staged file's entries ride in the log entry itself, so every
        # replica — current and future (log/snapshot catch-up) — applies the
        # same bytes (fsm/apply.rs:1427-1445 exec_ingest_sst role)
        out.append(7)
        out += codec.encode_compact_bytes(admin[1])
    else:
        raise ValueError(admin)
    return bytes(out)


def scan_region_states(snapshot):
    """Yield (region_id, raw_state_bytes) for every persisted region meta —
    THE region-enumeration idiom (fsm/store.rs init scan), shared by
    recovery, the debugger and offline tooling instead of each re-deriving
    the prefix arithmetic."""
    prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
    for k, v in snapshot.scan_cf(CF_RAFT, prefix,
                                 prefix[:-1] + bytes([prefix[-1] + 1])):
        yield codec.decode_u64(k, 2), v


def decode_cmd(b: bytes) -> dict:
    cv, off = codec.decode_var_u64(b, 0)
    v, off = codec.decode_var_u64(b, off)
    kind = b[off]
    off += 1
    cmd: dict = {"epoch": (cv, v)}
    if kind == 0:
        n, off = codec.decode_var_u64(b, off)
        ops = []
        for _ in range(n):
            op = {1: "put", 2: "delete", 3: "delete_range"}[b[off]]
            off += 1
            cf, off = codec.decode_compact_bytes(b, off)
            key, off = codec.decode_compact_bytes(b, off)
            val, off = codec.decode_compact_bytes(b, off)
            ops.append((op, cf.decode(), key, val))
        cmd["ops"] = ops
    elif kind == 1:
        split_key, off = codec.decode_compact_bytes(b, off)
        new_id, off = codec.decode_var_u64(b, off)
        n, off = codec.decode_var_u64(b, off)
        pids = []
        for _ in range(n):
            pid, off = codec.decode_var_u64(b, off)
            pids.append(pid)
        cmd["admin"] = ("split", split_key, new_id, pids)
    elif kind == 2:
        op, off = codec.decode_compact_bytes(b, off)
        pid, off = codec.decode_var_u64(b, off)
        sid, off = codec.decode_var_u64(b, off)
        cmd["admin"] = ("conf_change", op.decode(), pid, sid)
    elif kind == 5:
        cmd["admin"] = ("compute_hash",)
    elif kind == 6:
        idx, off = codec.decode_var_u64(b, off)
        h, off = codec.decode_var_u64(b, off)
        fps: dict = {}
        if off < len(b):  # pre-integrity-plane log entries carry no payload
            n, off = codec.decode_var_u64(b, off)
            for _ in range(n):
                kid, off = codec.decode_compact_bytes(b, off)
                ai, off = codec.decode_var_u64(b, off)
                sts, off = codec.decode_var_u64(b, off)
                mct, off = codec.decode_var_u64(b, off)
                fp, off = codec.decode_var_u64(b, off)
                fps[kid.decode()] = {"apply_index": ai, "snapshot_ts": sts,
                                     "max_commit_ts": mct, "fingerprint": fp}
        cmd["admin"] = ("verify_hash", idx, h, fps)
    elif kind == 3:
        tid, off = codec.decode_var_u64(b, off)
        cmd["admin"] = ("prepare_merge", tid)
    elif kind == 4:
        sid, off = codec.decode_var_u64(b, off)
        end, off = codec.decode_compact_bytes(b, off)
        sv, off = codec.decode_var_u64(b, off)
        scommit, off = codec.decode_var_u64(b, off)
        n, off = codec.decode_var_u64(b, off)
        entries = []
        for _ in range(n):
            eb, off = codec.decode_compact_bytes(b, off)
            entries.append(eb)
        cmd["admin"] = ("commit_merge", sid, end, sv, scommit, entries)
    elif kind == 7:
        blob, off = codec.decode_compact_bytes(b, off)
        cmd["admin"] = ("ingest_sst", blob)
    return cmd


def erase_region_state(engine, region_id: int, wb: WriteBatch | None = None) -> None:
    """THE one definition of wiping a region's persisted identity (region
    meta, raft state, apply state, log) — shared by tombstone destruction,
    commit-merge source cleanup, and the debugger's offline tombstone."""
    own_wb = wb is None
    if own_wb:
        wb = WriteBatch()
    wb.delete_cf(CF_RAFT, keys.region_state_key(region_id))
    wb.delete_cf(CF_RAFT, keys.raft_state_key(region_id))
    wb.delete_cf(CF_RAFT, keys.apply_state_key(region_id))
    log_prefix = keys.region_raft_prefix(region_id) + keys.RAFT_LOG_SUFFIX
    wb.delete_range_cf(CF_RAFT, log_prefix, log_prefix[:-1] + bytes([log_prefix[-1] + 1]))
    if own_wb:
        engine.write(wb)


def _decode_ingest_entries(blob: bytes):
    """Yield (cf, key, value) from an ingest_sst admin payload."""
    off = 0
    n, off = codec.decode_var_u64(blob, off)
    for _ in range(n):
        cf, off = codec.decode_compact_bytes(blob, off)
        key, off = codec.decode_compact_bytes(blob, off)
        val, off = codec.decode_compact_bytes(blob, off)
        yield cf.decode(), key, val


def _ingest_key_outside(blob: bytes, region) -> bytes | None:
    """First payload key outside the region's range, or None."""
    for _cf, key, _val in _decode_ingest_entries(blob):
        if not region.contains(key):
            return key
    return None


# ---------------------------------------------------------------------------
# Transport
# ---------------------------------------------------------------------------

@dataclass
class RaftMessage:
    """Envelope for peer-to-peer raft traffic (kvproto RaftMessage)."""

    region_id: int
    from_peer: RegionPeer
    to_peer: RegionPeer
    msg: Message
    region_epoch: RegionEpoch = field(default_factory=RegionEpoch)
    # region carried on snapshot/first-contact messages so the receiver can
    # bootstrap the peer (raftstore maybe_create_peer)
    region: Region | None = None
    # kvproto RaftMessage.is_tombstone: "you have been removed by a committed
    # conf change at this epoch — destroy yourself".  Sent by the leader on
    # applying RemovePeer, and by any member contacted by a peer that a newer
    # epoch excludes (raftstore's stale-peer GC), so a lagging removed peer
    # is destroyed even though it never receives its own removal entry.
    is_tombstone: bool = False


class Transport:
    def send(self, to_store: int, rmsg: RaftMessage) -> None:
        raise NotImplementedError


class Filter:
    """Message filter for fault injection (transport_simulate.rs:34)."""

    def before(self, rmsg: RaftMessage) -> bool:
        """False = drop."""
        return True


class DropPacketFilter(Filter):
    def __init__(self, region_id: int | None = None, rate: float = 1.0, rng=None):
        import random

        self.region_id = region_id
        self.rate = rate
        self.rng = rng or random.Random(0)

    def before(self, rmsg: RaftMessage) -> bool:
        if self.region_id is not None and rmsg.region_id != self.region_id:
            return True
        return self.rng.random() >= self.rate


class PartitionFilter(Filter):
    def __init__(self, stores_a: set[int], stores_b: set[int]):
        self.a = stores_a
        self.b = stores_b

    def before(self, rmsg: RaftMessage) -> bool:
        fa, ta = rmsg.from_peer.store_id, rmsg.to_peer.store_id
        return not ((fa in self.a and ta in self.b) or (fa in self.b and ta in self.a))


class RegionPacketFilter(Filter):
    def __init__(self, region_id: int, store_id: int | None = None, msg_types: set | None = None):
        self.region_id = region_id
        self.store_id = store_id
        self.msg_types = msg_types

    def before(self, rmsg: RaftMessage) -> bool:
        if rmsg.region_id != self.region_id:
            return True
        if self.store_id is not None and rmsg.to_peer.store_id != self.store_id:
            return True
        if self.msg_types is not None and rmsg.msg.type not in self.msg_types:
            return True
        return False


class ChannelTransport(Transport):
    """In-memory transport wiring stores directly (test_raftstore NodeCluster)."""

    def __init__(self):
        self.stores: dict[int, "Store"] = {}
        self.filters: list[Filter] = []
        self._mu = make_lock("raft.transport")

    def register(self, store: "Store") -> None:
        self.stores[store.store_id] = store

    def send(self, to_store: int, rmsg: RaftMessage) -> None:
        for f in self.filters:
            if not f.before(rmsg):
                return
        store = self.stores.get(to_store)
        if store is not None:
            store.enqueue_message(rmsg)


# ---------------------------------------------------------------------------
# Peer (region replica)
# ---------------------------------------------------------------------------

class Proposal:
    def __init__(self, index: int, term: int, cb: Callable):
        self.index = index
        self.term = term
        self.cb = cb


class StorePeer:
    """One region replica on this store (PeerFsm + ApplyDelegate merged)."""

    def __init__(self, store: "Store", region: Region, peer_id: int):
        self.store = store
        self.region = region
        self.peer_id = peer_id
        self.node = RaftNode(peer_id, region.voter_ids())
        self.node.learners = set(region.learner_ids())
        self.node.witnesses = set(region.witness_ids())
        self.proposals: list[Proposal] = []
        # ctx -> (cb, expiry deadline); see read_index / _expire_stale_reads
        self.pending_reads: dict[bytes, tuple[Callable, float]] = {}
        self._was_leader = False  # stepdown-transition detector (handle_ready)
        self._read_seq = 0
        self.merging = False  # PrepareMerge applied: no more data proposals
        # Completed apply progress.  node.applied advances when ready()
        # DRAINS committed entries (they are handed to the apply pipeline);
        # apply_index advances when their effects are IN the engine.  Reads,
        # snapshot generation, and log GC gate on apply_index (the
        # reference's ApplyState vs RaftLocalState split, peer_storage.rs).
        self.apply_index = 0
        # a failed apply latches the region: advancing past the gap would
        # persist an ApplyState covering entries the engine never saw
        self.apply_broken = False
        # guards proposals / pending_reads / pending_read_states: proposers
        # run on service threads, acks on apply workers, reads on the raft
        # thread
        self._cb_mu = make_lock("raft.peer.cb", label=f"region-{region.id}")
        self.pending_read_states: list[tuple[bytes, int]] = []

    # -- raft driving ------------------------------------------------------

    def propose_cmd(self, cmd: dict, cb: Callable) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_raftstore_proposal_total", "Proposals entering raft, by kind"
        ).inc(kind=cmd.get("type", "data"))
        if not self.node.is_leader():
            cb(NotLeaderError(self.region.id, self.store.leader_store_of(self.region.id)))
            return
        if not self._epoch_ok(cmd):
            cb(EpochError(self.region.clone()))
            return
        if self.merging:
            # a merging region rejects ALL proposals (data, split, conf
            # change) until CommitMerge resolves it — raftstore's rule
            cb(EpochError(self.region.clone()))
            return
        admin = cmd.get("admin")
        if admin is not None and admin[0] == "ingest_sst":
            # range check at propose time (exec_ingest_sst rejects SSTs whose
            # range exceeds the region): out-of-range keys would ride this
            # region's log but be excluded from its range-bounded snapshots,
            # silently diverging any replica that catches up via snapshot
            bad = _ingest_key_outside(admin[1], self.region)
            if bad is not None:
                cb(KeyError(f"ingest key {bad!r} outside region "
                            f"{self.region.start_key!r}..{self.region.end_key!r}"))
                return
        if admin is not None and admin[0] == "conf_change_v2":
            # atomic multi-peer change via joint consensus: admin carries
            # [(op, peer_id, store_id), ...] — placement rides IN the entry
            # so any future leader knows where new peers live, not just the
            # proposing store
            # propose + register atomically under _cb_mu: an apply worker's
            # ack sweep (which takes the same lock) must not observe the
            # entry committed before its proposal is in self.proposals
            with self._cb_mu:
                index = self.node.propose_conf_change(("enter_joint", tuple(admin[1])))
                if index is not None:
                    self.proposals.append(Proposal(index, self.node.term, cb))
            if index is None:
                cb(NotLeaderError(self.region.id, None))
            else:
                self.store.notify_region(self.region.id)
            return
        if admin is not None and admin[0] == "conf_change":
            # placement (store id) rides in the entry, like the reference's
            # ConfChange carrying the full Peer message
            with self._cb_mu:
                index = self.node.propose_conf_change((admin[1], admin[2], admin[3]))
                if index is not None:
                    self.proposals.append(Proposal(index, self.node.term, cb))
            if index is None:
                cb(NotLeaderError(self.region.id, None))
            else:
                self.store.notify_region(self.region.id)
            return
        with self._cb_mu:
            index = self.node.propose(encode_cmd(cmd))
            if index is not None:
                self.proposals.append(Proposal(index, self.node.term, cb))
        if index is None:
            cb(NotLeaderError(self.region.id, None))
        else:
            self.store.notify_region(self.region.id)

    def _epoch_ok(self, cmd: dict) -> bool:
        """Data commands only care about the range (version); admin commands
        also require membership (conf_ver) to be current — the reference's
        util::check_region_epoch rules."""
        cv, v = cmd["epoch"]
        if cmd.get("admin") is not None:
            return (cv, v) == (self.region.epoch.conf_ver, self.region.epoch.version)
        return v == self.region.epoch.version

    def propose_split(self, split_key: bytes, new_region_id: int, new_pids: list[int], cb: Callable) -> None:
        """Propose the split admin command (shared by auto-split, the
        cluster harness, and the split_region RPC — ONE definition of the
        admin tuple shape + epoch capture).  ``split_key`` must already be
        in engine key space (memcomparable-encoded for txn data)."""
        self.propose_cmd(
            {
                "epoch": (self.region.epoch.conf_ver, self.region.epoch.version),
                "ops": [],
                "admin": ("split", split_key, new_region_id, new_pids),
            },
            cb,
        )

    # follower replica-read waiters whose READ_INDEX (or its RESP) vanished
    # — leader stepdown mid-round, partition — are failed after this long
    # so pending_reads can never grow without bound on a live follower
    READ_WAIT_TTL = 15.0

    def read_index(self, cb: Callable) -> None:
        """Linearizable read barrier; cb() fires once safe to read locally.
        Works on followers too (replica read): the ctx forwards to the
        leader and the RESP releases it here."""
        if not self.node.is_leader() and self.node.leader_id is None:
            # no known leader (election window): the raft core would drop
            # the forward on the floor — fail fast so the caller retries
            # instead of burning its whole timeout
            cb(NotLeaderError(self.region.id, None))
            return
        with self._cb_mu:
            self._read_seq += 1
            # ctx must be unique CLUSTER-wide: every peer starts its seq at
            # 0, so without the peer id two forwarding followers collide in
            # the leader's pending-read table and one waiter never fires
            ctx = (codec.encode_u64(self.region.id)
                   + codec.encode_u64(self.peer_id)
                   + codec.encode_u64(self._read_seq))
            self.pending_reads[ctx] = (cb, time.monotonic() + self.READ_WAIT_TTL)
        self.node.read_index(ctx)
        self.store.notify_region(self.region.id)

    def _expire_stale_reads(self) -> None:
        if not self.pending_reads:
            return
        now = time.monotonic()
        fire = []
        with self._cb_mu:
            for ctx, (cb, deadline) in list(self.pending_reads.items()):
                if now >= deadline:
                    del self.pending_reads[ctx]
                    fire.append(cb)
        for cb in fire:
            cb(NotLeaderError(self.region.id, self.store.leader_store_of(self.region.id)))

    def handle_ready(self, sync_apply: bool = False) -> bool:
        is_leader = self.node.is_leader()
        self._expire_stale_reads()
        if (self._was_leader or self.proposals) and not is_leader:
            # stepped DOWN (transition, not merely "is a follower" — a
            # follower legitimately parks replica-read waiters here): fail
            # every pending proposal and read-index waiter NOW (the
            # reference notifies on leader change rather than leaving
            # callers to time out — a deposed leader never produces the
            # awaited read states either).  This also keeps self.proposals
            # sorted by index — the invariant _ack's front-pop relies on —
            # because a re-election on this store starts from an empty list.
            with self._cb_mu:
                stale, self.proposals = self.proposals, []
                stale_reads = [cb for cb, _dl in self.pending_reads.values()]
                self.pending_reads.clear()
                self.pending_read_states.clear()
            leader = self.store.leader_store_of(self.region.id)
            for p in stale:
                p.cb(NotLeaderError(self.region.id, leader))
            for cb in stale_reads:
                cb(NotLeaderError(self.region.id, leader))
        self._was_leader = is_leader
        rd = self.node.ready()
        if rd.is_empty():
            return False
        eng = self.store.engine
        # persist raft log + hard state (PeerStorage: RaftLocalState)
        if rd.entries or rd.hard_state_changed:
            rl = self.store.raft_log
            if rl is not None:
                # one group-committed batch: entries + state (raftlog.cc)
                rl.append(
                    self.region.id,
                    rd.entries[0].index if rd.entries else 0,
                    [_encode_entry(e) for e in rd.entries],
                    state=self._encode_raft_state(),
                )
            else:
                wb = WriteBatch()
                for e in rd.entries:
                    wb.put_cf(CF_RAFT, keys.raft_log_key(self.region.id, e.index), _encode_entry(e))
                wb.put_cf(CF_RAFT, keys.raft_state_key(self.region.id), self._encode_raft_state())
                eng.write(wb)
        if rd.snapshot is not None:
            if self.store.apply_system is not None:
                # queued runs reference the pre-snapshot region: drain them
                # before the snapshot swaps region/engine state underneath
                self.store.apply_system.flush(self.region.id)
            self._apply_snapshot(rd.snapshot)
        apply_sys = None if sync_apply else self.store.apply_system
        if rd.committed_entries:
            if apply_sys is None:
                self._apply_entries_inline(rd.committed_entries)
            else:
                self._schedule_apply(rd.committed_entries, apply_sys)
        if rd.read_states:
            # enqueue under the lock FIRST, then sweep: checking apply_index
            # before appending loses the wakeup if the apply worker advances
            # and sweeps in between (_flush_pending_reads re-checks under
            # the same lock, so one of the two sweeps always fires the cb)
            with self._cb_mu:
                self.pending_read_states.extend(rd.read_states)
            self._flush_pending_reads()
        for m in rd.messages:
            self._send_raft_msg(m)
        return True

    def _apply_entries_inline(self, entries: list[Entry]) -> None:
        eng = self.store.engine
        applied = entries[0].index - 1
        saw_admin = False
        try:
            for e in entries:
                cmd = self._apply_entry(e)
                if e.conf_change is not None or (cmd or {}).get("admin") is not None:
                    saw_admin = True
                applied = e.index
        except BaseException:
            # a fault mid-apply (e.g. an injected failpoint) must not
            # lose committed entries: ready() advanced node.applied to
            # commit when it drained them, so rewind to the last entry
            # actually applied — the next ready() re-delivers the rest
            self.node.applied = applied
            self.apply_index = max(self.apply_index, applied)
            eng.put_cf(
                CF_RAFT, keys.apply_state_key(self.region.id), codec.encode_u64(applied)
            )
            raise
        # ApplyState: recovery resumes application after this index
        self.apply_index = max(self.apply_index, self.node.applied)
        eng.put_cf(
            CF_RAFT, keys.apply_state_key(self.region.id), codec.encode_u64(self.node.applied)
        )
        if saw_admin:
            self.store.sync_kv_wal()  # see _schedule_apply's admin barrier
        self._flush_pending_reads()

    def _schedule_apply(self, entries: list[Entry], apply_sys) -> None:
        """Route committed entries into the apply pipeline (apply.rs:920).

        Plain data entries stream to the region's apply worker in FIFO runs;
        admin / conf-change entries are a BARRIER: they mutate raft and store
        state owned by this thread, so the queue drains, then they apply
        inline.  Decode happens once, here, and the decoded command rides
        into the worker."""
        run: list = []
        for e in entries:
            cmd = None
            if e.conf_change is None and e.data:
                cmd = decode_cmd(e.data)
            if e.conf_change is None and (cmd is None or cmd.get("admin") is None):
                run.append((e, cmd))
                continue
            # admin or conf entry: flush the pipeline, apply inline
            if run:
                self._submit_run(run, apply_sys)
                run = []
            apply_sys.flush(self.region.id)
            self._apply_entry(e)
            self.apply_index = max(self.apply_index, e.index)
            self.store.engine.put_cf(
                CF_RAFT, keys.apply_state_key(self.region.id), codec.encode_u64(e.index)
            )
            # admin mutations (split/merge/conf) rewrite region meta that
            # recovery cannot re-derive from the raft log alone — close any
            # buffered-apply window immediately (no-op otherwise)
            self.store.sync_kv_wal()
            self._flush_pending_reads()  # reads waiting on this admin index
        if run:
            self._submit_run(run, apply_sys)

    def _submit_run(self, run: list, apply_sys) -> None:
        apply_sys.submit(self.region.id, lambda run=run: self._apply_run(run))

    def _apply_run(self, run: list) -> None:
        """Executed on an apply worker: data commands only (no admin, no
        conf change — those applied inline under the barrier).

        The whole run — data ops AND the ApplyState advance — folds into ONE
        engine WriteBatch (apply.rs likewise commits a committed-entry batch
        as one atomic RocksDB write): acks fire after the combined write
        lands, observers see each command in order.

        A failure LATCHES the peer broken (apply_broken): later runs must
        not advance apply_index past a gap whose effects never reached the
        engine — that would persist an ApplyState recovery believes, silently
        diverging the replica from its log (the reference panics the store
        here, apply.rs; we stop the region and surface the error)."""
        if self.apply_broken:
            return
        import time as _time

        from ..util.metrics import REGISTRY

        t0 = _time.perf_counter()
        try:
            self._apply_run_inner(run)
        except BaseException:
            self.apply_broken = True
            raise  # the worker records the error (batch_system errors list)
        REGISTRY.histogram(
            "tikv_raftstore_apply_duration_seconds",
            "Committed-entry batch apply latency",
        ).observe(_time.perf_counter() - t0)
        REGISTRY.histogram(
            "tikv_raftstore_apply_batch_entries", "Entries per apply batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
        ).observe(len(run))

    def _apply_run_inner(self, run: list) -> None:
        eng = self.store.engine
        applied = None
        is_witness = self.peer_id in self.node.witnesses
        wb = WriteBatch()
        executed: list = []  # (entry, cmd) whose ops are in wb
        acks: list = []  # deferred until the batch is durable in the engine
        for e, cmd in run:
            if cmd is None:
                applied = e.index  # leader noop: nothing to execute
                continue
            if not self._epoch_ok(cmd):
                acks.append((e, None, EpochError(self.region.clone())))
                applied = e.index
                continue
            fail_point("apply_before_exec")
            if is_witness:
                # witnesses replicate and vote on the LOG but never
                # materialize data
                acks.append((e, {"applied_index": e.index}, None))
                applied = e.index
                continue
            for op, cf, key, val in cmd["ops"]:
                dkey = keys.data_key(key)
                if op == "put":
                    wb.put_cf(cf, dkey, val)
                elif op == "delete":
                    wb.delete_cf(cf, dkey)
                elif op == "delete_range":
                    wb.delete_range_cf(cf, dkey, keys.data_key(val))
            executed.append((e, cmd))
            acks.append((e, {"applied_index": e.index}, None))
            applied = e.index
        if applied is not None:
            new_apply = max(self.apply_index, applied)
            wb.put_cf(CF_RAFT, keys.apply_state_key(self.region.id), codec.encode_u64(new_apply))
            eng.write(wb)
            if executed:
                # write-through delta BEFORE apply_index becomes visible: a
                # snapshot carrying new_apply can then only be taken after
                # the region cache buffered this batch (no gap window)
                self._emit_write_delta(
                    [op for _e, c in executed for op in c["ops"]], new_apply
                )
            self.apply_index = new_apply
        elif wb.ops:
            eng.write(wb)
        for _e, cmd in executed:
            self.store.on_applied(self.region, cmd)
        for e, result, err in acks:
            self._ack(e, result, err)
        self._flush_pending_reads()

    def _flush_pending_reads(self) -> None:
        fire = []
        with self._cb_mu:
            rest = []
            for ctx, index in self.pending_read_states:
                if self.apply_index >= index:
                    ent = self.pending_reads.pop(ctx, None)
                    if ent is not None:
                        fire.append(ent[0])
                else:
                    rest.append((ctx, index))
            self.pending_read_states = rest
        for cb in fire:
            cb(None)

    def _send_raft_msg(self, m: Message) -> None:
        to_peer = self.region.peer_by_id(m.to)
        if to_peer is None or to_peer.store_id == 0:
            # placement unknown (region metadata lags the conf entry that
            # carries it) — drop; retries resolve once the entry applies
            return
        if m.type == MsgType.SNAPSHOT and m.snapshot is None:
            m.snapshot = self._generate_snapshot(for_witness=m.to in self.node.witnesses)
        rmsg = RaftMessage(
            region_id=self.region.id,
            from_peer=RegionPeer(self.peer_id, self.store.store_id),
            to_peer=to_peer,
            msg=m,
            region_epoch=RegionEpoch(self.region.epoch.conf_ver, self.region.epoch.version),
            region=self.region.clone(),
        )
        self.store.transport.send(to_peer.store_id, rmsg)

    # -- apply -------------------------------------------------------------

    def _apply_entry(self, e: Entry):
        """Apply one committed entry; returns the decoded cmd (None for conf
        changes / noops) so callers can inspect it without re-decoding."""
        if e.conf_change is not None:
            self._apply_conf_change(e)
            self._ack(e, None, None)
            return None
        if not e.data:
            return None  # leader noop
        cmd = decode_cmd(e.data)
        if not self._epoch_ok(cmd):
            self._ack(e, None, EpochError(self.region.clone()))
            return cmd
        admin = cmd.get("admin")
        if admin is not None and admin[0] == "split":
            self._apply_split(admin)
            self._ack(e, {"split": True}, None)
            return cmd
        if admin is not None and admin[0] == "compute_hash":
            # witnesses hold no data: they ack but never hash or verify —
            # their empty-range hash would flag a bogus divergence
            if self.peer_id not in self.node.witnesses:
                self._apply_compute_hash(e)
            self._ack(e, {"compute_hash": True}, None)
            return cmd
        if admin is not None and admin[0] == "verify_hash":
            if self.peer_id not in self.node.witnesses:
                self._apply_verify_hash(
                    admin[1], admin[2], admin[3] if len(admin) > 3 else None)
            self._ack(e, {"verify_hash": True}, None)
            return cmd
        if admin is not None and admin[0] == "prepare_merge":
            self.merging = True
            self.region.epoch.version += 1
            self.store.persist_region(self.region, merging=True)
            _notify_region_cache(self.region.id, "prepare_merge")
            self._ack(e, {"prepare_merge": True}, None)
            return cmd
        if admin is not None and admin[0] == "commit_merge":
            self._apply_commit_merge(admin)
            self._ack(e, {"commit_merge": True}, None)
            return cmd
        if admin is not None and admin[0] == "ingest_sst":
            # every non-witness replica materializes the staged entries from
            # the log payload (fsm/apply.rs exec_ingest_sst): a replica that
            # was down replays this entry (or receives it in a snapshot) and
            # converges without any side-channel file transfer
            if self.peer_id not in self.node.witnesses:
                self._apply_ingest_sst(admin[1], apply_index=e.index)
            self._ack(e, {"ingest_sst": True, "applied_index": e.index}, None)
            return cmd
        fail_point("apply_before_exec")
        if self.peer_id in self.node.witnesses:
            # witnesses replicate and vote on the LOG but never materialize
            # data (raftstore witness feature); acking keeps apply advancing
            self._ack(e, {"applied_index": e.index}, None)
            return cmd
        self._exec_data_cmd(cmd, self.region, apply_index=e.index)
        self._ack(e, {"applied_index": e.index}, None)
        return cmd

    def _emit_write_delta(self, ops, apply_index: int) -> None:
        """Write-through delta emission (ISSUE 4): after a committed data
        batch is IN the engine — and before ``apply_index`` becomes visible
        to new snapshots — hand the batch's ops to the coprocessor region
        column cache, so warm reads under write load fold the change in
        without re-scanning CF_WRITE.  The ``apply_emit_write_delta``
        failpoint (and any emission failure) degrades to a lost-marker: the
        cache then repairs through its scan_delta fallback, never through a
        gapped delta chain."""
        try:
            from ..copr.region_cache import (
                notify_region_write,
                notify_region_write_lost,
            )
        except ImportError:
            return
        token = self.store.data_token  # matches RegionSnapshot.data_token
        try:
            fail_point("apply_emit_write_delta")
        except Exception:  # noqa: BLE001 — emission off: content unknown
            notify_region_write_lost(self.region.id, apply_index, token=token)
            return
        try:
            notify_region_write(self.region.id, ops, apply_index,
                                get_default=self._get_default_value,
                                token=token)
        except Exception:  # noqa: BLE001 — a cache-side fault must never
            # break apply: degrade to the lost-marker (scan_delta repairs)
            notify_region_write_lost(self.region.id, apply_index, token=token)

    def _get_default_value(self, enc_key_with_ts: bytes) -> bytes | None:
        return self.store.engine.get_cf(CF_DEFAULT, keys.data_key(enc_key_with_ts))

    def _apply_ingest_sst(self, blob: bytes, apply_index: int | None = None) -> None:
        """Write the ingest payload — encoded (cf, key, value) entries, keys
        already in their final (rewritten) form — under the region prefix.
        Keys outside the region range are dropped identically on every
        replica (the propose-time check rejects them; this keeps a replayed
        entry deterministic even across a racing split)."""
        wb = WriteBatch()
        ops = []
        for cf, key, val in _decode_ingest_entries(blob):
            if not self.region.contains(key):
                continue
            wb.put_cf(cf, keys.data_key(key), val)
            ops.append(("put", cf, key, val))
        self.store.engine.write(wb)
        if ops and apply_index is not None:
            self._emit_write_delta(ops, apply_index)
        # apply observers (CDC, resolved-ts) must see ingested writes like
        # any other applied command — a change feed that silently misses an
        # imported batch is data loss downstream
        self.store.on_applied(self.region, {"ops": ops, "ingest_sst": True})

    def _exec_data_cmd(self, cmd: dict, region: Region,
                       apply_index: int | None = None) -> None:
        """Execute a data command's write ops against the engine (shared by
        the normal apply path and commit-merge catch-up).  With an
        ``apply_index``, the committed batch also flows into the region
        column cache as a write-through delta — emission runs BEFORE the
        caller advances the peer's visible apply_index, so a snapshot that
        reports this index can only exist after its delta was buffered."""
        wb = WriteBatch()
        for op, cf, key, val in cmd["ops"]:
            dkey = keys.data_key(key)
            if op == "put":
                wb.put_cf(cf, dkey, val)
            elif op == "delete":
                wb.delete_cf(cf, dkey)
            elif op == "delete_range":
                wb.delete_range_cf(cf, dkey, keys.data_key(val))
        self.store.engine.write(wb)
        if cmd["ops"] and apply_index is not None:
            self._emit_write_delta(cmd["ops"], apply_index)
        self.store.on_applied(region, cmd)

    def _ack(self, e: Entry, result, err) -> None:
        # proposals append in index order, so everything relevant to this
        # entry sits at the FRONT: pop while index <= e.index instead of
        # rescanning the whole in-flight window per committed entry (that
        # rescan made the ack path O(window²) across a batch)
        fire = []
        with self._cb_mu:
            props = self.proposals
            i = 0
            n = len(props)
            while i < n and props[i].index <= e.index:
                p = props[i]
                if p.index == e.index and p.term == e.term:
                    fire.append((p.cb, err if err is not None else result))
                else:
                    # behind the applied index, or overwritten by a
                    # different term's entry at the same index
                    fire.append((p.cb, NotLeaderError(self.region.id, None)))
                i += 1
            if i:
                del props[:i]
        for cb, arg in fire:
            cb(arg)

    def _notify_removed_peer(self, pid: int, applied_index: int) -> None:
        """Final notification to a peer leaving the config: push the commit
        index covering its own removal before it stops hearing from us (the
        reference relies on PD stale-peer GC as the backstop)."""
        if pid != self.peer_id and self.node.is_leader() and self.region.peer_by_id(pid) is not None:
            self._send_raft_msg(
                Message(
                    MsgType.HEARTBEAT, self.peer_id, pid, self.node.term,
                    commit=min(applied_index, self.node.match_index.get(pid, 0)),
                )
            )

    # -- consistency check (coprocessor/consistency_check.rs + mvcc) --------

    def _region_hash(self) -> int:
        """crc64 over every (cf, key, value) of the region's data range at
        the CURRENT apply point — every replica applying the compute_hash
        entry at the same log index must produce the same value (the raw +
        mvcc hash of consistency_check.rs, one pass over the data CFs)."""
        from ..copr.analyze import crc64

        eng = self.store.engine
        start = keys.data_key(self.region.start_key)
        end = keys.data_end_key(self.region.end_key)
        h = 0
        for cf in DATA_CFS:
            for k, v in eng.scan_cf(cf, start, end):
                h = crc64(cf.encode(), h)
                h = crc64(k, h)
                h = crc64(v, h)
        return h

    def _apply_compute_hash(self, e: Entry) -> None:
        """Every replica hashes its region data at this entry's apply point
        (ConsistencyCheckObserver).  The LEADER follows up by replicating
        its own hash in a verify_hash entry, so replicas compare against
        the leader at the exact same index.

        Integrity ride-along (docs/integrity.md): the same apply point is
        the perfect pin for the DERIVED plane — every replica scrubs its
        resident device images of this region against its own engine here,
        and the leader's verify_hash additionally carries its image
        fingerprints so replicas holding an image at the same apply index
        literally cross-check device-image hashes alongside the mvcc hash."""
        h = self._region_hash()
        self.store.consistency_hashes[self.region.id] = (e.index, h)
        _count_consistency("compute")
        img_fps: dict = {}
        try:
            from ..copr import integrity as _copr_integrity
            from .raftkv import RegionSnapshot

            snap = RegionSnapshot(
                self.store.engine.snapshot(), self.region.clone(),
                apply_index=e.index, data_token=self.store.data_token,
            )
            _copr_integrity.scrub_region_on_consistency_check(
                self.region.id, self.store.data_token, snap)
            img_fps = _copr_integrity.region_image_fingerprints(
                self.region.id, self.store.data_token)
        except Exception as exc:  # noqa: BLE001 — the derived plane must
            # never poison raft apply; the scrubber re-covers it.  But a
            # FATAL-mode mismatch must not vanish silently either: log it
            # (the quarantine + mismatch counters already fired inside
            # verify_image before the raise)
            from ..copr.integrity import IntegrityMismatch

            if isinstance(exc, IntegrityMismatch):
                _LOG.error("fatal integrity mismatch at consistency check",
                           region=self.region.id, error=repr(exc))
        if self.node.is_leader():
            self.propose_cmd(
                {
                    "epoch": (self.region.epoch.conf_ver, self.region.epoch.version),
                    "ops": [],
                    "admin": ("verify_hash", e.index, h, img_fps),
                },
                lambda r: None,
            )

    def _apply_verify_hash(self, index: int, expected: int,
                           image_fps: dict | None = None) -> None:
        rec = self.store.consistency_hashes.get(self.region.id)
        if rec is None or rec[0] != index:
            return  # this replica joined after the compute entry (snapshot)
        if rec[1] != expected:
            _count_consistency("mismatch")
            # divergence: the reference panics the store; we record the
            # region as inconsistent and surface it via the debug service
            self.store.inconsistent_regions[self.region.id] = {
                "index": index,
                "local_hash": rec[1],
                "leader_hash": expected,
            }
        else:
            _count_consistency("match")
        if image_fps:
            # derived-plane replica cross-check: local images pinned at the
            # leader's recorded apply index compare fingerprints; divergence
            # quarantines the LOCAL image (the mvcc hash above adjudicates
            # the region — the derived plane just rebuilds)
            try:
                from ..copr import integrity as _copr_integrity

                _copr_integrity.cross_check_image_fps(
                    self.region.id, self.store.data_token, image_fps)
            except Exception as exc:  # noqa: BLE001 — never poison apply,
                # but never let a fatal-mode signal vanish unlogged either
                _LOG.error("image fingerprint cross-check failed",
                           region=self.region.id, error=repr(exc))

    def schedule_consistency_check(self, cb: Callable | None = None) -> None:
        """Leader-side: replicate a compute_hash point (the periodic
        CONSISTENCY_CHECK tick of raftstore)."""
        self.propose_cmd(
            {
                "epoch": (self.region.epoch.conf_ver, self.region.epoch.version),
                "ops": [],
                "admin": ("compute_hash",),
            },
            cb or (lambda r: None),
        )

    def transfer_leader_to(self, target_peer_id: int) -> bool:
        """PD-ordered transfer (MsgTransferLeader -> MsgTimeoutNow): tell the
        target to campaign with stickiness bypassed."""
        if not self.node.is_leader():
            return False
        target = self.region.peer_by_id(target_peer_id)
        if target is None or target.role != "voter":
            return False
        # only transfer to a fully caught-up target (raft-rs gates
        # MsgTimeoutNow on matched progress): a lagging target would lose
        # the forced election and cost a leaderless round for nothing
        if self.node.match_index.get(target_peer_id, 0) < self.node.log.last_index():
            return False
        self._send_raft_msg(
            Message(MsgType.TIMEOUT_NOW, self.peer_id, target_peer_id, self.node.term)
        )
        return True

    def _send_tombstone(self, to_peer: RegionPeer) -> None:
        """Explicit destroy order for a peer a committed conf change removed
        (kvproto is_tombstone; raftstore gc of stale peers).  Carries the
        POST-change epoch, which excludes the target — the receiver verifies
        before destroying.  Lossy delivery is fine: a surviving stale peer
        campaigns eventually, and members answer those contacts with fresh
        tombstones (Store.process_messages)."""
        self.store.transport.send(
            to_peer.store_id,
            RaftMessage(
                region_id=self.region.id,
                from_peer=RegionPeer(self.peer_id, self.store.store_id),
                to_peer=to_peer,
                msg=Message(MsgType.HEARTBEAT, self.peer_id, to_peer.peer_id, self.node.term),
                region_epoch=RegionEpoch(self.region.epoch.conf_ver, self.region.epoch.version),
                region=self.region.clone(),
                is_tombstone=True,
            ),
        )

    def _sync_added_peer(self, pid: int, sid: int = 0) -> None:
        """Region bookkeeping for a peer entering the config: record its
        placement (from the replicated entry) and role, and seed brand-new
        peers by snapshot, never by full log replay (peer_storage.rs:
        uninitialized peers wait for one).

        Keeps region metadata in lockstep with the raft node's view:
        add_learner on an existing VOTER is a role no-op there, so it is
        here too (single-step demotion goes remove → add_learner; joint
        demotion flips the node's sets first, so the role follows)."""
        existing = self.region.peer_by_id(pid)
        if pid in self.node.witnesses:
            role = "witness"
        elif pid in self.node.learners:
            role = "learner"
        else:
            role = "voter"
        if existing is None:
            self.region.peers.append(RegionPeer(pid, sid, role))
            if self.node.is_leader() and pid != self.peer_id:
                self.node.force_snapshot.add(pid)
        else:
            existing.role = role

    def _persist_conf_change_state(self, e: Entry) -> None:
        """Membership changed at apply time: region meta, the raft-state blob
        (which embeds the ConfState — the copy written earlier in this ready
        is PRE-change), and the apply index covering this entry go down in
        ONE WriteBatch.  Atomicity matters: a new ConfState persisted with a
        stale apply index would replay the conf entry on recovery against the
        already-updated voter set (enter_joint replay would corrupt outgoing
        to C_new and double-bump conf_ver)."""
        wb = WriteBatch()
        wb.put_cf(
            CF_RAFT, keys.region_state_key(self.region.id), encode_region(self.region, self.merging)
        )
        rl = self.store.raft_log
        if rl is not None:
            rl.put_state(self.region.id, self._encode_raft_state())
        else:
            wb.put_cf(CF_RAFT, keys.raft_state_key(self.region.id), self._encode_raft_state())
        wb.put_cf(CF_RAFT, keys.apply_state_key(self.region.id), codec.encode_u64(e.index))
        self.store.engine.write(wb)

    def _apply_conf_change(self, e: Entry) -> None:
        op, pid = e.conf_change[0], e.conf_change[1]
        if op in ("enter_joint", "leave_joint"):
            to_tombstone = self._apply_conf_change_v2(e, op, pid)
            if to_tombstone is None:
                return  # we left the config and erased our own state
            self.region.epoch.conf_ver += 1
            self._persist_conf_change_state(e)
            _notify_region_cache(self.region.id, "conf_change")
            for p in to_tombstone:
                self._send_tombstone(p)  # after the bump: epoch must exclude them
            return
        removed_peer = self.region.peer_by_id(pid) if op == "remove" else None
        if op == "remove":
            self._notify_removed_peer(pid, e.index)
        was_witness = pid in self.node.witnesses
        self.node.apply_conf_change(e.conf_change)
        if op in ("add", "add_learner", "add_witness"):
            self._sync_added_peer(pid, e.conf_change[2] if len(e.conf_change) > 2 else 0)
            if op == "add" and was_witness:
                # witness -> data voter conversion: the peer has NO data and
                # must be reseeded with a full snapshot before serving
                if self.node.is_leader() and pid != self.peer_id:
                    self.node.force_snapshot.add(pid)
                    self.node._send_append(pid)  # queue the snapshot now
                elif pid == self.peer_id:
                    # we are the converted peer: accept the reseed snapshot
                    # even though our log/commit look fully caught up
                    self.node.force_accept_snapshot = True
        elif op == "promote":
            existing = self.region.peer_by_id(pid)
            if existing is not None:
                existing.role = "voter"
        else:
            self.region.peers = [p for p in self.region.peers if p.peer_id != pid]
            if pid == self.peer_id:
                # applying our own removal: erase persisted identity — a
                # plain destroy would let recover() resurrect the replica
                self.store.destroy_peer_tombstone(self.region.id)
                return
        self.region.epoch.conf_ver += 1
        self._persist_conf_change_state(e)
        _notify_region_cache(self.region.id, "conf_change")
        if removed_peer is not None and self.node.is_leader() and removed_peer.peer_id != self.peer_id:
            # the removed peer may never receive its own removal entry (the
            # leader stops replicating to it the moment it leaves the
            # config) — an explicit tombstone at the NEW epoch destroys it
            self._send_tombstone(removed_peer)

    def _apply_conf_change_v2(self, e: Entry, op: str, changes) -> "list[RegionPeer] | None":
        """Joint membership change (raft thesis 4.3; raft-rs ConfChangeV2,
        applied by components/raftstore/src/store/peer.rs on_admin): the
        enter_joint entry reshapes the incoming config atomically while the
        old voters remain a second quorum; leave_joint retires them.  The
        leader auto-proposes leave_joint as soon as enter_joint applies
        (raft-rs auto_leave); if leadership changes in between, the NEW
        leader re-proposes it from _become_leader.  Region metadata mirrors
        the node's view; peers absent from both configs after leaving are
        destroyed."""
        node = self.node
        if op == "enter_joint":
            node.apply_conf_change(e.conf_change)
            for ch in changes:
                sop, pid = ch[0], ch[1]
                if sop != "remove":
                    self._sync_added_peer(pid, ch[2] if len(ch) > 2 else 0)
                # peers removed-in-joint stay listed as voters: they still
                # vote via the outgoing config until leave_joint
            if node.is_leader():
                node.propose_conf_change(("leave_joint", ()))
            return []
        # leave_joint
        dropped = (node.outgoing or set()) - node.voters - node.learners
        dropped_peers = [p for p in self.region.peers if p.peer_id in dropped]
        for pid in dropped:
            self._notify_removed_peer(pid, e.index)
        node.apply_conf_change(e.conf_change)
        members = node.voters | node.learners
        self.region.peers = [p for p in self.region.peers if p.peer_id in members]
        for p in self.region.peers:
            if p.peer_id in node.witnesses:
                p.role = "witness"
            elif p.peer_id in node.learners:
                p.role = "learner"
            else:
                p.role = "voter"
        if self.peer_id in dropped:
            self.store.destroy_peer_tombstone(self.region.id)
            return None  # self-destroyed: caller must not re-persist us
        return dropped_peers if node.is_leader() else []

    def _apply_split(self, admin) -> None:
        _, split_key, new_region_id, new_pids = admin
        _LOG.info(
            "region split applied",
            region=self.region.id,
            new_region=new_region_id,
            split_key=slog.key(split_key),
        )
        old = self.region
        new_peers = [
            RegionPeer(pid, p.store_id, p.role) for pid, p in zip(new_pids, old.peers)
        ]
        new_region = Region(
            id=new_region_id,
            start_key=split_key,
            end_key=old.end_key,
            epoch=RegionEpoch(old.epoch.conf_ver, old.epoch.version + 1),
            peers=new_peers,
        )
        old.end_key = split_key
        old.epoch.version += 1
        self.store.persist_region(old)
        self.store.create_peer(new_region)
        _notify_region_cache(old.id, "split")
        _notify_region_cache(new_region.id, "split")
        self.store.on_split(old, new_region)

    def _encode_raft_state(self) -> bytes:
        n = self.node
        out = bytearray(
            codec.encode_u64(n.term)
            + codec.encode_u64(n.vote or 0)
            + codec.encode_u64(n.commit)
            + codec.encode_u64(n.log.snapshot_index)
            + codec.encode_u64(n.log.snapshot_term)
        )
        # membership (ConfState): region roles alone can't reconstruct a
        # joint config after a crash — C_old ∩ C_new is ambiguous — so the
        # three sets ride in RaftLocalState
        out += encode_conf_state(n.voters, n.learners, n.outgoing, n.witnesses)
        return bytes(out)

    def _apply_commit_merge(self, admin) -> None:
        """Absorb the (frozen) right-neighbor source region: catch a lagging
        local source replica up from the entries carried in the command
        (raftstore's CatchUpLogs — peer.rs on_catch_up_logs_for_merge), then
        extend our range, bump version above both, and destroy the local
        source peer (CommitMerge)."""
        _, source_id, source_end, source_version, source_commit, carried = admin
        src = self.store.peers.get(source_id)
        if src is not None:
            self._catch_up_source(src, source_commit, carried)
        self.region.end_key = source_end
        self.region.epoch.version = max(self.region.epoch.version, source_version) + 1
        self.store.persist_region(self.region)
        if src is not None:
            self.store.destroy_peer(source_id)
        self.store.erase_region_state(source_id)
        _notify_region_cache(self.region.id, "merge")
        _notify_region_cache(source_id, "merge")
        self.store.on_merge(self.region, source_id)

    def _catch_up_source(self, src: "StorePeer", source_commit: int, carried: list) -> None:
        """CatchUpLogs: a source replica that trails source_commit splices the
        carried (canonical, committed) entries into its OWN raft log and
        applies them through its normal apply path — epoch checks, admin
        entries (splits committed before the freeze), acks and observers all
        behave exactly as they would have without the lag, so the replica
        cannot diverge from the ones that applied these entries live.  This
        removes the quiesce-before-CommitMerge requirement
        (peer.rs on_catch_up_logs_for_merge)."""
        # drain what the replica itself knows to be committed first —
        # synchronously: the assertions below need the engine caught up
        if self.store.apply_system is not None:
            self.store.apply_system.flush(src.region.id)
        src.handle_ready(sync_apply=True)
        node = src.node
        if node.applied >= source_commit:
            return
        for eb in carried:
            e = _decode_entry(eb)
            if e.index <= node.commit or e.index > source_commit:
                continue  # below: already canonical locally; above: not needed
            t = node.log.term_at(e.index)
            if t is None:
                if e.index > node.log.last_index() + 1:
                    raise AssertionError(
                        f"catch-up gap on region {src.region.id}: log ends at "
                        f"{node.log.last_index()}, next carried entry {e.index} "
                        "(source log compacted below this replica — needs snapshot)"
                    )
                node.log.append([e])
            elif t != e.term:
                # local uncommitted leftovers of an old term lose to the
                # committed history
                node.log.truncate_from(e.index)
                node.log.append([e])
        if node.log.last_index() < source_commit:
            raise AssertionError(
                f"catch-up incomplete on region {src.region.id}: log reaches "
                f"{node.log.last_index()} of {source_commit}"
            )
        node.commit = max(node.commit, source_commit)
        src.handle_ready(sync_apply=True)  # normal apply: epoch checks, splits, observers
        if node.applied < source_commit:
            raise AssertionError(
                f"catch-up applied {node.applied} of {source_commit} on region {src.region.id}"
            )

    # -- snapshots ---------------------------------------------------------

    def _generate_snapshot(self, for_witness: bool = False) -> RaftSnapshot:
        """Full region-range snapshot of the data CFs + region meta
        (store/snap.rs; meta rides along like SnapshotMeta).  Witness
        targets get META ONLY — they vote but never store data."""
        fail_point("region_gen_snapshot")
        if self.store.apply_system is not None:
            # the engine scan below must contain every entry the snapshot
            # index claims — drain in-flight applies first (apply.rs
            # observes the same barrier through its FSM ordering)
            self.store.apply_system.flush(self.region.id)
        eng = self.store.engine
        out = bytearray()
        out += codec.encode_compact_bytes(encode_region(self.region, self.merging))
        if not for_witness:
            start = keys.data_key(self.region.start_key)
            end = keys.data_end_key(self.region.end_key)
            for cf in DATA_CFS:
                items = list(eng.scan_cf(cf, start, end))
                out += codec.encode_compact_bytes(cf.encode())
                out += codec.encode_var_u64(len(items))
                for k, v in items:
                    out += codec.encode_compact_bytes(k)
                    out += codec.encode_compact_bytes(v)
        return RaftSnapshot(
            # apply_index, not node.applied: the data scanned above is only
            # guaranteed complete up to what actually finished applying
            index=self.apply_index,
            term=self.node.log.term_at(self.apply_index) or self.node.term,
            data=bytes(out),
            voters=tuple(self.node.voters),
            learners=tuple(self.node.learners),
            outgoing=tuple(self.node.outgoing or ()),
            witnesses=tuple(self.node.witnesses),
        )

    def _apply_snapshot(self, snap: RaftSnapshot) -> None:
        eng = self.store.engine
        b = snap.data
        meta, off = codec.decode_compact_bytes(b, 0)
        self.region, self.merging = decode_region(meta)
        wb = WriteBatch()
        start = keys.data_key(self.region.start_key)
        end = keys.data_end_key(self.region.end_key)
        for cf in DATA_CFS:
            wb.delete_range_cf(cf, start, end)
        while off < len(b):
            cf, off = codec.decode_compact_bytes(b, off)
            n, off = codec.decode_var_u64(b, off)
            for _ in range(n):
                k, off = codec.decode_compact_bytes(b, off)
                v, off = codec.decode_compact_bytes(b, off)
                wb.put_cf(cf.decode(), k, v)
        eng.write(wb)
        self.store.persist_region(self.region)
        wb2 = WriteBatch()
        rl = self.store.raft_log
        if rl is not None:
            rl.put_state(self.region.id, self._encode_raft_state())
            # log below the snapshot point is obsolete; purge lets the log
            # engine drop/unlink dead segments (engine.rs gc on snapshot).
            # The snapshot data itself must outlive the purged entries.
            self.store.sync_kv_wal()
            rl.purge(self.region.id, self.node.log.snapshot_index)
        else:
            wb2.put_cf(CF_RAFT, keys.raft_state_key(self.region.id), self._encode_raft_state())
        wb2.put_cf(CF_RAFT, keys.apply_state_key(self.region.id), codec.encode_u64(self.node.applied))
        eng.write(wb2)
        # a snapshot replaces region data wholesale — no per-batch deltas
        # exist for it, so pending write-through chains must not survive
        _notify_region_write_lost(self.region.id, self.node.applied,
                                  token=self.store.data_token)
        self.apply_index = max(self.apply_index, self.node.applied)


def encode_region(region: Region, merging: bool = False) -> bytes:
    out = bytearray()
    out += codec.encode_var_u64(region.id)
    out += codec.encode_compact_bytes(region.start_key)
    out += codec.encode_compact_bytes(region.end_key)
    out += codec.encode_var_u64(region.epoch.conf_ver)
    out += codec.encode_var_u64(region.epoch.version)
    out += codec.encode_var_u64(len(region.peers))
    for p in region.peers:
        out += codec.encode_var_u64(p.peer_id)
        out += codec.encode_var_u64(p.store_id)
        out.append({"voter": 0, "learner": 1, "witness": 2}.get(p.role, 0))
    out.append(1 if merging else 0)
    return bytes(out)


def decode_region(b: bytes) -> tuple[Region, bool]:
    """Returns (region, merging)."""
    rid, off = codec.decode_var_u64(b, 0)
    start, off = codec.decode_compact_bytes(b, off)
    end, off = codec.decode_compact_bytes(b, off)
    cv, off = codec.decode_var_u64(b, off)
    v, off = codec.decode_var_u64(b, off)
    n, off = codec.decode_var_u64(b, off)
    peers = []
    for _ in range(n):
        pid, off = codec.decode_var_u64(b, off)
        sid, off = codec.decode_var_u64(b, off)
        role = {0: "voter", 1: "learner", 2: "witness"}.get(b[off], "voter")
        off += 1
        peers.append(RegionPeer(pid, sid, role))
    merging = off < len(b) and b[off] == 1
    return Region(rid, start, end, RegionEpoch(cv, v), peers), merging


def encode_conf_state(voters, learners, outgoing, witnesses=()) -> bytes:
    """The ConfState tail of the raft-state blob: varint-counted u64 groups
    (voters, learners, outgoing, witnesses).  Shared by persistence,
    recovery, and the Debugger's unsafe-recover so the layout has exactly
    one definition."""
    out = bytearray()
    for group in (voters, learners, outgoing or set(), witnesses or set()):
        out += codec.encode_var_u64(len(group))
        for pid in sorted(group):
            out += codec.encode_u64(pid)
    return bytes(out)


def decode_conf_state(state: bytes, off: int = 40) -> tuple[set, set, set, set]:
    """Inverse of encode_conf_state, reading at ``off`` (after the 40-byte
    fixed term/vote/commit/snapshot header).  The witness group is optional
    for blobs persisted before it existed."""
    groups = []
    for gi in range(4):
        if gi == 3 and off >= len(state):
            groups.append(set())
            break
        cnt, off = codec.decode_var_u64(state, off)
        ids = set()
        for _ in range(cnt):
            ids.add(codec.decode_u64(state, off))
            off += 8
        groups.append(ids)
    return groups[0], groups[1], groups[2], groups[3]


def _encode_entry(e: Entry) -> bytes:
    out = bytearray()
    out += codec.encode_var_u64(e.term)
    out += codec.encode_var_u64(e.index)
    out += codec.encode_compact_bytes(e.data)
    if e.conf_change and e.conf_change[0] in ("enter_joint", "leave_joint"):
        out.append(2)
        out += codec.encode_compact_bytes(e.conf_change[0].encode())
        changes = e.conf_change[1]
        out += codec.encode_var_u64(len(changes))
        for ch in changes:
            out += codec.encode_compact_bytes(ch[0].encode())
            out += codec.encode_var_u64(ch[1])
            out += codec.encode_var_u64(ch[2] if len(ch) > 2 else 0)
    elif e.conf_change:
        out.append(3)  # (op, peer_id, store_id) — placement rides in the log
        out += codec.encode_compact_bytes(e.conf_change[0].encode())
        out += codec.encode_var_u64(e.conf_change[1])
        out += codec.encode_var_u64(e.conf_change[2] if len(e.conf_change) > 2 else 0)
    else:
        out.append(0)
    return bytes(out)


def _decode_entry(b: bytes) -> Entry:
    term, off = codec.decode_var_u64(b, 0)
    index, off = codec.decode_var_u64(b, off)
    data, off = codec.decode_compact_bytes(b, off)
    conf = None
    if b[off] == 1:
        op, off2 = codec.decode_compact_bytes(b, off + 1)
        pid, _ = codec.decode_var_u64(b, off2)
        conf = (op.decode(), pid)
    elif b[off] == 3:
        op, off2 = codec.decode_compact_bytes(b, off + 1)
        pid, off2 = codec.decode_var_u64(b, off2)
        sid, _ = codec.decode_var_u64(b, off2)
        conf = (op.decode(), pid, sid)
    elif b[off] == 2:
        op, off2 = codec.decode_compact_bytes(b, off + 1)
        n, off2 = codec.decode_var_u64(b, off2)
        changes = []
        for _ in range(n):
            sop, off2 = codec.decode_compact_bytes(b, off2)
            pid, off2 = codec.decode_var_u64(b, off2)
            sid, off2 = codec.decode_var_u64(b, off2)
            changes.append((sop.decode(), pid, sid))
        conf = (op.decode(), tuple(changes))
    return Entry(term, index, data, conf)


# ---------------------------------------------------------------------------
# Store
# ---------------------------------------------------------------------------

class Store:
    """All region peers on one node + message routing (StoreFsm + router)."""

    def __init__(self, store_id: int, transport: Transport, engine: BTreeEngine | None = None,
                 raft_log=None):
        self.store_id = store_id
        self.transport = transport
        self.engine = engine or BTreeEngine()
        # optional purpose-built raft log engine (native/raftlog.cc — the
        # raft_log_engine role, selected per-store like the reference at
        # components/server/src/server.rs:153-157).  When set, raft entries +
        # hard state live there; region meta + apply state stay in CF_RAFT of
        # the KV engine so they remain crash-atomic with applied data.
        self.raft_log = raft_log
        # True when the KV engine's WAL runs buffered because the raft log is
        # the durable source of truth (the reference applies with sync=false
        # and flushes kvdb before purging raft logs).  Set by the server
        # assembly; gates the sync_kv_wal() barriers below.
        self.kv_buffered = False
        self.peers: dict[int, StorePeer] = {}
        self._inbox: list[RaftMessage] = []
        self._compact_requested = threading.Event()
        self._mu = make_rlock("raft.store", label=f"store-{store_id}")
        self.split_observers: list[Callable] = []
        self.merge_observers: list[Callable] = []
        self.apply_observers: list[Callable] = []
        # apply pipeline (batch-system shape): None = inline apply on the
        # raft thread (deterministic test clusters); enabled by server nodes
        self.apply_system = None
        # generic FSM batch system (fsm_system.Router): when attached, raft
        # messages route to per-region mailboxes and poller threads drive
        # step/ready — the synchronous inbox path is only for test clusters
        self.fsm_router = None
        # consistency check (consistency_check.rs): per-region (index, hash)
        # recorded at compute_hash apply; divergences land in
        # inconsistent_regions for the debug service / operator
        self.consistency_hashes: dict[int, tuple[int, int]] = {}
        self.inconsistent_regions: dict[int, dict] = {}

    @property
    def data_token(self):
        """THE identity of this store's data (docs/write_path.md): stamped
        on RegionSnapshots, carried by write-through notifies, bound by the
        region column cache.  One definition — a mismatch anywhere silently
        drops every delta as foreign."""
        return id(self.engine)

    def enable_apply_pipeline(self, workers: int = 2) -> None:
        """Apply committed data entries off the raft thread (apply.rs
        ApplyBatchSystem): append of entry N+1 overlaps apply of entry N."""
        from .batch_system import ApplySystem

        if self.apply_system is None:
            self.apply_system = ApplySystem(workers, name=f"apply-{self.store_id}")

    def stop_apply_pipeline(self) -> None:
        if self.apply_system is not None:
            self.apply_system.stop()
            self.apply_system = None

    # -- lifecycle ---------------------------------------------------------

    def create_peer(self, region: Region) -> StorePeer:
        with self._mu:
            me = region.peer_on_store(self.store_id)
            assert me is not None, f"store {self.store_id} not in region {region.id}"
            peer = StorePeer(self, region.clone(), me.peer_id)
            self.peers[region.id] = peer
            # a peer born after the replication status arrived still needs
            # the label-group config (set_replication_mode no-ops on repeats)
            last_repl = getattr(self, "_last_repl_status", None)
            if last_repl is not None:
                self._apply_repl_to_peer(peer, last_repl)
            self.persist_region(peer.region)
            # under buffered apply the meta write above is not yet durable,
            # but the peer may durably VOTE (raft log) before any admin
            # barrier flushes it — recovery only enumerates KV region meta,
            # so a crash would forget the vote.  Region creation is rare;
            # pay one fdatasync here.
            self.sync_kv_wal()
            if self.fsm_router is not None:
                self.fsm_router.register(region.id)
                self.fsm_router.send(region.id, ("ready",))
            return peer

    def destroy_peer(self, region_id: int) -> None:
        self.peers.pop(region_id, None)
        if self.fsm_router is not None:
            self.fsm_router.close(region_id)

    def destroy_peer_tombstone(self, region_id: int) -> None:
        """Destroy a peer AND erase its persisted identity (the reference
        writes PeerState::Tombstone): recovery must not resurrect a replica
        the config no longer contains."""
        if self.apply_system is not None:
            # an in-flight apply run would re-write data + apply_state for
            # the region AFTER the erase, leaving orphaned keys recovery
            # could mistake for live state — drain first
            self.apply_system.flush(region_id)
        self.peers.pop(region_id, None)
        if self.fsm_router is not None:
            self.fsm_router.close(region_id)
        self.erase_region_state(region_id)

    def erase_region_state(self, region_id: int) -> None:
        erase_region_state(self.engine, region_id)
        if self.raft_log is not None:
            # ordering matters under buffered apply: the CF_RAFT tombstone
            # deletes must be durable BEFORE the log engine forgets the
            # region's vote/term — a crash in between would otherwise
            # resurrect the peer with term=0 and let it double-vote
            self.sync_kv_wal()
            self.raft_log.clean(region_id)

    def set_replication_mode(self, status: dict) -> None:
        """Apply the PD ReplicationStatus (replication_mode.rs) to every
        peer's raft node: DrAutoSync in ``sync`` state turns label-group
        commit on; ``async``/``sync_recover`` (or Majority mode) turn it
        off.  Safe to call from the heartbeat thread — flag/dict swaps are
        atomic under the GIL and the raft thread re-evaluates commit on its
        next tick.  No-ops when the status is unchanged (it rides EVERY
        heartbeat) so the common majority-mode path costs one comparison."""
        if status == getattr(self, "_last_repl_status", None):
            return
        self._last_repl_status = dict(status)
        with self._mu:
            peers = list(self.peers.values())
        for peer in peers:
            self._apply_repl_to_peer(peer, status)
            self.notify_region(peer.region.id)

    def _apply_repl_to_peer(self, peer, status: dict) -> None:
        node = peer.node
        if status.get("mode") == "dr_auto_sync":
            labels = status.get("labels") or {}
            node.peer_groups = {
                p.peer_id: labels.get(p.store_id) for p in peer.region.peers
            }
            node.group_commit = status.get("state") == "sync"
        else:
            node.group_commit = False
            node.peer_groups = {}

    def sync_kv_wal(self) -> None:
        """Make every buffered apply write durable (kvdb flush before raft-log
        purge, and after rare admin mutations whose loss recovery could not
        re-derive).  No-op unless the server opted into buffered apply."""
        if self.kv_buffered:
            # closing the unsynced window = one fdatasync of the engine WAL
            self.engine.set_sync(True)
            self.engine.set_sync(False)

    def persist_region(self, region: Region, merging: bool = False) -> None:
        self.engine.put_cf(
            CF_RAFT, keys.region_state_key(region.id), encode_region(region, merging)
        )

    def recover(self) -> int:
        """Rebuild every peer from persisted state after a restart
        (fsm/store.rs init: scan region states, restore PeerStorage).
        Returns the number of recovered peers."""
        snap = self.engine.snapshot()
        prefix = keys.LOCAL_PREFIX + keys.REGION_META_PREFIX
        recovered = 0
        for k, v in snap.scan_cf(CF_RAFT, prefix, prefix[:-1] + bytes([prefix[-1] + 1])):
            region, merging = decode_region(v)
            me = region.peer_on_store(self.store_id)
            if me is None or region.id in self.peers:
                continue
            peer = StorePeer(self, region, me.peer_id)
            peer.merging = merging
            node = peer.node
            if self.raft_log is not None:
                state = self.raft_log.state(region.id)
                if state is None:
                    # store predates the log engine (or it was switched on):
                    # migrate this region's CF_RAFT log + state into the log
                    # engine, or recovery would come up amnesiac (term=0,
                    # no entries) while the real state sits in CF_RAFT
                    state = self._migrate_region_log(snap, region.id)
            else:
                state = snap.get_cf(CF_RAFT, keys.raft_state_key(region.id))
            if state is not None:
                node.term = codec.decode_u64(state, 0)
                vote = codec.decode_u64(state, 8)
                node.vote = vote or None
                node.commit = codec.decode_u64(state, 16)
                node.log.snapshot_index = codec.decode_u64(state, 24)
                node.log.snapshot_term = codec.decode_u64(state, 32)
                node.log.offset = node.log.snapshot_index + 1
                if len(state) > 40:  # persisted ConfState (incl. joint config)
                    voters, learners, outgoing, witnesses = decode_conf_state(state)
                    node.voters, node.learners = voters, learners
                    node.outgoing = outgoing or None
                    node.witnesses = witnesses
            applied_raw = snap.get_cf(CF_RAFT, keys.apply_state_key(region.id))
            applied = codec.decode_u64(applied_raw) if applied_raw else 0
            entries = []
            if self.raft_log is not None:
                for _idx, blob in self.raft_log.entries(region.id):
                    e = _decode_entry(blob)
                    if e.index > node.log.snapshot_index:
                        entries.append(e)
            else:
                log_prefix = keys.region_raft_prefix(region.id) + keys.RAFT_LOG_SUFFIX
                for lk, lv in snap.scan_cf(
                    CF_RAFT, log_prefix, log_prefix[:-1] + bytes([log_prefix[-1] + 1])
                ):
                    e = _decode_entry(lv)
                    if e.index > node.log.snapshot_index:
                        entries.append(e)
                entries.sort(key=lambda e: e.index)
            node.log.entries = entries
            node.applied = max(applied, node.log.snapshot_index)
            node.commit = max(node.commit, node.applied)
            peer.apply_index = node.applied
            self.peers[region.id] = peer
            recovered += 1
        return recovered

    def _migrate_region_log(self, snap, region_id: int) -> bytes | None:
        """One-shot CF_RAFT -> log-engine migration for a region persisted
        before the raft log engine was enabled.  Returns the legacy raft
        state blob (also written into the log engine), or None if the region
        never persisted one."""
        state = snap.get_cf(CF_RAFT, keys.raft_state_key(region_id))
        log_prefix = keys.region_raft_prefix(region_id) + keys.RAFT_LOG_SUFFIX
        legacy = []
        for lk, lv in snap.scan_cf(
            CF_RAFT, log_prefix, log_prefix[:-1] + bytes([log_prefix[-1] + 1])
        ):
            e = _decode_entry(lv)
            legacy.append((e.index, lv))
        legacy.sort()
        if state is None and not legacy:
            return None
        # contiguous runs (splits/compactions can leave gaps in CF_RAFT)
        run_start = 0
        for i in range(1, len(legacy) + 1):
            if i == len(legacy) or legacy[i][0] != legacy[i - 1][0] + 1:
                run = legacy[run_start:i]
                if run:
                    self.raft_log.append(region_id, run[0][0], [b for _, b in run])
                run_start = i
        if state is not None:
            self.raft_log.put_state(region_id, state)
        # drop the legacy copies so the two stores never diverge
        wb = WriteBatch()
        wb.delete_range_cf(
            CF_RAFT,
            log_prefix + codec.encode_u64(0),
            log_prefix + codec.encode_u64(1 << 62),
        )
        wb.delete_cf(CF_RAFT, keys.raft_state_key(region_id))
        self.engine.write(wb)
        return state

    # -- routing -----------------------------------------------------------

    def region_for_key(self, key: bytes) -> StorePeer | None:
        with self._mu:
            for peer in self.peers.values():
                if peer.region.contains(key):
                    return peer
        return None

    def leader_store_of(self, region_id: int) -> int | None:
        peer = self.peers.get(region_id)
        if peer is None:
            return None
        lid = peer.node.leader_id
        if lid is None:
            return None
        p = peer.region.peer_by_id(lid)
        return p.store_id if p else None

    def enqueue_message(self, rmsg: RaftMessage) -> None:
        router = self.fsm_router
        if router is None:
            with self._mu:
                self._inbox.append(rmsg)
            return
        # batch-system mode: peer traffic lands in the region mailbox; store-
        # level work (tombstones, first contact for an unknown region) goes
        # to the control FSM (router.rs send vs control_box)
        if not rmsg.is_tombstone and rmsg.region_id in self.peers:
            if router.send(rmsg.region_id, ("raft", rmsg)):
                return
        router.send_control(("route", rmsg))

    def notify_region(self, region_id: int) -> None:
        """Wake a region FSM (propose/read just added work for its poller)."""
        if self.fsm_router is not None:
            self.fsm_router.send(region_id, ("ready",))

    def attach_fsm_router(self, router) -> None:
        """Enter batch-system mode: register every live peer's mailbox and
        hand any messages that arrived pre-attach to the control FSM."""
        self.fsm_router = router
        with self._mu:
            for rid in self.peers:
                router.register(rid)
                router.send(rid, ("ready",))
            backlog, self._inbox = self._inbox, []
        for rmsg in backlog:
            router.send_control(("route", rmsg))

    # -- driving -----------------------------------------------------------

    def _route_one(self, rmsg: RaftMessage) -> "StorePeer | None":
        """Store-level routing (fsm/store.rs maybe_create_peer): tombstone
        destruction and first-contact bootstrap.  Returns the peer the
        message should be stepped into, or None if consumed/dropped."""
        peer = self.peers.get(rmsg.region_id)
        if rmsg.is_tombstone:
            # a committed conf change removed us at this epoch: verify
            # and self-destruct (raftstore handling of is_tombstone)
            if (
                peer is not None
                and peer.peer_id == rmsg.to_peer.peer_id
                and rmsg.region_epoch.conf_ver >= peer.region.epoch.conf_ver
                and (rmsg.region is None or rmsg.region.peer_by_id(peer.peer_id) is None)
            ):
                self.destroy_peer_tombstone(rmsg.region_id)
            return None
        if peer is None and rmsg.region is not None:
            # first contact for a new peer (conf change / snapshot):
            # bootstrap it if we're in the carried region
            if rmsg.region.peer_on_store(self.store_id) is not None or rmsg.to_peer.store_id == self.store_id:
                region = rmsg.region.clone()
                if region.peer_on_store(self.store_id) is None:
                    region.peers.append(RegionPeer(rmsg.to_peer.peer_id, self.store_id))
                with self._mu:
                    peer = self.peers.get(rmsg.region_id)
                    if peer is None:
                        peer = StorePeer(self, region, rmsg.to_peer.peer_id)
                        self.peers[rmsg.region_id] = peer
                if self.fsm_router is not None:
                    self.fsm_router.register(rmsg.region_id)
        if peer is not None and rmsg.to_peer.peer_id == peer.peer_id:
            return peer
        return None

    def _step_checked(self, peer: "StorePeer", rmsg: RaftMessage) -> None:
        """Step with the stale-sender GC check (raftstore is_msg_stale):
        a sender a NEWER committed epoch excludes gets a tombstone back
        instead of a vote/step — the retry path when the removal-time
        tombstone was lost."""
        if (
            rmsg.region_epoch.conf_ver < peer.region.epoch.conf_ver
            and peer.region.peer_by_id(rmsg.from_peer.peer_id) is None
            and rmsg.from_peer.peer_id != peer.peer_id
        ):
            peer._send_tombstone(rmsg.from_peer)
            return
        peer.node.step(rmsg.msg)

    def process_messages(self) -> bool:
        with self._mu:
            inbox, self._inbox = self._inbox, []
        moved = bool(inbox)
        for rmsg in inbox:
            peer = self._route_one(rmsg)
            if peer is not None:
                self._step_checked(peer, rmsg)
        return moved

    def handle_readies(self) -> bool:
        moved = False
        for peer in list(self.peers.values()):
            if peer.handle_ready():
                moved = True
        return moved

    def tick(self) -> None:
        for peer in list(self.peers.values()):
            peer.node.tick()
        if self._compact_requested.is_set():
            self._compact_requested.clear()
            self.compact_raft_logs()

    def request_log_compaction(self) -> None:
        """Ask the raft-driving thread to compact at its next tick — log
        state is single-writer (the raft loop); other threads must not
        mutate it concurrently."""
        self._compact_requested.set()

    # -- raft log GC (store/worker/raftlog_gc.rs) ---------------------------

    def compact_raft_logs(self, threshold: int = 1024, slack: int = 64) -> int:
        """Truncate each region's applied log prefix once it exceeds
        ``threshold`` entries.  ``slack`` recent entries always stay for
        cheap catch-up; followers lagging more than ``threshold`` behind are
        abandoned to snapshot seeding (which the append path already
        handles).  Must run on the raft-driving thread (see
        request_log_compaction) — or per region on its own poller in
        batch-system mode.  Returns entries dropped."""
        dropped = 0
        for peer in list(self.peers.values()):
            dropped += self.compact_peer_log(peer, threshold, slack)
        return dropped

    def compact_peer_log(self, peer: "StorePeer", threshold: int = 1024, slack: int = 64) -> int:
        """One region's log truncation; must run on whatever thread owns the
        region's raft state (raft loop, or its FSM poller)."""
        node = peer.node
        # compact at COMPLETED apply: with the pipeline, node.applied may
        # run ahead of the engine — compacting past apply_index would
        # strand recovery (persisted ApplyState behind a truncated log)
        applied = min(node.applied, peer.apply_index)
        first = node.log.offset
        if applied - first + 1 <= threshold:
            return 0
        compact_to = applied - slack
        if node.is_leader():
            # don't compact below followers that are close enough to catch
            # up from the log; stragglers further behind than the
            # threshold are abandoned to snapshot seeding (raftlog_gc.rs)
            near_matches = [
                m
                for p in node._replicas()
                if (m := node.match_index.get(p, 0)) >= applied - threshold
            ]
            if near_matches:
                compact_to = min(compact_to, min(near_matches))
        if compact_to <= first - 1:
            return 0
        term = node.log.term_at(compact_to)
        if term is None:
            return 0
        node.log.compact_to(compact_to, term)
        if self.raft_log is not None:
            # applied data must be durable before the entries that produced
            # it disappear (the reference flushes kvdb before raft-engine
            # purge), and the raft state carrying the new truncated index
            # must be durable before purge unlinks segments — recovery with
            # the OLD snapshot_index against a purged log would misalign
            # RaftLog's positional entry indexing (core.py:135).  Then a
            # logical purge marker, not a range delete — the log engine
            # unlinks whole dead segments (raftlog.cc gc/rewrite).
            self.sync_kv_wal()
            self.raft_log.put_state(peer.region.id, peer._encode_raft_state())
            self.raft_log.purge(peer.region.id, compact_to)
        else:
            wb = WriteBatch()
            log_prefix = keys.region_raft_prefix(peer.region.id) + keys.RAFT_LOG_SUFFIX
            wb.delete_range_cf(
                CF_RAFT,
                log_prefix + codec.encode_u64(0),
                log_prefix + codec.encode_u64(compact_to + 1),
            )
            wb.put_cf(CF_RAFT, keys.raft_state_key(peer.region.id), peer._encode_raft_state())
            self.engine.write(wb)
        return compact_to - first + 1

    def on_split(self, old: Region, new: Region) -> None:
        for cb in self.split_observers:
            cb(self, old, new)

    def on_merge(self, target: Region, source_id: int) -> None:
        for cb in self.merge_observers:
            cb(self, target, source_id)

    def on_applied(self, region: Region, cmd: dict) -> None:
        for cb in self.apply_observers:
            cb(self, region, cmd)


class StoreFsmDelegate:
    """PollHandler driving one store's region FSMs (fsm/peer.rs PeerFsmDelegate
    + fsm/store.rs StoreFsmDelegate, on fsm_system.BatchSystem).

    Region mailbox messages: ("raft", rmsg) step + ready, ("tick",) election/
    heartbeat timers, ("ready",) wake after a propose/read, ("compact",) log
    GC.  Control mailbox: ("route", rmsg) store-level routing (bootstrap /
    tombstone), after which the message is forwarded to the now-live region.
    """

    def __init__(self, store: Store):
        self.store = store

    def begin(self, batch_size: int) -> None:
        pass

    def end(self, addrs: list) -> None:
        pass

    def handle(self, region_id: int, msgs: list) -> None:
        store = self.store
        peer = store.peers.get(region_id)
        if peer is None:
            return
        for m in msgs:
            kind = m[0]
            if kind == "raft":
                rmsg = m[1]
                if rmsg.to_peer.peer_id == peer.peer_id:
                    store._step_checked(peer, rmsg)
            elif kind == "tick":
                peer.node.tick()
            elif kind == "compact":
                store.compact_peer_log(peer)
            elif kind == "tombstone":
                # destruction runs HERE, on the poller that owns this FSM —
                # never on the control poller, which could otherwise erase
                # region state concurrently with a handle_ready persist
                rmsg = m[1]
                if (
                    peer.peer_id == rmsg.to_peer.peer_id
                    and rmsg.region_epoch.conf_ver >= peer.region.epoch.conf_ver
                    and (rmsg.region is None or rmsg.region.peer_by_id(peer.peer_id) is None)
                ):
                    store.destroy_peer_tombstone(region_id)
                    return
            # ("ready",) carries no action: the unconditional ready sweep
            # below is the point of the wakeup
        while peer.handle_ready():
            if store.peers.get(region_id) is not peer:
                break  # destroyed mid-sweep (merge source / tombstone)

    def handle_control(self, msgs: list) -> None:
        store = self.store
        for m in msgs:
            if m[0] != "route":
                continue
            rmsg = m[1]
            if rmsg.is_tombstone:
                # forward to the owning FSM: destruction must not run on the
                # control poller (it would race the region poller's persists)
                if rmsg.region_id in store.peers:
                    store.fsm_router.send(rmsg.region_id, ("tombstone", rmsg))
                continue
            peer = store._route_one(rmsg)
            if peer is None:
                continue
            # peer now exists (possibly just bootstrapped): its own FSM
            # processes the message so per-region state stays single-owner
            if not store.fsm_router.send(rmsg.region_id, ("raft", rmsg)):
                # mailbox raced closed — drop, sender will retry
                pass
