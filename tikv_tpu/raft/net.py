"""RaftMessage wire codec: peer raft traffic as self-describing frames.

Re-expression of the peer-transport message surface of
``src/server/raft_client.rs`` (:588 BatchRaftMessage streaming, :844 send)
and ``src/server/snap.rs`` (:41 send_snap chunking, :260 recv task): the
reference ships kvproto ``RaftMessage`` protobufs over a dedicated gRPC
client-stream; this framework ships the same envelope as wire-codec tuples
over its framed TCP transport.

Snapshot-bearing messages are the one special case, exactly as in the
reference: snapshot data can be arbitrarily large, so it never rides the
batched raft stream.  ``split_snapshot`` / ``join_snapshot`` cut the encoded
message into chunk frames with a transfer id; the receiving store re-joins
them and injects the completed message (snap.rs's RecvSnapContext).
"""

from __future__ import annotations

from .core import Entry, Message, MsgType, Snapshot
from .region import Peer as RegionPeer, Region, RegionEpoch
from .store import RaftMessage, _decode_entry, _encode_entry, decode_region, encode_region

SNAP_CHUNK_BYTES = 1 << 20  # snap.rs SNAP_CHUNK_LEN is 1MB


def _peer_to_wire(p: RegionPeer) -> tuple:
    return (p.peer_id, p.store_id, p.role)


def _peer_from_wire(t) -> RegionPeer:
    return RegionPeer(t[0], t[1], t[2])


def _snapshot_to_wire(s: Snapshot | None):
    if s is None:
        return None
    return (
        s.index,
        s.term,
        s.data,
        tuple(s.voters),
        tuple(s.learners),
        tuple(s.outgoing),
        tuple(s.witnesses),
    )


def _snapshot_from_wire(t) -> Snapshot | None:
    if t is None:
        return None
    return Snapshot(
        index=t[0], term=t[1], data=t[2], voters=tuple(t[3]),
        learners=tuple(t[4]), outgoing=tuple(t[5]), witnesses=tuple(t[6]),
    )


def msg_to_wire(m: Message) -> tuple:
    return (
        m.type.value,
        m.frm,
        m.to,
        m.term,
        m.log_index,
        m.log_term,
        [_encode_entry(e) for e in m.entries],
        m.commit,
        m.reject,
        m.reject_hint,
        _snapshot_to_wire(m.snapshot),
        m.context,
        m.hb_round,
        m.force,
    )


def msg_from_wire(t) -> Message:
    return Message(
        type=MsgType(t[0]),
        frm=t[1],
        to=t[2],
        term=t[3],
        log_index=t[4],
        log_term=t[5],
        entries=[_decode_entry(b) for b in t[6]],
        commit=t[7],
        reject=bool(t[8]),
        reject_hint=t[9],
        snapshot=_snapshot_from_wire(t[10]),
        context=t[11],
        hb_round=t[12],
        force=bool(t[13]),
    )


def rmsg_to_wire(rmsg: RaftMessage) -> tuple:
    return (
        rmsg.region_id,
        _peer_to_wire(rmsg.from_peer),
        _peer_to_wire(rmsg.to_peer),
        msg_to_wire(rmsg.msg),
        (rmsg.region_epoch.conf_ver, rmsg.region_epoch.version),
        encode_region(rmsg.region) if rmsg.region is not None else None,
        rmsg.is_tombstone,
    )


def rmsg_from_wire(t) -> RaftMessage:
    region = None
    if t[5] is not None:
        region, _merging = decode_region(t[5])
    return RaftMessage(
        region_id=t[0],
        from_peer=_peer_from_wire(t[1]),
        to_peer=_peer_from_wire(t[2]),
        msg=msg_from_wire(t[3]),
        region_epoch=RegionEpoch(t[4][0], t[4][1]),
        region=region,
        is_tombstone=bool(t[6]),
    )


# -- snapshot chunking (snap.rs:41 SnapChunk stream) -------------------------

def split_snapshot(rmsg: RaftMessage, xfer_id: int, chunk_bytes: int = SNAP_CHUNK_BYTES):
    """Yield ``snap_chunk`` request dicts for one snapshot-bearing message.

    The header (everything except snapshot data) rides in the first chunk;
    data is cut into ``chunk_bytes`` pieces.  The last chunk is marked so the
    receiver knows when to join + inject."""
    assert rmsg.msg.snapshot is not None
    snap = rmsg.msg.snapshot
    header_msg = Message(
        type=rmsg.msg.type, frm=rmsg.msg.frm, to=rmsg.msg.to, term=rmsg.msg.term,
        log_index=rmsg.msg.log_index, log_term=rmsg.msg.log_term,
        commit=rmsg.msg.commit,
        snapshot=Snapshot(
            index=snap.index, term=snap.term, data=b"", voters=snap.voters,
            learners=snap.learners, outgoing=snap.outgoing, witnesses=snap.witnesses,
        ),
        context=rmsg.msg.context,
    )
    header = rmsg_to_wire(
        RaftMessage(
            region_id=rmsg.region_id, from_peer=rmsg.from_peer, to_peer=rmsg.to_peer,
            msg=header_msg, region_epoch=rmsg.region_epoch, region=rmsg.region,
        )
    )
    data = snap.data
    n_chunks = max(1, (len(data) + chunk_bytes - 1) // chunk_bytes)
    for i in range(n_chunks):
        chunk = data[i * chunk_bytes : (i + 1) * chunk_bytes]
        yield {
            "xfer_id": xfer_id,
            "seq": i,
            "last": i == n_chunks - 1,
            "header": header if i == 0 else None,
            "data": chunk,
        }


class SnapshotAssembler:
    """Receiver side of the snapshot stream: joins chunk frames back into a
    complete snapshot-bearing RaftMessage (snap.rs recv_snap)."""

    def __init__(self, max_transfers: int = 16):
        import threading

        self._xfers: dict[int, dict] = {}
        self.max_transfers = max_transfers
        self._mu = threading.Lock()

    def add_chunk(self, req: dict) -> RaftMessage | None:
        """Returns the completed message when the last chunk arrives.
        Thread-safe: different peer stores stream on different connections."""
        with self._mu:
            return self._add_chunk(req)

    def _add_chunk(self, req: dict) -> RaftMessage | None:
        xid = req["xfer_id"]
        st = self._xfers.get(xid)
        if st is None:
            if req["seq"] != 0 or req.get("header") is None:
                return None  # mid-transfer chunk for an unknown/aborted xfer
            while len(self._xfers) >= self.max_transfers:
                self._xfers.pop(next(iter(self._xfers)))
            st = {"header": req["header"], "chunks": {}, "next": 0}
            self._xfers[xid] = st
        st["chunks"][req["seq"]] = req["data"]
        if not req["last"]:
            return None
        # join in seq order; a gap aborts the transfer (sender will re-send
        # the snapshot: raft re-queues it when the follower stays behind)
        n = req["seq"] + 1
        if any(i not in st["chunks"] for i in range(n)):
            del self._xfers[xid]
            return None
        data = b"".join(st["chunks"][i] for i in range(n))
        del self._xfers[xid]
        rmsg = rmsg_from_wire(st["header"])
        snap = rmsg.msg.snapshot
        rmsg.msg.snapshot = Snapshot(
            index=snap.index, term=snap.term, data=data, voters=snap.voters,
            learners=snap.learners, outgoing=snap.outgoing, witnesses=snap.witnesses,
        )
        return rmsg
