"""Apply batch system: committed entries applied OFF the raft thread.

Re-expression of the reference's two-pool write path
(``components/batch-system/src/batch.rs:284`` Poller,
``raftstore/src/store/fsm/apply.rs:3120`` ApplyBatchSystem + :920
handle_raft_committed_entries): the store thread persists log appends and
sends messages, while committed DATA entries flow through per-region ordered
queues to apply workers.  Append of entry N+1 (WAL fsync) overlaps apply of
entry N (engine write) — both release the GIL in the native engine, so the
pipeline is real parallelism, not just interleaving.

Ordering contract: one region's tasks always run on the same worker
(region_id -> worker hash), FIFO — exactly the reference's one-ApplyFsm-per-
region rule.  Admin entries (split/merge/conf change) do NOT come through
here: they mutate raft/store state owned by the raft thread, so the store
flushes the region's queue and applies them inline (apply.rs takes the same
barrier through its own message ordering).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable

from ..analysis.sanitizer import make_condition


class ApplySystem:
    """N workers, per-region FIFO ordering, flush barriers."""

    def __init__(self, workers: int = 2, name: str = "apply"):
        self.n = max(1, workers)
        self._queues: list[deque] = [deque() for _ in range(self.n)]
        self._cvs = [
            make_condition("raft.apply_system", label=f"{name}-{i}")
            for i in range(self.n)
        ]
        self._stop = False
        self._threads = []
        # faults escaping a task land here (the store surfaces them)
        self.errors: list[Exception] = []
        for i in range(self.n):
            t = threading.Thread(target=self._worker, args=(i,), daemon=True, name=f"{name}-{i}")
            t.start()
            self._threads.append(t)

    def _slot(self, region_id: int) -> int:
        return region_id % self.n

    def submit(self, region_id: int, task: Callable[[], None]) -> None:
        i = self._slot(region_id)
        with self._cvs[i]:
            self._queues[i].append((region_id, task))
            self._cvs[i].notify()

    def flush(self, region_id: int, timeout: float = 30.0) -> None:
        """Barrier: returns once every task for ``region_id`` submitted
        before this call has completed (admin-entry / snapshot-gen gate)."""
        done = threading.Event()
        self.submit(region_id, done.set)
        if not done.wait(timeout):
            raise TimeoutError(f"apply queue for region {region_id} stalled")

    def _worker(self, i: int) -> None:
        cv = self._cvs[i]
        q = self._queues[i]
        while True:
            with cv:
                while not q and not self._stop:
                    cv.wait(0.2)
                if self._stop and not q:
                    return
                region_id, task = q.popleft()
            try:
                task()
            except Exception as exc:  # noqa: BLE001 — worker must survive
                if len(self.errors) < 128:
                    self.errors.append(exc)

    def stop(self) -> None:
        self._stop = True
        for cv in self._cvs:
            with cv:
                cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
