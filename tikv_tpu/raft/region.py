"""Region metadata: key ranges, epochs, peer placement.

Re-expression of the kvproto ``metapb.Region`` used throughout raftstore:
a region owns the half-open raw-key range [start_key, end_key) (empty end =
+inf), carries an epoch (conf_ver bumps on membership change, version bumps
on split/merge), and lists its peers (peer_id → store_id).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace


@dataclass
class Peer:
    peer_id: int
    store_id: int
    role: str = "voter"  # "voter" | "learner"


@dataclass
class RegionEpoch:
    conf_ver: int = 1
    version: int = 1


@dataclass
class Region:
    """Region metadata.

    ``start_key``/``end_key`` are **opaque engine-space keys** (for
    transactional data that is the memcomparable-encoded user key, ts-free;
    for raw mode the raw key) — exactly the reference's convention, where
    boundaries compare against ``origin_key(engine key)`` and are never
    decoded.  b"" end_key = +inf.
    """

    id: int
    start_key: bytes = b""
    end_key: bytes = b""  # b"" = +inf
    epoch: RegionEpoch = field(default_factory=RegionEpoch)
    peers: list[Peer] = field(default_factory=list)

    def contains(self, key: bytes) -> bool:
        if key < self.start_key:
            return False
        return not self.end_key or key < self.end_key

    def peer_on_store(self, store_id: int) -> Peer | None:
        for p in self.peers:
            if p.store_id == store_id:
                return p
        return None

    def peer_by_id(self, peer_id: int) -> Peer | None:
        for p in self.peers:
            if p.peer_id == peer_id:
                return p
        return None

    def voter_ids(self) -> list[int]:
        # witnesses ARE voters (log-only ones) — quorum membership includes them
        return [p.peer_id for p in self.peers if p.role in ("voter", "witness")]

    def learner_ids(self) -> list[int]:
        return [p.peer_id for p in self.peers if p.role == "learner"]

    def witness_ids(self) -> list[int]:
        return [p.peer_id for p in self.peers if p.role == "witness"]

    def clone(self) -> "Region":
        return Region(
            self.id,
            self.start_key,
            self.end_key,
            RegionEpoch(self.epoch.conf_ver, self.epoch.version),
            [Peer(p.peer_id, p.store_id, p.role) for p in self.peers],
        )


class EpochError(Exception):
    def __init__(self, current: Region):
        self.current = current
        super().__init__(f"stale region epoch; current {current.epoch}")


class NotLeaderError(Exception):
    def __init__(self, region_id: int, leader_store: int | None):
        self.region_id = region_id
        self.leader_store = leader_store
        super().__init__(f"not leader of region {region_id}; try store {leader_store}")


class KeyNotInRegionError(Exception):
    def __init__(self, key: bytes, region: Region):
        super().__init__(f"key {key!r} not in region {region.id} [{region.start_key!r}, {region.end_key!r})")
