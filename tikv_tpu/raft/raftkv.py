"""RaftKv: the Engine implementation that routes through raft consensus.

Re-expression of ``src/server/raftkv.rs`` (:214 exec_snapshot, :244
exec_write_requests, :378/:435): writes become proposed commands applied by
quorum; snapshots are linearizable views obtained after a ReadIndex barrier
(leader lease local reads are the fast path in the reference; ReadIndex keeps
the same correctness with less machinery).

``RegionSnapshot`` exposes the store engine under the region's range with the
``z`` data prefix applied transparently, so the whole txn/coprocessor stack
works unchanged over raft-replicated data (store/region_snapshot.rs).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ..analysis.sanitizer import note_blocking
from ..storage.engine import Cursor, Snapshot, WriteBatch
from ..storage.kv import Engine
from ..util import keys
from .region import NotLeaderError, Region
from .store import Store


class _PrefixCursor(Cursor):
    """Cursor over data keys with the z-prefix stripped (region-bounded)."""

    def __init__(self, inner: Cursor):
        self._c = inner

    def seek(self, key: bytes) -> bool:
        return self._c.seek(keys.data_key(key))

    def seek_for_prev(self, key: bytes) -> bool:
        return self._c.seek_for_prev(keys.data_key(key))

    def seek_to_first(self) -> bool:
        return self._c.seek_to_first()

    def seek_to_last(self) -> bool:
        return self._c.seek_to_last()

    def next(self) -> bool:
        return self._c.next()

    def prev(self) -> bool:
        return self._c.prev()

    def valid(self) -> bool:
        return self._c.valid()

    def key(self) -> bytes:
        return keys.origin_key(self._c.key())

    def value(self) -> bytes:
        return self._c.value()


class RegionSnapshot(Snapshot):
    def __init__(self, engine_snapshot: Snapshot, region: Region,
                 apply_index: int | None = None, data_token=None):
        self._snap = engine_snapshot
        self.region = region
        # data version this snapshot reflects (the peer's apply_index at
        # snapshot time): the coprocessor's region column cache keys on
        # (region epoch, apply_index) and reads both straight off the
        # snapshot, so serving paths need no extra context plumbing
        self.apply_index = apply_index
        # identity of the underlying store engine: the region cache binds to
        # the first token it serves and drops write-through notifies from
        # any OTHER engine — region ids alone are not process-unique
        # (embedded endpoints, multi-store test processes)
        self.data_token = data_token
        # stale-read provenance (docs/stale_reads.md): the stale path stamps
        # ``stale=True`` plus the RegionReadProgress pair it was admitted
        # under, so serving layers can count follower-served reads and
        # assert the pairing invariant (apply_index >= required index)
        self.stale = False
        self.read_progress: tuple[int, int] | None = None
        self._lower = keys.data_key(region.start_key)
        self._upper = keys.data_end_key(region.end_key)

    def get_cf(self, cf: str, key: bytes) -> bytes | None:
        dkey = keys.data_key(key)
        if not (self._lower <= dkey < self._upper):
            return None
        return self._snap.get_cf(cf, dkey)

    def cursor_cf(self, cf: str, lower: bytes | None = None, upper: bytes | None = None) -> Cursor:
        lo = keys.data_key(lower) if lower is not None else self._lower
        hi = keys.data_key(upper) if upper is not None else self._upper
        lo = max(lo, self._lower)
        hi = min(hi, self._upper)
        return _PrefixCursor(self._snap.cursor_cf(cf, lo, hi))


class RaftKv(Engine):
    """Engine over one store's raft peers.  ``pump`` drives the cluster's
    message loop until a callback fires (test clusters pump synchronously;
    the server wires a background poller)."""

    def __init__(
        self,
        store: Store,
        pump: Callable[[], None] | None = None,
        resolved_ts=None,
        propose_timeout: float = 10.0,
    ):
        self.store = store
        # default: yield to the node's background raft loop
        self.pump = pump or (lambda: time.sleep(0.0005))
        # ResolvedTsEndpoint enabling follower stale reads (kv.rs stale-read
        # path gated by RegionReadProgress/resolved-ts)
        self.resolved_ts = resolved_ts
        self.propose_timeout = propose_timeout

    @property
    def data_token(self):
        """Identity of the data this engine serves — delegates to the ONE
        definition on the store (docs/write_path.md): RegionSnapshots stamp
        it, apply-side write-through notifies carry it, and the region
        column cache binds to it at construction."""
        return self.store.data_token

    def _peer_for_ctx(self, ctx: dict | None):
        ctx = ctx or {}
        region_id = ctx.get("region_id")
        if region_id is not None:
            peer = self.store.peers.get(region_id)
            if peer is None:
                raise NotLeaderError(region_id, None)
            return peer
        key = ctx.get("key", b"")
        peer = self.store.region_for_key(key)
        if peer is None:
            raise NotLeaderError(-1, None)
        return peer

    class DataNotReadyError(Exception):
        def __init__(self, region_id: int, read_ts: int, resolved: int):
            self.region_id = region_id
            self.read_ts = read_ts
            self.resolved = resolved
            super().__init__(
                f"region {region_id}: stale read at {read_ts} above resolved ts {resolved}"
            )

    def _stale_ready(self, peer, ctx: dict) -> tuple[int, int]:
        """ONE definition of stale-read admission (snapshot() and the copr
        scheduler's ``check_read_ready`` probe): returns the region's
        RegionReadProgress pair when this replica may serve ``read_ts``,
        else raises NotLeader (witness) / DataNotReady (watermark or apply
        lag).  Never touches the engine."""
        # follower stale read: safe at/below the region's resolved-ts
        # watermark on any DATA replica — witnesses store no data
        if peer.peer_id in peer.node.witnesses:
            raise NotLeaderError(peer.region.id, self.store.leader_store_of(peer.region.id))
        if self.resolved_ts is None:
            raise ValueError("stale reads need a resolved-ts endpoint")
        read_ts = ctx.get("read_ts")
        if read_ts is None:
            raise ValueError("stale reads need read_ts in the context")
        resolved, required_idx = self.resolved_ts.progress_of(peer.region.id)
        # RegionReadProgress pairing: the watermark is only meaningful on
        # a replica whose ENGINE contains at least the index it was
        # computed at (apply_index — node.applied may run ahead of the
        # apply pipeline) — a lagging follower must refuse rather than
        # serve a snapshot missing committed data
        if read_ts > resolved or peer.apply_index < required_idx:
            raise RaftKv.DataNotReadyError(peer.region.id, read_ts, resolved)
        return resolved, required_idx

    def check_read_ready(self, ctx: dict | None) -> tuple[int, int] | None:
        """Admission-time readiness probe: raises exactly what ``snapshot``
        would raise for a stale read — NotLeader on a witness, DataNotReady
        on a lagging watermark/apply — WITHOUT freezing the engine.  The
        copr read scheduler calls this before a stale request costs a queue
        slot, let alone a device dispatch (docs/stale_reads.md).  Returns
        the (resolved_ts, required_apply_index) pair, or None for reads
        that don't take the stale path."""
        ctx = ctx or {}
        if not ctx.get("stale_read"):
            return None
        return self._stale_ready(self._peer_for_ctx(ctx), ctx)

    def local_snapshot(self, region_id: int) -> RegionSnapshot:
        """A PROTOCOL-FREE snapshot of this store's local apply state for
        ``region_id`` — no lease, no ReadIndex, works on followers.  Not
        linearizable; exists for the integrity scrubber (docs/integrity.md),
        which verifies derived images against the LOCAL engine at a pinned
        apply index — exactly what this returns.  Never serve client reads
        off it."""
        peer = self.store.peers.get(region_id)
        if peer is None:
            raise NotLeaderError(region_id, None)
        applied = peer.apply_index  # before the freeze — see stale path
        return RegionSnapshot(self.store.engine.snapshot(), peer.region.clone(),
                              apply_index=applied,
                              data_token=self.data_token)

    def snapshot(self, ctx: dict | None = None) -> RegionSnapshot:
        peer = self._peer_for_ctx(ctx)
        ctx = ctx or {}
        if ctx.get("stale_read"):
            resolved, required_idx = self._stale_ready(peer, ctx)
            # apply_index SAMPLED BEFORE the engine freeze: the snapshot may
            # contain later applies, but must never claim an index whose data
            # it lacks — the region cache stamps images with this index and a
            # too-high claim would mark missing writes as present
            # (docs/write_path.md apply_index contract)
            applied = peer.apply_index
            snap = RegionSnapshot(self.store.engine.snapshot(), peer.region.clone(),
                                  apply_index=applied,
                                  data_token=self.data_token)
            snap.stale = True
            snap.read_progress = (resolved, required_idx)
            return snap
        if not peer.node.is_leader():
            if ctx.get("replica_read") and peer.peer_id not in peer.node.witnesses:
                # replica read (read.rs replica-read + ReplicaReadLockChecker
                # role): the FOLLOWER serves a linearizable snapshot by
                # asking the leader for a ReadIndex over the wire and waiting
                # until its own apply catches up to it — the raft core's
                # READ_INDEX forward/RESP machinery does the round trip
                return self._read_index_barrier(peer)
            raise NotLeaderError(peer.region.id, self.store.leader_store_of(peer.region.id))
        # lease fast path (LocalReader, read.rs:342): while the leader holds a
        # quorum-granted lease and the ENGINE contains everything committed
        # (apply_index, not node.applied — the pipeline may still be writing),
        # reads skip the ReadIndex round entirely
        if peer.node.lease_valid() and peer.apply_index >= peer.node.commit:
            applied = peer.apply_index  # before the freeze — see stale path
            return RegionSnapshot(self.store.engine.snapshot(), peer.region.clone(),
                                  apply_index=applied,
                                  data_token=self.data_token)
        return self._read_index_barrier(peer)

    def _read_index_barrier(self, peer) -> RegionSnapshot:
        """ONE definition of the ReadIndex wait (leader slow path AND
        follower replica reads): block until the read point is applied
        locally, then snapshot."""
        note_blocking("raftkv.read_index_barrier")
        done = threading.Event()
        err: list = []

        def cb(e):
            if e is not None:
                err.append(e)
            done.set()

        peer.read_index(cb)
        self._pump_until(done, peer.region.id)
        if err:
            raise err[0]
        applied = peer.apply_index  # before the freeze — see stale path
        return RegionSnapshot(self.store.engine.snapshot(), peer.region.clone(),
                              apply_index=applied,
                              data_token=self.data_token)

    def write(self, ctx: dict | None, batch: WriteBatch) -> None:
        # one full propose -> replicate -> apply -> ack round trip: a caller
        # holding any subsystem lock across this stalls every peer of that
        # lock for a raft round (sanitizer flags exactly that)
        note_blocking("raftkv.write")
        peer = self._peer_for_ctx(ctx)
        ops = []
        for op, cf, key, val in batch.ops:
            ops.append((op, cf, key, val))
        cmd = {
            "epoch": (peer.region.epoch.conf_ver, peer.region.epoch.version),
            "ops": ops,
        }
        done = threading.Event()
        result: list = []
        # propose→apply span handle (docs/tracing.md): begun at propose on
        # the caller's thread, FINISHED inside the write callback — which
        # fires on the apply pipeline's thread, so the span's duration is
        # the true replicate+apply time, not the caller's ack-wait.  The
        # tracer lock is a leaf: finishing under apply locks is safe.
        from ..util import trace

        sp = trace.begin("raft.propose_apply", region=peer.region.id,
                         ops=len(ops))

        def cb(r):
            sp.finish()
            result.append(r)
            done.set()

        try:
            peer.propose_cmd(cmd, cb)
            self._pump_until(done, peer.region.id)
        finally:
            if not done.is_set():
                # timeout/propose failure: the callback will never fire —
                # close the handle so the trace record cannot leak open
                sp.tag(error="propose_incomplete").finish()
        r = result[0]
        if isinstance(r, Exception):
            raise r

    def _pump_until(self, done, region_id: int) -> None:
        """Wall-clock deadline, not a round count: completion may come from
        the apply pipeline's worker threads, which need real time regardless
        of how fast the caller's pump spins."""
        deadline = time.monotonic() + self.propose_timeout
        while time.monotonic() < deadline:
            if done.is_set():
                return
            self.pump()
        raise TimeoutError(f"raft command on region {region_id} did not complete (no quorum?)")
