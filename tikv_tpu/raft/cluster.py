"""Multi-node test cluster harness.

Re-expression of ``components/test_raftstore``'s ``Cluster<T: Simulator>``
(src/cluster.rs:128): N real stores in one process over an in-memory
ChannelTransport, with deterministic message pumping, fault-injection filters,
node stop/restart, leader transfer by campaign, and region split.
"""

from __future__ import annotations

import itertools

from ..storage.engine import CF_DEFAULT, WriteBatch
from ..util import retry
from .raftkv import RaftKv, RegionSnapshot
from .region import NotLeaderError, Peer as RegionPeer, Region, RegionEpoch
from .store import ChannelTransport, RaftMessage, Store, StorePeer

FIRST_REGION_ID = 1

# the deterministic harness shares the ONE retry policy with the networked
# cluster client, but "sleeping" means pumping ticks: wall-clock sleeps
# would add nothing (no background threads move this cluster) and would
# break determinism.  Attempts are bounded instead of deadline-bound.
PUMP_RETRY = retry.RetryPolicy(
    base_s=0.0, jitter=0.0, max_attempts=50,
    # a proposal timeout here means quorum is gone and pumping cannot bring
    # it back (nothing heals without the TEST acting) — fail fast-ish
    class_attempts={"suspect": 8, "timeout": 2},
)


class Cluster:
    def __init__(self, n_stores: int, pd=None):
        self.transport = ChannelTransport()
        self.stores: dict[int, Store] = {}
        self.stopped: set[int] = set()
        self.pd = pd
        self._ids = itertools.count(1000)
        for sid in range(1, n_stores + 1):
            store = Store(sid, self.transport)
            self.transport.register(store)
            self.stores[sid] = store

    def alloc_id(self) -> int:
        if self.pd is not None:
            return self.pd.alloc_id()
        return next(self._ids)

    # -- bootstrap ---------------------------------------------------------

    def bootstrap(self) -> Region:
        """First region spans the whole key space with one peer per store
        (node.rs:153 bootstrap semantics)."""
        peers = [RegionPeer(self.alloc_id(), sid) for sid in self.stores]
        region = Region(FIRST_REGION_ID, b"", b"", RegionEpoch(), peers)
        for store in self.stores.values():
            store.create_peer(region)
        if self.pd is not None:
            self.pd.bootstrap_region(region.clone())
            for s in self.stores.values():
                s.split_observers.append(self._report_split_to_pd)
        return region

    def _report_split_to_pd(self, store, old, new):
        if self.pd is not None:
            self.pd.report_split(old.clone(), new.clone())

    def bootstrap_subset(self, store_ids: list[int]) -> Region:
        """First region placed on a subset of stores (conf-change tests)."""
        peers = [RegionPeer(self.alloc_id(), sid) for sid in store_ids]
        region = Region(FIRST_REGION_ID, b"", b"", RegionEpoch(), peers)
        for sid in store_ids:
            self.stores[sid].create_peer(region)
        return region

    def run(self) -> None:
        self.bootstrap()
        self.elect_leader(FIRST_REGION_ID, 1)

    # -- driving -----------------------------------------------------------

    def process(self, max_rounds: int = 200) -> None:
        for _ in range(max_rounds):
            moved = False
            for sid, store in self.stores.items():
                if sid in self.stopped:
                    store._inbox.clear()
                    continue
                if store.process_messages():
                    moved = True
                if store.handle_readies():
                    moved = True
            if not moved:
                return

    def tick(self, n: int = 1) -> None:
        for _ in range(n):
            for sid, store in self.stores.items():
                if sid not in self.stopped:
                    store.tick()
            self.process()

    def elect_leader(self, region_id: int, store_id: int) -> StorePeer:
        peer = self.stores[store_id].peers[region_id]
        peer.node.campaign()
        self.process()
        assert peer.node.is_leader(), f"store {store_id} failed to take region {region_id}"
        return peer

    def leader_peer(self, region_id: int) -> StorePeer | None:
        leaders = []
        for sid, store in self.stores.items():
            if sid in self.stopped:
                continue
            p = store.peers.get(region_id)
            if p is not None and p.node.is_leader():
                leaders.append(p)
        if not leaders:
            return None
        # during partitions a deposed leader may linger at a lower term —
        # the real leader is the one with the highest term
        return max(leaders, key=lambda p: p.node.term)

    def wait_leader(self, region_id: int, max_ticks: int = 100) -> StorePeer:
        for _ in range(max_ticks):
            p = self.leader_peer(region_id)
            if p is not None:
                return p
            self.tick()
        raise AssertionError(f"no leader for region {region_id}")

    # -- node lifecycle (Simulator trait) ----------------------------------

    def stop_node(self, store_id: int) -> None:
        self.stopped.add(store_id)

    def restart_node(self, store_id: int) -> None:
        self.stopped.discard(store_id)

    # -- KV helpers --------------------------------------------------------

    def raftkv(self, store_id: int) -> RaftKv:
        # synchronous pump converges in a few rounds when quorum exists, so
        # a short deadline keeps expected-stall tests fast
        return RaftKv(self.stores[store_id], pump=self.process, propose_timeout=2.0)

    def region_for_key(self, key: bytes) -> int:
        for store in self.stores.values():
            p = store.region_for_key(key)
            if p is not None:
                return p.region.id
        raise KeyError(key)

    def _pump_retry(self, fn, site: str):
        """Run a leader-routed op under the shared retry policy, with tick
        pumping as the backoff action (NotLeader during churn re-routes to
        the new leader after the pump elects one)."""
        return retry.call(
            fn, policy=PUMP_RETRY, site=site,
            sleep=lambda _s: self.tick(),
        )

    def must_put(self, key: bytes, value: bytes, cf: str = CF_DEFAULT) -> None:
        def attempt():
            region_id = self.region_for_key(key)
            leader = self.wait_leader(region_id)
            kv = self.raftkv(leader.store.store_id)
            wb = WriteBatch()
            wb.put_cf(cf, key, value)
            kv.write({"region_id": region_id}, wb)

        self._pump_retry(attempt, "cluster.must_put")

    def must_delete(self, key: bytes, cf: str = CF_DEFAULT) -> None:
        def attempt():
            region_id = self.region_for_key(key)
            leader = self.wait_leader(region_id)
            kv = self.raftkv(leader.store.store_id)
            wb = WriteBatch()
            wb.delete_cf(cf, key)
            kv.write({"region_id": region_id}, wb)

        self._pump_retry(attempt, "cluster.must_delete")

    def must_get(self, key: bytes, cf: str = CF_DEFAULT) -> bytes | None:
        def attempt():
            region_id = self.region_for_key(key)
            leader = self.wait_leader(region_id)
            kv = self.raftkv(leader.store.store_id)
            snap = kv.snapshot({"region_id": region_id})
            return snap.get_cf(cf, key)

        return self._pump_retry(attempt, "cluster.must_get")

    def get_on_store(self, store_id: int, key: bytes, cf: str = CF_DEFAULT) -> bytes | None:
        """Read the store's local applied state directly (follower check)."""
        from ..util import keys as keymod

        return self.stores[store_id].engine.get_cf(cf, keymod.data_key(key))

    # -- admin -------------------------------------------------------------

    def split_region(self, region_id: int, split_key: bytes) -> int:
        leader = self.wait_leader(region_id)
        new_region_id = self.alloc_id()
        new_pids = [self.alloc_id() for _ in leader.region.peers]
        import threading

        done = threading.Event()
        res: list = []

        def cb(r):
            res.append(r)
            done.set()

        leader.propose_split(split_key, new_region_id, new_pids, cb)
        while not done.is_set():
            self.process()
        if isinstance(res[0], Exception):
            raise res[0]
        # give the new region a leader
        self.wait_leader(new_region_id)
        return new_region_id

    def add_peer(self, region_id: int, store_id: int) -> int:
        leader = self.wait_leader(region_id)
        new_pid = self.alloc_id()
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "add", new_pid, store_id),
        }
        self._run_admin(leader, cmd)
        return new_pid

    def add_witness(self, region_id: int, store_id: int) -> int:
        """Add a log-only voting replica (the raftstore witness feature)."""
        leader = self.wait_leader(region_id)
        pid = self.alloc_id()
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "add_witness", pid, store_id),
        }
        self._run_admin(leader, cmd)
        return pid

    def add_learner(self, region_id: int, store_id: int) -> int:
        leader = self.wait_leader(region_id)
        pid = self.alloc_id()
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "add_learner", pid, store_id),
        }
        self._run_admin(leader, cmd)
        return pid

    def promote_learner(self, region_id: int, peer_id: int) -> None:
        leader = self.wait_leader(region_id)
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "promote", peer_id, 0),
        }
        self._run_admin(leader, cmd)

    def joint_conf_change(self, region_id: int, changes: list[tuple[str, int]]) -> list[int]:
        """Atomic multi-peer membership change via joint consensus
        (ConfChangeV2 — pd_client uses this for e.g. replace-peer).

        ``changes``: ("add"|"add_learner", store_id) or
        ("promote"|"demote"|"remove", peer_id).  Returns the new peer ids for
        the add ops, after the automatic leave_joint completes."""
        leader = self.wait_leader(region_id)
        wire: list[tuple[str, int, int]] = []
        new_pids: list[int] = []
        for op, _arg in changes:
            if op not in ("add", "add_learner", "promote", "demote", "remove"):
                raise ValueError(f"unknown conf change op {op!r}")
        for op, arg in changes:
            if op in ("add", "add_learner"):
                pid = self.alloc_id()
                new_pids.append(pid)
                wire.append((op, pid, arg))
            elif op == "demote":
                wire.append(("add_learner", arg, 0))
            else:
                wire.append((op, arg, 0))
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change_v2", tuple(wire)),
        }
        self._run_admin(leader, cmd)
        for _ in range(100):
            self.tick()
            lp = self.leader_peer(region_id)
            if lp is not None and lp.node.outgoing is None:
                return new_pids
        raise AssertionError(f"joint change on region {region_id} never left the joint config")

    def remove_peer(self, region_id: int, peer_id: int) -> None:
        leader = self.wait_leader(region_id)
        cmd = {
            "epoch": (leader.region.epoch.conf_ver, leader.region.epoch.version),
            "ops": [],
            "admin": ("conf_change", "remove", peer_id, 0),
        }
        self._run_admin(leader, cmd)

    def _run_admin(self, leader: StorePeer, cmd: dict) -> None:
        import threading

        done = threading.Event()
        res: list = []

        def cb(r):
            res.append(r)
            done.set()

        leader.propose_cmd(cmd, cb)
        while not done.is_set():
            self.process()
        if isinstance(res[0], Exception):
            raise res[0]

    def transfer_leader(self, region_id: int, to_store: int) -> None:
        self.elect_leader(region_id, to_store)

    def merge_regions(self, target_id: int, source_id: int) -> None:
        """Merge source (right neighbor) into target (left neighbor):
        PrepareMerge freezes the source, then CommitMerge on the target
        absorbs the range.  The CommitMerge command carries the source
        leader's committed log tail, so lagging source replicas catch up
        from the payload (CatchUpLogs) — no quiesce requirement."""
        from .store import _encode_entry

        target = self.wait_leader(target_id)
        source = self.wait_leader(source_id)
        assert target.region.end_key == source.region.start_key, "regions must be adjacent"
        src_region_id = source.region.id
        # feasibility BEFORE freezing the source: carrying entries requires
        # the source log to reach back to the laggiest live replica's applied
        # index — refuse up front (the straggler needs a snapshot first; the
        # reference's PD gates merges on replica health, with RollbackMerge
        # as the escape hatch we deliberately make unnecessary here)
        live = [
            s.peers[src_region_id]
            for sid, s in self.stores.items()
            if sid not in self.stopped and src_region_id in s.peers
        ]
        floor = min((p.node.applied for p in live), default=source.node.commit)
        if floor < source.node.commit and source.node.log.term_at(floor + 1) is None:
            raise AssertionError(
                f"source region {src_region_id} log compacted below a lagging "
                f"replica (applied {floor}); seed it with a snapshot before merging"
            )
        cmd = {
            "epoch": (source.region.epoch.conf_ver, source.region.epoch.version),
            "ops": [],
            "admin": ("prepare_merge", target_id),
        }
        self._run_admin(source, cmd)
        src_end = source.region.end_key
        src_version = source.region.epoch.version
        src_commit = source.node.commit
        # carry only what the laggiest live replica actually needs
        carried = [
            _encode_entry(e)
            for e in source.node.log.entries
            if floor < e.index <= src_commit
        ]
        cmd = {
            "epoch": (target.region.epoch.conf_ver, target.region.epoch.version),
            "ops": [],
            "admin": ("commit_merge", src_region_id, src_end, src_version, src_commit, carried),
        }
        self._run_admin(target, cmd)
        if self.pd is not None:
            self.pd.regions.pop(src_region_id, None)
            self.pd.region_heartbeat(target.region.clone(), target.store.store_id)
