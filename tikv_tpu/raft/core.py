"""Raft consensus core.

The reference consumes the external ``raft-rs`` crate (``RawNode``/``Ready``;
pinned in Cargo.toml:184).  This is this framework's own implementation of
the Raft state machine with the same interaction style:

    node.step(msg)        # feed a message from a peer
    node.tick()           # advance logical time (elections, heartbeats)
    node.propose(data)    # leader: append a proposal
    rd = node.ready()     # drain: entries to persist, messages to send,
                          #        committed entries to apply
    node.advance(rd)

Implemented: randomized election timeout, pre-vote, leader election, log
replication with consistency check, quorum commitment, heartbeats + leases
(broadcast-tick granted, sticky votes), learners (non-voting replicas with
promote), snapshot install for lagging/new peers, single-step membership
change AND joint consensus (ConfChangeV2: dual-quorum commit/election/lease
while in C_old,new, auto-leave re-proposed on leadership change), hibernation,
ReadIndex.  Log compaction is driven by the store layer.
"""

from __future__ import annotations

import enum
import random
from dataclasses import dataclass, field


_HIBERNATE_CTX = b"\x00hibernate"



# read once at import: stores are spawned with the knob fixed, and
# lease_valid() sits on the local-read hot path
import os as _os

_LEASES_OFF = _os.environ.get("TIKV_TPU_DISABLE_LEASES") == "1"

class Role(enum.Enum):
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"


class MsgType(enum.Enum):
    PRE_VOTE = "pre_vote"
    PRE_VOTE_RESP = "pre_vote_resp"
    VOTE = "vote"
    VOTE_RESP = "vote_resp"
    APPEND = "append"
    APPEND_RESP = "append_resp"
    HEARTBEAT = "heartbeat"
    HEARTBEAT_RESP = "heartbeat_resp"
    SNAPSHOT = "snapshot"
    READ_INDEX = "read_index"
    READ_INDEX_RESP = "read_index_resp"
    # leadership transfer (raft-rs MsgTimeoutNow): the leader tells the
    # transfer target to campaign immediately with stickiness bypassed
    TIMEOUT_NOW = "timeout_now"


@dataclass
class Entry:
    term: int
    index: int
    data: bytes = b""
    # conf change entries carry (op, peer_id[, store_id]) for single-step
    # changes or ("enter_joint", ((op, peer_id[, store_id]), ...)) /
    # ("leave_joint", ()) for joint consensus, instead of data
    conf_change: tuple | None = None


@dataclass
class Snapshot:
    index: int
    term: int
    data: bytes  # opaque state-machine snapshot
    voters: tuple[int, ...]
    learners: tuple[int, ...] = ()
    outgoing: tuple[int, ...] = ()  # non-empty while a joint change is in flight
    witnesses: tuple[int, ...] = ()


@dataclass
class Message:
    type: MsgType
    frm: int
    to: int
    term: int
    log_index: int = 0  # prev_log_index for APPEND, candidate last index for VOTE
    log_term: int = 0
    entries: list[Entry] = field(default_factory=list)
    commit: int = 0
    reject: bool = False
    reject_hint: int = 0
    snapshot: Snapshot | None = None
    context: bytes = b""  # read-index correlation
    hb_round: int = 0  # heartbeat round tag (lease accounting)
    force: bool = False  # leadership-transfer vote (bypasses stickiness)


@dataclass
class Ready:
    """What the container must do before advancing (raft-rs Ready)."""

    entries: list[Entry] = field(default_factory=list)  # to persist
    messages: list[Message] = field(default_factory=list)  # to send
    committed_entries: list[Entry] = field(default_factory=list)  # to apply
    snapshot: Snapshot | None = None  # to restore
    hard_state_changed: bool = False
    read_states: list[tuple[bytes, int]] = field(default_factory=list)  # (ctx, index)

    def is_empty(self) -> bool:
        return not (
            self.entries
            or self.messages
            or self.committed_entries
            or self.snapshot
            or self.hard_state_changed
            or self.read_states
        )


class RaftLog:
    """In-memory log with an offset (entries before offset live in snapshots)."""

    def __init__(self):
        self.entries: list[Entry] = []
        self.offset = 1  # index of entries[0]
        self.snapshot_index = 0
        self.snapshot_term = 0

    def last_index(self) -> int:
        return self.offset + len(self.entries) - 1 if self.entries else self.snapshot_index

    def term_at(self, index: int) -> int | None:
        if index == 0:
            return 0
        if index == self.snapshot_index:
            return self.snapshot_term
        i = index - self.offset
        if 0 <= i < len(self.entries):
            return self.entries[i].term
        return None

    def slice_from(self, index: int) -> list[Entry]:
        i = index - self.offset
        if i < 0:
            return []
        return self.entries[max(i, 0) :]

    def entry(self, index: int) -> Entry | None:
        i = index - self.offset
        if 0 <= i < len(self.entries):
            return self.entries[i]
        return None

    def truncate_from(self, index: int) -> None:
        self.entries = self.entries[: index - self.offset]

    def append(self, entries: list[Entry]) -> None:
        self.entries.extend(entries)

    def compact_to(self, index: int, term: int) -> None:
        """Drop entries up to ``index`` (now covered by a snapshot)."""
        keep = index + 1 - self.offset
        if keep > 0:
            self.entries = self.entries[keep:]
            self.offset = index + 1
        self.snapshot_index = index
        self.snapshot_term = term

    def reset_to_snapshot(self, snap: Snapshot) -> None:
        self.entries = []
        self.offset = snap.index + 1
        self.snapshot_index = snap.index
        self.snapshot_term = snap.term


class RaftNode:
    """One raft participant (raft-rs RawNode equivalent)."""

    def __init__(
        self,
        node_id: int,
        voters: list[int],
        election_tick: int = 10,
        heartbeat_tick: int = 2,
        rng: random.Random | None = None,
        hibernate_after: int = 0,
    ):
        self.id = node_id
        self.voters: set[int] = set(voters)
        self.learners: set[int] = set()
        # joint consensus (raft thesis 4.3 / raft-rs ConfChangeV2): while not
        # None this is the OUTGOING voter config C_old; self.voters is the
        # incoming C_new, and every quorum decision needs a majority of BOTH
        self.outgoing: set[int] | None = None
        # witnesses (raftstore-v2 witness feature): full voters for quorum
        # and elections, but they store the LOG only — no data — so they
        # must never become leader themselves
        self.witnesses: set[int] = set()
        # witness->data conversion: the peer is log-caught-up but has NO
        # data, so the next snapshot must be applied even at an index the
        # staleness guard would normally skip
        self.force_accept_snapshot = False
        self.pre_vote = True
        self.term = 0
        self.vote: int | None = None
        self.role = Role.FOLLOWER
        self.leader_id: int | None = None
        self.log = RaftLog()
        self.commit = 0
        self.applied = 0
        # index of the newest conf-change entry in the log; while it trails
        # applied, no further conf change may be proposed (raft-rs
        # has_pending_conf) — overlapping changes would corrupt the config
        self._pending_conf_index = 0

        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self.rng = rng or random.Random(node_id)
        self._elapsed = 0
        self._randomized_timeout = self._rand_timeout()
        self._tick_count = 0
        # hibernation (store/hibernate_state.rs): after this many idle leader
        # ticks with every follower caught up, the group stops exchanging
        # heartbeats until any message or proposal wakes it.  0 = disabled.
        self.hibernate_after = hibernate_after
        self.hibernated = False
        self._idle_ticks = 0
        # lease: leader may serve local reads until this tick.  Granted ONLY
        # from a complete heartbeat round, measured from the round's
        # *broadcast* tick (granting at response time would let the lease
        # outlive follower election timers under message delay)
        self._lease_until = 0
        self._hb_round = 0
        self._hb_round_tick = 0
        self._hb_acks: set[int] = set()

        # DR auto-sync (raftstore/src/store/replication_mode.rs): when
        # group_commit is on, an entry commits only once SOME member of
        # EVERY label group holds it — majority alone is not enough, so a
        # whole-datacenter loss can never lose committed data.  peer_groups
        # maps peer id -> label group; unlabeled peers don't constrain.
        self.group_commit = False
        self.peer_groups: dict[int, object] = {}

        # leader state
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}
        # peers that must be seeded by snapshot (fresh conf-change additions)
        self.force_snapshot: set[int] = set()
        self._votes: dict[int, bool] = {}
        self._pre_votes: dict[int, bool] | None = None
        # pending read-index requests: ctx -> (index, acks)
        self._pending_reads: dict[bytes, tuple[int, set[int]]] = {}
        # reads deferred until the leader commits in its own term: (ctx, origin)
        self._deferred_reads: list[tuple[bytes, int | None]] = []

        self._ready = Ready()

    # ------------------------------------------------------------------ util

    def _rand_timeout(self) -> int:
        return self.election_tick + self.rng.randrange(self.election_tick)

    def _all_voters(self) -> set[int]:
        return self.voters | (self.outgoing or set())

    def _has_quorum(self, acks: set[int]) -> bool:
        """Joint-aware quorum test: a majority of the incoming config, AND —
        while a joint membership change is in flight — of the outgoing one."""
        if len(acks & self.voters) < len(self.voters) // 2 + 1:
            return False
        if self.outgoing is not None:
            return len(acks & self.outgoing) >= len(self.outgoing) // 2 + 1
        return True

    def _quorum_lost(self, rejects: set[int]) -> bool:
        """An election is unwinnable once either config's majority rejected."""
        if len(rejects & self.voters) >= len(self.voters) // 2 + 1:
            return True
        return self.outgoing is not None and len(rejects & self.outgoing) >= len(self.outgoing) // 2 + 1

    def _replicas(self) -> set[int]:
        return (self.voters | self.learners | (self.outgoing or set())) - {self.id}

    def is_leader(self) -> bool:
        return self.role == Role.LEADER

    def _send(self, msg: Message) -> None:
        self._ready.messages.append(msg)

    def _become_follower(self, term: int, leader: int | None) -> None:
        if term > self.term:
            self.term = term
            self.vote = None
            self._ready.hard_state_changed = True
        self.role = Role.FOLLOWER
        self.leader_id = leader
        self._elapsed = 0
        self._randomized_timeout = self._rand_timeout()
        # a deposed leader must not serve (or later flush) reads it queued —
        # callers time out and retry against the new leader
        self._deferred_reads.clear()
        self._pending_reads.clear()
        # abandon any in-flight pre-vote round: delayed grants must not
        # trigger a campaign after we've acknowledged a leader
        self._pre_votes = None

    def _become_candidate(self, force: bool = False) -> None:
        self.term += 1
        self.role = Role.CANDIDATE
        self.vote = self.id
        self.leader_id = None
        self._votes = {self.id: True}
        self._elapsed = 0
        self._randomized_timeout = self._rand_timeout()
        self._ready.hard_state_changed = True
        if self._has_quorum({self.id}):
            self._become_leader()
            return
        for peer in self._all_voters() - {self.id}:
            self._send(
                Message(
                    MsgType.VOTE, self.id, peer, self.term,
                    log_index=self.log.last_index(),
                    log_term=self.log.term_at(self.log.last_index()) or 0,
                    force=force,
                )
            )

    def _become_leader(self) -> None:
        self.role = Role.LEADER
        self.leader_id = self.id
        last = self.log.last_index()
        members = self.voters | self.learners | (self.outgoing or set())
        self.next_index = {p: last + 1 for p in members}
        self.match_index = {p: 0 for p in members}
        self.match_index[self.id] = last
        # inherit in-flight conf entries appended by a previous leader — they
        # re-arm the no-overlap guard until applied
        for e in self.log.slice_from(self.applied + 1):
            if e.conf_change is not None:
                self._pending_conf_index = max(self._pending_conf_index, e.index)
        entries = [Entry(self.term, last + 1)]  # noop commits prior terms (§5.4.2)
        if self.outgoing is not None and self._pending_conf_index <= self.applied:
            # the previous leader died between enter_joint applying and
            # leave_joint committing: re-propose auto-leave (raft-rs keeps
            # joint exit leader-driven the same way)
            entries.append(Entry(self.term, last + 2, b"", conf_change=("leave_joint", ())))
            self._pending_conf_index = last + 2
        self._append_entries(entries)
        self._broadcast_append()

    # ---------------------------------------------------------------- public

    def tick(self) -> None:
        if self.hibernated:
            return  # frozen clock: no heartbeats, no election timeout
        self._tick_count += 1
        self._elapsed += 1
        if self.role == Role.LEADER:
            # replication-mode flips (sync -> async) can unblock commit
            # without any new append traffic; re-evaluating here keeps the
            # group stable-state-driven (runs on the raft-driving thread)
            self._maybe_commit()
            if (
                self.hibernate_after
                and self._idle_ticks >= self.hibernate_after
                and self.commit == self.log.last_index()
                and all(
                    self.match_index.get(p, 0) == self.log.last_index()
                    for p in self._all_voters()
                )
            ):
                # final round tells followers to freeze their election timers;
                # the lease dies with the clock — a frozen tick counter must
                # not keep lease_valid() true indefinitely
                self._broadcast_heartbeat(ctx=_HIBERNATE_CTX)
                self.hibernated = True
                self._lease_until = 0
                return
            self._idle_ticks += 1
            if self._elapsed >= self.heartbeat_tick:
                self._elapsed = 0
                self._broadcast_heartbeat()
        elif self._elapsed >= self._randomized_timeout:
            if (
                self.id in self.learners
                or self.id in self.witnesses
                or self.id not in self._all_voters()
            ):
                self._elapsed = 0  # learners/witnesses/removed never campaign
            elif self.pre_vote:
                self._start_pre_vote()
            else:
                self._become_candidate()

    def _wake(self) -> None:
        if self.hibernated:
            self.hibernated = False
            self._elapsed = 0  # fresh timer: no instant campaigns on wake
        self._idle_ticks = 0

    def _start_pre_vote(self) -> None:
        """Pre-vote (raft thesis 9.6 / raft-rs pre_vote): ask for votes at
        term+1 WITHOUT bumping our term — a partitioned node cannot inflate
        cluster terms, and disruptions only happen when a quorum agrees the
        leader is gone."""
        self._pre_votes = {self.id: True}
        self.leader_id = None
        self._elapsed = 0
        self._randomized_timeout = self._rand_timeout()
        if self._has_quorum({self.id}):
            self._become_candidate()
            return
        for peer in self._all_voters() - {self.id}:
            self._send(
                Message(
                    MsgType.PRE_VOTE, self.id, peer, self.term + 1,
                    log_index=self.log.last_index(),
                    log_term=self.log.term_at(self.log.last_index()) or 0,
                )
            )

    def _on_pre_vote(self, m: Message) -> None:
        last_index = self.log.last_index()
        last_term = self.log.term_at(last_index) or 0
        up_to_date = (m.log_term, m.log_index) >= (last_term, last_index)
        # sticky rule applies to pre-votes too; granting changes NO state
        fresh_leader = self.leader_id is not None and self._elapsed < self.election_tick
        grant = up_to_date and not fresh_leader and m.term > self.term
        self._send(
            Message(MsgType.PRE_VOTE_RESP, self.id, m.frm, m.term, reject=not grant)
        )

    def _on_pre_vote_resp(self, m: Message) -> None:
        if self.role == Role.LEADER or m.term <= self.term:
            return
        votes = getattr(self, "_pre_votes", None)
        if votes is None:
            return
        votes[m.frm] = not m.reject
        if self._has_quorum({p for p, ok in votes.items() if ok}):
            self._pre_votes = None
            self._become_candidate()

    def campaign(self, force: bool = True) -> None:
        """Explicit campaign = leadership transfer (MsgTimeoutNow semantics):
        its votes bypass leader stickiness.  Timeout campaigns (tick) stay
        sticky so natural disruptions cannot break an active lease.
        Witnesses hold no data and must never lead — transfer attempts are
        refused here, not just the timeout path."""
        if self.id in self.witnesses:
            return
        self._wake()
        self._become_candidate(force=force)

    def propose(self, data: bytes) -> int | None:
        """Leader appends a proposal; returns its index (None if not leader)."""
        self._wake()
        if self.role != Role.LEADER:
            return None
        index = self.log.last_index() + 1
        self._append_entries([Entry(self.term, index, data)])
        self._broadcast_append()
        return index

    def propose_conf_change(self, change: tuple) -> int | None:
        self._wake()
        if self.role != Role.LEADER:
            return None
        # one membership change in flight at a time (raft-rs has_pending_conf):
        # overlapping conf entries would both commit and the second apply
        # would clobber the joint config
        if self._pending_conf_index > self.applied:
            return None
        # joint transitions are strictly ordered: only leave_joint may be
        # proposed while the joint config is active
        if self.outgoing is not None and change[0] != "leave_joint":
            return None
        if change[0] == "leave_joint" and self.outgoing is None:
            return None
        index = self.log.last_index() + 1
        self._pending_conf_index = index
        self._append_entries([Entry(self.term, index, b"", conf_change=change)])
        self._broadcast_append()
        return index

    def _committed_in_term(self) -> bool:
        """A new leader may hold a commit index from a previous term that
        trails entries it acked as follower — reads are only safe once an
        entry of ITS term commits (§6.4; raft-rs requires the same)."""
        return self.log.term_at(self.commit) == self.term

    def read_index(self, ctx: bytes) -> None:
        """Linearizable read point (read_queue.rs): leader confirms leadership
        via a heartbeat round, then releases the read at commit index —
        deferred until the leader has committed in its own term."""
        self._wake()
        if self.role != Role.LEADER:
            if self.leader_id is not None:
                self._send(Message(MsgType.READ_INDEX, self.id, self.leader_id, self.term, context=ctx))
            return
        if not self._committed_in_term():
            self._deferred_reads.append((ctx, None))
            return
        if self._has_quorum({self.id}):
            self._ready.read_states.append((ctx, self.commit))
            return
        self._pending_reads[ctx] = (self.commit, {self.id})
        self._broadcast_heartbeat(ctx=ctx)

    def apply_conf_change(self, change: tuple) -> None:
        """Called by the container when a conf-change entry is applied.

        Simple ops mirror ConfChange (single-step, one peer); "enter_joint"
        carries a tuple of simple (op, peer[, store]) changes applied
        atomically with the prior voter set retained as the outgoing config,
        and "leave_joint" drops it (raft thesis 4.3; raft-rs ConfChangeV2 +
        apply_conf_change in components/raftstore/src/store/peer.rs).  Extra
        elements (the container's placement info) are opaque here — like the
        Peer message riding in the reference's ConfChange — so they replicate
        with the entry instead of living only on the proposing node."""
        op, peer = change[0], change[1]
        if op == "enter_joint":
            self.outgoing = set(self.voters)
            for ch in peer:
                sop, pid = ch[0], ch[1]
                if sop in ("add", "promote"):
                    self.voters.add(pid)
                    self.learners.discard(pid)
                elif sop == "add_learner":
                    # inside a joint change this doubles as voter demotion —
                    # safe because the peer keeps voting via the outgoing
                    # config until leave_joint
                    self.voters.discard(pid)
                    self.learners.add(pid)
                elif sop == "remove":
                    self.voters.discard(pid)
                    self.learners.discard(pid)
                if self.role == Role.LEADER and sop != "remove" and pid not in self.next_index:
                    self.next_index[pid] = self.log.last_index() + 1
                    self.match_index[pid] = 0
            if self.role == Role.LEADER:
                self._maybe_commit()
            return
        if op == "leave_joint":
            for pid in (self.outgoing or set()) - self.voters - self.learners:
                self.next_index.pop(pid, None)
                self.match_index.pop(pid, None)
            self.witnesses &= self.voters  # dropped witnesses lose the marker
            self.outgoing = None
            if self.role == Role.LEADER:
                self._maybe_commit()
            return
        if op == "add_witness":
            self.voters.add(peer)
            self.witnesses.add(peer)
            self.learners.discard(peer)
            if self.role == Role.LEADER and peer not in self.next_index:
                self.next_index[peer] = self.log.last_index() + 1
                self.match_index[peer] = 0
            return
        if op == "add":
            self.voters.add(peer)
            self.learners.discard(peer)
            self.witnesses.discard(peer)  # witness->data conversion
            if self.role == Role.LEADER and peer not in self.next_index:
                self.next_index[peer] = self.log.last_index() + 1
                self.match_index[peer] = 0
        elif op == "add_learner":
            if peer not in self.voters:
                self.learners.add(peer)
            if self.role == Role.LEADER and peer not in self.next_index:
                self.next_index[peer] = self.log.last_index() + 1
                self.match_index[peer] = 0
        elif op == "promote":
            self.learners.discard(peer)
            self.voters.add(peer)
            if self.role == Role.LEADER:
                self._maybe_commit()
        elif op == "remove":
            self.voters.discard(peer)
            self.learners.discard(peer)
            self.witnesses.discard(peer)
            self.next_index.pop(peer, None)
            self.match_index.pop(peer, None)
            if self.role == Role.LEADER:
                self._maybe_commit()

    def ready(self) -> Ready:
        rd = self._ready
        if self.commit > self.applied:
            lo = self.applied + 1
            for idx in range(lo, self.commit + 1):
                e = self.log.entry(idx)
                if e is not None:
                    rd.committed_entries.append(e)
            self.applied = self.commit
        self._ready = Ready()
        return rd

    # -------------------------------------------------------------- messages

    def step(self, m: Message) -> None:
        if m.type == MsgType.HEARTBEAT and m.context == _HIBERNATE_CTX:
            pass  # freeze decision happens in _on_heartbeat, AFTER term checks
        elif m.type in (
            MsgType.APPEND,
            MsgType.SNAPSHOT,
            MsgType.VOTE,
            MsgType.PRE_VOTE,
            MsgType.READ_INDEX,
            MsgType.READ_INDEX_RESP,
        ):
            self._wake()  # real activity
        elif m.type == MsgType.HEARTBEAT and self.hibernated:
            self._wake()  # an awake leader pulls the group out of hibernation
        # heartbeat/vote responses are not activity — they must not keep
        # resetting the idle counter that leads into hibernation
        if (
            m.type == MsgType.VOTE
            and not m.force
            and m.term > self.term
            and self.leader_id is not None
            and self._elapsed < self.election_tick
        ):
            # leader stickiness (raft §6 / raft-rs check_quorum): a node that
            # recently heard from a live leader ignores disruptive campaigns —
            # this is what makes leader leases sound
            self._send(Message(MsgType.VOTE_RESP, self.id, m.frm, self.term, reject=True))
            return
        if m.type in (MsgType.PRE_VOTE, MsgType.PRE_VOTE_RESP):
            # pre-vote rounds run ABOVE our term without mutating it
            handler = {
                MsgType.PRE_VOTE: self._on_pre_vote,
                MsgType.PRE_VOTE_RESP: self._on_pre_vote_resp,
            }[m.type]
            handler(m)
            return
        if m.term > self.term:
            leader = m.frm if m.type in (MsgType.APPEND, MsgType.HEARTBEAT, MsgType.SNAPSHOT) else None
            self._become_follower(m.term, leader)
        if m.term < self.term:
            # stale sender: tell it the current term
            if m.type in (MsgType.APPEND, MsgType.HEARTBEAT, MsgType.VOTE):
                resp_type = {
                    MsgType.APPEND: MsgType.APPEND_RESP,
                    MsgType.HEARTBEAT: MsgType.HEARTBEAT_RESP,
                    MsgType.VOTE: MsgType.VOTE_RESP,
                }[m.type]
                self._send(Message(resp_type, self.id, m.frm, self.term, reject=True))
            return

        handler = {
            MsgType.VOTE: self._on_vote,
            MsgType.VOTE_RESP: self._on_vote_resp,
            MsgType.APPEND: self._on_append,
            MsgType.APPEND_RESP: self._on_append_resp,
            MsgType.HEARTBEAT: self._on_heartbeat,
            MsgType.HEARTBEAT_RESP: self._on_heartbeat_resp,
            MsgType.SNAPSHOT: self._on_snapshot,
            MsgType.READ_INDEX: self._on_read_index,
            MsgType.READ_INDEX_RESP: self._on_read_index_resp,
            MsgType.TIMEOUT_NOW: self._on_timeout_now,
        }[m.type]
        handler(m)

    def _on_timeout_now(self, m: Message) -> None:
        """PD-ordered leadership transfer target (MsgTimeoutNow): campaign
        immediately, bypassing leader stickiness.  Witnesses and learners
        never lead, so they ignore the order."""
        if self.id in self.witnesses or self.id in self.learners:
            return
        self.campaign(force=True)

    # voting ----------------------------------------------------------------

    def _on_vote(self, m: Message) -> None:
        last_index = self.log.last_index()
        last_term = self.log.term_at(last_index) or 0
        up_to_date = (m.log_term, m.log_index) >= (last_term, last_index)
        can_vote = self.vote in (None, m.frm) and self.leader_id is None
        if up_to_date and can_vote:
            self.vote = m.frm
            self._elapsed = 0
            self._ready.hard_state_changed = True
            self._send(Message(MsgType.VOTE_RESP, self.id, m.frm, self.term))
        else:
            self._send(Message(MsgType.VOTE_RESP, self.id, m.frm, self.term, reject=True))

    def _on_vote_resp(self, m: Message) -> None:
        if self.role != Role.CANDIDATE:
            return
        self._votes[m.frm] = not m.reject
        if self._has_quorum({p for p, ok in self._votes.items() if ok}):
            self._become_leader()
        elif self._quorum_lost({p for p, ok in self._votes.items() if not ok}):
            self._become_follower(self.term, None)

    # replication -----------------------------------------------------------

    def _append_entries(self, entries: list[Entry]) -> None:
        self.log.append(entries)
        self._ready.entries.extend(entries)
        self.match_index[self.id] = self.log.last_index()
        self._maybe_commit()

    def _broadcast_append(self) -> None:
        for peer in self._replicas():
            self._send_append(peer)

    def _send_append(self, peer: int) -> None:
        next_idx = self.next_index.get(peer, self.log.last_index() + 1)
        prev = next_idx - 1
        prev_term = self.log.term_at(prev)
        if peer in self.force_snapshot or prev_term is None:
            # log truncated below next_idx — ship a snapshot (container fills data)
            self._ready.messages.append(
                Message(MsgType.SNAPSHOT, self.id, peer, self.term)
            )
            return
        entries = self.log.slice_from(next_idx)
        self._send(
            Message(
                MsgType.APPEND, self.id, peer, self.term,
                log_index=prev, log_term=prev_term,
                entries=list(entries), commit=self.commit,
            )
        )

    def _on_append(self, m: Message) -> None:
        self._become_follower(m.term, m.frm)
        prev_term = self.log.term_at(m.log_index)
        if prev_term is None or prev_term != m.log_term:
            hint = min(m.log_index, self.log.last_index())
            self._send(
                Message(
                    MsgType.APPEND_RESP, self.id, m.frm, self.term,
                    reject=True, reject_hint=hint,
                )
            )
            return
        # find conflict point, truncate, append the rest
        new_entries = []
        for e in m.entries:
            if e.index < self.log.offset:
                # already covered by our snapshot (committed state) — a late
                # retransmit must not splice pre-snapshot entries into the
                # list, which would corrupt offset-based index arithmetic
                continue
            t = self.log.term_at(e.index)
            if t is None:
                new_entries.append(e)
            elif t != e.term:
                self.log.truncate_from(e.index)
                new_entries.append(e)
        if new_entries:
            self.log.append(new_entries)
            self._ready.entries.extend(new_entries)
        last_new = max(m.log_index + len(m.entries), self.log.snapshot_index)
        if m.commit > self.commit:
            new_commit = min(m.commit, last_new)
            if new_commit > self.commit:
                self.commit = new_commit
                self._ready.hard_state_changed = True
        self._send(
            Message(MsgType.APPEND_RESP, self.id, m.frm, self.term, log_index=last_new)
        )

    def _on_append_resp(self, m: Message) -> None:
        if self.role != Role.LEADER:
            return
        if m.reject:
            self.next_index[m.frm] = max(1, min(m.reject_hint + 1, self.next_index.get(m.frm, 2) - 1))
            self._send_append(m.frm)
            return
        self.match_index[m.frm] = max(self.match_index.get(m.frm, 0), m.log_index)
        self.next_index[m.frm] = self.match_index[m.frm] + 1
        self.force_snapshot.discard(m.frm)
        self._maybe_commit()
        if self.next_index[m.frm] <= self.log.last_index():
            self._send_append(m.frm)

    def _quorum_index(self, cfg: set[int]) -> int:
        matches = sorted((self.match_index.get(p, 0) for p in cfg), reverse=True)
        return matches[len(cfg) // 2] if cfg else 0

    def _group_index(self) -> int:
        """Highest index present in EVERY label group (replication_mode.rs
        IntegrityOverLabel): per group, the best match among its voters;
        the constraint is the min across groups.  One known group (or none)
        imposes nothing."""
        groups: dict[object, int] = {}
        for p in self.voters:
            g = self.peer_groups.get(p)
            if g is None:
                continue
            cur = groups.get(g, 0)
            groups[g] = max(cur, self.match_index.get(p, 0))
        if len(groups) <= 1:
            return self.log.last_index()
        return min(groups.values())

    def _maybe_commit(self) -> None:
        if self.role != Role.LEADER:
            return
        candidate = self._quorum_index(self.voters)
        if self.outgoing is not None:
            # joint rule: an entry commits only when replicated to a majority
            # of BOTH configs
            candidate = min(candidate, self._quorum_index(self.outgoing))
        if self.group_commit:
            candidate = min(candidate, self._group_index())
        # only commit entries of the current term by counting (§5.4.2)
        if candidate > self.commit and self.log.term_at(candidate) == self.term:
            self.commit = candidate
            self._ready.hard_state_changed = True
            self._broadcast_append_commit()
            if self._deferred_reads and self._committed_in_term():
                deferred, self._deferred_reads = self._deferred_reads, []
                for ctx, origin in deferred:
                    if origin is None:
                        self.read_index(ctx)
                    else:
                        self._serve_remote_read(ctx, origin)

    def _broadcast_append_commit(self) -> None:
        for peer in self._replicas():
            if self.next_index.get(peer, 1) > self.log.last_index():
                # nothing to replicate; push the commit index via heartbeat
                self._send(
                    Message(MsgType.HEARTBEAT, self.id, peer, self.term, commit=min(self.commit, self.match_index.get(peer, 0)))
                )
            else:
                self._send_append(peer)

    # heartbeats ------------------------------------------------------------

    def lease_valid(self) -> bool:
        """Leader lease for local reads (worker/read.rs LocalReader): valid
        while a quorum acknowledged us within the last election timeout.
        TIKV_TPU_DISABLE_LEASES=1 turns leases off everywhere (reads take
        ReadIndex; resolved-ts advance must confirm via check_leader) — the
        clock-skew-paranoid deployment mode, and what lets tests prove the
        quorum paths carry the system on their own."""
        if _LEASES_OFF:
            return False
        return (
            self.role == Role.LEADER
            and self._committed_in_term()
            and (self._has_quorum({self.id}) or self._tick_count < self._lease_until)
        )

    def _broadcast_heartbeat(self, ctx: bytes = b"") -> None:
        self._hb_round += 1
        self._hb_round_tick = self._tick_count
        self._hb_acks = {self.id}
        for peer in self._replicas():
            self._send(
                Message(
                    MsgType.HEARTBEAT, self.id, peer, self.term,
                    commit=min(self.commit, self.match_index.get(peer, 0)),
                    context=ctx, hb_round=self._hb_round,
                )
            )

    def _on_heartbeat(self, m: Message) -> None:
        self._become_follower(m.term, m.frm)
        if m.context == _HIBERNATE_CTX:
            # current-term leader's hibernate round (stale leaders were
            # already rejected by step()'s term check)
            self.hibernated = True
        if m.commit > self.commit:
            self.commit = min(m.commit, self.log.last_index())
            self._ready.hard_state_changed = True
        self._send(
            Message(
                MsgType.HEARTBEAT_RESP, self.id, m.frm, self.term,
                context=m.context, hb_round=m.hb_round,
            )
        )

    def _on_heartbeat_resp(self, m: Message) -> None:
        if self.role != Role.LEADER:
            return
        if m.hb_round == self._hb_round and not self.hibernated:
            # hibernate-round acks must not re-grant a lease the frozen clock
            # could never expire
            self._hb_acks.add(m.frm)
            if self._has_quorum(self._hb_acks):
                self._lease_until = max(
                    self._lease_until, self._hb_round_tick + self.election_tick
                )
        if m.context and m.context in self._pending_reads:
            index, acks = self._pending_reads[m.context]
            acks.add(m.frm)
            # learner acks carry no quorum weight (same rule as the lease path)
            if self._has_quorum(acks):
                del self._pending_reads[m.context]
                origin = getattr(self, "_read_origins", {}).pop(m.context, None)
                if origin is None:
                    self._ready.read_states.append((m.context, index))
                else:
                    self._send(
                        Message(
                            MsgType.READ_INDEX_RESP, self.id, origin, self.term,
                            log_index=index, context=m.context,
                        )
                    )
        if self.match_index.get(m.frm, 0) < self.log.last_index():
            self._send_append(m.frm)

    # snapshots -------------------------------------------------------------

    def _on_snapshot(self, m: Message) -> None:
        snap = m.snapshot
        if snap is None:
            return
        self._become_follower(m.term, m.frm)
        if snap.index <= self.commit and not self.force_accept_snapshot:
            self._send(Message(MsgType.APPEND_RESP, self.id, m.frm, self.term, log_index=self.commit))
            return
        self.force_accept_snapshot = False
        self.log.reset_to_snapshot(snap)
        self.commit = snap.index
        self.applied = snap.index
        self.voters = set(snap.voters)
        self.learners = set(snap.learners)
        self.outgoing = set(snap.outgoing) if snap.outgoing else None
        self.witnesses = set(snap.witnesses)
        self._pending_conf_index = min(self._pending_conf_index, snap.index)
        self._ready.snapshot = snap
        self._ready.hard_state_changed = True
        self._send(Message(MsgType.APPEND_RESP, self.id, m.frm, self.term, log_index=snap.index))

    # read index ------------------------------------------------------------

    def _on_read_index(self, m: Message) -> None:
        if self.role != Role.LEADER:
            return
        self._serve_remote_read(m.context, m.frm)

    def _serve_remote_read(self, ctx: bytes, origin: int) -> None:
        if not self._committed_in_term():
            self._deferred_reads.append((ctx, origin))
            return
        if self._has_quorum({self.id}):
            self._send(Message(MsgType.READ_INDEX_RESP, self.id, origin, self.term, log_index=self.commit, context=ctx))
            return
        # piggyback on a heartbeat round keyed by the follower's ctx; remember
        # the origin so the response routes back when quorum acks arrive
        self._pending_reads[ctx] = (self.commit, {self.id})
        self._read_origins = getattr(self, "_read_origins", {})
        self._read_origins[ctx] = origin
        self._broadcast_heartbeat(ctx=ctx)

    def _on_read_index_resp(self, m: Message) -> None:
        self._ready.read_states.append((m.context, m.log_index))
