"""Mesh-sharded coprocessor evaluation.

TiKV scales horizontally by splitting the key space into regions
(``raftstore/src/coprocessor/split_check/``); the TPU-native re-expression is
a ``jax.sharding.Mesh`` with two axes:

* ``"regions"`` — row blocks sharded across devices (the data-parallel axis:
  each device scans/filters/aggregates its own region shard; partial
  aggregate states merge with ``psum``/``pmin``/``pmax`` over ICI, exactly the
  mergeable-state design the CPU pipeline uses across batches)
* ``"groups"`` — the aggregation state (group capacity) sharded across
  devices (the tensor-parallel axis: each device owns a slice of the
  group-state vector after the cross-region reduction)

The collectives ride ICI inside a pod; nothing here assumes a host count, so
the same program runs on a virtual 8-CPU-device mesh (tests / driver dryrun)
and a real TPU slice.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from ..copr.dag import DagRequest
from ..copr.jax_eval import _NO_ROW, JaxDagEvaluator, _seg_extreme, _seg_sum
from ..copr.rpn import eval_rpn


def make_mesh(devices=None, groups: int = 1) -> Mesh:
    """A (regions × groups) mesh over the given (or all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % groups == 0, "device count must divide into group shards"
    arr = np.array(devices).reshape(n // groups, groups)
    return Mesh(arr, axis_names=("regions", "groups"))


# per-leaf merge semantics of each aggregate's carry (leaf 0 is always count)
_MERGE = {
    "count": ("sum",),
    "sum": ("sum", "sum"),
    "avg": ("sum", "sum"),
    "var_pop": ("sum", "sum", "sum"),
    "min": ("sum", "min"),
    "max": ("sum", "max"),
}


def _collective(kind: str, x, axis: str):
    if kind == "sum":
        return jax.lax.psum(x, axis)
    if kind == "min":
        return jax.lax.pmin(x, axis)
    return jax.lax.pmax(x, axis)


def _combine(kind: str, a, b):
    if kind == "sum":
        return a + b
    if kind == "min":
        return jnp.minimum(a, b)
    return jnp.maximum(a, b)


class ShardedDagEvaluator:
    """Multi-device DAG aggregation step for an eligible aggregation DAG.

    ``step(col_data, col_nulls, valid, gids, state)`` consumes one super-block
    whose rows are sharded over the ``regions`` axis and whose state shards
    over ``groups``; it returns the updated sharded state.  Finalization uses
    the same host code as the single-device evaluator.
    """

    def __init__(self, dag: DagRequest, mesh: Mesh, rows_per_shard: int, capacity: int = 16):
        self.ev = JaxDagEvaluator(dag, block_rows=rows_per_shard)
        if self.ev.plan.agg is None:
            raise ValueError("sharded evaluation requires an aggregation DAG")
        self.mesh = mesh
        self.rows_per_shard = rows_per_shard
        self.n_regions = mesh.shape["regions"]
        self.n_groups = mesh.shape["groups"]
        assert capacity % self.n_groups == 0
        self.capacity = capacity
        self.total_rows = rows_per_shard * self.n_regions
        self._step = self._build_step()

    def _build_step(self):
        ev = self.ev
        capacity = self.capacity
        gshard = capacity // self.n_groups
        n_rows = self.rows_per_shard
        device_cols = ev.device_cols
        nullable = ev.nullable_cols
        sel_rpns = ev.sel_rpns
        device_aggs = ev.device_aggs

        col_specs = tuple(P("regions") for _ in device_cols)
        null_specs = tuple(P("regions") for _ in nullable)
        state_spec = (
            P("groups"),
            tuple(
                tuple(P("groups") for _ in _MERGE[da.op])
                for da in device_aggs
            ),
        )
        in_specs = (col_specs, null_specs, P("regions"), P("regions"), state_spec)

        @partial(
            jax.shard_map,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=state_spec,
        )
        def step(col_data, col_nulls, valid, gids, state):
            first_shard, carry_shards = state
            no_nulls = jnp.zeros(n_rows, dtype=bool)
            nullmap = dict(zip(nullable, col_nulls))
            cols = {
                i: (col_data[j], nullmap.get(i, no_nulls))
                for j, i in enumerate(device_cols)
            }
            active = valid
            for rpn in sel_rpns:
                d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
                active = active & (d != 0) & ~nl
            gidx = jax.lax.axis_index("groups")
            lo = gidx * gshard
            new_first = first_shard
            new_carries = []
            for da, carry_shard in zip(device_aggs, carry_shards):
                zero = da.init_carry(capacity)
                partial_full = da.update(zero, cols, n_rows, gids, active, capacity)
                merged = []
                for kind, leaf in zip(_MERGE[da.op], partial_full):
                    # reduce partial states across region shards, then each
                    # groups-member keeps its slice of the state vector
                    leaf = _collective(kind, leaf, "regions")
                    my = jax.lax.dynamic_slice_in_dim(leaf, lo, gshard)
                    merged.append(my)
                new_carries.append(
                    tuple(_combine(k, c, m) for k, c, m in zip(_MERGE[da.op], carry_shard, merged))
                )
            # global row index (region shards hold consecutive row ranges), so
            # group order matches the single-stream first-occurrence order
            shard_base = jax.lax.axis_index("regions").astype(jnp.int64) * n_rows
            ridx = jnp.where(
                active, shard_base + jnp.arange(n_rows, dtype=jnp.int64), _NO_ROW
            )
            bf = _seg_extreme(ridx, gids, capacity, True, _NO_ROW)
            bf = jax.lax.pmin(bf, "regions")
            my_bf = jax.lax.dynamic_slice_in_dim(bf, lo, gshard)
            new_first = jnp.minimum(new_first, my_bf)
            return (new_first, tuple(new_carries))

        return jax.jit(step)

    def init_state(self):
        gshard = self.capacity // self.n_groups
        first = jnp.full(self.capacity, _NO_ROW, dtype=jnp.int64)
        carries = tuple(da.init_carry(self.capacity) for da in self.ev.device_aggs)
        return (first, carries)

    def step(self, col_data, col_nulls, valid, gids, state):
        return self._step(col_data, col_nulls, valid, gids, state)

    def run_arrays(self, columns: dict, n_valid: int, gids: np.ndarray):
        """Evaluate one super-block given per-column numpy (data, nulls)."""
        col_data = tuple(np.asarray(columns[i][0]) for i in self.ev.device_cols)
        col_nulls = tuple(np.asarray(columns[i][1]) for i in self.ev.nullable_cols)
        valid = np.zeros(self.total_rows, dtype=bool)
        valid[:n_valid] = True
        state = self.init_state()
        return self.step(col_data, col_nulls, valid, gids, state)
