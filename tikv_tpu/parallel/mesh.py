"""Mesh-sharded coprocessor evaluation.

TiKV scales horizontally by splitting the key space into regions
(``raftstore/src/coprocessor/split_check/``); the TPU-native re-expression is
a ``jax.sharding.Mesh`` with two axes:

* ``"regions"`` — row blocks sharded across devices (the data-parallel axis:
  each device scans/filters/aggregates its own region shard; partial
  aggregate states merge with ``psum``/``pmin``/``pmax`` over ICI, exactly the
  mergeable-state design the CPU pipeline uses across batches)
* ``"groups"`` — the aggregation state (group capacity) sharded across
  devices (the tensor-parallel axis: each device owns a slice of the
  group-state vector after the cross-region reduction)

The collectives ride ICI inside a pod; nothing here assumes a host count, so
the same program runs on a virtual 8-CPU-device mesh (tests / driver dryrun)
and a real TPU slice.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from ..analysis.sanitizer import note_blocking
from ..copr import observatory as _obs
from ..copr.dag import DagRequest
from ..copr.jax_eval import (
    _NO_ROW,
    JaxDagEvaluator,
    XRegionPending,
    _build_cols,
    _fused_step,
    _seg_extreme,
    _seg_sum,
    _topn_key_operands,
)
from ..copr.rpn import eval_rpn

# shard_map moved to the jax top level (with ``check_vma``) after 0.4.x; on
# 0.4.x it lives in jax.experimental with the replication check spelled
# ``check_rep``.  One shim so every sharded program here compiles on both.
if hasattr(jax, "shard_map"):
    _SHARD_MAP, _SM_CHECK_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - exercised on 0.4.x images
    from jax.experimental.shard_map import shard_map as _SHARD_MAP

    _SM_CHECK_KW = "check_rep"


def _smap(mesh: Mesh, in_specs, out_specs, check: bool = True):
    """Version-portable ``shard_map`` decorator."""
    kw = {} if check else {_SM_CHECK_KW: False}
    return partial(_SHARD_MAP, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, **kw)


_KEY_SENTINEL = jnp.int64(2**62)  # empty group-dictionary slot (sorts last)


def make_mesh(devices=None, groups: int = 1) -> Mesh:
    """A (regions × groups) mesh over the given (or all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % groups == 0, "device count must divide into group shards"
    arr = np.array(devices).reshape(n // groups, groups)
    return Mesh(arr, axis_names=("regions", "groups"))


# per-leaf merge semantics of each aggregate's carry (leaf 0 is always count).
# bitwise ops are associative+commutative, so they merge across region shards
# like min/max; ``first`` is NOT here — its carry is a paired (value, row
# index) argmin that a leaf-wise merge cannot express, so mesh construction
# declines it (ValueError) and the endpoint memoizes the single-device route.
_MERGE = {
    "count": ("sum",),
    "sum": ("sum", "sum"),
    "avg": ("sum", "sum"),
    "var_pop": ("sum", "sum", "sum"),
    "min": ("sum", "min"),
    "max": ("sum", "max"),
    "bit_and": ("sum", "bit_and"),
    "bit_or": ("sum", "bit_or"),
    "bit_xor": ("sum", "bit_xor"),
}


def _require_mesh_mergeable(device_aggs) -> None:
    for da in device_aggs:
        if da.op not in _MERGE:
            raise ValueError(f"aggregate {da.op!r} has no mesh merge rule")


def _marshal_block(ev: JaxDagEvaluator, columns: dict, n_valid: int, total_rows: int):
    """Host-side marshalling of one super-block: THE one definition shared
    by every sharded evaluator's run_blocks."""
    col_data = tuple(np.asarray(columns[i][0]) for i in ev.device_cols)
    col_nulls = tuple(np.asarray(columns[i][1]) for i in ev.nullable_cols)
    valid = np.zeros(total_rows, dtype=bool)
    valid[:n_valid] = True
    return col_data, col_nulls, valid


def _shard_active_cols(device_cols, nullable, sel_rpns, col_data, col_nulls, valid, n_rows):
    """In-jit preamble shared by every sharded step: build the per-column
    (data, nulls) map and fold the selection predicates into the row mask."""
    no_nulls = jnp.zeros(n_rows, dtype=bool)
    nullmap = dict(zip(nullable, col_nulls))
    cols = {
        i: (col_data[j], nullmap.get(i, no_nulls))
        for j, i in enumerate(device_cols)
    }
    active = valid
    for rpn in sel_rpns:
        d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
        active = active & (d != 0) & ~nl
    return cols, active


def _collective(kind: str, x, axis: str):
    if kind == "sum":
        return jax.lax.psum(x, axis)
    if kind == "min":
        return jax.lax.pmin(x, axis)
    if kind == "max":
        return jax.lax.pmax(x, axis)
    # bitwise monoids: no dedicated collective exists, so gather the shard
    # partials and fold them with the XLA and/or/xor reduction.  The fold's
    # result is identical on every member but shard_map cannot infer that
    # statically, so a final psum (member 0 contributes, others add zero)
    # re-establishes provable replication.
    from ..copr.jax_eval import _BIT_FN, _BIT_IDENT

    g = jax.lax.all_gather(x, axis)
    folded = jax.lax.reduce(g, jnp.int64(_BIT_IDENT[kind]), _BIT_FN[kind], (0,))
    mine = jnp.where(jax.lax.axis_index(axis) == 0, folded, jnp.zeros_like(folded))
    return jax.lax.psum(mine, axis)


def _combine(kind: str, a, b):
    if kind == "sum":
        return a + b
    if kind == "min":
        return jnp.minimum(a, b)
    if kind == "max":
        return jnp.maximum(a, b)
    from ..copr.jax_eval import _BIT_FN

    return _BIT_FN[kind](a, b)


class ShardedDagEvaluator:
    """Multi-device DAG aggregation step for an eligible aggregation DAG.

    ``step(col_data, col_nulls, valid, gids, state)`` consumes one super-block
    whose rows are sharded over the ``regions`` axis and whose state shards
    over ``groups``; it returns the updated sharded state.  Finalization uses
    the same host code as the single-device evaluator.
    """

    def __init__(self, dag: DagRequest, mesh: Mesh, rows_per_shard: int, capacity: int = 16):
        self.ev = JaxDagEvaluator(dag, block_rows=rows_per_shard)
        if self.ev.plan.agg is None:
            raise ValueError("sharded evaluation requires an aggregation DAG")
        _require_mesh_mergeable(self.ev.device_aggs)
        self.mesh = mesh
        self.rows_per_shard = rows_per_shard
        self.n_regions = mesh.shape["regions"]
        self.n_groups = mesh.shape["groups"]
        assert capacity % self.n_groups == 0
        self.capacity = capacity
        self.total_rows = rows_per_shard * self.n_regions
        self._step = self._build_step()

    def _build_step(self):
        ev = self.ev
        capacity = self.capacity
        gshard = capacity // self.n_groups
        n_rows = self.rows_per_shard
        device_cols = ev.device_cols
        nullable = ev.nullable_cols
        sel_rpns = ev.sel_rpns
        device_aggs = ev.device_aggs

        col_specs = tuple(P("regions") for _ in device_cols)
        null_specs = tuple(P("regions") for _ in nullable)
        state_spec = (
            P("groups"),
            tuple(
                tuple(P("groups") for _ in _MERGE[da.op])
                for da in device_aggs
            ),
        )
        in_specs = (col_specs, null_specs, P("regions"), P("regions"), P(), state_spec)

        @_smap(self.mesh, in_specs, state_spec)
        def step(col_data, col_nulls, valid, gids, block_base, state):
            first_shard, carry_shards = state
            cols, active = _shard_active_cols(
                device_cols, nullable, sel_rpns, col_data, col_nulls, valid, n_rows
            )
            gidx = jax.lax.axis_index("groups")
            lo = gidx * gshard
            new_first = first_shard
            new_carries = []
            for da, carry_shard in zip(device_aggs, carry_shards):
                zero = da.init_carry(capacity)
                partial_full = da.update(zero, cols, n_rows, gids, active, capacity)
                merged = []
                for kind, leaf in zip(_MERGE[da.op], partial_full):
                    # reduce partial states across region shards, then each
                    # groups-member keeps its slice of the state vector
                    leaf = _collective(kind, leaf, "regions")
                    my = jax.lax.dynamic_slice_in_dim(leaf, lo, gshard)
                    merged.append(my)
                new_carries.append(
                    tuple(_combine(k, c, m) for k, c, m in zip(_MERGE[da.op], carry_shard, merged))
                )
            # global row index (region shards hold consecutive row ranges), so
            # group order matches the single-stream first-occurrence order
            shard_base = jax.lax.axis_index("regions").astype(jnp.int64) * n_rows
            ridx = jnp.where(
                active,
                block_base + shard_base + jnp.arange(n_rows, dtype=jnp.int64),
                _NO_ROW,
            )
            bf = _seg_extreme(ridx, gids, capacity, True, _NO_ROW)
            bf = jax.lax.pmin(bf, "regions")
            my_bf = jax.lax.dynamic_slice_in_dim(bf, lo, gshard)
            new_first = jnp.minimum(new_first, my_bf)
            return (new_first, tuple(new_carries))

        # lint: allow(jit-nocache) -- compiled ONCE per evaluator in
        # __init__ (self._step/self._fin memoize the returned callable)
        return _obs.timed_jit(jax.jit(step), "mesh.agg_step", "mesh",
                              self.ev.obs_sig)

    def init_state(self):
        gshard = self.capacity // self.n_groups
        first = jnp.full(self.capacity, _NO_ROW, dtype=jnp.int64)
        carries = tuple(da.init_carry(self.capacity) for da in self.ev.device_aggs)
        return (first, carries)

    def step(self, col_data, col_nulls, valid, gids, state, block_base: int = 0):
        return self._step(col_data, col_nulls, valid, gids, np.int64(block_base), state)

    def run_arrays(self, columns: dict, n_valid: int, gids: np.ndarray):
        """Evaluate one super-block given per-column numpy (data, nulls)."""
        return self.run_blocks([(columns, n_valid, gids)])

    def run_blocks(self, blocks):
        """Multi-block evaluation with carried state: each super-block's rows
        shard over ``regions`` while the aggregate state stays resident on
        device between blocks — the long-scan streaming shape of §2.5
        (blockwise evaluation with carry, applied across the mesh)."""
        state = self.init_state()
        for b, (columns, n_valid, gids) in enumerate(blocks):
            col_data, col_nulls, valid = _marshal_block(
                self.ev, columns, n_valid, self.total_rows
            )
            state = self.step(
                col_data, col_nulls, valid, np.asarray(gids), state,
                block_base=b * self.total_rows,
            )
        return state


class ShardedGroupedEvaluator:
    """Grouped aggregation with the group DICTIONARY built on device, sharded
    over the mesh (fast_hash_aggr_executor.rs:38 re-expressed for SPMD).

    The single-device warm path dict-codes group keys on the host; here each
    region shard packs its group-by column values into one int64 key, merges
    the keys into a bounded SORTED dictionary (static-shape union: concat →
    sort → unique-rank scatter), all-gathers the dictionaries over the
    ``regions`` axis into one global dictionary, and group ids are
    ``searchsorted`` positions in it.  Aggregate partial states then merge
    with psum/pmin/pmax exactly as in ShardedDagEvaluator.

    Output group ORDER follows first occurrence in the global row stream —
    recovered from the first-row-index state, so results are comparable to
    the CPU executor's dict-coded order.  Capacity overflow is detected
    (``overflow`` flag in the state) rather than silently dropping groups —
    the caller falls back to the host path, like every other device gate.
    """

    def __init__(
        self,
        dag: DagRequest,
        mesh: Mesh,
        rows_per_shard: int,
        capacity: int = 64,
        key_bits: int = 31,
    ):
        self.ev = JaxDagEvaluator(dag, block_rows=rows_per_shard)
        plan = self.ev.plan
        if plan.agg is None or not plan.agg.group_by:
            raise ValueError("grouped evaluation requires GROUP BY aggregation")
        _require_mesh_mergeable(self.ev.device_aggs)
        self.group_rpns = self.ev.group_rpns
        # the single-device path group-codes on the HOST, so the evaluator
        # does not ship group-by columns; here the dictionary builds on
        # device — extend the shipped set
        extra: set[int] = set()
        for g in self.group_rpns:
            extra |= g.referenced_columns()
        self.ev.ship_extra_columns(extra)
        if len(self.group_rpns) * key_bits > 62:
            raise ValueError(
                f"{len(self.group_rpns)} group keys x {key_bits} bits "
                "overflow the packed int64 key"
            )
        self.mesh = mesh
        self.rows_per_shard = rows_per_shard
        self.n_regions = mesh.shape["regions"]
        self.capacity = capacity
        self.key_bits = key_bits
        self.total_rows = rows_per_shard * self.n_regions
        self._step = self._build_step()

    def _build_step(self):
        ev = self.ev
        cap = self.capacity
        n_rows = self.rows_per_shard
        device_cols = ev.device_cols
        nullable = ev.nullable_cols
        sel_rpns = ev.sel_rpns
        device_aggs = ev.device_aggs
        group_rpns = self.group_rpns
        key_bits = self.key_bits

        col_specs = tuple(P("regions") for _ in device_cols)
        null_specs = tuple(P("regions") for _ in nullable)
        # replicated state: dict keys, first-row index, carries, overflow flag
        state_spec = (
            P(),
            P(),
            tuple(tuple(P() for _ in _MERGE[da.op]) for da in device_aggs),
            P(),
        )
        in_specs = (col_specs, null_specs, P("regions"), P(), state_spec)

        # every output IS replicated — it flows through psum/pmin/pmax or
        # all_gather before leaving — but the static varying-axis
        # inference cannot see that through the scatter/searchsorted
        # dictionary rebuild; the equality tests assert it dynamically
        @_smap(self.mesh, in_specs, state_spec, check=False)
        def step(col_data, col_nulls, valid, block_base, state):
            dict_keys, first, carries, overflow = state
            cols, active = _shard_active_cols(
                device_cols, nullable, sel_rpns, col_data, col_nulls, valid, n_rows
            )
            # pack group-by values into ONE int64 key; NULL packs as the
            # all-ones lane so it groups separately from every real value.
            # Values outside [0, 2^key_bits-1) cannot pack losslessly —
            # flag them into `overflow` (the host-fallback gate) instead of
            # silently merging distinct groups by truncation.
            key = jnp.zeros(n_rows, dtype=jnp.int64)
            lane_max = (1 << key_bits) - 1  # all-ones = NULL, so exclusive
            range_over = jnp.asarray(False)
            for rpn in group_rpns:
                d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
                v = d.astype(jnp.int64)
                bad = active & ~nl & ((v < 0) | (v >= lane_max))
                range_over = range_over | jnp.any(bad)
                lane = jnp.where(nl, lane_max, v)
                key = (key << key_bits) | (lane & lane_max)
            key = jnp.where(active, key, _KEY_SENTINEL)
            # bounded sorted union: dict ∪ block keys (static shapes)
            combined = jnp.sort(jnp.concatenate([dict_keys, key]))
            fresh = jnp.concatenate(
                [jnp.array([True]), combined[1:] != combined[:-1]]
            ) & (combined < _KEY_SENTINEL)
            rank = jnp.cumsum(fresh) - 1
            local_dict = jnp.full(cap, _KEY_SENTINEL, dtype=jnp.int64)
            pos = jnp.where(fresh & (rank < cap), rank, cap)
            local_dict = local_dict.at[pos].set(combined, mode="drop")
            local_over = jnp.any(fresh & (rank >= cap))
            # global dictionary: union of every region shard's dictionary
            gathered = jax.lax.all_gather(local_dict, "regions", tiled=True)
            gsorted = jnp.sort(gathered)
            gfresh = jnp.concatenate(
                [jnp.array([True]), gsorted[1:] != gsorted[:-1]]
            ) & (gsorted < _KEY_SENTINEL)
            grank = jnp.cumsum(gfresh) - 1
            new_dict = jnp.full(cap, _KEY_SENTINEL, dtype=jnp.int64)
            gpos = jnp.where(gfresh & (grank < cap), grank, cap)
            new_dict = new_dict.at[gpos].set(gsorted, mode="drop")
            new_over = (
                overflow
                | (
                    jax.lax.psum(
                        (local_over | range_over).astype(jnp.int32), "regions"
                    )
                    > 0
                )
                | jnp.any(gfresh & (grank >= cap))
            )
            gids = jnp.searchsorted(new_dict, key).astype(jnp.int32)
            gids = jnp.clip(gids, 0, cap - 1)
            # REMAP carried slots: new keys can reshuffle the sorted
            # dictionary, so position i of the old dict moves to
            # searchsorted(new_dict, old_key).  Old sentinel slots hold
            # identity values and scatter-drop past the end.
            perm = jnp.where(
                dict_keys < _KEY_SENTINEL,
                jnp.searchsorted(new_dict, dict_keys),
                cap,
            )
            new_carries = []
            for da, carry in zip(device_aggs, carries):
                ident = da.init_carry(cap)
                remapped = tuple(
                    iv.at[perm].set(cv, mode="drop") for iv, cv in zip(ident, carry)
                )
                part = da.update(da.init_carry(cap), cols, n_rows, gids, active, cap)
                merged = []
                for kind, leaf, cur in zip(_MERGE[da.op], part, remapped):
                    leaf = _collective(kind, leaf, "regions")
                    merged.append(_combine(kind, cur, leaf))
                new_carries.append(tuple(merged))
            first_remap = jnp.full(cap, _NO_ROW, dtype=jnp.int64).at[perm].set(
                first, mode="drop"
            )
            shard_base = jax.lax.axis_index("regions").astype(jnp.int64) * n_rows
            ridx = jnp.where(
                active,
                block_base + shard_base + jnp.arange(n_rows, dtype=jnp.int64),
                _NO_ROW,
            )
            bf = _seg_extreme(ridx, gids, cap, True, _NO_ROW)
            bf = jax.lax.pmin(bf, "regions")
            new_first = jnp.minimum(first_remap, bf)
            return (new_dict, new_first, tuple(new_carries), new_over)

        # lint: allow(jit-nocache) -- compiled ONCE per evaluator in
        # __init__ (self._step/self._fin memoize the returned callable)
        return _obs.timed_jit(jax.jit(step), "mesh.grouped_step", "mesh",
                              self.ev.obs_sig)

    def init_state(self):
        dict_keys = jnp.full(self.capacity, _KEY_SENTINEL, dtype=jnp.int64)
        first = jnp.full(self.capacity, _NO_ROW, dtype=jnp.int64)
        carries = tuple(da.init_carry(self.capacity) for da in self.ev.device_aggs)
        return (dict_keys, first, carries, jnp.asarray(False))

    def run_blocks(self, blocks):
        """blocks: [(columns, n_valid), ...] in stream order — multi-block
        carry with the dictionary, first-row order and aggregate state all
        resident on device between blocks."""
        state = self.init_state()
        for b, (columns, n_valid) in enumerate(blocks):
            col_data, col_nulls, valid = _marshal_block(
                self.ev, columns, n_valid, self.total_rows
            )
            state = self._step(
                col_data, col_nulls, valid,
                np.int64(b * self.total_rows), state,
            )
        return state

    def finalize(self, state) -> dict:
        """Pull the state and order groups by FIRST OCCURRENCE in the row
        stream (the CPU dict-coded order): returns {"keys": [...],
        "counts": ..., "aggs": [per-agg leaves], "overflow": bool} with
        group axis in first-occurrence order."""
        dict_keys, first, carries, overflow = jax.tree.map(np.asarray, state)
        live = dict_keys < int(_KEY_SENTINEL)
        order = np.argsort(first[live], kind="stable")
        idx = np.nonzero(live)[0][order]
        return {
            "keys": dict_keys[idx],
            "first": first[idx],
            "aggs": [tuple(leaf[idx] for leaf in c) for c in carries],
            "overflow": bool(overflow),
        }


class ShardedTopNEvaluator:
    """Raw TopN (TableScan → Selection? → TopN) across the mesh: every region
    shard carries its own running top-K (the single-device _topn_step shape),
    and ``finalize`` merges the shards with one collective program —
    all_gather over ``regions`` then one more stable sort (top_n_executor.rs
    re-expressed as SPMD).

    Ties resolve in GLOBAL STREAM ORDER even across shards: a global row
    index rides as the final sort key, so the merged result is byte-
    comparable with the single-stream executor."""

    def __init__(self, dag: DagRequest, mesh: Mesh, rows_per_shard: int):
        self.ev = JaxDagEvaluator(dag, block_rows=rows_per_shard)
        plan = self.ev.plan
        if plan.topn is None or plan.agg is not None:
            raise ValueError("sharded TopN requires a raw TopN DAG")
        self.k = plan.topn.limit
        self.mesh = mesh
        self.rows_per_shard = rows_per_shard
        self.n_regions = mesh.shape["regions"]
        self.total_rows = rows_per_shard * self.n_regions
        self.payload_cols = list(range(len(self.ev.schema)))
        # leaves: rank, (null-rank, key) per order key, global row idx,
        # then (data, null) per payload column
        self.n_key_ops = 1 + 2 * len(self.ev.topn_rpns) + 1
        self._step = self._build_step()
        self._fin = self._build_finalize()

    def _leaf_specs(self):
        n_leaves = self.n_key_ops + 2 * len(self.payload_cols)
        return tuple(P("regions") for _ in range(n_leaves))

    def _build_step(self):
        ev = self.ev
        k = self.k
        n_rows = self.rows_per_shard
        device_cols = ev.device_cols
        nullable = ev.nullable_cols
        sel_rpns = ev.sel_rpns
        order_rpns = ev.topn_rpns
        payload_cols = self.payload_cols
        n_key_ops = self.n_key_ops

        col_specs = tuple(P("regions") for _ in device_cols)
        null_specs = tuple(P("regions") for _ in nullable)
        state_spec = self._leaf_specs()
        in_specs = (col_specs, null_specs, P("regions"), P(), state_spec)

        @_smap(self.mesh, in_specs, state_spec)
        def step(col_data, col_nulls, valid, block_base, state):
            cols, active = _shard_active_cols(
                device_cols, nullable, sel_rpns, col_data, col_nulls, valid, n_rows
            )
            rank_blk = jnp.where(active, jnp.int64(0), jnp.int64(1))
            operands_blk = [rank_blk]
            for rpn, desc in order_rpns:
                d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
                operands_blk += _topn_key_operands(d, nl, desc)
            shard_base = jax.lax.axis_index("regions").astype(jnp.int64) * n_rows
            gidx = jnp.where(
                active,
                block_base + shard_base + jnp.arange(n_rows, dtype=jnp.int64),
                jnp.int64(2**62),
            )
            operands_blk.append(gidx)
            merged = [jnp.concatenate([s, b]) for s, b in zip(state, operands_blk)]
            idx = jnp.arange(k + n_rows, dtype=jnp.int64)
            sorted_ops = jax.lax.sort(
                merged + [idx], num_keys=n_key_ops, is_stable=True
            )
            top = [op[:k] for op in sorted_ops[:n_key_ops]]
            top_idx = sorted_ops[n_key_ops][:k]
            out = list(top)
            for j, ci in enumerate(payload_cols):
                bd, bn = cols[ci]
                sd = state[n_key_ops + 2 * j]
                sn = state[n_key_ops + 2 * j + 1]
                out.append(jnp.concatenate([sd, bd])[top_idx])
                out.append(jnp.concatenate([sn, bn])[top_idx])
            return tuple(out)

        # lint: allow(jit-nocache) -- compiled ONCE per evaluator in
        # __init__ (self._step/self._fin memoize the returned callable)
        return _obs.timed_jit(jax.jit(step), "mesh.topn_step", "mesh",
                              self.ev.obs_sig)

    def _build_finalize(self):
        k = self.k
        n_key_ops = self.n_key_ops
        n_payload = len(self.payload_cols)
        state_spec = self._leaf_specs()
        out_spec = tuple(P() for _ in range(n_key_ops + 2 * n_payload))

        # outputs are replicated by construction (all_gather then a
        # deterministic sort), which the static inference cannot prove
        # through the index gathers; tests assert the values
        @_smap(self.mesh, (state_spec,), out_spec, check=False)
        def fin(state):
            gathered = [
                jax.lax.all_gather(leaf, "regions", tiled=True) for leaf in state
            ]
            idx = jnp.arange(gathered[0].shape[0], dtype=jnp.int64)
            sorted_ops = jax.lax.sort(
                gathered[:n_key_ops] + [idx], num_keys=n_key_ops, is_stable=True
            )
            top = [op[:k] for op in sorted_ops[:n_key_ops]]
            top_idx = sorted_ops[n_key_ops][:k]
            out = list(top)
            for j in range(n_payload):
                out.append(gathered[n_key_ops + 2 * j][top_idx])
                out.append(gathered[n_key_ops + 2 * j + 1][top_idx])
            return tuple(out)

        # lint: allow(jit-nocache) -- compiled ONCE per evaluator in
        # __init__ (self._step/self._fin memoize the returned callable)
        return _obs.timed_jit(jax.jit(fin), "mesh.topn_fin", "mesh",
                              self.ev.obs_sig)

    def init_state(self):
        from ..copr.jax_eval import _np_dtype

        n = self.total_rows // self.rows_per_shard * self.k  # k per shard
        leaves = [np.ones(n, dtype=np.int64)]  # rank 1 = empty slot
        for _rpn, _desc in self.ev.topn_rpns:
            leaves.append(np.zeros(n, dtype=np.int64))
            leaves.append(np.zeros(n, dtype=_np_dtype(_rpn.eval_type)))
        leaves.append(np.full(n, 2**62, dtype=np.int64))  # global row idx
        for ci in self.payload_cols:
            leaves.append(np.zeros(n, dtype=_np_dtype(self.ev.schema[ci][0])))
            leaves.append(np.zeros(n, dtype=bool))
        return tuple(leaves)

    def run_blocks(self, blocks):
        """blocks: [(columns, n_valid), ...] in stream order."""
        state = self.init_state()
        for b, (columns, n_valid) in enumerate(blocks):
            col_data, col_nulls, valid = _marshal_block(
                self.ev, columns, n_valid, self.total_rows
            )
            state = self._step(
                col_data, col_nulls, valid, np.int64(b * self.total_rows), state
            )
        return state

    def finalize(self, state) -> dict:
        """Merge every shard's top-K into the global top-K; returns
        {"rows": n_live, "gidx": ..., "payload": [(data, nulls) per col]}."""
        out = jax.tree.map(np.asarray, self._fin(state))
        rank = out[0]
        live = int((rank == 0).sum())
        payload = []
        for j in range(len(self.payload_cols)):
            payload.append(
                (
                    out[self.n_key_ops + 2 * j][:live],
                    out[self.n_key_ops + 2 * j + 1][:live],
                )
            )
        return {
            "rows": live,
            "gidx": out[self.n_key_ops - 1][:live],
            "payload": payload,
        }


# ---------------------------------------------------------------------------
# Mesh-sharded warm serving: the shard_map twin of launch_xregion_cached
# ---------------------------------------------------------------------------


def mesh_mergeable(device_aggs) -> bool:
    """True when every aggregate's carry has a mesh merge rule — the gate in
    front of sharded warm serving (``first`` has none; those plans keep the
    single-device path)."""
    return all(da.op in _MERGE for da in device_aggs)


_FLAT_MESHES: dict = {}


def _flat_regions_mesh(mesh: Mesh) -> Mesh:
    """A 1-D ``regions``-axis view over every device of ``mesh``.  The warm
    sharded program has no use for the ``groups`` axis (its state is a small
    replicated (R, capacity) carry), so slabs shard over ALL chips."""
    devs = list(np.asarray(mesh.devices).reshape(-1))
    key = tuple(d.id for d in devs)
    m = _FLAT_MESHES.get(key)
    if m is None:
        m = _FLAT_MESHES[key] = Mesh(np.array(devs), axis_names=("regions",))
        while len(_FLAT_MESHES) > 8:
            _FLAT_MESHES.pop(next(iter(_FLAT_MESHES)))
    return m


_ZERO_SLABS: dict = {}


def _zero_slab(dev, pad: int, n_rows: int, dtype):
    """Cached per-device zero padding slabs (content is irrelevant — pad
    slabs carry ``n_valid == 0``, so the validity mask excludes every row)."""
    key = (dev.id, pad, n_rows, np.dtype(dtype).str)
    z = _ZERO_SLABS.get(key)
    if z is None:
        z = _ZERO_SLABS[key] = jax.device_put(
            np.zeros((pad, n_rows), dtype=dtype), dev)
        while len(_ZERO_SLABS) > 64:
            _ZERO_SLABS.pop(next(iter(_ZERO_SLABS)))
    return z


def _slab_pins(ev, cache, assign: dict, by_id: dict, ship, nullable,
               plan=None):
    """Per-owner-device pinned slab stacks for ONE region image.

    ``assign``: device id -> ascending block indices.  Returns {device_id:
    (data_tuple[(B_d, rows)] per ship col, nulls_tuple per nullable col)},
    each leaf COMMITTED to its owner device.  Pinned on the cache under a
    ``shardslab`` signature, so repeat batches pay zero transfer; a delta
    apply drops the pins (cache.scatter_update treats the kind as opaque)
    and they rebuild here from the updated host blocks.

    With an encoding ``plan`` (copr/encoding.py — every cache in the batch
    carries the same signature, RLE excluded), bitpacked/narrow-code lanes
    pin AS-IS: the devices hold the encoded HBM bytes and the shard_map
    program widens in-kernel with the per-region frame-of-reference row."""
    fp = tuple(sorted((did, tuple(bs)) for did, bs in assign.items()))
    enc = None if plan is None else plan.sig
    sig = ("shardslab", fp, tuple(ship), tuple(nullable), ev.block_rows, enc)

    def _canon(arr):
        # one dtype per lane across every cache in a batch (the global
        # sharded array needs uniform shards even from devices whose slabs
        # came from different regions): f64 stays, everything else rides
        # the int64 lanes the device step computes in anyway — except
        # encoded lanes, whose narrow dtype IS uniform by plan signature
        arr = np.asarray(arr)
        return arr.astype(np.int64, copy=False) if arr.dtype != np.float64 else arr

    from ..copr import encoding as _encoding

    def build(_blk):
        out = {}
        for did, idxs in assign.items():
            dev = by_id[did]
            blocks = [cache.blocks[i] for i in idxs]
            if plan is not None:
                # ONE stacked-payload assembly (encoding.stack_block_payloads,
                # shared with jax_eval._stacked_device); RLE is excluded on
                # this path so every leaf is a plain (B, rows) array
                data_np, nulls_np, _refs = _encoding.stack_block_payloads(
                    blocks, ship, nullable, plan, ev.block_rows)
                data = tuple(jax.device_put(a, dev) for a in data_np)
                nulls = tuple(jax.device_put(a, dev) for a in nulls_np)
            else:
                # decoded_data/nulls: a decode-ship of an encoded image must
                # not leave a full decode cached (the budget counts encoded)
                data = tuple(
                    jax.device_put(
                        np.stack([_canon(ev._pad(_encoding.decoded_data(b.cols[i])))
                                  for b in blocks]),
                        dev,
                    )
                    for i in ship
                )
                nulls = tuple(
                    jax.device_put(
                        np.stack([np.asarray(ev._pad(_encoding.decoded_nulls(b.cols[i]), True))
                                  for b in blocks]),
                        dev,
                    )
                    for i in nullable
                )
            out[did] = (data, nulls)
        note_blocking("device.pin:sharded_slabs")
        for leaf in jax.tree.leaves(out):
            leaf.block_until_ready()
        return out

    return cache.device_arrays(cache.blocks[0], sig, build)


def slab_assignment(caches, mesh) -> list[dict]:
    """Per-cache {device_id: block indices} over the flat mesh: honors the
    region cache's placement metadata (``owner_devices``, written by
    RegionColumnCache in sharded mode) and falls back to whole-region
    round-robin for caches without one (block caches, tests)."""
    devices = list(np.asarray(mesh.devices).reshape(-1))
    ids = {d.id for d in devices}
    out = []
    for r, cache in enumerate(caches):
        owners = getattr(cache, "owner_devices", None)
        if (owners is None or len(owners) != len(cache.blocks)
                or any(o not in ids for o in owners)):
            if len(caches) == 1:
                # a lone unplaced cache (plain block cache, cache_version
                # path): block-spread it — pinning a whole region on one
                # device while N-1 idle defeats the sharded program
                owners = [devices[b % len(devices)].id
                          for b in range(len(cache.blocks))]
            else:
                owners = [devices[r % len(devices)].id] * len(cache.blocks)
        assign: dict[int, list[int]] = {}
        for b, did in enumerate(owners):
            assign.setdefault(did, []).append(b)
        out.append(assign)
    return out


def device_slab_load(caches, mesh) -> dict[int, int]:
    """Slabs per device for a prospective batch, derived from
    :func:`slab_assignment` — THE one fold shared by the scheduler's
    padding-shed/occupancy metrics and the benches, so reported geometry
    can never diverge from what the launcher dispatches."""
    devices = list(np.asarray(mesh.devices).reshape(-1))
    load = {d.id: 0 for d in devices}
    for assign in slab_assignment(caches, mesh):
        for did, idxs in assign.items():
            load[did] += len(idxs)
    return load


def launch_xregion_sharded(ev: JaxDagEvaluator, caches, mesh: Mesh) -> XRegionPending:
    """ONE aggregation plan over R cached region images as ONE ``shard_map``
    program over EVERY device of ``mesh`` — the sharded twin of
    ``jax_eval.launch_xregion_cached``.

    Each (region, block) pair is a SLAB living on its owner device (the
    region column cache's placement: whole regions normally, block-spread
    for single huge regions).  Every device scans its local slabs with the
    same fused block step as the single-device path — per-slab ``n_valid``
    masks keep padding inert — accumulating partial states into a
    region-slot-segmented carry (capacity R×C).  Partial states then merge
    across devices with the ``_collective`` rules (`psum`/`pmin`/`pmax` over
    ICI; bitwise via gather+fold), the exact merge semantics the sharded
    evaluators above already use, and ONE packed pull serves every region.

    Raises ValueError on documented declines (non-aggregation plan, an
    aggregate with no mesh merge rule, unstable group dictionaries, empty
    cache); callers fall back to the single-device warm path per request.
    """
    from ..copr.jax_eval import xregion_specs

    _require_mesh_mergeable(ev.device_aggs)
    specs, group_cols, capacity = xregion_specs(ev, caches)
    flat = _flat_regions_mesh(mesh)
    devices = list(np.asarray(flat.devices).reshape(-1))
    by_id = {d.id: d for d in devices}
    N = len(devices)
    R = len(caches)
    ship = tuple(ev._ship_cols(group_cols))
    nullable = tuple(ev.nullable_cols)
    n_rows = ev.block_rows

    assigns = slab_assignment(caches, flat)
    per_dev_slabs = {d.id: 0 for d in devices}
    for assign in assigns:
        for did, idxs in assign.items():
            per_dev_slabs[did] += len(idxs)
    S = max(1, max(per_dev_slabs.values()))

    # encoded residency (copr/encoding.py): slab stacks mix blocks of
    # several regions on one device, so the whole batch must agree on one
    # encoding signature and RLE is excluded (run capacities differ per
    # image) — batch_plan decides and counts the decode-ship declines
    from ..copr import encoding as _encoding

    plans = _encoding.batch_plan(caches, list(ship), list(nullable),
                                 "mesh_sharded", allow_rle=False)
    enc = plans[0].sig if plans else None

    pins = [
        _slab_pins(ev, c, a, by_id, ship, nullable,
                   plan=plans[r] if plans else None)
        for r, (c, a) in enumerate(zip(caches, assigns))
    ]
    # zone-map pruning (docs/zone_maps.md): a pruned slab ships with
    # n_valid == 0 in the metadata, so its owner device scans it as pure
    # padding — the compile key, slab placement, and row offsets (global
    # row ids for first-row tracking) are untouched
    from ..copr import zone_maps as _zm

    region_keeps = []
    region_prunes = []
    for cache in caches:
        ps = _zm.PruneStats()
        region_keeps.append(
            _zm.prune_blocks(cache, ev.sel_rpns, path="mesh", stats=ps))
        region_prunes.append((ps.examined, ps.pruned))

    region_offsets = []
    for cache in caches:
        nv = np.array([b.n_valid for b in cache.blocks], dtype=np.int64)
        region_offsets.append(np.concatenate([[0], np.cumsum(nv)[:-1]]).astype(np.int64))

    # per-device shard assembly: concat each device's pinned slab stacks in
    # region-major order (matching the metadata below), zero-pad to S slabs.
    # All inputs are committed to the device, so the concat runs THERE —
    # the host never touches row data on the warm path.
    from ..copr.datatypes import EvalType

    ship_dtypes = [
        np.float64 if ev.schema[i][0] == EvalType.REAL else np.int64 for i in ship
    ]
    if enc is not None:
        # encoded lanes keep their narrow dtype (zero-pad slabs must match)
        ship_dtypes = [
            np.dtype(enc[j][1]) if enc[j][0] in ("bp", "code") else ship_dtypes[j]
            for j in range(len(ship))
        ]
    meta_region = np.zeros((N, S), dtype=np.int32)
    meta_nv = np.zeros((N, S), dtype=np.int64)
    meta_off = np.zeros((N, S), dtype=np.int64)
    shard_data: list = []
    shard_nulls: list = []
    for di, dev in enumerate(devices):
        did = dev.id
        parts_d: list = [[] for _ in ship]
        parts_n: list = [[] for _ in nullable]
        si = 0
        for r, cache in enumerate(caches):
            idxs = assigns[r].get(did)
            if not idxs:
                continue
            data, nulls = pins[r][did]
            for j in range(len(ship)):
                parts_d[j].append(data[j])
            for j in range(len(nullable)):
                parts_n[j].append(nulls[j])
            keep_r = region_keeps[r]
            for b in idxs:
                meta_region[di, si] = r
                meta_nv[di, si] = (
                    0 if keep_r is not None and not keep_r[b]
                    else cache.blocks[b].n_valid)
                meta_off[di, si] = region_offsets[r][b]
                si += 1
        pad = S - si

        def _cat(parts, dtype):
            if pad:
                parts = parts + [_zero_slab(dev, pad, n_rows, dtype)]
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        shard_data.append([_cat(parts_d[j], ship_dtypes[j]) for j in range(len(ship))])
        shard_nulls.append([_cat(parts_n[j], np.bool_) for j in range(len(nullable))])

    ns = NamedSharding(flat, P("regions"))
    ns_rep = NamedSharding(flat, P())
    col_data = tuple(
        jax.make_array_from_single_device_arrays(
            (N * S, n_rows), ns, [shard_data[di][j] for di in range(N)]
        )
        for j in range(len(ship))
    )
    col_nulls = tuple(
        jax.make_array_from_single_device_arrays(
            (N * S, n_rows), ns, [shard_nulls[di][j] for di in range(N)]
        )
        for j in range(len(nullable))
    )
    slab_region = jax.device_put(meta_region.reshape(N * S), ns)
    n_valids = jax.device_put(meta_nv.reshape(N * S), ns)
    offsets = jax.device_put(meta_off.reshape(N * S), ns)
    dl_arr = jax.device_put(
        np.array([s[1] for s in specs], dtype=np.int64).reshape(R, len(group_cols)),
        ns_rep,
    )
    ref_arr = jax.device_put(
        (np.stack([np.asarray(p.refs) for p in plans])
         if plans else np.zeros((R, len(ship)), dtype=np.int64)),
        ns_rep,
    )

    key = ("xshard", tuple(d.id for d in devices), S, R, capacity,
           ship, nullable, len(group_cols), enc)
    fn = ev._agg_fn_cache.get(key)
    if fn is None:
        device_aggs = ev.device_aggs
        sel_rpns = ev.sel_rpns
        track_first = bool(ev.group_rpns)
        cap_total = R * capacity
        in_specs = (
            tuple(P("regions") for _ in ship),
            tuple(P("regions") for _ in nullable),
            P("regions"), P("regions"), P("regions"), P(), P(),
        )

        @_smap(flat, in_specs, (P(), P()))
        def xfn(col_data, col_nulls, slab_region, n_valids, offsets, dl_arr,
                ref_arr):
            state = (
                jnp.full(cap_total, _NO_ROW, dtype=jnp.int64),
                tuple(da.init_carry(cap_total) for da in device_aggs),
            )

            def body(st, xs):
                cd, cn, r, nv, off = xs
                # per-slab in-kernel decode: the slab's region row of the
                # frame-of-reference matrix widens its bitpacked lanes
                cols = _build_cols(ship, nullable, cd, cn, n_rows, enc,
                                   None if enc is None else ref_arr[r])
                local = jnp.zeros(n_rows, dtype=jnp.int64)
                for k, gi in enumerate(group_cols):
                    codes, gnulls = cols[gi]
                    dlen = dl_arr[r, k]
                    local = local * (dlen + 1) + jnp.where(gnulls, dlen, codes)
                # region-slot-segmented gids: slab r's rows land in the
                # [r*capacity, (r+1)*capacity) segment window, so ONE fused
                # step accumulates every region's state side by side
                gids = r.astype(jnp.int64) * capacity + local
                return _fused_step(
                    sel_rpns, device_aggs, cap_total, n_rows, cols, nv, gids,
                    off, st, track_first=track_first,
                ), None

            state, _ = jax.lax.scan(
                body, state, (col_data, col_nulls, slab_region, n_valids, offsets)
            )
            first, carries = state
            # cross-device merge: a region's slabs may live on one device
            # (others contribute identity) or spread across several (a
            # block-sharded huge region) — the leaf-wise collective rules
            # cover both
            first = _collective("min", first, "regions")
            merged = tuple(
                tuple(
                    _collective(kind, leaf, "regions")
                    for kind, leaf in zip(_MERGE[da.op], c)
                )
                for da, c in zip(device_aggs, carries)
            )
            from ..copr.jax_eval import _pack_region_leaves

            leaves = [first] + jax.tree.leaves(merged)
            return _pack_region_leaves(leaves, R, capacity)  # (R, L*, cap)

        fn = _obs.timed_jit(jax.jit(xfn), "mesh.xshard", "mesh", ev.obs_sig)
        ev._agg_fn_cache[key] = fn
        xkeys = [k for k in ev._agg_fn_cache if isinstance(k, tuple)
                 and k and k[0] == "xshard"]
        while len(xkeys) > 16:
            ev._agg_fn_cache.pop(xkeys.pop(0))

    packed = fn(col_data, col_nulls, slab_region, n_valids, offsets, dl_arr,
                ref_arr)
    pending = XRegionPending(ev, specs, capacity, packed, order=None,
                             prunes=region_prunes)
    # observatory encoding label for the riders' profiles
    pending.obs_encoding = "encoded" if plans else "plain"
    return pending


def run_xregion_sharded(ev: JaxDagEvaluator, caches, mesh: Mesh):
    """launch + finalize in one step (tests / single-batch callers)."""
    return launch_xregion_sharded(ev, caches, mesh).finalize()


class MeshServingRunner:
    """Endpoint-facing mesh execution of an eligible aggregation DAG.

    The scale-out analog of region sharding (``raftstore/src/coprocessor/
    split_check/``): ``Endpoint`` hands this runner the same MVCC scan source
    the single-device path uses; rows are decoded on host into super-blocks,
    sharded over the ``regions`` axis, and the group state stays sharded over
    ``groups`` between blocks.  Group-id assignment and finalization reuse the
    single-device evaluator's host code, so the encoded ``SelectResponse`` is
    byte-identical to the one-device (and CPU) answer.
    """

    def __init__(self, dag: DagRequest, mesh: Mesh, rows_per_shard: int = 1024):
        from math import gcd

        from ..copr.jax_eval import _analyze

        # eligibility first, before any evaluator construction: the rejection
        # path must stay cheap (Endpoint probes every device-eligible DAG)
        if _analyze(dag).agg is None:
            raise ValueError("mesh serving requires an aggregation DAG")
        self.mesh = mesh
        self.rows_per_shard = rows_per_shard
        self.n_groups = mesh.shape["groups"]
        # smallest multiple of n_groups >= 16 (doubling alone never reaches
        # divisibility for a non-power-of-two groups axis)
        cap = 16 * self.n_groups // gcd(16, self.n_groups)
        self.sharded = ShardedDagEvaluator(dag, mesh, rows_per_shard, capacity=cap)
        self.total_rows = self.sharded.total_rows
        # decode/gid/finalize machinery at super-block granularity
        self.decode_ev = JaxDagEvaluator(dag, block_rows=self.total_rows)
        # observatory profile key: cold mesh serves record under the same
        # plan sig as every other path (docs/observatory.md)
        self.obs_sig = self.decode_ev.obs_sig
        self.obs_desc = self.decode_ev.obs_desc

    def _grow(self, state, n_groups: int):
        from ..copr.jax_eval import _grow_carry

        cap = self.sharded.capacity
        while n_groups > cap:
            cap *= 2
        first, carries = jax.tree.map(np.asarray, state)
        new_first = np.full(cap, _NO_ROW, dtype=np.int64)
        new_first[: len(first)] = first
        new_carries = tuple(
            _grow_carry(da, c, cap)
            for da, c in zip(self.sharded.ev.device_aggs, carries)
        )
        self.sharded = ShardedDagEvaluator(
            self.decode_ev.dag, self.mesh, self.rows_per_shard, capacity=cap
        )
        return (jnp.asarray(new_first), new_carries)

    def run(self, source, cache=None) -> "SelectResponse":
        """Same signature as JaxDagEvaluator.run; the block cache is a
        single-device HBM concept and is ignored here (Endpoint routes cached
        requests down the single-device path)."""
        from ..copr.groupby import GroupDict
        from ..copr.jax_eval import _ZERO_GIDS

        ev = self.decode_ev
        total = self.total_rows
        groups = GroupDict()
        state = self.sharded.init_state()
        block_base = 0
        for cols, n_valid in ev._decode_blocks(source):
            if ev.group_rpns:
                gids, n_groups = ev._assign_gids(cols, n_valid, groups)
                if n_groups > self.sharded.capacity:
                    state = self._grow(state, n_groups)
            else:
                gids = _ZERO_GIDS.setdefault(total, np.zeros(total, dtype=np.int32))
            need = set(ev.device_cols) | set(ev.nullable_cols)
            columns = {
                i: (ev._pad(cols[i].data), ev._pad(cols[i].nulls, True))
                for i in need
            }
            col_data, col_nulls, valid = _marshal_block(ev, columns, n_valid, total)
            state = self.sharded.step(col_data, col_nulls, valid, gids, state,
                                      block_base=block_base)
            block_base += total
        n_slots = len(groups) if ev.group_rpns else 1
        state_np = jax.tree.map(np.asarray, state)
        resp = ev._finalize_agg(state_np, n_slots, lambda r: groups.rows[r])
        resp._obs_path = "mesh"  # observatory path marker
        return resp
