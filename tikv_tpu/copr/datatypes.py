"""Columnar type system for the query engine.

Re-expression of ``tidb_query_datatype``: the ``EvalType`` lattice
(``src/def/eval_type.rs:11``), ``FieldType``, and the columnar containers
(``src/codec/data_type/vector.rs`` ``VectorValue``/``ChunkedVec*``).

TPU-first design decisions:

* Every numeric column is a dense numpy array + a boolean null mask — the
  exact layout device transfer wants (two host buffers → two device arrays),
  instead of the reference's per-type chunked vectors.
* ``DECIMAL`` is fixed-point: int64 scaled by ``10^frac`` (frac carried on the
  FieldType).  Exact, orderable, and vectorizes onto integer lanes.
* ``BYTES`` columns are numpy object arrays on host.  For device execution the
  group-by path dictionary-encodes them to int32 codes first (see jax_eval).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

from . import datum as datum_mod


class EvalType(enum.Enum):
    INT = "int"
    REAL = "real"
    DECIMAL = "decimal"
    BYTES = "bytes"
    DATETIME = "datetime"  # packed int64 (μs since epoch)
    DURATION = "duration"  # int64 nanoseconds
    JSON = "json"
    # Enum/Set (eval_type.rs:11 lists both as first-class eval types).
    # ENUM columns hold the 1-based element index (0 = MySQL's invalid '')
    # — already a dense dictionary code, which is exactly the device layout;
    # SET columns hold the u64 element bitmask.
    ENUM = "enum"
    SET = "set"


# MySQL type codes (subset; tidb_query_datatype/src/def/field_type.rs)
class FieldTypeTp(enum.IntEnum):
    TINY = 1
    SHORT = 2
    LONG = 3
    FLOAT = 4
    DOUBLE = 5
    NULL = 6
    TIMESTAMP = 7
    LONGLONG = 8
    INT24 = 9
    DATE = 10
    DURATION = 11
    DATETIME = 12
    JSON = 245
    NEW_DECIMAL = 246
    ENUM = 247
    SET = 248
    BLOB = 252
    VAR_STRING = 253
    STRING = 254


UNSIGNED_FLAG = 1 << 5
NOT_NULL_FLAG = 1 << 0
PRI_KEY_FLAG = 1 << 1


_TP_TO_EVAL = {
    FieldTypeTp.TINY: EvalType.INT,
    FieldTypeTp.SHORT: EvalType.INT,
    FieldTypeTp.LONG: EvalType.INT,
    FieldTypeTp.LONGLONG: EvalType.INT,
    FieldTypeTp.INT24: EvalType.INT,
    FieldTypeTp.FLOAT: EvalType.REAL,
    FieldTypeTp.DOUBLE: EvalType.REAL,
    FieldTypeTp.NEW_DECIMAL: EvalType.DECIMAL,
    FieldTypeTp.TIMESTAMP: EvalType.DATETIME,
    FieldTypeTp.DATE: EvalType.DATETIME,
    FieldTypeTp.DATETIME: EvalType.DATETIME,
    FieldTypeTp.DURATION: EvalType.DURATION,
    FieldTypeTp.JSON: EvalType.JSON,
    FieldTypeTp.ENUM: EvalType.ENUM,
    FieldTypeTp.SET: EvalType.SET,
    FieldTypeTp.BLOB: EvalType.BYTES,
    FieldTypeTp.VAR_STRING: EvalType.BYTES,
    FieldTypeTp.STRING: EvalType.BYTES,
}


@dataclass
class FieldType:
    tp: FieldTypeTp = FieldTypeTp.LONGLONG
    flag: int = 0
    flen: int = -1
    decimal: int = 0  # frac digits for NEW_DECIMAL
    collation: str = "binary"
    elems: tuple = ()  # element names (bytes) for ENUM/SET

    @property
    def eval_type(self) -> EvalType:
        return _TP_TO_EVAL[self.tp]

    @property
    def is_unsigned(self) -> bool:
        return bool(self.flag & UNSIGNED_FLAG)

    @classmethod
    def int64(cls, unsigned: bool = False) -> "FieldType":
        return cls(FieldTypeTp.LONGLONG, UNSIGNED_FLAG if unsigned else 0)

    @classmethod
    def double(cls) -> "FieldType":
        return cls(FieldTypeTp.DOUBLE)

    @classmethod
    def decimal_type(cls, frac: int) -> "FieldType":
        return cls(FieldTypeTp.NEW_DECIMAL, decimal=frac)

    @classmethod
    def varchar(cls) -> "FieldType":
        return cls(FieldTypeTp.VAR_STRING)

    @classmethod
    def enum_type(cls, elems: list[bytes]) -> "FieldType":
        if len(elems) > 65535:
            raise ValueError("ENUM supports at most 65535 elements")
        return cls(FieldTypeTp.ENUM, elems=tuple(elems))

    @classmethod
    def set_type(cls, elems: list[bytes]) -> "FieldType":
        if len(elems) > 64:
            raise ValueError("SET supports at most 64 elements")
        return cls(FieldTypeTp.SET, elems=tuple(elems))


@dataclass
class ColumnInfo:
    """Schema entry for a table/index scan (tipb ColumnInfo equivalent)."""

    col_id: int
    ftype: FieldType
    is_pk_handle: bool = False
    default_value: object = None


class Column:
    """One columnar vector: dense values + null mask (True = NULL).

    The reference keeps NULLs implicit per chunked vec; here the mask is an
    explicit numpy bool array so that it ships to the device as-is and
    selection stays a mask operation (never a gather — static shapes).

    BYTES columns may be **dictionary-encoded** (Arrow-style): ``data`` holds
    int64 codes into ``dictionary`` (an object array of bytes).  This is the
    TPU-friendly representation — group-bys over such columns become dense
    segment ids with no per-row Python.
    """

    __slots__ = ("eval_type", "data", "nulls", "frac", "dictionary")

    def __init__(
        self,
        eval_type: EvalType,
        data,
        nulls: np.ndarray,
        frac: int = 0,
        dictionary: np.ndarray | None = None,
    ):
        self.eval_type = eval_type
        self.data = data
        self.nulls = nulls
        self.frac = frac  # decimal scale
        self.dictionary = dictionary

    @property
    def is_dict_encoded(self) -> bool:
        return self.dictionary is not None

    def decoded(self) -> "Column":
        """Materialize dictionary codes back into an object array.

        ENUM/SET columns are *not* decoded here: their dictionary is a name
        table and their logical value is the index/bitmask itself (use
        ``enum_names``/``set_names`` for the string cast)."""
        if self.dictionary is None or self.eval_type in (EvalType.ENUM, EvalType.SET):
            return self
        return Column(self.eval_type, self.dictionary[self.data], self.nulls, self.frac)

    def __len__(self) -> int:
        return len(self.data)

    @classmethod
    def from_values(cls, eval_type: EvalType, values: list, frac: int = 0) -> "Column":
        """Build from a python list, None meaning NULL."""
        n = len(values)
        nulls = np.array([v is None for v in values], dtype=bool)
        if eval_type == EvalType.SET:
            # u64 bitmask: bit 63 (a 64-element SET) must be representable
            data = np.array([0 if v is None else v for v in values], dtype=np.uint64)
        elif eval_type in (
            EvalType.INT,
            EvalType.DATETIME,
            EvalType.DURATION,
            EvalType.DECIMAL,
            EvalType.ENUM,
        ):
            data = np.array([0 if v is None else v for v in values], dtype=np.int64)
        elif eval_type == EvalType.REAL:
            data = np.array([0.0 if v is None else v for v in values], dtype=np.float64)
        elif eval_type in (EvalType.BYTES, EvalType.JSON):
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = b"" if v is None else v
        else:
            raise ValueError(f"unsupported eval type {eval_type}")
        return cls(eval_type, data, nulls, frac)

    def to_values(self) -> list:
        col = self.decoded()
        return [None if null else _pyval(col.eval_type, v) for v, null in zip(col.data, col.nulls)]

    def take(self, indices: np.ndarray) -> "Column":
        return Column(self.eval_type, self.data[indices], self.nulls[indices], self.frac, self.dictionary)

    def slice(self, start: int, stop: int) -> "Column":
        return Column(self.eval_type, self.data[start:stop], self.nulls[start:stop], self.frac, self.dictionary)

    @classmethod
    def concat(cls, cols: list["Column"]) -> "Column":
        assert cols
        dictionary = None
        if cols[0].eval_type in (EvalType.ENUM, EvalType.SET):
            # codes are only meaningful against one shared name table
            dictionary = cols[0].dictionary
            for c in cols[1:]:
                if not np.array_equal(c.dictionary, dictionary):
                    raise ValueError("cannot concat ENUM/SET columns with different elems")
        elif any(c.is_dict_encoded for c in cols):
            cols = [c.decoded() for c in cols]
        return cls(
            cols[0].eval_type,
            np.concatenate([c.data for c in cols]),
            np.concatenate([c.nulls for c in cols]),
            cols[0].frac,
            dictionary,
        )

    def datum_at(self, i: int) -> tuple[int, object]:
        """(flag, value) pair for datum encoding of row ``i``."""
        if self.nulls[i]:
            return datum_mod.NIL_FLAG, None
        if self.eval_type == EvalType.INT:
            return datum_mod.INT_FLAG, int(self.data[i])
        if self.eval_type == EvalType.REAL:
            return datum_mod.FLOAT_FLAG, float(self.data[i])
        if self.eval_type == EvalType.DECIMAL:
            return datum_mod.DECIMAL_FLAG, (int(self.data[i]), self.frac)
        if self.eval_type in (EvalType.BYTES, EvalType.JSON):
            flag = datum_mod.JSON_FLAG if self.eval_type == EvalType.JSON else datum_mod.BYTES_FLAG
            if self.dictionary is not None:
                return flag, bytes(self.dictionary[self.data[i]])
            return flag, bytes(self.data[i])
        if self.eval_type == EvalType.DURATION:
            return datum_mod.DURATION_FLAG, int(self.data[i])
        if self.eval_type in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
            return datum_mod.UINT_FLAG, int(self.data[i])
        raise ValueError(f"unsupported eval type {self.eval_type}")


def enum_dictionary(elems: tuple) -> np.ndarray:
    """Name dictionary for an ENUM column: slot 0 is MySQL's invalid ''."""
    d = np.empty(len(elems) + 1, dtype=object)
    d[0] = b""
    for i, e in enumerate(elems):
        d[i + 1] = bytes(e)
    return d


def enum_column(indices: list, elems: tuple) -> Column:
    """ENUM column: int codes + name dictionary — device-ready as-is."""
    col = Column.from_values(EvalType.ENUM, indices)
    col.dictionary = enum_dictionary(elems)
    return col


def set_dictionary(elems: tuple) -> np.ndarray:
    """Name dictionary for a SET column: slot b = name of bitmask bit b."""
    return np.array([bytes(e) for e in elems], dtype=object)


def set_column(masks: list, elems: tuple) -> Column:
    col = Column.from_values(EvalType.SET, masks)
    col.dictionary = set_dictionary(elems)
    return col


def enum_names(col: Column) -> Column:
    """Materialize an ENUM column's names as a BYTES column (cast enum→string)."""
    assert col.eval_type == EvalType.ENUM and col.dictionary is not None
    # out-of-range codes are MySQL's invalid '' (slot 0), not the last element
    idx = np.where((col.data >= 0) & (col.data < len(col.dictionary)), col.data, 0)
    return Column(EvalType.BYTES, col.dictionary[idx], col.nulls.copy())


def set_names(col: Column) -> Column:
    """Materialize a SET column as comma-joined names (cast set→string)."""
    assert col.eval_type == EvalType.SET and col.dictionary is not None
    elems = col.dictionary
    out = np.empty(len(col.data), dtype=object)
    for i, mask in enumerate(col.data):
        m = int(mask)
        out[i] = b",".join(elems[b] for b in range(len(elems)) if m >> b & 1)
    return Column(EvalType.BYTES, out, col.nulls.copy())


def attach_schema_dictionary(info: "ColumnInfo", col: Column) -> Column:
    """Attach the ENUM/SET name table declared by the schema entry."""
    if col.eval_type == EvalType.ENUM:
        col.dictionary = enum_dictionary(info.ftype.elems)
    elif col.eval_type == EvalType.SET:
        col.dictionary = set_dictionary(info.ftype.elems)
    return col


def typed_column(info: "ColumnInfo", values: list) -> Column:
    """Column.from_values typed by a schema entry (shared by the v1 and v2
    row decoders so the construction rule lives in exactly one place)."""
    col = Column.from_values(info.ftype.eval_type, values, info.ftype.decimal)
    return attach_schema_dictionary(info, col)


def _pyval(et: EvalType, v):
    if et == EvalType.REAL:
        return float(v)
    if et in (EvalType.BYTES, EvalType.JSON):
        return bytes(v)
    return int(v)


@dataclass
class Chunk:
    """A batch of columns with a shared logical row selection.

    ``logical_rows`` mirrors BatchExecuteResult.logical_rows
    (tidb_query_executors/src/interface.rs:144): executors filter by updating
    the selection, not by physically compacting — same trick the TPU path uses
    with masks.
    """

    columns: list[Column]
    logical_rows: np.ndarray  # int indices into the physical rows

    @property
    def num_rows(self) -> int:
        return len(self.logical_rows)

    @classmethod
    def full(cls, columns: list[Column]) -> "Chunk":
        n = len(columns[0]) if columns else 0
        return cls(columns, np.arange(n))

    def compact(self) -> "Chunk":
        """Physically apply the selection."""
        cols = [c.take(self.logical_rows) for c in self.columns]
        return Chunk(cols, np.arange(len(self.logical_rows)))
