"""MySQL DATETIME/DURATION value semantics.

Re-expression of ``tidb_query_datatype/src/codec/mysql/{time/,duration.rs}``:
DATETIME is the packed-u64 layout TiDB uses on the wire —

    ((year*13 + month) << 46) | (day << 41) | (hour << 36)
      | (minute << 30) | (second << 24) | microsecond

which keeps chronological order == integer order, so packed times flow
through the INT comparison/min/max kernels (and the TPU path) unchanged.
DURATION is signed nanoseconds.  Field-extraction kernels are pure bit
arithmetic — vectorizable on both backends, registered into the shared
kernel table.
"""

from __future__ import annotations

from .kernels import KERNELS, _reg

_MICRO_BITS = 24
_SECOND_BITS = 6
_MINUTE_BITS = 6
_HOUR_BITS = 5
_DAY_BITS = 5

_SEC_SHIFT = _MICRO_BITS
_MIN_SHIFT = _SEC_SHIFT + _SECOND_BITS
_HOUR_SHIFT = _MIN_SHIFT + _MINUTE_BITS
_DAY_SHIFT = _HOUR_SHIFT + _HOUR_BITS
_YM_SHIFT = _DAY_SHIFT + _DAY_BITS  # == 46


def pack_datetime(
    year: int, month: int, day: int, hour: int = 0, minute: int = 0,
    second: int = 0, micro: int = 0,
) -> int:
    # month/day 0 are legal: MySQL's zero date '0000-00-00' and zero-part
    # dates like '2021-00-00' are representable values (time/mod.rs)
    if not (0 <= month <= 12 and 0 <= day <= 31):
        raise ValueError(f"invalid date {year}-{month}-{day}")
    if not (0 <= hour < 24 and 0 <= minute < 60 and 0 <= second < 60 and 0 <= micro < 1_000_000):
        raise ValueError("invalid time component")
    ym = year * 13 + month
    return (
        (ym << _YM_SHIFT)
        | (day << _DAY_SHIFT)
        | (hour << _HOUR_SHIFT)
        | (minute << _MIN_SHIFT)
        | (second << _SEC_SHIFT)
        | micro
    )


def unpack_datetime(packed: int) -> tuple[int, int, int, int, int, int, int]:
    ym = packed >> _YM_SHIFT
    return (
        ym // 13,
        ym % 13,
        (packed >> _DAY_SHIFT) & 0x1F,
        (packed >> _HOUR_SHIFT) & 0x1F,
        (packed >> _MIN_SHIFT) & 0x3F,
        (packed >> _SEC_SHIFT) & 0x3F,
        packed & 0xFFFFFF,
    )


def parse_datetime(text: str) -> int:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' → packed."""
    date_part, _, time_part = text.strip().partition(" ")
    y, m, d = (int(x) for x in date_part.split("-"))
    hh = mm = ss = micro = 0
    if time_part:
        hms, _, frac = time_part.partition(".")
        hh, mm, ss = (int(x) for x in hms.split(":"))
        if frac:
            micro = int(frac.ljust(6, "0")[:6])
    return pack_datetime(y, m, d, hh, mm, ss, micro)


def format_datetime(packed: int) -> str:
    y, m, d, hh, mm, ss, micro = unpack_datetime(packed)
    base = f"{y:04d}-{m:02d}-{d:02d} {hh:02d}:{mm:02d}:{ss:02d}"
    return f"{base}.{micro:06d}" if micro else base


# -- duration ---------------------------------------------------------------

NANOS_PER_SEC = 1_000_000_000


def duration_nanos(hours: int = 0, minutes: int = 0, seconds: int = 0, micro: int = 0, neg: bool = False) -> int:
    total = ((hours * 60 + minutes) * 60 + seconds) * NANOS_PER_SEC + micro * 1000
    return -total if neg else total


def parse_duration(text: str) -> int:
    text = text.strip()
    neg = text.startswith("-")
    if neg:
        text = text[1:]
    hms, _, frac = text.partition(".")
    parts = [int(x) for x in hms.split(":")]
    # MySQL left-aligns: '11:30' is HH:MM (11:30:00), not MM:SS
    while len(parts) < 3:
        parts.append(0)
    micro = int(frac.ljust(6, "0")[:6]) if frac else 0
    return duration_nanos(parts[0], parts[1], parts[2], micro, neg)


def format_duration(nanos: int) -> str:
    neg = nanos < 0
    nanos = abs(nanos)
    total_sec, sub = divmod(nanos, NANOS_PER_SEC)
    hh, rem = divmod(total_sec, 3600)
    mm, ss = divmod(rem, 60)
    micro = sub // 1000
    out = f"{'-' if neg else ''}{hh:02d}:{mm:02d}:{ss:02d}"
    return f"{out}.{micro:06d}" if micro else out


# -- field-extraction kernels (device-eligible: pure int arithmetic) --------

def _dt_field(name: str, fn):
    @_reg(name, 1, "int")
    def kernel(xp, a, _fn=fn):
        ad, an = a
        return _fn(xp, ad), an

    return kernel


_dt_field("year", lambda xp, v: (v >> _YM_SHIFT) // 13)
_dt_field("month", lambda xp, v: (v >> _YM_SHIFT) % 13)
_dt_field("day", lambda xp, v: (v >> _DAY_SHIFT) & 0x1F)
_dt_field("hour", lambda xp, v: (v >> _HOUR_SHIFT) & 0x1F)
_dt_field("minute", lambda xp, v: (v >> _MIN_SHIFT) & 0x3F)
_dt_field("second", lambda xp, v: (v >> _SEC_SHIFT) & 0x3F)
_dt_field("micro_second", lambda xp, v: v & 0xFFFFFF)


@_reg("duration_hours", 1, "int")
def _duration_hours(xp, a):
    ad, an = a
    return xp.abs(ad) // (3600 * NANOS_PER_SEC), an


# -- calendar kernels (impl_time.rs: weekday/dayofyear/quarter/to_days…) ----

import datetime as _dt

from .kernels import _bytes_op, _reg_nullable_int


def _ymd(packed: int):
    y, m, d, *_ = unpack_datetime(int(packed))
    return y, m, d


def _as_date(packed: int) -> _dt.date:
    y, m, d = _ymd(packed)
    return _dt.date(y, m, d)


def _nullable_dt_int(name, fn):
    """DATETIME→INT kernel where invalid dates (e.g. zero date) yield NULL."""

    def wrapped(v):
        try:
            return fn(int(v))
        except ValueError:
            return None

    _reg_nullable_int(name, 1, wrapped)


_nullable_dt_int("day_of_week", lambda p: _as_date(p).toordinal() % 7 + 1)  # 1=Sunday
_nullable_dt_int("week_day", lambda p: _as_date(p).weekday())  # 0=Monday
_nullable_dt_int("day_of_year", lambda p: _as_date(p).timetuple().tm_yday)
_nullable_dt_int("quarter", lambda p: (_ymd(p)[1] + 2) // 3)


def _last_dom(y: int, m: int) -> int:
    """Last day of month (shared by last_day and the month-arithmetic
    clamp); December 9999 must not construct year 10000."""
    if m == 0:
        raise ValueError("zero month has no last day")  # LAST_DAY → NULL
    if m == 12:
        return 31
    return (_dt.date(y, m + 1, 1) - _dt.timedelta(days=1)).day
_nullable_dt_int("to_days", lambda p: _as_date(p).toordinal() + 365)
_nullable_dt_int(
    "last_day",
    lambda p: pack_datetime(_ymd(p)[0], _ymd(p)[1], _last_dom(_ymd(p)[0], _ymd(p)[1])),
)


def _from_days(n):
    n = int(n) - 365
    if n < 1:
        return None
    d = _dt.date.fromordinal(n)
    return pack_datetime(d.year, d.month, d.day)


_reg_nullable_int("from_days", 1, _from_days)


def _datediff(a, b):
    try:
        return (_as_date(a) - _as_date(b)).days
    except ValueError:
        return None


_reg_nullable_int("date_diff", 2, _datediff)


# -- DATE_FORMAT / STR_TO_DATE (impl_time.rs date_format; the %-specifier
# table is MySQL's own) ------------------------------------------------------

_MONTHS = ["January", "February", "March", "April", "May", "June", "July",
           "August", "September", "October", "November", "December"]
_DAYS = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday", "Sunday"]


def date_format(packed: int, fmt: str) -> str:
    y, mo, d, hh, mi, ss, us = unpack_datetime(packed)
    date = _dt.date(y, mo, d)
    h12 = hh % 12 or 12
    out = []
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        i += 1
        if i >= len(fmt):
            out.append("%")
            break
        s = fmt[i]
        i += 1
        if s == "Y":
            out.append(f"{y:04d}")
        elif s == "y":
            out.append(f"{y % 100:02d}")
        elif s == "m":
            out.append(f"{mo:02d}")
        elif s == "c":
            out.append(str(mo))
        elif s == "d":
            out.append(f"{d:02d}")
        elif s == "e":
            out.append(str(d))
        elif s == "H":
            out.append(f"{hh:02d}")
        elif s == "k":
            out.append(str(hh))
        elif s in ("h", "I"):
            out.append(f"{h12:02d}")
        elif s == "l":
            out.append(str(h12))
        elif s == "i":
            out.append(f"{mi:02d}")
        elif s in ("s", "S"):
            out.append(f"{ss:02d}")
        elif s == "f":
            out.append(f"{us:06d}")
        elif s == "p":
            out.append("AM" if hh < 12 else "PM")
        elif s == "r":
            out.append(f"{h12:02d}:{mi:02d}:{ss:02d} " + ("AM" if hh < 12 else "PM"))
        elif s == "T":
            out.append(f"{hh:02d}:{mi:02d}:{ss:02d}")
        elif s == "M":
            out.append(_MONTHS[mo - 1])
        elif s == "b":
            out.append(_MONTHS[mo - 1][:3])
        elif s == "W":
            out.append(_DAYS[date.weekday()])
        elif s == "a":
            out.append(_DAYS[date.weekday()][:3])
        elif s == "j":
            out.append(f"{date.timetuple().tm_yday:03d}")
        elif s == "w":
            out.append(str(date.toordinal() % 7))  # 0=Sunday
        elif s in ("u",):
            # %u: week 1..53, Monday-start, ISO-like (mode 1)
            out.append(f"{date.isocalendar()[1]:02d}")
        elif s in ("V", "v", "U", "X", "x"):
            # week-mode specifiers: %v/%x are ISO (mode 3); %U/%V/%X
            # (Sunday-start modes) approximate with the Sunday-week count
            if s in ("v", "V"):
                out.append(f"{date.isocalendar()[1]:02d}")
            elif s in ("x", "X"):
                out.append(f"{date.isocalendar()[0]:04d}")
            else:  # %U: Sunday-start week 0..53
                jan1 = _dt.date(y, 1, 1)
                # days from week-start: Sunday jan1 must count as 7, not 0
                off = jan1.toordinal() % 7 or 7
                out.append(f"{(date.timetuple().tm_yday + off - 1) // 7:02d}")
        elif s == "%":
            out.append("%")
        else:
            out.append(s)  # MySQL: unknown specifier passes through
    return "".join(out)


def _k_date_format(v, fmt):
    try:
        return date_format(int(v), fmt.decode("utf-8", "replace")).encode()
    except (ValueError, IndexError):
        return None


_bytes_op("date_format", 2, "bytes")(_k_date_format)
_bytes_op("month_name", 1, "bytes")(
    lambda v: _MONTHS[unpack_datetime(int(v))[1] - 1].encode()
    if 1 <= unpack_datetime(int(v))[1] <= 12
    else None
)
def _k_day_name(v):
    try:
        return _DAYS[_as_date(int(v)).weekday()].encode()
    except ValueError:
        return None  # zero/invalid date -> NULL, like the sibling kernels


_bytes_op("day_name", 1, "bytes")(_k_day_name)


def str_to_date(text: str, fmt: str) -> int | None:
    """Inverse of date_format for the numeric/name specifiers MySQL's
    STR_TO_DATE accepts; None on mismatch (MySQL returns NULL)."""
    vals = {"y": 0, "mo": 1, "d": 1, "hh": 0, "mi": 0, "ss": 0, "us": 0}
    ti = 0
    fi = 0
    try:
        while fi < len(fmt):
            c = fmt[fi]
            if c != "%":
                if ti >= len(text) or text[ti] != c:
                    return None
                ti += 1
                fi += 1
                continue
            fi += 1
            s = fmt[fi]
            fi += 1

            def num(maxlen):
                nonlocal ti
                j = ti
                while j < len(text) and j - ti < maxlen and text[j].isdigit():
                    j += 1
                if j == ti:
                    raise ValueError
                v = int(text[ti:j])
                ti = j
                return v

            if s == "Y":
                vals["y"] = num(4)
            elif s == "y":
                v = num(2)
                vals["y"] = 2000 + v if v < 70 else 1900 + v
            elif s in ("m", "c"):
                vals["mo"] = num(2)
            elif s in ("d", "e"):
                vals["d"] = num(2)
            elif s in ("H", "k", "h", "I", "l"):
                vals["hh"] = num(2)
            elif s == "i":
                vals["mi"] = num(2)
            elif s in ("s", "S"):
                vals["ss"] = num(2)
            elif s == "f":
                j = ti
                while j < len(text) and j - ti < 6 and text[j].isdigit():
                    j += 1
                vals["us"] = int(text[ti:j].ljust(6, "0")) if j > ti else 0
                ti = j
            elif s == "b":
                for k, name in enumerate(_MONTHS):
                    if text[ti : ti + 3].lower() == name[:3].lower():
                        vals["mo"] = k + 1
                        ti += 3
                        break
                else:
                    return None
            elif s == "M":
                for k, name in enumerate(_MONTHS):
                    if text[ti : ti + len(name)].lower() == name.lower():
                        vals["mo"] = k + 1
                        ti += len(name)
                        break
                else:
                    return None
            elif s == "%":
                if ti >= len(text) or text[ti] != "%":
                    return None
                ti += 1
            else:
                return None
        _dt.date(vals["y"], vals["mo"], vals["d"])  # reject Feb 31 etc.
        return pack_datetime(
            vals["y"], vals["mo"], vals["d"], vals["hh"], vals["mi"], vals["ss"], vals["us"]
        )
    except (ValueError, IndexError):
        return None


def _k_str_to_date(raw, fmt):
    return str_to_date(raw.decode("utf-8", "replace"), fmt.decode("utf-8", "replace"))


_reg_nullable_int("str_to_date", 2, _k_str_to_date)


# -- interval arithmetic + unix-timestamp family (impl_time.rs date_add /
# date_sub / unix_timestamp / from_unixtime) --------------------------------

_INTERVAL_UNITS = {
    "MICROSECOND", "SECOND", "MINUTE", "HOUR", "DAY", "WEEK",
    "MONTH", "QUARTER", "YEAR",
}


def date_add(packed: int, n: int, unit: str):
    """DATE_ADD/DATE_SUB (negative n).  Returns None (SQL NULL) when the
    result leaves MySQL's supported range, like the reference."""
    unit = unit.upper()
    if unit not in _INTERVAL_UNITS:
        raise ValueError(f"unknown interval unit {unit!r}")
    y, mo, d, hh, mi, ss, us = unpack_datetime(packed)
    try:
        base = _dt.datetime(y, mo, d, hh, mi, ss, us)
    except ValueError:
        return None
    if unit in ("YEAR", "QUARTER", "MONTH"):
        months = n * {"YEAR": 12, "QUARTER": 3, "MONTH": 1}[unit]
        total = (base.year * 12 + base.month - 1) + months
        ny, nm = divmod(total, 12)
        nm += 1
        if not 1 <= ny <= 9999:
            return None
        try:
            # clamp the day to the target month's length (MySQL rule)
            base = base.replace(year=ny, month=nm, day=min(base.day, _last_dom(ny, nm)))
        except (ValueError, OverflowError):
            return None
    else:
        kw = {
            "MICROSECOND": "microseconds", "SECOND": "seconds",
            "MINUTE": "minutes", "HOUR": "hours", "DAY": "days", "WEEK": "weeks",
        }[unit]
        try:
            base = base + _dt.timedelta(**{kw: n})
        except (OverflowError, ValueError):
            return None
    if not 1 <= base.year <= 9999:
        return None
    return pack_datetime(
        base.year, base.month, base.day, base.hour, base.minute, base.second,
        base.microsecond,
    )


def _k_date_add(v, n, unit):
    return date_add(int(v), int(n), unit.decode("utf-8", "replace"))


def _k_date_sub(v, n, unit):
    return date_add(int(v), -int(n), unit.decode("utf-8", "replace"))


_reg_nullable_int("date_add", 3, _k_date_add)
_reg_nullable_int("date_sub", 3, _k_date_sub)

_EPOCH = _dt.datetime(1970, 1, 1)
# MySQL TIMESTAMP cap second is 2038-01-19 03:14:07 with ANY microseconds
_TS_MAX = _dt.datetime(2038, 1, 19, 3, 14, 7, 999999)


def _k_unix_timestamp(v):
    """UNIX_TIMESTAMP(dt): seconds since epoch, 0 outside the TIMESTAMP
    range (MySQL semantics; session timezone = UTC here)."""
    y, mo, d, hh, mi, ss, us = unpack_datetime(int(v))
    try:
        t = _dt.datetime(y, mo, d, hh, mi, ss, us)
    except ValueError:
        return 0
    if t < _EPOCH or t > _TS_MAX:
        return 0
    return int((t - _EPOCH).total_seconds())


_reg_nullable_int("unix_timestamp", 1, _k_unix_timestamp)


def _k_from_unixtime(n):
    n = int(n)
    if n < 0 or n > int((_TS_MAX - _EPOCH).total_seconds()):
        return None
    t = _EPOCH + _dt.timedelta(seconds=n)
    return pack_datetime(t.year, t.month, t.day, t.hour, t.minute, t.second)


_reg_nullable_int("from_unixtime", 1, _k_from_unixtime)
