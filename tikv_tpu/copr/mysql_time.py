"""MySQL DATETIME/DURATION value semantics.

Re-expression of ``tidb_query_datatype/src/codec/mysql/{time/,duration.rs}``:
DATETIME is the packed-u64 layout TiDB uses on the wire —

    ((year*13 + month) << 46) | (day << 41) | (hour << 36)
      | (minute << 30) | (second << 24) | microsecond

which keeps chronological order == integer order, so packed times flow
through the INT comparison/min/max kernels (and the TPU path) unchanged.
DURATION is signed nanoseconds.  Field-extraction kernels are pure bit
arithmetic — vectorizable on both backends, registered into the shared
kernel table.
"""

from __future__ import annotations

from .kernels import KERNELS, _reg

_MICRO_BITS = 24
_SECOND_BITS = 6
_MINUTE_BITS = 6
_HOUR_BITS = 5
_DAY_BITS = 5

_SEC_SHIFT = _MICRO_BITS
_MIN_SHIFT = _SEC_SHIFT + _SECOND_BITS
_HOUR_SHIFT = _MIN_SHIFT + _MINUTE_BITS
_DAY_SHIFT = _HOUR_SHIFT + _HOUR_BITS
_YM_SHIFT = _DAY_SHIFT + _DAY_BITS  # == 46


def pack_datetime(
    year: int, month: int, day: int, hour: int = 0, minute: int = 0,
    second: int = 0, micro: int = 0,
) -> int:
    if not (1 <= month <= 12 and 1 <= day <= 31):
        raise ValueError(f"invalid date {year}-{month}-{day}")
    if not (0 <= hour < 24 and 0 <= minute < 60 and 0 <= second < 60 and 0 <= micro < 1_000_000):
        raise ValueError("invalid time component")
    ym = year * 13 + month
    return (
        (ym << _YM_SHIFT)
        | (day << _DAY_SHIFT)
        | (hour << _HOUR_SHIFT)
        | (minute << _MIN_SHIFT)
        | (second << _SEC_SHIFT)
        | micro
    )


def unpack_datetime(packed: int) -> tuple[int, int, int, int, int, int, int]:
    ym = packed >> _YM_SHIFT
    return (
        ym // 13,
        ym % 13,
        (packed >> _DAY_SHIFT) & 0x1F,
        (packed >> _HOUR_SHIFT) & 0x1F,
        (packed >> _MIN_SHIFT) & 0x3F,
        (packed >> _SEC_SHIFT) & 0x3F,
        packed & 0xFFFFFF,
    )


def parse_datetime(text: str) -> int:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' → packed."""
    date_part, _, time_part = text.strip().partition(" ")
    y, m, d = (int(x) for x in date_part.split("-"))
    hh = mm = ss = micro = 0
    if time_part:
        hms, _, frac = time_part.partition(".")
        hh, mm, ss = (int(x) for x in hms.split(":"))
        if frac:
            micro = int(frac.ljust(6, "0")[:6])
    return pack_datetime(y, m, d, hh, mm, ss, micro)


def format_datetime(packed: int) -> str:
    y, m, d, hh, mm, ss, micro = unpack_datetime(packed)
    base = f"{y:04d}-{m:02d}-{d:02d} {hh:02d}:{mm:02d}:{ss:02d}"
    return f"{base}.{micro:06d}" if micro else base


# -- duration ---------------------------------------------------------------

NANOS_PER_SEC = 1_000_000_000


def duration_nanos(hours: int = 0, minutes: int = 0, seconds: int = 0, micro: int = 0, neg: bool = False) -> int:
    total = ((hours * 60 + minutes) * 60 + seconds) * NANOS_PER_SEC + micro * 1000
    return -total if neg else total


def parse_duration(text: str) -> int:
    text = text.strip()
    neg = text.startswith("-")
    if neg:
        text = text[1:]
    hms, _, frac = text.partition(".")
    parts = [int(x) for x in hms.split(":")]
    # MySQL left-aligns: '11:30' is HH:MM (11:30:00), not MM:SS
    while len(parts) < 3:
        parts.append(0)
    micro = int(frac.ljust(6, "0")[:6]) if frac else 0
    return duration_nanos(parts[0], parts[1], parts[2], micro, neg)


def format_duration(nanos: int) -> str:
    neg = nanos < 0
    nanos = abs(nanos)
    total_sec, sub = divmod(nanos, NANOS_PER_SEC)
    hh, rem = divmod(total_sec, 3600)
    mm, ss = divmod(rem, 60)
    micro = sub // 1000
    out = f"{'-' if neg else ''}{hh:02d}:{mm:02d}:{ss:02d}"
    return f"{out}.{micro:06d}" if micro else out


# -- field-extraction kernels (device-eligible: pure int arithmetic) --------

def _dt_field(name: str, fn):
    @_reg(name, 1, "int")
    def kernel(xp, a, _fn=fn):
        ad, an = a
        return _fn(xp, ad), an

    return kernel


_dt_field("year", lambda xp, v: (v >> _YM_SHIFT) // 13)
_dt_field("month", lambda xp, v: (v >> _YM_SHIFT) % 13)
_dt_field("day", lambda xp, v: (v >> _DAY_SHIFT) & 0x1F)
_dt_field("hour", lambda xp, v: (v >> _HOUR_SHIFT) & 0x1F)
_dt_field("minute", lambda xp, v: (v >> _MIN_SHIFT) & 0x3F)
_dt_field("second", lambda xp, v: (v >> _SEC_SHIFT) & 0x3F)
_dt_field("micro_second", lambda xp, v: v & 0xFFFFFF)


@_reg("duration_hours", 1, "int")
def _duration_hours(xp, a):
    ad, an = a
    return xp.abs(ad) // (3600 * NANOS_PER_SEC), an
