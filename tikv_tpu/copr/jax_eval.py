"""JAX/TPU DAG evaluator — the coprocessor's device execution backend.

This is the subsystem the whole build aims at (BASELINE.json north star): DAGs
whose shape fits (TableScan → Selection? → Aggregation? → TopN?/Limit?) run as
ONE jitted XLA program per fixed-size row block, with aggregation carry state
living on device across blocks:

    host: MVCC scan → RowBatchDecoder → numpy columns → pad to block shape
    device (jit): RPN predicates → mask; RPN agg args; segment reductions
    host: finalize via the same AggState/encoder as the CPU path

Design rules (see SURVEY.md §7):
* fixed block shapes + validity masks — never dynamic shapes, so XLA compiles
  exactly once per (plan, block, group-capacity bucket)
* selection = mask, never gather
* group ids are dictionary codes assigned on host in first-occurrence stream
  order — which makes group output order *identical* to the CPU hash-agg's
  insertion order, so responses match byte-for-byte
* all-int/decimal pipelines are exact on device (int64 lanes); REAL sums are
  float and may differ from CPU in last-ulp rounding (documented caveat)
* the per-block step is dispatched asynchronously: block N+1 is decoded on
  host while block N runs on device (runner.rs's 1ms-yield loop becomes
  pipelining)

The reference CPU path stays the default and the correctness oracle, exactly
like the plugin gating described in src/coprocessor/endpoint.rs.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax

# Exact int64 lanes are a correctness requirement (decimal sums, counts over
# 100M rows): without x64, jnp silently downcasts to int32 and aggregates
# overflow.  Must be set before any jnp array is created.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from .aggr import AggDescriptor, AggState
from .dag import (
    Aggregation,
    DagRequest,
    Limit,
    ResponseEncoder,
    SelectResponse,
    Selection,
    TableScan,
    TopN,
)
from .datatypes import Chunk, Column, EvalType
from .executors import BatchTopNExecutor, ScanSource
from .rpn import RpnExpression, compile_expr, eval_rpn
from .table import RowBatchDecoder, decode_record_key

DEFAULT_BLOCK_ROWS = 1 << 16
_GROUP_CAPACITY_START = 1024

_DEVICE_AGG_OPS = {"count", "sum", "avg", "min", "max", "var_pop"}
_DEVICE_EVAL_TYPES = {EvalType.INT, EvalType.REAL, EvalType.DECIMAL, EvalType.DATETIME, EvalType.DURATION}


# ---------------------------------------------------------------------------
# Eligibility (the endpoint's routing predicate)
# ---------------------------------------------------------------------------

def supports(dag: DagRequest) -> bool:
    """True if this DAG can run on the device path."""
    try:
        _analyze(dag)
        return True
    except (_Unsupported, ValueError):
        return False


class _Unsupported(Exception):
    pass


@dataclass
class _Plan:
    scan: TableScan
    selection: Selection | None
    agg: Aggregation | None
    topn: TopN | None
    limit: Limit | None


def _analyze(dag: DagRequest) -> _Plan:
    execs = list(dag.executors)
    if not execs or not isinstance(execs[0], TableScan):
        raise _Unsupported("leaf must be TableScan")
    scan = execs[0]
    rest = execs[1:]
    plan = _Plan(scan, None, None, None, None)
    stage = 0  # 0=selection allowed, 1=agg allowed, 2=topn/limit allowed
    for e in rest:
        if isinstance(e, Selection) and stage == 0 and plan.selection is None:
            plan.selection = e
        elif isinstance(e, Aggregation) and stage <= 1 and plan.agg is None:
            plan.agg = e
            stage = 2
        elif isinstance(e, TopN) and plan.topn is None and plan.limit is None:
            # TopN over raw scan output would need full row retention on
            # device; only the post-aggregation (small) case is device-routed
            if plan.agg is None:
                raise _Unsupported("TopN without aggregation stays on CPU")
            plan.topn = e
            stage = 3
        elif isinstance(e, Limit) and plan.limit is None:
            plan.limit = e
            stage = 3
        else:
            raise _Unsupported(f"executor {type(e).__name__} not device-routable here")
    schema = [(c.ftype.eval_type, c.ftype.decimal) for c in scan.columns_info]
    for et, _ in schema:
        if et not in _DEVICE_EVAL_TYPES and et != EvalType.BYTES:
            raise _Unsupported(f"column type {et}")
    if plan.selection is not None:
        for cond in plan.selection.conditions:
            rpn = compile_expr(cond, schema)
            _check_rpn_device(rpn, schema)
    if plan.agg is not None:
        for a in plan.agg.agg_funcs:
            if a.op not in _DEVICE_AGG_OPS:
                raise _Unsupported(f"aggregate {a.op}")
            if a.expr is not None:
                rpn = compile_expr(a.expr, schema)
                _check_rpn_device(rpn, schema)
        # group-by exprs are evaluated on host (numpy) then dictionary-encoded,
        # so BYTES group keys are fine; exprs just need compilable kernels
        for g in plan.agg.group_by:
            compile_expr(g, schema)
    return plan


def _check_rpn_device(rpn: RpnExpression, schema) -> None:
    for node in rpn.nodes:
        if node.eval_type == EvalType.BYTES or node.eval_type == EvalType.JSON:
            raise _Unsupported("bytes in device expression")


# ---------------------------------------------------------------------------
# Device block step
# ---------------------------------------------------------------------------

def _np_dtype(et: EvalType):
    return np.float64 if et == EvalType.REAL else np.int64


class _DeviceAgg:
    """Builds the jitted block update + carry init for one aggregate."""

    def __init__(self, op: str, rpn: RpnExpression | None):
        self.op = op
        self.rpn = rpn
        self.input_type = rpn.eval_type if rpn is not None else EvalType.INT
        self.frac = rpn.frac if rpn is not None else 0
        self.dtype = _np_dtype(self.input_type)

    def init_carry(self, capacity: int):
        z_i = jnp.zeros(capacity, dtype=jnp.int64)
        if self.op == "count":
            return (z_i,)
        z_v = jnp.zeros(capacity, dtype=self.dtype)
        if self.op in ("sum", "avg"):
            return (z_i, z_v)
        if self.op == "var_pop":
            return (z_i, z_v, jnp.zeros(capacity, dtype=jnp.float64))
        if self.op in ("min", "max"):
            if self.dtype == np.float64:
                ident = jnp.inf if self.op == "min" else -jnp.inf
            else:
                info = np.iinfo(np.int64)
                ident = info.max if self.op == "min" else info.min
            return (z_i, jnp.full(capacity, ident, dtype=self.dtype))
        raise AssertionError(self.op)

    def update(self, carry, cols, n_rows, gids, active, capacity):
        """One block update. ``active``: row mask after selection+validity."""
        if self.rpn is None:
            data, nulls = None, None
            live = active
        else:
            data, nulls = eval_rpn(self.rpn, cols, n_rows, xp=jnp)
            live = active & ~nulls
        seg = lambda x: jax.ops.segment_sum(x, gids, num_segments=capacity)
        cnt = carry[0] + seg(live.astype(jnp.int64))
        if self.op == "count":
            return (cnt,)
        vals = jnp.where(live, data, jnp.zeros_like(data))
        if self.op in ("sum", "avg"):
            return (cnt, carry[1] + seg(vals))
        if self.op == "var_pop":
            f = jnp.where(live, data.astype(jnp.float64), 0.0)
            return (cnt, carry[1] + seg(vals), carry[2] + seg(f * f))
        if self.op in ("min", "max"):
            if self.dtype == np.float64:
                ident = jnp.inf if self.op == "min" else -jnp.inf
            else:
                info = np.iinfo(np.int64)
                ident = info.max if self.op == "min" else info.min
            masked = jnp.where(live, data, jnp.full_like(data, ident))
            segfn = jax.ops.segment_min if self.op == "min" else jax.ops.segment_max
            blockv = segfn(masked, gids, num_segments=capacity, indices_are_sorted=False)
            merge = jnp.minimum if self.op == "min" else jnp.maximum
            return (cnt, merge(carry[1], blockv))
        raise AssertionError(self.op)

    def to_state(self, carry, n_groups: int) -> AggState:
        """Fill a CPU AggState from the device carry — finalization then goes
        through the exact same result_columns code as the CPU path."""
        st = AggState(self.op, self.input_type, self.frac)
        st.grow(n_groups)
        count = np.asarray(carry[0])[:n_groups]
        st.count = count.astype(np.int64)
        if self.op in ("sum", "avg"):
            st.sum = np.asarray(carry[1])[:n_groups].astype(st.sum.dtype if len(st.sum) else self.dtype)
        elif self.op == "var_pop":
            st.sum = np.asarray(carry[1])[:n_groups]
            st.sum_sq = np.asarray(carry[2])[:n_groups]
        elif self.op in ("min", "max"):
            st.value = np.asarray(carry[1])[:n_groups]
            st.has_value = count > 0
        return st


class JaxDagEvaluator:
    """Run an eligible DAG over a scan source on the device."""

    def __init__(self, dag: DagRequest, block_rows: int = DEFAULT_BLOCK_ROWS):
        self.dag = dag
        self.plan = _analyze(dag)
        self.block_rows = block_rows
        scan = self.plan.scan
        self.schema = [(c.ftype.eval_type, c.ftype.decimal) for c in scan.columns_info]
        self.decoder = RowBatchDecoder(scan.columns_info)
        self.sel_rpns = (
            [compile_expr(c, self.schema) for c in self.plan.selection.conditions]
            if self.plan.selection
            else []
        )
        agg = self.plan.agg
        if agg is not None:
            self.group_rpns = [compile_expr(g, self.schema) for g in agg.group_by]
            self.device_aggs = [
                _DeviceAgg(a.op, compile_expr(a.expr, self.schema) if a.expr else None)
                for a in agg.agg_funcs
            ]
        else:
            self.group_rpns = []
            self.device_aggs = []
        # which leaf columns must ship to the device
        need: set[int] = set()
        for r in self.sel_rpns:
            need |= r.referenced_columns()
        for da in self.device_aggs:
            if da.rpn is not None:
                need |= da.rpn.referenced_columns()
        self.device_cols = sorted(need)
        self._block_fn = None
        self._capacity = _GROUP_CAPACITY_START if self.group_rpns else 1

    # -- jit construction --------------------------------------------------

    def _build_mask_fn(self):
        sel_rpns = self.sel_rpns
        device_cols = self.device_cols
        n_rows = self.block_rows

        def mask_fn(col_data, col_nulls, valid):
            cols = {i: (col_data[j], col_nulls[j]) for j, i in enumerate(device_cols)}
            active = valid
            for rpn in sel_rpns:
                d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
                active = active & (d != 0) & ~nl
            return active

        return jax.jit(mask_fn)

    def _build_agg_fn(self, capacity: int):
        device_aggs = self.device_aggs
        device_cols = self.device_cols
        n_rows = self.block_rows

        def agg_fn(col_data, col_nulls, active, gids, carries):
            cols = {i: (col_data[j], col_nulls[j]) for j, i in enumerate(device_cols)}
            new_carries = tuple(
                da.update(c, cols, n_rows, gids, active, capacity)
                for da, c in zip(device_aggs, carries)
            )
            return new_carries

        return jax.jit(agg_fn, donate_argnums=(4,))

    # -- host loop ---------------------------------------------------------

    def run(self, source: ScanSource) -> SelectResponse:
        if self.plan.agg is not None:
            return self._run_aggregated(source)
        return self._run_scan_filter(source)

    def _decode_blocks(self, source: ScanSource):
        """Yield (columns, n_valid) blocks of exactly block_rows rows (padded)."""
        br = self.block_rows
        pend_handles: list[np.ndarray] = []
        pend_values: list[bytes] = []
        drained = False
        while not drained:
            keys, values, drained = source.next_batch(br)
            if keys:
                h = np.empty(len(keys), dtype=np.int64)
                for i, k in enumerate(keys):
                    _, h[i] = decode_record_key(k)
                pend_handles.append(h)
                pend_values.extend(values)
            total = sum(len(x) for x in pend_handles)
            while total >= br or (drained and total > 0):
                handles = np.concatenate(pend_handles) if len(pend_handles) > 1 else pend_handles[0]
                take = min(br, total)
                block_h, rest_h = handles[:take], handles[take:]
                block_v, rest_v = pend_values[:take], pend_values[take:]
                pend_handles = [rest_h] if len(rest_h) else []
                pend_values = rest_v
                total = len(rest_h)
                cols = self.decoder.decode(block_h, block_v)
                yield cols, take

    def _pad(self, arr: np.ndarray, fill=0) -> np.ndarray:
        n = len(arr)
        if n == self.block_rows:
            return arr
        pad = self.block_rows - n
        if arr.dtype == object:
            ext = np.empty(pad, dtype=object)
            ext[:] = b""
            return np.concatenate([arr, ext])
        return np.concatenate([arr, np.full(pad, fill, dtype=arr.dtype)])

    def _run_aggregated(self, source: ScanSource) -> SelectResponse:
        group_index: dict = {}
        group_rows: list[tuple] = []
        capacity = self._capacity
        mask_fn = self._build_mask_fn() if self.sel_rpns else None
        agg_fn = self._build_agg_fn(capacity)
        carries = tuple(da.init_carry(capacity) for da in self.device_aggs)

        for cols, n_valid in self._decode_blocks(source):
            valid = np.zeros(self.block_rows, dtype=bool)
            valid[:n_valid] = True
            col_data = [self._pad(cols[i].data) for i in self.device_cols]
            col_nulls = [self._pad(cols[i].nulls, True) for i in self.device_cols]
            if mask_fn is not None:
                active = np.asarray(mask_fn(col_data, col_nulls, valid))
            else:
                active = valid
            # group ids: host-evaluated group exprs over rows that SURVIVED the
            # selection (first-occurrence order == CPU hash-agg insertion order)
            if self.group_rpns:
                gids_np, n_groups = self._assign_gids(cols, active, group_index, group_rows)
                if n_groups > capacity:
                    # grow to the next bucket and re-jit once; carries migrate
                    new_capacity = capacity
                    while n_groups > new_capacity:
                        new_capacity *= 2
                    carries = tuple(
                        _grow_carry(da, c, new_capacity) for da, c in zip(self.device_aggs, carries)
                    )
                    capacity = new_capacity
                    self._capacity = capacity
                    agg_fn = self._build_agg_fn(capacity)
            else:
                gids_np = np.zeros(self.block_rows, dtype=np.int32)
            carries = agg_fn(col_data, col_nulls, active, gids_np, carries)

        n_groups = len(group_rows) if self.group_rpns else 1
        states = [da.to_state(jax.tree.map(np.asarray, c), n_groups) for da, c in zip(self.device_aggs, carries)]
        out_cols: list[Column] = []
        for st in states:
            out_cols.extend(st.result_columns(n_groups))
        for gi, g in enumerate(self.group_rpns):
            vals = [group_rows[r][gi] for r in range(n_groups)]
            out_cols.append(Column.from_values(g.eval_type, vals, g.frac))
        chunk = Chunk.full(out_cols)
        # post-agg TopN / Limit are tiny — run them via the CPU executors
        chunk = self._post_agg(chunk)
        enc = ResponseEncoder(self.dag.chunk_rows)
        enc.add_chunk(chunk, self.dag.output_offsets)
        return SelectResponse(chunks=enc.finish())

    def _assign_gids(self, cols, active, group_index, group_rows):
        np_cols = {i: (c.data, c.nulls) for i, c in enumerate(cols)}
        n = len(cols[0]) if cols else 0
        parts = []
        for g in self.group_rpns:
            d, nl = eval_rpn(g, np_cols, n, xp=np)
            parts.append((np.asarray(d), np.asarray(nl)))
        gids = np.zeros(self.block_rows, dtype=np.int32)
        live_rows = np.flatnonzero(active[:n])
        if len(parts) == 1:
            data, nulls = parts[0]
            keys = [None if nulls[i] else (bytes(data[i]) if data.dtype == object else data[i].item()) for i in live_rows]
        else:
            keys = [
                tuple(
                    None if nl[i] else (bytes(d[i]) if d.dtype == object else d[i].item())
                    for d, nl in parts
                )
                for i in live_rows
            ]
        for i, key in zip(live_rows, keys):
            gid = group_index.get(key)
            if gid is None:
                gid = len(group_rows)
                group_index[key] = gid
                group_rows.append(key if isinstance(key, tuple) else (key,))
            gids[i] = gid
        return gids, len(group_rows)

    def _post_agg(self, chunk: Chunk) -> Chunk:
        """Apply TopN/Limit over the (small) aggregated output on host."""
        schema = None
        if self.plan.topn is not None:
            agg_schema = self._agg_output_schema()
            ex = BatchTopNExecutor(_ChunkExecutor(chunk, agg_schema), self.plan.topn.order_by, self.plan.topn.limit)
            chunk = ex.next_batch(len(chunk.logical_rows) or 1).chunk
        if self.plan.limit is not None:
            chunk = Chunk(chunk.columns, chunk.logical_rows[: self.plan.limit.limit])
        return chunk

    def _agg_output_schema(self):
        out = []
        for da, a in zip(self.device_aggs, self.plan.agg.agg_funcs):
            it, frac = da.input_type, da.frac
            if a.op == "count":
                out.append((EvalType.INT, 0))
            elif a.op == "avg":
                out.append((EvalType.INT, 0))
                out.append((it, frac))
            elif a.op == "var_pop":
                out.extend([(EvalType.INT, 0), (EvalType.REAL, 0), (EvalType.REAL, 0)])
            else:
                out.append((it, frac))
        for g in self.group_rpns:
            out.append((g.eval_type, g.frac))
        return out

    # -- selection-only pipeline ------------------------------------------

    def _run_scan_filter(self, source: ScanSource) -> SelectResponse:
        """TableScan → Selection? → Limit?: device computes the row mask,
        host compacts + encodes (row encoding is host work either way)."""
        remaining = self.plan.limit.limit if self.plan.limit else None
        sel_rpns = self.sel_rpns
        device_cols = self.device_cols
        mask_jit = self._build_mask_fn()
        enc = ResponseEncoder(self.dag.chunk_rows)
        for cols, n_valid in self._decode_blocks(source):
            valid = np.zeros(self.block_rows, dtype=bool)
            valid[:n_valid] = True
            if sel_rpns:
                col_data = [self._pad(cols[i].data) for i in device_cols]
                col_nulls = [self._pad(cols[i].nulls, True) for i in device_cols]
                mask = np.asarray(mask_jit(col_data, col_nulls, valid))
            else:
                mask = valid
            logical = np.flatnonzero(mask[: n_valid])
            if remaining is not None:
                logical = logical[:remaining]
                remaining -= len(logical)
            chunk = Chunk(cols, logical)
            enc.add_chunk(chunk, self.dag.output_offsets)
            if remaining is not None and remaining <= 0:
                break
        return SelectResponse(chunks=enc.finish())


class _ChunkExecutor:
    """Adapter: present an in-memory Chunk as a drained BatchExecutor."""

    def __init__(self, chunk: Chunk, schema):
        self._chunk = chunk
        self._schema = schema
        self._done = False

    def schema(self):
        return self._schema

    def next_batch(self, scan_rows: int):
        from .executors import BatchExecuteResult

        if self._done:
            return BatchExecuteResult(Chunk.full([]), True)
        self._done = True
        return BatchExecuteResult(self._chunk, True)


def _grow_carry(da: _DeviceAgg, carry, new_capacity: int):
    grown = list(da.init_carry(new_capacity))
    out = []
    for old, new in zip(carry, grown):
        old = jnp.asarray(old)
        out.append(new.at[: old.shape[0]].set(old))
    return tuple(out)
