"""JAX/TPU DAG evaluator — the coprocessor's device execution backend.

This is the subsystem the whole build aims at (BASELINE.json north star): DAGs
whose shape fits (TableScan → Selection? → Aggregation? → TopN?/Limit?) run as
ONE jitted XLA program per fixed-size row block, with aggregation carry state
living on device across blocks:

    host: MVCC scan → RowBatchDecoder → numpy columns → pad to block shape
    device (jit): RPN predicates → mask; RPN agg args; segment reductions
    host: finalize via the same AggState/encoder as the CPU path

Design rules (see SURVEY.md §7):
* fixed block shapes + validity masks — never dynamic shapes, so XLA compiles
  exactly once per (plan, block, group-capacity bucket)
* selection = mask, never gather
* group ids are dictionary codes assigned on host in first-occurrence stream
  order — which makes group output order *identical* to the CPU hash-agg's
  insertion order, so responses match byte-for-byte
* all-int/decimal pipelines are exact on device (int64 lanes); REAL sums are
  float and may differ from CPU in last-ulp rounding (documented caveat)
* the per-block step is dispatched asynchronously: block N+1 is decoded on
  host while block N runs on device (runner.rs's 1ms-yield loop becomes
  pipelining)

The reference CPU path stays the default and the correctness oracle, exactly
like the plugin gating described in src/coprocessor/endpoint.rs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

import jax

# Exact int64 lanes are a correctness requirement (decimal sums, counts over
# 100M rows): without x64, jnp silently downcasts to int32 and aggregates
# overflow.  Must be set before any jnp array is created.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from ..analysis.sanitizer import note_blocking
from ..util import trace
from . import observatory as _obs
from .aggr import AggDescriptor, AggState
from .dag import (
    Aggregation,
    DagRequest,
    IndexScan,
    Limit,
    SelectResponse,
    make_response_encoder,
    Selection,
    TableScan,
    TopN,
)
from .datatypes import Chunk, Column, EvalType
from .executors import BatchTopNExecutor, ScanSource
from .groupby import GroupDict
from .rpn import ColumnRef, RpnExpression, compile_expr, eval_rpn
from .table import RowBatchDecoder, decode_record_handles

DEFAULT_BLOCK_ROWS = 1 << 16
_GROUP_CAPACITY_START = 1024
_NO_ROW = 1 << 62  # first-active-row sentinel: "no row of this group survived"
_ZERO_GIDS: dict[int, np.ndarray] = {}
_MISSING_PLAN = object()  # sentinel: _stacked_device resolves the plan itself

_DEVICE_AGG_OPS = {
    "count", "sum", "avg", "min", "max", "var_pop",
    "first", "bit_and", "bit_or", "bit_xor",
}
_DEVICE_EVAL_TYPES = {EvalType.INT, EvalType.REAL, EvalType.DECIMAL, EvalType.DATETIME, EvalType.DURATION}
_TOPN_DEVICE_MAX = 2048  # raw TopN carries K rows of state per column


# ---------------------------------------------------------------------------
# Eligibility (the endpoint's routing predicate)
# ---------------------------------------------------------------------------

def supports(dag: DagRequest) -> bool:
    """True if this DAG can run on the device path."""
    try:
        _analyze(dag)
        return True
    except (_Unsupported, ValueError):
        return False


def decline_cause(dag: DagRequest) -> str | None:
    """None when the DAG is device-eligible, else a bounded-cardinality
    cause slug — the named half of :func:`supports`, so limit-bearing
    plans that stay on the CPU are never a silent fallback (the endpoint
    counts these under ``tikv_coprocessor_encoded_decline_total``
    path="device_plan")."""
    try:
        _analyze(dag)
        return None
    except _Unsupported as exc:
        return exc.cause
    except ValueError:
        return "expr_compile"


class _Unsupported(Exception):
    def __init__(self, msg: str, cause: str = "plan_shape"):
        super().__init__(msg)
        self.cause = cause


@dataclass
class _Plan:
    scan: TableScan
    selection: Selection | None
    agg: Aggregation | None
    topn: TopN | None
    limit: Limit | None


def _analyze(dag: DagRequest) -> _Plan:
    execs = list(dag.executors)
    if not execs or not isinstance(execs[0], (TableScan, IndexScan)):
        raise _Unsupported("leaf must be a scan", "leaf_not_scan")
    scan = execs[0]
    rest = execs[1:]
    plan = _Plan(scan, None, None, None, None)
    stage = 0  # 0=selection allowed, 1=agg allowed, 2=topn/limit allowed
    for e in rest:
        if isinstance(e, Selection) and stage == 0 and plan.selection is None:
            plan.selection = e
        elif isinstance(e, Aggregation) and stage <= 1 and plan.agg is None:
            plan.agg = e
            stage = 2
        elif isinstance(e, TopN) and plan.topn is None and plan.limit is None:
            plan.topn = e
            stage = 3
        elif isinstance(e, Limit) and plan.limit is None:
            plan.limit = e
            stage = 3
        else:
            from .dag import Join, Projection

            if isinstance(e, Join):
                # joins route through the dedicated device-join rung
                # (jax_join.py / docs/device_join.md), never this plan shape
                raise _Unsupported("join executors serve via the join rung",
                                   "join_executor")
            if isinstance(e, Projection):
                raise _Unsupported(
                    "projection executors serve via the join rung or CPU",
                    "projection_executor")
            raise _Unsupported(f"executor {type(e).__name__} not device-routable here",
                               "executor_shape")
    schema = [(c.ftype.eval_type, c.ftype.decimal) for c in scan.columns_info]
    for et, _ in schema:
        if et not in _DEVICE_EVAL_TYPES and et not in (EvalType.BYTES, EvalType.JSON):
            # BYTES/JSON columns may exist in the schema (group keys are
            # dictionary-encoded host-side); _check_rpn_device rejects them
            # inside device expressions
            raise _Unsupported(f"column type {et}", "column_type")
        if isinstance(scan, IndexScan) and et not in _DEVICE_EVAL_TYPES:
            # index entries decode through datum lists (object arrays), so
            # BYTES never arrives dictionary-coded on this leaf
            raise _Unsupported(f"index column type {et}", "index_column_type")
    if plan.selection is not None:
        for cond in plan.selection.conditions:
            rpn = compile_expr(cond, schema)
            _check_rpn_device(rpn, schema)
    if plan.agg is not None:
        if plan.agg.streamed:
            # stream agg emits one row per CONSECUTIVE run of the group key;
            # that equals hash-agg output (what the device computes) only
            # when the scan order sorts by the group key — guaranteed here
            # just for grouping on the HANDLE column (scan order is handle
            # order, wherever it sits in the schema).  Anything else takes
            # the CPU stream executor (stream_aggr_executor.rs semantics).
            cols_info = scan.columns_info
            if isinstance(scan, IndexScan):
                # index scan order sorts by the index column prefix
                # (index_scan_executor.rs:29 + stream_aggr_executor.rs:23's
                # common sorted-by-index shape): grouping on a PREFIX of the
                # index columns keeps stream output == hash output
                ok = all(
                    isinstance(g, ColumnRef) and g.index == gi
                    and g.index < len(cols_info)
                    and not cols_info[g.index].is_pk_handle
                    for gi, g in enumerate(plan.agg.group_by)
                )
            else:
                ok = len(plan.agg.group_by) <= 1 and all(
                    isinstance(g, ColumnRef)
                    and g.index < len(cols_info)
                    and cols_info[g.index].is_pk_handle
                    for g in plan.agg.group_by
                )
            if not ok:
                raise _Unsupported("streamed agg not sorted by group key",
                                   "streamed_agg_order")
        for a in plan.agg.agg_funcs:
            if a.op not in _DEVICE_AGG_OPS:
                raise _Unsupported(f"aggregate {a.op}", "agg_op")
            if a.expr is not None:
                rpn = compile_expr(a.expr, schema)
                _check_rpn_device(rpn, schema)
        # group-by exprs are evaluated on host (numpy) then dictionary-encoded,
        # so BYTES group keys are fine; exprs just need compilable kernels
        for g in plan.agg.group_by:
            compile_expr(g, schema)
    if plan.topn is not None and plan.agg is None:
        # raw TopN runs a device top-K merge: every schema column ships as
        # payload — numeric columns as values, BYTES as dictionary codes
        # (decoded back to bytes host-side at finalize; non-dict layouts
        # raise at run time and take the CPU fallback)
        if plan.topn.limit > _TOPN_DEVICE_MAX:
            raise _Unsupported(f"TopN limit {plan.topn.limit} too large for device",
                               "topn_limit_too_large")
        for et, _ in schema:
            if et not in _DEVICE_EVAL_TYPES and not (
                et == EvalType.BYTES and isinstance(scan, TableScan)
            ):
                raise _Unsupported(f"TopN payload column type {et}",
                                   "topn_payload_type")
        for expr, _desc in plan.topn.order_by:
            rpn = compile_expr(expr, schema)
            _check_rpn_device(rpn, schema)
            if rpn.eval_type not in _DEVICE_EVAL_TYPES:
                raise _Unsupported(f"TopN key type {rpn.eval_type}", "topn_key_type")
    return plan


def _check_rpn_device(rpn: RpnExpression, schema) -> None:
    for node in rpn.nodes:
        if node.eval_type == EvalType.BYTES or node.eval_type == EvalType.JSON:
            raise _Unsupported("bytes in device expression", "bytes_predicate")


# ---------------------------------------------------------------------------
# Device block step
# ---------------------------------------------------------------------------

def _np_dtype(et: EvalType):
    return np.float64 if et == EvalType.REAL else np.int64


_ONEHOT_CAPACITY_MAX = 64
_MATMUL_CAPACITY_MAX = 4096
_EXTREME_MASK_CAPACITY_MAX = 1024


_PREFETCH_END = object()


def _prefetch(it, depth: int = 1):
    """Run ``it`` on a worker thread, buffering ``depth`` items ahead: the
    producer (host decode — numpy-heavy, releases the GIL) overlaps the
    consumer (device dispatch).  Exceptions re-raise at the consumption
    point; an abandoned consumer unblocks the producer via queue timeout."""
    import queue as _queue

    q: _queue.Queue = _queue.Queue(maxsize=depth)
    done = threading.Event()

    def put_or_abandon(entry) -> bool:
        # EVERY put must observe `done`: an early-abandoned consumer (e.g.
        # a Limit satisfied mid-scan) never drains the queue, and a plain
        # blocking put would pin this thread + its decoded block forever
        while not done.is_set():
            try:
                q.put(entry, timeout=0.5)
                return True
            except _queue.Full:
                continue
        return False

    def produce():
        try:
            for item in it:
                if not put_or_abandon(("item", item)):
                    return
            put_or_abandon((None, _PREFETCH_END))
        except BaseException as exc:  # noqa: BLE001 — re-raised on consume
            put_or_abandon(("exc", exc))

    t = threading.Thread(target=produce, daemon=True, name="decode-prefetch")
    t.start()
    try:
        while True:
            kind, payload = q.get()
            if payload is _PREFETCH_END:
                return
            if kind == "exc":
                raise payload
            yield payload
    finally:
        done.set()


def _limb_matmul_seg_sum(x, gids, capacity: int):
    """Exact int64 per-group sums on the MXU: TPU scatter is ~1000× slower
    than reductions, so instead split each value into b-bit limbs, one-hot
    matmul every limb in a single (C×n)@(n×L) dot — systolic-array work —
    and reassemble with two's-complement wraparound.  Logical shifts make
    the limbs sign-free, so negative values round-trip exactly.

    b ≤ 8 is load-bearing: the TPU MXU's default precision truncates f32
    operands to bf16 (8 mantissa bits), so limbs must stay ≤ 2^8 to survive
    that pass bit-exact; products then accumulate in f32, exact while
    (2^b−1)·n < 2^24.  Callers guarantee n < 2^16 (block sizes)."""
    n = x.shape[0]
    bits = 8
    while bits > 1 and (2**bits - 1) * n >= 2**24:
        bits -= 1
    if (2**bits - 1) * n >= 2**24:  # n ≥ 2^23: exactness unattainable
        return jax.ops.segment_sum(x, gids, num_segments=capacity)
    n_limbs = -(-64 // bits)
    mask = jnp.int64((1 << bits) - 1)
    onehot = (gids[:, None] == jnp.arange(capacity, dtype=gids.dtype)[None, :]).astype(
        jnp.float32
    )
    limbs = jnp.stack(
        [
            (jax.lax.shift_right_logical(x, jnp.int64(k * bits)) & mask).astype(jnp.float32)
            for k in range(n_limbs)
        ],
        axis=1,
    )
    sums = jax.lax.dot_general(
        onehot, limbs, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # C×L, every entry an exact integer < 2^24
    acc = jnp.zeros(capacity, dtype=jnp.int64)
    for k in range(n_limbs):
        acc = acc + (sums[:, k].astype(jnp.int64) << (k * bits))
    return acc


def _scatter_ok() -> bool:
    """The one-hot/mask/limb-matmul shapes below exist because TPU scatter
    is ~1000× slower than MXU/VPU work — but on a CPU (or GPU) backend the
    trade INVERTS: XLA-CPU lowers the n×C broadcast compares to dreadful
    code while native scatter-adds are fast.  Decided at trace time, so
    each backend compiles its own best shape and results stay exact
    (segment ops are exact integer/f64 adds)."""
    return jax.default_backend() != "tpu"


def _seg_sum(x, gids, capacity: int):
    """Exact per-group sum avoiding TPU scatter: capacity 1 is a plain
    reduction; small capacities use a broadcast-compare mask reduction (VPU
    work, ~n·C lanes); int64 up to 4096 groups rides the MXU via limb
    matmuls; only float sums at large capacities fall back to scatter-based
    segment_sum (f32 matmul would diverge from the CPU oracle's f64 sums
    beyond the last-ulp exemption).  Non-TPU backends take the scatter path
    directly (_scatter_ok)."""
    if capacity == 1:
        return jnp.sum(x).reshape(1)
    if _scatter_ok():
        return jax.ops.segment_sum(x, gids, num_segments=capacity)
    if capacity <= _ONEHOT_CAPACITY_MAX:
        onehot = gids[:, None] == jnp.arange(capacity, dtype=gids.dtype)[None, :]
        return jnp.sum(jnp.where(onehot, x[:, None], jnp.zeros((), dtype=x.dtype)), axis=0)
    if x.dtype == jnp.int64 and capacity <= _MATMUL_CAPACITY_MAX:
        return _limb_matmul_seg_sum(x, gids, capacity)
    if capacity <= _MATMUL_CAPACITY_MAX:
        # float sums beyond the one-hot window: scan over blocks of 64
        # groups, each a full-precision f64 mask-reduce (VPU).  Same tree
        # reduction as the ≤64 path, so the same last-ulp behavior — and
        # still orders of magnitude cheaper than TPU scatter, which was the
        # round-1 fallback that knocked Q1-with-REAL shapes off the device.
        blocks = (capacity + _ONEHOT_CAPACITY_MAX - 1) // _ONEHOT_CAPACITY_MAX
        starts = jnp.arange(blocks, dtype=gids.dtype) * _ONEHOT_CAPACITY_MAX
        lane = jnp.arange(_ONEHOT_CAPACITY_MAX, dtype=gids.dtype)

        def one_block(start):
            onehot = gids[:, None] == (start + lane)[None, :]
            return jnp.sum(
                jnp.where(onehot, x[:, None], jnp.zeros((), dtype=x.dtype)), axis=0
            )

        out = jax.lax.map(one_block, starts)  # (blocks, 64)
        return out.reshape(blocks * _ONEHOT_CAPACITY_MAX)[:capacity]
    return jax.ops.segment_sum(x, gids, num_segments=capacity)


def _seg_extreme(x, gids, capacity: int, is_min: bool, identity):
    if capacity == 1:
        f = jnp.min if is_min else jnp.max
        return f(x).reshape(1)
    if _scatter_ok():
        f = jax.ops.segment_min if is_min else jax.ops.segment_max
        return f(x, gids, num_segments=capacity)
    if capacity <= _EXTREME_MASK_CAPACITY_MAX:
        # n×C masked reduce: pure VPU work, still far cheaper than scatter
        onehot = gids[:, None] == jnp.arange(capacity, dtype=gids.dtype)[None, :]
        masked = jnp.where(onehot, x[:, None], jnp.full((), identity, dtype=x.dtype))
        return (jnp.min if is_min else jnp.max)(masked, axis=0)
    f = jax.ops.segment_min if is_min else jax.ops.segment_max
    return f(x, gids, num_segments=capacity)


_BIT_IDENT = {"bit_and": -1, "bit_or": 0, "bit_xor": 0}
_BIT_FN = {
    "bit_and": jax.lax.bitwise_and,
    "bit_or": jax.lax.bitwise_or,
    "bit_xor": jax.lax.bitwise_xor,
}


def _seg_bitop(x, gids, capacity: int, op: str):
    """Per-group bitwise AND/OR/XOR via lax.reduce (XLA has native and/or/
    xor reduction monoids on every backend — no scatter exists for them).
    Masked n×C reduction in group-blocks of 64, same shape as _seg_sum's
    mid path; these aggregates are rare enough that the extra lanes are
    acceptable on either backend."""
    ident = jnp.int64(_BIT_IDENT[op])
    fn = _BIT_FN[op]
    if capacity == 1:
        return jax.lax.reduce(x, ident, fn, (0,)).reshape(1)
    blocks = (capacity + _ONEHOT_CAPACITY_MAX - 1) // _ONEHOT_CAPACITY_MAX
    starts = jnp.arange(blocks, dtype=gids.dtype) * _ONEHOT_CAPACITY_MAX
    lane = jnp.arange(_ONEHOT_CAPACITY_MAX, dtype=gids.dtype)

    def one_block(start):
        onehot = gids[:, None] == (start + lane)[None, :]
        masked = jnp.where(onehot, x[:, None], ident)
        return jax.lax.reduce(masked, ident, fn, (0,))

    out = jax.lax.map(one_block, starts)
    return out.reshape(blocks * _ONEHOT_CAPACITY_MAX)[:capacity]


def _build_cols(ship_cols, nullable, col_data, col_nulls, n_rows, enc=None,
                refs=None):
    """Column map for eval_rpn: NOT NULL columns get a folded constant mask.

    ``enc`` (static per-ship-col encoding descriptors from
    ``copr/encoding.py``) turns this into THE in-kernel decode point shared
    by every device program: bitpacked lanes widen ``+ refs[j]`` (refs are
    dynamic, so images with different value ranges share one executable),
    narrowed dict codes widen, RLE runs expand through one searchsorted
    gather — HBM holds the encoded payloads, everything downstream sees
    exact int64/f64 lanes."""
    no_nulls = jnp.zeros(n_rows, dtype=bool)
    nullmap = dict(zip(nullable, col_nulls))
    if enc is None:
        return {i: (col_data[j], nullmap.get(i, no_nulls)) for j, i in enumerate(ship_cols)}
    from .kernels import decode_device_column

    cols = {}
    for j, i in enumerate(ship_cols):
        cols[i] = decode_device_column(
            jnp, enc[j], col_data[j], nullmap.get(i, no_nulls),
            None if refs is None else refs[j], n_rows,
        )
    return cols


def _mixed_radix_gids(cols, group_cols, dict_lens, n_rows):
    """Group ids from resident dictionary-code columns (stable radices)."""
    local = jnp.zeros(n_rows, dtype=jnp.int64)
    for gi, dlen in zip(group_cols, dict_lens):
        codes, gnulls = cols[gi]
        local = local * (dlen + 1) + jnp.where(gnulls, dlen, codes)
    return local


def _fused_step(sel_rpns, device_aggs, capacity, n_rows, cols, n_valid, gids, offset, state,
                track_first: bool = True):
    """THE block step, shared by every device program: selection predicates →
    active mask; aggregate updates; first-active-row tracker.

    ``track_first=False`` skips the per-block first-active-row segment-min:
    with no group-by, finalize outputs the single slot unconditionally, so
    the tracker is dead work (a whole extra reduction pass per block)."""
    first_row, carries = state
    active = jnp.arange(n_rows, dtype=jnp.int64) < n_valid
    for rpn in sel_rpns:
        d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
        active = active & (d != 0) & ~nl
    new_carries = tuple(
        da.update(c, cols, n_rows, gids, active, capacity, offset)
        for da, c in zip(device_aggs, carries)
    )
    if not track_first:
        return (first_row, new_carries)
    ridx = jnp.where(active, offset + jnp.arange(n_rows, dtype=jnp.int64), _NO_ROW)
    block_first = _seg_extreme(ridx, gids, capacity, True, _NO_ROW)
    return (jnp.minimum(first_row, block_first), new_carries)


def _masked_nv(blocks, keep):
    """Survivor-count n_valid vector (docs/zone_maps.md): pruned blocks
    carry 0 valid rows, so the fixed-shape programs mask them out entirely
    while every compile key stays unchanged."""
    nv = np.fromiter(
        (b.n_valid if keep[bi] else 0 for bi, b in enumerate(blocks)),
        dtype=np.int64, count=len(blocks))
    return jnp.asarray(nv)


def _batch_prune_keep(evaluators, cache):
    """Fused-batch keep mask: the batch shares one block stream, so a block
    is masked out only when EVERY rider's zone maps prune it.  Returns
    (keep | None, (examined, pruned)) like the unary ``_prune_keep``."""
    from . import zone_maps as _zm

    if not _zm.enabled() or not cache.blocks:
        return None, (0, 0)
    keep = None
    for ev in evaluators:
        if not ev.sel_rpns:
            return None, (0, 0)
        m = _zm.prune_blocks(cache, ev.sel_rpns, path="fused")
        if m is None:
            return None, (0, 0)
        keep = m if keep is None else (keep | m)
    if keep.all():
        return None, (0, 0)
    return keep, (len(cache.blocks), int((~keep).sum()))


class _DeviceAgg:
    """Builds the jitted block update + carry init for one aggregate."""

    def __init__(self, op: str, rpn: RpnExpression | None):
        self.op = op
        self.rpn = rpn
        self.input_type = rpn.eval_type if rpn is not None else EvalType.INT
        self.frac = rpn.frac if rpn is not None else 0
        self.dtype = _np_dtype(self.input_type)

    def init_carry(self, capacity: int):
        z_i = jnp.zeros(capacity, dtype=jnp.int64)
        if self.op == "count":
            return (z_i,)
        if self.op in ("bit_and", "bit_or", "bit_xor"):
            return (z_i, jnp.full(capacity, _BIT_IDENT[self.op], dtype=jnp.int64))
        z_v = jnp.zeros(capacity, dtype=self.dtype)
        if self.op in ("sum", "avg"):
            return (z_i, z_v)
        if self.op == "first":
            # (count, first value, global row index that supplied it)
            return (z_i, z_v, jnp.full(capacity, _NO_ROW, dtype=jnp.int64))
        if self.op == "var_pop":
            return (z_i, z_v, jnp.zeros(capacity, dtype=jnp.float64))
        if self.op in ("min", "max"):
            if self.dtype == np.float64:
                ident = jnp.inf if self.op == "min" else -jnp.inf
            else:
                info = np.iinfo(np.int64)
                ident = info.max if self.op == "min" else info.min
            return (z_i, jnp.full(capacity, ident, dtype=self.dtype))
        raise AssertionError(self.op)

    def host_template(self):
        """Numpy dtype skeleton mirroring init_carry — for unpacking pulls."""
        zi = np.zeros(0, dtype=np.int64)
        zv = np.zeros(0, dtype=self.dtype)
        if self.op == "count":
            return (zi,)
        if self.op in ("bit_and", "bit_or", "bit_xor"):
            return (zi, zi)
        if self.op in ("sum", "avg"):
            return (zi, zv)
        if self.op == "first":
            return (zi, zv, zi)
        if self.op == "var_pop":
            return (zi, zv, np.zeros(0, dtype=np.float64))
        if self.op in ("min", "max"):
            return (zi, zv)
        raise AssertionError(self.op)

    def update(self, carry, cols, n_rows, gids, active, capacity, offset=0):
        """One block update. ``active``: row mask after selection+validity;
        ``offset``: the block's global first-valid-row index (used only by
        order-sensitive aggregates like ``first``)."""
        if self.rpn is None:
            data, nulls = None, None
            live = active
        else:
            data, nulls = eval_rpn(self.rpn, cols, n_rows, xp=jnp)
            live = active & ~nulls
        seg = lambda x: _seg_sum(x, gids, capacity)
        cnt = carry[0] + seg(live.astype(jnp.int64))
        if self.op == "count":
            return (cnt,)
        if self.op in ("bit_and", "bit_or", "bit_xor"):
            ident = jnp.int64(_BIT_IDENT[self.op])
            masked = jnp.where(live, data, ident)
            blockv = _seg_bitop(masked, gids, capacity, self.op)
            return (cnt, _BIT_FN[self.op](carry[1], blockv))
        vals = jnp.where(live, data, jnp.zeros_like(data))
        if self.op in ("sum", "avg"):
            return (cnt, carry[1] + seg(vals))
        if self.op == "first":
            # first live row's value per group, in stream order: per block a
            # segment-min of the live local row index picks the candidate, a
            # capacity-sized gather reads its value, and the carry keeps
            # whichever global index is smaller
            lidx = jnp.where(live, jnp.arange(n_rows, dtype=jnp.int64), jnp.int64(n_rows))
            blk_local = _seg_extreme(lidx, gids, capacity, True, n_rows)
            safe = jnp.clip(blk_local, 0, n_rows - 1)
            blk_val = data[safe]
            blk_global = jnp.where(blk_local < n_rows, offset + blk_local, _NO_ROW)
            better = blk_global < carry[2]
            return (
                cnt,
                jnp.where(better, blk_val, carry[1]),
                jnp.where(better, blk_global, carry[2]),
            )
        if self.op == "var_pop":
            f = jnp.where(live, data.astype(jnp.float64), 0.0)
            return (cnt, carry[1] + seg(vals), carry[2] + seg(f * f))
        if self.op in ("min", "max"):
            if self.dtype == np.float64:
                ident = jnp.inf if self.op == "min" else -jnp.inf
            else:
                info = np.iinfo(np.int64)
                ident = info.max if self.op == "min" else info.min
            masked = jnp.where(live, data, jnp.full_like(data, ident))
            blockv = _seg_extreme(masked, gids, capacity, self.op == "min", ident)
            merge = jnp.minimum if self.op == "min" else jnp.maximum
            return (cnt, merge(carry[1], blockv))
        raise AssertionError(self.op)

    def to_state(self, carry, n_groups: int) -> AggState:
        """Fill a CPU AggState from the device carry — finalization then goes
        through the exact same result_columns code as the CPU path."""
        st = AggState(self.op, self.input_type, self.frac)
        st.grow(n_groups)
        count = np.asarray(carry[0])[:n_groups]
        st.count = count.astype(np.int64)
        if self.op in ("sum", "avg"):
            st.sum = np.asarray(carry[1])[:n_groups].astype(st.sum.dtype if len(st.sum) else self.dtype)
        elif self.op == "var_pop":
            st.sum = np.asarray(carry[1])[:n_groups]
            st.sum_sq = np.asarray(carry[2])[:n_groups]
        elif self.op == "first":
            st.value = np.asarray(carry[1])[:n_groups]
            st.has_value = np.asarray(carry[2])[:n_groups] != _NO_ROW
        elif self.op in ("bit_and", "bit_or", "bit_xor"):
            st.value = np.asarray(carry[1])[:n_groups]
        elif self.op in ("min", "max"):
            st.value = np.asarray(carry[1])[:n_groups]
            st.has_value = count > 0
        return st


def _topn_key_operands(data, nulls, desc: bool):
    """[null_rank, key] sort operands reproducing the CPU comparator
    (_row_cmp): NULLs first ascending / last descending.  lax.sort takes
    mixed-dtype operands, so REAL keys stay f64 (negated for desc — exact)
    while int-family keys are int64 (bit-NOT for desc: negating INT64_MIN
    would overflow).  No bitcasts — the TPU x64 rewriter behind the tunnel
    compiler supports neither f64→s64 nor f64→u32.  Null rows pin the key
    to 0 so ties among NULLs fall through to later keys / stream order,
    exactly like the comparator's `continue`; −0 is normalized to +0 so it
    ties +0 the way python float comparison does."""
    if data.dtype == jnp.float64:
        x = data + 0.0  # −0 → +0
        kv = jnp.where(nulls, 0.0, -x if desc else x)
    else:
        v = data.astype(jnp.int64)
        kv = jnp.where(nulls, jnp.int64(0), ~v if desc else v)
    rank = jnp.where(nulls, jnp.int64(1), jnp.int64(0)) if desc else jnp.where(
        nulls, jnp.int64(0), jnp.int64(1)
    )
    return [rank, kv]


def _topn_step(sel_rpns, order_rpns, payload_cols, k, n_rows, cols, n_valid, state):
    """One block of the running top-K merge: compute sort operands for the
    block's rows, concatenate with the carried best-K, stable-sort
    lexicographically (rank, key1-null, key1, key2-null, key2, …) and keep
    the first K.  lax.sort is stable and state precedes block rows, so ties
    resolve in global stream order — exactly the CPU executor's seq
    tie-break.  No scatter, no gather beyond the K-slice."""
    ridx = jnp.arange(n_rows, dtype=jnp.int64)
    active = ridx < n_valid
    for rpn in sel_rpns:
        d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
        active = active & (d != 0) & ~nl
    rank_blk = jnp.where(active, jnp.int64(0), jnp.int64(1))
    operands_blk = [rank_blk]
    for rpn, desc in order_rpns:
        d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
        operands_blk += _topn_key_operands(d, nl, desc)
    n_key_ops = len(operands_blk)
    merged = [jnp.concatenate([s, b]) for s, b in zip(state, operands_blk)]
    # sort ONLY the key operands plus a row index — every extra sort operand
    # multiplies the bitonic comparator's compile cost; the K payload rows
    # are gathered by index afterwards (tiny gather, not scatter)
    idx = jnp.arange(k + n_rows, dtype=jnp.int64)
    sorted_ops = jax.lax.sort(merged + [idx], num_keys=n_key_ops, is_stable=True)
    top = [op[:k] for op in sorted_ops[:n_key_ops]]
    top_idx = sorted_ops[n_key_ops][:k]
    payload = []
    pbase = n_key_ops
    for j, ci in enumerate(payload_cols):
        bd, bn = cols[ci]
        sd = state[pbase + 2 * j]
        sn = state[pbase + 2 * j + 1]
        payload.append(jnp.concatenate([sd, bd])[top_idx])
        payload.append(jnp.concatenate([sn, bn])[top_idx])
    return tuple(top + payload)


def _pack_leaves(leaves):
    """Stack arbitrary leaves into (int64 matrix, float64 matrix) for a
    single-pull finalize; non-float leaves are widened to int64."""
    ints = [a.astype(jnp.int64) for a in leaves if a.dtype != jnp.float64]
    flts = [a for a in leaves if a.dtype == jnp.float64]
    k = leaves[0].shape[0]
    int_m = jnp.stack(ints) if ints else jnp.zeros((0, k), dtype=jnp.int64)
    flt_m = jnp.stack(flts) if flts else jnp.zeros((0, k), dtype=jnp.float64)
    return int_m, flt_m


def _unpack_leaves(packed, dtypes):
    int_m, flt_m = packed
    int_np = np.asarray(int_m)
    # all-integer states must stay ONE pull (the tunnel charges per RPC)
    flt_np = np.asarray(flt_m) if flt_m.shape[0] else None
    out, ii, fi = [], 0, 0
    for dt in dtypes:
        if dt == np.float64:
            out.append(flt_np[fi])
            fi += 1
        else:
            out.append(int_np[ii].astype(dt))
            ii += 1
    return out


def _pack_state(state):
    """Flatten (first_row, carries) into at most two matrices on device (one
    int64, one float64) — the tunnel charges a flat latency per device→host
    pull, so finalize pulls once for all-integer queries, twice with REAL
    aggregates (TPU's x64 emulation cannot bitcast f64 to int lanes).
    Thin wrapper over _pack_leaves so the int/float partition contract has
    exactly one implementation."""
    first_row, carries = state
    return _pack_leaves([first_row] + jax.tree.leaves(carries))


def _unpack_state(packed, state_template):
    """Host-side inverse of _pack_state, restoring the leaf order."""
    first_t, carries_t = state_template
    leaves_t = [first_t] + jax.tree.leaves(carries_t)
    out = _unpack_leaves(packed, [t.dtype for t in leaves_t])
    treedef = jax.tree.structure(carries_t)
    return out[0], jax.tree.unflatten(treedef, out[1:])


class JaxDagEvaluator:
    """Run an eligible DAG over a scan source on the device."""

    def __init__(self, dag: DagRequest, block_rows: int = DEFAULT_BLOCK_ROWS,
                 breaker=None):
        self.dag = dag
        self.plan = _analyze(dag)
        self.block_rows = block_rows
        # observatory profile key (docs/observatory.md): the scheduler's
        # plan-signature normalization, so profiles and micro-batches key
        # identically; compile events at this evaluator's jit boundaries
        # carry the sig into the device-cost ledger
        self.obs_sig, self.obs_desc = _obs.dag_sig(dag)
        # optional DeviceCircuitBreaker (copr/breaker.py): the zone path
        # consults it before running and reports its outcome, so repeated
        # zone faults trip to the generic warm path instead of re-crashing
        self.breaker = breaker
        # cost-router steering (docs/cost_router.md): "unary" skips the
        # zone probe for this run; set/cleared around run() by the
        # endpoint — a concurrent mis-read only picks a different
        # byte-identical warm rung
        self.route_hint: str | None = None
        scan = self.plan.scan
        self.schema = [(c.ftype.eval_type, c.ftype.decimal) for c in scan.columns_info]
        self.decoder = (
            RowBatchDecoder(scan.columns_info) if isinstance(scan, TableScan) else None
        )
        self.sel_rpns = (
            [compile_expr(c, self.schema) for c in self.plan.selection.conditions]
            if self.plan.selection
            else []
        )
        agg = self.plan.agg
        if agg is not None:
            self.group_rpns = [compile_expr(g, self.schema) for g in agg.group_by]
            self.device_aggs = [
                _DeviceAgg(a.op, compile_expr(a.expr, self.schema) if a.expr else None)
                for a in agg.agg_funcs
            ]
        else:
            self.group_rpns = []
            self.device_aggs = []
        if self.plan.topn is not None and agg is None:
            self.topn_rpns = [
                (compile_expr(e, self.schema), desc) for e, desc in self.plan.topn.order_by
            ]
        else:
            self.topn_rpns = []
        # which leaf columns must ship to the device
        need: set[int] = set()
        for r in self.sel_rpns:
            need |= r.referenced_columns()
        for da in self.device_aggs:
            if da.rpn is not None:
                need |= da.rpn.referenced_columns()
        if self.topn_rpns:
            # raw TopN outputs whole rows: every schema column is payload
            need |= set(range(len(self.schema)))
            for r, _d in self.topn_rpns:
                need |= r.referenced_columns()
        self.device_cols = sorted(need)
        # columns declared NOT NULL never ship a null mask — the device step
        # folds a constant all-false mask (XLA constant-propagates it away)
        from .datatypes import NOT_NULL_FLAG

        self.nullable_cols = [
            i for i in self.device_cols
            if not (scan.columns_info[i].ftype.flag & NOT_NULL_FLAG)
        ]
        self._capacity = _GROUP_CAPACITY_START if self.group_rpns else 1
        self._agg_fn_cache: dict[int, object] = {}

    # -- jit construction --------------------------------------------------

    def _build_mask_fn(self, enc=None):
        key = ("mask", enc)
        cached = self._agg_fn_cache.get(key)
        if cached is not None:
            return cached
        sel_rpns = self.sel_rpns
        device_cols = self.device_cols
        nullable = self.nullable_cols
        n_rows = self.block_rows

        def mask_fn(col_data, col_nulls, valid, refs):
            cols = _build_cols(device_cols, nullable, col_data, col_nulls,
                               n_rows, enc, refs)
            active = valid
            for rpn in sel_rpns:
                d, nl = eval_rpn(rpn, cols, n_rows, xp=jnp)
                active = active & (d != 0) & ~nl
            return active

        fn = _obs.timed_jit(jax.jit(mask_fn), "jax_eval.mask", "unary",
                            self.obs_sig)
        self._agg_fn_cache[key] = fn
        return fn

    def _build_agg_fn(self, capacity: int):
        """One fused device step per block: selection predicates, aggregate
        updates, AND the per-group first-active-row tracker all inside a
        single jit call, with the carry donated — so the whole block loop is
        async dispatches with ZERO device→host syncs (critical when the TPU
        sits behind a high-latency tunnel)."""
        cached = self._agg_fn_cache.get(capacity)
        if cached is not None:
            return cached
        device_aggs = self.device_aggs
        device_cols = self.device_cols
        nullable = self.nullable_cols
        sel_rpns = self.sel_rpns
        n_rows = self.block_rows
        track_first = bool(self.group_rpns)

        def agg_fn(col_data, col_nulls, n_valid, gids, block_offset, state):
            cols = _build_cols(device_cols, nullable, col_data, col_nulls, n_rows)
            return _fused_step(
                sel_rpns, device_aggs, capacity, n_rows, cols, n_valid, gids, block_offset, state,
                track_first=track_first,
            )

        fn = _obs.timed_jit(jax.jit(agg_fn, donate_argnums=(5,)),
                            "jax_eval.agg_step", "unary", self.obs_sig)
        self._agg_fn_cache[capacity] = fn
        return fn

    def _build_scan_fn(self, capacity: int, n_blocks: int, enc=None):
        """Whole-query device program for the warm-cache path: one jit call
        lax.scans the fused block step over ALL resident blocks — a single
        host→device round trip per query, which is what makes the TPU path
        latency-proof behind a high-RTT tunnel."""
        key = ("scan", capacity, n_blocks, enc)
        cached = self._agg_fn_cache.get(key)
        if cached is not None:
            return cached
        device_aggs = self.device_aggs
        device_cols = self.device_cols
        nullable = self.nullable_cols
        sel_rpns = self.sel_rpns
        n_rows = self.block_rows
        track_first = bool(self.group_rpns)

        def scan_fn(col_data, col_nulls, n_valids, gids, offsets, refs):
            state = (
                jnp.full(capacity, _NO_ROW, dtype=jnp.int64),
                tuple(da.init_carry(capacity) for da in device_aggs),
            )

            def body(st, xs):
                cd, cn, nv, g, off = xs
                cols = _build_cols(device_cols, nullable, cd, cn, n_rows, enc, refs)
                return _fused_step(sel_rpns, device_aggs, capacity, n_rows, cols, nv, g, off, st,
                                   track_first=track_first), None

            state, _ = jax.lax.scan(body, state, (col_data, col_nulls, n_valids, gids, offsets))
            # pack everything into ONE int64 matrix: the tunnel charges a flat
            # latency per device→host pull, so finalize must pull once
            return _pack_state(state)

        fn = _obs.timed_jit(jax.jit(scan_fn), "jax_eval.scan", "unary",
                            self.obs_sig)
        self._agg_fn_cache[key] = fn
        return fn

    def _build_scan_fn_coded(self, dict_lens: tuple, capacity: int, n_blocks: int, group_cols: list, enc=None):
        """Warm-path whole-query program where group ids are computed ON the
        device from resident dictionary codes (stable dictionaries): zero
        per-row host→device traffic per query."""
        key = ("scancoded", dict_lens, capacity, n_blocks, enc)
        cached = self._agg_fn_cache.get(key)
        if cached is not None:
            return cached
        device_aggs = self.device_aggs
        ship_cols = self._ship_cols(group_cols)
        nullable = self.nullable_cols
        sel_rpns = self.sel_rpns
        n_rows = self.block_rows
        track_first = bool(self.group_rpns)

        def scan_fn(col_data, col_nulls, n_valids, offsets, refs):
            state = (
                jnp.full(capacity, _NO_ROW, dtype=jnp.int64),
                tuple(da.init_carry(capacity) for da in device_aggs),
            )

            def body(st, xs):
                cd, cn, nv, off = xs
                cols = _build_cols(ship_cols, nullable, cd, cn, n_rows, enc, refs)
                gids = _mixed_radix_gids(cols, group_cols, dict_lens, n_rows)
                return _fused_step(sel_rpns, device_aggs, capacity, n_rows, cols, nv, gids, off, st,
                                   track_first=track_first), None

            state, _ = jax.lax.scan(body, state, (col_data, col_nulls, n_valids, offsets))
            return _pack_state(state)

        fn = _obs.timed_jit(jax.jit(scan_fn), "jax_eval.scan_coded", "unary",
                            self.obs_sig)
        self._agg_fn_cache[key] = fn
        return fn

    def _ship_cols(self, extra: list) -> list:
        return self.device_cols + [i for i in extra if i not in self.device_cols]

    def ship_extra_columns(self, extra) -> None:
        """Permanently extend the shipped column set (mesh evaluators build
        group dictionaries ON device, so group-by columns must ship even
        though the single-device path codes them on the host).  Keeps
        nullable_cols consistent — the NOT_NULL rule lives only here."""
        from .datatypes import NOT_NULL_FLAG

        need = set(self.device_cols) | set(extra)
        self.device_cols = sorted(need)
        scan = self.plan.scan
        self.nullable_cols = [
            i for i in self.device_cols
            if not (scan.columns_info[i].ftype.flag & NOT_NULL_FLAG)
        ]
        # derived jit caches keyed on the column set are now stale
        self._agg_fn_cache = {}

    def _stable_dict_group_cols(self, blocks):
        """If every group expr is a bare ref to a dict-encoded column whose
        dictionary object is shared by ALL cached blocks, return (col_idx
        list, dict list) — else None.  No group-by at all qualifies trivially
        (single slot, no codes needed) — crucially this keeps the zero-
        per-row-transfer path for simple aggregations."""
        if not self.group_rpns:
            return [], []
        idxs = []
        for g in self.group_rpns:
            if len(g.nodes) != 1 or g.nodes[0].kind != "col":
                return None
            idxs.append(g.nodes[0].index)
        dicts = []
        for i in idxs:
            c0 = blocks[0].cols[i]
            if not c0.is_dict_encoded:
                return None
            for b in blocks[1:]:
                if b.cols[i].dictionary is not c0.dictionary:
                    return None
            dicts.append(c0.dictionary)
        cap = 1
        for d in dicts:
            cap *= len(d) + 1
        if cap > (1 << 20):
            return None
        return idxs, dicts

    def _run_aggregated_cached(self, cache) -> SelectResponse:
        """Warm path: every block resident on device, one dispatch total.

        Tries the zone-tiled clustered layout first (jax_zone.py): group-
        clustered, range-sorted, narrowed tiles whose full/empty/partial
        classification turns most of the work into pure unmasked reductions.
        Falls back to the generic stacked-block scan when the plan or the
        data shape isn't zone-eligible."""
        blocks = cache.blocks
        n_blocks = len(blocks)

        zone_resp = None if self.route_hint == "unary" else self._try_zone(cache)
        if zone_resp is not None:
            # observatory path marker (docs/observatory.md): the endpoint
            # reads which warm rung actually served, per response
            zone_resp._obs_path = "zone"
            return zone_resp

        # zone-map pruning (docs/zone_maps.md): the stacked programs keep
        # their compile keys — survivor counts ship through the dynamic
        # ``n_valids`` geometry they already consume, so a pruned block's
        # rows are all invalid and contribute to no aggregate or group
        keep, prune_stats = self._prune_keep(cache, "unary")

        stable = self._stable_dict_group_cols(blocks)
        if stable is not None:
            group_cols, dicts = stable
            dict_lens = tuple(len(d) for d in dicts)
            n_slots = 1
            for dl in dict_lens:
                n_slots *= dl + 1
            capacity = 1
            while capacity < n_slots:
                capacity *= 2
            ship = self._ship_cols(group_cols)
            col_data, col_nulls, refs, enc = self._stacked_device(cache, blocks, ship)
            nv_dev, off_dev = self._nvoff_device(cache, blocks)
            if keep is not None:
                nv_dev = _masked_nv(blocks, keep)
            scan_fn = self._build_scan_fn_coded(dict_lens, capacity, n_blocks, group_cols, enc)
            packed = scan_fn(col_data, col_nulls, nv_dev, off_dev, refs)
            state_np = _unpack_state(packed, self._host_state_template())

            def key_of(slot: int) -> tuple:
                parts = []
                rem = int(slot)
                for d, dl in zip(reversed(dicts), reversed(dict_lens)):
                    c = rem % (dl + 1)
                    rem //= dl + 1
                    parts.append(None if c == dl else bytes(d[c]))
                return tuple(reversed(parts))

            resp = self._finalize_agg(state_np, n_slots, key_of)
            resp._obs_encoding = "encoded" if enc else "plain"
            if prune_stats[0]:
                resp._obs_prune = prune_stats
            return resp

        groups = GroupDict()
        all_gids = np.zeros((n_blocks, self.block_rows), dtype=np.int32)
        for bi, blk in enumerate(blocks):
            if self.group_rpns and (keep is None or keep[bi]):
                # pruned blocks skip host gid assignment too: none of their
                # rows can be active, and groups they alone would introduce
                # stay empty and drop at finalize either way
                gids_np, _ = self._assign_gids(blk.cols, blk.n_valid, groups)
                all_gids[bi] = gids_np
        n_slots = len(groups) if self.group_rpns else 1
        capacity = _GROUP_CAPACITY_START if self.group_rpns else 1
        while capacity < n_slots:
            capacity *= 2

        col_data, col_nulls, refs, enc = self._stacked_device(cache, blocks, self.device_cols)
        nv_dev, off_dev = self._nvoff_device(cache, blocks)
        if keep is not None:
            nv_dev = _masked_nv(blocks, keep)
        scan_fn = self._build_scan_fn(capacity, n_blocks, enc)
        packed = scan_fn(col_data, col_nulls, nv_dev, all_gids, off_dev, refs)
        state_np = _unpack_state(packed, self._host_state_template())
        resp = self._finalize_agg(state_np, n_slots, lambda r: groups.rows[r])
        resp._obs_encoding = "encoded" if enc else "plain"
        if prune_stats[0]:
            resp._obs_prune = prune_stats
        return resp

    def _try_zone(self, cache) -> SelectResponse | None:
        """ONE definition of the zone-path protocol: probe, run, finalize.

        try_run owns the crash-fallback protocol (failures recorded and
        remembered inside ZoneEvaluator), so a None here simply means
        "serve through the generic warm path"."""
        zone = self._zone_evaluator()
        if zone is None:
            return None
        out = zone.try_run(cache)
        if out is None:
            return None
        state_np, n_slots, key_of = out
        return self._finalize_agg(state_np, n_slots, key_of)

    def _zone_evaluator(self):
        """Lazily constructed zone-path runner (None when plainly ineligible)."""
        zone = getattr(self, "_zone", None)
        if zone is False:
            return None
        if zone is None:
            from .jax_zone import ZoneEvaluator, _ZONE_AGG_OPS

            if self.plan.agg is None or any(
                da.op not in _ZONE_AGG_OPS for da in self.device_aggs
            ):
                self._zone = False
                return None
            zone = self._zone = ZoneEvaluator(self)
        return zone

    def _host_state_template(self):
        return (
            np.zeros(0, dtype=np.int64),
            tuple(da.host_template() for da in self.device_aggs),
        )

    def _prune_keep(self, cache, path: str):
        """(keep_mask | None, (examined, pruned)) for a warm cache under
        this plan's selection conjuncts (copr/zone_maps.py) — the prune
        planner sitting between ``encoding.device_plan`` and the
        launchers.  None keep means "prune proved nothing": callers run
        their exact pre-zone-map path."""
        from . import zone_maps as _zm

        if cache is None or not getattr(cache, "filled", False) or not cache.blocks:
            return None, (0, 0)
        stats = _zm.PruneStats()
        keep = _zm.prune_blocks(cache, self.sel_rpns, path=path, stats=stats)
        return keep, (stats.examined, stats.pruned)

    def _nvoff_device(self, cache, blocks):
        """Per-cache pinned n_valids / offsets device arrays."""
        sig = ("nvoff", self.block_rows)

        def build(_blk):
            note_blocking("device.pin:nvoff")
            nv = np.array([b.n_valid for b in blocks], dtype=np.int64)
            off = np.concatenate([[0], np.cumsum(nv)[:-1]]).astype(np.int64)
            return jax.block_until_ready((jnp.asarray(nv), jnp.asarray(off)))

        return cache.device_arrays(blocks[0], sig, build)

    def _stacked_device(self, cache, blocks, ship_cols, nullable_cols=None,
                        plan=_MISSING_PLAN):
        """(B, n_rows)-stacked device arrays for the given columns, pinned
        in the cache so later queries reuse them without any transfer.

        Returns ``(data, nulls, refs, enc)``: with an encoding plan
        (``copr/encoding.py``) the pinned arrays are the ENCODED payloads
        (narrow lanes, run pairs) plus the dynamic frame-of-reference
        vector, and ``enc`` is the static descriptor tuple callers bake
        into their jit keys; plain images pin exactly as before
        (``refs``/``enc`` = None)."""
        from . import encoding as _encoding

        nullable = self.nullable_cols if nullable_cols is None else nullable_cols
        if plan is _MISSING_PLAN:
            plan = _encoding.device_plan(cache, ship_cols, nullable)
        if plan is None:
            sig = ("stacked", tuple(ship_cols), tuple(nullable), self.block_rows)

            def build(_blk):
                note_blocking("device.pin:stacked")
                # decoded_data/nulls: a decode-SHIP of an encoded image
                # (cross-region signature mismatch) must not leave a full
                # decode cached on the column — the budget counts encoded
                data = tuple(
                    jnp.stack([jnp.asarray(self._pad(_encoding.decoded_data(b.cols[i]))) for b in blocks])
                    for i in ship_cols
                )
                nulls = tuple(
                    jnp.stack([jnp.asarray(self._pad(_encoding.decoded_nulls(b.cols[i]), True)) for b in blocks])
                    for i in nullable
                )
                return jax.block_until_ready((data, nulls))

            data, nulls = cache.device_arrays(blocks[0], sig, build)
            return data, nulls, None, None
        sig = ("stackedenc", tuple(ship_cols), tuple(nullable),
               self.block_rows, plan.sig, plan.null_sig)

        def build_enc(_blk):
            note_blocking("device.pin:stacked_encoded")
            data, nulls, refs = _encoding.stack_block_payloads(
                blocks, ship_cols, nullable, plan, self.block_rows)
            entry = jax.tree.map(jnp.asarray, (tuple(data), tuple(nulls), refs))
            return jax.block_until_ready(entry)

        data, nulls, refs = cache.device_arrays(blocks[0], sig, build_enc)
        return data, nulls, refs, plan.sig

    # -- host loop ---------------------------------------------------------

    def run(self, source: ScanSource, cache: "ColumnBlockCache | None" = None) -> SelectResponse:
        self._cache = cache
        # first run of an evaluator traces+compiles its XLA programs; later
        # runs reuse the jit caches — the tag separates compile cost from
        # steady-state execute+pull in the trace timeline (docs/tracing.md)
        first = not getattr(self, "_trace_ran", False)
        self._trace_ran = True
        if self.plan.agg is not None:
            path = "agg_cached" if (cache is not None and cache.filled
                                    and cache.blocks) else "agg"
        elif self.topn_rpns:
            path = "topn"
        else:
            path = "scan"
        try:
            with trace.span("device.run", path=path, first_call=first):
                if self.plan.agg is not None:
                    if cache is not None and cache.filled and cache.blocks:
                        return self._run_aggregated_cached(cache)
                    return self._run_aggregated(source)
                if self.topn_rpns:
                    return self._run_topn(source)
                return self._run_scan_filter(source)
        finally:
            self._cache = None

    def _blocks(self, source: ScanSource | None):
        """Decoded blocks, through the block cache when one is provided.
        Cold scans (no cache) run the host MVCC decode ONE BLOCK AHEAD on a
        worker thread (SURVEY §7's double-buffering): block N executes on
        the device while block N+1 decodes — the decode cost hides behind
        device time instead of adding to it."""
        cache = getattr(self, "_cache", None)
        if cache is None:
            if source is None:
                raise ValueError("no scan source and no filled block cache")
            yield from _prefetch(self._decode_blocks(source))
            return
        if not cache.filled:
            if source is None:
                raise ValueError("block cache is not filled and no source given")
            for cols, n_valid in _prefetch(self._decode_blocks(source)):
                cache.add(cols, n_valid)
            cache.filled = True
        yield from cache

    def _device_block(self, cols, n_valid):
        """(col_data, col_nulls, refs, enc) device-ready arrays; served
        from the block cache's HBM-pinned entries when a cache is active —
        as ENCODED payloads (narrow lanes / runs) when the image is encoded
        (copr/encoding.py), so per-block warm serving pins encoded HBM
        too."""
        from . import encoding as _encoding

        cache = getattr(self, "_cache", None)
        build = lambda blk: (
            [jnp.asarray(self._pad(blk.cols[i].data)) for i in self.device_cols],
            [jnp.asarray(self._pad(blk.cols[i].nulls, True)) for i in self.nullable_cols],
        )
        if cache is not None and cache.filled:
            plan = _encoding.device_plan(cache, self.device_cols, self.nullable_cols)
            for blk in cache.blocks:
                if blk.cols is cols:
                    if plan is None:
                        sig = (tuple(self.device_cols), tuple(self.nullable_cols), self.block_rows)
                        d, nl = cache.device_arrays(blk, sig, build)
                        return d, nl, None, None
                    sig = ("blockenc", tuple(self.device_cols),
                           tuple(self.nullable_cols), self.block_rows,
                           plan.sig, plan.null_sig)

                    def build_enc(blk):
                        note_blocking("device.pin:block_encoded")
                        br = self.block_rows
                        data = []
                        for j, i in enumerate(self.device_cols):
                            p = _encoding.block_payload(blk.cols[i], br)
                            data.append(
                                (jnp.asarray(p[0]), jnp.asarray(p[1]))
                                if plan.sig[j][0] == "rle" else jnp.asarray(p)
                            )
                        nulls = [
                            jnp.asarray(_encoding.block_null_payload(blk.cols[i], br))
                            for i in self.nullable_cols
                        ]
                        return jax.block_until_ready(
                            (data, nulls, jnp.asarray(plan.refs)))

                    d, nl, refs = cache.device_arrays(blk, sig, build_enc)
                    return d, nl, refs, plan.sig
        col_data = [self._pad(cols[i].data) for i in self.device_cols]
        col_nulls = [self._pad(cols[i].nulls, True) for i in self.nullable_cols]
        return col_data, col_nulls, None, None

    def _decode_blocks(self, source: ScanSource):
        """Yield (columns, n_valid) blocks of exactly block_rows rows (padded)."""
        if isinstance(self.plan.scan, IndexScan):
            yield from self._decode_blocks_index(source)
            return
        br = self.block_rows
        pend_handles: list[np.ndarray] = []
        pend_values: list[bytes] = []
        drained = False
        while not drained:
            keys, values, drained = source.next_batch(br)
            if keys:
                pend_handles.append(decode_record_handles(keys))
                pend_values.extend(values)
            total = sum(len(x) for x in pend_handles)
            while total >= br or (drained and total > 0):
                handles = np.concatenate(pend_handles) if len(pend_handles) > 1 else pend_handles[0]
                take = min(br, total)
                block_h, rest_h = handles[:take], handles[take:]
                block_v, rest_v = pend_values[:take], pend_values[take:]
                pend_handles = [rest_h] if len(rest_h) else []
                pend_values = rest_v
                total = len(rest_h)
                cols = self.decoder.decode(block_h, block_v)
                yield cols, take

    def _decode_blocks_index(self, source: ScanSource):
        """Index-scan leaf (index_scan_executor.rs:29): decode index entries
        through the same BatchIndexScanExecutor the CPU pipeline uses, then
        re-block its chunks to exactly block_rows rows so the device step
        sees the fixed shapes it compiled for."""
        from .executors import BatchIndexScanExecutor
        from .table import index_range

        scan = self.plan.scan
        prefix_len = len(index_range(scan.table_id, scan.index_id)[0])
        ex = BatchIndexScanExecutor(source, scan.columns_info, prefix_len)
        br = self.block_rows
        pend: list = []  # list of column lists
        total = 0
        drained = False
        while not drained:
            r = ex.next_batch(br)
            drained = r.is_drained
            chunk = r.chunk
            n = len(chunk.columns[0]) if chunk.columns else 0
            if n:
                pend.append(chunk.columns)
                total += n
            while total >= br or (drained and total > 0):
                take = min(br, total)
                cols: list[Column] = []
                rest: list[Column] = []
                for ci in range(len(scan.columns_info)):
                    parts = [p[ci] for p in pend]
                    data = np.concatenate([np.asarray(c.data) for c in parts])
                    nulls = np.concatenate([np.asarray(c.nulls) for c in parts])
                    cols.append(
                        Column(parts[0].eval_type, data[:take], nulls[:take], parts[0].frac)
                    )
                    if total > take:
                        rest.append(
                            Column(parts[0].eval_type, data[take:], nulls[take:], parts[0].frac)
                        )
                pend = [rest] if total > take else []
                total -= take
                yield cols, take

    def _pad(self, arr: np.ndarray, fill=0) -> np.ndarray:
        n = len(arr)
        if n == self.block_rows:
            return arr
        pad = self.block_rows - n
        if arr.dtype == object:
            ext = np.empty(pad, dtype=object)
            ext[:] = b""
            return np.concatenate([arr, ext])
        return np.concatenate([arr, np.full(pad, fill, dtype=arr.dtype)])

    def _run_aggregated(self, source: ScanSource) -> SelectResponse:
        """Block loop with no device→host traffic until finalize.

        Group ids are assigned on host over ALL valid rows (pre-selection):
        groups whose every row the device filters out end up with
        ``first_row == _NO_ROW`` and are dropped at finalize, and surviving
        groups are ordered by their first *active* row — so the output is
        byte-identical to the CPU path without ever pulling the mask back.
        """
        groups = GroupDict()
        capacity = self._capacity
        agg_fn = self._build_agg_fn(capacity)
        carries = tuple(da.init_carry(capacity) for da in self.device_aggs)
        first_row = jnp.full(capacity, _NO_ROW, dtype=jnp.int64)
        state = (first_row, carries)
        offset = 0

        for cols, n_valid in self._blocks(source):
            # cold/COP-cache blocks are always decoded (only region images
            # encode, and those route through _run_aggregated_cached); if an
            # encoded image ever lands here, ship it decoded — this block
            # step compiles without the in-kernel decode
            col_data, col_nulls, _refs, _enc = self._device_block(cols, n_valid)
            if _enc is not None:
                # unreachable today: run() routes every filled cache to
                # _run_aggregated_cached and only region images encode —
                # but this block step compiles WITHOUT the in-kernel
                # decode, so silently feeding it narrow lanes would be
                # wrong math; fail loudly and let the endpoint's CPU
                # fallback serve
                raise RuntimeError("encoded image reached the cold block path")
            if self.group_rpns:
                gids_np, n_groups = self._assign_gids(cols, n_valid, groups)
                if n_groups > capacity:
                    # grow to the next bucket and re-jit once; state migrates
                    new_capacity = capacity
                    while n_groups > new_capacity:
                        new_capacity *= 2
                    old_first, old_carries = state
                    new_first = jnp.full(new_capacity, _NO_ROW, dtype=jnp.int64)
                    new_first = new_first.at[:capacity].set(old_first)
                    new_carries = tuple(
                        _grow_carry(da, c, new_capacity)
                        for da, c in zip(self.device_aggs, old_carries)
                    )
                    state = (new_first, new_carries)
                    capacity = new_capacity
                    self._capacity = capacity
                    agg_fn = self._build_agg_fn(capacity)
            else:
                gids_np = _ZERO_GIDS.setdefault(self.block_rows, np.zeros(self.block_rows, dtype=np.int32))
            state = agg_fn(col_data, col_nulls, n_valid, gids_np, offset, state)
            offset += n_valid

        n_slots = len(groups) if self.group_rpns else 1
        pack_key = ("pack", capacity)
        pack_fn = self._agg_fn_cache.get(pack_key)
        if pack_fn is None:
            pack_fn = _obs.timed_jit(jax.jit(_pack_state), "jax_eval.pack",
                                     "unary", self.obs_sig)
            self._agg_fn_cache[pack_key] = pack_fn
        state_np = _unpack_state(pack_fn(state), state)
        return self._finalize_agg(state_np, n_slots, lambda r: groups.rows[r])

    def _finalize_agg(self, state, n_slots: int, key_of) -> SelectResponse:
        first_row, carries = state
        first_np = np.asarray(first_row)
        alive = np.flatnonzero(first_np[:n_slots] != _NO_ROW) if self.group_rpns else np.array([0])
        if self.group_rpns:
            order = alive[np.argsort(first_np[alive], kind="stable")]
        else:
            order = alive
        states = [
            da.to_state(jax.tree.map(np.asarray, c), n_slots)
            for da, c in zip(self.device_aggs, carries)
        ]
        out_cols: list[Column] = []
        for st in states:
            for c in st.result_columns(n_slots):
                out_cols.append(c.take(order))
        for gi, g in enumerate(self.group_rpns):
            vals = [key_of(r)[gi] for r in order]
            out_cols.append(Column.from_values(g.eval_type, vals, g.frac))
        chunk = Chunk.full(out_cols)
        # post-agg TopN / Limit are tiny — run them via the CPU executors
        chunk = self._post_agg(chunk)
        enc = make_response_encoder(self.dag)
        enc.add_chunk(chunk, self.dag.output_offsets)
        return enc.to_response()

    def _assign_gids(self, cols, n_valid: int, groups: GroupDict):
        from .executors import _coded_group_parts, cols_for_eval

        rows = np.arange(n_valid)
        # bare dict-encoded group columns: dense-code path, no unique pass
        coded = _coded_group_parts(self.group_rpns, cols, rows)
        if coded is not None:
            gids = np.zeros(self.block_rows, dtype=np.int32)
            if len(coded) == 1:
                gids[:n_valid] = groups.assign_coded(*coded[0])
            else:
                gids[:n_valid] = groups.assign_coded_multi(coded)
            return gids, len(groups)
        needed = set()
        for g in self.group_rpns:
            needed |= g.referenced_columns()
        n = len(cols[0]) if cols else 0
        np_cols = cols_for_eval(cols, needed)
        parts = []
        for g in self.group_rpns:
            d, nl = eval_rpn(g, np_cols, n, xp=np)
            parts.append((np.asarray(d)[:n_valid], np.asarray(nl)[:n_valid]))
        gids = np.zeros(self.block_rows, dtype=np.int32)
        gids[:n_valid] = groups.assign(parts)
        return gids, len(groups)

    def _post_agg(self, chunk: Chunk) -> Chunk:
        """Apply TopN/Limit over the (small) aggregated output on host."""
        schema = None
        if self.plan.topn is not None:
            agg_schema = self._agg_output_schema()
            ex = BatchTopNExecutor(_ChunkExecutor(chunk, agg_schema), self.plan.topn.order_by, self.plan.topn.limit)
            chunk = ex.next_batch(len(chunk.logical_rows) or 1).chunk
        if self.plan.limit is not None:
            chunk = Chunk(chunk.columns, chunk.logical_rows[: self.plan.limit.limit])
        return chunk

    def _agg_output_schema(self):
        out = []
        for da, a in zip(self.device_aggs, self.plan.agg.agg_funcs):
            it, frac = da.input_type, da.frac
            if a.op == "count":
                out.append((EvalType.INT, 0))
            elif a.op == "avg":
                out.append((EvalType.INT, 0))
                out.append((it, frac))
            elif a.op == "var_pop":
                out.extend([(EvalType.INT, 0), (EvalType.REAL, 0), (EvalType.REAL, 0)])
            elif a.op in ("bit_and", "bit_or", "bit_xor"):
                out.append((EvalType.INT, 0))
            else:
                out.append((it, frac))
        for g in self.group_rpns:
            out.append((g.eval_type, g.frac))
        return out

    # -- raw TopN pipeline -------------------------------------------------

    def _topn_key_operand_count(self) -> int:
        return 1 + 2 * len(self.topn_rpns)  # global rank + (null-rank, key) each

    def _topn_state_dtypes(self):
        dts = [np.int64]
        for rpn, _desc in self.topn_rpns:
            dts += [np.int64, _np_dtype(rpn.eval_type)]
        for ci in range(len(self.schema)):
            dts += [_np_dtype(self.schema[ci][0]), np.bool_]
        return dts

    def _build_topn_fn(self, k: int, enc=None):
        key = ("topn", k, enc)
        cached = self._agg_fn_cache.get(key)
        if cached is not None:
            return cached
        sel_rpns = self.sel_rpns
        order_rpns = self.topn_rpns
        device_cols = self.device_cols
        nullable = self.nullable_cols
        n_rows = self.block_rows
        payload_cols = list(range(len(self.schema)))

        def step(col_data, col_nulls, n_valid, state, refs):
            cols = _build_cols(device_cols, nullable, col_data, col_nulls,
                               n_rows, enc, refs)
            return _topn_step(
                sel_rpns, order_rpns, payload_cols, k, n_rows, cols, n_valid, state
            )

        fn = _obs.timed_jit(jax.jit(step, donate_argnums=(3,)),
                            "jax_eval.topn", "unary", self.obs_sig)
        self._agg_fn_cache[key] = fn
        return fn

    def _run_topn(self, source: ScanSource) -> SelectResponse:
        """TableScan → Selection? → TopN (no aggregation): a running top-K
        lives ON the device — per block one fused dispatch computes selection
        + sort operands and stable-sort-merges the carried best K, so the
        whole query is async dispatches plus ONE packed pull of K rows.
        The sort-operand encoding reproduces the CPU executor's comparator
        bit-for-bit, so responses stay byte-identical."""
        k = self.plan.topn.limit
        if self.plan.limit is not None:
            k = min(k, self.plan.limit.limit)
        if k == 0:
            return make_response_encoder(self.dag).to_response()
        dtypes = self._topn_state_dtypes()
        jdt = {np.float64: jnp.float64, np.bool_: jnp.bool_}
        state = tuple(
            # empty slots carry rank 1 (sorted last, excluded at finalize)
            (jnp.ones if i == 0 else jnp.zeros)(k, dtype=jdt.get(dt, jnp.int64))
            for i, dt in enumerate(dtypes)
        )
        bytes_cols = [
            ci for ci, (et, _f) in enumerate(self.schema) if et == EvalType.BYTES
        ]
        payload_dicts: dict[int, np.ndarray] = {}
        step = None
        cache = getattr(self, "_cache", None)
        keep, prune_stats = self._prune_keep(cache, "unary")
        # zone-order early exit (docs/zone_maps.md): with no selection and a
        # bare-column first sort key, zone bounds alone can prove which
        # blocks may still contribute to the top-k — the rest never launch.
        # Blocks stay in STREAM order (tie-breaks are stream-ordered), only
        # provably-dominated ones drop out, so the bytes cannot change.
        if (cache is not None and cache.filled and cache.blocks
                and not self.sel_rpns):
            from . import zone_maps as _zm

            rpn0, desc0 = self.topn_rpns[0]
            if (_zm.enabled() and len(rpn0.nodes) == 1
                    and rpn0.nodes[0].kind == "col"
                    and _zm.ensure_zones(cache)):
                base = (keep if keep is not None
                        else np.ones(len(cache.blocks), dtype=bool))
                cut = _zm.topn_cutoff_order(
                    cache.blocks, base, rpn0.nodes[0].index, bool(desc0), k)
                exited = int((base & ~cut).sum()) if cut is not None else 0
                if exited:
                    keep = cut
                    _zm.count_prune("unary", "early_exit", exited)
                    prune_stats = (prune_stats[0] or len(cache.blocks),
                                   prune_stats[1] + exited)
        for bi, (cols, n_valid) in enumerate(self._blocks(source)):
            for ci in bytes_cols:
                # BYTES payloads ride as dictionary codes; every block must
                # agree on the dictionary or the codes are meaningless (the
                # endpoint's CPU fallback catches this raise)
                d = cols[ci].dictionary
                if d is None:
                    raise ValueError(f"TopN BYTES payload column {ci} not dict-coded")
                seen = payload_dicts.setdefault(ci, d)
                if seen is not d and (
                    len(seen) != len(d) or any(a != b for a, b in zip(seen, d))
                ):
                    raise ValueError(f"TopN BYTES payload column {ci}: unstable dictionary")
            if keep is not None and not keep[bi]:
                continue  # zone-pruned / dominated: contributes no top-k row
            col_data, col_nulls, refs, enc_sig = self._device_block(cols, n_valid)
            if step is None:
                # the encoding signature is uniform across one source's
                # blocks (images encode image-wide), so the first block
                # fixes the compiled program
                step = self._build_topn_fn(k, enc_sig)
            state = step(col_data, col_nulls, n_valid, state, refs)
        pack_key = ("packtopn", k)
        pack_fn = self._agg_fn_cache.get(pack_key)
        if pack_fn is None:
            pack_fn = self._agg_fn_cache[pack_key] = _obs.timed_jit(
                jax.jit(lambda st: _pack_leaves(list(st))),
                "jax_eval.pack_topn", "unary", self.obs_sig)
        leaves = _unpack_leaves(pack_fn(state), dtypes)
        rank = leaves[0]
        n_out = int((rank == 0).sum())
        base = self._topn_key_operand_count()
        out_cols: list[Column] = []
        for ci, (et, frac) in enumerate(self.schema):
            data = leaves[base + 2 * ci][:n_out]
            nulls = leaves[base + 2 * ci + 1][:n_out]
            out_cols.append(
                Column(et, data, nulls.astype(bool), frac, payload_dicts.get(ci))
            )
        enc = make_response_encoder(self.dag)
        enc.add_chunk(Chunk.full(out_cols), self.dag.output_offsets)
        resp = enc.to_response()
        if prune_stats[0]:
            resp._obs_prune = prune_stats
        return resp

    # -- selection-only pipeline ------------------------------------------

    def _run_scan_filter(self, source: ScanSource) -> SelectResponse:
        """TableScan → Selection? → Limit?: device computes the row mask,
        host compacts + encodes (row encoding is host work either way)."""
        from . import encoding as _encoding

        remaining = self.plan.limit.limit if self.plan.limit else None
        sel_rpns = self.sel_rpns
        mask_jit = None
        # zone-map pruning (docs/zone_maps.md): blocks whose zones prove no
        # row can pass the conjuncts are skipped before any device dispatch
        # — they contribute zero rows to the stream, so the response bytes
        # are identical; with a Limit the loop also reaches its early break
        # having touched only qualifying blocks
        keep, prune_stats = self._prune_keep(getattr(self, "_cache", None),
                                             "unary")
        enc = make_response_encoder(self.dag)
        for bi, (cols, n_valid) in enumerate(self._blocks(source)):
            if keep is not None and not keep[bi]:
                continue
            valid = np.zeros(self.block_rows, dtype=bool)
            valid[:n_valid] = True
            if sel_rpns:
                # served from the block cache's HBM-pinned arrays when one is
                # active — warm selections ship only the valid mask per block
                # (encoded images ship their narrow/run payloads and decode
                # in-kernel; the output below gathers ONLY surviving rows
                # through the encodings — late materialization)
                col_data, col_nulls, refs, enc_sig = self._device_block(cols, n_valid)
                if mask_jit is None:
                    mask_jit = self._build_mask_fn(enc_sig)
                mask = np.asarray(mask_jit(col_data, col_nulls, valid, refs))
            else:
                mask = valid
            logical = np.flatnonzero(mask[: n_valid])
            if remaining is not None:
                logical = logical[:remaining]
                remaining -= len(logical)
            out_cols, logical = _encoding.late_materialize_chunk(cols, logical)
            chunk = Chunk(out_cols, logical)
            enc.add_chunk(chunk, self.dag.output_offsets)
            if remaining is not None and remaining <= 0:
                break
        resp = enc.to_response()
        if prune_stats[0]:
            resp._obs_prune = prune_stats
        return resp


_BATCH_FN_CACHE: dict = {}
_BATCH_FN_CACHE_MAX = 32


def run_batch_cached(evaluators: list["JaxDagEvaluator"], cache) -> list[SelectResponse]:
    """Fuse K eligible queries over the same cached region into ONE device
    program — the coprocessor's answer to the reference's ``batch_commands``
    multiplexing (service/kv.rs:891) and ``batch_coprocessor`` surface: the
    tunnel's per-execution and per-pull costs are paid once for the whole
    batch instead of once per query.

    Requirements: every query is an aggregation DAG whose group-by is empty or
    all bare dict-encoded columns with stable dictionaries (the same queries
    the single warm path runs with zero per-row transfers).
    """
    blocks = cache.blocks
    if not blocks:
        raise ValueError("batched evaluation over an empty block cache")
    n_blocks = len(blocks)

    # Zone-tiled fast path: when EVERY query rides the clustered layout the
    # per-query cost is a handful of pure tile reductions — far below the
    # fused program's shared full-data pass — and the layouts themselves are
    # shared across queries with the same (group, sort) signature.  Cheap
    # eligibility pre-probe first (no device work), then all-or-nothing
    # execution with finalize deferred until every query served — a decline
    # falls back to the fused program with no wasted zone passes.
    def _zone_probe(ev):
        zone = ev._zone_evaluator()
        if zone is None or cache in zone._declined:
            return None
        return zone if zone.eligible(blocks) is not None else None

    zones = [_zone_probe(ev) for ev in evaluators]
    if all(z is not None for z in zones):
        outs = []
        for ev, zone in zip(evaluators, zones):
            out = zone.try_run(cache)  # crash-fallback lives inside try_run
            if out is None:  # late decline (partial-fraction or failure)
                outs = None
                break
            outs.append((ev, out))
        if outs is not None:
            return [
                ev._finalize_agg(state_np, n_slots, key_of)
                for ev, (state_np, n_slots, key_of) in outs
            ]

    specs = []  # (ev, group_cols, dicts, dict_lens, capacity)
    ship: list[int] = []
    for ev in evaluators:
        if ev.plan.agg is None:
            raise ValueError("batched evaluation requires aggregation DAGs")
        stable = ev._stable_dict_group_cols(blocks)
        if ev.group_rpns and stable is None:
            raise ValueError("batched evaluation requires stable dict group keys")
        group_cols, dicts = stable if stable else ([], [])
        dict_lens = tuple(len(d) for d in dicts)
        n_slots = 1
        for dl in dict_lens:
            n_slots *= dl + 1
        capacity = 1
        while capacity < n_slots:
            capacity *= 2
        specs.append((ev, group_cols, dicts, dict_lens, capacity, n_slots))
        for i in ev._ship_cols(group_cols):
            if i not in ship:
                ship.append(i)
    ship = sorted(ship)
    base = evaluators[0]
    nullable = sorted(set().union(*[set(ev.nullable_cols) for ev in evaluators]))
    col_data, col_nulls, refs, enc = base._stacked_device(cache, blocks, ship, nullable)
    if enc is not None:
        from . import encoding as _encoding

        _encoding.count_path("fused", "encoded")
    n_rows = base.block_rows

    key = (
        tuple(id(ev) for ev in evaluators),
        n_blocks,
        tuple(ship),
        n_rows,
        enc,
        # dict radices and capacities are baked into the compiled program —
        # a cache whose dictionaries grew must compile a fresh program
        tuple((spec[3], spec[4]) for spec in specs),
    )
    fn = _BATCH_FN_CACHE.get(key)
    if fn is None:
        def batch_fn(col_data, col_nulls, n_valids, offsets, refs):
            states = tuple(
                (
                    jnp.full(capacity, _NO_ROW, dtype=jnp.int64),
                    tuple(da.init_carry(capacity) for da in ev.device_aggs),
                )
                for (ev, _gc, _d, _dl, capacity, _ns) in specs
            )

            def body(sts, xs):
                cd, cn, nv, off = xs
                cols = _build_cols(ship, nullable, cd, cn, n_rows, enc, refs)
                new_sts = []
                for (ev, group_cols, _dicts, dict_lens, capacity, _ns), st in zip(specs, sts):
                    gids = _mixed_radix_gids(cols, group_cols, dict_lens, n_rows)
                    new_sts.append(
                        _fused_step(
                            ev.sel_rpns, ev.device_aggs, capacity, n_rows, cols, nv, gids, off, st,
                            track_first=bool(ev.group_rpns),
                        )
                    )
                return tuple(new_sts), None

            states, _ = jax.lax.scan(body, states, (col_data, col_nulls, n_valids, offsets))
            # ALL queries' states pack into two matrices (int64 + float64)
            # padded to the max capacity — one pull for the whole batch
            max_cap = max(cap for (_e, _g, _d, _dl, cap, _n) in specs)
            ints, flts = [], []
            for st in states:
                first_row, carries = st
                for a in [first_row] + jax.tree.leaves(carries):
                    a = jnp.pad(a, (0, max_cap - a.shape[0]))
                    (flts if a.dtype == jnp.float64 else ints).append(a)
            int_m = jnp.stack(ints)
            flt_m = jnp.stack(flts) if flts else jnp.zeros((0, max_cap), dtype=jnp.float64)
            return int_m, flt_m

        fn = _obs.timed_jit(jax.jit(batch_fn), "jax_eval.fused_batch",
                            "fused", base.obs_sig)
        _BATCH_FN_CACHE[key] = fn
        while len(_BATCH_FN_CACHE) > _BATCH_FN_CACHE_MAX:
            _BATCH_FN_CACHE.pop(next(iter(_BATCH_FN_CACHE)))

    nv_dev, off_dev = base._nvoff_device(cache, blocks)
    keep, prune_stats = _batch_prune_keep(evaluators, cache)
    if keep is not None:
        # survivor-count geometry: masked blocks ship n_valid == 0, so the
        # fused step's validity masks exclude every one of their rows while
        # the compiled program and its pins stay byte-for-byte identical
        nv_dev = _masked_nv(blocks, keep)
    int_m, flt_m = fn(col_data, col_nulls, nv_dev, off_dev, refs)
    int_np = np.asarray(int_m)
    flt_np = np.asarray(flt_m) if flt_m.shape[0] else None
    out = []
    ii = fi = 0
    for ev, _gc, dicts, dict_lens, cap, n_slots in specs:
        first_t, carries_t = ev._host_state_template()
        leaves_t = [first_t] + jax.tree.leaves(carries_t)
        leaves_np = []
        for t in leaves_t:
            if t.dtype == np.float64:
                leaves_np.append(flt_np[fi][:cap])
                fi += 1
            else:
                leaves_np.append(int_np[ii][:cap])
                ii += 1
        treedef = jax.tree.structure(carries_t)
        state_np = (leaves_np[0], jax.tree.unflatten(treedef, leaves_np[1:]))

        def key_of(slot: int, dicts=dicts, dict_lens=dict_lens) -> tuple:
            parts = []
            rem = int(slot)
            for d, dl in zip(reversed(dicts), reversed(dict_lens)):
                c = rem % (dl + 1)
                rem //= dl + 1
                parts.append(None if c == dl else bytes(d[c]))
            return tuple(reversed(parts))

        out.append(ev._finalize_agg(state_np, n_slots, key_of))
    if prune_stats[0]:
        for resp in out:
            resp._obs_prune = prune_stats
    return out


# ---------------------------------------------------------------------------
# Cross-region batched execution (copr/scheduler.py's device backend)
# ---------------------------------------------------------------------------


class XRegionPending:
    """An in-flight cross-region batch: the device program is dispatched
    (async), the pull has not happened yet.  The scheduler launches batch
    N, prepares batch N+1's caches on the host while N executes, and only
    then calls :meth:`finalize` — double-buffering without threads."""

    def __init__(self, ev: "JaxDagEvaluator", specs, capacity: int, packed,
                 order=None, prunes=None):
        self._ev = ev
        self._specs = specs  # [(dicts, dict_lens, n_slots)] per EXECUTED region
        self._capacity = capacity
        self._packed = packed  # (int_m (R,Li,cap), flt_m (R,Lf,cap)) device
        # executed-position -> caller-position (launch sorts regions by
        # block count to canonicalize the compile key)
        self._order = order
        # per-executed-region (blocks_examined, blocks_pruned) zone-map
        # stats; finalize stamps them on the responses for the observatory
        self._prunes = prunes

    def finalize(self) -> list[SelectResponse]:
        """Pull the packed states (one transfer per dtype matrix for the
        WHOLE batch) and finalize each region through the exact same
        host code as the per-region warm path — so responses stay
        byte-identical to per-request serving."""
        ev = self._ev
        int_m, flt_m = self._packed
        with trace.span("device.pull", regions=len(self._specs)):
            int_np = np.asarray(int_m)
            flt_np = np.asarray(flt_m) if flt_m.shape[1] else None
        template = ev._host_state_template()
        out = []
        for r, (dicts, dict_lens, n_slots) in enumerate(self._specs):
            packed_r = (int_np[r], flt_np[r] if flt_np is not None
                        else np.zeros((0, self._capacity), dtype=np.float64))
            state_np = _unpack_state(packed_r, template)

            def key_of(slot: int, dicts=dicts, dict_lens=dict_lens) -> tuple:
                parts = []
                rem = int(slot)
                for d, dl in zip(reversed(dicts), reversed(dict_lens)):
                    c = rem % (dl + 1)
                    rem //= dl + 1
                    parts.append(None if c == dl else bytes(d[c]))
                return tuple(reversed(parts))

            resp = ev._finalize_agg(state_np, n_slots, key_of)
            if self._prunes is not None and self._prunes[r][0]:
                resp._obs_prune = self._prunes[r]
            out.append(resp)
        if self._order is not None:
            restored = [None] * len(out)
            for pos, i in enumerate(self._order):
                restored[i] = out[pos]
            out = restored
        return out


def xregion_specs(ev: "JaxDagEvaluator", caches):
    """Shared eligibility/geometry prologue of BOTH cross-region launchers
    (the single-device vmapped one below and ``parallel.mesh``'s shard_map
    twin): validates the plan and every cache, computes the per-region
    (dicts, dict_lens, n_slots) specs, the group columns, and the shared
    power-of-two capacity.  Raises ValueError on the documented declines
    (non-aggregation plan, unstable group dictionaries, empty cache) — ONE
    implementation so the two launchers can never disagree about what is
    batchable."""
    if ev.plan.agg is None:
        raise ValueError("cross-region batching requires aggregation DAGs")
    if not caches:
        raise ValueError("cross-region batching requires at least one region")
    specs = []
    n_slots_max = 1
    for cache in caches:
        if not cache.blocks:
            raise ValueError("cross-region batching over an empty block cache")
        stable = ev._stable_dict_group_cols(cache.blocks)
        if ev.group_rpns and stable is None:
            raise ValueError("cross-region batching requires stable dict group keys")
        _gc, dicts = stable if stable else ([], [])
        dict_lens = tuple(len(d) for d in dicts)
        n_slots = 1
        for dl in dict_lens:
            n_slots *= dl + 1
        n_slots_max = max(n_slots_max, n_slots)
        specs.append((dicts, dict_lens, n_slots))
    group_cols = [g.nodes[0].index for g in ev.group_rpns]
    capacity = 1
    while capacity < n_slots_max:
        capacity *= 2
    return specs, group_cols, capacity


def _pack_region_leaves(leaves, n_regions: int, capacity: int):
    """Region-slot-segmented variant of :func:`_pack_leaves`: flat
    ``(R*C,)`` state leaves → ``((R, Li, C) int64, (R, Lf, C) float64)``
    matrices under the SAME int/float partition rule, so
    ``XRegionPending.finalize`` unpacks either launcher's output against
    the one packing contract."""
    ints = [l.reshape(n_regions, capacity).astype(jnp.int64)
            for l in leaves if l.dtype != jnp.float64]
    flts = [l.reshape(n_regions, capacity)
            for l in leaves if l.dtype == jnp.float64]
    int_m = jnp.stack(ints, axis=1)
    flt_m = (jnp.stack(flts, axis=1) if flts
             else jnp.zeros((n_regions, 0, capacity), dtype=jnp.float64))
    return int_m, flt_m


def launch_xregion_cached(ev: "JaxDagEvaluator", caches) -> XRegionPending:
    """ONE aggregation plan over R different region images as ONE device
    program: each region's resident blocks are padded to a shared block
    geometry, stacked along a new leading region axis, and the per-region
    block scan is vmapped over that axis — one dispatch and one packed pull
    amortize the XLA/tunnel round-trip over every region in the batch.

    Correctness relies on the per-block validity masks the single-region
    step already applies: padded blocks carry ``n_valid == 0`` so padding
    never reaches an aggregate.  Group capacities are shared (the max
    region's, rounded to a power of two) while dictionary radices stay
    per-region DYNAMIC inputs — so regions whose group dictionaries differ
    still ride one compiled program.

    Raises ValueError when the plan or any region's data shape is not
    batchable (non-aggregation plan, unstable group dictionaries, empty
    cache); the scheduler sheds those to the per-request path.
    """
    from . import encoding as _encoding

    specs, group_cols, capacity = xregion_specs(ev, caches)
    ship = ev._ship_cols(group_cols)
    nullable = ev.nullable_cols
    n_rows = ev.block_rows
    # encoded residency (copr/encoding.py): the vmapped program stacks
    # per-region pinned arrays, so every region must carry the SAME
    # encoding signature — batch_plan decides (and counts) encoded vs
    # decode-ship; the descriptors ride the jit key, the per-region
    # frame-of-reference vectors ride as a dynamic (R, n_ship) input
    plans = _encoding.batch_plan(caches, ship, nullable, "xregion")
    enc = plans[0].sig if plans else None
    # canonicalize region order by block count: the compiled program's cache
    # key is the block-count tuple, so (2,3) and (3,2) must not compile two
    # programs — batches differing only in arrival order share one
    # executable.  finalize restores the caller's order.
    order = sorted(range(len(caches)), key=lambda i: len(caches[i].blocks),
                   reverse=True)
    caches = [caches[i] for i in order]
    specs = [specs[i] for i in order]
    if plans:
        plans = [plans[i] for i in order]
    n_blocks = tuple(len(c.blocks) for c in caches)
    B = max(n_blocks)
    # per-region inputs are the caches' ALREADY-PINNED device arrays (the
    # same pins the per-request warm path uses, kept fresh by delta
    # scatter_update / drop_device) — zero per-row host→device traffic, and
    # no cross-cache pin that could go stale behind a region's back
    from . import zone_maps as _zm

    region_inputs = []
    prunes = []  # (examined, pruned) per executed region, for the riders' obs
    for r, cache in enumerate(caches):
        data, nulls, _refs, _e = ev._stacked_device(
            cache, cache.blocks, ship,
            plan=plans[r] if plans else None,
        )
        nv, off = ev._nvoff_device(cache, cache.blocks)
        # zone-map pruning (docs/zone_maps.md): masked blocks ship
        # n_valid == 0 through the dynamic nv input, so the vmapped program
        # skips their rows without perturbing the shared compile key
        pstats = _zm.PruneStats()
        keep = _zm.prune_blocks(cache, ev.sel_rpns, path="xregion",
                                stats=pstats)
        if keep is not None:
            nv = _masked_nv(cache.blocks, keep)
        prunes.append((pstats.examined, pstats.pruned))
        region_inputs.append((data, nulls, nv, off))
    dl_arr = np.array([s[1] for s in specs], dtype=np.int64).reshape(
        len(caches), len(group_cols)
    )
    refs_arr = (np.stack([np.asarray(p.refs) for p in plans])
                if plans else np.zeros((len(caches), len(ship)), dtype=np.int64))

    key = ("xregion", n_blocks, capacity, tuple(ship), tuple(nullable), enc)
    fn = ev._agg_fn_cache.get(key)
    if fn is None:
        device_aggs = ev.device_aggs
        sel_rpns = ev.sel_rpns
        track_first = bool(ev.group_rpns)

        def pad_b(a):
            pad = B - a.shape[0]
            if pad == 0:
                return a
            return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))

        def xregion_fn(region_inputs, dl_arr, refs_arr):
            padded = [jax.tree.map(pad_b, ri) for ri in region_inputs]
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *padded)

            def one_region(ri, dlens, refs_r):
                cd_r, cn_r, nv_r, off_r = ri
                state = (
                    jnp.full(capacity, _NO_ROW, dtype=jnp.int64),
                    tuple(da.init_carry(capacity) for da in device_aggs),
                )

                def body(st, xs):
                    cd, cn, nv, off = xs
                    cols = _build_cols(ship, nullable, cd, cn, n_rows, enc, refs_r)
                    if group_cols:
                        gids = jnp.zeros(n_rows, dtype=jnp.int64)
                        for k, gi in enumerate(group_cols):
                            codes, gnulls = cols[gi]
                            dlen = dlens[k]
                            gids = gids * (dlen + 1) + jnp.where(gnulls, dlen, codes)
                    else:
                        gids = jnp.zeros(n_rows, dtype=jnp.int64)
                    return _fused_step(
                        sel_rpns, device_aggs, capacity, n_rows, cols, nv, gids, off, st,
                        track_first=track_first,
                    ), None

                state, _ = jax.lax.scan(body, state, (cd_r, cn_r, nv_r, off_r))
                return _pack_state(state)

            return jax.vmap(one_region)(stacked, dl_arr, refs_arr)

        fn = _obs.timed_jit(jax.jit(xregion_fn), "jax_eval.xregion",
                            "xregion", ev.obs_sig)
        ev._agg_fn_cache[key] = fn
        # block-count compositions drift (deltas, splits): bound the
        # executables retained for this plan so compile churn cannot grow
        # memory without limit
        xkeys = [k for k in ev._agg_fn_cache if isinstance(k, tuple)
                 and k and k[0] == "xregion"]
        while len(xkeys) > 16:
            ev._agg_fn_cache.pop(xkeys.pop(0))

    # the async dispatch itself; the encoded-path decision batch_plan made
    # (and counted) rides the trace as a tag (docs/tracing.md)
    with trace.span("device.launch", kind="xregion", regions=len(caches),
                    encoding="encoded" if plans else "decoded"):
        packed = fn(tuple(region_inputs), dl_arr, refs_arr)
    pending = XRegionPending(ev, specs, capacity, packed, order, prunes)
    # observatory encoding label for the riders' profiles
    pending.obs_encoding = "encoded" if plans else "plain"
    return pending


def run_xregion_cached(ev: "JaxDagEvaluator", caches) -> list[SelectResponse]:
    """launch + finalize in one step (tests / single-batch callers)."""
    return launch_xregion_cached(ev, caches).finalize()


def launch_xregion_sharded(ev: "JaxDagEvaluator", caches, mesh) -> XRegionPending:
    """The ``shard_map`` twin of :func:`launch_xregion_cached`: the same
    cross-region batch executed over EVERY device of ``mesh``, each region
    image (or block, for a block-spread huge region) scanned on its owner
    device and the partial aggregate states merged with the mesh collective
    rules.  Implemented in ``parallel.mesh`` (where the collectives and the
    merge table live); this wrapper keeps the scheduler's device backend a
    single import site.  Raises ValueError on the same documented declines
    as the single-device launcher, plus "no mesh merge rule"."""
    from ..parallel.mesh import launch_xregion_sharded as _impl

    return _impl(ev, caches, mesh)


class _ChunkExecutor:
    """Adapter: present an in-memory Chunk as a drained BatchExecutor."""

    def __init__(self, chunk: Chunk, schema):
        self._chunk = chunk
        self._schema = schema
        self._done = False

    def schema(self):
        return self._schema

    def next_batch(self, scan_rows: int):
        from .executors import BatchExecuteResult

        if self._done:
            return BatchExecuteResult(Chunk.full([]), True)
        self._done = True
        return BatchExecuteResult(self._chunk, True)


def _grow_carry(da: _DeviceAgg, carry, new_capacity: int):
    grown = list(da.init_carry(new_capacity))
    out = []
    for old, new in zip(carry, grown):
        old = jnp.asarray(old)
        out.append(new.at[: old.shape[0]].set(old))
    return tuple(out)
