"""RPN expression engine.

Re-expression of ``tidb_query_expr/src/types/{expr.rs:12, expr_builder.rs:19,
expr_eval.rs:149}``: expression trees compile to a postfix (RPN) node list;
evaluation is a stack machine over whole columns.  The same RPN program is
interpreted twice:

* ``eval_rpn(..., xp=numpy)`` — the CPU oracle path
* ``eval_rpn(..., xp=jax.numpy)`` inside ``jit`` — the TPU path (the RPN list
  is static Python structure, so tracing unrolls it into one fused XLA graph)

Decimal frac propagation happens here (statically, from the expression types),
so kernels never branch on scale at runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .datatypes import Column, EvalType
from .kernels import KERNELS


# ---------------------------------------------------------------------------
# Expression tree (tipb::Expr equivalent)
# ---------------------------------------------------------------------------

@dataclass
class ColumnRef:
    index: int


@dataclass
class Constant:
    value: object  # None | int | float | bytes (decimal: pre-scaled int)
    eval_type: EvalType
    frac: int = 0


@dataclass
class FuncCall:
    op: str  # kernel name
    children: list
    # filled by type inference:
    eval_type: EvalType | None = None
    frac: int = 0


Expr = ColumnRef | Constant | FuncCall


# ---------------------------------------------------------------------------
# RPN compilation with static type/frac inference
# ---------------------------------------------------------------------------

@dataclass
class RpnNode:
    kind: str  # "col" | "const" | "fn"
    eval_type: EvalType
    frac: int = 0
    index: int = 0  # col index
    value: object = None  # const value
    op: str = ""  # fn kernel name
    arity: int = 0
    scale_by: tuple[int, ...] = ()  # per-operand decimal rescale multipliers


@dataclass
class RpnExpression:
    nodes: list[RpnNode]

    @property
    def eval_type(self) -> EvalType:
        return self.nodes[-1].eval_type

    @property
    def frac(self) -> int:
        return self.nodes[-1].frac

    def referenced_columns(self) -> set[int]:
        return {n.index for n in self.nodes if n.kind == "col"}


DIVIDE_FRAC_INCR = 4  # MySQL: decimal division adds 4 frac digits
_VARIADIC_MIN = {
    "in": 2, "case_when": 2, "concat": 1, "coalesce": 1,
    "json_extract": 2, "json_length": 1, "json_keys": 1, "json_array": 1,
    "json_object": 2, "json_merge": 2, "json_set": 3, "json_insert": 3,
    "json_replace": 3, "json_remove": 2,
}


def compile_expr(expr: Expr, schema: list[tuple[EvalType, int]]) -> RpnExpression:
    """Compile a tree to RPN. ``schema`` maps column index → (eval_type, frac)."""
    nodes: list[RpnNode] = []
    _compile(expr, schema, nodes)
    return RpnExpression(nodes)


def _compile(expr: Expr, schema, nodes: list[RpnNode]) -> tuple[EvalType, int]:
    if isinstance(expr, ColumnRef):
        et, frac = schema[expr.index]
        nodes.append(RpnNode("col", et, frac, index=expr.index))
        return et, frac
    if isinstance(expr, Constant):
        nodes.append(RpnNode("const", expr.eval_type, expr.frac, value=expr.value))
        return expr.eval_type, expr.frac
    if isinstance(expr, FuncCall):
        if expr.op not in KERNELS:
            raise ValueError(f"unsupported scalar function {expr.op!r}")
        arity, rkind, _ = KERNELS[expr.op]
        if arity == -1:
            min_arity = _VARIADIC_MIN.get(expr.op, 1)
            if len(expr.children) < min_arity:
                raise ValueError(f"{expr.op} needs at least {min_arity} arguments")
            arity = len(expr.children)
        elif arity != len(expr.children):
            raise ValueError(f"{expr.op} expects {arity} args, got {len(expr.children)}")
        child_types = [_compile(c, schema, nodes) for c in expr.children]
        et, frac, scale_by = _infer(expr.op, rkind, child_types)
        nodes.append(
            RpnNode("fn", et, frac, op=expr.op, arity=arity, scale_by=scale_by)
        )
        expr.eval_type, expr.frac = et, frac
        return et, frac
    raise TypeError(f"not an expression: {expr!r}")


def _infer(op: str, rkind: str, child_types) -> tuple[EvalType, int, tuple[int, ...]]:
    """Result type + frac + the decimal rescaling each operand needs.

    Mixed-frac decimal operands are aligned to the max frac by multiplying the
    lower-frac side by 10^diff — done once, statically planned here.
    """
    scale_by = tuple(1 for _ in child_types)
    types = [t[0] for t in child_types]
    fracs = [t[1] for t in child_types]
    has_decimal = EvalType.DECIMAL in types

    if op == "multiply" and has_decimal:
        # scaled(a*b) = scaled(a)*scaled(b), frac adds — no rescale needed
        return EvalType.DECIMAL, sum(f for t, f in child_types if t == EvalType.DECIMAL), scale_by

    if has_decimal and rkind in ("same", "int") and len(child_types) >= 2:
        # align fracs for +,-,comparisons,mod — and n-ary value comparisons
        # (greatest/least/in), where unaligned scaled ints would compare wrong
        f = max(fracs)
        scale_by = tuple(10 ** (f - fi) for fi in fracs)
        if rkind == "int":
            return EvalType.INT, 0, scale_by
        return EvalType.DECIMAL, f, scale_by

    if rkind == "int":
        return EvalType.INT, 0, scale_by
    if rkind == "real":
        # decimal operands feeding a real function must be unscaled to their
        # numeric value: scaled-int64 * 10^-frac (float multiplier)
        if has_decimal:
            scale_by = tuple(
                10.0 ** -f if t == EvalType.DECIMAL and f else 1
                for t, f in child_types
            )
        return EvalType.REAL, 0, scale_by
    if rkind == "bytes":
        return EvalType.BYTES, 0, scale_by
    if rkind == "json":
        return EvalType.JSON, 0, scale_by
    if rkind == "same":
        return types[0], fracs[0], scale_by
    if rkind == "same_2":
        # if(c, t, f): result typed like t/f — align their fracs
        if types[1] == EvalType.DECIMAL or types[2] == EvalType.DECIMAL:
            f = max(fracs[1], fracs[2])
            scale_by = (1, 10 ** (f - fracs[1]), 10 ** (f - fracs[2]))
            return EvalType.DECIMAL, f, scale_by
        return types[1], fracs[1], scale_by
    if rkind == "same_case":
        # case_when(c1, r1, ..., [else]): typed like the result operands
        result_positions = [i for i in range(1, len(child_types), 2)]
        if len(child_types) % 2 == 1:
            result_positions.append(len(child_types) - 1)
        rtypes = [types[i] for i in result_positions]
        rfracs = [fracs[i] for i in result_positions]
        if EvalType.DECIMAL in rtypes:
            f = max(rfracs)
            sb = [1] * len(child_types)
            for i in result_positions:
                sb[i] = 10 ** (f - fracs[i])
            return EvalType.DECIMAL, f, tuple(sb)
        return rtypes[0], rfracs[0], scale_by
    raise AssertionError(rkind)


# ---------------------------------------------------------------------------
# Stack-machine evaluation
# ---------------------------------------------------------------------------

_DTYPE = {
    EvalType.INT: np.int64,
    EvalType.DECIMAL: np.int64,
    EvalType.DATETIME: np.int64,
    EvalType.DURATION: np.int64,
    EvalType.REAL: np.float64,
    # enum index / set bitmask ride integer lanes directly
    EvalType.ENUM: np.int64,
    EvalType.SET: np.uint64,
}


def eval_rpn(rpn: RpnExpression, columns: list, n_rows: int, xp=np):
    """Evaluate over column (data, nulls) pairs. Returns (data, nulls).

    ``columns`` holds per-column (data, nulls) arrays (only referenced indices
    need to be present).  With ``xp=jax.numpy`` the arrays may be tracers.
    """
    stack: list[tuple[object, object]] = []
    for node in rpn.nodes:
        if node.kind == "col":
            stack.append(columns[node.index])
        elif node.kind == "const":
            dtype = _DTYPE.get(node.eval_type, object)
            if node.value is None:
                data = xp.zeros(n_rows, dtype=dtype if dtype is not object else np.int64)
                nulls = xp.ones(n_rows, dtype=bool)
            elif node.eval_type in (EvalType.BYTES, EvalType.JSON):
                data = np.empty(n_rows, dtype=object)
                data[:] = node.value
                nulls = xp.zeros(n_rows, dtype=bool)
            else:
                data = xp.full(n_rows, node.value, dtype=dtype)
                nulls = xp.zeros(n_rows, dtype=bool)
            stack.append((data, nulls))
        else:
            _, _, fn = KERNELS[node.op]
            args = stack[-node.arity :]
            del stack[-node.arity :]
            if any(m != 1 for m in node.scale_by):
                args = [
                    (d * m, nl) if m != 1 else (d, nl)
                    for (d, nl), m in zip(args, node.scale_by)
                ]
            stack.append(fn(xp, *args))
    assert len(stack) == 1, "malformed RPN"
    return stack[0]


def eval_expr_on_chunk(rpn: RpnExpression, chunk, xp=np):
    """Convenience: evaluate over a Chunk's physical columns."""
    cols = {}
    for i in rpn.referenced_columns():
        c = chunk.columns[i]
        cols[i] = (c.data, c.nulls)
    n = len(chunk.columns[0]) if chunk.columns else 0
    return eval_rpn(rpn, cols, n, xp=xp)


# -- convenience builders ---------------------------------------------------

def col(i: int) -> ColumnRef:
    return ColumnRef(i)


def const_int(v: int | None) -> Constant:
    return Constant(v, EvalType.INT)


def const_real(v: float | None) -> Constant:
    return Constant(v, EvalType.REAL)


def const_decimal(scaled: int | None, frac: int) -> Constant:
    return Constant(scaled, EvalType.DECIMAL, frac)


def const_bytes(v: bytes | None) -> Constant:
    return Constant(v, EvalType.BYTES)


def const_set(mask: int | None) -> Constant:
    """SET bitmask constant — uint64 lanes, so bit 63 survives (a plain
    const_int would wrap negative against a 64-element SET column)."""
    return Constant(mask, EvalType.SET)


def const_json(v) -> Constant:
    """Constant from a Python JSON value (encoded to binary JSON)."""
    from .json_value import json_encode

    return Constant(None if v is None else json_encode(v), EvalType.JSON)


def call(op: str, *children) -> FuncCall:
    return FuncCall(op, list(children))
