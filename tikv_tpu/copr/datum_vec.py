"""Vectorized datum-v1 row encoding: whole chunks without a Python loop.

The unary response path (``dag.ResponseEncoder``) historically encoded one
datum at a time — flag byte + payload per (row, column) through
``datum.encode_datum`` — which made the encode stage of the cluster wire
path scale with row count in interpreter time.  This module produces the
EXACT same bytes with numpy batch codecs:

* per column, the selected rows' cells (flag byte + payload) are computed as
  one concatenated uint8 buffer plus per-row lengths — fixed-width types
  (REAL/DECIMAL/DURATION) as a reshape, varint types (INT and the UINT
  family) through :func:`codec.encode_var_i64_batch` /
  :func:`codec.encode_var_u64_batch`, var-len types (BYTES/JSON) through one
  C-level join;
* rows are then assembled with a single ragged scatter per column into one
  output buffer, with the ``ncols`` varint prefix written at row starts.

Byte-identity with the scalar path is enforced by
``tests/test_wire_path.py`` across every datum type, null patterns, and
dictionary-encoded columns.
"""

from __future__ import annotations

import numpy as np

from ..util import codec
from . import datum as datum_mod

_ALL64 = np.uint64(0xFFFFFFFFFFFFFFFF)

#: below this many rows the scalar loop wins (numpy call overhead)
VEC_MIN_ROWS = 16


def _cells_fixed(flag: int, payload: np.ndarray, extra: bytes = b"") -> tuple[np.ndarray, np.ndarray]:
    """Cells of a fixed-width type: [flag, *extra, *payload8] per row."""
    n = len(payload)
    h = 1 + len(extra)
    out = np.empty((n, h + 8), np.uint8)
    out[:, 0] = flag
    if extra:
        out[:, 1:h] = np.frombuffer(extra, np.uint8)
    out[:, h:] = payload
    return out.reshape(-1), np.full(n, h + 8, np.int64)


def _cells_varint(flag: int, data: np.ndarray, signed: bool) -> tuple[np.ndarray, np.ndarray]:
    body, blens = (codec.encode_var_i64_batch(data) if signed
                   else codec.encode_var_u64_batch(data))
    n = len(blens)
    lens = blens + 1
    total = int(lens.sum())
    out = np.empty(total, np.uint8)
    starts = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    out[starts] = flag
    # body bytes land everywhere except the per-row flag positions
    mask = np.ones(total, bool)
    mask[starts] = False
    out[mask] = body
    return out, lens


def _cells_bytes(flag: int, values: list) -> tuple[np.ndarray, np.ndarray]:
    """COMPACT_BYTES / JSON cells via one C-level join."""
    if flag == datum_mod.JSON_FLAG:
        head = bytes((flag,))
        cells = [head + v for v in values]
    else:
        head = bytes((datum_mod.COMPACT_BYTES_FLAG,))
        cells = [head + codec.encode_var_i64(len(v)) + v for v in values]
    lens = np.fromiter((len(c) for c in cells), np.int64, len(cells))
    buf = np.frombuffer(b"".join(cells), np.uint8) if cells else np.empty(0, np.uint8)
    return buf, lens


_NIL_CELL = np.array([datum_mod.NIL_FLAG], np.uint8)


def _apply_nulls(cells: np.ndarray, lens: np.ndarray, nulls: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Replace null rows' cells with the one-byte NIL datum."""
    if not nulls.any():
        return cells, lens
    n = len(lens)
    starts = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    new_lens = np.where(nulls, 1, lens)
    total = int(new_lens.sum())
    out = np.empty(total, np.uint8)
    new_starts = np.zeros(n, np.int64)
    np.cumsum(new_lens[:-1], out=new_starts[1:])
    # copy surviving (non-null) cells with one ragged gather
    keep = ~nulls
    if keep.any():
        src = np.repeat(starts[keep], lens[keep]) + _within(lens[keep])
        dst = np.repeat(new_starts[keep], lens[keep]) + _within(lens[keep])
        out[dst] = cells[src]
    out[new_starts[nulls]] = datum_mod.NIL_FLAG
    return out, new_lens


def _within(lens: np.ndarray) -> np.ndarray:
    """[0..l0), [0..l1), ... — per-segment offsets for ragged copies."""
    total = int(lens.sum())
    starts = np.zeros(len(lens), np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lens)


def _column_cells(col, rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(cells buffer, per-row lens) for the selected rows of one column —
    bytes identical to ``datum.encode_datum(*col.datum_at(i))`` per row."""
    from .datatypes import EvalType

    et = col.eval_type
    nulls = np.asarray(col.nulls)[rows]
    if et == EvalType.INT:
        data = np.asarray(col.data)[rows].astype(np.int64)
        cells, lens = _cells_varint(datum_mod.VARINT_FLAG, data, signed=True)
    elif et in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
        data = np.asarray(col.data)[rows]
        u = data.astype(np.int64).view(np.uint64) if data.dtype != np.uint64 else data
        cells, lens = _cells_varint(datum_mod.UVARINT_FLAG, u, signed=False)
    elif et == EvalType.REAL:
        data = np.asarray(col.data)[rows].astype(np.float64)
        cells, lens = _cells_fixed(datum_mod.FLOAT_FLAG,
                                   codec.encode_f64_batch(data))
    elif et == EvalType.DECIMAL:
        data = np.asarray(col.data)[rows].astype(np.int64)
        cells, lens = _cells_fixed(datum_mod.DECIMAL_FLAG,
                                   codec.encode_i64_batch(data),
                                   extra=bytes((col.frac,)))
    elif et == EvalType.DURATION:
        data = np.asarray(col.data)[rows].astype(np.int64)
        cells, lens = _cells_fixed(datum_mod.DURATION_FLAG,
                                   codec.encode_i64_batch(data))
    elif et in (EvalType.BYTES, EvalType.JSON):
        data = np.asarray(col.data)[rows]
        if col.dictionary is not None:
            data = col.dictionary[data]
        flag = (datum_mod.JSON_FLAG if et == EvalType.JSON
                else datum_mod.BYTES_FLAG)
        values = [bytes(v) for v in data]
        cells, lens = _cells_bytes(flag, values)
    else:
        raise ValueError(f"unsupported eval type {et}")
    return _apply_nulls(cells, lens, nulls)


def supported(cols) -> bool:
    """True when every column's eval type has a vectorized cell encoder.
    ENUM/SET reach ``datum_at`` only through the UINT branch, so the set
    here matches ``Column.datum_at`` exactly."""
    from .datatypes import EvalType

    ok = (EvalType.INT, EvalType.REAL, EvalType.DECIMAL, EvalType.BYTES,
          EvalType.JSON, EvalType.DURATION, EvalType.DATETIME, EvalType.ENUM,
          EvalType.SET)
    return all(c.eval_type in ok for c in cols)


def encode_chunk_rows(cols, rows: np.ndarray) -> tuple[bytes, np.ndarray]:
    """Encode the selected ``rows`` of ``cols`` as datum-v1 response rows
    (``varint(ncols)`` prefix + one datum per column, per row).  Returns the
    concatenated buffer and the byte offset of the END of each row — the
    chunk framer slices at those bounds."""
    rows = np.asarray(rows, dtype=np.int64)
    n = len(rows)
    prefix = codec.encode_var_u64(len(cols))
    p = len(prefix)
    per_col = [_column_cells(c, rows) for c in cols]
    row_lens = np.full(n, p, np.int64)
    for _, lens in per_col:
        row_lens += lens
    row_ends = np.cumsum(row_lens)
    total = int(row_ends[-1]) if n else 0
    out = np.empty(total, np.uint8)
    row_starts = row_ends - row_lens
    pfx = np.frombuffer(prefix, np.uint8)
    for j in range(p):
        out[row_starts + j] = pfx[j]
    cursor = row_starts + p
    for cells, lens in per_col:
        if len(cells):
            dst = np.repeat(cursor, lens) + _within(lens)
            out[dst] = cells
        cursor = cursor + lens
    return out.tobytes(), row_ends
