"""Row format v2: id-indexed compact row encoding + batch columnar decode.

Re-expression of ``tidb_query_datatype/src/codec/row/v2/`` (row_slice.rs:30
header layout, compat_v1.rs:13 cell encodings).  Layout per row:

    [128][flags][non_null_cnt u16 LE][null_cnt u16 LE]
    [non-null ids asc][null ids asc][end-offsets][cell values]

ids/offsets are u8/u16 in the small form, u32/u32 when any id > 255 or the
value section exceeds 64KiB (flags bit 0 = big).  NULL columns store no value
at all; absent columns fall back to schema defaults — both reasons v2 rows
are much smaller than datum (v1) rows for wide sparse schemas.

Cell encodings (compat_v1.rs write_v2_as_datum):

* INT family / YEAR: little-endian minimal width (1/2/4/8), sign-extended
* DATETIME / ENUM / SET and unsigned ints: LE minimal width, zero-extended
* DURATION: signed LE minimal width
* REAL: this framework's 8-byte memcomparable f64 (util.codec.encode_f64)
* BYTES: raw; JSON: binary JSON (self-delimiting)
* DECIMAL: ``[prec][frac][MySQL bin decimal]`` (mydecimal.encode_bin).  The
  stored cell covers the full 81-digit envelope; the *columnar* decode bridges
  to the device's scaled-int64 form (≤18 digits) and rejects wider values
  with a pointer to ``decode_cell_wide`` for host-side access.

TPU-first: the batch decoder recognises blocks whose rows share one byte
layout (same ids, same offsets — the steady state for fixed-width schemas)
and decodes each column with one numpy reshape+slice over the whole block,
the same trick ``RowBatchDecoder._try_fast_decode`` plays for v1 rows.
"""

from __future__ import annotations

import numpy as np

from ..util import codec
from .datatypes import Column, ColumnInfo, EvalType, attach_schema_dictionary, typed_column
from .mydecimal import DecimalOverflow, MyDecimal

CODEC_VERSION = 128
FLAG_BIG = 1

_DEFAULT_PREC = 65  # MySQL max precision, used when the schema has no flen


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------

def _enc_i64_le(v: int) -> bytes:
    """Signed LE minimal width (1/2/4/8)."""
    for w in (1, 2, 4, 8):
        if -(1 << (8 * w - 1)) <= v < 1 << (8 * w - 1):
            return int(v).to_bytes(w, "little", signed=True)
    raise OverflowError(v)


def _enc_u64_le(v: int) -> bytes:
    for w in (1, 2, 4, 8):
        if v < 1 << (8 * w):
            return int(v).to_bytes(w, "little")
    raise OverflowError(v)


def _decimal_prec(info: ColumnInfo) -> int:
    return info.ftype.flen if info.ftype.flen and info.ftype.flen > 0 else _DEFAULT_PREC


def _encode_cell(info: ColumnInfo, v) -> bytes:
    et = info.ftype.eval_type
    if et == EvalType.INT:
        if info.ftype.is_unsigned:
            return _enc_u64_le(int(v) & ((1 << 64) - 1))
        return _enc_i64_le(int(v))
    if et in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
        return _enc_u64_le(int(v))
    if et == EvalType.DURATION:
        return _enc_i64_le(int(v))
    if et == EvalType.REAL:
        return codec.encode_f64(float(v))
    if et == EvalType.BYTES:
        return bytes(v)
    if et == EvalType.JSON:
        return bytes(v)
    if et == EvalType.DECIMAL:
        frac = info.ftype.decimal
        prec = _decimal_prec(info)
        if isinstance(v, MyDecimal):
            d = v
        else:
            d = MyDecimal.from_i64_scaled(int(v), frac)
        return bytes([prec, frac]) + d.encode_bin(prec, frac)
    raise ValueError(f"unsupported eval type {et}")


def encode_row_v2(columns: list[ColumnInfo], values: list) -> bytes:
    """Encode one row. ``values`` align with ``columns``; None ⇒ NULL."""
    cells: list[tuple[int, bytes]] = []
    null_ids: list[int] = []
    for info, v in zip(columns, values):
        if v is None:
            null_ids.append(info.col_id)
        else:
            cells.append((info.col_id, _encode_cell(info, v)))
    cells.sort()
    null_ids.sort()

    value_len = sum(len(c) for _, c in cells)
    big = (
        any(cid > 255 for cid, _ in cells)
        or any(cid > 255 for cid in null_ids)
        or value_len > 0xFFFF
    )
    id_w, off_w = (4, 4) if big else (1, 2)

    out = bytearray([CODEC_VERSION, FLAG_BIG if big else 0])
    out += len(cells).to_bytes(2, "little")
    out += len(null_ids).to_bytes(2, "little")
    for cid, _ in cells:
        out += cid.to_bytes(id_w, "little")
    for cid in null_ids:
        out += cid.to_bytes(id_w, "little")
    end = 0
    for _, c in cells:
        end += len(c)
        out += end.to_bytes(off_w, "little")
    for _, c in cells:
        out += c
    return bytes(out)


# ---------------------------------------------------------------------------
# Per-row slice (row_slice.rs RowSlice)
# ---------------------------------------------------------------------------

class RowSliceV2:
    """Parsed header over one encoded row; cell lookup by column id."""

    __slots__ = ("raw", "non_null_ids", "null_ids", "offsets", "values_start")

    def __init__(self, raw: bytes):
        if not raw or raw[0] != CODEC_VERSION:
            raise ValueError("not a v2 row")
        if len(raw) < 6:
            raise ValueError("truncated v2 row")
        big = bool(raw[1] & FLAG_BIG)
        nn = int.from_bytes(raw[2:4], "little")
        nl = int.from_bytes(raw[4:6], "little")
        id_w = 4 if big else 1
        off_w = 4 if big else 2
        pos = 6
        self.raw = raw
        self.non_null_ids = [
            int.from_bytes(raw[pos + i * id_w : pos + (i + 1) * id_w], "little")
            for i in range(nn)
        ]
        pos += nn * id_w
        self.null_ids = [
            int.from_bytes(raw[pos + i * id_w : pos + (i + 1) * id_w], "little")
            for i in range(nl)
        ]
        pos += nl * id_w
        self.offsets = [
            int.from_bytes(raw[pos + i * off_w : pos + (i + 1) * off_w], "little")
            for i in range(nn)
        ]
        pos += nn * off_w
        self.values_start = pos
        # Truncation check (row_slice.rs returns Error::corrupted on short
        # input): every header int above decoded from a short slice as 0, so
        # without this a truncated row yields garbage cells instead of failing.
        if pos > len(raw) or (self.offsets and pos + self.offsets[-1] > len(raw)):
            raise ValueError("truncated v2 row")

    def header_len(self) -> int:
        return self.values_start

    def get(self, col_id: int):
        """cell bytes | None (NULL) — raises KeyError when the id is absent."""
        lo, hi = 0, len(self.non_null_ids)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.non_null_ids[mid] < col_id:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(self.non_null_ids) and self.non_null_ids[lo] == col_id:
            start = self.offsets[lo - 1] if lo else 0
            # lint: allow(view-escape) -- self.raw is bytes (immutable): the
            # slice is a copy by construction, no aliasing view can escape
            return self.raw[self.values_start + start : self.values_start + self.offsets[lo]]
        if col_id in self.null_ids:
            return None
        raise KeyError(col_id)


def _dec_i64_le(cell: bytes) -> int:
    return int.from_bytes(cell, "little", signed=True)


def _dec_u64_le(cell: bytes) -> int:
    return int.from_bytes(cell, "little")


def decode_cell(info: ColumnInfo, cell: bytes):
    """One cell → the column's stored Python value (scaled int for DECIMAL)."""
    et = info.ftype.eval_type
    if et == EvalType.INT:
        if info.ftype.is_unsigned:
            v = _dec_u64_le(cell)
            return v - (1 << 64) if v >= 1 << 63 else v  # int64 view
        return _dec_i64_le(cell)
    if et in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
        return _dec_u64_le(cell)
    if et == EvalType.DURATION:
        return _dec_i64_le(cell)
    if et == EvalType.REAL:
        return codec.decode_f64(cell)
    if et in (EvalType.BYTES, EvalType.JSON):
        return bytes(cell)
    if et == EvalType.DECIMAL:
        d = decode_cell_wide(cell)
        try:
            return d.round(info.ftype.decimal).to_i64_scaled()[0]
        except DecimalOverflow as e:
            raise ValueError(
                f"decimal {d} exceeds the columnar scaled-int64 form "
                f"(≤18 digits); read it through RowSliceV2.get + "
                f"decode_cell_wide instead"
            ) from e
    raise ValueError(f"unsupported eval type {et}")


def decode_cell_wide(cell: bytes) -> MyDecimal:
    """Full-envelope (81-digit) decode of a DECIMAL cell."""
    prec, frac = cell[0], cell[1]
    d, _ = MyDecimal.decode_bin(cell[2:], prec, frac)
    return d


# ---------------------------------------------------------------------------
# Batch decode
# ---------------------------------------------------------------------------

def is_v2_row(raw: bytes) -> bool:
    return bool(raw) and raw[0] == CODEC_VERSION


_MAX_LAYOUT_GROUPS = 32


def decode_rows_v2(schema: list[ColumnInfo], row_values: list[bytes]) -> list[Column]:
    """Decode a block of v2 rows into Columns (handle columns left zeroed).

    Fast path: every row shares the first row's exact header bytes (ids +
    offsets) ⇒ each cell lives at one fixed [start, end) for the whole block,
    so fixed-width columns decode as a reshape + byte-slice with no per-row
    Python.  Mixed layouts are *grouped* by identical (length, header) and
    each group fast-decodes the same way (delta blocks and mid-migration
    blocks typically hold a handful of layouts, not one per row); only a
    pathological layout explosion takes the per-row walk.
    """
    n = len(row_values)
    first = RowSliceV2(row_values[0])
    h = first.header_len()
    header = row_values[0][:h]
    nbytes = len(row_values[0])
    same = all(
        len(rv) == nbytes and rv[:h] == header for rv in row_values[1:]
    )
    if same:
        return _fast_decode(schema, first, row_values, n)
    return _grouped_decode(schema, row_values, n)


def _grouped_decode(schema, row_values, n) -> list[Column]:
    """Partition rows into identical-layout groups and fast-decode each.

    Grouping is vectorized per byte-length bucket: rows of one length stack
    into a byte matrix, the first unclaimed row's header selects every row
    matching it with one matrix compare, and the group decodes via
    ``_fast_decode``.  Output columns stitch back into original row order.
    """
    lens = np.fromiter((len(rv) for rv in row_values), dtype=np.int64, count=n)
    groups: list[tuple[np.ndarray, list[Column]]] = []  # (orig indices, cols)
    n_groups = 0
    for ln in np.unique(lens):
        idx = np.flatnonzero(lens == ln)
        sub = [row_values[i] for i in idx]
        mat = np.frombuffer(b"".join(sub), dtype=np.uint8).reshape(len(sub), int(ln))
        todo = np.arange(len(sub))
        while len(todo):
            n_groups += 1
            if n_groups > _MAX_LAYOUT_GROUPS:
                return _slow_decode(schema, row_values, n)
            lead = sub[todo[0]]
            h = RowSliceV2(lead).header_len()
            match = (mat[todo, :h] == np.frombuffer(lead[:h], dtype=np.uint8)).all(axis=1)
            take = todo[match]
            grp_rows = [sub[i] for i in take]
            cols = (
                _fast_decode(schema, RowSliceV2(lead), grp_rows, len(grp_rows))
                if len(grp_rows) > 1
                else _slow_decode(schema, grp_rows, 1)
            )
            groups.append((idx[take], cols))
            todo = todo[~match]
    order = np.empty(n, dtype=np.int64)
    pos = 0
    for gidx, _cols in groups:
        order[gidx] = pos + np.arange(len(gidx))
        pos += len(gidx)
    out: list[Column] = []
    for ci in range(len(schema)):
        out.append(Column.concat([cols[ci] for _gidx, cols in groups]).take(order))
    return out


def _fast_decode(schema, first: RowSliceV2, row_values, n) -> list[Column]:
    buf = np.frombuffer(b"".join(row_values), dtype=np.uint8).reshape(n, -1)
    base = first.values_start
    cell_pos = {}
    for i, cid in enumerate(first.non_null_ids):
        start = first.offsets[i - 1] if i else 0
        cell_pos[cid] = (base + start, base + first.offsets[i])
    null_ids = set(first.null_ids)

    out: list[Column] = []
    for info in schema:
        et = info.ftype.eval_type
        if info.is_pk_handle:
            out.append(Column(EvalType.INT, np.zeros(n, dtype=np.int64), np.zeros(n, dtype=bool)))
            continue
        span = cell_pos.get(info.col_id)
        if span is None:
            if info.col_id in null_ids or info.default_value is None:
                out.append(typed_column(info, [None] * n))
            else:
                out.append(typed_column(info, [info.default_value] * n))
            continue
        s, e = span
        w = e - s
        raw = buf[:, s:e]
        nulls = np.zeros(n, dtype=bool)
        if et in (EvalType.INT, EvalType.DURATION) and not info.ftype.is_unsigned:
            data = _le_signed_batch(raw, w)
            out.append(Column(et, data, nulls))
        elif et in (EvalType.INT, EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
            data = _le_unsigned_batch(raw, w)
            dtype = np.uint64 if et == EvalType.SET else np.int64
            out.append(attach_schema_dictionary(info, Column(et, data.astype(dtype), nulls)))
        elif et == EvalType.REAL:
            data = codec.decode_f64_batch(np.ascontiguousarray(raw))
            out.append(Column(et, data, nulls))
        else:
            vals = [decode_cell(info, bytes(raw[r])) for r in range(n)]
            out.append(typed_column(info, vals))
    return out


def _le_unsigned_batch(raw: np.ndarray, w: int) -> np.ndarray:
    padded = np.zeros((len(raw), 8), dtype=np.uint8)
    padded[:, :w] = raw
    return padded.view(np.uint64).reshape(len(raw))


def _le_signed_batch(raw: np.ndarray, w: int) -> np.ndarray:
    u = _le_unsigned_batch(raw, w)
    if w == 8:
        return u.view(np.int64)
    sign = 1 << (8 * w - 1)
    return np.where(u >= sign, u.astype(np.int64) - (1 << (8 * w)), u.astype(np.int64))


def _slow_decode(schema, row_values, n) -> list[Column]:
    slices = [RowSliceV2(rv) for rv in row_values]
    out: list[Column] = []
    for info in schema:
        if info.is_pk_handle:
            out.append(Column(EvalType.INT, np.zeros(n, dtype=np.int64), np.zeros(n, dtype=bool)))
            continue
        vals = []
        for sl in slices:
            try:
                cell = sl.get(info.col_id)
            except KeyError:
                vals.append(info.default_value)
                continue
            vals.append(None if cell is None else decode_cell(info, cell))
        out.append(typed_column(info, vals))
    return out
